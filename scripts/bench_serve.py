#!/usr/bin/env python
"""Decode benchmark for the serving engine — the serving twin of bench.py.

Measures the continuous-batching engine (distributed_lion_tpu/serve/) the
way the training bench measures the train step, and writes ONE strict-JSON
evidence artifact under ``runs/serving/`` that check_evidence's ``serving``
stage judges (so serving regressions gate like training ones —
ROADMAP item 4):

- **decode rows** — tokens/s/chip at full-occupancy decode batch
  {32, 128, 256} (every slot active, K timed one-dispatch ticks), each row
  carrying the NF4-vs-bf16 weight-bytes column (ops/quant: the measured
  storage of the quantized tree vs the 2-byte/param bf16 dense serve).
- **prefill-share ablation** — the same staggered workload drained under
  different ``prefill_cap_tokens`` fairness caps: how much decode
  throughput a prefill burst is allowed to steal per tick.
- **bit-identity markers** — (a) greedy decode through the paged engine
  vs the dense-KV ``models/generate.generate`` on the same prompts with
  MATCHED attended length (bit-identical logits ⇒ identical tokens), and
  (b) a staggered continuous-batching run vs solo runs of each request.
  Both recomputed live at artifact-capture time; check_evidence requires
  them true.
- **speculative frontier** (ISSUE 11) — accept-rate × tokens/s/chip over
  drafter (``ngram`` prompt-lookup, ``draft`` self-draft smoke) × k on a
  repetitive and a random workload, plus the speculative identity
  markers recomputed live (greedy speculative == plain paged decode;
  sampled speculative == the same per-request PRNG stream). Judged by
  check_evidence's ``speculative`` stage (runbook stage 5j).
- **serve_resilience section** (ISSUE 14) — the replica plane's fault
  matrix through `serve/replica_plane.ServingFleet`: the crash-at-tick
  rows (tokens lost == 0 and migrated outputs token-identical at every
  cut, recovery-latency column), the one-slow-replica leg (per-replica
  p99 tick latency vs clean, detection + route-around facts), drain and
  rejoin legs, and identity markers recomputed live across
  greedy/sampled/speculative/prefix-cache engines. Judged by
  check_evidence's ``serve_resilience`` stage (runbook stage 5l).
- **tp_serving section** (ISSUE 13) — TP-degree rows (tokens/s/CHIP at
  each measured tp with p50/p99 tick latency: the per-chip number is the
  honest one — tp divides HBM per chip, not free throughput) and the
  shared-prefix memory leg: a 256-request shared-system-prompt workload
  drained through the prefix-cache engine vs the unshared engine,
  ``prefix_mem_ratio`` = physical pages allocated ÷ the unshared run's
  allocations (MEASURED, both runs, not derived). Identity markers
  recomputed live: tp=1 == unsharded, tp>1 == unsharded, and
  shared == unshared for greedy / sampled / speculative decode. Judged
  by check_evidence's ``tp_serving`` stage (runbook stage 5k). The tp>1
  markers/rows need ≥2 devices — on CPU run under
  ``DLION_PLATFORM=cpu8`` (the bench honors it via force_cpu_platform).
- **moe_serving section** (ISSUE 15) — the dense-vs-MoE-vs-MoE+ep decode
  matrix at the standard batches (tokens/s/CHIP, expert-capacity
  utilization and dropped-token-rate columns measured from the engine's
  on-device MoE routing stats against the capacity_factor budget), plus
  six live-recomputed identity markers on the tiny MoE config: paged MoE
  decode == dense-KV MoE generate, engine batched == solo, left-padded
  batched generate == solo, ep=1 bit-identical to the unsharded engine,
  ep>=2 and ep×tp token-identical. Judged by check_evidence's
  ``moe_serving`` stage (runbook stage 5m). The ep>=2 rows/markers need
  enough devices — on CPU run under ``DLION_PLATFORM=cpu8``.
- **fleet_resilience section** (ISSUE 20) — the process-isolated fleet's
  fault matrix over real OS processes and a live socket: the
  SIGKILL-at-tick rows (a replica CHILD PROCESS killed mid-decode under
  ``serve/net.drive_open_loop`` traffic — zero accepted-token loss,
  token-identical migrated responses, greedy and sampled), the
  full-stop restart leg (``serve/fleet_state`` shadow + chain index →
  fresh fleet, token-identical with prefill tokens saved by the
  warm-started pool), and the seeded workload soak through the socket
  front with its ``stream_sha256`` byte-determinism pin. Judged by
  check_evidence's ``fleet_resilience`` stage (runbook stage 5o).
- **slo section** (ISSUE 17) — the seeded scripts/workload_gen.py soak
  through the serve/metrics.py plane: TTFT and per-token decode latency
  p50/p95/p99 read from the LogHistogram sketches, goodput (in-SLO
  tokens/s), terminal status counts, token-loss accounting, breach
  count, and the ``metrics_inert`` marker (metrics-ON token streams
  byte-identical to metrics-OFF — the plane is observationally free).
  Judged by check_evidence's ``slo`` stage (runbook stage 5n).

CPU-produced artifacts are first-class smoke evidence (tiny model — the
engine mechanism, not chip throughput); ``meta.backend`` records what
measured it, and the runbook re-captures on chip at gpt2_124m.

    python scripts/bench_serve.py --out runs/serving
    python scripts/bench_serve.py --batches 32 --ticks 10   # quick look
    DLION_PLATFORM=cpu8 python scripts/bench_serve.py --out runs/serving
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

PROMPT_LEN = 16          # decode-row prompt length (uniform: the decode
#                          measurement wants full slots, not prompt variety)
DEFAULT_BATCHES = (32, 128, 256)


_MODEL_CACHE = {}


def _serve_model(model_name: str, family: str):
    # one init per (model, family) for the whole bench: the speculative
    # legs build many engines over the same weights, and a draft:<k> leg
    # needs the target twice (self-draft smoke — measures the mechanism)
    key = (model_name, family)
    if key not in _MODEL_CACHE:
        import jax

        from distributed_lion_tpu.serve.engine import ServeModel

        if family == "gpt2":
            from distributed_lion_tpu.models.gpt2 import GPT2Config, gpt2_init

            # "<base>_moe": the base architecture with a Switch-MoE FFN in
            # every other block (tiny: 4 experts, gpt2_124m: 8) — the
            # moe_serving matrix's MoE arm (ISSUE 15)
            base = (model_name[:-4] if model_name.endswith("_moe")
                    else model_name)
            moe = {}
            if model_name.endswith("_moe"):
                moe = dict(moe_experts=4 if base == "tiny" else 8)
            cfg = (GPT2Config.tiny(**moe) if base == "tiny"
                   else GPT2Config.gpt2_124m(**moe))
            params = gpt2_init(jax.random.key(0), cfg)
            model = ServeModel.for_gpt2(params, cfg)
        else:
            from distributed_lion_tpu.models.llama import LlamaConfig, llama_init

            cfg = LlamaConfig.named(model_name)
            params = llama_init(jax.random.key(0), cfg)
            model = ServeModel.for_llama(params, cfg)
        _MODEL_CACHE[key] = (model, params, cfg)
    return _MODEL_CACHE[key]


def _build(model_name: str, family: str, quant: str, max_seqs: int,
           block_size: int, max_blocks_per_seq: int,
           prefill_cap: int = 1 << 30, temperature: float = 0.0,
           top_k=None, speculate: str = "", tp: int = 0, ep: int = 0,
           ep_batch: bool = False, ep_overlap: bool = False,
           prefix_cache: bool = False, num_blocks: int = 0,
           moe_stats: bool = False, metrics: bool = False):
    from distributed_lion_tpu.serve.engine import ServeConfig, ServingEngine

    model, params, cfg = _serve_model(model_name, family)
    scfg = ServeConfig(max_seqs=max_seqs, block_size=block_size,
                       max_blocks_per_seq=max_blocks_per_seq,
                       num_blocks=num_blocks,
                       prefill_cap_tokens=prefill_cap,
                       temperature=temperature, top_k=top_k, quant=quant,
                       tp=tp, ep=ep, ep_batch=ep_batch,
                       ep_overlap=ep_overlap, prefix_cache=prefix_cache,
                       speculate=speculate, moe_stats=moe_stats,
                       metrics=metrics)
    draft = model if speculate.startswith("draft") else None
    return ServingEngine(model, scfg, draft_model=draft), params, cfg


def _prompts(n: int, vocab: int, length: int = PROMPT_LEN, seed: int = 0):
    import numpy as np

    rng = np.random.default_rng(seed)
    return [list(map(int, rng.integers(1, vocab, length))) for _ in range(n)]


def bench_decode(batch: int, model_name: str, family: str, quant: str,
                 block_size: int, ticks: int, warmup: int) -> dict:
    """Fill every slot, then time ``ticks`` full-batch decode dispatches."""
    from distributed_lion_tpu.serve.engine import Request

    need = PROMPT_LEN + warmup + ticks + 2
    nblocks = -(-need // block_size)
    engine, params, cfg = _build(model_name, family, quant, batch,
                                 block_size, nblocks)
    for i, toks in enumerate(_prompts(batch, cfg.vocab_size)):
        engine.submit(Request(req_id=i, tokens=toks,
                              max_new_tokens=need, seed=i))
    while engine.pending:  # prefill phase (uncapped) until every slot runs
        engine.step()
    assert all(s is not None for s in engine.slots), "slots did not fill"
    for _ in range(warmup):
        engine.step()
    t0 = time.perf_counter()
    for _ in range(ticks):
        engine.step()  # each tick host-syncs its token batch — the
        #                dispatch is fully retired inside the window
    dt = time.perf_counter() - t0
    return {
        "batch": batch,
        "decode_ticks": ticks,
        "ms_per_tick": round(dt / ticks * 1e3, 4),
        "tokens_per_sec_per_chip": round(batch * ticks / dt, 2),
        "quant": quant,
    }


def bench_prefill_share(model_name: str, family: str, quant: str,
                        caps: list, block_size: int) -> list:
    """Drain one staggered mixed workload per fairness cap: the ablation
    showing what a prefill burst costs the decode batch."""
    from distributed_lion_tpu.serve.engine import Request

    rows = []
    for cap in caps:
        engine, params, cfg = _build(model_name, family, quant, 16,
                                     block_size, 8, prefill_cap=cap)
        prompts = _prompts(48, cfg.vocab_size, seed=7)
        reqs = [Request(req_id=i, tokens=t, max_new_tokens=24, seed=i)
                for i, t in enumerate(prompts)]
        arrivals = {i: (i // 8) * 2 for i in range(len(reqs))}
        t0 = time.perf_counter()
        done = engine.run(reqs, arrivals)
        dt = time.perf_counter() - t0
        total = sum(len(c.tokens) for c in done.values())
        st = engine.stats
        rows.append({
            "prefill_cap_tokens": cap,
            "ticks": st["ticks"],
            "tokens_per_sec": round(total / dt, 2),
            "prefill_token_share": round(
                st["padded_prefill_tokens"]
                / max(st["padded_prefill_tokens"] + st["decode_tokens"], 1),
                4),
        })
    return rows


def _spec_prompts(n: int, vocab: int, kind: str, seed: int = 21):
    """Frontier workloads: ``repetitive`` prompts are repeated short
    motifs (the traffic prompt-lookup drafting exists for — system
    prompts, templated requests), ``random`` prompts carry no n-gram
    signal (the drafter must cost nothing when it can't help)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    if kind == "repetitive":
        out = []
        for _ in range(n):
            motif = list(map(int, rng.integers(1, vocab, 4)))
            out.append(motif * 4)
        return out
    return _prompts(n, vocab, length=PROMPT_LEN, seed=seed)


def bench_speculative(model_name: str, family: str, quant: str,
                      block_size: int, ticks: int, warmup: int,
                      batch: int, ks=(2, 4)) -> dict:
    """The ISSUE 11 evidence: the accept-rate × tokens/s/chip frontier
    over drafter × k on two workloads, plus live-recomputed identity
    markers (greedy speculative == plain paged decode; sampled
    speculative == the same per-request PRNG stream). Speculation never
    changes an output — the frontier shows what each drafter's accept
    rate buys in committed tokens per second."""
    from distributed_lion_tpu.serve.engine import Request

    model, _, cfg = _serve_model(model_name, family)

    def timed_leg(speculate: str, kind: str) -> dict:
        # full-occupancy timed ticks, the decode-row recipe: budgets are
        # sized so no slot finishes inside the window (plain ticks commit
        # 1 token; a speculative tick commits up to k+1). The window is
        # capped by the model's position budget (tiny n_ctx=128 bounds
        # the CPU smoke; gpt2_124m's 1024 fits the full default window).
        k = int(speculate.split(":")[1]) if speculate else 0
        # the random leg decodes SAMPLED: greedy decode from a tiny model
        # degenerates into repeated motifs within a few tokens, handing
        # the self-drafter the very signal the leg exists to withhold —
        # a sampled stream keeps the workload genuinely n-gram-free
        # (identity markers below still pin sampled == the plain stream)
        samp = dict(temperature=0.9, top_k=40) if kind == "random" else {}
        # budget from the PAGE-ROUNDED position budget: pages quantize the
        # horizon, so a non-divisor --block_size must round DOWN here or
        # nblocks*block_size overshoots max_positions and the engine
        # refuses the geometry (e.g. n_ctx=128 at block_size 12)
        cap = model.max_positions or 1 << 30
        cap = (cap // block_size) * block_size
        assert cap > PROMPT_LEN + 2, \
            f"--block_size {block_size} leaves no room under the model's " \
            f"position budget {model.max_positions}"
        # admission steps ALSO run a decode tick (engine.step admits then
        # decodes), so budget FILL_TICKS extra ticks of commits — without
        # them slots exhaust max_new_tokens inside the timed window and
        # the speculative rows read biased-low vs the k=0 baseline
        FILL_TICKS = 2
        total = min(warmup + ticks,
                    (cap - PROMPT_LEN - 2) // (k + 1) - FILL_TICKS)
        w = min(warmup, max(total - 1, 0))
        t = total - w
        need = (total + FILL_TICKS) * (k + 1) + 2
        nblocks = -(-(PROMPT_LEN + need) // block_size)
        eng, _, _ = _build(model_name, family, quant, batch, block_size,
                           nblocks, speculate=speculate, **samp)
        for i, toks in enumerate(_spec_prompts(batch, cfg.vocab_size, kind)):
            eng.submit(Request(req_id=i, tokens=toks, max_new_tokens=need,
                               seed=i))
        while eng.pending:
            eng.step()
        assert all(s is not None for s in eng.slots), "slots did not fill"
        for _ in range(w):
            eng.step()
        t0 = time.perf_counter()
        tok0 = eng.stats["decode_tokens"]
        prop0 = eng.stats.get("spec_proposed", 0)
        acc0 = eng.stats.get("spec_accepted", 0)
        for _ in range(t):
            eng.step()
        dt = time.perf_counter() - t0
        assert all(s is not None for s in eng.slots), \
            "a slot finished inside the timed window — budget miscount"
        committed = eng.stats["decode_tokens"] - tok0
        proposed = eng.stats.get("spec_proposed", 0) - prop0
        accepted = eng.stats.get("spec_accepted", 0) - acc0
        name = speculate.split(":")[0] if speculate else "none"
        return {
            "drafter": name, "k": k, "workload": kind,
            "proposed": int(proposed), "accepted": int(accepted),
            "accept_rate": round(accepted / proposed, 4) if proposed
            else 0.0,
            "ticks": t,
            "ms_per_tick": round(dt / t * 1e3, 4),
            "tokens_per_tick": round(committed / t, 3),
            "tokens_per_sec_per_chip": round(committed / dt, 2),
        }

    frontier = []
    for kind in ("repetitive", "random"):
        legs = [""] + [f"{d}:{k}" for d in ("ngram", "draft") for k in ks]
        for leg in legs:
            frontier.append(timed_leg(leg, kind))
            print(json.dumps(frontier[-1], allow_nan=False), flush=True)

    # live-recomputed identity markers on the measured model: speculation
    # must EARN its "outputs unchanged" claim at capture time. Greedy:
    # both drafters; sampled: the per-request stream replay (ngram leg —
    # one drafter suffices, the acceptance rule is drafter-independent).
    def outputs(speculate: str, **samp):
        eng, _, _ = _build(model_name, family, quant, 8, block_size, 8,
                           speculate=speculate, **samp)
        reqs = [Request(req_id=i, tokens=toks, max_new_tokens=12, seed=i)
                for i, toks in enumerate(
                    _spec_prompts(4, cfg.vocab_size, "repetitive")
                    + _spec_prompts(4, cfg.vocab_size, "random"))]
        done = eng.run(reqs)
        return {r: c.tokens for r, c in done.items()}

    plain_greedy = outputs("")
    greedy_ok = all(outputs(s) == plain_greedy
                    for s in ("ngram:4", "draft:2"))
    sampled = dict(temperature=0.9, top_k=40)
    sampled_ok = outputs("ngram:4", **sampled) == outputs("", **sampled)
    return {
        "markers": {"greedy_vs_plain": bool(greedy_ok),
                    "sampled_vs_stream": bool(sampled_ok)},
        "frontier": frontier,
    }


def bit_identity_markers(family: str, model_name: str = "tiny") -> dict:
    """Live recompute of the two serving bit-identity claims on the tiny
    model (cheap on any backend) — the artifact must EARN its markers at
    capture time, not copy them from a test run. ``model_name``
    parameterizes the tiny architecture so the moe_serving section reuses
    the exact same recipe on the tiny MoE config (ISSUE 15)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_lion_tpu.models.generate import generate
    from distributed_lion_tpu.serve.engine import Request

    block_size, nblk = 4, 8                  # paged horizon = 32 tokens
    new_tokens = 8
    engine, params, cfg = _build(model_name, family, "none", 4, block_size,
                                 nblk)
    if family == "gpt2":
        from distributed_lion_tpu.models.gpt2 import gpt2_decode, gpt2_init_cache

        def dec(p, t, c, pos, off=None):
            return gpt2_decode(p, t, cfg, c, pos, off)

        def ic(b, m):
            return gpt2_init_cache(cfg, b, m)
    else:
        from distributed_lion_tpu.models.llama import llama_decode, llama_init_cache

        def dec(p, t, c, pos, off=None):
            return llama_decode(p, t, cfg, c, pos, off)

        def ic(b, m):
            return llama_init_cache(cfg, b, m)

    # (a) paged engine vs dense generate, MATCHED attended length
    # (max_len == blocks*block_size), uniform prompts, greedy
    prompts = _prompts(4, cfg.vocab_size, length=7, seed=11)
    dense = np.asarray(generate(
        dec, ic, params, jnp.asarray(prompts, jnp.int32), new_tokens,
        max_len=block_size * nblk))
    done = engine.run([Request(req_id=i, tokens=t, max_new_tokens=new_tokens,
                               seed=0) for i, t in enumerate(prompts)])
    paged_vs_dense = all(
        list(dense[i]) == done[i].tokens for i in range(len(prompts)))

    # (b) staggered continuous batching vs solo runs, varied lengths
    varied = [p[: 3 + 2 * i] for i, p in enumerate(_prompts(4, cfg.vocab_size,
                                                            length=12, seed=13))]
    reqs = [Request(req_id=i, tokens=t, max_new_tokens=new_tokens, seed=i)
            for i, t in enumerate(varied)]
    eng2, _, _ = _build(model_name, family, "none", 4, block_size,
                        nblk)
    stag = eng2.run(reqs, arrivals={0: 0, 1: 1, 2: 1, 3: 4})
    ok = True
    for r in reqs:
        solo_eng, _, _ = _build(model_name, family, "none", 4,
                                block_size, nblk)
        solo = solo_eng.run([Request(r.req_id, list(r.tokens),
                                     r.max_new_tokens, r.seed)])
        ok = ok and solo[r.req_id].tokens == stag[r.req_id].tokens
    return {"paged_vs_dense": bool(paged_vs_dense),
            "batched_vs_solo": bool(ok)}


def _feasible_tps(family, cfg, requested) -> list:
    """Filter the requested TP degrees to ones this backend/model can
    actually run (enough devices, heads/kv-heads/d_ff divide) — dropped
    degrees are reported, never silently skipped (no-silent-caps)."""
    import jax

    from distributed_lion_tpu.parallel.tensor_parallel import validate_tp

    n_dev = len(jax.devices())
    out, dropped = [], []
    for t in requested:
        try:
            if t > n_dev:
                raise ValueError(f"{t} > {n_dev} devices")
            if t >= 1:
                validate_tp(cfg, t, family)
                kv = cfg.n_head if family == "gpt2" else cfg.n_kv_head
                if kv % t:
                    raise ValueError(f"kv heads {kv} % {t}")
            out.append(t)
        except ValueError as e:
            dropped.append((t, str(e)))
    for t, why in dropped:
        print(json.dumps({"dropped_tp_degree": t, "why": why},
                         allow_nan=False), flush=True)
    return out


def bench_tp_serving(model_name: str, family: str, quant: str,
                     block_size: int, ticks: int, warmup: int,
                     batch: int, tps, prefix_requests: int) -> dict:
    """The ISSUE 13 evidence: TP-degree decode rows (tokens/s/CHIP +
    p50/p99 tick latency), the shared-prefix memory leg (physical ÷
    logical pages, both MEASURED by draining the same workload through
    the shared and unshared engines), and the five live-recomputed
    identity markers (tiny model — identity is backend-independent)."""
    import numpy as np

    from distributed_lion_tpu.serve.engine import Request

    model, _, cfg = _serve_model(model_name, family)

    # ---- TP rows: full-occupancy timed decode ticks per degree
    rows = []
    for tp in _feasible_tps(family, cfg, tps):
        need = PROMPT_LEN + warmup + ticks + 2
        nblocks = -(-need // block_size)
        eng, _, _ = _build(model_name, family, quant, batch, block_size,
                           nblocks, tp=tp)
        for i, toks in enumerate(_prompts(batch, cfg.vocab_size)):
            eng.submit(Request(req_id=i, tokens=toks, max_new_tokens=need,
                               seed=i))
        while eng.pending:
            eng.step()
        assert all(s is not None for s in eng.slots), "slots did not fill"
        for _ in range(warmup):
            eng.step()
        tick_ms = []
        for _ in range(ticks):
            t0 = time.perf_counter()
            eng.step()  # host-syncs its token batch: fully retired
            tick_ms.append((time.perf_counter() - t0) * 1e3)
        total_s = sum(tick_ms) / 1e3
        chips = max(tp, 1)
        row = {
            "tp": tp, "batch": batch, "decode_ticks": ticks,
            "ms_per_tick_p50": round(float(np.percentile(tick_ms, 50)), 4),
            "ms_per_tick_p99": round(float(np.percentile(tick_ms, 99)), 4),
            "tokens_per_sec_per_chip": round(
                batch * ticks / total_s / chips, 2),
        }
        rows.append(row)
        print(json.dumps(row, allow_nan=False), flush=True)

    # ---- shared-prefix memory leg: 256 requests, one system prompt
    rng = np.random.default_rng(31)
    prompt_len = 132  # NOT page-aligned at the default block 16: the
    #                   partial boundary page exercises real CoW
    horizon = model.max_positions or 1 << 30
    prompt_len = min(prompt_len, (horizon // block_size) * block_size - 12)
    gen = 8
    sys_prompt = list(map(int, rng.integers(1, cfg.vocab_size, prompt_len)))
    reqs = [Request(req_id=i, tokens=list(sys_prompt), max_new_tokens=gen,
                    seed=i) for i in range(prefix_requests)]
    bps = -(-(prompt_len + gen + 1) // block_size)
    geom = dict(max_seqs=32, block_size=block_size, max_blocks_per_seq=bps)

    def drain(prefix_cache):
        eng, _, _ = _build(model_name, family, quant,
                           prefix_cache=prefix_cache, **geom)
        t0 = time.perf_counter()
        eng.run([Request(r.req_id, list(r.tokens), r.max_new_tokens,
                         r.seed) for r in reqs])
        dt = time.perf_counter() - t0
        return eng, dt

    unshared, dt_u = drain(False)
    shared, dt_s = drain(True)
    logical = unshared.tables.pages_allocated
    physical = shared.tables.pages_allocated
    prefix = {
        "requests": prefix_requests,
        "prompt_len": prompt_len,
        "max_new_tokens": gen,
        "logical_pages": int(logical),
        "physical_pages": int(physical),
        "prefix_mem_ratio": round(physical / logical, 4),
        "prefix_hits": int(shared.stats["prefix_hits"]),
        "cow_copies": int(shared.stats["cow_copies"]),
        "tokens_per_sec_shared": round(
            shared.stats["decode_tokens"] / dt_s, 2),
        "tokens_per_sec_unshared": round(
            unshared.stats["decode_tokens"] / dt_u, 2),
    }
    print(json.dumps(prefix, allow_nan=False), flush=True)

    # ---- identity markers, recomputed live on the tiny model (identity
    # is backend/scale-independent; the tiny model keeps capture cheap)
    def outputs(ident_kw, samp=None):
        eng, _, tcfg = _build("tiny", family, "none", 6, 4, 16,
                              num_blocks=128, **(ident_kw or {}),
                              **(samp or {}))
        trng = np.random.default_rng(17)
        sysp = list(map(int, trng.integers(1, tcfg.vocab_size, 13)))
        prompts = [sysp + list(map(int, trng.integers(1, tcfg.vocab_size,
                                                      3)))
                   for _ in range(4)] + [list(sysp)] * 2
        done = eng.run([Request(req_id=i, tokens=list(t), max_new_tokens=8,
                                seed=i) for i, t in enumerate(prompts)])
        return {r: c.tokens for r, c in done.items()}

    plain = outputs({})
    tiny_cfg = _serve_model("tiny", family)[2]
    tpn = max(_feasible_tps(family, tiny_cfg, [4, 2]) or [0])
    sampled = dict(temperature=0.9, top_k=40)
    markers = {
        "tp1_vs_unsharded": outputs({"tp": 1}) == plain,
        "tpN_vs_unsharded": (tpn >= 2
                             and outputs({"tp": tpn}) == plain),
        "shared_vs_unshared_greedy":
            outputs({"prefix_cache": True}) == plain,
        "shared_vs_unshared_sampled":
            outputs({"prefix_cache": True}, sampled)
            == outputs({}, sampled),
        "shared_vs_unshared_speculative":
            outputs({"prefix_cache": True, "speculate": "ngram:4"})
            == plain,
    }
    markers = {k: bool(v) for k, v in markers.items()}
    return {"markers": markers, "tp_degree_max_measured": int(tpn),
            "rows": rows, "prefix": prefix}


def bench_moe_serving(model_name: str, quant: str, block_size: int,
                      ticks: int, warmup: int, batches, eps) -> dict:
    """The ISSUE 15 evidence: the dense-vs-MoE-vs-MoE+ep decode matrix
    (tokens/s/CHIP at the standard batches, with expert-capacity
    utilization and dropped-token-rate columns measured from the engine's
    on-device MoE routing stats against the config's capacity_factor
    budget — serving itself never drops: inference routing is no-drop),
    plus the live-recomputed identity markers on the tiny MoE config:
    paged MoE == dense-KV MoE generate, batched == solo (engine AND
    left-padded batched generate), ep=1 bit-identical to the unsharded
    engine, ep>=2 and ep×tp token-identical on the measuring mesh. MoE
    is a gpt2 architecture; the section always measures the gpt2 family."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_lion_tpu.serve.engine import Request

    family = "gpt2"
    moe_name = model_name + "_moe"
    _, _, mcfg = _serve_model(moe_name, family)
    E = mcfg.moe_experts
    n_dev = len(jax.devices())

    # feasible ep degrees — dropped degrees reported, never silently
    # skipped (no-silent-caps)
    feasible, dropped = [], []
    for e in eps:
        if e < 2:
            dropped.append((e, "matrix rows measure ep >= 2 (ep=1 is the "
                               "bit-identity marker)"))
        elif e > n_dev:
            dropped.append((e, f"{e} > {n_dev} devices"))
        elif E % e:
            dropped.append((e, f"moe_experts {E} % {e}"))
        else:
            feasible.append(e)
    for e, why in dropped:
        print(json.dumps({"dropped_ep_degree": e, "why": why},
                         allow_nan=False), flush=True)

    rows = []

    def routing_cols(batch: int) -> dict:
        """The capacity columns, measured in a SEPARATE UNTIMED pass with
        ``moe_stats`` armed — the per-tick stats host reads must never
        ride the timed throughput window (they would bias the
        dense-vs-MoE delta with instrumentation cost). Measured once per
        batch at ep=0: routing is pinned token-identical across
        ep/sharding, so one measurement honestly serves every MoE row of
        that batch."""
        stat_ticks = 8
        need = PROMPT_LEN + stat_ticks + 2
        nblocks = -(-need // block_size)
        eng, _, cfg = _build(moe_name, family, quant, batch, block_size,
                             nblocks, moe_stats=True)
        for i, toks in enumerate(_prompts(batch, cfg.vocab_size)):
            eng.submit(Request(req_id=i, tokens=toks, max_new_tokens=need,
                               seed=i))
        while eng.pending:
            eng.step()
        assert all(s is not None for s in eng.slots), "slots did not fill"
        v0, k0 = (eng.stats["moe_valid_tokens"],
                  eng.stats["moe_kept_tokens"])
        c0 = eng.stats["moe_capacity_slots"]
        for _ in range(stat_ticks):
            eng.step()
        vv = eng.stats["moe_valid_tokens"] - v0
        kk = eng.stats["moe_kept_tokens"] - k0
        cc = eng.stats["moe_capacity_slots"] - c0
        return {
            # routing load vs the capacity_factor budget (what-if columns:
            # the no-drop serving path drops nothing, these say how the
            # traffic would load the Switch training budget)
            "capacity_utilization": round(min(kk / cc, 1.0), 4) if cc
            else 0.0,
            "dropped_rate": round(max(vv - kk, 0.0) / vv, 4) if vv else 0.0,
        }

    dense_pc = {}  # batch -> dense tokens/s/chip (the per-chip yardstick)

    def timed(config: str, m_name: str, batch: int, ep: int,
              cols: dict, ep_batch: bool = False) -> None:
        need = PROMPT_LEN + warmup + ticks + 2
        nblocks = -(-need // block_size)
        is_moe = m_name == moe_name
        # moe_stats stays OFF here: every row (dense and MoE) times the
        # identical un-instrumented engine — apples to apples
        eng, _, cfg = _build(m_name, family, quant, batch, block_size,
                             nblocks, ep=ep, ep_batch=ep_batch)
        for i, toks in enumerate(_prompts(batch, cfg.vocab_size)):
            eng.submit(Request(req_id=i, tokens=toks, max_new_tokens=need,
                               seed=i))
        while eng.pending:
            eng.step()
        assert all(s is not None for s in eng.slots), "slots did not fill"
        for _ in range(warmup):
            eng.step()
        t0 = time.perf_counter()
        for _ in range(ticks):
            eng.step()  # host-syncs its token batch: fully retired
        dt = time.perf_counter() - t0
        pc = round(batch * ticks / dt / max(ep, 1), 2)
        if not is_moe:
            dense_pc[batch] = pc
        row = {
            "config": config, "experts": E if is_moe else 0, "ep": ep,
            # how the batch meets the expert axis: 'none' (no axis),
            # 'replicated' (every shard decodes the whole batch — ep is an
            # HBM lever only), 'batch' (rows sharded over the axis — each
            # shard decodes batch/ep rows, ISSUE 16's throughput lever)
            "sharding": ("batch" if ep_batch
                         else ("replicated" if ep else "none")),
            "batch": batch, "decode_ticks": ticks,
            "ms_per_tick": round(dt / ticks * 1e3, 4),
            "tokens_per_sec_per_chip": pc,
            "beats_dense_per_chip": bool(is_moe and batch in dense_pc
                                         and pc > dense_pc[batch]),
            "capacity_utilization": cols["capacity_utilization"] if is_moe
            else 0.0,
            "dropped_rate": cols["dropped_rate"] if is_moe else 0.0,
        }
        rows.append(row)
        print(json.dumps(row, allow_nan=False), flush=True)

    for batch in batches:
        cols = routing_cols(batch)
        timed("dense", model_name, batch, 0, cols)
        timed("moe", moe_name, batch, 0, cols)
        for e in feasible:
            timed(f"moe_ep{e}", moe_name, batch, e, cols)
            if batch % e == 0:
                timed(f"moe_ep{e}_batch", moe_name, batch, e, cols,
                      ep_batch=True)
            else:
                print(json.dumps(
                    {"dropped_row": f"moe_ep{e}_batch",
                     "why": f"batch {batch} % ep {e}"},
                    allow_nan=False), flush=True)

    # ---- identity markers, recomputed live on the tiny MoE config
    # (identity is backend/scale-independent; capture stays cheap)
    tiny = "tiny_moe"
    _, tparams, tcfg = _serve_model(tiny, family)
    bits = bit_identity_markers(family, model_name=tiny)

    # batched left-padded generate == solo (the lifted models/generate
    # refusal): greedy, varied prompt lengths
    from distributed_lion_tpu.models.generate import generate
    from distributed_lion_tpu.models.gpt2 import (
        gpt2_decode,
        gpt2_init_cache,
    )

    def dec(p, t, c, pos, off=None):
        return gpt2_decode(p, t, tcfg, c, pos, off)

    def ic(b, m):
        return gpt2_init_cache(tcfg, b, m)

    grng = np.random.default_rng(23)
    lens = [3, 7, 5, 9]
    prompts = [list(map(int, grng.integers(1, tcfg.vocab_size, L)))
               for L in lens]
    T = max(lens)
    padded = np.zeros((len(prompts), T), np.int32)
    for i, p in enumerate(prompts):
        padded[i, T - len(p):] = p
    batched = np.asarray(generate(
        dec, ic, tparams, jnp.asarray(padded), 8,
        prompt_lens=jnp.asarray(lens, jnp.int32)))
    gen_ok = True
    for i, p in enumerate(prompts):
        solo = np.asarray(generate(dec, ic, tparams,
                                   jnp.asarray([p], jnp.int32), 8))
        gen_ok = gen_ok and (batched[i] == solo[0]).all()

    # ep identity: engine outputs across sharding degrees
    def outputs(kw=None, samp=None):
        eng, _, _ = _build(tiny, family, "none", 4, 4, 8, **(kw or {}),
                           **(samp or {}))
        trng = np.random.default_rng(29)
        pr = [list(map(int, trng.integers(1, tcfg.vocab_size, 3 + 2 * i)))
              for i in range(4)]
        done = eng.run([Request(req_id=i, tokens=list(t), max_new_tokens=8,
                                seed=i) for i, t in enumerate(pr)])
        return {r: c.tokens for r, c in done.items()}

    plain = outputs()
    e_tiny = tcfg.moe_experts
    epn = max([e for e in (4, 2) if e <= n_dev and e_tiny % e == 0] or [0])
    can_ep_tp = n_dev >= 4 and tcfg.n_head % 2 == 0 and e_tiny % 2 == 0
    markers = {
        "paged_vs_dense": bits["paged_vs_dense"],
        "batched_vs_solo": bits["batched_vs_solo"],
        "batched_generate_vs_solo": bool(gen_ok),
        "ep1_vs_unsharded": outputs({"ep": 1}) == plain,
        "epN_vs_unsharded": epn >= 2 and outputs({"ep": epn}) == plain,
        "ep_tp_vs_unsharded": can_ep_tp
        and outputs({"ep": 2, "tp": 2}) == plain,
        # ISSUE 16: the batch-sharded rows are only admissible if the
        # sharding is a pure re-schedule — token-identical to the
        # unsharded engine, alone, with tp, and with the microbatch
        # overlap split
        "ep_batch1_vs_unsharded":
        outputs({"ep": 1, "ep_batch": True}) == plain,
        "ep_batchN_vs_unsharded": epn >= 2
        and outputs({"ep": epn, "ep_batch": True}) == plain,
        "ep_batch_tp_vs_unsharded": can_ep_tp
        and outputs({"ep": 2, "tp": 2, "ep_batch": True}) == plain,
        # overlap needs an even per-shard slot count: ep=2 on the 4-slot
        # identity engine (2 slots/shard, one per microbatch half)
        "ep_batch_overlap_vs_unsharded": epn >= 2 and e_tiny % 2 == 0
        and outputs({"ep": 2, "ep_batch": True,
                     "ep_overlap": True}) == plain,
    }
    markers = {k: bool(v) for k, v in markers.items()}
    return {"markers": markers, "ep_degree_max_measured": int(epn),
            "rows": rows}


def bench_serve_resilience(model_name: str, family: str, quant: str,
                           block_size: int) -> dict:
    """The ISSUE 14 evidence: the serve-side fault matrix through the
    replica plane. Crash-at-tick rows (tokens lost MUST be 0 and the
    migrated outputs token-identical — both measured against the
    single-engine baseline, with the recovery-latency column from the
    fleet's migration clock), the one-slow-replica leg (per-replica p99
    tick latency slow-vs-clean, detection + route-around facts), the
    drain and rejoin legs, and the identity markers recomputed live
    across greedy / sampled / speculative / prefix-cache engines."""
    from distributed_lion_tpu.serve.engine import Request
    from distributed_lion_tpu.serve.replica_plane import ServingFleet
    from distributed_lion_tpu.train import resilience

    model, _, cfg = _serve_model(model_name, family)
    gen = 16
    need = PROMPT_LEN + gen + 2
    nblocks = -(-need // block_size)
    n_req = 12
    prompts = _prompts(n_req, cfg.vocab_size, seed=5)
    arrivals = {i: (i // 2) for i in range(n_req)}

    def reqs():
        return [Request(req_id=i, tokens=list(t), max_new_tokens=gen,
                        seed=i) for i, t in enumerate(prompts)]

    def factory_for(**kw):
        def factory():
            eng, _, _ = _build(model_name, family, quant, 8, block_size,
                               nblocks, **kw)
            return eng
        return factory

    def fleet_run(specs, reqs_list=None, arr=None, record_latency=False,
                  **kw):
        resilience.inject_fault(
            "serve", resilience.parse_serve_specs(specs) if specs else [])
        fleet = ServingFleet(factory_for(**kw), replicas=2,
                             record_latency=record_latency)
        done = fleet.run(reqs_list if reqs_list is not None else reqs(),
                         dict(arr if arr is not None else arrivals))
        resilience.inject_fault("serve", [])
        return fleet, done

    def identical(done, base):
        return all(done[i].tokens == base[i].tokens
                   and done[i].reason == base[i].reason for i in base)

    def lost(done, base):
        return int(sum(max(len(base[i].tokens) - len(done[i].tokens), 0)
                       for i in base))

    base = factory_for()().run(reqs(), dict(arrivals))

    # ---- crash-at-tick matrix: zero accepted-token loss at every cut
    crash_matrix = []
    for crash_tick in (1, 3, 6):
        fleet, done = fleet_run(f"replica_crash:0:{crash_tick}")
        row = {
            "crash_tick": crash_tick,
            "migrated": int(fleet.stats["migrations"]),
            "tokens_lost": lost(done, base),
            "identical": bool(identical(done, base)),
            "recovery_latency_ticks": int(
                max(fleet.migration_latency_ticks, default=0)),
        }
        crash_matrix.append(row)
        print(json.dumps({"serve_resilience": "crash", **row},
                         allow_nan=False), flush=True)

    # ---- identity under sampling / speculation / prefix sharing: the
    # migrated stream must be the SAME stream, not just a plausible one
    samp = dict(temperature=0.9, top_k=40)
    base_samp = factory_for(**samp)().run(reqs(), dict(arrivals))
    _, done_samp = fleet_run("replica_crash:0:3", **samp)
    base_pc = factory_for(prefix_cache=True)().run(reqs(), dict(arrivals))
    _, done_spec = fleet_run("replica_crash:0:3", speculate="ngram:4")
    _, done_pc = fleet_run("replica_crash:0:3", prefix_cache=True)

    # ---- drain: admission stops, residents finish, nothing is lost
    fleet_d, done_d = fleet_run("replica_drain:0:2")
    drain = {
        "completed": int(len(done_d)),
        "identical": bool(identical(done_d, base)),
        "drained_departed": bool(fleet_d.lifecycle()[0] == "departed"),
        "migrated_pending": int(fleet_d.stats["migrations"]),
    }
    print(json.dumps({"serve_resilience": "drain", **drain},
                     allow_nan=False), flush=True)

    # ---- one slow replica: detected by the tick-latency watch, new
    # work routes around it, and the p99 story is measured per replica
    slow_ms = 20
    n_slow = 24
    slow_prompts = _prompts(n_slow, cfg.vocab_size, seed=6)
    slow_reqs = [Request(req_id=i, tokens=list(t), max_new_tokens=gen,
                         seed=i) for i, t in enumerate(slow_prompts)]
    slow_arr = {i: (i // 2) for i in range(n_slow)}
    fleet_c, done_c = fleet_run("", reqs_list=[
        Request(r.req_id, list(r.tokens), r.max_new_tokens, r.seed)
        for r in slow_reqs], arr=slow_arr, record_latency=True)
    fleet_s, done_s = fleet_run(f"slow_tick:0:{slow_ms}", reqs_list=[
        Request(r.req_id, list(r.tokens), r.max_new_tokens, r.seed)
        for r in slow_reqs], arr=slow_arr, record_latency=True)

    def p99(win):
        # TickLatencyWindow: exact percentile over the bounded recency
        # window — the first jit-compile tick ages out instead of
        # dominating p99 on BOTH replicas and masking the straggler
        return round(win.percentile(99), 3) if len(win) else 0.0

    slow_base = {i: c.tokens for i, c in done_c.items()}
    slow = {
        "slow_ms": slow_ms,
        "p99_ms_slow_replica": p99(fleet_s.tick_latency_log[0]),
        "p99_ms_clean_replica": p99(fleet_s.tick_latency_log[1]),
        "p99_ms_clean_run": max(p99(fleet_c.tick_latency_log[0]),
                                p99(fleet_c.tick_latency_log[1])),
        "detected": bool(fleet_s.stats["slow_detected"] >= 1),
        "admissions_slow": int(fleet_s.replicas[0].admissions),
        "admissions_fast": int(fleet_s.replicas[1].admissions),
        "identical": bool(all(done_s[i].tokens == slow_base[i]
                              for i in slow_base)),
    }
    print(json.dumps({"serve_resilience": "slow", **slow},
                     allow_nan=False), flush=True)

    # ---- crash then rejoin: the rejoiner re-enters the rotation with a
    # FRESH page pool and actually serves (its new engine's own stats
    # can only count post-rejoin work). Arrivals stretch PAST the rejoin
    # tick so there is new work to route to it — per-request outputs are
    # batch/arrival-independent (the engine's pinned streams), so the
    # same baseline still judges identity.
    fleet_r, done_r = fleet_run("replica_crash:0:2,replica_rejoin:0:6",
                                arr={i: i for i in range(n_req)})
    rep0 = fleet_r.replicas[0]
    rejoin = {
        "rejoined": bool(fleet_r.stats["replica_rejoins"] == 1),
        "served_after_rejoin": bool(
            rep0.engine is not None
            and rep0.engine.stats["prefill_dispatches"] > 0),
        "identical": bool(identical(done_r, base)),
        "final_lifecycle": list(fleet_r.lifecycle()),
    }
    print(json.dumps({"serve_resilience": "rejoin", **rejoin},
                     allow_nan=False), flush=True)

    markers = {
        "migrated_identity_greedy": all(r["identical"]
                                        for r in crash_matrix),
        "migrated_identity_sampled": identical(done_samp, base_samp),
        "migrated_identity_speculative": identical(done_spec, base),
        "migrated_identity_prefix_cache": identical(done_pc, base_pc),
        "zero_token_loss": all(r["tokens_lost"] == 0
                               for r in crash_matrix),
        "drain_completes_residents": drain["identical"]
        and drain["drained_departed"],
        "slow_detected_and_routed": slow["detected"]
        and slow["admissions_slow"] < slow["admissions_fast"],
        "rejoin_serves": rejoin["rejoined"]
        and rejoin["served_after_rejoin"] and rejoin["identical"],
    }
    markers = {k: bool(v) for k, v in markers.items()}
    return {"markers": markers, "crash_matrix": crash_matrix,
            "drain": drain, "slow": slow, "rejoin": rejoin}


def bench_fleet_resilience(block_size: int) -> dict:
    """The ISSUE 20 evidence: the process-isolated serving fleet's fault
    matrix, measured over real OS processes and a live socket.

    - **kill matrix** — a replica CHILD PROCESS is SIGKILLed for real at
      tick 1 / 3 / 6 (plus a sampled cut at tick 3) while
      ``serve/net.drive_open_loop`` streams the workload over a live
      socket connection; every response must come back token-identical
      to the never-killed single-engine run with zero accepted tokens
      lost, the cut registering as a process death (EOF on the pipe →
      ``replicas_declared_dead``), not a polite in-process exception.
    - **restart leg** — a fleet with a ``state_dir`` is stopped
      mid-decode (the persisted recovery shadow + prefix-chain index are
      all that survive) and a FRESH fleet resumes from disk:
      token-identical completions, with the warm-started page pool
      saving real prefill work (``shared_tokens`` > 0).
    - **socket soak** — a seeded workload_gen stream (imported by file
      path like the slo section) driven open-loop at a process fleet
      behind the socket front; banked with goodput and the
      byte-determinism ``stream_sha256`` pin (the digest every rerun of
      the same generator seed must reproduce).

    A CPU-produced artifact is first-class here for the same reason as
    the elasticity stage: process spawn, SIGKILL, pipe-EOF detection and
    the persistence manifest are host-plane mechanics on every backend.
    The section pins the tiny gpt2 model regardless of ``--model`` — the
    ``gpt2_tiny`` worker builder reconstructs those weights from the
    init seed alone, so parent baseline and child engines provably share
    weights with no checkpoint file in the loop."""
    import importlib.util
    import shutil
    import tempfile
    import threading
    import time

    import numpy as np

    from distributed_lion_tpu.serve import fleet_proc, fleet_state, net
    from distributed_lion_tpu.serve.engine import (
        Request,
        ServeConfig,
        ServingEngine,
    )
    from distributed_lion_tpu.serve.replica_plane import ServingFleet
    from distributed_lion_tpu.train import resilience

    model, _, cfg = _serve_model("tiny", "gpt2")
    gen = 10
    n_req = 8
    # worst prompt across the legs: 6-token shared prefix + 10-token
    # tail (kill matrix), or prefix_len+prompt_max = 22 (soak)
    serve_kw = dict(max_seqs=4, block_size=block_size,
                    max_blocks_per_seq=-(-(22 + 12 + 2) // block_size),
                    prefix_cache=True)
    builder = {"kind": "gpt2_tiny", "init_seed": 0, "serve": serve_kw}

    rng = np.random.default_rng(17)
    shared = [int(t) for t in rng.integers(1, cfg.vocab_size, 6)]
    wire = []
    for i in range(n_req):
        tail = [int(t) for t in rng.integers(1, cfg.vocab_size, 3 + i)]
        d = {"id": f"k{i}", "max_new_tokens": gen, "seed": i}
        if i % 2 == 0:
            d.update(tokens=shared + tail, prefix_group="sys")
        else:
            d["tokens"] = tail
        wire.append(d)

    def as_reqs():
        return [Request(req_id=d["id"], tokens=list(d["tokens"]),
                        max_new_tokens=d["max_new_tokens"], seed=d["seed"],
                        prefix_group=d.get("prefix_group"))
                for d in wire]

    def offline(**samp):
        eng = ServingEngine(model, ServeConfig(**{**serve_kw, **samp}))
        return eng.run(as_reqs())

    def kill_run(kill_tick, **samp):
        resilience.inject_fault("serve", resilience.parse_serve_specs(
            f"replica_kill:0:{kill_tick}"))
        fleet = ServingFleet(
            fleet_proc.process_replica_factory(
                {**builder, "serve": {**serve_kw, **samp}}),
            replicas=2)
        reps = [rep.engine for rep in fleet.replicas]
        pids = [r.pid for r in reps]
        srv = net.ServeServer(fleet, port=0)
        th = threading.Thread(target=srv.run,
                              kwargs={"max_wall_s": 300.0}, daemon=True)
        th.start()
        try:
            out = net.drive_open_loop(*srv.addr, records=wire,
                                      tick_s=0.0, max_wall_s=240.0)
        finally:
            srv.stop = True
            th.join(timeout=30)
            srv.close()
            fleet.close()
            resilience.inject_fault("serve", [])
        reaped = all(r.proc.poll() is not None for r in reps)
        isolated = (len(set(pids)) == 2 and os.getpid() not in pids
                    and all(p > 0 for p in pids) and reaped)
        return fleet, out, isolated

    # ---- SIGKILL matrix under live socket traffic
    kill_matrix = []
    for kill_tick, sampling in ((1, "greedy"), (3, "greedy"),
                                (6, "greedy"), (3, "stochastic")):
        samp = (dict(temperature=0.0) if sampling == "greedy"
                else dict(temperature=0.9, top_k=40))
        base = offline(**samp)
        fleet, out, isolated = kill_run(kill_tick, **samp)
        lost = sum(max(len(base[d["id"]].tokens)
                       - len(out["responses"][d["id"]]["tokens"]), 0)
                   for d in wire if d["id"] in out["responses"])
        row = {
            "kill_tick": kill_tick,
            "sampling": sampling,
            "migrated": int(fleet.stats["migrations"]),
            "declared_dead": int(fleet.stats["replicas_declared_dead"]),
            "tokens_lost": int(lost),
            "completed": int(len(out["responses"])),
            "identical": bool(
                len(out["responses"]) == n_req
                and all(out["responses"][d["id"]]["tokens"]
                        == base[d["id"]].tokens for d in wire)),
            "process_isolated": bool(isolated),
        }
        kill_matrix.append(row)
        print(json.dumps({"fleet_resilience": "kill", **row},
                         allow_nan=False), flush=True)

    # ---- full-stop restart from the persisted shadow + chain index
    base = offline()
    sdir = tempfile.mkdtemp(prefix="bench_fleet_state_")
    try:
        def factory():
            return ServingEngine(model, ServeConfig(**serve_kw))

        fleet_a = ServingFleet(factory, replicas=2, state_dir=sdir)
        done = {}
        for r in as_reqs():
            fleet_a.submit(r)
        for _ in range(4):              # mid-decode, nothing finished
            for c in fleet_a.step():
                done[c.req_id] = c
        fleet_a.save_state()
        inflight = len(fleet_a.export_records())
        # fleet_a is now abandoned — a kill -9 of the parent process
        fleet_b = ServingFleet(factory, replicas=2)
        state = fleet_state.load_fleet_state(sdir, now=time.monotonic())
        res = fleet_state.resume_into(fleet_b, state)
        while fleet_b.has_work():
            for c in fleet_b.step():
                done[c.req_id] = c
        saved = sum(rep.engine.stats["shared_tokens"]
                    for rep in fleet_b.replicas
                    if rep.engine is not None)
    finally:
        shutil.rmtree(sdir, ignore_errors=True)
    restart = {
        "inflight_at_stop": int(inflight),
        "restored": int(res["restored"]),
        "chains_primed": int(res["chains_primed"]),
        "resumed_from_tick": int(state["tick"]),
        "prefill_tokens_saved": int(saved),
        "identical": bool(all(
            done[d["id"]].tokens == base[d["id"]].tokens
            and done[d["id"]].reason == base[d["id"]].reason
            for d in wire)),
    }
    print(json.dumps({"fleet_resilience": "restart", **restart},
                     allow_nan=False), flush=True)

    # ---- seeded workload soak through the socket front
    spec = importlib.util.spec_from_file_location(
        "workload_gen_fleet", os.path.join(REPO, "scripts",
                                           "workload_gen.py"))
    wg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(wg)
    records = wg.generate(requests=24, seed=3, vocab=cfg.vocab_size,
                          prompt_max=16, out_max=12, prefix_len=6,
                          deadline_frac=0.0)
    fleet = ServingFleet(fleet_proc.process_replica_factory(builder),
                         replicas=2)
    srv = net.ServeServer(fleet, port=0)
    th = threading.Thread(target=srv.run, kwargs={"max_wall_s": 300.0},
                          daemon=True)
    th.start()
    try:
        summary = wg.stream(records, "%s:%d" % srv.addr, tick_s=0.0,
                            max_wall_s=300.0)
    finally:
        srv.stop = True
        th.join(timeout=30)
        srv.close()
        fleet.close()
    soak = {
        "requests": int(len(records)),
        "completed": int(summary["completed"]),
        "rejects": int(summary["rejects"]),
        "retries": int(summary["retries"]),
        "wall_s": float(summary["wall_s"]),
        "tokens_out": int(summary["tokens_out"]),
        "goodput_tokens_per_s": round(
            summary["tokens_out"] / max(summary["wall_s"], 1e-9), 3),
        "stream_sha256": str(summary["stream_sha256"]),
    }
    print(json.dumps({"fleet_resilience": "soak", **soak},
                     allow_nan=False), flush=True)

    markers = {
        "sigkill_identity": all(r["identical"] for r in kill_matrix),
        "sigkill_zero_token_loss": all(r["tokens_lost"] == 0
                                       for r in kill_matrix),
        "process_isolated": all(r["process_isolated"]
                                and r["declared_dead"] == 1
                                for r in kill_matrix),
        "restart_identity": restart["identical"],
        "restart_prefill_saved": restart["prefill_tokens_saved"] > 0,
        "socket_soak_served": soak["completed"] == soak["requests"] > 0,
    }
    markers = {k: bool(v) for k, v in markers.items()}
    return {"markers": markers,
            "meta": {"model": "tiny", "replicas": 2,
                     "builder": "gpt2_tiny"},
            "kill_matrix": kill_matrix, "restart": restart,
            "socket_soak": soak}


def bench_slo(model_name: str, family: str, quant: str, block_size: int,
              requests: int = 48, seed: int = 0,
              slo_ttft_ms: float = 30_000.0, slo_tok_ms: float = 5_000.0,
              slo_p99: float = 0.99) -> dict:
    """The ISSUE 17 evidence: the seeded workload_gen soak through the
    metrics plane. One fixed open-loop workload (Poisson + bursts,
    heavy-tail lengths, shared-prefix populations — scripts/
    workload_gen.generate, imported by file path like the other script
    cross-imports) runs twice through identical engines: once with the
    metrics plane OFF (the baseline token streams) and once with
    metrics + SLO monitor ON (the measured soak). Banked:

    - TTFT and per-token decode latency p50/p95/p99 — read from the
      LogHistogram sketches, so the banked numbers exercise the same
      bounded path a fleet aggregates through;
    - goodput — tokens/s counted ONLY from requests that finished
      successfully (eos | length) with TTFT inside the target (the
      per-token side of the SLO is judged fleet-wide by the banked
      tok_ms quantiles and the breach counter — per-request wall decode
      clocks live inside the monitor and are not re-derivable here);
    - terminal status counts, token-loss accounting, breach count;
    - the ``metrics_inert`` marker: ON-run token streams byte-identical
      to the OFF run — the whole plane must be observationally free.

    The wide default targets are deliberate: a shared CI box can stall
    for seconds, and this leg's regression gate is token loss + schema +
    inertness, not wall-clock luck. Tight-target burn-rate behavior is
    pinned deterministically in tests/test_serve_metrics.py with an
    injected clock."""
    import importlib.util

    from distributed_lion_tpu.serve.engine import Request
    from distributed_lion_tpu.serve.metrics import ServeMetrics, SLOMonitor

    wg_path = os.path.join(REPO, "scripts", "workload_gen.py")
    spec_ = importlib.util.spec_from_file_location("dlt_workload_gen",
                                                   wg_path)
    wg = importlib.util.module_from_spec(spec_)
    spec_.loader.exec_module(wg)

    _, _, cfg = _serve_model(model_name, family)
    prompt_max, out_max = 24, 24
    records = wg.generate(
        requests=requests, seed=seed, rate=1.0, burst_every=10,
        burst_size=3, vocab=cfg.vocab_size, prompt_median=8.0,
        prompt_max=prompt_max, out_median=8.0, out_max=out_max)
    reqs = [Request(req_id=r["id"], tokens=list(r["tokens"]),
                    max_new_tokens=r["max_new_tokens"], seed=r["seed"],
                    prefix_group=r.get("prefix_group"))
            for r in records]
    arrivals = {r["id"]: r["arrival_tick"] for r in records}
    nblocks = -(-(prompt_max + out_max + 2) // block_size)

    def fresh(**kw):
        eng, _, _ = _build(model_name, family, quant, 8, block_size,
                           nblocks, **kw)
        return eng

    def clone(rs):
        return [Request(r.req_id, list(r.tokens), r.max_new_tokens,
                        r.seed, prefix_group=r.prefix_group) for r in rs]

    base = fresh().run(clone(reqs), dict(arrivals))

    eng = fresh(metrics=True)
    eng.metrics = ServeMetrics(eng.times, slo=SLOMonitor(
        ttft_ms=slo_ttft_ms, tok_ms=slo_tok_ms, p99=slo_p99))
    t0 = time.perf_counter()
    done = eng.run(clone(reqs), dict(arrivals))
    wall_s = max(time.perf_counter() - t0, 1e-9)

    inert = (set(done) == set(base) and all(
        done[i].tokens == base[i].tokens
        and done[i].reason == base[i].reason for i in base))
    tokens_lost = int(sum(
        max(len(base[i].tokens) - len(done.get(i, base[i]).tokens), 0)
        for i in base))
    timed = all(
        isinstance(c.timing, dict)
        and isinstance(c.timing.get("queue_ticks"), int)
        and isinstance(c.timing.get("decode_ticks"), int)
        for c in done.values())

    counts = {k: 0 for k in ("eos", "length", "overflow", "timeout",
                             "failed")}
    for c in done.values():
        counts[c.reason] = counts.get(c.reason, 0) + 1
    good_tokens = sum(
        len(c.tokens) for c in done.values()
        if c.reason in ("eos", "length") and isinstance(c.timing, dict)
        and c.timing.get("ttft_ms") is not None
        and c.timing["ttft_ms"] <= slo_ttft_ms)

    snap = eng.metrics.snapshot()
    quantiles = {
        sec: {k: round(float(snap[sec][k]), 4)
              for k in ("p50", "p95", "p99")}
        for sec in ("ttft_ms", "tok_ms")}
    markers = {
        "metrics_inert": bool(inert),
        "zero_token_loss": bool(tokens_lost == 0),
        "responses_timed": bool(timed),
    }
    out = {
        "markers": markers,
        "targets": {"ttft_ms": float(slo_ttft_ms),
                    "tok_ms": float(slo_tok_ms), "p99": float(slo_p99)},
        "requests": int(len(done)),
        "tokens_out": int(sum(len(c.tokens) for c in done.values())),
        "tokens_lost": tokens_lost,
        "ticks": int(eng.stats["ticks"]),
        "breaches": int(eng.metrics.slo.breaches),
        "ttft_ms": quantiles["ttft_ms"],
        "tok_ms": quantiles["tok_ms"],
        "goodput_tokens_per_sec": round(float(good_tokens) / wall_s, 3),
        "status_counts": counts,
    }
    print(json.dumps({"slo": "soak", **{k: v for k, v in out.items()
                                        if k != "markers"}, **markers},
                     allow_nan=False), flush=True)
    return out


def main() -> int:
    from distributed_lion_tpu.parallel.mesh import force_cpu_platform

    force_cpu_platform()  # DLION_PLATFORM=cpu8 → 8 virtual devices for
    #                       the TP legs (must run before first device use)

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=os.path.join(REPO, "runs", "serving"))
    ap.add_argument("--model", default=None,
                    help="tiny (default off-TPU) | gpt2_124m (default on TPU)"
                         " | llama small/...")
    ap.add_argument("--family", default="gpt2", choices=("gpt2", "llama"))
    ap.add_argument("--quant", default="none",
                    choices=("none", "nf4", "int8"),
                    help="weight format of the MEASURED decode arm (the "
                         "bytes columns always report both)")
    ap.add_argument("--batches", default=",".join(map(str, DEFAULT_BATCHES)))
    ap.add_argument("--block_size", type=int, default=16)
    ap.add_argument("--ticks", type=int, default=30)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--spec_batch", type=int, default=8,
                    help="decode batch of the speculative frontier legs "
                         "(smaller than the decode rows: each leg runs "
                         "drafter x k x workload engines)")
    ap.add_argument("--spec_ks", default="2,4",
                    help="draft lengths measured per drafter")
    ap.add_argument("--tps", default="1,2,4",
                    help="TP degrees for the tp_serving rows (degrees the "
                         "backend/model can't run are dropped LOUDLY)")
    ap.add_argument("--tp_batch", type=int, default=32,
                    help="decode batch of the TP rows")
    ap.add_argument("--prefix_requests", type=int, default=256,
                    help="requests in the shared-system-prompt memory leg")
    ap.add_argument("--slo_requests", type=int, default=48,
                    help="requests in the seeded workload_gen soak of "
                         "the slo section")
    ap.add_argument("--slo_ttft_ms", type=float, default=30_000.0,
                    help="banked TTFT target of the slo soak (wide by "
                         "default: the gate is token loss + schema + "
                         "metrics inertness, not CI wall-clock luck)")
    ap.add_argument("--slo_tok_ms", type=float, default=5_000.0,
                    help="banked per-token latency target of the slo soak")
    ap.add_argument("--moe_eps", default="2,4",
                    help="expert-parallel degrees for the moe_serving "
                         "matrix rows (infeasible degrees dropped LOUDLY; "
                         "ep=1 is covered by the bit-identity marker)")
    args = ap.parse_args()

    import jax

    from distributed_lion_tpu.ops.quant import quantize_tree
    from distributed_lion_tpu.serve.engine import weight_bytes

    backend = jax.default_backend()
    model_name = args.model or ("gpt2_124m" if backend == "tpu" else "tiny")
    batches = [int(b) for b in args.batches.split(",") if b]

    # the NF4-vs-bf16 column: measured storage bytes of the same tree in
    # both formats (dense counted at 2 bytes/param — the bf16 serving
    # copy — so an f32 checkpoint doesn't inflate the comparison)
    _, params, cfg = _build(model_name, args.family, "none", 2,
                            args.block_size, 2)
    n_params = sum(x.size for x in jax.tree.leaves(params))
    bytes_bf16 = 2 * n_params
    bytes_nf4 = weight_bytes(quantize_tree(params, "nf4"))
    del params

    decode_rows = []
    for b in batches:
        row = bench_decode(b, model_name, args.family, args.quant,
                           args.block_size, args.ticks, args.warmup)
        row["weight_bytes_bf16"] = int(bytes_bf16)
        row["weight_bytes_nf4"] = int(bytes_nf4)
        decode_rows.append(row)
        print(json.dumps(row, allow_nan=False), flush=True)

    share_rows = bench_prefill_share(model_name, args.family, args.quant,
                                     [args.block_size, 4 * args.block_size,
                                      1 << 30], args.block_size)
    bits = bit_identity_markers(args.family)
    spec = bench_speculative(model_name, args.family, args.quant,
                             args.block_size, args.ticks, args.warmup,
                             args.spec_batch,
                             tuple(int(k) for k in args.spec_ks.split(",")
                                   if k))
    tp_serving = bench_tp_serving(
        model_name, args.family, args.quant, args.block_size, args.ticks,
        args.warmup, args.tp_batch,
        [int(t) for t in args.tps.split(",") if t], args.prefix_requests)
    serve_resilience = bench_serve_resilience(
        model_name, args.family, args.quant, args.block_size)
    fleet_resilience = bench_fleet_resilience(args.block_size)
    # MoE is a gpt2 architecture; a llama bench still measures the MoE
    # matrix against the default gpt2 model at this scale
    moe_base = (model_name if args.family == "gpt2"
                else ("gpt2_124m" if backend == "tpu" else "tiny"))
    moe_serving = bench_moe_serving(
        moe_base, args.quant, args.block_size, args.ticks, args.warmup,
        batches, [int(e) for e in args.moe_eps.split(",") if e])
    slo = bench_slo(model_name, args.family, args.quant, args.block_size,
                    requests=args.slo_requests,
                    slo_ttft_ms=args.slo_ttft_ms,
                    slo_tok_ms=args.slo_tok_ms)

    doc = {
        "meta": {
            "backend": backend,
            "device_kind": jax.devices()[0].device_kind,
            "num_devices": 1,  # the engine is single-device today; rows
            #                    are per chip by construction
            "model": model_name,
            "family": args.family,
            "quant_measured": args.quant,
            "block_size": args.block_size,
            "prompt_len": PROMPT_LEN,
            "n_params": int(n_params),
        },
        "decode": decode_rows,
        "prefill_share": share_rows,
        "bit_identity": bits,
        "speculative": spec,
        "tp_serving": tp_serving,
        "serve_resilience": serve_resilience,
        "fleet_resilience": fleet_resilience,
        "moe_serving": moe_serving,
        "slo": slo,
    }
    os.makedirs(args.out, exist_ok=True)
    path = os.path.join(args.out, "serving.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, allow_nan=False)
        f.write("\n")
    os.replace(tmp, path)
    print(json.dumps({"artifact": path, **bits,
                      **{f"spec_{k}": v
                         for k, v in spec["markers"].items()},
                      **{f"tp_{k}": v
                         for k, v in tp_serving["markers"].items()},
                      **{f"sr_{k}": v
                         for k, v in serve_resilience["markers"].items()},
                      **{f"fr_{k}": v
                         for k, v in fleet_resilience["markers"].items()},
                      **{f"moe_{k}": v
                         for k, v in moe_serving["markers"].items()},
                      **{f"slo_{k}": v
                         for k, v in slo["markers"].items()},
                      "prefix_mem_ratio":
                          tp_serving["prefix"]["prefix_mem_ratio"],
                      "best_tokens_per_sec_per_chip": max(
                          r["tokens_per_sec_per_chip"] for r in decode_rows)},
                     allow_nan=False), flush=True)
    return 0 if (all(bits.values()) and all(spec["markers"].values())
                 and all(tp_serving["markers"].values())
                 and all(serve_resilience["markers"].values())
                 and all(fleet_resilience["markers"].values())
                 and all(moe_serving["markers"].values())
                 and all(slo["markers"].values())) else 1


if __name__ == "__main__":
    sys.exit(main())
