#!/usr/bin/env python
"""Decode benchmark for the serving engine — the serving twin of bench.py.

Measures the continuous-batching engine (distributed_lion_tpu/serve/) the
way the training bench measures the train step, and writes ONE strict-JSON
evidence artifact under ``runs/serving/`` that check_evidence's ``serving``
stage judges (so serving regressions gate like training ones —
ROADMAP item 4):

- **decode rows** — tokens/s/chip at full-occupancy decode batch
  {32, 128, 256} (every slot active, K timed one-dispatch ticks), each row
  carrying the NF4-vs-bf16 weight-bytes column (ops/quant: the measured
  storage of the quantized tree vs the 2-byte/param bf16 dense serve).
- **prefill-share ablation** — the same staggered workload drained under
  different ``prefill_cap_tokens`` fairness caps: how much decode
  throughput a prefill burst is allowed to steal per tick.
- **bit-identity markers** — (a) greedy decode through the paged engine
  vs the dense-KV ``models/generate.generate`` on the same prompts with
  MATCHED attended length (bit-identical logits ⇒ identical tokens), and
  (b) a staggered continuous-batching run vs solo runs of each request.
  Both recomputed live at artifact-capture time; check_evidence requires
  them true.

CPU-produced artifacts are first-class smoke evidence (tiny model — the
engine mechanism, not chip throughput); ``meta.backend`` records what
measured it, and the runbook re-captures on chip at gpt2_124m.

    python scripts/bench_serve.py --out runs/serving
    python scripts/bench_serve.py --batches 32 --ticks 10   # quick look
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

PROMPT_LEN = 16          # decode-row prompt length (uniform: the decode
#                          measurement wants full slots, not prompt variety)
DEFAULT_BATCHES = (32, 128, 256)


def _build(model_name: str, family: str, quant: str, max_seqs: int,
           block_size: int, max_blocks_per_seq: int,
           prefill_cap: int = 1 << 30, temperature: float = 0.0):
    import jax

    from distributed_lion_tpu.serve.engine import (
        ServeConfig,
        ServeModel,
        ServingEngine,
    )

    if family == "gpt2":
        from distributed_lion_tpu.models.gpt2 import GPT2Config, gpt2_init

        cfg = (GPT2Config.tiny() if model_name == "tiny"
               else GPT2Config.gpt2_124m())
        params = gpt2_init(jax.random.key(0), cfg)
        model = ServeModel.for_gpt2(params, cfg)
    else:
        from distributed_lion_tpu.models.llama import LlamaConfig, llama_init

        cfg = LlamaConfig.named(model_name)
        params = llama_init(jax.random.key(0), cfg)
        model = ServeModel.for_llama(params, cfg)
    scfg = ServeConfig(max_seqs=max_seqs, block_size=block_size,
                       max_blocks_per_seq=max_blocks_per_seq,
                       prefill_cap_tokens=prefill_cap,
                       temperature=temperature, quant=quant)
    return ServingEngine(model, scfg), params, cfg


def _prompts(n: int, vocab: int, length: int = PROMPT_LEN, seed: int = 0):
    import numpy as np

    rng = np.random.default_rng(seed)
    return [list(map(int, rng.integers(1, vocab, length))) for _ in range(n)]


def bench_decode(batch: int, model_name: str, family: str, quant: str,
                 block_size: int, ticks: int, warmup: int) -> dict:
    """Fill every slot, then time ``ticks`` full-batch decode dispatches."""
    from distributed_lion_tpu.serve.engine import Request

    need = PROMPT_LEN + warmup + ticks + 2
    nblocks = -(-need // block_size)
    engine, params, cfg = _build(model_name, family, quant, batch,
                                 block_size, nblocks)
    for i, toks in enumerate(_prompts(batch, cfg.vocab_size)):
        engine.submit(Request(req_id=i, tokens=toks,
                              max_new_tokens=need, seed=i))
    while engine.pending:  # prefill phase (uncapped) until every slot runs
        engine.step()
    assert all(s is not None for s in engine.slots), "slots did not fill"
    for _ in range(warmup):
        engine.step()
    t0 = time.perf_counter()
    for _ in range(ticks):
        engine.step()  # each tick host-syncs its token batch — the
        #                dispatch is fully retired inside the window
    dt = time.perf_counter() - t0
    return {
        "batch": batch,
        "decode_ticks": ticks,
        "ms_per_tick": round(dt / ticks * 1e3, 4),
        "tokens_per_sec_per_chip": round(batch * ticks / dt, 2),
        "quant": quant,
    }


def bench_prefill_share(model_name: str, family: str, quant: str,
                        caps: list, block_size: int) -> list:
    """Drain one staggered mixed workload per fairness cap: the ablation
    showing what a prefill burst costs the decode batch."""
    from distributed_lion_tpu.serve.engine import Request

    rows = []
    for cap in caps:
        engine, params, cfg = _build(model_name, family, quant, 16,
                                     block_size, 8, prefill_cap=cap)
        prompts = _prompts(48, cfg.vocab_size, seed=7)
        reqs = [Request(req_id=i, tokens=t, max_new_tokens=24, seed=i)
                for i, t in enumerate(prompts)]
        arrivals = {i: (i // 8) * 2 for i in range(len(reqs))}
        t0 = time.perf_counter()
        done = engine.run(reqs, arrivals)
        dt = time.perf_counter() - t0
        total = sum(len(c.tokens) for c in done.values())
        st = engine.stats
        rows.append({
            "prefill_cap_tokens": cap,
            "ticks": st["ticks"],
            "tokens_per_sec": round(total / dt, 2),
            "prefill_token_share": round(
                st["padded_prefill_tokens"]
                / max(st["padded_prefill_tokens"] + st["decode_tokens"], 1),
                4),
        })
    return rows


def bit_identity_markers(family: str) -> dict:
    """Live recompute of the two serving bit-identity claims on the tiny
    model (cheap on any backend) — the artifact must EARN its markers at
    capture time, not copy them from a test run."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_lion_tpu.models.generate import generate
    from distributed_lion_tpu.serve.engine import Request

    block_size, nblk = 4, 8                  # paged horizon = 32 tokens
    new_tokens = 8
    engine, params, cfg = _build("tiny", family, "none", 4, block_size, nblk)
    if family == "gpt2":
        from distributed_lion_tpu.models.gpt2 import gpt2_decode, gpt2_init_cache

        def dec(p, t, c, pos, off=None):
            return gpt2_decode(p, t, cfg, c, pos, off)

        def ic(b, m):
            return gpt2_init_cache(cfg, b, m)
    else:
        from distributed_lion_tpu.models.llama import llama_decode, llama_init_cache

        def dec(p, t, c, pos, off=None):
            return llama_decode(p, t, cfg, c, pos, off)

        def ic(b, m):
            return llama_init_cache(cfg, b, m)

    # (a) paged engine vs dense generate, MATCHED attended length
    # (max_len == blocks*block_size), uniform prompts, greedy
    prompts = _prompts(4, cfg.vocab_size, length=7, seed=11)
    dense = np.asarray(generate(
        dec, ic, params, jnp.asarray(prompts, jnp.int32), new_tokens,
        max_len=block_size * nblk))
    done = engine.run([Request(req_id=i, tokens=t, max_new_tokens=new_tokens,
                               seed=0) for i, t in enumerate(prompts)])
    paged_vs_dense = all(
        list(dense[i]) == done[i].tokens for i in range(len(prompts)))

    # (b) staggered continuous batching vs solo runs, varied lengths
    varied = [p[: 3 + 2 * i] for i, p in enumerate(_prompts(4, cfg.vocab_size,
                                                            length=12, seed=13))]
    reqs = [Request(req_id=i, tokens=t, max_new_tokens=new_tokens, seed=i)
            for i, t in enumerate(varied)]
    eng2, _, _ = _build("tiny", family, "none", 4, block_size, nblk)
    stag = eng2.run(reqs, arrivals={0: 0, 1: 1, 2: 1, 3: 4})
    ok = True
    for r in reqs:
        solo_eng, _, _ = _build("tiny", family, "none", 4, block_size, nblk)
        solo = solo_eng.run([Request(r.req_id, list(r.tokens),
                                     r.max_new_tokens, r.seed)])
        ok = ok and solo[r.req_id].tokens == stag[r.req_id].tokens
    return {"paged_vs_dense": bool(paged_vs_dense),
            "batched_vs_solo": bool(ok)}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=os.path.join(REPO, "runs", "serving"))
    ap.add_argument("--model", default=None,
                    help="tiny (default off-TPU) | gpt2_124m (default on TPU)"
                         " | llama small/...")
    ap.add_argument("--family", default="gpt2", choices=("gpt2", "llama"))
    ap.add_argument("--quant", default="none",
                    choices=("none", "nf4", "int8"),
                    help="weight format of the MEASURED decode arm (the "
                         "bytes columns always report both)")
    ap.add_argument("--batches", default=",".join(map(str, DEFAULT_BATCHES)))
    ap.add_argument("--block_size", type=int, default=16)
    ap.add_argument("--ticks", type=int, default=30)
    ap.add_argument("--warmup", type=int, default=5)
    args = ap.parse_args()

    import jax

    from distributed_lion_tpu.ops.quant import quantize_tree
    from distributed_lion_tpu.serve.engine import weight_bytes

    backend = jax.default_backend()
    model_name = args.model or ("gpt2_124m" if backend == "tpu" else "tiny")
    batches = [int(b) for b in args.batches.split(",") if b]

    # the NF4-vs-bf16 column: measured storage bytes of the same tree in
    # both formats (dense counted at 2 bytes/param — the bf16 serving
    # copy — so an f32 checkpoint doesn't inflate the comparison)
    _, params, cfg = _build(model_name, args.family, "none", 2,
                            args.block_size, 2)
    n_params = sum(x.size for x in jax.tree.leaves(params))
    bytes_bf16 = 2 * n_params
    bytes_nf4 = weight_bytes(quantize_tree(params, "nf4"))
    del params

    decode_rows = []
    for b in batches:
        row = bench_decode(b, model_name, args.family, args.quant,
                           args.block_size, args.ticks, args.warmup)
        row["weight_bytes_bf16"] = int(bytes_bf16)
        row["weight_bytes_nf4"] = int(bytes_nf4)
        decode_rows.append(row)
        print(json.dumps(row, allow_nan=False), flush=True)

    share_rows = bench_prefill_share(model_name, args.family, args.quant,
                                     [args.block_size, 4 * args.block_size,
                                      1 << 30], args.block_size)
    bits = bit_identity_markers(args.family)

    doc = {
        "meta": {
            "backend": backend,
            "device_kind": jax.devices()[0].device_kind,
            "num_devices": 1,  # the engine is single-device today; rows
            #                    are per chip by construction
            "model": model_name,
            "family": args.family,
            "quant_measured": args.quant,
            "block_size": args.block_size,
            "prompt_len": PROMPT_LEN,
            "n_params": int(n_params),
        },
        "decode": decode_rows,
        "prefill_share": share_rows,
        "bit_identity": bits,
    }
    os.makedirs(args.out, exist_ok=True)
    path = os.path.join(args.out, "serving.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, allow_nan=False)
        f.write("\n")
    os.replace(tmp, path)
    print(json.dumps({"artifact": path, **bits,
                      "best_tokens_per_sec_per_chip": max(
                          r["tokens_per_sec_per_chip"] for r in decode_rows)},
                     allow_nan=False), flush=True)
    return 0 if all(bits.values()) else 1


if __name__ == "__main__":
    sys.exit(main())
