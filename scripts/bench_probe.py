"""Probe the timing semantics of the tunneled TPU backend: compare
block_until_ready vs device_get sync, and throughput vs number of steps.
If tokens/s inflates with step count or sync method, the dispatch queue is
absorbing work and the timer must fetch a value dependent on the full chain.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(batch_per_dev=8, remat=True):
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_lion_tpu.data.sources import synthetic_lm_dataset
    from distributed_lion_tpu.models.gpt2 import GPT2Config
    from distributed_lion_tpu.parallel.mesh import make_mesh
    from distributed_lion_tpu.train.loop import TrainConfig, Trainer

    mesh = make_mesh()
    model_cfg = dataclasses.replace(GPT2Config.gpt2_124m(), remat=remat)
    cfg = TrainConfig(
        lion=True, async_grad=True, learning_rate=1e-4, weight_decay=0.1,
        warmup_steps=10, max_steps=10_000,
        per_device_train_batch_size=batch_per_dev,
        gradient_accumulation_steps=1, block_size=model_cfg.n_ctx,
        logging_steps=10_000, output_dir=None,
        # pin the banked-row methodology (see bench.py): auto would change
        # the measured comm on W>1 meshes
        wire="sign_psum", vote_every=1,
    )
    trainer = Trainer.for_gpt2(cfg, mesh, model_cfg)
    global_bs = trainer.global_train_batch()
    tokens_per_step = global_bs * cfg.block_size
    blocks = synthetic_lm_dataset(global_bs, cfg.block_size, model_cfg.vocab_size, seed=0)
    batch = jax.device_put(blocks[:global_bs].astype(np.int32),
                           NamedSharding(mesh, P("data")))
    key = jax.random.key(0)
    trainer.params, trainer.state, trainer.vote_health, m = (
        trainer._train_step(trainer.params, trainer.state,
                            trainer.vote_health, trainer._frozen_arg(),
                            batch, key))
    print("warmup loss:", float(np.asarray(jax.device_get(m["loss"]))), flush=True)

    for steps, sync in [(5, "get"), (20, "get"), (50, "get"), (20, "block"),
                        (20, "get_each")]:
        t0 = time.perf_counter()
        for _ in range(steps):
            trainer.params, trainer.state, trainer.vote_health, m = (
                trainer._train_step(trainer.params, trainer.state,
                                    trainer.vote_health,
                                    trainer._frozen_arg(), batch, key))
            if sync == "get_each":
                _ = float(np.asarray(jax.device_get(m["loss"])))
        if sync == "block":
            jax.block_until_ready(m["loss"])
        elif sync == "get":
            _ = float(np.asarray(jax.device_get(m["loss"])))
        dt = time.perf_counter() - t0
        print(json.dumps({
            "steps": steps, "sync": sync, "ms_per_step": round(dt / steps * 1e3, 1),
            "tokens_per_sec": round(tokens_per_step * steps / dt, 1),
        }), flush=True)


if __name__ == "__main__":
    main()
