"""Throughput sweep for bench.py tuning: remat × batch × attention impl.

Uses the fused K-step dispatch (Trainer._train_chunk) and an honest
device_get sync on the final loss, so tunnel dispatch latency is amortized
and the timer can't stop before the device work exists. Prints one JSON line
per config. Used to pick the flagship bench configuration; not run by the
driver.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

K = 10          # steps per device dispatch
N_CHUNKS = 4    # timed dispatches → K * N_CHUNKS steps


def run(remat: str, batch_per_dev: int, attn_impl: str = "auto",
        accum: int = 1, dtype: str = "f32", vocab_chunks: int = 0,
        mom_dtype: str = "", vocab_pad: int = 0) -> float:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_lion_tpu.data.sources import synthetic_lm_dataset
    from distributed_lion_tpu.models.gpt2 import GPT2Config
    from distributed_lion_tpu.parallel.mesh import make_mesh
    from distributed_lion_tpu.train.loop import TrainConfig, Trainer

    n_dev = len(jax.devices())
    mesh = make_mesh()
    from distributed_lion_tpu.ops.attention import parse_attn_spec

    attn_spec = attn_impl
    attn_impl, bq, bkv, bqb, bkvb = parse_attn_spec(attn_spec)
    model_cfg = dataclasses.replace(
        GPT2Config.gpt2_124m(), remat=remat != "noremat",
        remat_policy="dots" if remat == "dots" else "full",
        attn_impl=attn_impl, flash_block_q=bq, flash_block_kv=bkv,
        flash_block_q_bwd=bqb, flash_block_kv_bwd=bkvb,
        param_dtype=jnp.bfloat16 if dtype == "bf16" else jnp.float32,
        vocab_pad_multiple=vocab_pad,
    )
    cfg = TrainConfig(
        lion=True, async_grad=True, learning_rate=1e-4, weight_decay=0.1,
        warmup_steps=10, max_steps=10_000,
        per_device_train_batch_size=batch_per_dev,
        gradient_accumulation_steps=accum, block_size=model_cfg.n_ctx,
        steps_per_call=K, logging_steps=10_000, output_dir=None,
        vocab_chunks=vocab_chunks, mom_dtype=mom_dtype,
    )
    trainer = Trainer.for_gpt2(cfg, mesh, model_cfg)
    global_bs = trainer.global_train_batch()
    tokens_per_step = global_bs * cfg.block_size
    blocks = synthetic_lm_dataset(global_bs * K, cfg.block_size,
                                  model_cfg.vocab_size, seed=0)
    batches = jax.device_put(
        blocks[: global_bs * K].astype(np.int32).reshape(K, global_bs, cfg.block_size),
        NamedSharding(mesh, P(None, "data")),
    )
    key = jax.random.key(0)
    trainer.params, trainer.state, m = trainer._train_chunk(
        trainer.params, trainer.state, trainer._frozen_arg(), batches, key
    )
    _ = float(np.asarray(jax.device_get(m["loss"])))  # warmup + honest sync
    t0 = time.perf_counter()
    for _ in range(N_CHUNKS):
        trainer.params, trainer.state, m = trainer._train_chunk(
            trainer.params, trainer.state, trainer._frozen_arg(), batches, key
        )
    final_loss = float(np.asarray(jax.device_get(m["loss"])))
    dt = time.perf_counter() - t0
    steps = K * N_CHUNKS
    tps = tokens_per_step * steps / dt / n_dev
    print(json.dumps({
        "remat": remat, "batch_per_dev": batch_per_dev, "attn": attn_spec,
        "accum": accum, "dtype": dtype, "vocab_chunks": vocab_chunks,
        "mom_dtype": mom_dtype or "f32", "vocab_pad": vocab_pad,
        "ms_per_step": round(dt / steps * 1e3, 1), "loss": round(final_loss, 3),
        "tokens_per_sec_per_chip": round(tps, 1),
    }), flush=True)
    return tps


if __name__ == "__main__":
    # spec: remat:batch[:attn[@bqxbkv][:accum[:dtype[:chunks[:mom[:pad]]]]]]
    DEFAULTS = ["auto", "1", "f32", "0", ""]
    for spec in sys.argv[1:]:
        parts = spec.split(":")
        parts += DEFAULTS[len(parts) - 2:]  # pad only the missing tail
        remat_s, bs_s, attn, accum_s, dtype = parts[:5]
        vc = int(parts[5]) if len(parts) > 5 else 0
        mom = parts[6] if len(parts) > 6 else ""
        pad = int(parts[7]) if len(parts) > 7 else 0
        try:
            run(remat_s, int(bs_s), attn, int(accum_s), dtype, vc,
                "bfloat16" if mom in ("bf16", "bfloat16") else mom, pad)
        except Exception as e:  # OOM on big configs: report and keep sweeping
            print(json.dumps({
                "remat": remat_s, "batch_per_dev": int(bs_s),
                "attn": attn, "accum": int(accum_s), "dtype": dtype,
                "vocab_chunks": vc, "vocab_pad": pad,
                "error": str(e).split("\n")[0][:160],
            }), flush=True)
