"""Throughput sweep over bench.py's tuning axes: remat x batch x attention
impl/tiles x accum x dtype x vocab_chunks x momentum dtype x vocab pad x T.

Since round 4 each config runs as a CHILD `bench.py --inner` process driven
through the BENCH_* env knobs — bench.py's timed-step implementation (fused
K-step dispatch via Trainer._train_chunk, honest device_get sync on the
final loss) IS the sweep's measurement core, so a sweep row and a bench.py
capture are the same methodology by construction (round-3 had two
hand-kept copies that the judge flagged as 14% apart across configs).
Every row records backend/device_kind from the child so a CPU/fallback-
produced row can never masquerade as TPU evidence (bench._best_sweep_row
filters on it). Prints one JSON line per config; errors become error rows
so a sweep survives OOM/hang on individual configs. Used to pick the
flagship bench configuration; not run by the driver.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")
sys.path.insert(0, REPO)
# shared with bench.main()'s own child handling: ONE output parser and ONE
# process-group child lifecycle (spawn in own session, SIGKILL the group on
# timeout/SIGTERM/exit) — the TPU-lock-release semantics live in bench.py
# only, so the two harnesses can't drift
from bench import (  # noqa: E402
    _extract_json_line,
    install_child_teardown,
    run_child,
)

# per-config budget: TPU compile of a fresh (attn-tile, shape) combination
# is 20-40s cached / worse cold, plus 50 fused steps (~35s) — 1200s is
# ample, AND two consecutive timeouts (the backend-down abort threshold
# below) still fit inside the runbook's smallest stage window (timeout
# 3000), so the abort path actually fires instead of the outer SIGTERM
CONFIG_TIMEOUT_S = float(os.environ.get("SWEEP_CONFIG_TIMEOUT_S", "1200"))


def _row_key(d: dict) -> tuple:
    return (d.get("remat"), d.get("batch_per_dev"), d.get("attn"),
            d.get("accum"), d.get("dtype"), d.get("vocab_chunks", 0),
            d.get("mom_dtype", "f32"), d.get("vocab_pad", 0),
            d.get("block", 1024), d.get("vote_buckets", 1))


def _captured_keys() -> set:
    """Config keys already holding a RESULT row in $SWEEP_SKIP_FILE (the
    jsonl this sweep appends to): lets a watcher-re-fired window resume at
    the first unmeasured config instead of re-burning chip time on captured
    ones. Error rows don't count — a config that failed gets retried."""
    path = os.environ.get("SWEEP_SKIP_FILE", "")
    keys: set = set()
    if not path:
        return keys
    try:
        with open(path) as f:
            for line in f:
                try:
                    d = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if d.get("tokens_per_sec_per_chip"):
                    keys.add(_row_key(d))
    except OSError:
        pass
    return keys


def run(remat: str, batch_per_dev: int, attn_impl: str = "auto",
        accum: int = 1, dtype: str = "f32", vocab_chunks: int = 0,
        mom_dtype: str = "", vocab_pad: int = 0, block: int = 1024,
        vote_buckets: int = 1) -> float:
    row = {
        "remat": remat, "batch_per_dev": batch_per_dev, "attn": attn_impl,
        "accum": accum, "dtype": dtype, "vocab_chunks": vocab_chunks,
        "mom_dtype": mom_dtype or "f32", "vocab_pad": vocab_pad,
    }
    if block != 1024:
        row["block"] = block
    if vote_buckets != 1:
        # only carried when non-default so pre-buckets rows keep matching
        # their skip keys / evidence markers (same treatment as block)
        row["vote_buckets"] = vote_buckets
    env = dict(os.environ)
    env.update({
        "BENCH_REMAT": remat, "BENCH_BATCH": str(batch_per_dev),
        "BENCH_ATTN": attn_impl, "BENCH_ACCUM": str(accum),
        "BENCH_DTYPE": dtype, "BENCH_VOCAB_CHUNKS": str(vocab_chunks),
        "BENCH_MOM_DTYPE": mom_dtype, "BENCH_VOCAB_PAD": str(vocab_pad),
        "BENCH_BLOCK": str(block),
        "BENCH_VOTE_BUCKETS": str(vote_buckets),
    })
    try:
        rc, stdout, stderr = run_child(
            [sys.executable, BENCH, "--inner"], env, CONFIG_TIMEOUT_S, REPO)
    except subprocess.TimeoutExpired:
        print(json.dumps(
            {**row, "error": f"timeout after {CONFIG_TIMEOUT_S:.0f}s"}),
            flush=True)
        return -1.0  # distinguishable from an error row: timeouts in a row
        # usually mean the tunnel died, and the caller aborts the window
    rec = _extract_json_line(stdout)
    if rc != 0 or rec is None:
        tail = (stderr or stdout or "").strip().splitlines()[-3:]
        print(json.dumps(
            {**row, "error": (f"rc={rc}: " + " | ".join(tail))[:200]}),
            flush=True)
        return 0.0
    row.update({
        "ms_per_step": rec.get("ms_per_step"),
        "loss": rec.get("loss"),
        "tokens_per_sec_per_chip": rec.get("value"),
        "mfu": rec.get("mfu"),
        "backend": rec.get("backend"),
        "device_kind": rec.get("device_kind"),
    })
    if rec.get("attn_resolved") is not None:
        # what the autotune-cache resolver made of an 'auto' attn spec on
        # the measuring device (bench.py consults ops/autotune — the one
        # resolver — and reports it); "auto" = cache miss, heuristics ran
        row["attn_resolved"] = rec["attn_resolved"]
    print(json.dumps(row), flush=True)
    return float(rec.get("value") or 0.0)


if __name__ == "__main__":
    # spec: remat:batch[:attn[@bqxbkv[@bqbxbkvb]][:accum[:dtype[:chunks[
    #   :mom[:pad[:T[:buckets]]]]]]]]
    install_child_teardown()
    DEFAULTS = ["auto", "1", "f32", "0", ""]
    consecutive_timeouts = 0
    captured = _captured_keys()
    for spec in sys.argv[1:]:
        parts = spec.split(":")
        parts += DEFAULTS[len(parts) - 2:]  # pad only the missing tail
        remat_s, bs_s, attn, accum_s, dtype = parts[:5]
        vc = int(parts[5]) if len(parts) > 5 else 0
        mom = parts[6] if len(parts) > 6 else ""
        pad = int(parts[7]) if len(parts) > 7 else 0
        block = int(parts[8]) if len(parts) > 8 and parts[8] else 1024
        buckets = int(parts[9]) if len(parts) > 9 and parts[9] else 1
        mom = "bfloat16" if mom in ("bf16", "bfloat16") else mom
        key = (remat_s, int(bs_s), attn, int(accum_s), dtype, vc,
               mom or "f32", pad, block, buckets)
        if key in captured:
            print(f"[sweep] skip (already captured): {spec}",
                  file=sys.stderr, flush=True)
            continue
        tps = run(remat_s, int(bs_s), attn, int(accum_s), dtype, vc,
                  mom, pad, block, buckets)
        consecutive_timeouts = consecutive_timeouts + 1 if tps < 0 else 0
        if consecutive_timeouts >= 2:
            # two full-budget child timeouts back-to-back = the backend is
            # gone (the tunnel hangs without erroring); stop burning the
            # stage window so the re-arming watcher can retry the REMAINING
            # configs on the next recovery instead of timing out here
            print(json.dumps({"abort": "2 consecutive config timeouts — "
                              "backend presumed down"}), flush=True)
            sys.exit(3)
