"""Single source of truth for "is this round-3 TPU evidence captured?" —
shared by the idempotent runbook (scripts/tpu_runbook_auto2.sh, per-stage
skip guards) and the re-arming watcher (scripts/tpu_watch_loop.sh, exit
condition), so the two can never disagree about what "captured" means.

    python scripts/check_evidence.py parity local   # exit 0 = captured
    python scripts/check_evidence.py sweep2
    python scripts/check_evidence.py sft7b
    python scripts/check_evidence.py bench_best
    python scripts/check_evidence.py all
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "scripts", "SWEEP_r3_raw")
PARITY_MIN_STEP = 1900

# the LAST config of the runbook's sweep window / 7B spec list: the stages
# run sequentially and bench_sweep/bench_sft_7b emit a row (result OR
# error) per config before moving on, so the last config's row implies the
# whole window executed
SWEEP2_LAST_CONFIG = "512x1024@512x512"
# round-4 anchor-chasing window (scripts/SWEEP_r3_raw/sweep3.jsonl): the
# last config is the T=2048 bwd-tile leg; batch_per_dev=2 disambiguates it
# from sweep3's T=1024 rows with the same attn spec (row dicts are
# insertion-ordered, so this fragment is stable)
SWEEP3_LAST_CONFIG = '"batch_per_dev": 2, "attn": "flash@512x1024@512x512"'
# structurally anchored to the last 7B spec's row (nf4:1:2:8::2048:dots —
# the only spec with seq_len 2048, and row dicts are insertion-ordered) —
# a bare "2048" needle would also match unrelated numbers (ms_per_step,
# tok/s) in EARLIER specs' rows and mark the stage captured before the
# 2048 leg ran
SFT7B_LAST_SPEC = '"seq_len": 2048'


def parity(mode: str) -> bool:
    """Captured = enough steps AND stamped as an f32-master-params run —
    bf16-era curves had frozen large-magnitude params (Lion's ±lr is below
    bf16 ULP there) and must not satisfy the evidence check."""
    try:
        last, f32 = 0, False
        with open(os.path.join(REPO, "runs", "parity", f"{mode}.jsonl")) as f:
            for line in f:
                try:
                    d = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if d.get("meta"):
                    f32 = d.get("param_dtype") == "float32"
                last = max(last, d.get("step", 0))
        return f32 and last >= PARITY_MIN_STEP
    except OSError:
        return False


def _window_captured(path: str, needle: str, result_key: str) -> bool:
    """Captured = the LAST window config has a RESULT row (stages run
    sequentially, so it implies every earlier config executed). An ERROR
    row for the marker config does NOT count: a window where every config
    failed fast (tunnel died mid-stage but each config still emitted an
    error row) must not mark the stage captured — and because the files are
    append-mode across watcher re-fires, a file-global "any result row"
    check would be satisfied by a PREVIOUS window's banked rows. This is
    the watcher's EXIT condition only — earlier configs that errored
    transiently are retried regardless: the runbook's sweep stages run
    UNCONDITIONALLY on every recovery and bench_sweep's SWEEP_SKIP_FILE
    skips result-row configs only, so retries cost seconds, not chip
    time."""
    try:
        with open(path) as f:
            return any(needle in line and result_key in line for line in f)
    except OSError:
        return False


def sweep2() -> bool:
    return _window_captured(os.path.join(OUT, "sweep2.jsonl"),
                            SWEEP2_LAST_CONFIG, "tokens_per_sec_per_chip")


def sweep3() -> bool:
    return _window_captured(os.path.join(OUT, "sweep3.jsonl"),
                            SWEEP3_LAST_CONFIG, "tokens_per_sec_per_chip")


def sft7b() -> bool:
    return _window_captured(os.path.join(OUT, "sft7b2.jsonl"),
                            SFT7B_LAST_SPEC, "tokens_per_sec_per_chip")


def bench_best() -> bool:
    return os.path.exists(os.path.join(OUT, "bench_best.done"))


def conv() -> bool:
    """Real-corpus convergence artifact (VERDICT r3 stretch): ≥1900 steps of
    the canonical-config run_clm with the reference's convergence signals
    (eval accuracy/perplexity, /root/reference/run_clm.py:562-577, 630-636)
    logged in runs/convergence/metrics.jsonl."""
    try:
        last, has_eval = 0, False
        with open(os.path.join(REPO, "runs", "convergence",
                               "metrics.jsonl")) as f:
            for line in f:
                try:
                    d = json.loads(line)
                except json.JSONDecodeError:
                    continue
                last = max(last, d.get("step", 0))
                if any(k.startswith("eval/") for k in d):
                    has_eval = True
        return has_eval and last >= 1900
    except OSError:
        return False


# the ONE stage list both check("all") and the CLI printout derive from —
# adding a stage here updates the watcher exit condition and the operator
# status display together
STAGES = [
    ("sweep2", sweep2),
    ("sweep3", sweep3),
    ("bench_best", bench_best),
    ("sft7b", sft7b),
    ("parity:local", lambda: parity("local")),
    ("parity:vote", lambda: parity("vote")),
    ("parity:lazy", lambda: parity("lazy")),
    ("conv", conv),
]


def check(what: str, arg: str | None = None) -> bool:
    if what == "parity":
        return parity(arg or "local")
    if what == "sweep2":
        return sweep2()
    if what == "sweep3":
        return sweep3()
    if what == "sft7b":
        return sft7b()
    if what == "bench_best":
        return bench_best()
    if what == "conv":
        return conv()
    if what == "all":
        return all(fn() for _, fn in STAGES)
    raise SystemExit(f"unknown evidence check {what!r}")


if __name__ == "__main__":
    what = sys.argv[1]
    if what == "all":
        # per-stage status printout for operators; exit 0 only when complete
        status = [(name, fn()) for name, fn in STAGES]
        for name, ok in status:
            print(f"{name}: {'captured' if ok else 'MISSING'}")
        sys.exit(0 if all(ok for _, ok in status) else 1)
    ok = check(what, sys.argv[2] if len(sys.argv) > 2 else None)
    sys.exit(0 if ok else 1)
