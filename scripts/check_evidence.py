"""Single source of truth for "is this round-3 TPU evidence captured?" —
shared by the idempotent runbook (scripts/tpu_runbook_auto2.sh, per-stage
skip guards) and the re-arming watcher (scripts/tpu_watch_loop.sh, exit
condition), so the two can never disagree about what "captured" means.

    python scripts/check_evidence.py parity local   # exit 0 = captured
    python scripts/check_evidence.py sweep2
    python scripts/check_evidence.py sft7b
    python scripts/check_evidence.py bench_best
    python scripts/check_evidence.py overlap        # buckets {1,4,16} rows
    python scripts/check_evidence.py telemetry      # vote-health JSONL
    python scripts/check_evidence.py static         # graft-check both tiers
    python scripts/check_evidence.py vote_guard     # poisoned-run rescue
    python scripts/check_evidence.py autotune       # TPU-keyed tuning cache
    python scripts/check_evidence.py journal        # run-journal attribution
    python scripts/check_evidence.py dcn_overlap    # pipelined hier DCN leg
    python scripts/check_evidence.py serving        # paged-KV decode bench
    python scripts/check_evidence.py speculative    # draft/verify/commit
    python scripts/check_evidence.py tp_serving     # TP decode + prefix share
    python scripts/check_evidence.py serve_resilience  # replica fault matrix
    python scripts/check_evidence.py fleet_resilience  # SIGKILLed processes
    python scripts/check_evidence.py moe_serving    # MoE paged decode + ep
    python scripts/check_evidence.py elasticity     # live worker leave/join
    python scripts/check_evidence.py all

parity:vote / parity:lazy are STRICT since ISSUE 6: a leg counts as
captured only when the pre-registered numeric criterion PASSES (mean
|Δloss| vs local over the tail ≤ PARITY_EPS_NATS), not on mere presence.
The watcher exit condition (`automation`) still judges presence — see
_AUTOMATION_OVERRIDES.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "scripts", "SWEEP_r3_raw")
PARITY_MIN_STEP = 1900
# full-scale TPU legs take precedence; runs/parity_cpu holds the reduced
# (>=10M-param, short-seq) CPU legs captured when the tunnel is dead —
# legs are only ever COMPARED within one directory (same scale/config)
PARITY_DIRS = ("parity", "parity_cpu")
# ---- the pre-registered numeric parity criterion (VERDICT r4 #4), pinned
# BEFORE the data lands: over the last quarter of training, the mean
# per-logged-step |loss(vote) - loss(local)| must be within EPS nats (legs
# share seed => identical per-step batches, so the gap is optimizer
# trajectory, not data noise). Same bound for the lazy (vote_every=4) leg.
# loss_parity.py --phase report imports these and prints PASS/FAIL.
PARITY_EPS_NATS = 0.05
PARITY_TAIL_FRAC = 0.75


def _load_leg(dirname: str, mode: str):
    """(meta, {step: loss}) from runs/<dirname>/<mode>.jsonl, or None.
    ``dirname`` may also be an absolute directory (loss_parity's report
    phase reuses this loader on an arbitrary --out dir)."""
    base = (dirname if os.path.isabs(dirname)
            else os.path.join(REPO, "runs", dirname))
    meta, curve = None, {}
    try:
        with open(os.path.join(base, f"{mode}.jsonl")) as f:
            for line in f:
                try:
                    d = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn last line from a mid-write crash
                if d.get("meta"):
                    meta = d
                elif "loss" in d and "step" in d:
                    curve[d["step"]] = d["loss"]
    except OSError:
        return None
    return (meta, curve) if meta is not None else None


def _leg_ok(leg) -> bool:
    """Captured = enough steps AND stamped as an f32-master-params run —
    bf16-era curves had frozen large-magnitude params (Lion's ±lr is below
    bf16 ULP there) and must not satisfy the evidence check."""
    if leg is None:
        return False
    meta, curve = leg
    return (meta.get("param_dtype") == "float32"
            and curve and max(curve) >= PARITY_MIN_STEP)


def _metas_comparable(a: dict, b: dict) -> bool:
    """Two legs may only be numerically compared when every config stamp
    they BOTH carry (scale, seed, batch, precision, step budget — all but
    the mode itself) agrees; intersection semantics keep older metas
    without the round-5 scale stamps comparable."""
    keys = (set(a) & set(b)) - {"mode", "meta", "backend"}
    return all(a[k] == b[k] for k in keys)


def parity(mode: str) -> bool:
    """Presence check (the watcher/automation exit condition): a
    qualifying leg exists in either parity directory. The evidence-facing
    ``parity:*`` stages use :func:`parity_strict` — presence alone is NOT
    capture for the vote/lazy legs anymore (ISSUE 6: a present-but-
    diverged curve must not read 'captured'); presence stays the
    AUTOMATION semantics because a failing numeric criterion is
    deterministic in the seed and needs a human, not an infinite watcher
    loop re-burning identical 2000-step legs."""
    return any(_leg_ok(_load_leg(d, mode)) for d in PARITY_DIRS)


def parity_strict(mode: str) -> bool:
    """The ``parity:<mode>`` stage: a qualifying leg exists AND — for the
    vote/lazy comparison legs — the pre-registered numeric criterion
    PASSES in the directory providing it (mean |Δloss| vs the same-dir
    local leg over the last (1 − PARITY_TAIL_FRAC) of steps ≤
    PARITY_EPS_NATS — with 10-step logging over 2000 steps that tail is
    the last 500 steps). ``local`` is the baseline leg: presence only."""
    if mode == "local":
        return parity("local")
    for d in PARITY_DIRS:
        if not _leg_ok(_load_leg(d, mode)):
            continue
        m = parity_mad(d, mode)
        if m is not None and m <= PARITY_EPS_NATS:
            return True
    return False


def parity_full(mode: str) -> bool:
    """Full-scale (runs/parity) presence only — the TPU runbook's stage-6
    skip guard. Reduced CPU legs satisfy parity()/the watcher, but must
    NOT stop a live TPU window from capturing the flagship-scale legs the
    docs say take precedence (code-review r5)."""
    return _leg_ok(_load_leg("parity", mode))


def parity_mad(dirname: str, mode: str):
    """Mean |loss(mode) - loss(local)| over the common logged steps in the
    last (1 - PARITY_TAIL_FRAC) of training, or None when either leg in
    that directory is missing/unqualified/config-mismatched."""
    leg_l, leg_m = _load_leg(dirname, "local"), _load_leg(dirname, mode)
    if not (_leg_ok(leg_l) and _leg_ok(leg_m)):
        return None
    if not _metas_comparable(leg_l[0], leg_m[0]):
        return None
    steps = leg_l[0].get("steps", PARITY_MIN_STEP)
    tail = [s for s in sorted(set(leg_l[1]) & set(leg_m[1]))
            if s >= PARITY_TAIL_FRAC * steps]
    if not tail:
        return None
    return sum(abs(leg_m[1][s] - leg_l[1][s]) for s in tail) / len(tail)


def parity_pass() -> bool:
    """The parity:PASS stage: some directory holds a complete local leg
    plus vote AND lazy legs whose tail curves are within PARITY_EPS_NATS
    of it. This is what makes check_evidence able to FAIL on bad parity
    data, not only on absent data (VERDICT r4 #4)."""
    for d in PARITY_DIRS:
        mads = [parity_mad(d, m) for m in ("vote", "lazy")]
        if all(m is not None and m <= PARITY_EPS_NATS for m in mads):
            return True
    return False


def _window_captured(path: str, marker: dict, result_key: str) -> bool:
    """Captured = the LAST window config has a RESULT row (stages run
    sequentially, so it implies every earlier config executed). Rows are
    parsed as JSON and the marker compared field-by-field — substring
    needles were coupled to dict insertion order and separator spacing
    (advisor r4). An ERROR row for the marker config does NOT count: a
    window where every config failed fast (tunnel died mid-stage but each
    config still emitted an error row) must not mark the stage captured —
    and because the files are append-mode across watcher re-fires, a
    file-global "any result row" check would be satisfied by a PREVIOUS
    window's banked rows. This is the watcher's EXIT condition only —
    earlier configs that errored transiently are retried regardless: the
    runbook's sweep stages run UNCONDITIONALLY on every recovery and
    bench_sweep's SWEEP_SKIP_FILE skips result-row configs only, so
    retries cost seconds, not chip time."""
    try:
        with open(path) as f:
            for line in f:
                try:
                    d = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if not isinstance(d, dict) or not d.get(result_key):
                    continue
                if d.get("validate"):
                    # pipeline-validation rows (bench_sft_7b SFT7B_VALIDATE)
                    # exercise the code path, not the measurement — they
                    # must never mark a capture stage done
                    continue
                if all(d.get(k, _MARKER_DEFAULTS.get(k)) == v
                       for k, v in marker.items()):
                    return True
        return False
    except OSError:
        return False


# absent row fields fall back to the emitting script's defaults before the
# marker compare (round-3 sweep2 rows omit block when it is 1024;
# pre-buckets rows omit vote_buckets when it is 1)
_MARKER_DEFAULTS = {"block": 1024, "vote_buckets": 1}

# the LAST config of each runbook window's spec list, as structural field
# markers (stages run sequentially, so the last config's result row
# implies the whole window executed):
#   sweep2 — noremat:4:flash@512x1024@512x512:...:1024 (bwd-tile leg)
#   sweep3 — noremat:2:flash@512x1024@512x512:...:2048 (T=2048 bwd-tile
#            leg; batch+block disambiguate it from the same attn at T=1024)
#   sft7b  — nf4:1:2:8::2048:dots (the only spec with seq_len 2048)
SWEEP2_MARKER = {"attn": "flash@512x1024@512x512", "block": 1024}
SWEEP3_MARKER = {"attn": "flash@512x1024@512x512", "batch_per_dev": 2,
                 "block": 2048}
SFT7B_MARKER = {"seq_len": 2048}


def sweep2() -> bool:
    return _window_captured(os.path.join(OUT, "sweep2.jsonl"),
                            SWEEP2_MARKER, "tokens_per_sec_per_chip")


def sweep3() -> bool:
    return _window_captured(os.path.join(OUT, "sweep3.jsonl"),
                            SWEEP3_MARKER, "tokens_per_sec_per_chip")


def sft7b() -> bool:
    return _window_captured(os.path.join(OUT, "sft7b2.jsonl"),
                            SFT7B_MARKER, "tokens_per_sec_per_chip")


def bench_best() -> bool:
    return os.path.exists(os.path.join(OUT, "bench_best.done"))


# the vote-wire overlap ablation (ISSUE 1): the flagship anchor config at
# vote_buckets ∈ {1, 4, 16} — every cell must hold a RESULT row, because the
# measured comm_overlap_frac (bench.overlap_from_ablation) needs the B=1
# anchor AND at least one pipelined row, and the {4, 16} pair shows whether
# more buckets keep buying overlap or launch latency wins
OVERLAP_BUCKETS = (1, 4, 16)


def overlap() -> bool:
    path = os.path.join(OUT, "overlap.jsonl")
    return all(
        _window_captured(path, {"vote_buckets": b}, "tokens_per_sec_per_chip")
        for b in OVERLAP_BUCKETS
    )


def dpo(tpu_only: bool = False) -> bool:
    """A DPO step-rate + comm-bytes result row exists (VERDICT r4 #7 —
    the last workload without numbers). Any backend counts for the
    evidence stage (rows carry backend honestly; the CPU-mesh fallback is
    explicitly allowed); ``tpu_only`` is the runbook's stage guard, so a
    live window still captures a chip row once."""
    return _window_captured(os.path.join(OUT, "dpo.jsonl"),
                            {"backend": "tpu"} if tpu_only else {},
                            "tokens_per_sec_per_chip")


def conv(dirname: str | None = None) -> bool:
    """Real-corpus convergence artifact (VERDICT r3 stretch, r4 #6):
    ≥1900 steps of run_clm with the reference's convergence signals (eval
    accuracy/perplexity, /root/reference/run_clm.py:562-577, 630-636)
    logged in metrics.jsonl. Canonical-config TPU run in
    runs/convergence; the reduced tunnel-dead fallback (gpt2_small on the
    same corpus/BPE, scripts/conv_cpu_chain.sh) in runs/convergence_cpu —
    mirror of the parity-leg directory split."""
    dirs = (dirname,) if dirname else ("convergence", "convergence_cpu")
    for d in dirs:
        try:
            last, has_eval = 0, False
            with open(os.path.join(REPO, "runs", d, "metrics.jsonl")) as f:
                for line in f:
                    try:
                        r = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    last = max(last, r.get("step", 0))
                    if any(k.startswith("eval/") for k in r):
                        has_eval = True
            if has_eval and last >= 1900:
                return True
        except OSError:
            continue
    return False


# vote-health telemetry artifact (ISSUE 2): the runbook's telemetry stage
# runs a short --telemetry --nan_sentinel training (runs/telemetry) whose
# metrics.jsonl must hold vote-health rows with a CONSERVED margin
# histogram: the histogram is normalized per voted coordinate, so its mass
# times the voted-coordinate count must equal the voted-coordinate count
# (mass == 1 ⇔ every voted coordinate landed in a bin — the invariant that
# catches binning/masking bugs in the on-device accumulator). Only rows
# from tally wires are judged (margin_exact == 1; the two-phase wires ship
# a ±1 proxy and zero the histogram by design).
TELEMETRY_MASS_RTOL = 0.01


def telemetry_ok(dirname: str = "telemetry") -> bool:
    path = os.path.join(REPO, "runs", dirname, "metrics.jsonl")
    found = False
    try:
        with open(path) as f:
            for line in f:
                try:
                    r = json.loads(line)
                except json.JSONDecodeError:
                    continue
                hist = r.get("train/vote/margin_hist")
                if hist is None or r.get("train/vote/margin_exact") != 1:
                    continue
                voted = r.get("train/vote/voted_per_step", 0)
                if not voted or None in hist:
                    return False
                mass = sum(hist)
                if abs(mass * voted - voted) > TELEMETRY_MASS_RTOL * voted:
                    return False  # histogram lost/invented coordinates
                found = True
    except OSError:
        return False
    return found


# resilience artifact (ISSUE 3): the runbook's resilience stage runs a short
# async-checkpoint training (runs/resilience) plus a synchronous baseline
# (runs/resilience_sync). Captured = the async run's newest checkpoint
# VERIFIES (per-file sha256 manifest + COMMITTED marker, via the pure-stdlib
# reader in distributed_lion_tpu.train.resilience — no jax import) AND the
# async run's logged ckpt_stall_s peak is below the sync baseline's (the
# overlap actually keeps the step loop unblocked at save boundaries).

def _peak_metric(path: str, key: str):
    peak = None
    try:
        with open(path) as f:
            for line in f:
                try:
                    r = json.loads(line)
                except json.JSONDecodeError:
                    continue
                v = r.get(key)
                if isinstance(v, (int, float)):
                    peak = v if peak is None else max(peak, v)
    except OSError:
        return None
    return peak


def resilience_ok(dirname: str = "resilience") -> bool:
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    try:
        from distributed_lion_tpu.train.resilience import latest_valid_step_in
    except ImportError:
        return False
    base = os.path.join(REPO, "runs", dirname)
    if latest_valid_step_in(os.path.join(base, "checkpoints")) is None:
        return False  # no committed+verified checkpoint — the stage's point
    a = _peak_metric(os.path.join(base, "metrics.jsonl"),
                     "train/ckpt_stall_s")
    s = _peak_metric(os.path.join(REPO, "runs", f"{dirname}_sync",
                                  "metrics.jsonl"), "train/ckpt_stall_s")
    # the sync leg must have actually paid a visible save (>0) for the
    # comparison to mean anything
    return a is not None and s is not None and s > 0 and a < s


# vote-guard artifact (ISSUE 5): the runbook's vote_guard stage runs four
# short same-seed trainings under runs/vote_guard/ —
#   clean          (no poison, --vote_guard off)
#   clean_enforce  (no poison, --vote_guard enforce)
#   poison_enforce (one flipped-ballot worker, enforce)
#   poison_off     (same poison, guard off)
# Captured = (a) ALL-HEALTHY BIT-IDENTITY: clean and clean_enforce log
# byte-identical loss curves (enforce with an all-True mask must not move
# one election), and (b) the DEGRADED-MODE claim: poison_enforce's tail
# loss stays within GUARD_ENFORCE_EPS of clean while poison_off sits at
# least GUARD_MIN_GAP further out — the guard demonstrably rescues the run
# the adversary demonstrably degrades. (The stricter clean-W−1 comparison
# is pinned by tests/test_vote_guard.py, where the mesh can be carved.)
GUARD_ENFORCE_EPS = 0.35
GUARD_MIN_GAP = 0.1
GUARD_TAIL_FRAC = 0.75
GUARD_MIN_STEPS = 30


def _loss_curve(dirname: str):
    path = os.path.join(REPO, "runs", dirname, "metrics.jsonl")
    curve = {}
    try:
        with open(path) as f:
            for line in f:
                try:
                    r = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(r.get("train/loss"), (int, float)) \
                        and isinstance(r.get("step"), int):
                    curve[r["step"]] = r["train/loss"]
    except OSError:
        return None
    return curve or None


def _tail_mean(curve: dict):
    last = max(curve)
    tail = [v for s, v in curve.items() if s >= GUARD_TAIL_FRAC * last]
    return sum(tail) / len(tail)


def vote_guard_ok(base: str = "vote_guard") -> bool:
    legs = {leg: _loss_curve(os.path.join(base, leg))
            for leg in ("clean", "clean_enforce", "poison_enforce",
                        "poison_off")}
    if any(c is None or max(c) < GUARD_MIN_STEPS for c in legs.values()):
        return False
    clean, clean_enf = legs["clean"], legs["clean_enforce"]
    common = sorted(set(clean) & set(clean_enf))
    if not common or any(clean[s] != clean_enf[s] for s in common):
        return False  # all-healthy enforce moved an election
    gap_enf = abs(_tail_mean(legs["poison_enforce"]) - _tail_mean(clean))
    gap_off = abs(_tail_mean(legs["poison_off"]) - _tail_mean(clean))
    return gap_enf <= GUARD_ENFORCE_EPS and gap_off >= gap_enf + GUARD_MIN_GAP


# static-analysis gate (ISSUE 4): the stage is green when (a) the
# ci_static.sh gate passes RIGHT NOW — ruff baseline + graft-check tier-1
# AST lint + shellcheck, each skipped gracefully where not installed — and
# (b) the jaxpr contract tier's report (written by the runbook's static
# stage via `python -m distributed_lion_tpu.analysis --tier2 --json-out`)
# exists with ok=true. Tier 1 re-runs on every poll (sub-second, no jax);
# tier 2 traces the real train step, so it is captured once per runbook
# pass like every other evidence artifact.
STATIC_TIER2_REPORT = os.path.join(OUT, "static_tier2.json")

# serve-plane graft-check gate (ISSUE 19): the committed
# runs/static/serve_check.json (written by `python -m
# distributed_lion_tpu.analysis serve-check --json-out`, re-captured by
# the runbook's stage 0b) passes validate_metrics' strict schema — every
# matrix cell present and ok, inventories re-derived equal, zero host
# callbacks, donation present, compile counts within budget.
SERVE_CHECK_REPORT = os.path.join(REPO, "runs", "static",
                                  "serve_check.json")


def static_serve_ok(path: str | None = None) -> bool:
    path = path or SERVE_CHECK_REPORT
    if not os.path.exists(path):
        return False
    vm = _validate_metrics_module()
    return not vm.validate_json_doc(path)


def static_ok() -> bool:
    try:
        gate = subprocess.run(
            ["bash", os.path.join(REPO, "scripts", "ci_static.sh")],
            capture_output=True, timeout=600)
    except (OSError, subprocess.TimeoutExpired):
        return False
    if gate.returncode != 0:
        return False
    try:
        with open(STATIC_TIER2_REPORT) as f:
            report = json.load(f)
    except (OSError, json.JSONDecodeError):
        return False
    return report.get("ok") is True


# the autotune stage (ISSUE 6): the committed device-keyed tuning cache
# (scripts/tuning_cache.json, written by cli/run_tune) exists, passes the
# strict schema, and holds at least one TPU-keyed entry — i.e. the on-chip
# tile search actually ran. The validator is ops/autotune's stdlib-only
# validate_cache_doc, loaded by FILE PATH so this script stays jax-free
# (the package __init__ pulls in jax).
TUNE_CACHE = os.path.join(REPO, "scripts", "tuning_cache.json")


def _autotune_module():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "dlt_autotune_standalone",
        os.path.join(REPO, "distributed_lion_tpu", "ops", "autotune.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def autotune_ok() -> bool:
    """Captured = the cache validates AND EVERY knob holds a TPU-keyed
    entry — all five are tunable on chip, so 'search complete' means all
    five landed. Requiring any-one-entry would let a window that dropped
    after the first knob permanently skip the rest (the runbook re-fires
    with --skip_cached, so finished knobs cost nothing on recovery)."""
    try:
        with open(TUNE_CACHE) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return False
    try:
        at = _autotune_module()
    except Exception:
        return False
    if at.validate_cache_doc(doc):
        return False
    tpu_knobs = {key.split("|")[1] for key in doc["entries"]
                 if key.split("|")[0].lower().startswith("tpu")}
    return set(at.KNOBS) <= tpu_knobs


# the run-journal stage (ISSUE 7): the runbook's journal leg records a
# --journal training (runs/journal) whose journal must (a) exist and parse
# under the strict schema (run_analyze counts schema errors), (b) close —
# named buckets + other + unattributed == measured wall — and (c) attribute
# at least JOURNAL_MIN_COVERAGE of the measured step wall to the NAMED
# buckets (device / dispatch / data / ckpt / logging): the acceptance
# criterion that makes the next MFU push start from a named stall budget
# instead of a guess. The analyzer is cli/run_analyze — stdlib-only,
# loaded by FILE PATH like the autotune validator, so this script stays
# jax-free.
JOURNAL_MIN_COVERAGE = 0.95


def _run_analyze_module():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "dlt_run_analyze_standalone",
        os.path.join(REPO, "distributed_lion_tpu", "cli", "run_analyze.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# the DCN-overlap stage (ISSUE 8): scripts/bench_dcn.py's artifact under
# runs/dcn_overlap — (a) passes the strict dcn_overlap.json schema
# (validate_metrics, loaded by FILE PATH so this script stays jax-free),
# (b) the depth-0 bit-identity legs hold (the dcn_delay fault is
# timing-only and the synchronous wire deterministic), (c) the depth-1
# pipeline recovered >= DCN_OVERLAP_MIN of the injected per-step latency,
# (d) the bits-per-param × steps-to-loss frontier is present and
# row-valid, and (e) the pre-registered depth {1,2} loss-parity bound
# held. A CPU-produced artifact is first-class here: the DCN link is
# emulated on every backend (the point is the pipeline mechanism, not
# chip throughput); meta.backend records what measured it.
DCN_OVERLAP_MIN = 0.8
DCN_ARTIFACT = os.path.join(REPO, "runs", "dcn_overlap", "dcn_overlap.json")


def _validate_metrics_module():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "dlt_validate_metrics_standalone",
        os.path.join(REPO, "scripts", "validate_metrics.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def dcn_overlap_ok(path: str = DCN_ARTIFACT) -> bool:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return False
    try:
        vm = _validate_metrics_module()
        if vm.validate_json_doc(path):
            return False  # schema violations
    except Exception:
        return False
    bit = doc.get("bit_identity", {})
    if not (bit.get("depth0_deterministic") is True
            and bit.get("depth0_fault_inert") is True):
        return False
    overlap = doc.get("overlap", {})
    frac = overlap.get("recovered_frac_depth1")
    if not isinstance(frac, (int, float)) or frac < DCN_OVERLAP_MIN:
        return False
    if not doc.get("frontier"):
        return False
    return doc.get("parity", {}).get("pass") is True


# the serving stage (ISSUE 9): scripts/bench_serve.py's artifact under
# runs/serving — (a) passes the strict serving.json schema
# (validate_metrics, loaded by FILE PATH so this script stays jax-free),
# (b) both live-recomputed bit-identity markers hold (paged-engine greedy
# == dense-KV generate at matched attended length; staggered continuous
# batching == solo runs per request), (c) a decode row exists at every
# required batch size {32, 128, 256} with tokens/s/chip above the floor —
# SERVE_MIN_TOKS is calibrated to the banked CPU smoke artifact (tiny
# model on a 2-core box measures >1k; a TPU gpt2_124m run is orders of
# magnitude above), so any regression that stalls the tick loop trips it
# on every backend — and (d) the NF4 weight-bytes column actually shows
# the 4-bit story (nf4 < bf16/3, i.e. < ~0.67 byte/param incl. scales).
SERVE_ARTIFACT = os.path.join(REPO, "runs", "serving", "serving.json")
SERVE_BATCHES = (32, 128, 256)
SERVE_MIN_TOKS = 50.0


def serving_ok(path: str = SERVE_ARTIFACT) -> bool:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return False
    try:
        vm = _validate_metrics_module()
        if vm.validate_json_doc(path):
            return False  # schema violations
    except Exception:
        return False
    bits = doc.get("bit_identity", {})
    if not (bits.get("paged_vs_dense") is True
            and bits.get("batched_vs_solo") is True):
        return False
    rows = {r.get("batch"): r for r in doc.get("decode", [])}
    for b in SERVE_BATCHES:
        row = rows.get(b)
        if row is None or not isinstance(
                row.get("tokens_per_sec_per_chip"), (int, float)):
            return False
        if row["tokens_per_sec_per_chip"] < SERVE_MIN_TOKS:
            return False
        if not (isinstance(row.get("weight_bytes_nf4"), int)
                and isinstance(row.get("weight_bytes_bf16"), int)
                and row["weight_bytes_nf4"] * 3 < row["weight_bytes_bf16"]):
            return False
    return True


# the speculative stage (ISSUE 11): the speculative-decode section of
# the SAME serving.json artifact (bench_serve writes both; stage 5j
# re-captures on chip) — (a) the whole artifact passes the strict schema
# (which pins accept_rate ∈ [0,1], drafter/k/tokens-per-sec columns on
# every frontier row), (b) both live-recomputed speculative identity
# markers hold (greedy speculative == plain paged decode; sampled
# speculative == the same per-request PRNG stream — speculation may only
# change SPEED, never an output), (c) the frontier actually covers the
# claim: a non-speculative baseline row plus both drafters measured on
# the repetitive AND random workloads, and (d) the n-gram drafter EARNS
# accept_rate > 0 on the repetitive workload (prompt-lookup drafting
# must work where its traffic exists, not just ride the schema).
def speculative_ok(path: str = SERVE_ARTIFACT) -> bool:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return False
    try:
        vm = _validate_metrics_module()
        if vm.validate_json_doc(path):
            return False  # schema violations (incl. accept_rate range)
    except Exception:
        return False
    spec = doc.get("speculative")
    if not isinstance(spec, dict):
        return False
    marks = spec.get("markers", {})
    if not (marks.get("greedy_vs_plain") is True
            and marks.get("sampled_vs_stream") is True):
        return False
    rows = spec.get("frontier", [])
    for workload in ("repetitive", "random"):
        here = [r for r in rows if r.get("workload") == workload]
        if not any(r.get("drafter") == "none" for r in here):
            return False  # no baseline to read the frontier against
        for drafter in ("ngram", "draft"):
            if not any(r.get("drafter") == drafter for r in here):
                return False
    return any(r.get("drafter") == "ngram"
               and r.get("workload") == "repetitive"
               and r.get("accept_rate", 0) > 0 for r in rows)


# the tp_serving stage (ISSUE 13): the TP-sharded + prefix-sharing
# section of the SAME serving.json artifact (bench_serve writes it;
# runbook stage 5k re-captures on chip) — (a) the whole artifact passes
# the strict schema (validate_metrics: TP rows + prefix leg per-row
# validated), (b) ALL FIVE live-recomputed identity markers hold (tp=1
# sharded == unsharded, tp>1 == unsharded on the measuring mesh, and
# shared-prefix == unshared for greedy/sampled/speculative decode —
# sharding and sharing may only change HBM and speed, never an output),
# (c) a TP row at degree >= 2 exists (the section is about multi-chip
# serving; on CPU the bench runs under DLION_PLATFORM=cpu8) with
# tokens/s/chip above the same floor the serving stage uses at every
# measured degree, and (d) the shared-system-prompt workload actually
# demonstrates the memory story: >= 256 requests and
# prefix_mem_ratio <= TP_SERVE_MEM_RATIO (physical ÷ logical pages,
# both MEASURED by draining the workload through both engines).
TP_SERVE_MEM_RATIO = 0.15
TP_SERVE_MIN_REQUESTS = 256


def tp_serving_ok(path: str = SERVE_ARTIFACT) -> bool:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return False
    try:
        vm = _validate_metrics_module()
        if vm.validate_json_doc(path):
            return False  # schema violations
    except Exception:
        return False
    sec = doc.get("tp_serving")
    if not isinstance(sec, dict):
        return False
    marks = sec.get("markers", {})
    for k in ("tp1_vs_unsharded", "tpN_vs_unsharded",
              "shared_vs_unshared_greedy", "shared_vs_unshared_sampled",
              "shared_vs_unshared_speculative"):
        if marks.get(k) is not True:
            return False
    rows = sec.get("rows", [])
    if not any(r.get("tp", 0) >= 2 for r in rows):
        return False  # no multi-chip measurement: the section's point
    for r in rows:
        if not isinstance(r.get("tokens_per_sec_per_chip"), (int, float)):
            return False
        if r["tokens_per_sec_per_chip"] < SERVE_MIN_TOKS:
            return False
    pref = sec.get("prefix", {})
    if pref.get("requests", 0) < TP_SERVE_MIN_REQUESTS:
        return False
    ratio = pref.get("prefix_mem_ratio")
    if not isinstance(ratio, (int, float)) or ratio > TP_SERVE_MEM_RATIO:
        return False
    return True


# the moe_serving stage (ISSUE 15): the MoE-serving section of the SAME
# serving.json artifact (bench_serve writes it; runbook stage 5m
# re-captures on chip) — (a) the whole artifact passes the strict schema
# (validate_metrics: matrix rows per-row validated incl.
# capacity_utilization/dropped_rate ∈ [0,1] and the ISSUE 16
# sharding/beats_dense_per_chip columns), (b) ALL TEN live-recomputed
# identity markers hold (paged MoE decode == dense-KV MoE generate,
# engine batched == solo, left-padded batched generate == solo — the
# lifted PR 9 refusals — plus ep=1 bit-identical to the unsharded engine,
# ep>=2 / ep×tp token-identical on the measuring mesh, and the four
# batch-sharded markers: ep_batch at ep=1 bit-identical, ep>=2 / ep×tp /
# microbatch-overlap token-identical), and (c) the matrix actually
# covers the claim: a dense baseline row, a MoE row, a replicated MoE+ep
# row at ep >= 2, AND a batch-sharded row at the same (batch, ep) whose
# per-chip tokens/s is STRICTLY above the replicated row's — ep as a
# throughput lever, not just an HBM lever — with every MoE row carrying
# a measured tokens/s/chip above the serving floor and its
# capacity-utilization and dropped-rate columns.
MOE_SERVE_MARKERS = ("paged_vs_dense", "batched_vs_solo",
                     "batched_generate_vs_solo", "ep1_vs_unsharded",
                     "epN_vs_unsharded", "ep_tp_vs_unsharded",
                     "ep_batch1_vs_unsharded", "ep_batchN_vs_unsharded",
                     "ep_batch_tp_vs_unsharded",
                     "ep_batch_overlap_vs_unsharded")


def moe_serving_ok(path: str = SERVE_ARTIFACT) -> bool:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return False
    try:
        vm = _validate_metrics_module()
        if vm.validate_json_doc(path):
            return False  # schema violations
    except Exception:
        return False
    sec = doc.get("moe_serving")
    if not isinstance(sec, dict):
        return False
    marks = sec.get("markers", {})
    for k in MOE_SERVE_MARKERS:
        if marks.get(k) is not True:
            return False
    rows = sec.get("rows", [])
    configs = {r.get("config") for r in rows}
    if "dense" not in configs or "moe" not in configs:
        return False  # no baseline (or no MoE arm) to read the matrix
    if not any(r.get("ep", 0) >= 2 and r.get("experts", 0) > 0
               for r in rows):
        return False  # no expert-parallel measurement: the section's point
    # ISSUE 16: at least one (batch, ep>=2) pair must carry BOTH a
    # replicated and a batch-sharded row, and the batch-sharded row's
    # per-chip throughput must be STRICTLY above the replicated one —
    # otherwise 'ep is a throughput lever' is an unmeasured claim
    lever = False
    for r in rows:
        if r.get("sharding") != "batch" or r.get("ep", 0) < 2:
            continue
        rep = [x for x in rows
               if x.get("sharding") == "replicated"
               and x.get("ep") == r.get("ep")
               and x.get("batch") == r.get("batch")]
        if rep and all(r.get("tokens_per_sec_per_chip", 0)
                       > x.get("tokens_per_sec_per_chip", 0) for x in rep):
            lever = True
    if not lever:
        return False
    for r in rows:
        if r.get("experts", 0) <= 0:
            continue  # dense baseline rows judge only by presence
        if not isinstance(r.get("tokens_per_sec_per_chip"), (int, float)):
            return False
        if r["tokens_per_sec_per_chip"] < SERVE_MIN_TOKS:
            return False
        for k in ("capacity_utilization", "dropped_rate"):
            v = r.get(k)
            if not isinstance(v, (int, float)) or not 0.0 <= v <= 1.0:
                return False
    return True


# the serve_resilience stage (ISSUE 14): the replica-plane section of
# the SAME serving.json artifact (bench_serve writes it; runbook stage
# 5l re-captures on chip) — (a) the whole artifact passes the strict
# schema (validate_metrics: crash-matrix/slow/drain/rejoin rows per-row
# validated), (b) ALL EIGHT live-recomputed markers hold (crash-migrated
# outputs token-identical greedy/sampled/speculative/prefix-cache, zero
# accepted-token loss, drain finishes residents and departs, the slow
# replica is detected AND routed around, a rejoiner serves from a fresh
# pool), (c) the crash matrix covers >= SERVE_RES_MIN_CRASH_TICKS cut
# points, every row with tokens_lost == 0, identical, and at least one
# actual migration, and (d) the slow leg's measured story holds: the
# slow replica's p99 tick latency strictly above its clean peer's in the
# same run (the latency watch had a real signal to act on).
SERVE_RES_MIN_CRASH_TICKS = 3


def serve_resilience_ok(path: str = SERVE_ARTIFACT) -> bool:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return False
    try:
        vm = _validate_metrics_module()
        if vm.validate_json_doc(path):
            return False  # schema violations
    except Exception:
        return False
    sec = doc.get("serve_resilience")
    if not isinstance(sec, dict):
        return False
    marks = sec.get("markers", {})
    for k in ("migrated_identity_greedy", "migrated_identity_sampled",
              "migrated_identity_speculative",
              "migrated_identity_prefix_cache", "zero_token_loss",
              "drain_completes_residents", "slow_detected_and_routed",
              "rejoin_serves"):
        if marks.get(k) is not True:
            return False
    rows = sec.get("crash_matrix", [])
    if len({r.get("crash_tick") for r in rows}) < SERVE_RES_MIN_CRASH_TICKS:
        return False  # 'crash at any tick' needs more than one cut point
    for r in rows:
        if r.get("tokens_lost") != 0 or r.get("identical") is not True:
            return False
    if not any(r.get("migrated", 0) > 0 for r in rows):
        return False  # a matrix where nothing migrated proved nothing
    slow = sec.get("slow", {})
    if not (isinstance(slow.get("p99_ms_slow_replica"), (int, float))
            and isinstance(slow.get("p99_ms_clean_replica"), (int, float))
            and slow["p99_ms_slow_replica"] > slow["p99_ms_clean_replica"]):
        return False
    return True


# the process-isolated fleet stage (ISSUE 20): the fleet_resilience
# section of the same serving artifact — (a) the whole document passes
# the strict serving.json schema, (b) all six markers recomputed true at
# capture time (SIGKILL identity + zero token loss, real-process
# isolation, restart identity + prefill-tokens-saved, socket soak
# served), (c) the kill matrix covers >= FLEET_RES_MIN_KILL_TICKS
# distinct cut points and includes a sampled cut, every row with
# tokens_lost == 0, identical, the dead process actually declared and at
# least one real migration somewhere in the matrix, (d) the restart leg
# restored in-flight work (the stop really interrupted a fleet) with
# prefill_tokens_saved > 0 (the persisted chains did real work), and
# (e) the soak completed every request and pinned its byte stream. A
# CPU-produced artifact is first-class here for the same reason as the
# elasticity stage: process spawn, SIGKILL, pipe-EOF detection and the
# persistence manifest are host-plane mechanics on every backend.
FLEET_RES_MIN_KILL_TICKS = 3


def fleet_resilience_ok(path: str = SERVE_ARTIFACT) -> bool:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return False
    try:
        vm = _validate_metrics_module()
        if vm.validate_json_doc(path):
            return False  # schema violations
    except Exception:
        return False
    sec = doc.get("fleet_resilience")
    if not isinstance(sec, dict):
        return False
    marks = sec.get("markers", {})
    for k in ("sigkill_identity", "sigkill_zero_token_loss",
              "process_isolated", "restart_identity",
              "restart_prefill_saved", "socket_soak_served"):
        if marks.get(k) is not True:
            return False
    rows = sec.get("kill_matrix", [])
    if len({r.get("kill_tick") for r in rows}) < FLEET_RES_MIN_KILL_TICKS:
        return False  # 'SIGKILL at any tick' needs more than one cut
    if not any(r.get("sampling") == "stochastic" for r in rows):
        return False  # greedy-only identity is the easy half
    for r in rows:
        if (r.get("tokens_lost") != 0 or r.get("identical") is not True
                or r.get("declared_dead") != 1
                or r.get("process_isolated") is not True):
            return False
    if not any(r.get("migrated", 0) > 0 for r in rows):
        return False  # a matrix where nothing migrated proved nothing
    restart = sec.get("restart", {})
    if not (restart.get("inflight_at_stop", 0) > 0
            and restart.get("restored", 0) > 0
            and restart.get("prefill_tokens_saved", 0) > 0):
        return False
    soak = sec.get("socket_soak", {})
    if not (soak.get("requests", 0) > 0
            and soak.get("completed") == soak.get("requests")):
        return False
    return True


# the live-elasticity stage (ISSUE 10): scripts/bench_elasticity.py's
# artifact under runs/elasticity — (a) passes the strict elasticity.json
# schema (validate_metrics, loaded by FILE PATH so this script stays
# jax-free), (b) the headline drop/rejoin scenario SURVIVED: every step
# completed without restart, losses/momenta finite, exactly one leave and
# one rejoin, ending all-healthy at full W, (c) both degraded-phase
# bit-identity markers hold (departed-from-step-0 == masked-from-scratch
# W−1; the drop/rejoin schedule is deterministic), (d) the journal-read
# membership timeline carries the worker_left AND worker_rejoined events
# (the run_analyze leg actually closed), and (e) the pre-registered
# post-rejoin parity bound PASSED. A CPU-produced artifact is first-class
# here: membership transitions are host-side mask flips on every backend
# (the point is the control-plane mechanism, not chip throughput);
# meta.backend records what measured it and the runbook re-captures on
# chip (stage 5i).
ELASTICITY_ARTIFACT = os.path.join(REPO, "runs", "elasticity",
                                   "elasticity.json")


def elasticity_ok(path: str = ELASTICITY_ARTIFACT) -> bool:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return False
    try:
        vm = _validate_metrics_module()
        if vm.validate_json_doc(path):
            return False  # schema violations
    except Exception:
        return False
    sv = doc.get("survive", {})
    world = doc.get("meta", {}).get("world")
    if not (sv.get("completed") is True and sv.get("finite") is True
            and sv.get("left_events") == 1 and sv.get("rejoin_events") == 1
            and sv.get("final_alive") == world):
        return False
    bits = doc.get("bit_identity", {})
    if not (bits.get("degraded_vs_masked") is True
            and bits.get("drop_deterministic") is True):
        return False
    names = [r.get("event") for r in doc.get("timeline", [])]
    if not ("worker_left" in names and "worker_rejoined" in names):
        return False
    return doc.get("parity", {}).get("pass") is True


# the serve-SLO stage (ISSUE 17): serving.json's ``slo`` section — the
# seeded workload_gen soak through the serve/metrics.py plane. Captured
# means (a) the document passes the strict serving.json schema
# (including the slo section's ordered non-negative quantiles and
# required status counts), (b) all three markers hold — metrics_inert
# (metrics-ON token streams byte-identical to metrics-OFF),
# zero_token_loss, responses_timed (every terminal status carried its
# timing columns), (c) the soak actually ran (requests > 0 with
# tokens_out > 0) and lost NOTHING (tokens_lost == 0 — the token-loss
# regression gate), and (d) the banked TTFT p99 sits inside the banked
# target (the SLO regression gate: the target rides the artifact, so a
# re-bank that quietly widened it is visible in review, not laundered
# through this check).
def slo_ok(path: str = SERVE_ARTIFACT) -> bool:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return False
    try:
        vm = _validate_metrics_module()
        if vm.validate_json_doc(path):
            return False  # schema violations
    except Exception:
        return False
    sec = doc.get("slo")
    if not isinstance(sec, dict):
        return False
    marks = sec.get("markers", {})
    for k in ("metrics_inert", "zero_token_loss", "responses_timed"):
        if marks.get(k) is not True:
            return False
    if not (sec.get("requests", 0) > 0 and sec.get("tokens_out", 0) > 0):
        return False  # an empty soak proved nothing
    if sec.get("tokens_lost") != 0:
        return False
    targets = sec.get("targets", {})
    ttft = sec.get("ttft_ms", {})
    tok = sec.get("tok_ms", {})
    if not (isinstance(ttft.get("p99"), (int, float))
            and isinstance(targets.get("ttft_ms"), (int, float))
            and ttft["p99"] <= targets["ttft_ms"]):
        return False
    return (isinstance(tok.get("p99"), (int, float))
            and isinstance(targets.get("tok_ms"), (int, float))
            and tok["p99"] <= targets["tok_ms"])


def journal_ok(dirname: str = "journal") -> bool:
    base = (dirname if os.path.isabs(dirname)
            else os.path.join(REPO, "runs", dirname))
    try:
        ra = _run_analyze_module()
        report = ra.analyze_dir(base)
    except Exception:
        return False
    if report is None or report.get("schema_errors"):
        return False
    att = report.get("attribution")
    return bool(att and att["closes"] and att.get("steps", 0) > 0
                and att["coverage"] >= JOURNAL_MIN_COVERAGE)


# the ONE stage list both check("all") and the CLI printout derive from —
# adding a stage here updates the watcher exit condition and the operator
# status display together
STAGES = [
    ("sweep2", sweep2),
    ("sweep3", sweep3),
    ("bench_best", bench_best),
    ("overlap", overlap),
    ("sft7b", sft7b),
    ("parity:local", lambda: parity_strict("local")),
    ("parity:vote", lambda: parity_strict("vote")),
    ("parity:lazy", lambda: parity_strict("lazy")),
    ("parity:PASS", parity_pass),
    ("conv", conv),
    ("dpo", dpo),
    ("telemetry", telemetry_ok),
    ("resilience", resilience_ok),
    ("static", static_ok),
    ("static_serve", static_serve_ok),
    ("vote_guard", vote_guard_ok),
    ("autotune", autotune_ok),
    ("journal", journal_ok),
    ("dcn_overlap", dcn_overlap_ok),
    ("serving", serving_ok),
    ("speculative", speculative_ok),
    ("tp_serving", tp_serving_ok),
    ("serve_resilience", serve_resilience_ok),
    ("fleet_resilience", fleet_resilience_ok),
    ("moe_serving", moe_serving_ok),
    ("elasticity", elasticity_ok),
    ("slo", slo_ok),
]

# automation (the watcher exit condition) judges the parity legs on
# PRESENCE, not the numeric criterion: the criterion is a deterministic
# function of already-captured legs (same seed reproduces the same curve),
# so once a leg exists no amount of re-fired windows can flip its verdict —
# a failing criterion needs a human, not an infinite watcher loop
# (code-review r5). The evidence-facing STAGES entries above stay strict.
_AUTOMATION_OVERRIDES = {
    "parity:vote": lambda: parity("vote"),
    "parity:lazy": lambda: parity("lazy"),
}


def automation_complete() -> bool:
    """The watcher's exit condition: every stage automation can still
    affect is captured (parity legs by presence — see
    _AUTOMATION_OVERRIDES; parity:PASS excluded entirely). `all` keeps
    the full strict list for operators/judges."""
    return all(_AUTOMATION_OVERRIDES.get(name, fn)()
               for name, fn in STAGES if name != "parity:PASS")


def check(what: str, arg: str | None = None) -> bool:
    if what == "parity":
        # the CLI parity check is the STRICT one (presence + numeric PASS
        # for vote/lazy); the watcher's presence semantics ride
        # `automation`, and the runbook's skip guards use parity_full
        return parity_strict(arg or "local")
    if what == "sweep2":
        return sweep2()
    if what == "sweep3":
        return sweep3()
    if what == "sft7b":
        return sft7b()
    if what == "bench_best":
        return bench_best()
    if what == "overlap":
        return overlap()
    if what == "conv":
        return conv()
    if what == "conv_full":
        # canonical-scale artifact only — the TPU runbook's stage guard
        # (mirrors parity_full: a reduced CPU fallback must not stop a
        # live window from capturing the canonical run)
        return conv("convergence")
    if what == "parity_pass":
        return parity_pass()
    if what == "parity_full":
        return parity_full(arg or "local")
    if what == "dpo":
        return dpo(tpu_only=arg == "tpu")
    if what == "telemetry":
        return telemetry_ok(arg or "telemetry")
    if what == "resilience":
        return resilience_ok(arg or "resilience")
    if what == "static":
        return static_ok()
    if what == "static_serve":
        return static_serve_ok(arg)
    if what == "vote_guard":
        return vote_guard_ok(arg or "vote_guard")
    if what == "autotune":
        return autotune_ok()
    if what == "journal":
        return journal_ok(arg or "journal")
    if what == "dcn_overlap":
        return dcn_overlap_ok(arg or DCN_ARTIFACT)
    if what == "serving":
        return serving_ok(arg or SERVE_ARTIFACT)
    if what == "speculative":
        return speculative_ok(arg or SERVE_ARTIFACT)
    if what == "tp_serving":
        return tp_serving_ok(arg or SERVE_ARTIFACT)
    if what == "serve_resilience":
        return serve_resilience_ok(arg or SERVE_ARTIFACT)
    if what == "fleet_resilience":
        return fleet_resilience_ok(arg or SERVE_ARTIFACT)
    if what == "moe_serving":
        return moe_serving_ok(arg or SERVE_ARTIFACT)
    if what == "elasticity":
        return elasticity_ok(arg or ELASTICITY_ARTIFACT)
    if what == "slo":
        return slo_ok(arg or SERVE_ARTIFACT)
    if what == "all":
        return all(fn() for _, fn in STAGES)
    if what == "automation":
        return automation_complete()
    raise SystemExit(f"unknown evidence check {what!r}")


if __name__ == "__main__":
    what = sys.argv[1]
    if what == "all":
        # per-stage status printout for operators; exit 0 only when complete
        status = [(name, fn()) for name, fn in STAGES]
        for name, ok in status:
            print(f"{name}: {'captured' if ok else 'MISSING'}")
        sys.exit(0 if all(ok for _, ok in status) else 1)
    ok = check(what, sys.argv[2] if len(sys.argv) > 2 else None)
    sys.exit(0 if ok else 1)
