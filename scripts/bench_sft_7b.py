"""SFT throughput/memory smoke at Llama-2-7B shapes on the local chip.

The reference's flagship finetune is Llama-2-7B QLoRA SFT
(/root/reference/sft_llama2.py:141-153: 4-bit NF4 base, bf16 compute, LoRA
q/v r=8). This script runs that workload's train step at FULL 7B shapes
(32 layers, d=4096, random-init base — throughput and memory don't care
about weight values) and reports tokens/s/chip plus peak HBM, the number
VERDICT r1 asked to have recorded.

Methodology matches scripts/bench_sweep.py: fused K-step dispatches via
Trainer._train_chunk, timer stopped on a device_get of the final loss so
queued-but-unexecuted work can't inflate the number.

    python scripts/bench_sft_7b.py             # nf4, bs1, accum 4, chunks 8
    python scripts/bench_sft_7b.py bf16:2:4:0  # quant:bs:accum:vocab_chunks
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

K = 4           # steps per device dispatch
TIMED_CALLS = 2
# SFT7B_VALIDATE=1: pipeline-validation mode (VERDICT r4 #5 — "first
# validate the full spec list end to end on CPU host-RAM so the window is
# spent measuring, not debugging"). Each spec runs the REAL pipeline (host
# init at full d_model/vocab, NF4/int8 quantize, LoRA, chunked loss,
# trainer step) but at n_layer=2 / bs=1 / accum=1 / one dispatch — full
# 7B depth is days of work on the 1-core host. Rows are stamped
# "validate": true and never create skip keys, so a later real TPU window
# still measures every spec.
VALIDATE = os.environ.get("SFT7B_VALIDATE") == "1"


def run(quant: str = "nf4", batch_per_dev: int = 1, accum: int = 4,
        vocab_chunks: int = 8, n_layer: int | None = None,
        seq_len: int = 1024, model: str = "llama2_7b",
        remat_policy: str = "full") -> None:
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_lion_tpu.models.llama import LlamaConfig, llama_init
    from distributed_lion_tpu.models.lora import LoraConfig, apply_adapters, lora_init
    from distributed_lion_tpu.ops.quant import quantize_tree
    from distributed_lion_tpu.parallel.mesh import make_mesh
    from distributed_lion_tpu.train.loop import TrainConfig, Trainer

    n_dev = len(jax.devices())
    device_kind = jax.devices()[0].device_kind
    mesh = make_mesh()
    kw = {} if n_layer is None else {"n_layer": n_layer}
    ctor = {"llama2_7b": LlamaConfig.llama2_7b, "tiny": LlamaConfig.tiny}[model]
    model_cfg = ctor(remat_policy=remat_policy, **kw)
    cfg = TrainConfig(
        lion=True, async_grad=True, learning_rate=1e-4, weight_decay=0.0,
        warmup_steps=10, max_steps=10_000,
        per_device_train_batch_size=batch_per_dev,
        gradient_accumulation_steps=accum, block_size=seq_len,
        steps_per_call=K, logging_steps=10_000, output_dir=None,
        vocab_chunks=vocab_chunks,
        # pin the banked-row methodology: the auto sentinels would resolve
        # to packed_a2a (+ lazy votes) on a W>1 mesh and rank incomparably
        # against rows measured under every-step sign_psum (same pin as
        # bench.py)
        wire="sign_psum", vote_every=1,
    )

    # Init + quantize the frozen base ON HOST CPU, then ship only the packed
    # codes: a 7B f32 base is 26 GB — bigger than the whole v5e chip — so
    # on-device init OOMs (or crawls through the tunnel) before quantization
    # can shrink it. Host RAM holds it easily; the device only ever sees the
    # ~3.5 GB NF4 codes (+ small dense leaves). Throughput/memory don't
    # care about weight values (random init either way).
    import contextlib
    import dataclasses as _dc

    import jax.numpy as jnp

    try:
        # needs "cpu" in JAX_PLATFORMS (the runbook exports "axon,cpu";
        # the axon env's default is axon-only)
        cpu = jax.local_devices(backend="cpu")[0]
        ctx = jax.default_device(cpu)
    except RuntimeError:
        # no host backend exposed: init on device — bf16 keeps the dense
        # tree at 13 GB (fits one v5e chip; the per-leaf quantize peak adds
        # only the largest single leaf's codes)
        ctx = contextlib.nullcontext()
    with ctx:
        # quant "nf4"/"int8" → packed codes from a bf16 host init (absmax
        # at bf16 precision is irrelevant for a random-init throughput
        # bench); "bf16" → DENSE bf16 base (13 GB at 7B — fits the chip);
        # "none" → dense base in the config's own param_dtype (f32: 26 GB,
        # only viable with an n_layer override on one chip)
        dense = quant in ("none", "bf16")
        base_dtype = model_cfg.param_dtype if quant == "none" else jnp.bfloat16
        host_cfg = _dc.replace(model_cfg, param_dtype=base_dtype)
        base = llama_init(jax.random.key(0), host_cfg)
        n_base = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(base))
        if not dense:
            base = quantize_tree(base, quant)
    lora_cfg = LoraConfig(r=8, alpha=16)
    adapters = lora_init(jax.random.key(1), base, lora_cfg)
    n_adapter = sum(int(np.prod(a.shape)) for a in jax.tree.leaves(adapters))

    from distributed_lion_tpu.models.llama import llama_apply, llama_hidden
    from distributed_lion_tpu.models.loss import clm_loss_and_metrics
    from distributed_lion_tpu.ops.quant import maybe_dequant
    from distributed_lion_tpu.ops.xent import chunked_clm_loss_and_metrics

    # the frozen base rides the Trainer's frozen_params slot (replicated
    # device_put + a (params, frozen, batch, key) loss) instead of a
    # Python closure: a closed-over jax.Array is baked into the jaxpr as a
    # CONSTANT, so XLA constant-folds over the multi-GB packed codes at
    # compile time (observed: minutes of u8[4096,2048] folding on the
    # validation run) and the executable carries them — as an argument the
    # codes ship once and compile stays shape-only
    def loss_fn(params, frozen, batch, dropout_key):
        effective = apply_adapters(frozen, params, lora_cfg)
        if vocab_chunks > 0:
            hidden = llama_hidden(effective, batch, model_cfg)
            emb = maybe_dequant(effective["lm_head"], model_cfg.compute_dtype)
            return chunked_clm_loss_and_metrics(
                hidden, emb, batch, vocab_chunks, None, emb_layout="dv")
        logits = llama_apply(effective, batch, model_cfg)
        return clm_loss_and_metrics(logits, batch, None)

    loss_fn._vocab_chunked = True
    trainer = Trainer(cfg, mesh, apply_fn=None, params=adapters, loss_fn=loss_fn,
                      frozen_params=base)
    gb = trainer.global_train_batch()
    tokens_per_step = gb * seq_len

    rng = np.random.default_rng(0)
    batches = jax.device_put(
        rng.integers(0, model_cfg.vocab_size,
                     size=(K, gb, seq_len)).astype(np.int32),
        NamedSharding(mesh, P(None, "data")),
    )
    key = jax.random.key(0)
    t0 = time.perf_counter()
    trainer.params, trainer.state, trainer.vote_health, m = (
        trainer._train_chunk(trainer.params, trainer.state,
                             trainer.vote_health, trainer._frozen_arg(),
                             batches, key))
    _ = float(np.asarray(jax.device_get(m["loss"])))
    compile_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    for _ in range(TIMED_CALLS):
        trainer.params, trainer.state, trainer.vote_health, m = (
            trainer._train_chunk(trainer.params, trainer.state,
                                 trainer.vote_health, trainer._frozen_arg(),
                                 batches, key))
    loss = float(np.asarray(jax.device_get(m["loss"])))
    dt = time.perf_counter() - t0
    steps = K * TIMED_CALLS
    tps = tokens_per_step * steps / dt / n_dev

    stats = {}
    try:
        ms = jax.local_devices()[0].memory_stats() or {}
        stats = {"peak_hbm_gb": round(ms.get("peak_bytes_in_use", 0) / 2**30, 2),
                 "hbm_limit_gb": round(ms.get("bytes_limit", 0) / 2**30, 2)}
    except Exception:
        pass
    print(json.dumps({
        "workload": f"{model} QLoRA SFT vote-Lion train step",
        **({"validate": True} if VALIDATE else {}),
        "quant": quant, "n_layer": model_cfg.n_layer,
        "base_params": n_base, "adapter_params": n_adapter,
        "batch_per_dev": batch_per_dev, "accum": accum, "seq_len": seq_len,
        "remat_policy": remat_policy,
        "vocab_chunks": vocab_chunks, "device_kind": device_kind,
        "compile_s": round(compile_s, 1), "loss": round(loss, 3),
        "ms_per_step": round(dt / steps * 1e3, 1),
        "tokens_per_sec_per_chip": round(tps, 1), **stats,
    }), flush=True)
    trainer.close()


def _captured_keys() -> set:
    """Specs already holding a RESULT row in $SFT7B_SKIP_FILE (the jsonl
    the runbook appends to): a watcher-re-fired window resumes at the
    first unmeasured spec instead of re-burning minutes of 7B quantize +
    compile per captured one. Error rows don't count — failed specs get
    retried. Key = the spec-derived config fields (n_layer is resolved
    model-side, so it's not part of the key)."""
    path = os.environ.get("SFT7B_SKIP_FILE", "")
    keys: set = set()
    if not path:
        return keys
    try:
        with open(path) as f:
            for line in f:
                try:
                    d = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if d.get("validate"):
                    continue  # pipeline-validation rows are not captures
                if d.get("tokens_per_sec_per_chip"):
                    keys.add((d.get("quant"), d.get("batch_per_dev"),
                              d.get("accum"), d.get("seq_len"),
                              d.get("remat_policy"), d.get("vocab_chunks")))
    except OSError:
        pass
    return keys


def _validate_full_init() -> None:
    """Full-DEPTH host init + quantize only (no train step): the one part
    of the real 7B pipeline the reduced-depth validation runs don't cover
    — 13 GB of host-RAM init and the per-leaf NF4 packing at true leaf
    shapes. Catches OOM/shape/dtype bugs before a TPU window pays for
    them."""
    import jax
    import numpy as np

    from distributed_lion_tpu.models.llama import LlamaConfig, llama_init
    from distributed_lion_tpu.ops.quant import quantize_tree

    cfg = LlamaConfig.llama2_7b()
    import dataclasses as _dc

    import jax.numpy as jnp
    t0 = time.time()
    base = llama_init(jax.random.key(0),
                      _dc.replace(cfg, param_dtype=jnp.bfloat16))
    n = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(base))
    init_s = time.time() - t0
    t0 = time.time()
    q = quantize_tree(base, "nf4")
    q_bytes = sum(x.nbytes for x in jax.tree.leaves(q))
    print(json.dumps({
        "validate": True, "full_init": True, "n_layer": cfg.n_layer,
        "base_params": n, "init_s": round(init_s, 1),
        "quantize_s": round(time.time() - t0, 1),
        "nf4_gb": round(q_bytes / 2**30, 2),
    }), flush=True)


if __name__ == "__main__":
    from distributed_lion_tpu.parallel.mesh import force_cpu_platform

    force_cpu_platform()
    specs = sys.argv[1:] or ["nf4:1:4:8"]
    DEFAULTS = ["nf4", "1", "4", "8", "", "1024", "full"]
    captured = _captured_keys()
    if VALIDATE:
        K, TIMED_CALLS = 1, 1
    for spec in specs:
        parts = spec.split(":")
        # pad with the defaults for the MISSING tail fields only (a plain
        # `parts + DEFAULTS` would splice the default list in positionally:
        # "nf4:1:4:8" must mean full-depth T=1024, not n_layer=1 seq=4)
        parts = (parts + DEFAULTS[len(parts):])[:7]
        quant, bs, accum, vc, nl, sl, pol = parts
        if VALIDATE:
            # exercise the spec's quant/seq_len/remat/chunks through the
            # real pipeline at a depth/budget the host core can afford
            bs, accum, nl = "1", "1", nl or "2"
        if not VALIDATE and (quant, int(bs), int(accum), int(sl),
                             pol or "full", int(vc or 0)) in captured:
            print(f"[7b] skip (already captured): {spec}", file=sys.stderr,
                  flush=True)
            continue
        try:
            run(quant, int(bs), int(accum), int(vc or 0),
                None if not nl else int(nl), int(sl), remat_policy=pol or "full")
        except Exception as e:
            print(json.dumps({"spec": spec,
                              "error": str(e).split("\n")[0][:200]}), flush=True)
    if VALIDATE:
        try:
            _validate_full_init()
        except Exception as e:
            print(json.dumps({"validate": True, "full_init": True,
                              "error": str(e).split("\n")[0][:200]}),
                  flush=True)
