"""Loss-parity experiment: vote-Lion (W=8) vs local Lion at equal global batch.

BASELINE.md north-star #1 — "distributed-vote Lion matches single-worker
Lion's loss curve at equal global batch" — at real scale: the GPT-2 124M
*architecture* (12L, d=768, T=1024) over real local text through the native
BPE pipeline, a few thousand optimizer steps, on the real chip.

Single-chip discipline: the 8 voters run as VIRTUAL workers on one device —
a ``lax.scan`` over 8 per-worker (momentum, microbatch) slices computing the
exact vote-Lion algorithm with ops/lion_math's op ordering (wd → ballot →
vote → apply → momentum-from-local-grad). This is algebraically identical to
the dp=8 mesh path: the wire tests (tests/test_distributed_lion.py,
test_hier_vote.py) already pin that every wire computes exactly this
ballot-sum election, so the only thing a real 8-chip mesh would change is
WHERE the int8 sum runs.

Phases:
    python scripts/loss_parity.py --phase prep        # corpus + vocab + tokens (CPU ok)
    python scripts/loss_parity.py --phase run --mode local
    python scripts/loss_parity.py --phase run --mode vote
    python scripts/loss_parity.py --phase report      # REPORT.md from the JSONLs

prep: concatenates ~200MB of local Python/Markdown sources, trains a 16384-
token byte-level BPE with the HF ``tokenizers`` trainer (Rust — the pure-
Python ``train_bpe`` is for small vocabularies; the ARTIFACT is the standard
vocab.json+merges.txt this framework's native C++ BPE consumes), then
encodes the corpus with OUR tokenizer (data/bpe._NativeCore) into a token
memmap. Model embeddings size to the 16k vocab → ~98M params.

Reference anchors: canonical config lr 1e-4, wd 0.1, bf16, T=1024
(/root/reference/README.md:18-38); update semantics distributed_lion.py:61-96.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in __import__("sys").path:  # `python scripts/loss_parity.py`
    __import__("sys").path.insert(0, REPO)
DEFAULT_OUT = os.path.join(REPO, "runs", "parity")
VOCAB = 16384
T = 1024
WORKERS = 8
ROWS_PER_WORKER = 4          # global batch 32 rows = 32768 tokens/step
SMOKE = False                # --smoke: tiny model/seq for a CPU pipeline check
REDUCED = False              # --reduced: ≥10M-param short-seq legs sized so a
# 2000-step curve completes on the 1-core CPU host when the TPU tunnel is
# dead (VERDICT r4 §next-1/3: "the claim is about trajectory, not
# throughput"). Writes to runs/parity_cpu so a later TPU window can still
# capture the full-scale legs in runs/parity without colliding.
LR, WD, B1, B2 = 1e-4, 0.1, 0.9, 0.99
WARMUP = 100


# ------------------------------------------------------------------- prep

def _corpus_files(max_bytes: int) -> list:
    pats = [
        os.path.join(REPO, "**", "*.py"),
        os.path.join(REPO, "**", "*.md"),
        "/opt/venv/lib/**/*.py",
    ]
    out, total = [], 0
    for pat in pats:
        for p in sorted(glob.glob(pat, recursive=True)):
            try:
                sz = os.path.getsize(p)
            except OSError:
                continue
            if sz < 256:
                continue
            out.append(p)
            total += sz
            if total >= max_bytes:
                return out
    return out


def prep(out_dir: str, max_bytes: int) -> None:
    os.makedirs(out_dir, exist_ok=True)
    corpus_path = os.path.join(out_dir, "corpus.txt")
    if not os.path.exists(corpus_path):
        files = _corpus_files(max_bytes)
        print(f"[prep] concatenating {len(files)} files")
        with open(corpus_path, "w", encoding="utf-8") as w:
            for p in files:
                try:
                    with open(p, encoding="utf-8", errors="replace") as f:
                        w.write(f.read())
                    w.write("\n\n")
                except OSError:
                    continue
        print(f"[prep] corpus: {os.path.getsize(corpus_path)/1e6:.0f} MB")

    tok_dir = os.path.join(out_dir, "tok")
    if not os.path.exists(os.path.join(tok_dir, "vocab.json")):
        # vocab learned by the fast Rust trainer; ARTIFACT is the standard
        # GPT-2 file format our native BPE loads (data/bpe.BPETokenizer)
        from tokenizers import Tokenizer, models, pre_tokenizers, trainers

        t0 = time.time()
        hf = Tokenizer(models.BPE())
        hf.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
        trainer = trainers.BpeTrainer(
            vocab_size=VOCAB - 1,  # + <|endoftext|> on our side
            special_tokens=[],
            initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
        )
        hf.train([corpus_path], trainer)
        os.makedirs(tok_dir, exist_ok=True)
        hf.model.save(tok_dir)  # vocab.json + merges.txt
        print(f"[prep] 16k BPE vocabulary trained in {time.time()-t0:.0f}s")

    tokens_path = os.path.join(out_dir, "tokens.npy")
    if not os.path.exists(tokens_path):
        import numpy as np

        from distributed_lion_tpu.data.bpe import BPETokenizer

        tok = BPETokenizer.load(tok_dir)
        assert tok.vocab_size <= VOCAB, tok.vocab_size
        t0 = time.time()
        ids: list = []
        with open(corpus_path, encoding="utf-8") as f:
            while True:
                chunk = f.read(4 << 20)
                if not chunk:
                    break
                ids.append(np.asarray(tok.encode(chunk), np.int32))
        stream = np.concatenate(ids)
        np.save(tokens_path, stream)
        mb = os.path.getsize(corpus_path) / 1e6
        print(f"[prep] {stream.size/1e6:.1f}M tokens in {time.time()-t0:.0f}s "
              f"({mb/(time.time()-t0):.1f} MB/s native BPE)")
    else:
        import numpy as np

        stream = np.load(tokens_path, mmap_mode="r")
    print(f"[prep] ready: {stream.size/1e6:.1f}M tokens at {tokens_path}")


# -------------------------------------------------------------------- run

def _blocks(out_dir: str):
    import numpy as np

    tokens_path = os.path.join(out_dir, "tokens.npy")
    if not os.path.exists(tokens_path):
        # reduced legs live in runs/parity_cpu but share the prepared
        # full-scale corpus/token stream — same data, same 16k vocab
        tokens_path = os.path.join(DEFAULT_OUT, "tokens.npy")
    stream = np.load(tokens_path, mmap_mode="r")
    n_blocks = stream.size // T
    blocks = stream[: n_blocks * T].reshape(n_blocks, T)
    n_eval = 64
    return blocks[n_eval:], blocks[:n_eval]  # train, held-out


def run(out_dir: str, mode: str, steps: int, log_every: int,
        eval_every: int, seed: int, force_cpu: bool = False) -> None:
    assert mode in ("local", "vote", "lazy")
    os.makedirs(out_dir, exist_ok=True)  # reduced legs skip the prep phase
    import jax

    if force_cpu:
        # the axon sitecustomize force-registers the TPU plugin and a dead
        # tunnel HANGS jax.devices(); the config knob set before first
        # backend use is the only reliable override (see bench.py)
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from distributed_lion_tpu.models.gpt2 import GPT2Config, gpt2_apply, gpt2_init
    from distributed_lion_tpu.models.loss import clm_loss_and_metrics
    from distributed_lion_tpu.ops.lion_math import (
        apply_signed_update,
        decay_params,
        local_lion_leaf,
        momentum_update,
        sign_vote_bool,
    )
    from distributed_lion_tpu.train.schedule import cosine_schedule_with_warmup

    dev = jax.devices()[0]
    print(f"[run:{mode}] backend={dev.platform} ({dev.device_kind})")
    import dataclasses

    if SMOKE:
        cfg = GPT2Config.tiny(vocab_size=VOCAB, n_ctx=T)
    elif REDUCED:
        # smallest scale at which the shipped lazy auto-default applies
        # (train/loop.resolve_auto_comm: W>1 ∧ replicated ∧ ≥10M params):
        # GPT2Config.small = 6L d=320 over the 16k vocab ≈ 12.7M params
        # (the shared reduced evidence preset). Short T keeps a 2000-step
        # leg within hours on the single host core.
        cfg = GPT2Config.small(vocab_size=VOCAB, n_ctx=T)
    else:
        cfg = GPT2Config.gpt2_124m(vocab_size=VOCAB)
    # f32 MASTER params (compute stays bf16, the config default): Lion's
    # fixed ±lr update is 1e-4 while bf16's ULP at |p| >= 0.05 is ~4e-4 —
    # bf16-stored params would silently absorb the entire update on most
    # large-magnitude coordinates (verified: apply_signed_update on bf16
    # p=0.05..0.5 is a no-op at lr=1e-4). Same reason torch training keeps
    # f32 master weights under bf16 autocast.
    cfg = dataclasses.replace(cfg, remat=False, attn_impl="xla")
    params = gpt2_init(jax.random.key(seed), cfg)
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    if REDUCED:
        # the reduced legs exist to evidence the ≥10M lazy auto-default —
        # a sub-threshold model would test a config the default never sees
        assert n_params >= 10_000_000, n_params
    print(f"[run:{mode}] {n_params/1e6:.1f}M params "
          f"({'reduced CPU-scale' if REDUCED else '124M'} architecture, "
          f"{VOCAB} local vocab)")
    schedule = cosine_schedule_with_warmup(LR, WARMUP, steps)

    def loss_fn(p, batch):
        logits = gpt2_apply(p, batch, cfg, dropout_key=None)
        loss, _ = clm_loss_and_metrics(logits, batch)
        return loss

    grad_fn = jax.value_and_grad(loss_fn)
    gb = WORKERS * ROWS_PER_WORKER

    if mode == "local":
        moms = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)

        @jax.jit
        def step_fn(params, moms, count, batch):  # batch [gb, T]
            lr = schedule(count)
            loss, g = grad_fn(params, batch)
            out = jax.tree.map(
                lambda p, gg, m: local_lion_leaf(p, gg, m, lr, WD, B1, B2),
                params, g, moms,
                is_leaf=lambda x: isinstance(x, jnp.ndarray),
            )
            params = jax.tree.map(lambda o: o[0], out,
                                  is_leaf=lambda x: isinstance(x, tuple))
            moms = jax.tree.map(lambda o: o[1], out,
                                is_leaf=lambda x: isinstance(x, tuple))
            return params, moms, count + 1, loss
    elif mode == "vote":
        # W=8 virtual vote workers: scan over per-worker (momentum slice,
        # microbatch); ballots accumulate as an int8 ±1 sum (the sign_psum
        # election); every worker applies the identical elected update.
        moms = jax.tree.map(
            lambda p: jnp.zeros((WORKERS,) + p.shape, jnp.float32), params)

        @jax.jit
        def step_fn(params, moms, count, batch):  # batch [W, rows, T]
            lr = schedule(count)

            def worker(ballots, xs):
                m_w, b = xs
                loss, g = grad_fn(params, b)
                ballots = jax.tree.map(
                    lambda bt, gg, mm: bt + jnp.where(
                        sign_vote_bool(gg, mm, B1), 1, -1).astype(jnp.int8),
                    ballots, g, m_w)
                m_new = jax.tree.map(
                    lambda gg, mm: momentum_update(gg, mm, B2), g, m_w)
                return ballots, (m_new, loss)

            zeros = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.int8), params)
            ballots, (moms_new, losses) = jax.lax.scan(
                worker, zeros, (moms, batch))
            params = jax.tree.map(
                lambda p, bt: apply_signed_update(
                    decay_params(p, lr, WD), bt > 0, lr),
                params, ballots)
            return params, moms_new, count + 1, losses.mean()
    else:  # mode == "lazy": the budget-meeting wire at realistic scale —
        # vote_every=4 lazy sign refresh (optim.distributed_lion._elect_lazy
        # semantics: rotating 1/K slice of the FLAT ballot vector voted each
        # step, cached elected signs elsewhere, cold-start validity mask).
        # With the packed_a2a wire this config is ~0.5 bit/param/step.
        from distributed_lion_tpu.ops.codec import vote_chunk_elems

        K = 4
        flat_leaves, treedef = jax.tree.flatten(params)
        sizes = [int(np.prod(p.shape)) for p in flat_leaves]
        shapes = [p.shape for p in flat_leaves]
        offsets = np.concatenate([[0], np.cumsum(sizes)])
        n_total = int(offsets[-1])
        chunk = vote_chunk_elems(n_total, K)
        moms = jax.tree.map(
            lambda p: jnp.zeros((WORKERS,) + p.shape, jnp.float32), params)
        cache = jnp.zeros((K * chunk,), bool)

        @jax.jit
        def step_fn(params, moms, cache, count, batch):  # batch [W, rows, T]
            lr = schedule(count)

            def worker(ballots, xs):
                m_w, b = xs
                loss, g = grad_fn(params, b)
                votes = jnp.concatenate([
                    sign_vote_bool(gg, mm, B1).reshape(-1)
                    for gg, mm in zip(jax.tree.leaves(g), jax.tree.leaves(m_w))
                ])
                ballots = ballots + jnp.where(votes, 1, -1).astype(jnp.int8)
                m_new = jax.tree.map(
                    lambda gg, mm: momentum_update(gg, mm, B2), g, m_w)
                return ballots, (m_new, loss)

            ballots, (moms_new, losses) = jax.lax.scan(
                worker, jnp.zeros((n_total,), jnp.int8), (moms, batch))
            pad = K * chunk - n_total
            padded = (jnp.concatenate([ballots, jnp.zeros((pad,), jnp.int8)])
                      if pad else ballots)
            slot = jax.lax.rem(count, jnp.int32(K))
            sl = jax.lax.dynamic_slice(padded, (slot * chunk,), (chunk,))
            cache = jax.lax.dynamic_update_slice(cache, sl > 0, (slot * chunk,))
            slot_idx = jnp.arange(K * chunk, dtype=jnp.int32) // chunk
            valid = (slot_idx <= count)[:n_total].astype(jnp.float32)
            sign_flat = jnp.where(cache[:n_total], 1.0, -1.0) * valid
            new_leaves = [
                decay_params(p, lr, WD)
                - jnp.asarray(lr, p.dtype)
                * sign_flat[offsets[i]:offsets[i + 1]].reshape(
                    shapes[i]).astype(p.dtype)
                for i, p in enumerate(jax.tree.leaves(params))
            ]
            params = jax.tree.unflatten(treedef, new_leaves)
            return params, moms_new, cache, count + 1, losses.mean()

    @jax.jit
    def eval_loss(params, batch):
        return loss_fn(params, batch)

    train_blocks, eval_blocks = _blocks(out_dir)
    eval_dev = jnp.asarray(np.asarray(eval_blocks[:32]), jnp.int32)
    rng = np.random.default_rng(seed + 1)
    order = rng.permutation(len(train_blocks))
    pos = 0

    def next_batch():
        nonlocal pos, order
        if pos + gb > len(order):
            order = rng.permutation(len(train_blocks))
            pos = 0
        idx = np.sort(order[pos: pos + gb])
        pos += gb
        rows = np.asarray(train_blocks[idx], np.int32)
        if mode in ("vote", "lazy"):
            return jnp.asarray(rows.reshape(WORKERS, ROWS_PER_WORKER, T))
        return jnp.asarray(rows)

    log_path = os.path.join(out_dir, f"{mode}.jsonl")
    ckpt_path = os.path.join(out_dir, f"{mode}.ckpt.npz")
    dtype_name = str(cfg.param_dtype.__name__
                     if hasattr(cfg.param_dtype, "__name__")
                     else cfg.param_dtype)

    # ---- mid-leg checkpoint/resume: a 2000-step leg is ~45 min of chip
    # time and the tunnel drops without warning — without resume, every
    # drop restarts the leg from step 0 AND truncates the partial curve
    # (mode "w"). Params+momenta+iterator state persist every SAVE_EVERY
    # steps (atomic tmp+rename), so a re-fired leg loses at most that
    # window. The checkpoint stamps mode/dtype/steps and is ignored on
    # mismatch (a config change must not silently splice curves).
    SAVE_EVERY = 250
    p_leaves, p_tree = jax.tree.flatten(params)
    m_leaves, m_tree = jax.tree.flatten(moms)
    count = jnp.int32(0)
    start_step = 0
    wall_base = 0.0  # cumulative wall time from earlier windows
    resumed = False
    if os.path.exists(ckpt_path):
        # TRANSACTIONAL restore: decode everything into temporaries first —
        # a partial/old-format npz that raises halfway must not leave
        # params at checkpoint values while the leg restarts "fresh" at
        # step 0 (a silently corrupt curve)
        try:
            ck = np.load(ckpt_path, allow_pickle=False)
            meta_ok = (str(ck["mode"]) == mode
                       and str(ck["param_dtype"]) == dtype_name
                       and int(ck["steps"]) == steps)
            if meta_ok:
                r_params = jax.tree.unflatten(
                    p_tree, [jnp.asarray(ck[f"p{i}"])
                             for i in range(len(p_leaves))])
                r_moms = jax.tree.unflatten(
                    m_tree, [jnp.asarray(ck[f"m{i}"])
                             for i in range(len(m_leaves))])
                r_cache = (jnp.asarray(ck["cache"])
                           if mode == "lazy" else None)
                r_count = jnp.int32(int(ck["count"]))
                r_pos = int(ck["pos"])
                r_order = np.asarray(ck["order"])
                r_rng_state = json.loads(str(ck["rng_state"]))
                r_wall = float(ck["wall_s"]) if "wall_s" in ck else 0.0
                # every key decoded — commit the restore atomically
                params, moms, count = r_params, r_moms, r_count
                if mode == "lazy":
                    cache = r_cache
                start_step = int(ck["step"]) + 1
                pos, order = r_pos, r_order
                rng.bit_generator.state = r_rng_state
                wall_base = r_wall
                resumed = True
                # rows past the checkpoint will be re-run and re-logged —
                # drop them now or the curve carries duplicate steps. Parse
                # per line: a TORN last line is the normal artifact of the
                # crash resume exists for, and must be dropped, not abort
                # the prune (report()'s loader would crash on it later).
                try:
                    kept = []
                    with open(log_path) as f:
                        for ln in f:
                            try:
                                d = json.loads(ln)
                            except json.JSONDecodeError:
                                continue
                            if d.get("meta") or d.get("step", steps) \
                                    < start_step:
                                kept.append(ln)
                    with open(log_path, "w") as f:
                        f.writelines(kept)
                except OSError:
                    pass
                print(f"[run:{mode}] resumed checkpoint at step {start_step}")
            else:
                print(f"[run:{mode}] checkpoint config mismatch — fresh run")
        except Exception as e:  # corrupt/partial ckpt: fresh run
            print(f"[run:{mode}] checkpoint unreadable ({e}) — fresh run")

    def save_ckpt(s):
        arrs = {f"p{i}": np.asarray(p) for i, p in
                enumerate(jax.tree.leaves(params))}
        arrs.update({f"m{i}": np.asarray(m) for i, m in
                     enumerate(jax.tree.leaves(moms))})
        if mode == "lazy":
            arrs["cache"] = np.asarray(cache)
        arrs.update(mode=mode, param_dtype=dtype_name, steps=steps,
                    step=s, count=int(np.asarray(count)), pos=pos,
                    order=order,
                    rng_state=json.dumps(rng.bit_generator.state),
                    # cumulative wall time: logged wall_s/tok-s must stay
                    # monotone and honest across resume boundaries
                    wall_s=wall_base + (time.time() - t0))
        tmp = ckpt_path + ".tmp.npz"  # .npz suffix: np.savez appends it
        np.savez(tmp, **arrs)         # to any other name, breaking the
        os.replace(tmp, ckpt_path)    # atomic rename

    t0 = time.time()
    # header row stamps the config so curve consumers (check_evidence,
    # report) can reject runs captured under a different precision —
    # bf16-era curves had frozen large-magnitude params (see the f32
    # master-params comment above) and must not be compared against
    # f32 runs as if the optimizer mode were the difference. Written on
    # fresh runs AND on a resume whose log vanished (a ckpt without its
    # jsonl would otherwise produce a headerless curve check_evidence
    # rejects for no visible reason).
    need_meta = (not resumed or not os.path.exists(log_path)
                 or os.path.getsize(log_path) == 0)
    with open(log_path, "a" if resumed else "w") as logf:
        if need_meta:
            logf.write(json.dumps({
                "meta": True, "mode": mode, "param_dtype": dtype_name,
                "lr": LR, "workers": WORKERS, "steps": steps,
                # scale + provenance stamps: the report/check must only
                # compare legs with identical config, and reduced CPU legs
                # must be distinguishable from full-scale TPU captures
                "d_model": cfg.d_model, "n_layer": cfg.n_layer, "T": T,
                "rows_per_worker": ROWS_PER_WORKER,
                "n_params": n_params, "seed": seed,
                "backend": dev.platform, "reduced": REDUCED,
            }) + "\n")
        for s in range(start_step, steps):
            if mode == "lazy":
                params, moms, cache, count, loss = step_fn(
                    params, moms, cache, count, next_batch())
            else:
                params, moms, count, loss = step_fn(
                    params, moms, count, next_batch())
            if (s + 1) % SAVE_EVERY == 0 and s != steps - 1:
                save_ckpt(s)
            if s % log_every == 0 or s == steps - 1:
                lv = float(np.asarray(jax.device_get(loss)))
                rec = {"step": s, "loss": round(lv, 5),
                       "lr": float(schedule(s)),
                       "tokens": (s + 1) * gb * T,
                       "wall_s": round(wall_base + time.time() - t0, 1)}
                logf.write(json.dumps(rec) + "\n")
                logf.flush()
                print(f"[run:{mode}] step {s}: loss {lv:.4f} "
                      f"({rec['tokens']/max(rec['wall_s'],1e-9)/1e3:.1f}k tok/s)")
            if eval_every and (s + 1) % eval_every == 0:
                ev = float(np.asarray(jax.device_get(
                    eval_loss(params, eval_dev))))
                logf.write(json.dumps(
                    {"step": s, "eval_loss": round(ev, 5)}) + "\n")
                logf.flush()
                print(f"[run:{mode}] step {s}: eval {ev:.4f}")
    # a completed leg's checkpoint is dead weight (and a stale one could
    # splice duplicate tail rows if the jsonl were ever lost) — drop it
    try:
        os.remove(ckpt_path)
    except OSError:
        pass
    print(f"[run:{mode}] done: {log_path}")


# ----------------------------------------------------------------- report

def report(out_dir: str) -> None:
    def load(mode):
        tr, ev, meta = {}, {}, None
        path = os.path.join(out_dir, f"{mode}.jsonl")
        if not os.path.exists(path):
            return None, None, None
        with open(path) as f:
            for line in f:
                try:
                    r = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn last line: the leg died mid-write
                    # AFTER the capture threshold — the curve is valid
                if r.get("meta"):
                    meta = r
                elif "eval_loss" in r:
                    ev[r["step"]] = r["eval_loss"]
                elif "loss" in r:
                    tr[r["step"]] = r["loss"]
        return tr, ev, meta

    tr_l, ev_l, meta_l = load("local")
    tr_v, ev_v, _ = load("vote")
    tr_z, ev_z, _ = load("lazy")  # optional third curve: vote_every=4 wire
    if not tr_l or not tr_v:
        raise SystemExit(
            "[report] need BOTH local.jsonl and vote.jsonl with train "
            "records; run --phase run --mode local and --mode vote first"
        )
    common = sorted(set(tr_l) & set(tr_v))
    if not common:
        raise SystemExit("[report] local and vote curves share no logged steps")
    has_lazy = bool(tr_z)
    # scale/provenance line from the leg's own meta stamp — a reduced CPU
    # leg set must not publish a report claiming 124M/T=1024 full-scale
    # provenance (the jsonl is the source of truth, the prose follows it)
    m = meta_l or {}
    if m.get("d_model"):
        arch = (f"GPT-2-family {m['n_params']/1e6:.1f}M params "
                f"({m['n_layer']}L d={m['d_model']} T={m['T']}, "
                f"{VOCAB}-token local BPE vocab)"
                + (f", {m.get('backend', '?')} backend"
                   if m.get("backend") else "")
                + (" — REDUCED tunnel-dead fallback scale"
                   if m.get("reduced") else ""))
    else:
        arch = ("GPT-2 124M architecture (12L d=768 T=1024, 16,384-token "
                "local BPE vocab ≈ 98M params)")
    lines = [
        "# Loss parity: vote-Lion (W=8) vs local Lion — equal global batch",
        "",
        arch + ", real local text, canonical reference config "
        "(lr 1e-4, wd 0.1, cosine+warmup). Generated by "
        "scripts/loss_parity.py; raw curves in local.jsonl / vote.jsonl"
        + (" / lazy.jsonl (vote_every=4 — the ≤0.5 bit/param wire)"
           if has_lazy else "") + ".",
        "",
        "| step | local loss | vote-W8 loss | Δ |"
        + (" lazy-K4 loss | Δ |" if has_lazy else ""),
        "|---|---|---|---|" + ("---|---|" if has_lazy else ""),
    ]
    show = [s for i, s in enumerate(common)
            if i % max(1, len(common) // 20) == 0] + common[-1:]
    for s in dict.fromkeys(show):
        d = tr_v[s] - tr_l[s]
        row = f"| {s} | {tr_l[s]:.4f} | {tr_v[s]:.4f} | {d:+.4f} |"
        if has_lazy:
            row += (f" {tr_z[s]:.4f} | {tr_z[s] - tr_l[s]:+.4f} |"
                    if s in tr_z else " — | — |")
        lines.append(row)
    # ---- the ONE numeric parity statement: the pre-registered pass/fail
    # criterion (VERDICT r4 #4), imported from check_evidence so the
    # report and the evidence gate can never disagree on what "parity
    # achieved" means — no second, differently-spanned mad is printed
    # alongside it (two divergent numbers in one document, code-review r5)
    import sys as _sys
    _sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from check_evidence import (PARITY_EPS_NATS, PARITY_TAIL_FRAC,
                                parity_mad)
    lines += ["",
              f"## Criterion (pre-registered): mean |Δloss| vs local over "
              f"the last {1 - PARITY_TAIL_FRAC:.0%} of steps ≤ "
              f"{PARITY_EPS_NATS} nats", ""]
    abs_dir = os.path.abspath(out_dir)
    for label in ("vote", "lazy"):
        if label == "lazy" and not has_lazy:
            continue
        v = parity_mad(abs_dir, label)
        verdict = ("UNCOMPUTABLE (leg missing/unqualified/config mismatch)"
                   if v is None else
                   f"{v:.4f} nats — "
                   + ("PASS" if v <= PARITY_EPS_NATS else "FAIL"))
        lines += [f"- {label} vs local: {verdict}", ""]
    if ev_l and ev_v:
        lines += ["| step | local eval | vote-W8 eval |"
                  + (" lazy-K4 eval |" if has_lazy else ""),
                  "|---|---|---|" + ("---|" if has_lazy else "")]
        for s in sorted(set(ev_l) & set(ev_v)):
            row = f"| {s} | {ev_l[s]:.4f} | {ev_v[s]:.4f} |"
            if has_lazy:
                row += (f" {ev_z[s]:.4f} |" if ev_z and s in ev_z
                        else " — |")
            lines.append(row)
        lines.append("")
    path = os.path.join(out_dir, "REPORT.md")
    with open(path, "w") as f:
        f.write("\n".join(lines))
    print(f"[report] {path}\n" + "\n".join(lines[:14]))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--phase", choices=("prep", "run", "report", "all"),
                    default="all")
    ap.add_argument("--mode", choices=("local", "vote", "lazy"),
                    default="local")
    ap.add_argument("--out", default=DEFAULT_OUT)
    ap.add_argument("--steps", type=int, default=2000)
    ap.add_argument("--log_every", type=int, default=10)
    ap.add_argument("--eval_every", type=int, default=500)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--corpus_bytes", type=int, default=200_000_000)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny model + short seq: CPU pipeline check only")
    ap.add_argument("--reduced", action="store_true",
                    help="≥10M-param short-seq legs on the CPU backend, "
                    "written to runs/parity_cpu (tunnel-dead fallback; "
                    "full-scale TPU legs in runs/parity take precedence)")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (a dead TPU tunnel hangs "
                    "backend init otherwise); implied by --smoke")
    args = ap.parse_args()
    global SMOKE, REDUCED, T, ROWS_PER_WORKER
    if args.smoke:
        SMOKE = True
        T = 128
        ROWS_PER_WORKER = 1
        args.cpu = True
    elif args.reduced:
        REDUCED = True
        T = 256
        ROWS_PER_WORKER = 1   # global batch 8 rows = 2048 tokens/step
        args.cpu = True
        if os.path.abspath(args.out) == os.path.abspath(DEFAULT_OUT):
            # path-compare, not string-compare: `--out runs/parity` (or a
            # trailing slash) must ALSO redirect — a reduced leg writing
            # into the full-scale directory would truncate a captured TPU
            # curve via run()'s mode-"w" open (code-review r5)
            args.out = DEFAULT_OUT + "_cpu"
    if args.phase in ("prep", "all"):
        # reduced legs share the full-scale corpus/tokens via _blocks()'s
        # fallback — prep into the shared DEFAULT_OUT, never into the
        # reduced dir (a second ~200MB corpus + hours of 1-core BPE
        # retraining, which the watcher would then auto-commit)
        prep(DEFAULT_OUT if REDUCED else args.out, args.corpus_bytes)
    if args.phase == "run":
        run(args.out, args.mode, args.steps, args.log_every,
            args.eval_every, args.seed, force_cpu=args.cpu)
    elif args.phase == "all":
        for mode in ("local", "vote"):
            run(args.out, mode, args.steps, args.log_every,
                args.eval_every, args.seed, force_cpu=args.cpu)
        report(args.out)
    if args.phase == "report":
        report(args.out)


if __name__ == "__main__":
    main()
