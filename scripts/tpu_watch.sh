#!/bin/bash
# TPU-tunnel watcher: poll until jax sees a TPU, then fire a pipeline.
#
# The axon tunnel this environment reaches its chip through can hang
# jax.devices() for HOURS (not error — hang), which is how round 1 lost its
# perf axis and round 2 recorded a CPU fallback. Run this early in a round,
# detached, so a transient outage can't erase the TPU evidence:
#
#   nohup scripts/tpu_watch.sh 'python bench.py > BENCH_TPU.json' \
#       > /tmp/tpu_watch.log 2>&1 &
#
# Every probe runs in a child process under a hard timeout (never probe
# in-process). Kill by PID, not pkill -f (which matches your own shell).

set -u
PIPELINE="${1:?usage: tpu_watch.sh '<command to run when TPU is up>'}"
INTERVAL="${2:-90}"

while true; do
  out=$(timeout 120 python -c \
    "import jax; d=jax.devices(); print(len(d), d[0].platform)" 2>/dev/null)
  case "$out" in
    *tpu*)
      echo "$(date -u +%FT%TZ) TPU up ($out); running pipeline"
      bash -c "$PIPELINE"
      exit $?
      ;;
    "")
      echo "$(date -u +%FT%TZ) probe timed out/failed" ;;
    *)
      echo "$(date -u +%FT%TZ) backend: $out (not tpu)" ;;
  esac
  sleep "$INTERVAL"
done
