#!/usr/bin/env python
"""Live-elasticity evidence (ISSUE 10): the control plane's worker
leave/join without a restart, exercised end-to-end on a W=4 mesh.

Writes ONE strict-JSON artifact, ``<out>/elasticity.json`` (schema in
scripts/validate_metrics.py; judged by check_evidence's ``elasticity``
stage):

- ``survive`` — the headline scenario: a run that drops worker 2 at step
  k (``--drop_step``) and re-absorbs it at step k+m (``--rejoin_step``)
  completes every step without restart or stall, keeps every loss and
  momentum finite, counts exactly one leave and one rejoin, and ends
  all-healthy.
- ``bit_identity`` — the degraded-phase pin: a run whose worker departed
  BEFORE the first dispatch (``worker_drop:2:0``) is byte-identical —
  loss curve — to a from-scratch W−1 masked run (the PR 5 masked-election
  machinery driven by hand, an independent path to the same mask). While
  degraded, "worker left" is a mask transition and nothing more. Plus
  determinism: two identical drop/rejoin runs produce identical curves.
- ``timeline`` — the drop/rejoin leg's membership events as
  ``cli/run_analyze.membership_timeline`` reads them back from the run
  journal (the artifact proves the journal/analyzer leg too).
- ``parity`` — the post-rejoin bound, pre-registered BEFORE capture: the
  drop/rejoin run's tail-mean loss vs the always-healthy clean run's.
  Full scale (>= PARITY_FULL_MIN_PARAMS): the absolute
  ``ELASTIC_PARITY_EPS_NATS``. Reduced CPU scale (this script's default
  tiny shape): tiny-scale tails move by O(0.1) nats under ANY change to
  the election sequence, so the criterion is RELATIVE — the transient
  degradation must cost no more than
  max(ELASTIC_PARITY_EPS_NATS_REDUCED, RELATIVE_FACTOR x the benign gap),
  where the benign gap is the tail gap of a PERMANENTLY degraded
  (never-rejoined) run vs clean: a drop that heals must not cost more
  than 1.5x a drop that never does. Both gaps are recorded so the
  judgement is inspectable.

CPU is first-class here, like bench_dcn: membership transitions are
host-side mask flips on every backend (the point is the control-plane
mechanism, not chip throughput); ``meta.backend`` records what measured
it. The runbook re-captures on chip (stage 5i).
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import shutil
import sys

# W=4 needs 4 devices; on a bare CPU host jax exposes 1 — fork BEFORE jax
# loads (the conftest trick). TPU/GPU backends are left untouched.
if os.environ.get("JAX_PLATFORMS", "") == "cpu" or not os.environ.get(
        "JAX_PLATFORMS"):
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=4").strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# ---- pre-registered criteria (fixed BEFORE the data lands) ----
ELASTIC_PARITY_EPS_NATS = 0.05          # full-scale absolute bound
ELASTIC_PARITY_EPS_NATS_REDUCED = 0.10  # reduced-scale floor
ELASTIC_PARITY_RELATIVE_FACTOR = 1.5    # x the permanent-degradation gap
PARITY_FULL_MIN_PARAMS = 10_000_000
PARITY_TAIL_FRAC = 0.75                 # tail window start

WORLD = 4
DROP_WORKER = 2


def _mesh():
    import jax

    from distributed_lion_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < WORLD:
        raise SystemExit(f"bench_elasticity needs >= {WORLD} devices, "
                         f"have {len(jax.devices())}")
    return make_mesh(data=WORLD, devices=jax.devices()[:WORLD])


def _model_cfg():
    from distributed_lion_tpu.models.gpt2 import GPT2Config

    return GPT2Config.tiny(vocab_size=512, n_layer=2, n_head=4,
                           d_model=128, n_ctx=64)


def _train_cfg(steps, **kw):
    from distributed_lion_tpu.train.loop import TrainConfig

    base = dict(
        lion=True, async_grad=True, wire="sign_psum", vote_every=1,
        vote_buckets=1, learning_rate=1e-3, lr_scheduler_type="constant",
        warmup_steps=2, max_steps=steps, per_device_train_batch_size=2,
        gradient_accumulation_steps=1, block_size=64, logging_steps=1,
        eval_steps=10**9, save_steps=10**9, output_dir=None,
    )
    base.update(kw)
    return TrainConfig(**base)


def _run_leg(steps, *, membership="", control_plane=None, mask=None,
             journal_dir=""):
    """One training leg → (curve {step: loss}, trainer facts dict).
    ``membership`` arms the control plane's drop/rejoin schedule;
    ``mask`` runs the PR 5 masked-from-scratch reference instead (guard
    enforce, mask set by hand — an independent path to the same masked
    election)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_lion_tpu.data.sources import (
        batch_iterator,
        synthetic_lm_dataset,
    )
    from distributed_lion_tpu.train import resilience
    from distributed_lion_tpu.train.loop import Trainer

    model = _model_cfg()
    mesh = _mesh()
    if control_plane is None:
        control_plane = not mask
    cfg = _train_cfg(
        steps, control_plane=control_plane,
        inject_membership=membership,
        vote_guard="enforce" if mask is not None else "off",
        # the masked-from-scratch reference runs the plain guard, whose
        # default cooldown would READMIT the hand-masked worker at step
        # ~50 (heal + mask flip) while the compared departed leg never
        # readmits by plane authority — pin readmission off so the
        # bit-identity comparison holds at any --identity_steps
        guard_cooldown=10**9 if mask is not None else 50,
        journal=bool(journal_dir), journal_dir=journal_dir)
    resilience.clear_faults()
    tr = Trainer.for_gpt2(cfg, mesh, model, seed=3)
    if mask is not None:
        tr.state = tr.state._replace(health=jnp.asarray(mask))
        tr._guard.adopt_mask(mask, step=0)
    blocks = synthetic_lm_dataset(
        max(64, tr.global_train_batch()), 64, model.vocab_size, seed=1)
    it = batch_iterator(blocks, tr.global_train_batch(), seed=5)
    hist = tr.train(it, max_steps=steps)
    losses = [h["loss"] for h in hist if "loss" in h]
    facts = {
        "completed_steps": int(tr.step_count),
        "finite": bool(np.all(np.isfinite(losses))) and all(
            bool(np.isfinite(np.asarray(m)).all())
            for m in jax.tree.leaves(tr.state.exp_avg)),
        "final_alive": int(np.asarray(tr.state.health).sum())
        if tr.state.health is not None else WORLD,
        "left_events": (tr._cplane.left_events if tr._cplane else 0),
        "rejoin_events": (tr._cplane.rejoin_events if tr._cplane else 0),
        "lifecycle": (tr._cplane.lifecycle() if tr._cplane
                      else ["healthy"] * WORLD),
    }
    tr.close()
    resilience.clear_faults()
    return {h["step"]: h["loss"] for h in hist if "loss" in h}, facts


def _tail_gap(a: dict, b: dict, steps: int) -> float:
    common = [s for s in sorted(set(a) & set(b))
              if s >= PARITY_TAIL_FRAC * steps]
    return sum(abs(a[s] - b[s]) for s in common) / max(len(common), 1)


def _run_analyze_module():
    spec = importlib.util.spec_from_file_location(
        "dlt_run_analyze_elastic",
        os.path.join(REPO, "distributed_lion_tpu", "cli", "run_analyze.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=os.path.join(REPO, "runs",
                                                  "elasticity"))
    ap.add_argument("--steps", type=int, default=80,
                    help="scenario leg length (optimizer steps)")
    ap.add_argument("--drop_step", type=int, default=10)
    ap.add_argument("--rejoin_step", type=int, default=30)
    ap.add_argument("--identity_steps", type=int, default=16,
                    help="length of the degraded bit-identity legs")
    args = ap.parse_args()
    if not 0 < args.drop_step < args.rejoin_step < args.steps:
        raise SystemExit("need 0 < drop_step < rejoin_step < steps")

    import jax

    backend = jax.devices()[0].platform
    from distributed_lion_tpu.models.gpt2 import count_params, gpt2_init

    n_params = count_params(gpt2_init(jax.random.key(0), _model_cfg()))
    os.makedirs(args.out, exist_ok=True)
    spec = (f"worker_drop:{DROP_WORKER}:{args.drop_step},"
            f"worker_rejoin:{DROP_WORKER}:{args.rejoin_step}")

    # ---- the headline scenario: drop at k, rejoin at k+m, journaled
    print(f"[bench_elasticity] drop/rejoin leg ({spec})", flush=True)
    jdir = os.path.join(args.out, "journal")
    # the journal sink appends: a re-capture over a previous artifact
    # (runbook stage 5i re-runs into the committed runs/elasticity) must
    # not merge the stale run's events into the fresh timeline
    shutil.rmtree(jdir, ignore_errors=True)
    c_scenario, facts = _run_leg(args.steps, membership=spec,
                                 journal_dir=jdir)
    survive = {
        "completed": facts["completed_steps"] == args.steps,
        "steps": facts["completed_steps"],
        "finite": facts["finite"],
        "left_events": facts["left_events"],
        "rejoin_events": facts["rejoin_events"],
        "final_alive": facts["final_alive"],
        "final_lifecycle": facts["lifecycle"],
    }

    # ---- determinism: the same schedule reproduces the same curve
    print("[bench_elasticity] drop/rejoin determinism leg", flush=True)
    c_scenario2, _ = _run_leg(args.steps, membership=spec)

    # ---- degraded bit-identity: departed-from-step-0 == masked-from-
    # scratch (the independent PR 5 path to the same masked election)
    print("[bench_elasticity] degraded bit-identity legs", flush=True)
    c_drop0, _ = _run_leg(args.identity_steps,
                          membership=f"worker_drop:{DROP_WORKER}:0")
    mask = [w != DROP_WORKER for w in range(WORLD)]
    c_masked, _ = _run_leg(args.identity_steps, mask=mask)
    bit_identity = {
        "degraded_vs_masked": c_drop0 == c_masked,
        "drop_deterministic": c_scenario == c_scenario2,
    }

    # ---- parity: clean + permanently-degraded comparators
    print("[bench_elasticity] clean + permanent-degradation legs",
          flush=True)
    c_clean, _ = _run_leg(args.steps)
    c_perm, _ = _run_leg(args.steps,
                         membership=f"worker_drop:{DROP_WORKER}:"
                                    f"{args.drop_step}")
    rejoin_gap = _tail_gap(c_scenario, c_clean, args.steps)
    benign = _tail_gap(c_perm, c_clean, args.steps)
    full_scale = n_params >= PARITY_FULL_MIN_PARAMS
    bound = (ELASTIC_PARITY_EPS_NATS if full_scale
             else max(ELASTIC_PARITY_EPS_NATS_REDUCED,
                      ELASTIC_PARITY_RELATIVE_FACTOR * benign))
    parity = {
        "bound_nats": round(bound, 6),
        "scale": "full" if full_scale else "reduced",
        "benign_permanent_gap_nats": round(benign, 6),
        "relative_factor": (None if full_scale
                            else ELASTIC_PARITY_RELATIVE_FACTOR),
        "tail_frac": PARITY_TAIL_FRAC,
        "rejoin_gap_nats": round(rejoin_gap, 6),
        "pass": rejoin_gap <= bound,
    }

    # ---- the journal's view of the scenario, read back through the
    # analyzer (proves the membership-timeline leg end to end)
    try:
        report = _run_analyze_module().analyze_dir(jdir)
        timeline = (report or {}).get("membership") or []
    except Exception as e:
        print(f"[bench_elasticity] run_analyze failed: {e}", flush=True)
        timeline = []

    doc = {
        "meta": {
            "backend": backend, "world": WORLD, "wire": "sign_psum",
            "n_params": int(n_params), "steps": args.steps,
            "drop_worker": DROP_WORKER, "drop_step": args.drop_step,
            "rejoin_step": args.rejoin_step,
            "note": "CPU-produced artifacts are first-class here: "
                    "membership transitions are host-side mask flips on "
                    "every backend (see module doc)",
        },
        "survive": survive,
        "bit_identity": bit_identity,
        "timeline": timeline,
        "parity": parity,
    }
    path = os.path.join(args.out, "elasticity.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, allow_nan=False)
        f.write("\n")
    ok = (survive["completed"] and survive["finite"]
          and survive["left_events"] == 1 and survive["rejoin_events"] == 1
          and survive["final_alive"] == WORLD
          and bit_identity["degraded_vs_masked"]
          and bit_identity["drop_deterministic"] and parity["pass"])
    print(json.dumps({"artifact": path, "survive": survive["completed"],
                      "bit_identity": bit_identity,
                      "parity_pass": parity["pass"],
                      "rejoin_gap_nats": parity["rejoin_gap_nats"],
                      "bound_nats": parity["bound_nats"]},
                     allow_nan=False), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
