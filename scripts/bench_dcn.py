#!/usr/bin/env python
"""Cross-step DCN overlap evidence (ISSUE 8): the hier wire's level-2 leg
under ``--dcn_pipeline_depth`` {0, 1, 2} with an injected ``dcn_delay``
link, plus the bits-per-param × steps-to-loss frontier.

Writes ONE strict-JSON artifact, ``<out>/dcn_overlap.json`` (schema in
scripts/validate_metrics.py; judged by check_evidence's ``dcn_overlap``
stage):

- ``bit_identity`` — the ``dcn_delay`` fault is TIMING-ONLY (depth-0 loss
  curves byte-identical armed vs unarmed), and the depth-0 wire is
  deterministic across fresh trainers. The depth-0 == pre-split-election
  pin lives in tests/test_dcn_overlap.py (vs an independent reference
  implementation); this artifact records the runtime-provable halves.
- ``ablation`` — per depth {0, 1, 2}: wall ms/step and the emulated link's
  measured residual wait (collectives.DCN_WAIT — the UNHIDDEN part of the
  injected round trip). The consume gate blocks only until
  ``launch_stamp + delay``, so compute executed during the d-step flight
  counts toward the deadline: depth 0 pays ~the full delay every step,
  depth ≥ 1 pays only what d steps of compute could not cover.
- ``overlap`` — ``recovered_frac_depth{1,2}`` = 1 − wait_d/wait_0: the
  fraction of the per-step latency the synchronous wire loses that the
  pipeline hid. The acceptance floor is ``DCN_OVERLAP_MIN`` (0.8 at
  depth 1 with a 100 ms link — ISSUE 8).
- ``frontier`` — bits/param/step (analytic, codec.wire_bytes_per_param ==
  measured: comm_drift_bytes is pinned 0 by tests) × steps-to-target-loss
  rows across wire configs, the comm-cost/convergence trade the paper's
  thesis is about. Target = the sign_psum baseline's final loss +
  ``TARGET_MARGIN_NATS`` (pre-registered; null steps_to_loss = never
  reached within the budget).
- ``parity`` — depth {1, 2} loss parity vs depth 0 over the tail
  (``PARITY_TAIL_FRAC``, the parity_strict methodology): mean |Δloss| ≤
  ``DCN_PARITY_EPS_NATS``, pre-registered BEFORE capture.

CPU is this bench's native habitat — the link is emulated wherever it
runs, and the CPU mesh is where DCN shaping is reproducible — so a
CPU-produced artifact is first-class evidence here (unlike throughput
benches); ``meta.backend`` records what measured it. The runbook re-runs
it on chip (stage 5g) so the pipeline is also proven against real XLA
async scheduling.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# the W=4 (g=2) acceptance topology needs 4 devices; on a bare CPU host
# jax exposes 1 — fork it to 4 virtual devices BEFORE jax loads (the
# conftest trick). TPU/GPU backends are left untouched.
if os.environ.get("JAX_PLATFORMS", "") == "cpu" or not os.environ.get(
        "JAX_PLATFORMS"):
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=4").strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# ---- pre-registered criteria (fixed BEFORE the data lands) ----
DCN_DELAY_MS = 100.0        # the injected level-2 round trip (ISSUE 8)
DCN_OVERLAP_MIN = 0.8       # depth-1 must hide >= this fraction of it
# depth {1,2} tail-loss gap bound vs depth 0, parity_strict methodology
# (mean |Δloss| over the tail window). Two scales, both pre-registered:
# - full scale (>= PARITY_FULL_MIN_PARAMS, the on-chip gpt2-small leg):
#   the absolute check_evidence.PARITY_EPS_NATS bound.
# - reduced CPU scale (this script's default shape, <1M params over a
#   short horizon): tiny-scale tails are noisy and ANY change to the
#   election sequence — including merely choosing a different exact wire —
#   moves them by O(0.1) nats, so an absolute bound would measure the
#   scale, not the staleness. The reduced criterion is RELATIVE: the
#   d-step-stale election must track the synchronous hier election within
#   max(DCN_PARITY_EPS_NATS_REDUCED, RELATIVE_FACTOR x the benign gap) —
#   where the benign gap is the tail MAD between the sign_psum and
#   synchronous-hier legs, same seed and data: the trajectory divergence
#   two EXACT elections already exhibit at this scale. Staleness bounded
#   by 1.5x the cost of a wire swap is the claim; the artifact records
#   both gaps so the judgement is inspectable.
DCN_PARITY_EPS_NATS = 0.05
DCN_PARITY_EPS_NATS_REDUCED = 0.10
DCN_PARITY_RELATIVE_FACTOR = 1.5
PARITY_FULL_MIN_PARAMS = 10_000_000
PARITY_TAIL_FRAC = 0.75     # tail window start (parity_strict methodology)
TARGET_MARGIN_NATS = 0.02   # frontier target = slowest leg's final + this
# (a COMMON attainable target: every leg crosses it, so steps_to_loss
# ranks convergence speed per bits/param instead of reading mostly-null)

WIRE = "hier:2"             # W=4, g=2: 2 groups, a real cross-group leg
WORLD = 4


def _mesh():
    import jax

    from distributed_lion_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < WORLD:
        raise SystemExit(f"bench_dcn needs >= {WORLD} devices, have "
                         f"{len(jax.devices())}")
    return make_mesh(data=WORLD, devices=jax.devices()[:WORLD])


def _model_cfg():
    from distributed_lion_tpu.models.gpt2 import GPT2Config

    # sized so a CPU step's COMPUTE lands around 1-2x the injected 100 ms
    # link — the regime where one step of compute can cover the round trip
    # (depth-1 steady state waits max(0, L − P), so compute ≥ L hides all
    # of it) — while the whole matrix runs in minutes. Measured
    # ~100-150 ms/step on a 4-virtual-device host CPU at this shape.
    return GPT2Config.tiny(vocab_size=512, n_layer=2, n_head=4,
                           d_model=128, n_ctx=64)


def _train_cfg(steps, depth, wire=WIRE, vote_every=1):
    from distributed_lion_tpu.train.loop import TrainConfig

    return TrainConfig(
        lion=True, async_grad=True, wire=wire, vote_every=vote_every,
        vote_buckets=1, dcn_pipeline_depth=depth, learning_rate=1e-3,
        warmup_steps=2, max_steps=steps, per_device_train_batch_size=2,
        gradient_accumulation_steps=1, block_size=64, logging_steps=1,
        eval_steps=10**9, save_steps=10**9, output_dir=None,
    )


def _run_leg(steps, depth, *, wire=WIRE, vote_every=1, delay_s=None,
             timed_tail=0):
    """One training leg. Returns (curve {step: loss}, row dict). With
    ``delay_s`` the dcn_delay fault is armed for the WHOLE leg (trace
    time); ``timed_tail`` > 0 additionally times the last N steps as a
    separate train() call (compile + pipeline cold start excluded) and
    reports ms_per_step + the emulated link's residual wait."""
    import jax

    from distributed_lion_tpu.data.sources import (
        batch_iterator,
        synthetic_lm_dataset,
    )
    from distributed_lion_tpu.parallel import collectives
    from distributed_lion_tpu.train import resilience
    from distributed_lion_tpu.train.loop import Trainer

    model = _model_cfg()
    mesh = _mesh()
    resilience.inject_fault("dcn_delay", delay_s)
    collectives.dcn_link_reset()
    try:
        tr = Trainer.for_gpt2(_train_cfg(steps, depth, wire, vote_every),
                              mesh, model, seed=3)
        blocks = synthetic_lm_dataset(
            max(64, tr.global_train_batch()), 64, model.vocab_size, seed=1)
        it = batch_iterator(blocks, tr.global_train_batch(), seed=5)
        hist = tr.train(it, max_steps=steps - timed_tail)
        row = {"depth": depth, "wire": wire, "vote_every": vote_every,
               "delay_ms": None if delay_s is None else delay_s * 1e3}
        if timed_tail:
            t0 = time.monotonic()
            tail = tr.train(it, max_steps=timed_tail)
            wall = time.monotonic() - t0
            hist += tail
            # the trainer drains collectives.DCN_WAIT into the dcn_wait_s
            # metric at log cadence (logging_steps=1 here), so the residual
            # wait is read back from the history rows — popping the global
            # here would race the loop's own drain
            row["timed_steps"] = timed_tail
            row["ms_per_step"] = round(wall / timed_tail * 1e3, 3)
            row["dcn_wait_ms_per_step"] = round(
                sum(h.get("dcn_wait_s", 0.0) for h in tail)
                / timed_tail * 1e3, 3)
        tr.close()
        curve = {h["step"]: h["loss"] for h in hist if "loss" in h}
        return curve, row
    finally:
        resilience.inject_fault("dcn_delay", None)
        collectives.dcn_link_reset()


def _tail_mad(a: dict, b: dict, steps: int) -> float:
    common = [s for s in sorted(set(a) & set(b))
              if s >= PARITY_TAIL_FRAC * steps]
    return sum(abs(a[s] - b[s]) for s in common) / max(len(common), 1)


def _steps_to(curve: dict, target: float):
    hit = [s for s, v in sorted(curve.items()) if v <= target]
    return hit[0] if hit else None


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=os.path.join(REPO, "runs",
                                                  "dcn_overlap"))
    ap.add_argument("--steps", type=int, default=80,
                    help="frontier/parity leg length (optimizer steps)")
    ap.add_argument("--ablation_steps", type=int, default=8,
                    help="timed steps per ablation depth cell")
    ap.add_argument("--delay_ms", type=float, default=DCN_DELAY_MS)
    args = ap.parse_args()

    import jax

    backend = jax.devices()[0].platform
    delay_s = args.delay_ms / 1e3
    from distributed_lion_tpu.ops.codec import wire_bytes_per_param
    from distributed_lion_tpu.models.gpt2 import count_params, gpt2_init

    n_params = count_params(gpt2_init(jax.random.key(0), _model_cfg()))

    # ---- bit-identity: the fault is timing-only; depth 0 deterministic
    print("[bench_dcn] bit-identity legs (depth 0, fault armed vs not)",
          flush=True)
    c_plain, _ = _run_leg(10, 0)
    c_plain2, _ = _run_leg(10, 0)
    c_armed, _ = _run_leg(10, 0, delay_s=delay_s)
    bit_identity = {
        "depth0_deterministic": c_plain == c_plain2,
        "depth0_fault_inert": c_plain == c_armed,
        "refactor_identity": "pinned by tests/test_dcn_overlap.py against "
                             "an independent majority-of-majorities "
                             "reference",
    }

    # ---- the depth ablation under the injected link
    ablation = []
    for depth in (0, 1, 2):
        print(f"[bench_dcn] ablation depth={depth} "
              f"delay={args.delay_ms:.0f}ms", flush=True)
        _, row = _run_leg(4 + args.ablation_steps, depth, delay_s=delay_s,
                          timed_tail=args.ablation_steps)
        ablation.append(row)
    wait0 = ablation[0]["dcn_wait_ms_per_step"]
    overlap = {
        "injected_ms": args.delay_ms,
        "lost_ms_per_step_depth0": wait0,
        "criterion": f"recovered_frac_depth1 >= {DCN_OVERLAP_MIN}",
    }
    for row in ablation[1:]:
        frac = (1.0 - row["dcn_wait_ms_per_step"] / wait0) if wait0 else 0.0
        overlap[f"recovered_frac_depth{row['depth']}"] = round(frac, 4)
    overlap["pass"] = (wait0 > 0
                       and overlap["recovered_frac_depth1"]
                       >= DCN_OVERLAP_MIN)

    # ---- frontier + parity legs (no fault: convergence, not timing)
    legs = [
        ("sign_psum", 1, 0),
        (WIRE, 1, 0),
        (WIRE, 1, 1),
        (WIRE, 1, 2),
        (WIRE, 4, 1),
    ]
    curves, frontier = {}, []
    for wire, ve, depth in legs:
        print(f"[bench_dcn] frontier leg wire={wire} vote_every={ve} "
              f"depth={depth}", flush=True)
        curve, _ = _run_leg(args.steps, depth, wire=wire, vote_every=ve)
        curves[(wire, ve, depth)] = curve
    target = round(max(c[max(c)] for c in curves.values())
                   + TARGET_MARGIN_NATS, 6)
    for wire, ve, depth in legs:
        acct = wire_bytes_per_param(n_params, WORLD, wire, vote_every=ve,
                                    dcn_pipeline_depth=depth)
        curve = curves[(wire, ve, depth)]
        frontier.append({
            "wire": wire, "vote_every": ve, "dcn_pipeline_depth": depth,
            "bits_per_param": round(acct["bits_per_param"], 4),
            "dcn_bits_per_param": round(acct.get("dcn_bits_per_param", 0.0),
                                        4),
            "dcn_overlap_frac": acct.get("dcn_overlap_frac", 0.0),
            "steps_to_loss": _steps_to(curve, target),
            "target_loss": target,
            "final_loss": round(curve[max(curve)], 6),
        })
    gap1 = _tail_mad(curves[(WIRE, 1, 1)], curves[(WIRE, 1, 0)], args.steps)
    gap2 = _tail_mad(curves[(WIRE, 1, 2)], curves[(WIRE, 1, 0)], args.steps)
    benign = _tail_mad(curves[("sign_psum", 1, 0)], curves[(WIRE, 1, 0)],
                       args.steps)
    full_scale = n_params >= PARITY_FULL_MIN_PARAMS
    bound = (DCN_PARITY_EPS_NATS if full_scale
             else max(DCN_PARITY_EPS_NATS_REDUCED,
                      DCN_PARITY_RELATIVE_FACTOR * benign))
    parity = {
        "bound_nats": round(bound, 6),
        "scale": "full" if full_scale else "reduced",
        "benign_wire_gap_nats": round(benign, 6),
        "relative_factor": (None if full_scale
                            else DCN_PARITY_RELATIVE_FACTOR),
        "tail_frac": PARITY_TAIL_FRAC,
        "depth1_gap_nats": round(gap1, 6),
        "depth2_gap_nats": round(gap2, 6),
        "pass": gap1 <= bound and gap2 <= bound,
    }

    doc = {
        "meta": {
            "backend": backend,
            "world": WORLD, "wire": WIRE, "n_params": int(n_params),
            "steps": args.steps, "ablation_steps": args.ablation_steps,
            "note": "CPU-produced artifacts are first-class here: the DCN "
                    "link is emulated on every backend (see module doc)",
        },
        "bit_identity": bit_identity,
        "ablation": ablation,
        "overlap": overlap,
        "frontier": frontier,
        "parity": parity,
    }
    os.makedirs(args.out, exist_ok=True)
    path = os.path.join(args.out, "dcn_overlap.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, allow_nan=False)
        f.write("\n")
    print(json.dumps({"artifact": path, "overlap_pass": overlap["pass"],
                      "parity_pass": parity["pass"],
                      "bit_identity": bit_identity["depth0_fault_inert"]},
                     allow_nan=False), flush=True)
    return 0 if (overlap["pass"] and parity["pass"]
                 and bit_identity["depth0_fault_inert"]) else 1


if __name__ == "__main__":
    sys.exit(main())
