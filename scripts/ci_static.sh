#!/bin/bash
# Static-analysis gate (ISSUE 4): ruff baseline + graft-check tier 1 +
# shellcheck over the runbook scripts. Invoked by check_evidence's
# `static` stage (so it runs on every runbook pass / watcher poll) and
# runnable standalone. Exit 0 = clean.
#
# Tool availability is gated, not assumed: the gate must be meaningful on
# a bare box (no ruff/shellcheck wheels, no jax) — graft-check tier 1 is
# pure stdlib and ALWAYS runs (by file path, so even the package's jax
# import is not required); ruff/shellcheck join in when installed, using
# the pyproject.toml / default configs. The jaxpr tier (tier 2) is NOT
# here: it needs a traceable step, so the runbook captures it separately
# via `python -m distributed_lion_tpu.analysis --tier2 --json-out ...`.
set -u
cd "$(dirname "$0")/.."
rc=0

if command -v ruff >/dev/null 2>&1; then
  ruff check distributed_lion_tpu scripts bench.py || rc=1
else
  echo "ci_static: ruff not installed — skipped (baseline lives in pyproject.toml)"
fi

# graft-check tier 1 over the package (pure stdlib, loaded by file path)
python distributed_lion_tpu/analysis/lint.py distributed_lion_tpu || rc=1

# serve-plane graft-check (ISSUE 19): like tier 2, the traced matrix runs
# in the runbook (`python -m distributed_lion_tpu.analysis serve-check
# --json-out runs/static/serve_check.json`, stage 0b) — here the BANKED
# artifact is held to the strict schema (stdlib validate_metrics: every
# matrix cell present and ok, inventories re-derived equal, zero host
# callbacks, donation present, compile counts within budget)
if [ -f runs/static/serve_check.json ]; then
  python scripts/validate_metrics.py runs/static/serve_check.json || rc=1
else
  echo "ci_static: runs/static/serve_check.json not captured yet — run" \
       "python -m distributed_lion_tpu.analysis serve-check --json-out it"
  rc=1
fi

if command -v shellcheck >/dev/null 2>&1; then
  shellcheck scripts/*.sh || rc=1
else
  echo "ci_static: shellcheck not installed — skipped"
fi

exit $rc
