"""Distributed Lion: 1-bit majority-vote Lion over a JAX device mesh.

Capability parity with the reference's ``update_fn_distributed`` /
``update_fn_distributed_stoc`` (/root/reference/distributed_lion.py:61-136)
and its construction-time mode dispatch (:159-166), redesigned TPU-first:

- **One fused collective per step, not one per tensor.** The reference loops
  over ~148 parameter tensors calling a blocking NCCL ``all_gather`` each
  (SURVEY §3.1 hot loop). Here every leaf's votes are concatenated into a
  single 1-D ballot vector and voted with ONE ``psum`` (or one packed
  ``all_gather``) per optimizer step.
- **Reduction on the interconnect.** The default wire (``sign_psum``) sums ±1
  int8 ballots with ``lax.psum``: receive volume is independent of world
  size, vs the reference's O(W·N) gather-then-``torch.mode``-in-Python.
- **The intended dispatch, not the reference's broken one.** The reference's
  stochastic path is unreachable (lambda returns the function object;
  ``self.max_grad_norm`` never assigned — SURVEY §2.1). Here
  ``max_grad_norm=None`` → deterministic sign votes, set → stochastic
  binarization, and ``axis_name=None`` → plain local Lion (the reference's
  uninitialized-process-group fallback, :165-166).
- **Per-worker momentum is first-class state.** ``step`` must run inside
  ``jax.shard_map`` with params replicated; momentum is stored globally with
  a leading ``[world]`` axis sharded over the data axis, so Orbax checkpoints
  capture EVERY worker's momentum (the reference silently saves only rank
  0's — SURVEY §5, checkpoint gap).

Tie rule: ties elect −1, matching ``torch.mode``'s smaller-value behavior on
even worlds (SURVEY §2.3 step 6), so trajectories are comparable.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from distributed_lion_tpu.ops import lion_math
from distributed_lion_tpu.ops.codec import vote_chunk_elems
from distributed_lion_tpu.optim.lion import (
    FunctionalOptimizer,
    LionState,
    Schedule,
    _validate,
    lion,
    resolve_lr,
)
from distributed_lion_tpu.parallel import collectives


def _flatten_votes(vote_tree):
    """Concatenate a pytree of bool vote arrays into one 1-D ballot vector."""
    leaves = jax.tree.leaves(vote_tree)
    return jnp.concatenate([l.reshape(-1) for l in leaves])


def _split_votes(flat, like_tree):
    """Inverse of :func:`_flatten_votes` against a template pytree."""
    leaves, treedef = jax.tree.flatten(like_tree)
    out, off = [], 0
    for l in leaves:
        n = l.size
        out.append(flat[off : off + n].reshape(l.shape))
        off += n
    return jax.tree.unflatten(treedef, out)


def distributed_lion(
    learning_rate: Schedule = 1e-4,
    b1: float = 0.9,
    b2: float = 0.99,
    weight_decay: float = 0.0,
    *,
    axis_name: Optional[str] = "data",
    max_grad_norm: Optional[float] = None,
    wire: str = "sign_psum",
    vote_every: int = 1,
    mom_dtype: Optional[jnp.dtype] = None,
    kernel: str = "auto",
) -> FunctionalOptimizer:
    """Build the majority-vote Lion optimizer.

    Args:
        learning_rate: scalar or schedule ``step -> lr``.
        b1, b2, weight_decay: Lion hyperparameters (ref defaults :144-147).
        axis_name: mesh axis to vote across. ``None`` → local Lion fallback.
        max_grad_norm: ``None`` → deterministic sign votes (ref :61-96);
            set → stochastic binarization with range bound
            ``r = (1 + 1/b1) * max_grad_norm`` (ref :106-108). Requires an
            ``rng`` key at ``init``.
        wire: 'sign_psum' (int8 on-fabric reduce; ICI default),
            'packed_allgather' (1-bit uint8 wire; DCN-friendly),
            'packed_a2a' (two-phase 1-bit vote, ~2 bits/param independent
            of world size; minimum-bandwidth choice for large worlds), or
            'hier:<g>' (two-level chunked vote for ICI+DCN meshes: ballot
            reduce-scatter inside g-worker ICI subgroups, cross-group ring
            of the owners' packed 1-bit verdict chunks — (W/g − 1)/g
            bits/param on the slow fabric; majority-of-majorities,
            collectives.majority_vote_hier).
        vote_every: K > 1 enables *lazy sign refresh*: each step votes on a
            rotating 1/K slice of coordinates (wire volume ÷ K — e.g.
            packed_a2a at K=4 is ~0.5 bit/param/step, meeting BASELINE.md's
            ≤1/32-of-bf16-allreduce budget per optimizer step), while the
            other coordinates apply their *last elected* sign from a packed
            1-bit cache in the state. Replicas stay bit-identical because
            the cache holds voted (shared) results only. Coordinates not yet
            voted in the first K-1 steps receive no update. Sign staleness
            ≤ K steps is the accuracy trade — covered by a convergence test.
        mom_dtype: momentum dtype override (default: param dtype, ref :185).
        kernel: 'auto' (fused Pallas kernels on TPU, plain XLA elsewhere),
            'pallas' (force; interpreted off-TPU — tests), or 'xla'.
            The Pallas path covers the deterministic mode with
            dtype-uniform pytrees; other cases fall back to XLA.

    Returns:
        A :class:`FunctionalOptimizer` whose ``step`` MUST be traced inside
        ``jax.shard_map`` with ``axis_name`` bound (unless ``axis_name`` is
        None). Params in/out are replicated; ``state.exp_avg`` is this
        worker's momentum shard (see :func:`init_global_state`).
    """
    from distributed_lion_tpu.ops.codec import parse_wire

    parse_wire(wire)  # raises on unknown formats; accepts "hier:<g>" too
    if axis_name is None:
        # The reference's uninitialized-process-group fallback is plain local
        # Lion (distributed_lion.py:165-166). Refuse to silently drop an
        # explicit stochastic request rather than mimic the reference's
        # broken max_grad_norm branch (SURVEY §2.1).
        if max_grad_norm is not None:
            raise ValueError(
                "max_grad_norm (stochastic binarization) requires a vote axis; "
                "pass axis_name or use lion() for the local optimizer"
            )
        return lion(learning_rate, b1, b2, weight_decay, mom_dtype)

    _validate(learning_rate if not callable(learning_rate) else None, b1, b2)
    if vote_every < 1:
        raise ValueError(f"vote_every must be >= 1, got {vote_every}")
    stochastic = max_grad_norm is not None
    from distributed_lion_tpu.ops.pallas_lion import resolve_kernel_mode

    interpret = resolve_kernel_mode(kernel)  # None → XLA path

    def init(params, rng: Optional[jax.Array] = None) -> LionState:
        if stochastic and rng is None:
            raise ValueError("stochastic Distributed Lion requires an rng key at init")
        exp_avg = jax.tree.map(
            lambda p: jnp.zeros_like(p, dtype=mom_dtype or p.dtype), params
        )
        elected = None
        if vote_every > 1:
            n = sum(p.size for p in jax.tree.leaves(params))
            chunk = vote_chunk_elems(n, vote_every)
            elected = jnp.zeros((vote_every * chunk // 8,), jnp.uint8)
        return LionState(count=jnp.zeros((), jnp.int32), exp_avg=exp_avg,
                         rng=rng, elected=elected)

    def _step_pallas(params, grads, state: LionState):
        """Fused-kernel fast path: two VMEM passes + one collective over the
        flat pytree (ops/pallas_lion)."""
        from distributed_lion_tpu.ops import pallas_lion

        lr = resolve_lr(learning_rate, state.count)
        p_leaves, treedef = jax.tree.flatten(params)
        m_leaves = treedef.flatten_up_to(state.exp_avg)
        g_leaves = [g.astype(m.dtype) for g, m in
                    zip(treedef.flatten_up_to(grads), m_leaves)]
        p_flat = jnp.concatenate([p.reshape(-1) for p in p_leaves])
        g_flat = jnp.concatenate([g.reshape(-1) for g in g_leaves])
        m_flat = jnp.concatenate([m.reshape(-1) for m in m_leaves])

        ballots = pallas_lion.fused_ballots(g_flat, m_flat, b1, interpret=interpret)
        total = collectives.vote_total(ballots > 0, axis_name, wire)
        p_new_flat, m_new_flat = pallas_lion.fused_apply(
            p_flat, g_flat, m_flat, total, lr, weight_decay, b2, interpret=interpret
        )
        return (
            _split_votes(p_new_flat, params),
            LionState(state.count + 1, _split_votes(m_new_flat, state.exp_avg), state.rng),
        )

    def _elect_lazy(flat_votes, state: LionState):
        """vote_every > 1: vote the rotating slice, refresh the packed sign
        cache, return (full elected bools, update-validity mask, new cache)."""
        from distributed_lion_tpu.ops.codec import pack_signs, unpack_signs

        n = flat_votes.shape[0]
        chunk = vote_chunk_elems(n, vote_every)
        padded = jnp.concatenate(
            [flat_votes, jnp.zeros((vote_every * chunk - n,), flat_votes.dtype)]
        ) if vote_every * chunk > n else flat_votes
        slot = lax.rem(state.count, jnp.int32(vote_every))
        sl = lax.dynamic_slice(padded, (slot * chunk,), (chunk,))
        elected_sl = collectives.majority_vote(sl, axis_name, wire)
        new_cache = lax.dynamic_update_slice(
            state.elected, pack_signs(elected_sl), (slot * chunk // 8,)
        )
        bits = unpack_signs(new_cache, (vote_every * chunk,))
        # cold start: slot j is first voted at count == j, so until then its
        # coordinates get no update (replicas agree — count is shared)
        slot_idx = jnp.arange(vote_every * chunk, dtype=jnp.int32) // chunk
        valid = slot_idx <= state.count
        return bits[:n], valid[:n], new_cache

    def step(params, grads, state: LionState):
        if interpret is not None and not stochastic and vote_every == 1:
            p_dtypes = {p.dtype for p in jax.tree.leaves(params)}
            m_dtypes = {m.dtype for m in jax.tree.leaves(state.exp_avg)}
            if len(p_dtypes) == 1 and len(m_dtypes) == 1:
                return _step_pallas(params, grads, state)
        lr = resolve_lr(learning_rate, state.count)
        grads = jax.tree.map(lambda g, m: g.astype(m.dtype), grads, state.exp_avg)

        # 1) weight decay, multiplicatively, before the update (ref :64).
        decayed = jax.tree.map(lambda p: lion_math.decay_params(p, lr, weight_decay), params)

        # 2) binarize: this worker's bool ballots (ref :68-71 / :105-108).
        if stochastic:
            widx = lax.axis_index(axis_name)
            base = jax.random.fold_in(state.rng, state.count)
            worker_key = jax.random.fold_in(base, widx)
            leaves = jax.tree.leaves(state.exp_avg)
            keys = jax.random.split(worker_key, len(leaves))
            keytree = jax.tree.unflatten(jax.tree.structure(state.exp_avg), list(keys))
            votes = jax.tree.map(
                lambda k, g, m: lion_math.stochastic_vote_bool(k, g, m, b1, max_grad_norm),
                keytree, grads, state.exp_avg,
            )
        else:
            votes = jax.tree.map(
                lambda g, m: lion_math.sign_vote_bool(g, m, b1), grads, state.exp_avg
            )

        # 3) ONE collective for the whole pytree (vs per-tensor all_gather,
        #    ref :81): flatten → vote → split.
        flat = _flatten_votes(votes)
        new_cache = state.elected
        if vote_every == 1:
            elected = collectives.majority_vote(flat, axis_name, wire)
            elected_tree = _split_votes(elected, votes)
            # 4) apply the elected ±1 update (ref :91-92). The psum output is
            #    identical on every worker, so replicated params stay replicated.
            new_params = jax.tree.map(
                lambda p, v: lion_math.apply_signed_update(p, v, lr),
                decayed, elected_tree,
            )
        else:
            elected, valid, new_cache = _elect_lazy(flat, state)
            signs = jnp.where(elected, 1.0, -1.0) * valid
            signs_tree = _split_votes(signs, votes)
            new_params = jax.tree.map(
                lambda p, s: p - jnp.asarray(lr, p.dtype) * s.astype(p.dtype),
                decayed, signs_tree,
            )

        # 5) momentum with the LOCAL gradient — divergent by design (ref :96).
        new_m = jax.tree.map(
            lambda g, m: lion_math.momentum_update(g, m, b2), grads, state.exp_avg
        )
        return new_params, LionState(state.count + 1, new_m, state.rng, new_cache)

    return FunctionalOptimizer(init=init, step=step)


# ---------------------------------------------------------------------------
# Global-state helpers: stacked per-worker momentum with a leading [world]
# axis, sharded P('data'), so divergent state coexists with replicated params
# under shard_map and checkpoints capture all workers (SURVEY §7 hard part 1/3).
# ---------------------------------------------------------------------------

def init_global_state(opt: FunctionalOptimizer, params, world: int,
                      rng: Optional[jax.Array] = None) -> LionState:
    """Initialize optimizer state with exp_avg stacked to ``[world, ...]``.

    The result should be device_put with the leading axis sharded over the
    data mesh axis (``parallel.mesh.data_sharded``).
    """
    st_shapes = jax.eval_shape(lambda p: opt.init(p, rng), params)
    exp_avg = jax.tree.map(
        lambda m: jnp.zeros((world,) + m.shape, m.dtype), st_shapes.exp_avg
    )
    elected = (None if st_shapes.elected is None
               else jnp.zeros(st_shapes.elected.shape, st_shapes.elected.dtype))
    return LionState(count=jnp.zeros((), jnp.int32), exp_avg=exp_avg, rng=rng,
                     elected=elected)


def squeeze_worker_state(state: LionState) -> LionState:
    """Inside shard_map: drop this worker's leading [1] momentum axis (the
    elected-sign cache is replicated and passes through)."""
    return LionState(state.count, jax.tree.map(lambda m: m[0], state.exp_avg),
                     state.rng, state.elected)


def expand_worker_state(state: LionState) -> LionState:
    """Inside shard_map: restore the leading [1] axis before returning."""
    return LionState(state.count, jax.tree.map(lambda m: m[None], state.exp_avg),
                     state.rng, state.elected)
