"""Distributed Lion: 1-bit majority-vote Lion over a JAX device mesh.

Capability parity with the reference's ``update_fn_distributed`` /
``update_fn_distributed_stoc`` (/root/reference/distributed_lion.py:61-136)
and its construction-time mode dispatch (:159-166), redesigned TPU-first:

- **One fused collective per step, not one per tensor.** The reference loops
  over ~148 parameter tensors calling a blocking NCCL ``all_gather`` each
  (SURVEY §3.1 hot loop). Here every leaf's votes are concatenated into a
  single 1-D ballot vector and voted with ONE ``psum`` (or one packed
  ``all_gather``) per optimizer step.
- **…and that collective is pipelined.** With ``vote_buckets > 1`` the
  ballot is split at ``codec.bucket_bounds``' wire-aligned boundaries and
  each chunk voted as its own collective, software-pipelined against the
  fused apply: bucket k rides the interconnect while bucket k−1's Pallas
  apply runs in VMEM, so the wire hides behind compute instead of sitting
  on the critical path. Elections and byte totals are bit-identical to the
  monolithic vote (tests/test_vote_buckets.py).
- **Reduction on the interconnect.** The default wire (``sign_psum``) sums ±1
  int8 ballots with ``lax.psum``: receive volume is independent of world
  size, vs the reference's O(W·N) gather-then-``torch.mode``-in-Python.
- **The intended dispatch, not the reference's broken one.** The reference's
  stochastic path is unreachable (lambda returns the function object;
  ``self.max_grad_norm`` never assigned — SURVEY §2.1). Here
  ``max_grad_norm=None`` → deterministic sign votes, set → stochastic
  binarization, and ``axis_name=None`` → plain local Lion (the reference's
  uninitialized-process-group fallback, :165-166).
- **Per-worker momentum is first-class state.** ``step`` must run inside
  ``jax.shard_map`` with params replicated; momentum is stored globally with
  a leading ``[world]`` axis sharded over the data axis, so Orbax checkpoints
  capture EVERY worker's momentum (the reference silently saves only rank
  0's — SURVEY §5, checkpoint gap).

Tie rule: ties elect −1, matching ``torch.mode``'s smaller-value behavior on
even worlds (SURVEY §2.3 step 6), so trajectories are comparable.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from distributed_lion_tpu.ops import lion_math
from distributed_lion_tpu.ops.codec import (
    bucket_bounds,
    hier_chunk_slot_bytes,
    hier_ring_slot_bytes,
    pack_signs,
    packed_size,
    parse_wire,
    vote_chunk_elems,
)
from distributed_lion_tpu.optim.lion import (
    FunctionalOptimizer,
    LionState,
    Schedule,
    _validate,
    lion,
    resolve_lr,
)
from distributed_lion_tpu.parallel import collectives
from distributed_lion_tpu.parallel.mesh import DATA_AXIS


def _flatten_votes(vote_tree):
    """Concatenate a pytree of bool vote arrays into one 1-D ballot vector."""
    leaves = jax.tree.leaves(vote_tree)
    return jnp.concatenate([l.reshape(-1) for l in leaves])


def _split_votes(flat, like_tree):
    """Inverse of :func:`_flatten_votes` against a template pytree."""
    leaves, treedef = jax.tree.flatten(like_tree)
    out, off = [], 0
    for l in leaves:
        n = l.size
        out.append(flat[off : off + n].reshape(l.shape))
        off += n
    return jax.tree.unflatten(treedef, out)


def _bucket_windows(bounds, sizes):
    """Static window decomposition of the persistent flat-offset layout.

    ``bounds`` are contiguous flat-coordinate buckets (codec.bucket_bounds);
    ``sizes`` the leaf sizes in ``jax.tree.leaves`` order. Returns, per
    bucket, the ``(leaf_idx, leaf_start, length, bucket_offset)`` windows
    tiling it — all Python ints at trace time, so the bucket loop unrolls
    into a fixed dataflow graph with no dynamic indexing."""
    out = []
    leaf, loff = 0, 0  # running cursor over the flat coordinate space
    for _, size in bounds:
        ws, done = [], 0
        while done < size:
            while sizes[leaf] == loff:  # also skips zero-size leaves
                leaf, loff = leaf + 1, 0
            take = min(sizes[leaf] - loff, size - done)
            ws.append((leaf, loff, take, done))
            done, loff = done + take, loff + take
        out.append(ws)
    return out


def _guard_ballot_len(n: int, vote_every: int) -> int:
    """uint8 bytes of the guard's previous-ballot state: the elected-cache
    per-slot layout under lazy refresh (so the refreshed slot's bytes line
    up across steps), plain bit-packing otherwise. Single source of truth
    for init, init_global_state and the trainer's restore templates."""
    if vote_every > 1:
        return vote_every * vote_chunk_elems(n, vote_every) // 8
    return packed_size(n)


def _ballot_flips(packed_now: jnp.ndarray,
                  packed_prev: jnp.ndarray) -> jnp.ndarray:
    """Bit flips between two packed ballots: popcount of the XOR, summed.
    ≈ 0 across consecutive (re)votes is the frozen-voter signature."""
    xor = jnp.bitwise_xor(packed_now, packed_prev)
    return jnp.sum(lax.population_count(xor).astype(jnp.int32))


def _nonfinite_count(grads, exp_avg) -> jnp.ndarray:
    """i32 count of nonfinite elements in this worker's LOCAL grads and
    momentum — the ballot inputs, checked BEFORE sign-encoding (a NaN
    u-term silently votes −1: ``NaN > 0`` is False)."""
    tot = jnp.zeros((), jnp.int32)
    for leaf in jax.tree.leaves(grads) + jax.tree.leaves(exp_avg):
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            tot = tot + jnp.sum(~jnp.isfinite(leaf)).astype(jnp.int32)
    return tot


def distributed_lion(
    learning_rate: Schedule = 1e-4,
    b1: float = 0.9,
    b2: float = 0.99,
    weight_decay: float = 0.0,
    *,
    axis_name: Optional[str] = DATA_AXIS,
    max_grad_norm: Optional[float] = None,
    wire: str = "sign_psum",
    vote_every: int = 1,
    vote_buckets: int = 1,
    dcn_pipeline_depth: int = 0,
    mom_dtype: Optional[jnp.dtype] = None,
    kernel: str = "auto",
    row_block: int = 0,
    telemetry: bool = False,
    guard: str = "off",
) -> FunctionalOptimizer:
    """Build the majority-vote Lion optimizer.

    Args:
        learning_rate: scalar or schedule ``step -> lr``.
        b1, b2, weight_decay: Lion hyperparameters (ref defaults :144-147).
        axis_name: mesh axis to vote across. ``None`` → local Lion fallback.
        max_grad_norm: ``None`` → deterministic sign votes (ref :61-96);
            set → stochastic binarization with range bound
            ``r = (1 + 1/b1) * max_grad_norm`` (ref :106-108). Requires an
            ``rng`` key at ``init``.
        wire: 'sign_psum' (int8 on-fabric reduce; ICI default),
            'packed_allgather' (1-bit uint8 wire; DCN-friendly),
            'packed_a2a' (two-phase 1-bit vote, ~2 bits/param independent
            of world size; minimum-bandwidth choice for large worlds), or
            'hier:<g>' (two-level chunked vote for ICI+DCN meshes: ballot
            reduce-scatter inside g-worker ICI subgroups, cross-group ring
            of the owners' packed 1-bit verdict chunks — (W/g − 1)/g
            bits/param on the slow fabric; majority-of-majorities,
            collectives.majority_vote_hier).
        vote_every: K > 1 enables *lazy sign refresh*: each step votes on a
            rotating 1/K slice of coordinates (wire volume ÷ K — e.g.
            packed_a2a at K=4 is ~0.5 bit/param/step, meeting BASELINE.md's
            ≤1/32-of-bf16-allreduce budget per optimizer step), while the
            other coordinates apply their *last elected* sign from a packed
            1-bit cache in the state. Replicas stay bit-identical because
            the cache holds voted (shared) results only. Coordinates not yet
            voted in the first K-1 steps receive no update. Sign staleness
            ≤ K steps is the accuracy trade — covered by a convergence test.
        vote_buckets: B > 1 splits the ballot into B contiguous wire-aligned
            chunks (codec.bucket_bounds) voted as B independent collectives,
            software-pipelined against the fused apply on the Pallas path:
            bucket k's vote rides the interconnect while bucket k−1's update
            runs in VMEM. Params/momentum are bit-identical to B = 1 for
            every wire, and the summed wire bytes equal the monolithic
            vote's exactly — bucketing changes WHEN bytes move, never what
            is elected or how much ships. Composes with ``vote_every``
            (the rotating 1/K slice is itself voted bucket-wise) and the
            stochastic path. 1 = the monolithic vote.
        dcn_pipeline_depth: d > 0 (hier wire only) enables the *cross-step
            DCN pipeline*: each step still computes and combines its level-1
            ICI tally immediately and launches the level-2 cross-group
            (DCN) ring for its own ballot — but the ring's result is only
            CONSUMED d steps later, riding ``LionState.dcn_ring`` (one slot
            per in-flight step, codec.hier_ring_slot_bytes layout) so the
            slow leg's round trip hides behind d steps of compute instead
            of sitting on every step's critical path. The elected signs
            applied at step t are therefore the complete two-level election
            of step t−d's ballots — uniformly d steps stale on every
            worker, so replicas stay bit-identical; the first d steps apply
            no update (momentum still accumulates — the same cold-start
            rule as ``vote_every``'s unvoted slots). Composes with
            ``vote_buckets`` (each bucket launches/consumes its own ring
            segment), ``vote_every`` (the consumed election lands in the
            elected cache's slot (t−d) mod K) and the vote guard (the ring
            slot carries its launch-time group-health mask; a group fully
            quarantined mid-flight abstains from the stale tally at
            consume). Byte volume per step is depth-invariant — one launch
            and one consume execute every step — so ``comm_drift_bytes``
            stays 0. 0 = today's synchronous hier wire (bit-identical to
            the pre-pipeline election). Routed to the XLA path (the Pallas
            fused-apply kernels assume fresh per-bucket totals).
        mom_dtype: momentum dtype override (default: param dtype, ref :185).
        kernel: 'auto' (fused Pallas kernels on TPU, plain XLA elsewhere),
            'pallas' (force; interpreted off-TPU — tests), or 'xla'.
            The Pallas path covers the deterministic mode with
            dtype-uniform pytrees; other cases fall back to XLA.
        row_block: Pallas kernel tile override (rows per grid step,
            multiple of 32; 0 = pallas_lion.ROW_BLOCK). A pure tiling
            knob resolved from the autotune cache by the Trainer
            (ops/autotune, knob 'lion_row_block'): params/momentum/
            elections are bit-identical at any value
            (tests/test_autotune.py), only VMEM residency and grid
            geometry change.
        telemetry: True → ``step`` returns a third value, the per-step
            vote-health *frame* (train.telemetry: margin bincount over the
            voted coordinates for tally wires, packed elected-sign state,
            local-ballot disagreement / stochastic-flip / valid-update
            counts) — raw on-device arrays the trainer folds into its
            ``VoteHealth`` accumulator. Telemetry only OBSERVES the vote:
            elections, params and momentum are bit-identical to
            ``telemetry=False`` (pinned by tests/test_telemetry.py).
        guard: the vote guard (Byzantine-tolerant elections). ``'off'`` —
            no guard state, no extra outputs. ``'observe'`` / ``'enforce'``
            → ``LionState`` carries a ``[W]`` health mask + the packed
            previous LOCAL ballot, and ``step`` returns an extra *guard
            frame* (after the telemetry frame when both are on): per-worker
            nonfinite-input counts, ballot-flip counts vs the previous vote
            (popcount XOR — a ≈0 count is a frozen voter), and local-ballot
            disagreement fractions, each a replicated ``[W]`` vector built
            from two one-hot scalar psums. Under ``'enforce'`` the election
            additionally EXCLUDES workers whose ``state.health`` bit is
            False (collectives masked vote — the majority threshold shrinks
            to the healthy quorum) and nonfinite local gradients are zeroed
            out of the momentum update so a transient NaN batch cannot
            poison ``exp_avg`` forever. With an all-healthy mask and finite
            inputs, 'enforce' is bit-identical to 'off' in elections,
            params and momentum (tests/test_vote_guard.py pins this across
            all four wires × vote_buckets × det/stoch × XLA/Pallas).
            ``'observe'`` computes the same signals but never touches the
            election. The quarantine decisions themselves (strikes,
            cooldown, readmission healing) live in the trainer's host-side
            state machine (train/vote_guard.py).

    Returns:
        A :class:`FunctionalOptimizer` whose ``step`` MUST be traced inside
        ``jax.shard_map`` with ``axis_name`` bound (unless ``axis_name`` is
        None). Params in/out are replicated; ``state.exp_avg`` is this
        worker's momentum shard (see :func:`init_global_state`).
    """
    wire_kind, wire_group = parse_wire(wire)  # raises on unknown formats
    if dcn_pipeline_depth < 0:
        raise ValueError(
            f"dcn_pipeline_depth must be >= 0, got {dcn_pipeline_depth}")
    if dcn_pipeline_depth > 0 and wire_kind != "hier":
        raise ValueError(
            f"dcn_pipeline_depth pipelines the hier wire's level-2 (DCN) "
            f"leg; wire {wire!r} has no such leg — use 'hier:<g>' or depth 0"
        )
    if axis_name is None:
        # The reference's uninitialized-process-group fallback is plain local
        # Lion (distributed_lion.py:165-166). Refuse to silently drop an
        # explicit stochastic request rather than mimic the reference's
        # broken max_grad_norm branch (SURVEY §2.1).
        if max_grad_norm is not None:
            raise ValueError(
                "max_grad_norm (stochastic binarization) requires a vote axis; "
                "pass axis_name or use lion() for the local optimizer"
            )
        if telemetry:
            raise ValueError(
                "telemetry instruments the vote; with axis_name=None there "
                "is no election to observe — use lion() for local training"
            )
        if guard != "off":
            raise ValueError(
                "the vote guard protects the election; with axis_name=None "
                "there is no election to guard — use lion() for local "
                "training"
            )
        if dcn_pipeline_depth > 0:
            raise ValueError(
                "dcn_pipeline_depth pipelines the vote wire; with "
                "axis_name=None there is no wire — use lion() for local "
                "training"
            )
        return lion(learning_rate, b1, b2, weight_decay, mom_dtype)

    _validate(learning_rate if not callable(learning_rate) else None, b1, b2)
    if vote_every < 1:
        raise ValueError(f"vote_every must be >= 1, got {vote_every}")
    if vote_buckets < 1:
        raise ValueError(f"vote_buckets must be >= 1, got {vote_buckets}")
    if guard not in ("off", "observe", "enforce"):
        raise ValueError(
            f"guard must be 'off', 'observe' or 'enforce', got {guard!r}")
    guard_on = guard != "off"
    enforce = guard == "enforce"
    stochastic = max_grad_norm is not None
    from distributed_lion_tpu.ops.pallas_lion import (
        _resolve_row_block,
        resolve_kernel_mode,
    )

    interpret = resolve_kernel_mode(kernel)  # None → XLA path
    _resolve_row_block(row_block)  # fail at build time, not mid-trace
    if telemetry:
        # train.telemetry is a leaf module (imports ops/parallel only), so
        # this upward import cannot cycle; it stays out of the default path.
        from distributed_lion_tpu.train import telemetry as _vt

        wire_has_tally = _vt.tally_wire(wire)

    def init(params, rng: Optional[jax.Array] = None) -> LionState:
        if stochastic and rng is None:
            raise ValueError("stochastic Distributed Lion requires an rng key at init")
        exp_avg = jax.tree.map(
            lambda p: jnp.zeros_like(p, dtype=mom_dtype or p.dtype), params
        )
        n = sum(p.size for p in jax.tree.leaves(params))
        elected = None
        if vote_every > 1:
            chunk = vote_chunk_elems(n, vote_every)
            elected = jnp.zeros((vote_every * chunk // 8,), jnp.uint8)
        prev_ballot = None
        if guard_on:
            # the frozen-ballot detector's XOR base: the packed previous
            # LOCAL ballot, laid out like the elected cache under lazy
            # refresh (per-slot byte-aligned chunks) so the refreshed slot's
            # bytes line up across steps
            prev_ballot = jnp.zeros((_guard_ballot_len(n, vote_every),),
                                    jnp.uint8)
        # health is created by init_global_state (its [world] length is
        # unknown at worker level); None means "mask everything in"
        return LionState(count=jnp.zeros((), jnp.int32), exp_avg=exp_avg,
                         rng=rng, elected=elected, prev_ballot=prev_ballot)

    def _guard_frame(w, nf, flips, flip_valid, dis_frac, voted):
        """Assemble the per-step guard frame: the three per-worker scalars
        become replicated ``[W]`` vectors via one one-hot psum each — the
        only collectives the guard adds to the step (all O(W) scalars; no
        host traffic, the trainer reads them one dispatch behind)."""
        widx = lax.axis_index(axis_name)
        onehot = jnp.arange(w, dtype=jnp.int32) == widx

        def vec(x):
            return lax.psum(jnp.where(onehot, x, jnp.zeros_like(x)),
                            axis_name)

        return {
            "nonfinite": vec(nf),        # i32[W] local nonfinite counts
            "flips": vec(flips),         # i32[W] ballot bit flips vs prev
            "flip_valid": flip_valid,    # bool: prev ballot was a real vote
            "disagree": vec(dis_frac),   # f32[W] local-vs-elected fraction
            "voted": jnp.asarray(voted, jnp.int32),  # coords voted
        }

    def _step_pallas(params, grads, state: LionState, guard_nf=None):
        """Fused-kernel fast path: per-window VMEM kernels + the bucketed,
        software-pipelined vote wire. ``guard_nf`` is the pre-sanitize
        nonfinite count ``step`` measured (the guard's NaN signal must see
        the raw gradients; enforce mode zeroes them before this path).

        The pytree is addressed through a persistent flat-offset layout —
        leaf offsets are Python ints fixed at trace time — and the kernels
        slice shared per-leaf flat views (``reshape(-1)``), so the step no
        longer materializes full flat copies of params/grads/momentum via a
        per-step triple ``jnp.concatenate`` (three full HBM round-trips at
        f32 width on the old path). The only cross-leaf buffers built are
        the per-bucket int8 ballot chunks — the wire payload itself.

        Pipeline order: compute + send bucket k's ballots, then run bucket
        k−1's fused apply while k is on the wire; XLA's async collectives
        turn that dataflow into interconnect/VMEM overlap. ``grads`` arrive
        already cast to the momentum dtype (hoisted once in ``step``).
        """
        from distributed_lion_tpu.ops import pallas_lion

        lr = resolve_lr(learning_rate, state.count)
        p_leaves, treedef = jax.tree.flatten(params)
        m_leaves = treedef.flatten_up_to(state.exp_avg)
        g_leaves = treedef.flatten_up_to(grads)
        p_f = [p.reshape(-1) for p in p_leaves]
        g_f = [g.reshape(-1) for g in g_leaves]
        m_f = [m.reshape(-1) for m in m_leaves]
        sizes = [p.size for p in p_leaves]
        n = sum(sizes)
        w = collectives.axis_size(axis_name)
        bounds = bucket_bounds(n, vote_buckets, w, wire)
        if not bounds:  # zero-coordinate pytree: nothing to vote or apply
            out_state = LionState(state.count + 1, state.exp_avg,
                                  state.rng, state.elected,
                                  state.health, state.prev_ballot,
                                  state.dcn_ring)
            out = (params, out_state)
            if telemetry:
                out = out + (_vt.empty_frame(0),)
            if guard_on:
                out = out + (_guard_frame(
                    w, jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32),
                    jnp.asarray(False, jnp.bool_),
                    jnp.zeros((), jnp.float32), 0),)
            return out
        alive = state.health if enforce else None
        windows = _bucket_windows(bounds, sizes)
        pieces: list[list] = [[] for _ in sizes]  # per-leaf, in flat order

        def _bucket_ballots(k):
            parts = [
                pallas_lion.fused_ballots_window(
                    g_f[li], m_f[li], b1, start=ls, length=ln,
                    interpret=interpret, row_block=row_block)
                for li, ls, ln, _ in windows[k]
            ]
            return parts[0] if len(parts) == 1 else jnp.concatenate(parts)

        def _bucket_apply(k, total):
            for li, ls, ln, boff in windows[k]:
                pieces[li].append(pallas_lion.fused_apply_window(
                    p_f[li], g_f[li], m_f[li], total, lr, weight_decay, b2,
                    start=ls, length=ln, total_offset=boff,
                    interpret=interpret, row_block=row_block))

        totals = []
        # telemetry rides the bucket pipeline: each bucket's stats kernel
        # (margin bincount + local-ballot disagreement, pallas_lion.
        # bucket_vote_stats) consumes ballots/totals already resident in
        # VMEM, and packing the per-bucket elections concatenates to the
        # full packed vector because bucket boundaries are byte-aligned.
        # Purely observational — the vote/apply dataflow is untouched.
        hist_acc = jnp.zeros((_vt.NBINS,), jnp.int32) if telemetry else None
        dis_acc = jnp.zeros((), jnp.int32) if telemetry else None
        packed_parts: list = []
        # guard accumulators: the packed LOCAL ballot (flip detection) and
        # the local-vs-elected disagreement count, folded per bucket from
        # arrays the pipeline already has in registers/VMEM. The mask is
        # applied to the bucket ballot BEFORE the collective (inside
        # vote_total — a quarantined worker's int8 ballots become zeros on
        # the wire), never to the guard's own observation of them.
        guard_packed: list = []
        guard_dis = jnp.zeros((), jnp.int32) if guard_on else None
        for k in range(len(bounds)):
            ballots = _bucket_ballots(k)
            totals.append(collectives.vote_total(
                ballots > 0, axis_name, wire, alive, state.count))
            if telemetry:
                h, d = pallas_lion.bucket_vote_stats(
                    ballots, totals[k], w, _vt.NBINS, interpret=interpret,
                    row_block=row_block)
                hist_acc, dis_acc = hist_acc + h, dis_acc + d
                packed_parts.append(pack_signs(totals[k] > 0))
            if guard_on:
                guard_packed.append(pack_signs(ballots > 0))
                guard_dis = guard_dis + jnp.sum(
                    ((ballots > 0) != (totals[k] > 0)).astype(jnp.int32))
            if k:  # apply k−1 while bucket k's collective is in flight
                _bucket_apply(k - 1, totals[k - 1])
        _bucket_apply(len(bounds) - 1, totals[-1])

        def _join(parts, leaf, idx):
            if not parts:  # zero-size leaf: nothing was windowed onto it
                return jnp.zeros(leaf.shape, leaf.dtype)
            flat = (parts[0][idx] if len(parts) == 1
                    else jnp.concatenate([p[idx] for p in parts]))
            return flat.reshape(leaf.shape)

        new_p = [_join(ws, p, 0) for ws, p in zip(pieces, p_leaves)]
        new_m = [_join(ws, m, 1) for ws, m in zip(pieces, m_leaves)]
        new_prev = state.prev_ballot
        gframe = None
        if guard_on:
            # bucket boundaries are byte-aligned for every wire, so the
            # per-bucket packed ballots concatenate to the full vector
            packed_now = (guard_packed[0] if len(guard_packed) == 1
                          else jnp.concatenate(guard_packed))
            gframe = _guard_frame(
                w, guard_nf,
                _ballot_flips(packed_now, state.prev_ballot),
                state.count >= 1,
                guard_dis.astype(jnp.float32) / n, n)
            new_prev = packed_now
        out = (
            jax.tree.unflatten(treedef, new_p),
            # this path is gated to vote_every == 1 and dcn_depth == 0,
            # where the elected-sign cache and the DCN ring are None — but
            # the invariant is "state passes through", not "they may be
            # dropped": a future un-gating must not silently lose either
            LionState(state.count + 1, jax.tree.unflatten(treedef, new_m),
                      state.rng, state.elected, state.health, new_prev,
                      state.dcn_ring),
        )
        if not telemetry:
            return out if gframe is None else out + (gframe,)
        frame = {
            "margin_hist": (hist_acc if wire_has_tally
                            else jnp.zeros((_vt.NBINS,), jnp.int32)),
            "elected": (packed_parts[0] if len(packed_parts) == 1
                        else jnp.concatenate(packed_parts)),
            "disagree": dis_acc,
            "voted": jnp.asarray(n, jnp.int32),
            "valid": jnp.asarray(n, jnp.int32),
            # this path is gated to the deterministic mode: no quantizer
            "stoch_flip_frac": jnp.zeros((), jnp.float32),
            # gated to vote_every == 1: every step is a full re-election
            "flip_valid": jnp.asarray(True, jnp.bool_),
        }
        return out + (frame,) if gframe is None else out + (frame, gframe)

    def _hier_pipelined(vec, count, ring, alive):
        """Cross-step pipelined hier election (``dcn_pipeline_depth`` > 0):
        launch this step's level-1 (ICI) + level-2 (DCN) tallies for every
        bucket of ``vec`` into the ring slot the consume just vacated, and
        elect from the slot launched ``dcn_pipeline_depth`` steps ago —
        the complete, uniformly-stale election of step count − d's ballots
        (replica-identical by construction). Returns ``(elected [n] bool,
        elect_valid scalar bool, new_ring)``; ``elect_valid`` is False for
        the first d cold-start steps, when no in-flight tally has landed
        yet. In the jaxpr the fresh launch slots feed ONLY the ring output,
        which is what lets the DCN ppermute ring overlap the following
        steps' compute (XLA async collectives; ``lax.scan`` over fused
        steps)."""
        n = vec.shape[0]
        w = collectives.axis_size(axis_name)
        bounds = bucket_bounds(n, max(vote_buckets, 1), w, wire)
        expected = sum(hier_chunk_slot_bytes(size, w, wire_group)
                       for _, size in bounds)
        if ring.shape[-1] != expected:
            raise ValueError(
                f"dcn_ring slot holds {ring.shape[-1]} bytes but this "
                f"ballot/bucket layout needs {expected} — the ring was "
                "built for a different world/wire/bucket config "
                "(init_global_state and the step must agree)")
        slot_idx = lax.rem(count, jnp.int32(dcn_pipeline_depth))
        old_slot = lax.dynamic_slice(
            ring, (slot_idx, jnp.int32(0)), (1, ring.shape[-1]))[0]
        seg_off = 0
        new_segs, elected_parts = [], []
        for start, size in bounds:
            seg_len = hier_chunk_slot_bytes(size, w, wire_group)
            votes_b = lax.slice(vec, (start,), (start + size,))
            new_seg = collectives.hier_launch(
                votes_b, axis_name, w, wire_group, alive, count)
            old_seg = lax.slice(old_slot, (seg_off,), (seg_off + seg_len,))
            # token=new_seg[:1]: inert on real hardware (the fault is not
            # armed, no dependency is traced); under the dcn_delay link
            # emulator it pins the consume gate behind this step's launch
            # so the emulated flight time spans the real d steps of compute
            elected_parts.append(collectives.hier_consume(
                old_seg, size, axis_name, w, wire_group, alive, count,
                depth=dcn_pipeline_depth, token=new_seg[:1]))
            new_segs.append(new_seg)
            seg_off += seg_len
        new_slot = (new_segs[0] if len(new_segs) == 1
                    else jnp.concatenate(new_segs))
        new_ring = lax.dynamic_update_slice(
            ring, new_slot[None], (slot_idx, jnp.int32(0)))
        elected = (elected_parts[0] if len(elected_parts) == 1
                   else jnp.concatenate(elected_parts))
        return elected, count >= dcn_pipeline_depth, new_ring

    def _elect_lazy(flat_votes, state: LionState, alive=None):
        """vote_every > 1: vote the rotating slice, refresh the packed sign
        cache, return (full elected bools, update-validity mask, new cache,
        telemetry aux, refreshed guard prev-ballot or None, new DCN ring or
        None). The aux — (slice ballots, slice totals, slice elections,
        real-coordinate mask over the CONSUMED slice, real-coordinate mask
        over the LAUNCHED slice) — feeds the vote-health and guard frames;
        it is dead code XLA prunes when both are off. ``alive`` masks
        quarantined workers out of the slice election (the guard's enforce
        mode).

        Under the cross-step DCN pipeline (``dcn_pipeline_depth`` d > 0)
        the slice LAUNCHED this step is slot count mod K as always, but the
        election CONSUMED — and written into the elected cache — is of the
        slice launched d steps ago, slot (count − d) mod K: sign staleness
        compounds to ≤ K + d steps, and slot j's coordinates first receive
        an update at count == j + d (the combined cold start)."""
        from distributed_lion_tpu.ops.codec import pack_signs, unpack_signs

        n = flat_votes.shape[0]
        chunk = vote_chunk_elems(n, vote_every)
        padded = jnp.concatenate(
            [flat_votes, jnp.zeros((vote_every * chunk - n,), flat_votes.dtype)]
        ) if vote_every * chunk > n else flat_votes
        slot = lax.rem(state.count, jnp.int32(vote_every))
        sl = lax.dynamic_slice(padded, (slot * chunk,), (chunk,))
        new_ring = None
        if dcn_pipeline_depth > 0:
            # launch the fresh slice's tallies into the ring; elect the
            # slice launched d steps ago. The consumed election belongs to
            # slot (count − d) mod K of the rotation.
            elected_sl, elect_valid, new_ring = _hier_pipelined(
                sl, state.count, state.dcn_ring, alive)
            totals_sl = jnp.where(elected_sl, 1, -1)
            write_slot = lax.rem(state.count - dcn_pipeline_depth,
                                 jnp.int32(vote_every))
        else:
            # the rotating 1/K slice votes bucket-wise too: same elected
            # bits, but the slice's wire splits into pipelineable chunks
            totals_sl = collectives.vote_total_bucketed(
                sl, axis_name, wire, vote_buckets, alive, state.count)
            elected_sl = totals_sl > 0
            elect_valid = jnp.asarray(True)
            write_slot = slot
        cache_upd = lax.dynamic_update_slice(
            state.elected, pack_signs(elected_sl), (write_slot * chunk // 8,)
        )
        # during the pipeline's cold start no election landed: the cache
        # must not adopt the zero-slot garbage (write_slot also clamps
        # negative there — the where() discards that write entirely)
        new_cache = (cache_upd if dcn_pipeline_depth == 0
                     else jnp.where(elect_valid, cache_upd, state.elected))
        new_prev = None
        if guard_on:
            # the guard's prev-ballot cache mirrors the elected cache's
            # slot layout and tracks the LAUNCHED slice (the local ballot
            # cast this step), so XOR-ing old vs new isolates this slot's
            # flips against the SAME slot's ballot one rotation (K steps)
            # ago — launch-side at any pipeline depth
            new_prev = lax.dynamic_update_slice(
                state.prev_ballot, pack_signs(sl), (slot * chunk // 8,))
        bits = unpack_signs(new_cache, (vote_every * chunk,))
        # cold start: slot j's election first LANDS at count == j + d, so
        # until then its coordinates get no update (replicas agree — count
        # is shared)
        slot_idx = jnp.arange(vote_every * chunk, dtype=jnp.int32) // chunk
        valid = slot_idx <= state.count - dcn_pipeline_depth
        # only the LAST slot can run past n: alignment pads the slice there.
        # The consume mask covers the slice the ELECTION belongs to (and is
        # all-False while no election has landed); the launch mask covers
        # the slice the local ballots were cast for.
        ar = jnp.arange(chunk, dtype=jnp.int32)
        mask_launch = (slot * chunk + ar) < n
        mask_consume = (((write_slot * chunk + ar) < n) & elect_valid
                        if dcn_pipeline_depth > 0 else mask_launch)
        return bits[:n], valid[:n], new_cache, (sl, totals_sl, elected_sl,
                                                mask_consume, mask_launch), \
            new_prev, new_ring

    def _make_frame(local, totals, elected, *, mask, voted, valid,
                    elected_packed, flip_valid):
        """Assemble the per-step vote-health frame (telemetry mode only) from
        the XLA path's vote internals: local bool ballots, the (possibly
        ±1-proxy) totals, the elected bools, and — under lazy refresh — the
        real-coordinate mask over the padded slice plus the refreshed packed
        cache. Observational: consumes the vote, never feeds back into it."""
        from distributed_lion_tpu.ops.codec import pack_signs

        w = collectives.axis_size(axis_name)
        hist = (_vt.margin_hist(totals, w, mask=mask) if wire_has_tally
                else jnp.zeros((_vt.NBINS,), jnp.int32))
        dis = local != elected
        if mask is not None:
            dis = dis & mask
        return {
            "margin_hist": hist,
            "elected": (pack_signs(elected) if elected_packed is None
                        else elected_packed),
            "disagree": jnp.sum(dis.astype(jnp.int32)),
            "voted": jnp.asarray(voted, jnp.int32),
            "valid": valid,
            "stoch_flip_frac": jnp.zeros((), jnp.float32),
            "flip_valid": jnp.asarray(flip_valid, jnp.bool_),
        }

    def step(params, grads, state: LionState):
        # grad → momentum-dtype cast, hoisted ONCE for both kernel paths
        # (the Pallas path used to re-cast internally after this cast)
        grads = jax.tree.map(lambda g, m: g.astype(m.dtype), grads, state.exp_avg)
        guard_nf = None
        if guard_on:
            # nonfinite ballot inputs, measured BEFORE enforce's sanitize
            # (and before sign-encoding hides them: NaN u-terms vote −1)
            guard_nf = _nonfinite_count(grads, state.exp_avg)
        if enforce:
            # degraded-mode training: a poisoned worker's nonfinite grad
            # coordinates are zeroed so they can neither poison its local
            # momentum forever nor steer its ballot; with finite grads
            # where() is the identity, preserving the all-healthy
            # bit-identity contract
            grads = jax.tree.map(
                lambda g: jnp.where(jnp.isfinite(g), g, jnp.zeros_like(g)),
                grads)
        if (interpret is not None and not stochastic and vote_every == 1
                and dcn_pipeline_depth == 0):
            p_dtypes = {p.dtype for p in jax.tree.leaves(params)}
            m_dtypes = {m.dtype for m in jax.tree.leaves(state.exp_avg)}
            if len(p_dtypes) == 1 and len(m_dtypes) == 1:
                return _step_pallas(params, grads, state, guard_nf)
        alive = state.health if enforce else None
        w_guard = collectives.axis_size(axis_name) if guard_on else None
        lr = resolve_lr(learning_rate, state.count)

        # 1) weight decay, multiplicatively, before the update (ref :64).
        decayed = jax.tree.map(lambda p: lion_math.decay_params(p, lr, weight_decay), params)

        # 2) binarize: this worker's bool ballots (ref :68-71 / :105-108).
        if stochastic:
            widx = lax.axis_index(axis_name)
            base = jax.random.fold_in(state.rng, state.count)
            worker_key = jax.random.fold_in(base, widx)
            leaves = jax.tree.leaves(state.exp_avg)
            keys = jax.random.split(worker_key, len(leaves))
            keytree = jax.tree.unflatten(jax.tree.structure(state.exp_avg), list(keys))
            votes = jax.tree.map(
                lambda k, g, m: lion_math.stochastic_vote_bool(k, g, m, b1, max_grad_norm),
                keytree, grads, state.exp_avg,
            )
        else:
            votes = jax.tree.map(
                lambda g, m: lion_math.sign_vote_bool(g, m, b1), grads, state.exp_avg
            )

        # 3) ONE collective for the whole pytree (vs per-tensor all_gather,
        #    ref :81): flatten → vote → split. The vote runs through
        #    vote_total (elected ⇔ total > 0) so telemetry can read the
        #    margin where the wire moves it; the election itself is the
        #    same function majority_vote_bucketed computes.
        flat = _flatten_votes(votes)
        new_cache = state.elected
        new_prev = state.prev_ballot
        new_ring = state.dcn_ring
        frame = None
        gframe = None
        if vote_every == 1 and dcn_pipeline_depth > 0:
            # cross-step pipelined hier wire: launch this step's tallies
            # into the ring, apply the election of step count − d's ballots
            # (uniformly stale → replicas agree); the first d steps apply
            # no sign update (decay still runs — the lazy-slot rule)
            elected, elect_valid, new_ring = _hier_pipelined(
                flat, state.count, state.dcn_ring, alive)
            totals = jnp.where(elected, 1, -1)  # ±1 proxy (hier never
            # moves the tally magnitude — the telemetry histogram is
            # zeroed for proxy wires regardless)
            signs = jnp.where(elected, 1.0, -1.0) * elect_valid
            signs_tree = _split_votes(signs, votes)
            new_params = jax.tree.map(
                lambda p, s: p - jnp.asarray(lr, p.dtype) * s.astype(p.dtype),
                decayed, signs_tree,
            )
            n_flat = flat.shape[0]
            if telemetry:
                frame = _make_frame(
                    flat, totals, elected,
                    mask=jnp.broadcast_to(elect_valid, flat.shape),
                    voted=jnp.where(elect_valid, n_flat, 0),
                    valid=jnp.where(elect_valid, n_flat, 0)
                    .astype(jnp.int32),
                    elected_packed=None,
                    # the first landed election (count == d) has only the
                    # zero-init accumulator to XOR against
                    flip_valid=state.count >= dcn_pipeline_depth + 1)
            if guard_on:
                packed_now = pack_signs(flat)
                gframe = _guard_frame(
                    w_guard, guard_nf,
                    _ballot_flips(packed_now, state.prev_ballot),
                    state.count >= 1,
                    # local FRESH ballot vs the d-step-stale consensus —
                    # staleness inflates every worker equally, so the
                    # guard's RELATIVE outlier rule still separates a sick
                    # voter; zero while no election has landed
                    jnp.where(elect_valid,
                              jnp.mean((flat != elected)
                                       .astype(jnp.float32)), 0.0),
                    n_flat)
                new_prev = packed_now
        elif vote_every == 1:
            totals = collectives.vote_total_bucketed(
                flat, axis_name, wire, vote_buckets, alive, state.count)
            elected = totals > 0
            elected_tree = _split_votes(elected, votes)
            # 4) apply the elected ±1 update (ref :91-92). The psum output is
            #    identical on every worker, so replicated params stay replicated.
            new_params = jax.tree.map(
                lambda p, v: lion_math.apply_signed_update(p, v, lr),
                decayed, elected_tree,
            )
            if telemetry:
                frame = _make_frame(flat, totals, elected, mask=None,
                                    voted=flat.shape[0],
                                    valid=jnp.asarray(flat.shape[0],
                                                      jnp.int32),
                                    elected_packed=None, flip_valid=True)
            if guard_on:
                packed_now = pack_signs(flat)
                gframe = _guard_frame(
                    w_guard, guard_nf,
                    _ballot_flips(packed_now, state.prev_ballot),
                    state.count >= 1,
                    jnp.mean((flat != elected).astype(jnp.float32)),
                    flat.shape[0])
                new_prev = packed_now
        else:
            elected, valid, new_cache, aux, lazy_prev, lazy_ring = \
                _elect_lazy(flat, state, alive)
            if lazy_ring is not None:
                new_ring = lazy_ring
            signs = jnp.where(elected, 1.0, -1.0) * valid
            signs_tree = _split_votes(signs, votes)
            new_params = jax.tree.map(
                lambda p, s: p - jnp.asarray(lr, p.dtype) * s.astype(p.dtype),
                decayed, signs_tree,
            )
            sl, totals_sl, elected_sl, mask_sl, mask_launch = aux
            # under the DCN pipeline the launched slice (local ballots sl)
            # and the consumed election (elected_sl) cover DIFFERENT
            # coordinate slots — a local-vs-elected comparison would be
            # cross-coordinate noise, so disagreement reports 0 there
            # (documented in ARCHITECTURE 'DCN overlap')
            dis_defined = dcn_pipeline_depth == 0
            if telemetry:
                frame = _make_frame(
                    sl, totals_sl, elected_sl,
                    mask=(mask_sl if dis_defined
                          else jnp.zeros_like(mask_sl)),
                    voted=jnp.sum(mask_sl.astype(jnp.int32)),
                    valid=jnp.sum(valid.astype(jnp.int32)),
                    elected_packed=new_cache,
                    # the refreshed slot last voted one rotation (K steps,
                    # + the pipeline's d) ago: before that its cache bytes
                    # are the zero init, not a previous election
                    flip_valid=state.count >= vote_every
                    + dcn_pipeline_depth)
            if guard_on:
                voted_launch = jnp.sum(mask_launch.astype(jnp.int32))
                dis_sl = (jnp.sum(((sl != elected_sl) & mask_sl)
                                  .astype(jnp.int32)) if dis_defined
                          else jnp.zeros((), jnp.int32))
                gframe = _guard_frame(
                    w_guard, guard_nf,
                    _ballot_flips(lazy_prev, state.prev_ballot),
                    # the refreshed slot's previous ballot is real only
                    # after a full rotation (same cold start as the flip
                    # telemetry; prev_ballot tracks LAUNCHES, so the
                    # pipeline depth does not enter)
                    state.count >= vote_every,
                    dis_sl.astype(jnp.float32)
                    / jnp.maximum(voted_launch, 1).astype(jnp.float32),
                    voted_launch)
                new_prev = lazy_prev
        if telemetry and stochastic:
            # quantizer noise: how often the stochastic ballot differs from
            # the deterministic sign it replaces (full-ballot local mean)
            det_flat = _flatten_votes(jax.tree.map(
                lambda g, m: lion_math.sign_vote_bool(g, m, b1),
                grads, state.exp_avg))
            frame["stoch_flip_frac"] = jnp.mean(
                (flat != det_flat).astype(jnp.float32))

        # 5) momentum with the LOCAL gradient — divergent by design (ref :96;
        #    under enforce the gradient was already nonfinite-sanitized, so
        #    one NaN batch cannot poison exp_avg forever).
        new_m = jax.tree.map(
            lambda g, m: lion_math.momentum_update(g, m, b2), grads, state.exp_avg
        )
        out_state = LionState(state.count + 1, new_m, state.rng, new_cache,
                              state.health, new_prev, new_ring)
        out = (new_params, out_state)
        if telemetry:
            out = out + (frame,)
        if guard_on:
            out = out + (gframe,)
        return out

    # meta: the comm config init_global_state needs to shape world-sized
    # state (the DCN pipeline ring) that init cannot know the width of
    return FunctionalOptimizer(init=init, step=step, meta={
        "wire": wire, "vote_every": vote_every,
        "vote_buckets": max(vote_buckets, 1),
        "dcn_pipeline_depth": dcn_pipeline_depth,
    })


# ---------------------------------------------------------------------------
# Global-state helpers: stacked per-worker momentum with a leading [world]
# axis, sharded P('data'), so divergent state coexists with replicated params
# under shard_map and checkpoints capture all workers (SURVEY §7 hard part 1/3).
# ---------------------------------------------------------------------------

def init_global_state(opt: FunctionalOptimizer, params, world: int,
                      rng: Optional[jax.Array] = None) -> LionState:
    """Initialize optimizer state with exp_avg stacked to ``[world, ...]``.

    The result should be device_put with the leading axis sharded over the
    data mesh axis (``parallel.mesh.data_sharded``).
    """
    st_shapes = jax.eval_shape(lambda p: opt.init(p, rng), params)
    exp_avg = jax.tree.map(
        lambda m: jnp.zeros((world,) + m.shape, m.dtype), st_shapes.exp_avg
    )
    elected = (None if st_shapes.elected is None
               else jnp.zeros(st_shapes.elected.shape, st_shapes.elected.dtype))
    # guard state: the per-worker previous ballot stacks [world, bytes] like
    # the momenta; the health mask is replicated [world], all-healthy at init
    prev_ballot = (None if st_shapes.prev_ballot is None
                   else jnp.zeros((world,) + st_shapes.prev_ballot.shape,
                                  st_shapes.prev_ballot.dtype))
    health = (None if st_shapes.prev_ballot is None
              else jnp.ones((world,), jnp.bool_))
    # DCN pipeline ring (dcn_pipeline_depth > 0, hier wire): one slot per
    # in-flight step of per-worker packed level-2 tallies. Like health, it
    # is created HERE — its slot width needs the world size (W/g groups),
    # which worker-level init cannot know. The comm config rides opt.meta.
    meta = opt.meta or {}
    depth = int(meta.get("dcn_pipeline_depth", 0) or 0)
    dcn_ring = None
    if depth > 0:
        _, group = parse_wire(meta["wire"])
        n = sum(p.size for p in jax.tree.leaves(params))
        slot = hier_ring_slot_bytes(n, world, group,
                                    meta.get("vote_buckets", 1) or 1,
                                    meta.get("vote_every", 1) or 1)
        dcn_ring = jnp.zeros((world, depth, slot), jnp.uint8)
    return LionState(count=jnp.zeros((), jnp.int32), exp_avg=exp_avg, rng=rng,
                     elected=elected, health=health, prev_ballot=prev_ballot,
                     dcn_ring=dcn_ring)


def squeeze_worker_state(state: LionState) -> LionState:
    """Inside shard_map: drop this worker's leading [1] momentum (and guard
    prev-ballot / DCN-ring) axis; the elected-sign cache and health mask are
    replicated and pass through."""
    return LionState(state.count, jax.tree.map(lambda m: m[0], state.exp_avg),
                     state.rng, state.elected, state.health,
                     None if state.prev_ballot is None
                     else state.prev_ballot[0],
                     None if state.dcn_ring is None else state.dcn_ring[0],
                     None if state.moe_ring is None else state.moe_ring[0])


def expand_worker_state(state: LionState) -> LionState:
    """Inside shard_map: restore the leading [1] axis before returning."""
    return LionState(state.count, jax.tree.map(lambda m: m[None], state.exp_avg),
                     state.rng, state.elected, state.health,
                     None if state.prev_ballot is None
                     else state.prev_ballot[None],
                     None if state.dcn_ring is None
                     else state.dcn_ring[None],
                     None if state.moe_ring is None
                     else state.moe_ring[None])


def remap_worker_momentum(exp_avg, old_world: int, new_world: int):
    """Remap stacked ``[W, ...]`` per-worker Lion momenta to ``[W', ...]``
    for elastic resume (train/loop._maybe_resume + --elastic_resume).

    The per-worker momenta are the algorithm's only divergent state; the
    defined remap preserves their cross-worker MEAN exactly in every case,
    so the center of the vote distribution — what the majority election
    estimates — is unchanged by a world-size change:

    - ``W' == W``: identity (bit-exact round trip, pinned by tests).
    - ``W' < W``, ``W % W' == 0`` (e.g. 4→2, 4→1): **shard-group
      re-averaging** — new worker i takes the mean of old workers
      ``[i*g, (i+1)*g)`` with ``g = W/W'``; the mean of group means over
      equal-size groups is the overall mean.
    - ``W' > W``, ``W' % W == 0`` (e.g. 2→4): each old worker's momentum is
      replicated to its ``W'/W`` successors (``repeat`` along axis 0); every
      old momentum appears equally often, so the mean is unchanged. The
      clones re-diverge immediately through their per-worker gradients (and,
      under stochastic binarization, per-worker RNG folds of the new index).
    - otherwise (coprime W→W'): every new worker starts from the old
      cross-worker mean — per-worker diversity is deliberately collapsed
      rather than invented, and the vote center is still preserved.

    Reductions run in f32 and cast back (bf16 ``mom_dtype`` momenta must not
    lose their mean to accumulation order)."""
    if new_world == old_world:
        return exp_avg
    if new_world < 1 or old_world < 1:
        raise ValueError(f"invalid world sizes {old_world}->{new_world}")

    def _remap(m):
        if m.shape[0] != old_world:
            raise ValueError(
                f"momentum leaf has leading dim {m.shape[0]}, expected "
                f"old world {old_world}")
        f32 = jnp.asarray(m, jnp.float32)
        if old_world % new_world == 0:
            g = old_world // new_world
            out = f32.reshape((new_world, g) + f32.shape[1:]).mean(axis=1)
        elif new_world % old_world == 0:
            out = jnp.repeat(f32, new_world // old_world, axis=0)
        else:
            out = jnp.broadcast_to(f32.mean(axis=0, keepdims=True),
                                   (new_world,) + f32.shape[1:])
        return out.astype(m.dtype)

    return jax.tree.map(_remap, exp_avg)


def heal_worker_momentum(exp_avg, healthy, workers):
    """Reset quarantined/healed workers' momenta to the HEALTHY mean.

    The vote guard's readmission (and elastic resume over a checkpoint with
    quarantined workers) must not let a sick worker's stale or poisoned
    momentum re-enter the election: each worker in ``workers`` gets the mean
    of the momenta whose ``healthy`` bit is True — the center of the healthy
    vote distribution, the same quantity :func:`remap_worker_momentum`
    preserves. The healed clone re-diverges immediately through its own
    gradients. Reductions run in f32 and cast back (same precision rule as
    the remap).

    Args:
        exp_avg: stacked ``[W, ...]`` momentum pytree (outside shard_map).
        healthy: ``[W]`` bool mask of momenta trusted as the mean's source.
        workers: iterable of worker indices to overwrite.
    """
    healthy = jnp.asarray(healthy, jnp.bool_)
    workers = [int(w) for w in workers]
    denom = jnp.maximum(jnp.sum(healthy.astype(jnp.float32)), 1.0)

    def _heal(m):
        f32 = jnp.asarray(m, jnp.float32)
        wmask = healthy.astype(jnp.float32).reshape(
            (-1,) + (1,) * (f32.ndim - 1))
        mean = jnp.sum(f32 * wmask, axis=0) / denom
        out = f32
        for w in workers:
            out = out.at[w].set(mean)
        return out.astype(m.dtype)

    return jax.tree.map(_heal, exp_avg)
