"""ZeRO-1 AdamW: optimizer state sharded over the data axis.

Net-new vs the reference (whose AdamW keeps full m/v on every DDP rank).
TPU-idiomatic state partitioning: the flattened parameter vector is split
into W equal chunks; worker i owns chunk i's Adam moments (2·N/W floats per
device instead of 2·N), updates its chunk, and the updated chunks are
re-assembled with ONE ``lax.all_gather`` — the classic ZeRO-1 exchange,
riding ICI. Requires data-parallel-synchronous gradients (the non-async
path: grads are ``pmean``'d before the optimizer), because every worker must
see the same gradient for the chunk it owns.

State layout mirrors distributed Lion's stacked per-worker momentum: m/v are
``[world, chunk]`` arrays sharded ``P('data')`` outside shard_map, a
``[1, chunk]`` block inside (squeeze/expand helpers below), so the Trainer,
Orbax checkpointing, and the sharding specs treat both optimizers uniformly.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.flatten_util import ravel_pytree

from distributed_lion_tpu.optim.lion import FunctionalOptimizer, resolve_lr
from distributed_lion_tpu.parallel.mesh import DATA_AXIS


class Zero1State(NamedTuple):
    count: jnp.ndarray
    m: jnp.ndarray  # [world, chunk] f32 (or [1, chunk]/[chunk] inside shard_map)
    v: jnp.ndarray


def zero1_chunk(n_params: int, world: int) -> int:
    return max(1, math.ceil(n_params / world))


def adamw_zero1(
    learning_rate=1e-4,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.1,
    axis_name: Optional[str] = DATA_AXIS,
) -> FunctionalOptimizer:
    """AdamW with decoupled weight decay (optax.adamw semantics — verified
    equal to the replicated path by tests/test_zero.py) and ZeRO-1 state."""
    def init(params, rng=None, world: int = 1):
        n = sum(p.size for p in jax.tree.leaves(params))
        chunk = zero1_chunk(n, world)
        return Zero1State(
            count=jnp.zeros((), jnp.int32),
            m=jnp.zeros((world, chunk), jnp.float32),
            v=jnp.zeros((world, chunk), jnp.float32),
        )

    def step(params, grads, state: Zero1State):
        m, v = state.m, state.v  # [chunk] — squeezed by the caller
        chunk = m.shape[-1]
        flat_p, unravel = ravel_pytree(params)
        flat_g, _ = ravel_pytree(grads)
        n = flat_p.shape[0]
        if axis_name is None:
            w, widx = 1, 0
        else:
            w = lax.psum(1, axis_name)
            widx = lax.axis_index(axis_name)
        pad = chunk * w - n
        flat_p32 = jnp.pad(flat_p.astype(jnp.float32), (0, pad))
        flat_g32 = jnp.pad(flat_g.astype(jnp.float32), (0, pad))
        p_c = lax.dynamic_slice(flat_p32, (widx * chunk,), (chunk,))
        g_c = lax.dynamic_slice(flat_g32, (widx * chunk,), (chunk,))

        t = state.count + 1
        m = b1 * m + (1 - b1) * g_c
        v = b2 * v + (1 - b2) * g_c * g_c
        tf = t.astype(jnp.float32)
        mhat = m / (1 - b1**tf)
        vhat = v / (1 - b2**tf)
        lr = resolve_lr(learning_rate, state.count)
        p_c = p_c - lr * (mhat / (jnp.sqrt(vhat) + eps) + weight_decay * p_c)

        if axis_name is None:
            new_flat = p_c[:n]
        else:
            new_flat = lax.all_gather(p_c, axis_name).reshape(-1)[:n]  # ZeRO exchange
        new_params = unravel(new_flat.astype(flat_p.dtype))
        return new_params, Zero1State(t, m, v)

    return FunctionalOptimizer(init=init, step=step)


def squeeze_zero_state(state: Zero1State) -> Zero1State:
    """[1, chunk] shard_map block → [chunk] worker-local view."""
    return Zero1State(state.count, state.m[0], state.v[0])


def expand_zero_state(state: Zero1State) -> Zero1State:
    """[chunk] worker-local → [1, chunk] for P('data') out_specs."""
    return Zero1State(state.count, state.m[None], state.v[None])
