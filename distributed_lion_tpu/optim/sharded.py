"""shard_map wiring for Distributed Lion: replicated params, per-worker
momentum, one vote collective — the sharding layout SURVEY §7 flags as the
build's hard part #1.

This module provides the standalone optimizer-step wrapper (used by tests and
by users who bring their own training loop). The full training step (fwd/bwd
fused with the vote in one shard_map) lives in ``train.loop``.
"""

from __future__ import annotations

from functools import partial

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_lion_tpu.optim.distributed_lion import (
    expand_worker_state,
    squeeze_worker_state,
)
from distributed_lion_tpu.optim.lion import FunctionalOptimizer, LionState
from distributed_lion_tpu.parallel.mesh import DATA_AXIS


def state_specs(has_elected: bool = False,
                has_guard: bool = False) -> LionState:
    """PartitionSpec pytree-prefix for a stacked-momentum LionState. The
    elected-sign cache (``vote_every > 1``) and the guard's health mask are
    replicated when present; the guard's per-worker previous ballot shards
    like the momenta."""
    return LionState(count=P(), exp_avg=P(DATA_AXIS), rng=P(),
                     elected=P() if has_elected else None,
                     health=P() if has_guard else None,
                     prev_ballot=P(DATA_AXIS) if has_guard else None)


def make_sharded_step(opt: FunctionalOptimizer, mesh,
                      has_elected: bool = False, has_guard: bool = False):
    """Build a jitted step over ``mesh``:

    ``(params, stacked_grads, state) -> (new_params, new_state)``
    — plus a trailing guard frame when ``has_guard``.

    - ``params``: replicated pytree.
    - ``stacked_grads``: pytree with leading ``[world]`` axis, sharded over
      the data axis — each worker consumes its own slice, standing in for
      the per-device gradients a real train step computes in place (the
      reference's no_sync contract: gradients are never averaged,
      async_trainer.py:15).
    - ``state``: from ``init_global_state``, exp_avg sharded over data.
    - ``has_elected``: True when the optimizer was built with
      ``vote_every > 1`` (the state then carries the packed sign cache).
    - ``has_guard``: True when the optimizer was built with
      ``guard != 'off'`` — the state carries the health mask + previous
      ballot and the step returns ``(params, state, guard_frame)``, the
      frame's replicated [world] health vectors included. (Optimizers
      built with ``telemetry=True`` need the Trainer: the raw telemetry
      frame carries per-worker leaves this wrapper cannot declare
      replicated.)
    """
    extra = (P(),) if has_guard else ()

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), state_specs(has_elected, has_guard)),
        out_specs=(P(), state_specs(has_elected, has_guard)) + extra,
        check_vma=False,
    )
    def _step(params, stacked_grads, state):
        grads = jax.tree.map(lambda g: g[0], stacked_grads)
        st = squeeze_worker_state(state)
        outs = opt.step(params, grads, st)
        return (outs[0], expand_worker_state(outs[1])) + tuple(outs[2:])

    return jax.jit(_step)


def shard_state(state: LionState, mesh) -> LionState:
    """device_put a stacked state with exp_avg (and the guard's stacked
    prev-ballot) over the data axis."""
    repl = NamedSharding(mesh, P())
    return LionState(
        count=jax.device_put(state.count, repl),
        exp_avg=jax.tree.map(
            lambda m: jax.device_put(m, NamedSharding(mesh, P(DATA_AXIS))),
            state.exp_avg,
        ),
        rng=None if state.rng is None else jax.device_put(state.rng, repl),
        elected=None if state.elected is None
        else jax.device_put(state.elected, repl),
        health=None if state.health is None
        else jax.device_put(state.health, repl),
        prev_ballot=None if state.prev_ballot is None
        else jax.device_put(state.prev_ballot,
                            NamedSharding(mesh, P(DATA_AXIS))),
    )
