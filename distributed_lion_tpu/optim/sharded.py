"""shard_map wiring for Distributed Lion: replicated params, per-worker
momentum, one vote collective — the sharding layout SURVEY §7 flags as the
build's hard part #1.

This module provides the standalone optimizer-step wrapper (used by tests and
by users who bring their own training loop). The full training step (fwd/bwd
fused with the vote in one shard_map) lives in ``train.loop``.
"""

from __future__ import annotations

from functools import partial

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_lion_tpu.optim.distributed_lion import (
    expand_worker_state,
    squeeze_worker_state,
)
from distributed_lion_tpu.optim.lion import FunctionalOptimizer, LionState
from distributed_lion_tpu.parallel.mesh import DATA_AXIS


def state_specs(has_elected: bool = False) -> LionState:
    """PartitionSpec pytree-prefix for a stacked-momentum LionState. The
    elected-sign cache (``vote_every > 1``) is replicated when present."""
    return LionState(count=P(), exp_avg=P(DATA_AXIS), rng=P(),
                     elected=P() if has_elected else None)


def make_sharded_step(opt: FunctionalOptimizer, mesh, has_elected: bool = False):
    """Build a jitted step over ``mesh``:

    ``(params, stacked_grads, state) -> (new_params, new_state)``

    - ``params``: replicated pytree.
    - ``stacked_grads``: pytree with leading ``[world]`` axis, sharded over
      the data axis — each worker consumes its own slice, standing in for
      the per-device gradients a real train step computes in place (the
      reference's no_sync contract: gradients are never averaged,
      async_trainer.py:15).
    - ``state``: from ``init_global_state``, exp_avg sharded over data.
    - ``has_elected``: True when the optimizer was built with
      ``vote_every > 1`` (the state then carries the packed sign cache).
    """

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), state_specs(has_elected)),
        out_specs=(P(), state_specs(has_elected)),
        check_vma=False,
    )
    def _step(params, stacked_grads, state):
        grads = jax.tree.map(lambda g: g[0], stacked_grads)
        st = squeeze_worker_state(state)
        new_params, new_st = opt.step(params, grads, st)
        return new_params, expand_worker_state(new_st)

    return jax.jit(_step)


def shard_state(state: LionState, mesh) -> LionState:
    """device_put a stacked state with exp_avg over the data axis."""
    return LionState(
        count=jax.device_put(state.count, NamedSharding(mesh, P())),
        exp_avg=jax.tree.map(
            lambda m: jax.device_put(m, NamedSharding(mesh, P(DATA_AXIS))),
            state.exp_avg,
        ),
        rng=None if state.rng is None else jax.device_put(state.rng, NamedSharding(mesh, P())),
        elected=None if state.elected is None
        else jax.device_put(state.elected, NamedSharding(mesh, P())),
    )
