from distributed_lion_tpu.optim.lion import lion, LionState
from distributed_lion_tpu.optim.distributed_lion import (
    distributed_lion,
    init_global_state,
    squeeze_worker_state,
    expand_worker_state,
)
