from distributed_lion_tpu.optim.lion import lion, LionState
from distributed_lion_tpu.optim.distributed_lion import (
    distributed_lion,
    heal_worker_momentum,
    init_global_state,
    remap_worker_momentum,
    squeeze_worker_state,
    expand_worker_state,
)
from distributed_lion_tpu.optim.zero import (
    Zero1State,
    adamw_zero1,
    expand_zero_state,
    squeeze_zero_state,
)
