"""Adapter: wrap any optax GradientTransformation as a FunctionalOptimizer.

The reference's non-``--lion`` path is torch AdamW with hardcoded
weight_decay=0.1 (/root/reference/run_clm.py:583-585); :func:`adamw` mirrors
that default. Adapted optimizers have replicated state (no per-worker
divergence), so under data parallelism the train loop psum-averages gradients
first — the classic DDP contract the reference's AsyncTrainer suppresses.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from distributed_lion_tpu.optim.lion import FunctionalOptimizer, Schedule


class OptaxState(NamedTuple):
    count: jnp.ndarray
    inner: Any
    rng: Optional[jax.Array]


def from_optax(tx: optax.GradientTransformation) -> FunctionalOptimizer:
    def init(params, rng=None):
        return OptaxState(jnp.zeros((), jnp.int32), tx.init(params), rng)

    def step(params, grads, state: OptaxState):
        updates, inner = tx.update(grads, state.inner, params)
        return optax.apply_updates(params, updates), OptaxState(state.count + 1, inner, state.rng)

    return FunctionalOptimizer(init=init, step=step)


def adamw(
    learning_rate: Schedule = 1e-4,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.1,
) -> FunctionalOptimizer:
    """The reference's AdamW baseline (run_clm.py:583-585 — wd hardcoded 0.1)."""
    return from_optax(optax.adamw(learning_rate, b1=b1, b2=b2, eps=eps, weight_decay=weight_decay))
