"""Local Lion as a pure functional optimizer.

Semantic parity with the reference's ``Lion`` class + ``update_fn``
(/root/reference/distributed_lion.py:140-200, :47-59):

- hyperparameter defaults lr=1e-4, betas=(0.9, 0.99), weight_decay=0.0
  (ref :141-148) with the same validation (ref :149-150);
- the only optimizer state is ``exp_avg`` (ref :185-186) plus a step count
  (net-new, needed for LR schedules which the reference delegates to an
  external torch scheduler, run_clm.py:582);
- op order: weight decay (multiplicative) → sign update → momentum with the
  local gradient (ref :50-59).

Design difference vs torch: instead of an object mutating ``p.data`` in a
per-tensor Python loop (ref :179-198 — the reference's hot-loop bottleneck,
SURVEY §3.1), this is a pure ``step`` over whole pytrees that XLA fuses into
a handful of elementwise kernels.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp

from distributed_lion_tpu.ops import lion_math

Schedule = Union[float, Callable[[jnp.ndarray], jnp.ndarray]]


class LionState(NamedTuple):
    count: jnp.ndarray          # int32 step counter (replicated)
    exp_avg: Any                # momentum pytree, like params (ref :185-186)
    rng: Optional[jax.Array]    # base PRNG key; None unless stochastic mode
    elected: Optional[jnp.ndarray] = None  # packed uint8 elected-sign cache
    # (replicated); present only under vote_every > 1 lazy refresh — holds the
    # last elected sign for every coordinate, 1 bit/param of state
    health: Optional[jnp.ndarray] = None  # [world] bool worker-health mask
    # (replicated); present only under the vote guard (guard != 'off') —
    # True = this worker's ballots count in the election, False = it is
    # quarantined and abstains (parallel.collectives masked vote_total).
    # Updated by the trainer's host-side quarantine machine between
    # dispatches; the step only consumes it.
    prev_ballot: Optional[jnp.ndarray] = None  # packed uint8 LOCAL ballot of
    # the previous (re)vote, per-worker divergent state like exp_avg (stored
    # globally stacked [world, bytes], sharded over the data axis) — the
    # frozen-ballot detector's XOR base. Shaped like the elected cache under
    # vote_every > 1 (per-slot byte-aligned layout), packed_size(n) otherwise.
    dcn_ring: Optional[jnp.ndarray] = None  # uint8 [depth, slot_bytes] ring
    # of in-flight level-2 (DCN) hier tallies; present only under
    # --dcn_pipeline_depth > 0 on the hier wire. Slot (count mod depth)
    # holds the packed per-group verdict stack hier_launch produced at step
    # count − depth (codec.hier_ring_slot_bytes layout), consumed by
    # hier_consume this step before being overwritten with this step's
    # launch. Per-worker divergent (each member owns a different 1/g chunk
    # of coordinates), so stored globally stacked [world, depth, bytes] and
    # sharded over the data axis like exp_avg/prev_ballot. Created by
    # init_global_state (slot width needs the world size); serializes with
    # the checkpoint so crash-resume stays bit-identical mid-flight.
    moe_ring: Optional[jnp.ndarray] = None  # f32 [world, depth,
    # n_moe_blocks, E+1] ring of in-flight MoE balance tallies (training
    # --ep_dcn_pipeline > 0, ISSUE 16): slot (count mod depth) holds the
    # expert-axis-psummed per-block routing tallies (per-expert token
    # counts + lane count) this data worker produced at step count − depth,
    # read by the trainer's step core to feed the aux balance loss d steps
    # stale, then overwritten with this step's fresh tally. Per-DATA-worker
    # divergent BY DESIGN (each worker balances against its own batch's
    # stale load — no data-axis collective is added, preserving the
    # async-grad contract that the vote is the only optimizer collective),
    # so stacked [world, ...] and sharded over the data axis like exp_avg.
    # Created by the Trainer (the tally shape needs the model config, which
    # the optimizer never sees); the optimizer's step passes it through
    # untouched. Serializes with the checkpoint so crash-resume keeps the
    # in-flight staleness bit-identical; an all-zero slot (lane count 0)
    # is the cold-start sentinel — the aux falls back to the fresh local
    # load (parallel/expert.moe_ffn balance_tokens).


def _validate(lr_init: float, b1: float, b2: float) -> None:
    # Same guards as the reference (distributed_lion.py:149-150).
    if lr_init is not None and not callable(lr_init) and lr_init <= 0.0:
        raise ValueError(f"Invalid learning rate: {lr_init}")
    for i, b in enumerate((b1, b2)):
        if not 0.0 <= b <= 1.0:
            raise ValueError(f"Invalid beta parameter at index {i}: {b}")


def resolve_lr(learning_rate: Schedule, count: jnp.ndarray) -> jnp.ndarray:
    return learning_rate(count) if callable(learning_rate) else jnp.asarray(learning_rate)


class FunctionalOptimizer(NamedTuple):
    """Minimal pure-optimizer interface: ``init(params) -> state`` and
    ``step(params, grads, state) -> (new_params, new_state)``.

    ``step`` returns new params directly (rather than optax-style additive
    updates) so the multiplicative weight-decay ordering of the reference is
    preserved bit-for-bit in low precision.

    ``meta`` (optional) carries the build-time comm config world-level
    helpers need but ``init`` cannot know — ``init_global_state`` shapes the
    DCN pipeline ring from ``meta['wire'] / ['vote_every'] /
    ['vote_buckets'] / ['dcn_pipeline_depth']`` once the world size is in
    hand (same reason the guard's ``health`` mask is created there).
    """

    init: Callable[..., LionState]
    step: Callable[..., tuple]
    meta: Optional[dict] = None


def lion(
    learning_rate: Schedule = 1e-4,
    b1: float = 0.9,
    b2: float = 0.99,
    weight_decay: float = 0.0,
    mom_dtype: Optional[jnp.dtype] = None,
) -> FunctionalOptimizer:
    """Single-worker Lion (the reference's world_size==1 / fallback path,
    distributed_lion.py:165-166)."""
    _validate(learning_rate if not callable(learning_rate) else None, b1, b2)

    def init(params, rng: Optional[jax.Array] = None) -> LionState:
        exp_avg = jax.tree.map(
            lambda p: jnp.zeros_like(p, dtype=mom_dtype or p.dtype), params
        )
        return LionState(count=jnp.zeros((), jnp.int32), exp_avg=exp_avg, rng=rng)

    def step(params, grads, state: LionState):
        lr = resolve_lr(learning_rate, state.count)
        p_leaves, treedef = jax.tree.flatten(params)
        g_leaves = treedef.flatten_up_to(grads)
        m_leaves = treedef.flatten_up_to(state.exp_avg)
        pairs = [
            lion_math.local_lion_leaf(p, g.astype(m.dtype), m, lr, weight_decay, b1, b2)
            for p, g, m in zip(p_leaves, g_leaves, m_leaves)
        ]
        new_params = jax.tree.unflatten(treedef, [p for p, _ in pairs])
        new_m = jax.tree.unflatten(treedef, [m for _, m in pairs])
        return new_params, LionState(state.count + 1, new_m, state.rng)

    return FunctionalOptimizer(init=init, step=step)
