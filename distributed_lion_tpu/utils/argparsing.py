"""Dataclass-driven CLI parsing — the HfArgumentParser role.

The reference parses CLI flags into dataclass groups via HfArgumentParser,
including JSON-file configs (/root/reference/run_clm.py:252-258,
sft_llama2.py:42-43). Same surface here: every dataclass field becomes a
``--flag``; booleans accept ``--flag`` / ``--flag false``; a single JSON-file
argument populates all groups.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import pathlib
import sys
import typing
from typing import Optional, Sequence, Type


def _str2bool(v: str) -> bool:
    if isinstance(v, bool):
        return v
    if v.lower() in ("yes", "true", "t", "1"):
        return True
    if v.lower() in ("no", "false", "f", "0"):
        return False
    raise argparse.ArgumentTypeError(f"boolean value expected, got {v!r}")


def _unwrap_optional(tp):
    origin = typing.get_origin(tp)
    if origin is typing.Union:
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0], True
    return tp, False


def build_parser(dataclass_types: Sequence[Type]) -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        description="distributed_lion_tpu", allow_abbrev=False,
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    seen = set()
    for dc in dataclass_types:
        group = parser.add_argument_group(dc.__name__)
        for f in dataclasses.fields(dc):
            if not f.metadata.get("cli", True):
                # programmatic-only field (metadata {'cli': False}): no
                # flag, and it may shadow a same-named flag owned by
                # another group (e.g. TrainConfig.remat_policy vs
                # run_clm ModelArguments.remat_policy — the CLI flag
                # drives the model config; the TrainConfig field is the
                # Trainer-builder override bench/tests use)
                continue
            if f.name in seen:
                raise ValueError(f"duplicate field {f.name!r} across dataclasses")
            seen.add(f.name)
            tp, _ = _unwrap_optional(f.type if not isinstance(f.type, str) else eval(f.type, vars(typing) | {"Optional": Optional}))
            default = f.default if f.default is not dataclasses.MISSING else (
                f.default_factory() if f.default_factory is not dataclasses.MISSING else None
            )
            kw: dict = {"default": default, "help": f.metadata.get("help", "")}
            if tp is bool:
                # --flag (→ true) or --flag false, like HfArgumentParser
                kw.update(type=_str2bool, nargs="?", const=True)
            elif typing.get_origin(tp) in (list, typing.List):
                kw.update(type=typing.get_args(tp)[0] if typing.get_args(tp) else str, nargs="*")
            elif tp in (int, float, str):
                kw.update(type=tp)
            else:
                kw.update(type=str)
            group.add_argument(f"--{f.name}", **kw)
    return parser


def parse_dataclasses(
    dataclass_types: Sequence[Type], args: Optional[Sequence[str]] = None
) -> tuple:
    """Parse argv (or a JSON config file given as the sole argument) into one
    instance per dataclass, in order."""
    argv = list(sys.argv[1:] if args is None else args)
    if len(argv) == 1 and argv[0].endswith(".json"):
        values = json.loads(pathlib.Path(argv[0]).read_text())
    else:
        parser = build_parser(dataclass_types)
        ns = parser.parse_args(argv)
        values = vars(ns)

    out = []
    for dc in dataclass_types:
        # cli:False fields never populate from parsed flags or JSON —
        # without this, a same-named FLAG owned by another group leaks in
        # (e.g. ModelArguments.remat_policy default 'full' would land in
        # TrainConfig.remat_policy and break `--remat false`)
        kwargs = {f.name: values[f.name] for f in dataclasses.fields(dc)
                  if f.name in values and f.metadata.get("cli", True)}
        out.append(dc(**kwargs))
    return tuple(out)
