"""Flat .npz pytree serialization — the export format for merged LoRA models
and adapters (the reference's save_pretrained/merged-save flow,
sft_llama2.py:183-199). Orbax handles training checkpoints; this handles
portable single-file model export."""

from __future__ import annotations

import pathlib
from typing import Any

import jax
import numpy as np


def _flatten(tree, prefix=()):
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield from _flatten(v, prefix + (str(k),))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            yield from _flatten(v, prefix + (f"#{i}",))
    else:
        yield "/".join(prefix), tree


def save_pytree(path: str | pathlib.Path, tree: Any) -> None:
    flat = {k: np.asarray(v) for k, v in _flatten(tree)}
    pathlib.Path(path).parent.mkdir(parents=True, exist_ok=True)
    np.savez(path, **flat)


def load_pytree(path: str | pathlib.Path) -> Any:
    """Rebuild the nested dict/list structure from flat keys."""
    data = np.load(path)
    root: dict = {}
    for key in data.files:
        parts = key.split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = data[key]
    return _listify(root)


def _listify(node):
    if isinstance(node, dict):
        if node and all(k.startswith("#") for k in node):
            return [_listify(node[f"#{i}"]) for i in range(len(node))]
        return {k: _listify(v) for k, v in node.items()}
    return node
