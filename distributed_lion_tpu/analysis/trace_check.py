"""graft-check tier 2: jaxpr contract checks on the ACTUAL compiled step.

The wire recipe (README) is a promise about what the compiled train step
puts on the interconnect; PR 2 verifies it at runtime as
``comm_drift_bytes == 0``. This module verifies it STATICALLY, before a
single step runs, by walking the jaxpr of the real train step (one
abstract trace per config — the same ``jax.eval_shape``-cost pattern as
``telemetry.measure_step_wire``) and asserting:

- the **collective-primitive inventory** — every psum / all_gather /
  all_to_all / ppermute call site with its axis names and operand element
  count — exactly matches the expected set derived from
  ``codec.bucket_bounds`` for the configured wire × ``vote_buckets`` ×
  ``vote_every``. Scalar reductions (metric pmeans, telemetry's two
  psums; operands ≤ ``SCALAR_MAX`` elements) are tallied separately: the
  contract is that every LARGE operand on the wire belongs to the vote.
- **zero host callbacks** (``pure_callback`` / ``io_callback`` /
  ``jax.debug.*``) anywhere in the step's jaxpr — a callback is a hidden
  per-step host round-trip that telemetry only sees as a slow step.
- **donated buffers are actually donated**: the lowered module carries
  input-output aliasing (``tf.aliasing_output``) for the params/state
  arguments, so the step updates in place instead of doubling HBM.
- **no f32 upcast of bf16 param leaves**: a ``convert_element_type``
  consuming a bf16 *param input* into f32 doubles the param read traffic
  the bf16 storage opted out of; loss/norm/clip math upcasting computed
  values is fine and not flagged (the check follows the param inputs
  only).

Counts are at the call-site (eqn) level: a ppermute under ``lax.scan``
executes ring-length times per step but is ONE wire call site, exactly how
``collectives.WIRE_TALLY`` ledgers it.

Requires jax (this is the tier the CLI gates behind ``--tier2``); the
pure-stdlib source lint lives in :mod:`analysis.lint`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterable, Optional

import jax
import jax.numpy as jnp

from distributed_lion_tpu.ops.codec import (
    a2a_chunk_bytes,
    bucket_bounds,
    packed_size,
    parse_wire,
    vote_chunk_elems,
)
from distributed_lion_tpu.parallel.mesh import DATA_AXIS

COLLECTIVE_PRIMS = ("psum", "all_gather", "all_to_all", "ppermute")
# any primitive whose name contains one of these is a host round-trip
HOST_CALLBACK_MARKERS = ("callback", "debug_print")
# operands at or below this element count are "scalar reductions" (metric
# pmeans, telemetry's two per-step psums) — bookkeeping, not wire payload.
# Vote operands are ballot-bucket-sized (≥ thousands of elements for any
# real model), so the two classes cannot collide.
SCALAR_MAX = 64


@dataclasses.dataclass(frozen=True)
class CollectiveCall:
    """One collective call site in the step's jaxpr."""

    prim: str
    axes: tuple
    nelems: int
    dtype: str

    @property
    def key(self) -> tuple:
        return (self.prim, self.axes, self.nelems)


# ----------------------------------------------------------------- jaxpr walk
def _inner_jaxprs(eqn) -> list:
    """Sub-jaxprs of an eqn (pjit/shard_map/scan/remat/custom_* bodies),
    as ClosedJaxpr-or-Jaxpr objects."""
    out = []
    for v in eqn.params.values():
        vals = v if isinstance(v, (tuple, list)) else (v,)
        for item in vals:
            if hasattr(item, "eqns") or hasattr(item, "jaxpr"):
                out.append(item)
    return out


def _as_jaxpr(j):
    return j.jaxpr if hasattr(j, "jaxpr") else j


def iter_eqns(jaxpr) -> Iterable:
    """Depth-first over every eqn, descending into sub-jaxprs."""
    for eqn in _as_jaxpr(jaxpr).eqns:
        yield eqn
        for sub in _inner_jaxprs(eqn):
            yield from iter_eqns(sub)


def _axes_of(eqn) -> tuple:
    axes = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if isinstance(axes, (str, int)):
        axes = (axes,)
    return tuple(str(a) for a in axes)


def collective_calls(fn, *args) -> tuple[list[CollectiveCall], list[str]]:
    """Trace ``fn`` abstractly and return (collective call sites, host
    callback primitive names) over its whole jaxpr."""
    jaxpr = jax.make_jaxpr(fn)(*args)
    calls: list[CollectiveCall] = []
    callbacks: list[str] = []
    for eqn in iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            for v in eqn.invars:
                aval = getattr(v, "aval", None)
                if aval is None or not hasattr(aval, "shape"):
                    continue
                nelems = 1
                for d in aval.shape:
                    nelems *= int(d)
                calls.append(CollectiveCall(name, _axes_of(eqn), nelems,
                                            str(aval.dtype)))
        elif any(m in name for m in HOST_CALLBACK_MARKERS):
            callbacks.append(name)
    return calls, callbacks


# ----------------------------------------------------------- expected recipe
def expected_wire_calls(n_params: int, world: int, wire: str, *,
                        vote_every: int = 1, vote_buckets: int = 1,
                        dcn_pipeline_depth: int = 0,
                        axis_name: str = DATA_AXIS) -> list[tuple]:
    """The wire recipe's expected collective call sites, as a sorted list of
    ``(prim, axes, nelems)`` — derived from the SAME single sources of truth
    the collectives slice by (``codec.bucket_bounds`` /
    ``codec.vote_chunk_elems`` / ``codec.a2a_chunk_bytes``), so the
    expectation cannot drift from the accounting.

    Mirrors ``parallel.collectives`` call sites exactly:

    - ``sign_psum``: one psum of the (int-cast) ballot per bucket;
    - ``packed_allgather``: one all_gather of the packed bytes per bucket;
    - ``packed_a2a``: per bucket, one all_to_all of the ``[W, chunk]``
      packed ballots + one all_gather of the ``[chunk]`` packed verdicts;
    - ``hier:<g>``: per bucket, the three scan-ring ppermute call sites —
      ballot reduce-scatter (``[chunk]`` at the accumulator width, g > 1),
      cross-group packed-verdict ring (``[chunk/8]``, W/g > 1), intra-group
      packed-elected all-gather (``[chunk/8]``, g > 1).

    ``dcn_pipeline_depth`` is accepted to PIN the depth-invariance contract
    of the hier wire's cross-step pipeline: at any depth, every step runs
    exactly one launch (legs 1+2 for its own ballot) and one consume (leg 3
    for the ballot launched d steps earlier), so the expected inventory is
    IDENTICAL to the synchronous wire — no duplicate DCN collective (a
    cold-start path that traced both a fresh and a stale consume would
    double leg 3), no missing leg, the ICI legs untouched. The parameter
    deliberately does not change the expectation; callers pass it so the
    contract is explicit in every depth cell (tests/test_trace_check.py).
    """
    del dcn_pipeline_depth  # depth-invariant by design — see docstring
    kind, group = parse_wire(wire)
    ballot = (n_params if vote_every <= 1
              else vote_chunk_elems(n_params, vote_every))
    axes = (axis_name,)
    out: list[tuple] = []
    for _, size in bucket_bounds(ballot, max(vote_buckets, 1), world, wire):
        if kind == "sign_psum":
            out.append(("psum", axes, size))
        elif kind == "packed_allgather":
            out.append(("all_gather", axes, packed_size(size)))
        elif kind == "packed_a2a":
            chunk = a2a_chunk_bytes(size, world)
            out.append(("all_to_all", axes, world * chunk))
            out.append(("all_gather", axes, chunk))
        else:  # hier:<g>
            g = group
            n_groups = world // g
            chunk = 8 * a2a_chunk_bytes(size, g)
            if g > 1:
                out.append(("ppermute", axes, chunk))
            if n_groups > 1:
                out.append(("ppermute", axes, chunk // 8))
            if g > 1:
                out.append(("ppermute", axes, chunk // 8))
    return sorted(out)


# ------------------------------------------------------------- param upcasts
def param_upcasts(fn, args, param_argnum: int = 0) -> list[tuple]:
    """``convert_element_type`` eqns that consume a bf16 PARAM INPUT leaf
    directly into f32, followed through pjit/shard_map/scan bodies by
    positional invar mapping. Returns ``(shape,)`` tuples of the upcast
    leaves; [] when params are not bf16 or never upcast wholesale."""
    jaxpr = jax.make_jaxpr(fn)(*args)
    flat_before = sum(len(jax.tree.leaves(a)) for a in args[:param_argnum])
    n_leaves = len(jax.tree.leaves(args[param_argnum]))
    top = _as_jaxpr(jaxpr)
    pvars = set(top.invars[flat_before:flat_before + n_leaves])
    found: list[tuple] = []

    def walk(jx, pv) -> None:
        for eqn in jx.eqns:
            subs = _inner_jaxprs(eqn)
            if subs:
                for sub in subs:
                    sj = _as_jaxpr(sub)
                    if len(sj.invars) == len(eqn.invars):
                        # Literal invars are unhashable (and never params)
                        inner = {sj.invars[i]
                                 for i, v in enumerate(eqn.invars)
                                 if type(v).__name__ != "Literal"
                                 and v in pv}
                        walk(sj, inner)
                    else:  # conservative: positions unknown — don't follow
                        walk(sj, set())
                continue
            if eqn.primitive.name != "convert_element_type":
                continue
            v = eqn.invars[0]
            aval = getattr(v, "aval", None)
            if (v in pv and aval is not None
                    and aval.dtype == jnp.bfloat16
                    and eqn.params.get("new_dtype") == jnp.float32):
                found.append(tuple(aval.shape))

    walk(top, pvars)
    return found


# ---------------------------------------------------------------- the checks
def donation_report(jitted, *args) -> dict:
    """Lower the jitted step and count donation annotations — the
    lowering-level proof that ``donate_argnums`` buffers really alias
    outputs (zero of both means donation silently failed and params +
    momentum exist twice in HBM). jax marks resolved aliases as
    ``tf.aliasing_output`` and donation intent under sharded lowering as
    ``jax.buffer_donor``; either proves the request survived lowering."""
    text = jitted.lower(*args).as_text()
    return {
        "aliased_outputs": text.count("tf.aliasing_output"),
        "buffer_donors": text.count("jax.buffer_donor"),
    }


def check_step(fn, args: tuple, *, n_params: int, world: int, wire: str,
               vote_every: int = 1, vote_buckets: int = 1,
               dcn_pipeline_depth: int = 0,
               axis_name: str = DATA_AXIS,
               scalar_max: int = SCALAR_MAX) -> dict:
    """Run the jaxpr contract over one step function + example args.

    Returns a report dict; ``report["ok"]`` is the CI verdict (inventory
    matches AND zero host callbacks). Donation is checked separately
    (:func:`donation_report` needs the jitted wrapper, not the core fn).
    """
    calls, callbacks = collective_calls(fn, *args)
    wire_calls = sorted(c.key for c in calls if c.nelems > scalar_max)
    scalar_calls = [c for c in calls if c.nelems <= scalar_max]
    expected = expected_wire_calls(
        n_params, world, wire, vote_every=vote_every,
        vote_buckets=vote_buckets, dcn_pipeline_depth=dcn_pipeline_depth,
        axis_name=axis_name)
    inventory_ok = wire_calls == expected
    return {
        "ok": bool(inventory_ok and not callbacks),
        "inventory_ok": bool(inventory_ok),
        "observed": [list(c) for c in wire_calls],
        "expected": [list(c) for c in expected],
        "scalar_reductions": len(scalar_calls),
        "host_callbacks": callbacks,
        "wire": wire,
        "world": world,
        "vote_every": vote_every,
        "vote_buckets": vote_buckets,
        "dcn_pipeline_depth": dcn_pipeline_depth,
    }


def check_trainer(trainer, batch_example, *,
                  rng: Optional[Any] = None) -> dict:
    """The whole tier-2 contract against a live ``train.loop.Trainer``:
    collective inventory + host callbacks on the step core, donation on the
    jitted wrapper, param-upcast scan. One abstract trace + one lowering —
    startup cost, nothing per step."""
    cfg = trainer.cfg
    args = (trainer.params, trainer.state, trainer.vote_health,
            trainer._frozen_arg(), batch_example,
            rng if rng is not None else jax.random.key(0))
    report = check_step(
        trainer._train_step_core, args,
        n_params=trainer.n_params, world=trainer.world, wire=cfg.wire,
        vote_every=cfg.vote_every or 1, vote_buckets=cfg.vote_buckets or 1,
        dcn_pipeline_depth=cfg.dcn_pipeline_depth)
    report["donation"] = donation_report(trainer._train_step, *args)
    report["donation_ok"] = (report["donation"]["aliased_outputs"] > 0
                             or report["donation"]["buffer_donors"] > 0)
    report["param_upcasts"] = [list(s) for s in
                              param_upcasts(trainer._train_step_core, args)]
    report["upcast_ok"] = not report["param_upcasts"]
    report["ok"] = bool(report["ok"] and report["donation_ok"]
                        and report["upcast_ok"])
    return report
