"""graft-check CLI.

Tier 1 (default — pure stdlib, no accelerator needed)::

    python -m distributed_lion_tpu.analysis [paths...]

Lints the package (or the given files/dirs) with :mod:`analysis.lint`.
Exit 0 = clean, 1 = findings, 2 = usage error. (On a box without jax, run
``python distributed_lion_tpu/analysis/lint.py`` instead — same linter,
no package import.)

Tier 2 (jaxpr contract check — needs jax; honors ``DLION_PLATFORM``)::

    python -m distributed_lion_tpu.analysis --tier2 \
        [--json-out FILE] [--wires sign_psum,packed_a2a,...] \
        [--vote-buckets 1,4]

Builds the real train step (a small GPT-2 Trainer on a data mesh over all
local devices) for every wire × vote_buckets cell and asserts the
collective inventory matches the wire recipe, zero host callbacks,
donation applied, and no bf16-param f32 upcasts
(:func:`analysis.trace_check.check_trainer`). ``--json-out`` writes the
report the runbook's static stage captures for
``scripts/check_evidence.py static``.

Serve plane (jaxpr contracts on the SERVING dispatches — same tier-2
requirements)::

    python -m distributed_lion_tpu.analysis serve-check [--json-out FILE]

Builds a real ServingEngine for every cell of the serving config matrix
(tp × ep × ep_batch × quant × speculate) and walks the jaxprs/MLIR of the
actual registered dispatches (:mod:`analysis.serve_check`): collective
inventory, zero host callbacks in any dispatch, page-pool donation,
weight-upcast scan, and the compile-count budget after a mixed workload.
Exit codes match the lint: 0 = clean, 1 = findings. The report feeds
``scripts/check_evidence.py static_serve``.
"""

from __future__ import annotations

import argparse
import json
import sys


def _tier1(paths: list[str]) -> int:
    # one implementation of target resolution / printing / exit codes:
    # lint.main is also the `python .../lint.py` file-path entry point
    from distributed_lion_tpu.analysis import lint

    return lint.main(paths)


def _default_wires(world: int) -> list[str]:
    wires = ["sign_psum", "packed_allgather", "packed_a2a"]
    hier_g = next((g for g in (4, 2) if world % g == 0 and world > g), None)
    wires.append(f"hier:{hier_g}" if hier_g else f"hier:{world}")
    return wires


def _tier2(wires: list[str], buckets: list[int],
           json_out: str | None) -> int:
    from distributed_lion_tpu.parallel.mesh import force_cpu_platform

    force_cpu_platform()  # honor DLION_PLATFORM before first device use
    import jax
    import numpy as np

    from distributed_lion_tpu.analysis import trace_check
    from distributed_lion_tpu.models.gpt2 import GPT2Config
    from distributed_lion_tpu.parallel.mesh import make_mesh
    from distributed_lion_tpu.train.loop import TrainConfig, Trainer

    mesh = make_mesh()
    world = mesh.shape["data"]
    if not wires:
        wires = _default_wires(world)
    model_cfg = GPT2Config.tiny(vocab_size=512, n_layer=2, n_head=4,
                                d_model=128, n_ctx=64)
    reports = []
    for wire in wires:
        for vb in buckets:
            cfg = TrainConfig(
                lion=True, async_grad=True, wire=wire, vote_every=1,
                vote_buckets=vb, per_device_train_batch_size=1,
                gradient_accumulation_steps=1, block_size=32,
                output_dir=None)
            tr = Trainer.for_gpt2(cfg, mesh, model_cfg)
            batch = np.zeros((tr.global_train_batch(), cfg.block_size),
                             np.int32)
            rep = trace_check.check_trainer(tr, batch)
            tr.close()
            reports.append(rep)
            verdict = "ok" if rep["ok"] else "CONTRACT VIOLATION"
            print(f"graft-check tier2: wire={wire} vote_buckets={vb} "
                  f"world={world}: {verdict} "
                  f"(collectives {len(rep['observed'])}, scalar reductions "
                  f"{rep['scalar_reductions']}, callbacks "
                  f"{len(rep['host_callbacks'])}, aliased outputs "
                  f"{rep['donation']['aliased_outputs']})")
            if not rep["ok"]:
                print(f"  expected: {rep['expected']}")
                print(f"  observed: {rep['observed']}")
                if rep["host_callbacks"]:
                    print(f"  host callbacks: {rep['host_callbacks']}")
                if rep["param_upcasts"]:
                    print(f"  bf16 param upcasts: {rep['param_upcasts']}")
    ok = all(r["ok"] for r in reports)
    if json_out:
        out = {"ok": ok, "world": world, "jax": jax.__version__,
               "backend": jax.default_backend(), "configs": reports}
        with open(json_out, "w") as f:
            json.dump(out, f, indent=1, allow_nan=False)
            f.write("\n")
        print(f"graft-check tier2: report written to {json_out}")
    print(f"graft-check tier2: {'PASS' if ok else 'FAIL'} "
          f"({len(reports)} configs)")
    return 0 if ok else 1


def _serve_check(json_out: str | None) -> int:
    from distributed_lion_tpu.parallel.mesh import force_cpu_platform

    force_cpu_platform()  # honor DLION_PLATFORM before first device use
    from distributed_lion_tpu.analysis import serve_check

    return serve_check.main(json_out)


def main(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m distributed_lion_tpu.analysis",
        description="graft-check: JAX-aware static analysis "
                    "(tier 1 AST lint / tier 2 jaxpr contract)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the package), or "
                         "the literal 'serve-check' to run the serving-"
                         "plane jaxpr contract")
    ap.add_argument("--tier2", action="store_true",
                    help="run the jaxpr contract check instead of the lint")
    ap.add_argument("--wires", default="",
                    help="comma-separated wires for --tier2 "
                         "(default: all four for this device count)")
    ap.add_argument("--vote-buckets", default="1,4",
                    help="comma-separated bucket counts for --tier2")
    ap.add_argument("--json-out", default=None,
                    help="write the --tier2 report to this JSON file")
    args = ap.parse_args(argv)
    if args.paths and args.paths[0] == "serve-check":
        if args.tier2 or args.paths[1:]:
            ap.error("serve-check takes no paths and excludes --tier2")
        return _serve_check(args.json_out)
    if not args.tier2:
        return _tier1(args.paths)
    wires = [w for w in args.wires.split(",") if w]
    buckets = [int(b) for b in args.vote_buckets.split(",") if b]
    return _tier2(wires, buckets, args.json_out)


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
