"""graft-check: JAX-aware static analysis for the vote framework.

The vote IS the compiled program (ARCHITECTURE "The train step"), so the
most dangerous bugs here are the ones runtime telemetry only sees after a
chip run: a host sync slipped into the jitted step, a collective that
doesn't match the wire recipe, a typed PRNG key that silently fails to
serialize (the exact latent bug the resilience PR had to fix), an
unexpected retrace that doubles step time. This package verifies those
contracts BEFORE a single step runs, in two tiers:

- **Tier 1 — source lint** (:mod:`analysis.lint`): pure-stdlib ``ast``
  rules codifying pitfalls this repo has already paid for (host syncs and
  nondeterminism in traced scope, raw PRNG keys reaching serialization,
  hardcoded mesh-axis literals, swallowed exceptions, non-strict JSON,
  mutable defaults). No jax import — scripts (check_evidence, ci_static)
  load ``lint.py`` by file path and run it on boxes without an
  accelerator toolchain, like ``train/resilience.py``'s manifest readers.
- **Tier 2 — program contract check** (:mod:`analysis.trace_check`):
  walk the jaxpr of the ACTUAL compiled train step (one abstract trace
  per config, the ``telemetry.measure_step_wire`` pattern) and assert the
  collective-primitive inventory exactly matches the wire recipe's
  expected set — the static counterpart of the ``comm_drift_bytes``
  runtime metric — plus zero host callbacks, donation actually applied,
  and no f32 upcast of bf16 param leaves.

The runtime third leg — the retrace guard that hashes the step's abstract
signature at first dispatch — lives in ``train/loop.py``
(``--retrace_guard``); this package is everything that runs before
dispatch.

CLI (exit 0 = clean, 1 = findings, 2 = usage error)::

    python -m distributed_lion_tpu.analysis            # tier 1 over the package
    python -m distributed_lion_tpu.analysis --tier2    # jaxpr contract check

This ``__init__`` deliberately imports nothing heavy: tier 1 stays
importable everywhere, tier 2 is imported lazily by ``__main__``.
"""

from distributed_lion_tpu.analysis.lint import (  # noqa: F401
    Finding,
    RULES,
    lint_file,
    lint_paths,
    lint_source,
)
