"""graft-check tier 2 for the SERVING plane: jaxpr contracts on the
ACTUAL compiled serve dispatches.

The serving engine makes structural promises the benches only observe
indirectly (a slow tick, a surprise recompile, an HBM bump): one decode
program, O(log max) prefill buckets, collectives exactly where the
sharding says, no host round-trips inside a dispatch, page pools updated
in place. This module pins each of those STATICALLY, the same way
:mod:`analysis.trace_check` pins the trainer — build a real
:class:`~distributed_lion_tpu.serve.engine.ServingEngine` for every cell
of the serving config matrix (tp x ep x ep_batch x quant x speculate),
walk the jaxprs/lowered MLIR of the very callables the engine's ticks
dispatch (the ``engine._dispatches`` registry — not re-derived lookalike
programs), and assert per dispatch:

- **collective inventory** exactly matches the config-derived expectation
  (:func:`expected_serve_calls`): ``tp >= 1`` buys one row-parallel-exit
  psum per layer exit (attention out-proj + MLP/MoE out-proj — 2 per
  layer, operand ``[B, S, d_model]`` / the MoE dispatch buffer);
  ``ep > 1`` buys exactly TWO ``all_to_all`` hops per MoE block
  (``[E, cap, d_model]`` out and back); ``ep == 1`` buys ZERO fabric
  traffic (the ``ep > 1`` gate is static); the CoW page copy is
  collective-free on every mesh. Anything else fails naming the
  primitive, its axes/operand size, and the dispatch it appeared in.
- **zero host callbacks** in ANY dispatch — decode tick, every power-of-
  two prefill bucket, the speculative verify window, CoW.
- **donation survives lowering**: the page pool (2 buffers per layer)
  carries ``tf.aliasing_output`` / ``jax.buffer_donor`` in the lowered
  module. The engine turns ``donate_argnums`` off on the cpu backend, so
  the check re-jits the registered pre-jit body (``inner``) with
  donation forced — same program, donation provable on any backend.
- **no weight upcasts**: no ``convert_element_type`` takes a frozen
  bf16 / nf4-dequant weight matrix to f32. The ONLY legal large
  bf16->f32 converts in a serve dispatch are layer-norm's activation-
  stability upcasts, and those all have the activation shape
  ``[B_local, S, d_model]`` — any other large convert (in particular a
  weight-shaped one) fails. bf16 cells additionally run the positional
  param-leaf tracker (:func:`analysis.trace_check.param_upcasts`),
  filtered to matrix leaves (1-D ln/bias vectors upcast by design).
- **compile budget**: after a standard mixed workload (prompt lengths
  spanning every bucket + decode + speculative ticks), the engine's own
  jit caches (``engine.compile_counts()``) hold at most
  ``engine.compile_budget()`` distinct lowerings — ONE decode / verify /
  cow program, one prefill program per power-of-two page bucket. The
  runtime twin is ``ServeConfig.retrace_guard`` (``--serve_retrace_guard``).

Run it::

    python -m distributed_lion_tpu.analysis serve-check [--json-out F]
    python distributed_lion_tpu/analysis/serve_check.py   # file path, same

``runs/static/serve_check.json`` banks the report
(``scripts/validate_metrics.py`` schema, gated by
``scripts/check_evidence.py static_serve``).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from distributed_lion_tpu.analysis.trace_check import (
    SCALAR_MAX,
    collective_calls,
    donation_report,
    iter_eqns,
    param_upcasts,
)
from distributed_lion_tpu.parallel.mesh import EXPERT_AXIS, TENSOR_AXIS

# engine geometry shared by every matrix cell: page cap = 16 tokens ->
# prefill buckets {4, 8, 16} (three compiles), 4 decode slots, and the
# smallest collective operand (batch-sharded decode attention exit,
# [2, 1, 64]) still clears SCALAR_MAX so inventory and scalar-probe
# classes cannot collide.
MAX_SEQS = 4
BLOCK_SIZE = 4
MAX_BLOCKS_PER_SEQ = 4
NGRAM_K = 3

# the serving config matrix: every tp degree {0 (no mesh), 1 (1-mesh,
# bit-identical pin), 2}, ep {1 (zero-traffic pin), 2}, ep_batch on/off,
# both weight formats, speculation off/on (ngram arms the verify-window
# dispatch). MoE cells use the tiny MoE checkpoint (moe_every=2,
# n_layer=2 -> exactly one MoE block).
MATRIX: List[Dict[str, Any]] = [
    {"name": "dense_tp0_bf16", "moe": False},
    {"name": "dense_tp0_nf4", "moe": False, "quant": "nf4"},
    {"name": "dense_tp1_bf16", "moe": False, "tp": 1},
    {"name": "dense_tp2_bf16", "moe": False, "tp": 2},
    {"name": "dense_tp2_nf4", "moe": False, "tp": 2, "quant": "nf4"},
    {"name": "dense_tp0_ngram", "moe": False,
     "speculate": f"ngram:{NGRAM_K}"},
    {"name": "moe_ep1_bf16", "moe": True, "ep": 1},
    {"name": "moe_ep2_bf16", "moe": True, "ep": 2},
    {"name": "moe_ep2_batch_bf16", "moe": True, "ep": 2, "ep_batch": True},
    {"name": "moe_ep2_batch_tp2_bf16", "moe": True, "ep": 2,
     "ep_batch": True, "tp": 2},
    {"name": "moe_ep2_nf4", "moe": True, "ep": 2, "quant": "nf4"},
    {"name": "moe_ep2_ngram", "moe": True, "ep": 2,
     "speculate": f"ngram:{NGRAM_K}"},
]

# cells that also run the REAL mixed workload for the compile-count
# budget (mesh-free: the budget law is geometry, not sharding — the
# jit caches count lowerings identically under shard_map)
COMPILE_CELLS = ("dense_tp0_bf16", "dense_tp0_ngram")


def _model_cfg(moe: bool):
    from distributed_lion_tpu.models.gpt2 import GPT2Config

    # bf16 params so the upcast leg has teeth; vocab/n_ctx trimmed to
    # keep 12 cells' worth of abstract traces cheap
    return GPT2Config.tiny(vocab_size=128, n_ctx=64,
                           param_dtype=jnp.bfloat16,
                           moe_experts=4 if moe else 0)


def build_engine(cell: Dict[str, Any]):
    """A live engine for one matrix cell — the SAME constructor path the
    server uses, so the registry holds the real dispatch callables."""
    from distributed_lion_tpu.models.gpt2 import gpt2_init
    from distributed_lion_tpu.serve.engine import (
        ServeConfig,
        ServeModel,
        ServingEngine,
    )

    cfg = _model_cfg(cell.get("moe", False))
    params = gpt2_init(jax.random.key(0), cfg)
    kw = {k: v for k, v in cell.items() if k not in ("name", "moe")}
    if kw.get("quant") == "nf4":
        kw.setdefault("quant_block", 16)  # d_model=64 must shard under tp
    scfg = ServeConfig(max_seqs=MAX_SEQS, block_size=BLOCK_SIZE,
                       max_blocks_per_seq=MAX_BLOCKS_PER_SEQ, **kw)
    return ServingEngine(ServeModel.for_gpt2(params, cfg), scfg), scfg


# ----------------------------------------------------- expected inventory
def expected_serve_calls(model_cfg, scfg, kind: str,
                         window: Optional[int] = None) -> List[tuple]:
    """The config-derived collective inventory for ONE serve dispatch, as
    a sorted ``(prim, axes, nelems)`` list — same key as
    ``trace_check.CollectiveCall`` and derived from the same single
    sources of truth the engine shards by (``models.gpt2.is_moe_block``
    for block placement, the Megatron row-parallel exits for psum count,
    ``moe_ffn``'s no-drop ``capacity_override = B*S`` for operand sizes).

    ``kind``: ``decode`` | ``prefill`` | ``verify`` | ``cow``;
    ``window`` is the padded token width (a prefill bucket, or the
    speculative ``k+1``) for the windowed kinds.
    """
    from distributed_lion_tpu.models.gpt2 import is_moe_block

    if kind == "cow":
        return []  # page copies are shard-local on every mesh
    groups = scfg.ep if (scfg.ep_batch and scfg.ep) else 1
    if kind == "decode":
        b_local, s = scfg.max_seqs // groups, 1
    elif kind == "prefill":
        # batch-1 window; under ep_batch the tokens are REPLICATED and
        # only table/length operands shard, so every shard traces B=1
        b_local, s = 1, int(window)
    elif kind == "verify":
        b_local, s = scfg.max_seqs // groups, int(window)
    else:
        raise ValueError(f"unknown dispatch kind {kind!r}")
    d = model_cfg.d_model
    e = model_cfg.moe_experts
    cap = b_local * s  # moe_ffn's no-drop capacity_override
    out: List[tuple] = []
    for i in range(model_cfg.n_layer):
        moe = is_moe_block(model_cfg, i)
        if scfg.tp >= 1:
            # attention out-proj exit (one per layer) ...
            out.append(("psum", (TENSOR_AXIS,), b_local * s * d))
            # ... and the FFN exit: dense MLP psums the activation, the
            # MoE expert FFN psums the [E, cap, D] dispatch buffer
            out.append(("psum", (TENSOR_AXIS,),
                        e * cap * d if moe else b_local * s * d))
        if moe and scfg.ep > 1:
            # expert dispatch out + combine back — exactly two hops
            out.append(("all_to_all", (EXPERT_AXIS,), e * cap * d))
            out.append(("all_to_all", (EXPERT_AXIS,), e * cap * d))
    return sorted(k for k in out if k[2] > SCALAR_MAX)


# ------------------------------------------------------- example operands
def _example_rest(eng, kind: str, window: Optional[int] = None) -> tuple:
    """Abstract-trace operands for one dispatch, shape/dtype-identical to
    what the engine's tick builds (engine.py `_decode` /
    `_dispatch_prefill` / `_flush_cow`, speculate.py `decode_tick`)."""
    cfg = eng.cfg
    s_, w_ = cfg.max_seqs, cfg.max_blocks_per_seq
    i32, u32 = jnp.int32, jnp.uint32
    if kind == "decode":
        return (jnp.zeros((s_, w_), i32), jnp.zeros((s_,), i32),
                jnp.zeros((s_,), i32), jnp.zeros((s_,), bool),
                jnp.zeros((s_,), u32), jnp.zeros((s_,), i32))
    if kind == "prefill":
        toks = jnp.zeros((1, int(window)), i32)
        if eng._ep_batch:
            g = eng.tables.groups
            return (jnp.zeros((g, w_), i32), toks, jnp.zeros((g,), i32),
                    jnp.zeros((g,), i32), u32(0), i32(0))
        return (jnp.zeros((1, w_), i32), toks, jnp.zeros((1,), i32),
                i32(0), u32(0), i32(0))
    if kind == "verify":
        return (jnp.zeros((s_, w_), i32), jnp.zeros((s_,), i32),
                jnp.zeros((s_, int(window)), i32), jnp.zeros((s_,), i32),
                jnp.zeros((s_,), u32), jnp.zeros((s_,), i32))
    if kind == "cow":
        shape = ((eng.tables.groups, eng.tables.slots_per_group)
                 if eng._ep_batch else (s_,))
        return (jnp.zeros(shape, i32), jnp.zeros(shape, i32))
    raise ValueError(f"unknown dispatch kind {kind!r}")


def _dispatch_args(eng, kind: str, window: Optional[int] = None) -> tuple:
    rest = _example_rest(eng, kind, window)
    if kind == "cow":
        return (eng.pages,) + rest
    return (eng.params, eng.pages) + rest


def _prefill_buckets(scfg) -> List[int]:
    from distributed_lion_tpu.serve.kv_cache import bucket_tokens

    cap = scfg.block_size * scfg.max_blocks_per_seq
    return sorted({bucket_tokens(n, scfg.block_size,
                                 scfg.max_blocks_per_seq)
                   for n in range(1, cap + 1)})


# ------------------------------------------------------------ the checks
def _upcast_scan(jaxpr, allowed_shape: tuple) -> List[dict]:
    """Every large ``convert_element_type -> f32`` whose operand is NOT
    the layer-norm activation shape — a weight-shaped convert means a
    frozen bf16 / nf4-dequant matrix is being read at double width."""
    bad: List[dict] = []
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name != "convert_element_type":
            continue
        if eqn.params.get("new_dtype") != jnp.float32:
            continue
        aval = getattr(eqn.invars[0], "aval", None)
        if aval is None or not hasattr(aval, "shape"):
            continue
        nelems = 1
        for dim in aval.shape:
            nelems *= int(dim)
        if nelems <= SCALAR_MAX:
            continue
        if tuple(aval.shape) == tuple(allowed_shape):
            continue  # layer-norm stability upcast — by design
        bad.append({"shape": list(aval.shape), "dtype": str(aval.dtype),
                    "nelems": nelems})
    return bad


def check_dispatch(eng, model_cfg, scfg, kind: str,
                   window: Optional[int] = None) -> dict:
    """The whole per-dispatch contract: inventory + callbacks + donation
    + upcasts, against the REGISTERED callable (``engine._dispatches``)."""
    reg = eng._dispatches[kind.split(":")[0] if ":" in kind else kind]
    args = _dispatch_args(eng, kind, window)
    calls, callbacks = collective_calls(reg["jitted"], *args)
    observed = sorted(c.key for c in calls if c.nelems > SCALAR_MAX)
    scalar = [c for c in calls if c.nelems <= SCALAR_MAX]
    expected = expected_serve_calls(model_cfg, scfg, kind, window)
    obs_count: Dict[tuple, int] = {}
    for k in observed:
        obs_count[k] = obs_count.get(k, 0) + 1
    exp_count: Dict[tuple, int] = {}
    for k in expected:
        exp_count[k] = exp_count.get(k, 0) + 1
    unexpected = [list(k) for k in observed
                  if obs_count[k] > exp_count.get(k, 0)]
    missing = [list(k) for k in expected
               if exp_count[k] > obs_count.get(k, 0)]
    inventory_ok = observed == expected

    # donation: the engine disables donate_argnums on cpu (buffers are
    # host RAM), so prove it on the SAME program by re-jitting the
    # registered pre-jit body with donation forced. 2 pool buffers per
    # layer (k + v) must survive as aliases/donors.
    donate = (0,) if kind == "cow" else (1,)
    probe = jax.jit(reg["inner"], donate_argnums=donate)
    don = donation_report(probe, *args)
    need = 2 * model_cfg.n_layer
    donation_ok = (don["aliased_outputs"] + don["buffer_donors"]) >= need

    # upcasts: weight-shaped bf16->f32 converts (all cells) ...
    groups = scfg.ep if (scfg.ep_batch and scfg.ep) else 1
    if kind == "decode":
        act_shape = (scfg.max_seqs // groups, 1, model_cfg.d_model)
    elif kind == "prefill":
        act_shape = (1, int(window), model_cfg.d_model)
    elif kind == "verify":
        act_shape = (scfg.max_seqs // groups, int(window),
                     model_cfg.d_model)
    else:
        act_shape = ()
    jaxpr = jax.make_jaxpr(reg["jitted"])(*args)
    weight_upcasts = _upcast_scan(jaxpr, act_shape)
    # ... plus the positional bf16-param tracker on unquantized cells
    # (1-D ln/bias vectors upcast for stability by design — only matrix
    # leaves count)
    leaf_upcasts: List[list] = []
    if scfg.quant == "none" and kind != "cow":
        leaf_upcasts = [list(s) for s in
                        param_upcasts(reg["jitted"], args, param_argnum=0)
                        if len(s) >= 2]
    upcast_ok = not weight_upcasts and not leaf_upcasts

    ok = bool(inventory_ok and not callbacks and donation_ok and upcast_ok)
    return {
        "ok": ok,
        "inventory_ok": bool(inventory_ok),
        "observed": [list(k) for k in observed],
        "expected": [list(k) for k in expected],
        "unexpected": unexpected,
        "missing": missing,
        "scalar_reductions": len(scalar),
        "host_callbacks": list(callbacks),
        "donation": don,
        "donation_ok": bool(donation_ok),
        "weight_upcasts": weight_upcasts,
        "param_upcasts": leaf_upcasts,
        "upcast_ok": bool(upcast_ok),
    }


def check_cell(cell: Dict[str, Any]) -> dict:
    """Every dispatch of one matrix cell's engine: the decode tick, EVERY
    power-of-two prefill bucket, the verify window when armed, CoW."""
    eng, scfg = build_engine(cell)
    model_cfg = _model_cfg(cell.get("moe", False))
    dispatches: Dict[str, dict] = {}
    dispatches["decode"] = check_dispatch(eng, model_cfg, scfg, "decode")
    for bucket in _prefill_buckets(scfg):
        rep = check_dispatch(eng, model_cfg, scfg, "prefill", bucket)
        dispatches[f"prefill:{bucket}"] = rep
    if scfg.speculate:
        dispatches["verify"] = check_dispatch(eng, model_cfg, scfg,
                                              "verify", NGRAM_K + 1)
    dispatches["cow"] = check_dispatch(eng, model_cfg, scfg, "cow")
    report = {
        "cell": cell["name"],
        "tp": scfg.tp, "ep": scfg.ep, "ep_batch": bool(scfg.ep_batch),
        "quant": scfg.quant, "speculate": scfg.speculate,
        "ok": all(d["ok"] for d in dispatches.values()),
        "dispatches": dispatches,
    }
    if scfg.ep_batch:
        # the batch-sharded cells additionally pin the REGISTERED specs:
        # tables shard their slot-leading dim over the expert axis
        from jax.sharding import PartitionSpec as P

        specs = eng._dispatches["decode"]["rest_specs"]
        spec_ok = (specs is not None
                   and specs[0] == P(EXPERT_AXIS, None)
                   and all(sp == P(EXPERT_AXIS) for sp in specs[1:]))
        report["ep_batch_specs_ok"] = bool(spec_ok)
        report["ok"] = bool(report["ok"] and spec_ok)
    return report


# ------------------------------------------------------- compile budget
def _mixed_workload(vocab: int) -> list:
    """Prompt lengths spanning every page bucket (1->4, 3->4, 7->8,
    14->16) plus decode ticks — the standard workload the compile-count
    budget is measured against."""
    from distributed_lion_tpu.serve.engine import Request

    return [Request(req_id=i, tokens=[1 + (i + j) % (vocab - 1)
                                      for j in range(n)],
                    max_new_tokens=4, seed=i)
            for i, n in enumerate((1, 3, 7, 14))]


def check_compile_budget(cell: Dict[str, Any]) -> dict:
    """Run the real mixed workload on one cell's engine and pin the live
    jit-cache sizes against ``engine.compile_budget()`` — the O(log max)
    prefill / ONE decode program claim, measured from jax's own caches."""
    eng, scfg = build_engine(cell)
    model_cfg = _model_cfg(cell.get("moe", False))
    eng.run(_mixed_workload(model_cfg.vocab_size))
    counts = eng.compile_counts()
    budget = eng.compile_budget()
    over = {k: [v, budget.get(k, 0)] for k, v in counts.items()
            if v > budget.get(k, 0)}
    ok = not over and counts.get("prefill", 0) > 0
    return {"cell": cell["name"], "ok": bool(ok), "counts": counts,
            "budget": budget, "over_budget": over}


# --------------------------------------------------------------- driver
def run_matrix(cells: Optional[List[Dict[str, Any]]] = None,
               verbose: bool = True) -> dict:
    cells = MATRIX if cells is None else cells
    need = max(cell.get("ep", 0) * max(cell.get("tp", 0), 1) or
               max(cell.get("tp", 0), 1) for cell in cells)
    world = jax.local_device_count()
    if world < need:
        raise RuntimeError(
            f"serve-check needs {need} devices for the full matrix, "
            f"found {world} — run under DLION_PLATFORM=cpu8 (or a pod)")
    reports = [check_cell(cell) for cell in cells]
    compiles = [check_compile_budget(cell) for cell in cells
                if cell["name"] in COMPILE_CELLS]
    ok = all(r["ok"] for r in reports) and all(c["ok"] for c in compiles)
    if verbose:
        for r in reports:
            verdict = "ok" if r["ok"] else "CONTRACT VIOLATION"
            n_coll = sum(len(d["observed"])
                         for d in r["dispatches"].values())
            print(f"graft-check serve: {r['cell']}: {verdict} "
                  f"({len(r['dispatches'])} dispatches, "
                  f"{n_coll} collectives)")
            for dname, d in r["dispatches"].items():
                if d["ok"]:
                    continue
                if d["unexpected"]:
                    print(f"  {dname}: UNEXPECTED collectives "
                          f"{d['unexpected']}")
                if d["missing"]:
                    print(f"  {dname}: MISSING collectives "
                          f"{d['missing']}")
                if d["host_callbacks"]:
                    print(f"  {dname}: host callbacks "
                          f"{d['host_callbacks']}")
                if not d["donation_ok"]:
                    print(f"  {dname}: donation lost: {d['donation']}")
                if not d["upcast_ok"]:
                    print(f"  {dname}: weight upcasts "
                          f"{d['weight_upcasts'] or d['param_upcasts']}")
        for c in compiles:
            verdict = "ok" if c["ok"] else "OVER BUDGET"
            print(f"graft-check serve: compile[{c['cell']}]: {verdict} "
                  f"counts={c['counts']} budget={c['budget']}")
    return {
        "format": "dlt-serve-check-v1",
        "ok": bool(ok),
        "world": world,
        "backend": jax.default_backend(),
        "jax": jax.__version__,
        "cells": reports,
        "compile": compiles,
    }


def main(json_out: Optional[str] = None) -> int:
    report = run_matrix()
    if json_out:
        with open(json_out, "w") as f:
            json.dump(report, f, indent=1, allow_nan=False)
            f.write("\n")
        print(f"graft-check serve: report written to {json_out}")
    n = len(report["cells"])
    print(f"graft-check serve: {'PASS' if report['ok'] else 'FAIL'} "
          f"({n} cells)")
    return 0 if report["ok"] else 1


if __name__ == "__main__":  # file-path entry point, like lint.py
    import os
    import sys

    _root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    if _root not in sys.path:
        sys.path.insert(0, _root)
    from distributed_lion_tpu.parallel.mesh import force_cpu_platform

    force_cpu_platform()
    json_arg = None
    argv = sys.argv[1:]
    if "--json-out" in argv:
        json_arg = argv[argv.index("--json-out") + 1]
    sys.exit(main(json_arg))
