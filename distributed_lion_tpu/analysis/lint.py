"""graft-check tier 1: pure-stdlib AST lint for JAX/TPU pitfalls.

Every rule codifies a failure mode this repo has already paid for (or
refused to pay for twice). The linter is deliberately structural, not a
dataflow engine: *traced scope* is what it can prove syntactically — a
function decorated with (or wrapped in) ``jax.jit`` / ``jax.shard_map`` /
``partial(jax.shard_map, ...)``, a function passed by name to a tracing
higher-order function (``lax.scan``, ``jax.vmap``, ``jax.grad``, ...), or
any function nested inside one. Helpers *called from* traced scope across
module boundaries are the jaxpr tier's job (:mod:`analysis.trace_check`
sees the whole compiled program); this tier catches the mistake at the
line where it is written.

Rules (the table ARCHITECTURE.md "Static analysis" renders):

==========  ================================================================
DLT001      host-sync call in traced scope: ``float()``/``int()``/``bool()``
            on a traced value, ``.item()``/``.tolist()``/
            ``.block_until_ready()``, ``np.asarray``/``np.array``,
            ``jax.device_get`` — each forces a device→host transfer and a
            pipeline stall inside the compiled step (or a tracer error at
            run time, which is the lucky case).
DLT002      nondeterminism in traced scope: ``time.time()``, ``random.*``,
            ``np.random.*``, ``datetime.now()``, ``os.urandom``, ``uuid.*``
            — traced once, the "random" value is baked into the compiled
            program as a constant and silently identical every step.
DLT003      host callback in traced scope: ``print``, ``jax.debug.print``/
            ``jax.debug.callback``, ``pure_callback``, ``io_callback`` —
            the compiled-step contract here is ZERO host callbacks (the
            jaxpr tier asserts it on the real program; this rule names the
            offending line).
DLT004      raw PRNG key reaching serialization: a ``save``-like call whose
            payload mentions an ``rng`` leaf in a function with no
            ``key_data``/``pack_state_rng`` shim. Typed PRNG keys are not
            serializable — stochastic-mode checkpoints simply FAILED to
            save until the resilience PR added the pack/unpack shim
            (train/loop._pack_state_rng); this rule pins the lesson.
DLT005      hardcoded mesh-axis-name string literal (``"data"`` /
            ``"tensor"`` / ``"seq"`` / ``"pipe"`` / ``"expert"``) used as a
            call argument or parameter default outside ``parallel/mesh.py``
            — the axis-name constants exist so a mesh rename is one edit,
            not a grep-and-pray.
DLT006      swallowed exception: a broad ``except Exception:`` (or bare
            ``except:``) whose body neither raises, calls, nor assigns —
            the failure vanishes. Finalizers (``__del__``) are exempt (they
            must not raise). Committer-thread and save-I/O paths are where
            this has actually bitten (train/checkpoint.py).
DLT007      non-strict ``json.dump``/``dumps``: without ``allow_nan=False``
            a single NaN emits the bare token ``NaN`` — not JSON — and
            corrupts the line for every strict consumer (the MetricsLogger
            bug validate_metrics.py now guards).
DLT008      mutable default argument (``def f(x, acc=[])``): the default is
            created once and shared across calls — a classic aliasing bug,
            and in config dataclass helpers a cross-run state leak.
DLT009      bare ``print()`` in a ``train/`` or ``data/`` module outside
            the journal emitter (``train/journal.py``): console output
            there must go through ``journal.emit`` — mirrored to stdout
            exactly as before AND recorded in the run journal — so the
            control plane gets one consumable event stream instead of 27
            scattered prints (the ISSUE-7 migration this rule pins).
            Traced-scope prints stay DLT003's finding.
DLT010      device-array construction (``jnp.*`` / ``jax.device_put``)
            inside a host-side ``for``/``while`` loop in a ``serve/``
            module: the serving engine's tick contract is "build numpy
            inside per-slot loops, convert ONCE at the dispatch boundary"
            — a ``jnp.asarray`` per loop iteration is a hidden H2D
            transfer per slot per tick. Statement loops only: a
            comprehension-built device allocation (``init_pages``) is the
            one-shot construction idiom, and the per-request prefill
            dispatch lives in its own helper method so the admission
            loop's body stays numpy (the structural boundary this linter
            documents for every rule: cross-function flows are tier 2's
            job). Traced-scope ``jnp.*`` is ordinary jax code — the rule
            fires at host level only.
DLT011      direct wall-clock read (``time.time``/``time.monotonic``/
            ``time.perf_counter`` and their ``_ns`` twins) in a
            ``serve/`` module outside the injectable ``time_fn`` seam
            (serve/metrics.ServeMetrics introduced it; the engine and
            fleet carry it too): a hardwired clock makes deadline/SLO/
            latency behavior untestable — tests would need real sleeps.
            Referencing ``time.monotonic`` as a default (``time_fn=
            time.monotonic``) is the seam itself and stays legal (the
            rule matches CALLS). ``time.sleep`` is not a clock read.
DLT012      blocking socket/pipe read (``.recv``/``.recv_into``/
            ``.recvfrom``/``.accept``/``.connect`` method calls, or
            ``os.read``) in a ``serve/`` module with no deadline seam in
            the enclosing function: an unbounded block in the serving
            plane's host loop wedges EVERY request behind one dead peer
            (the process-isolated fleet's heartbeat verdicts depend on
            reads that return). The seam is structural, same tier as
            DLT004's shim check: the enclosing function must mention a
            timeout/deadline mechanism — ``settimeout``/``setblocking``,
            a ``select``/``poll`` wait, a ``deadline``/``timeout``
            variable, or the ``BlockingIOError`` non-blocking idiom.
            Host level only; fires on method-shaped calls (a bare
            ``read()`` name is not a pipe read).
==========  ================================================================

Suppression syntax (both forms take a comma-separated rule list):

- line:  ``some_call()  # graft: disable=DLT004`` — suppresses on that line
  (use sparingly, with a justification in the surrounding comment);
- file:  a comment line ``# graft: disable-file=DLT005`` anywhere in the
  file suppresses the rule for the whole file.

This module imports ONLY the stdlib and has no package-relative imports,
so dependency-light scripts (scripts/check_evidence.py, scripts/
ci_static.sh) load it by file path and run it without jax installed. It is
also directly runnable: ``python distributed_lion_tpu/analysis/lint.py
[paths...]``.
"""

from __future__ import annotations

import ast
import io
import pathlib
import re
import sys
import tokenize
from typing import Iterable, Optional

MESH_AXES = ("data", "tensor", "seq", "pipe", "expert")
MESH_MODULE_SUFFIX = "parallel/mesh.py"
# DLT009 scope: modules under these directory segments must route console
# output through the run-journal emitter (train/journal.emit — mirrored to
# stdout AND recorded as a journal event), so the control plane consumes
# ONE event stream instead of scraping scattered prints. The emitter
# module itself is the one place a real print belongs.
JOURNAL_DIR_SEGMENTS = ("train", "data")
JOURNAL_MODULE_SUFFIX = "train/journal.py"
# DLT010/DLT011 scope: the serving plane's host loop hygiene — modules
# under a serve/ directory run the tick loops whose contracts ("one H2D
# conversion set per dispatch", "injectable clocks") these rules pin.
SERVE_DIR_SEGMENTS = ("serve",)
# DLT011: clock-reading time.* calls (time.sleep is not a clock read;
# referencing time.monotonic as a default parameter is the seam, not a
# call, and never matches)
CLOCK_CALLS = ("time.time", "time.monotonic", "time.perf_counter",
               "time.time_ns", "time.monotonic_ns", "time.perf_counter_ns")
# DLT012: socket/pipe primitives that block unboundedly by default...
BLOCKING_IO_ATTRS = ("recv", "recv_into", "recvfrom", "accept", "connect")
# ...unless the enclosing function visibly bounds them: an explicit
# socket timeout, a select/poll wait, a deadline/timeout variable it
# computes against, or the non-blocking BlockingIOError idiom (substring
# match over the function's identifiers, the DLT004 shim-check tier)
BLOCKING_IO_SEAMS = ("settimeout", "setblocking", "select", "poll",
                     "deadline", "timeout", "BlockingIOError")

# function/decorator names that put their function argument under a jax
# trace; terminal-name match so jax.jit / lax.scan / plain jit all hit
TRACE_WRAPPERS = frozenset({
    "jit", "shard_map", "pmap", "vmap", "grad", "value_and_grad",
    "checkpoint", "remat", "custom_jvp", "custom_vjp",
})
TRACE_HOFS = TRACE_WRAPPERS | frozenset({
    "scan", "cond", "while_loop", "fori_loop", "switch", "associative_scan",
    "make_jaxpr", "eval_shape",
})

RULES = {
    "DLT001": "host-sync call inside traced scope",
    "DLT002": "nondeterministic host call inside traced scope",
    "DLT003": "host callback inside traced scope",
    "DLT004": "raw PRNG key reaching serialization without a pack shim",
    "DLT005": "hardcoded mesh-axis-name string literal outside parallel/mesh",
    "DLT006": "swallowed exception (broad except with an inert body)",
    "DLT007": "json.dump/dumps without allow_nan=False",
    "DLT008": "mutable default argument",
    "DLT009": "bare print in train//data/ outside the journal emitter",
    "DLT010": "device-array construction inside a host-side serve/ loop",
    "DLT011": "direct wall-clock read in serve/ outside the time_fn seam",
    "DLT012": "blocking socket/pipe read in serve/ without a deadline seam",
}

_DISABLE_LINE = re.compile(r"#\s*graft:\s*disable=([A-Z0-9,\s]+)")
_DISABLE_FILE = re.compile(r"#\s*graft:\s*disable-file=([A-Z0-9,\s]+)")


class Finding:
    """One lint finding. A plain class (not a dataclass/NamedTuple) on
    purpose: this module is loaded by FILE PATH from jax-less scripts, and
    the annotation-resolving class machineries require a sys.modules entry
    that path-loading doesn't guarantee."""

    __slots__ = ("rule", "path", "line", "col", "message")

    def __init__(self, rule, path, line, col, message):
        self.rule = rule
        self.path = path
        self.line = line
        self.col = col
        self.message = message

    def __repr__(self) -> str:
        return str(self)

    def __eq__(self, other) -> bool:
        return isinstance(other, Finding) and str(self) == str(other)

    def __str__(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


# --------------------------------------------------------------------- helpers
def _terminal_name(node: ast.AST) -> Optional[str]:
    """The rightmost identifier of a Name/Attribute chain (jax.lax.scan →
    'scan'), or None for anything else."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted path of a Name/Attribute chain ('jax.debug.print');
    non-name links render as '?'."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    parts.append(node.id if isinstance(node, ast.Name) else "?")
    return ".".join(reversed(parts))


def _mentions_name(tree: ast.AST, names: Iterable[str]) -> bool:
    needles = tuple(names)
    for node in ast.walk(tree):
        ident = None
        if isinstance(node, ast.Name):
            ident = node.id
        elif isinstance(node, ast.Attribute):
            ident = node.attr
        if ident and any(n in ident for n in needles):
            return True
    return False


def _is_traced_decorator(dec: ast.AST) -> bool:
    """True when a decorator expression mentions a trace wrapper anywhere —
    covers @jax.jit, @jit, @partial(jax.shard_map, mesh=...), nested
    partials, and jax.jit(f, donate_argnums=...) used as a decorator."""
    for node in ast.walk(dec):
        if _terminal_name(node) in TRACE_WRAPPERS:
            return True
    return False


_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


class _Suppressions:
    def __init__(self, src: str):
        self.by_line: dict[int, set] = {}
        self.file_wide: set = set()
        # only COMMENT tokens count: regex over raw source lines would also
        # match suppression syntax quoted inside strings/docstrings (e.g. a
        # module documenting the syntax would silently disable rules on
        # itself — this very docstring included)
        try:
            tokens = list(tokenize.generate_tokens(io.StringIO(src).readline))
        except (tokenize.TokenError, SyntaxError, IndentationError):
            return  # unparseable source: DLT000 already reports it
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _DISABLE_FILE.search(tok.string)
            if m:
                self.file_wide |= {r.strip() for r in m.group(1).split(",")}
                continue
            m = _DISABLE_LINE.search(tok.string)
            if m:
                self.by_line[tok.start[0]] = {
                    r.strip() for r in m.group(1).split(",")}

    def active(self, rule: str, line: int) -> bool:
        return rule in self.file_wide or rule in self.by_line.get(line, set())


# ----------------------------------------------------------------- the linter
class _Linter(ast.NodeVisitor):
    def __init__(self, tree: ast.Module, path: str, src: str):
        self.path = path
        self.findings: list[Finding] = []
        self.suppress = _Suppressions(src)
        norm = path.replace("\\", "/")
        self.in_mesh_module = norm.endswith(MESH_MODULE_SUFFIX)
        # DLT009 applies to modules living under a train/ or data/
        # directory, except the emitter module itself
        self.in_journal_scope = (
            not norm.endswith(JOURNAL_MODULE_SUFFIX)
            and any(f"/{seg}/" in norm or norm.startswith(f"{seg}/")
                    for seg in JOURNAL_DIR_SEGMENTS))
        # DLT010/DLT011 apply to modules living under a serve/ directory
        self.in_serve_scope = any(
            f"/{seg}/" in norm or norm.startswith(f"{seg}/")
            for seg in SERVE_DIR_SEGMENTS)
        self._func_stack: list[ast.AST] = []
        self._traced_depth = 0
        # statement-loop depth within the CURRENT function frame (DLT010);
        # reset at function boundaries — a def's body is a fresh frame
        # structurally, even when the def sits inside a loop
        self._host_loop_depth = 0
        # pre-pass: names passed as function args to tracing HOFs anywhere in
        # the module mark those functions traced (lax.scan(body, ...),
        # jax.jit(step), shard_map(f, mesh=...)); lambdas in that position
        # are marked by node identity
        self._hof_traced_names: set = set()
        self._hof_traced_nodes: set = set()
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if _terminal_name(node.func) not in TRACE_HOFS:
                continue
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(arg, ast.Name):
                    self._hof_traced_names.add(arg.id)
                elif isinstance(arg, ast.Lambda):
                    self._hof_traced_nodes.add(id(arg))

    def emit(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 0)
        if self.suppress.active(rule, line):
            return
        self.findings.append(Finding(rule, self.path, line,
                                     getattr(node, "col_offset", 0), message))

    # ------------------------------------------------------- scope tracking
    def _function_is_traced(self, node) -> bool:
        if self._traced_depth:  # nested inside a traced function
            return True
        if isinstance(node, _FUNC_NODES):
            if any(_is_traced_decorator(d) for d in node.decorator_list):
                return True
            if node.name in self._hof_traced_names:
                return True
        if id(node) in self._hof_traced_nodes:
            return True
        return False

    def _visit_function(self, node) -> None:
        if isinstance(node, _FUNC_NODES):
            self._check_mutable_defaults(node)
            self._check_axis_literal_defaults(node)
        traced = self._function_is_traced(node)
        self._func_stack.append(node)
        self._traced_depth += traced
        outer_loops, self._host_loop_depth = self._host_loop_depth, 0
        self.generic_visit(node)
        self._host_loop_depth = outer_loops
        self._traced_depth -= traced
        self._func_stack.pop()

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function
    visit_Lambda = _visit_function

    def _visit_loop(self, node) -> None:
        self._host_loop_depth += 1
        self.generic_visit(node)
        self._host_loop_depth -= 1

    visit_For = _visit_loop
    visit_While = _visit_loop

    # ------------------------------------------------------------ rule bodies
    def _check_mutable_defaults(self, node) -> None:
        defaults = list(node.args.defaults) + [
            d for d in node.args.kw_defaults if d is not None]
        for d in defaults:
            mutable = isinstance(d, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                                     ast.DictComp, ast.SetComp)) or (
                isinstance(d, ast.Call) and isinstance(d.func, ast.Name)
                and d.func.id in ("list", "dict", "set", "bytearray"))
            if mutable:
                self.emit("DLT008", d,
                          f"mutable default in {node.name}() is created once "
                          "and shared across calls; default to None and "
                          "build inside the body")

    def _check_axis_literal_defaults(self, node) -> None:
        if self.in_mesh_module:
            return
        for d in list(node.args.defaults) + [x for x in node.args.kw_defaults
                                             if x is not None]:
            if isinstance(d, ast.Constant) and d.value in MESH_AXES:
                self.emit("DLT005", d,
                          f"axis name {d.value!r} hardcoded as a parameter "
                          "default; use the parallel.mesh axis constants")

    def visit_Call(self, node: ast.Call) -> None:
        if self._traced_depth:
            self._check_traced_call(node)
        elif (self.in_journal_scope and isinstance(node.func, ast.Name)
                and node.func.id == "print"):
            # host-side print in train//data/: DLT009 (a print inside
            # traced scope is DLT003's — stronger — finding instead)
            self.emit("DLT009", node,
                      "bare print() in a train//data/ module bypasses the "
                      "run journal; route it through train/journal.emit "
                      "(same stdout mirror, plus a journal event)")
        if not self._traced_depth and self.in_serve_scope:
            self._check_serve_host_call(node)
        self._check_prng_serialization(node)
        self._check_json_dump(node)
        if not self.in_mesh_module:
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(arg, ast.Constant) and arg.value in MESH_AXES:
                    self.emit("DLT005", arg,
                              f"axis name {arg.value!r} hardcoded in a call "
                              "argument; use the parallel.mesh axis "
                              "constants")
        self.generic_visit(node)

    def _check_traced_call(self, node: ast.Call) -> None:
        func = node.func
        name = _terminal_name(func)
        dotted = _dotted(func) if name else ""
        # DLT001 — host syncs
        if (isinstance(func, ast.Name) and func.id in ("float", "int", "bool")
                and node.args
                and not all(isinstance(a, ast.Constant) for a in node.args)):
            self.emit("DLT001", node,
                      f"{func.id}() on a traced value forces a host sync "
                      "(or a tracer error) inside the compiled step")
        elif isinstance(func, ast.Attribute) and func.attr in (
                "item", "tolist", "block_until_ready"):
            self.emit("DLT001", node,
                      f".{func.attr}() inside traced scope forces a "
                      "device→host transfer")
        elif dotted in ("np.asarray", "np.array", "numpy.asarray",
                        "numpy.array", "jax.device_get"):
            self.emit("DLT001", node,
                      f"{dotted}() materializes a traced value on the host")
        # DLT002 — nondeterminism baked in at trace time
        elif dotted in ("time.time", "time.monotonic", "time.perf_counter",
                        "time.time_ns", "os.urandom", "uuid.uuid4",
                        "uuid.uuid1"):
            self.emit("DLT002", node,
                      f"{dotted}() is evaluated ONCE at trace time and baked "
                      "into the compiled step as a constant")
        elif (isinstance(func, ast.Attribute)
              and isinstance(func.value, ast.Name)
              and func.value.id == "random"):
            self.emit("DLT002", node,
                      f"stdlib random.{func.attr}() in traced scope: traced "
                      "once, constant every step — use jax.random with a "
                      "threaded key")
        elif dotted.startswith(("np.random.", "numpy.random.")):
            self.emit("DLT002", node,
                      f"{dotted}() in traced scope: host RNG is baked in at "
                      "trace time — use jax.random")
        elif isinstance(func, ast.Attribute) and func.attr in (
                "now", "utcnow") and _terminal_name(func.value) in (
                "datetime", "date"):
            self.emit("DLT002", node,
                      f"{dotted}() is trace-time constant inside the "
                      "compiled step")
        # DLT003 — host callbacks
        elif isinstance(func, ast.Name) and func.id == "print":
            self.emit("DLT003", node,
                      "print() in traced scope runs at TRACE time only (and "
                      "never per step); the compiled-step contract here is "
                      "zero host callbacks")
        elif name in ("pure_callback", "io_callback", "debug_callback") or (
                dotted in ("jax.debug.print", "jax.debug.callback",
                           "debug.print", "debug.callback")):
            self.emit("DLT003", node,
                      f"{dotted or name} injects a host callback into the "
                      "compiled step (the step contract is zero host "
                      "callbacks; see analysis.trace_check)")

    def _check_serve_host_call(self, node: ast.Call) -> None:
        """DLT010/DLT011 — serve/ host-loop hygiene (host level only:
        traced-scope clocks are DLT002's finding, traced jnp.* is just
        jax code)."""
        dotted = _dotted(node.func) if _terminal_name(node.func) else ""
        if dotted in CLOCK_CALLS:
            self.emit("DLT011", node,
                      f"{dotted}() hardwires the wall clock in serve/; "
                      "route it through the injectable time_fn seam "
                      "(ServeMetrics/ServingEngine/ServingFleet take "
                      "time_fn=...) so deadline/SLO/latency behavior is "
                      "testable without real sleeps")
            return
        if self._host_loop_depth and (
                dotted.startswith(("jnp.", "jax.numpy."))
                or dotted in ("jax.device_put", "device_put")):
            self.emit("DLT010", node,
                      f"{dotted}() inside a host-side serve/ loop builds a "
                      "device array per iteration (a hidden H2D transfer "
                      "per slot per tick); build numpy in the loop and "
                      "convert ONCE at the dispatch boundary")
            return
        # DLT012 — blocking socket/pipe read with no deadline seam in the
        # enclosing function (method-shaped calls only: a bare read()
        # name is not a pipe read; os.read is the one dotted form)
        blocking = (isinstance(node.func, ast.Attribute)
                    and node.func.attr in BLOCKING_IO_ATTRS) \
            or dotted == "os.read"
        if blocking:
            scope = self._func_stack[-1] if self._func_stack else None
            if scope is None or not _mentions_name(scope,
                                                   BLOCKING_IO_SEAMS):
                what = (node.func.attr if isinstance(node.func,
                                                     ast.Attribute)
                        else dotted)
                self.emit("DLT012", node,
                          f"{what}() can block forever in the serving "
                          "host loop; bound it in this function — "
                          "settimeout/setblocking, a select/poll wait "
                          "with a deadline, or the BlockingIOError "
                          "non-blocking idiom — so one dead peer cannot "
                          "wedge every request behind it")

    def _check_prng_serialization(self, node: ast.Call) -> None:
        if _terminal_name(node.func) not in (
                "save", "StandardSave", "savez", "savez_compressed"):
            return
        payload = list(node.args) + [k.value for k in node.keywords]
        rng_mention = None
        for arg in payload:
            for sub in ast.walk(arg):
                ident = None
                if isinstance(sub, ast.Name):
                    ident = sub.id
                elif isinstance(sub, ast.Attribute):
                    ident = sub.attr
                elif isinstance(sub, ast.Constant) and isinstance(sub.value,
                                                                  str):
                    ident = sub.value
                if ident and "rng" in ident.lower():
                    rng_mention = sub
                    break
            if rng_mention is not None:
                break
        if rng_mention is None:
            return
        scope = self._func_stack[-1] if self._func_stack else None
        shims = ("key_data", "pack_state_rng", "_pack_state")
        if scope is not None and _mentions_name(scope, shims):
            return
        self.emit("DLT004", node,
                  "an 'rng' leaf reaches a save call with no key_data/"
                  "pack_state_rng shim in scope: typed PRNG keys are not "
                  "serializable — the save fails (or silently drops the "
                  "key) at run time")

    def _check_json_dump(self, node: ast.Call) -> None:
        func = node.func
        if not (isinstance(func, ast.Attribute)
                and func.attr in ("dump", "dumps")
                and isinstance(func.value, ast.Name)
                and func.value.id == "json"):
            return
        for kw in node.keywords:
            if kw.arg == "allow_nan":
                if (isinstance(kw.value, ast.Constant)
                        and kw.value.value is True):
                    break  # explicit True: as bad as the default
                return  # False (or dynamic): caller made the choice
        self.emit("DLT007", node,
                  f"json.{func.attr} without allow_nan=False: one NaN emits "
                  "the bare token `NaN` — invalid JSON that corrupts the "
                  "line for every strict consumer")

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        broad = node.type is None or (
            isinstance(node.type, ast.Name)
            and node.type.id in ("Exception", "BaseException")) or (
            isinstance(node.type, ast.Tuple)
            and any(isinstance(e, ast.Name)
                    and e.id in ("Exception", "BaseException")
                    for e in node.type.elts))
        if broad and self._body_is_inert(node.body) and not self._in_del():
            self.emit("DLT006", node,
                      "broad except with an inert body swallows the failure "
                      "entirely; attach context and re-raise (or at least "
                      "record it) — finalizers (__del__) are exempt")
        self.generic_visit(node)

    def _in_del(self) -> bool:
        return any(isinstance(f, _FUNC_NODES) and f.name == "__del__"
                   for f in self._func_stack)

    @staticmethod
    def _body_is_inert(body) -> bool:
        """Inert = nothing escapes: only pass/continue/break, bare returns
        or constant returns, and docstrings. A call, assignment, or raise
        means the handler did SOMETHING with the failure."""
        for stmt in body:
            if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
                continue
            if isinstance(stmt, ast.Return) and (
                    stmt.value is None
                    or isinstance(stmt.value, ast.Constant)):
                continue
            if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                         ast.Constant):
                continue  # docstring / ellipsis
            return False
        return True


# ------------------------------------------------------------------ front end
def lint_source(src: str, path: str = "<string>") -> list[Finding]:
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding("DLT000", path, e.lineno or 0, e.offset or 0,
                        f"syntax error: {e.msg}")]
    linter = _Linter(tree, path, src)
    linter.visit(tree)
    return sorted(linter.findings, key=lambda f: (f.path, f.line, f.rule))


def lint_file(path: str | pathlib.Path) -> list[Finding]:
    p = pathlib.Path(path)
    try:
        src = p.read_text(encoding="utf-8", errors="replace")
    except OSError as e:
        return [Finding("DLT000", str(p), 0, 0, f"unreadable: {e}")]
    return lint_source(src, str(p))


def lint_paths(paths: Iterable[str | pathlib.Path]) -> list[Finding]:
    """Lint files and/or directories (directories are walked for ``*.py``,
    skipping hidden and ``__pycache__`` entries)."""
    findings: list[Finding] = []
    for path in paths:
        p = pathlib.Path(path)
        if p.is_dir():
            # skip hidden/__pycache__ components BELOW the root only: the
            # root itself may live under a hidden ancestor (~/.cache, a
            # .worktrees dir) and must still lint, not false-green
            files = sorted(
                f for f in p.rglob("*.py")
                if "__pycache__" not in f.relative_to(p).parts
                and not any(part.startswith(".")
                            for part in f.relative_to(p).parts))
        else:
            files = [p]
        for f in files:
            findings.extend(lint_file(f))
    return findings


def main(argv: list[str]) -> int:
    """Standalone entry point (no package import, no jax):
    ``python distributed_lion_tpu/analysis/lint.py [paths...]``."""
    targets = argv or [str(pathlib.Path(__file__).resolve().parents[1])]
    findings = lint_paths(targets)
    for f in findings:
        print(f)
    if findings:
        print(f"graft-check tier1: {len(findings)} finding(s)")
        return 1
    print(f"graft-check tier1: clean ({', '.join(map(str, targets))})")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
