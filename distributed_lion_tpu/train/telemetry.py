"""Vote-health telemetry: on-device election instrumentation for the
majority-vote optimizer, plus the trainer's anomaly layer.

The whole novelty of Distributed Lion is the 1-bit election, yet a run that
only logs loss cannot see it. signSGD-with-majority-vote analysis (Bernstein
et al., 2018) ties convergence under compression to exactly the signals this
module surfaces:

- **vote margin** |Σ worker signs|/W per coordinate — a near-unanimous
  election is a high-SNR gradient direction; mass piling up at margin ≈ 0
  means the workers are voting noise. Accumulated as a fixed-bin histogram
  (`NBINS` bins of margin fraction), exact only for wires that move the
  tally (`sign_psum`, `packed_allgather`); the two-phase wires ship a ±1
  verdict proxy by design, so their histogram is zeroed rather than faked
  (`margin_exact` says which regime a record came from).
- **elected-sign flip rate** — fraction of (re)voted coordinates whose
  elected sign changed vs the previous election: the election's temporal
  stability (high flip rate + low margin = the vote is thrashing).
- **worker disagreement** — fraction of voted coordinates where this
  worker's local ballot lost the election, meaned over workers: how far the
  per-worker momenta have diverged from the consensus direction.
- **stochastic-binarization flip fraction** — how often the stochastic vote
  differs from the deterministic sign (the quantizer's injected noise).
- **valid-update sparsity** under ``vote_every`` — fraction of coordinates
  that received a real (non-cold-start) update this step.

Everything is accumulated ON DEVICE in a small replicated
:class:`VoteHealth` pytree carried alongside ``LionState`` through the
jitted step (``fold``), and drained to host floats only at the trainer's
``logging_steps`` cadence (``drain``) — zero added host transfers on the
hot path. Counters are folded as per-step *fractions* in f32 (a 124M-
coordinate ballot over a 50-step log window overflows i32 counts; fractions
stay O(1) and keep the accumulator bit-deterministic).

The module also hosts the trainer's anomaly tooling: crash-bundle writing
(per-leaf finite masks naming the poisoned leaves), the multi-host step
heartbeat, and the trace-time measured-wire capture that cross-checks
``profiling.comm_report``'s analytic bytes against what the collectives are
actually handed (``measure_step_wire``; drift == 0 in-process is pinned by
test).

Layering: this module may import ``ops``/``parallel``; it must NOT import
``optim`` or ``train.loop`` (both import it).
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from distributed_lion_tpu.ops.codec import packed_size, parse_wire
from distributed_lion_tpu.train.journal import emit

# fixed margin-histogram bins over the margin fraction |total|/W in [0, 1]:
# bin k covers [k/NBINS, (k+1)/NBINS), with margin == 1 (unanimity) clipped
# into the top bin. Fixed (not configurable) so records from different runs
# and world sizes are always comparable bin-for-bin.
NBINS = 8


def tally_wire(wire: str) -> bool:
    """True when ``wire`` moves the exact vote tally Σ±1 (margin available);
    the two-phase wires (``packed_a2a``, ``hier``) ship only a ±1 verdict
    proxy — magnitude never crosses the fabric, which is their point."""
    kind, _ = parse_wire(wire)
    return kind in ("sign_psum", "packed_allgather")


def margin_hist(totals: jnp.ndarray, world: int,
                mask: Optional[jnp.ndarray] = None,
                nbins: int = NBINS) -> jnp.ndarray:
    """Fixed-bin bincount of the vote margin |total|/world over the voted
    coordinates (``mask`` excludes the lazy slice's alignment padding).
    Shared by the XLA optimizer path and the Pallas kernel's reference —
    the Pallas ``bucket_vote_stats`` must bin identically (pinned by test).
    """
    t = jnp.abs(totals.astype(jnp.int32))
    idx = jnp.minimum((t * nbins) // jnp.int32(world), nbins - 1)
    if mask is not None:
        idx = jnp.where(mask, idx, nbins)  # padding lands in a dropped bin
    return jnp.bincount(idx, length=nbins + 1)[:nbins].astype(jnp.int32)


# --------------------------------------------------------------------- frames
# A *frame* is the per-step raw telemetry the optimizer emits (plain dict of
# device arrays — the optimizer layer stays free of this module's types):
#   margin_hist  i32[NBINS]  margin bincount over voted coords (zeros when
#                            the wire is a ±1-proxy format)
#   elected      uint8[...]  packed elected-sign state (full vector for
#                            vote_every == 1; the sign cache for K > 1)
#   disagree     i32         voted coords where the LOCAL ballot lost
#   voted        i32         coords voted this step (lazy: the 1/K slice)
#   valid        i32         coords receiving a real update this step
#   stoch_flip_frac f32      local mean of (stochastic vote != det sign)
#   flip_valid   bool        the refreshed coords held a REAL previous
#                            election (lazy cold start: slot j's cache bytes
#                            are zero-init until count >= K, and comparing
#                            against them would fake a ~0.5 flip rate)


def empty_frame(packed_len: int) -> dict:
    """The zero frame (used by degenerate paths, e.g. an empty pytree)."""
    return {
        "margin_hist": jnp.zeros((NBINS,), jnp.int32),
        "elected": jnp.zeros((packed_len,), jnp.uint8),
        "disagree": jnp.zeros((), jnp.int32),
        "voted": jnp.zeros((), jnp.int32),
        "valid": jnp.zeros((), jnp.int32),
        "stoch_flip_frac": jnp.zeros((), jnp.float32),
        "flip_valid": jnp.zeros((), jnp.bool_),
    }


# ----------------------------------------------------------------- VoteHealth
class VoteHealth(NamedTuple):
    """On-device running vote-health accumulator (replicated; carried through
    the jitted step next to ``LionState``, reset after each drain). All
    counters are per-step fractions summed in f32 — see module docstring."""

    steps: jnp.ndarray          # i32: steps folded since the last drain
    voted: jnp.ndarray          # f32: Σ per-step voted-coordinate counts
    voted_steps: jnp.ndarray    # i32: steps that voted > 0 coordinates (the
    # last vote_every rotation slot can be pure alignment padding — those
    # steps must not dilute the per-voted-coordinate fractions)
    margin_hist: jnp.ndarray    # f32[NBINS]: Σ per-step fraction histograms
    flip_sum: jnp.ndarray       # f32: Σ per-step flip fractions
    flip_steps: jnp.ndarray     # i32: steps contributing a flip comparison
    disagree_sum: jnp.ndarray   # f32: Σ per-step mean disagreement fractions
    stoch_flip_sum: jnp.ndarray # f32: Σ per-step stochastic flip fractions
    valid_sum: jnp.ndarray      # f32: Σ per-step valid-update fractions
    prev_elected: jnp.ndarray   # uint8: last election, packed (flip base)
    has_prev: jnp.ndarray       # i32 0/1: prev_elected is a real election


def elected_packed_len(n_params: int, vote_every: int = 1) -> int:
    """Length in bytes of the packed elected-sign vector the optimizer
    emits: the full ballot for strict voting; the K-slot byte-aligned cache
    layout (codec.vote_chunk_elems) under lazy refresh."""
    if vote_every > 1:
        from distributed_lion_tpu.ops.codec import vote_chunk_elems

        return vote_every * vote_chunk_elems(n_params, vote_every) // 8
    return packed_size(n_params)


def init_vote_health(n_params: int, vote_every: int = 1) -> VoteHealth:
    z32 = jnp.zeros((), jnp.int32)
    zf = jnp.zeros((), jnp.float32)
    return VoteHealth(
        steps=z32, voted=zf, voted_steps=z32,
        margin_hist=jnp.zeros((NBINS,), jnp.float32),
        flip_sum=zf, flip_steps=z32, disagree_sum=zf, stoch_flip_sum=zf,
        valid_sum=zf,
        prev_elected=jnp.zeros((elected_packed_len(n_params, vote_every),),
                               jnp.uint8),
        has_prev=z32,
    )


def fold(vh: VoteHealth, frame: dict, axis_name: str, world: int,
         n_params: int) -> VoteHealth:
    """Fold one optimizer step's frame into the running accumulator. Runs
    INSIDE shard_map; the two per-worker scalars (disagreement, stochastic
    flips) are psum'd over the data axis so every output leaf is replicated
    — the only collectives telemetry adds, both O(1) scalars riding the
    compiled step (no host traffic)."""
    voted = frame["voted"].astype(jnp.float32)
    did_vote = frame["voted"] > 0
    denom = jnp.maximum(voted, 1.0)
    hist_frac = frame["margin_hist"].astype(jnp.float32) / denom
    disagree = (lax.psum(frame["disagree"].astype(jnp.float32), axis_name)
                / (world * denom))
    stoch = lax.psum(frame["stoch_flip_frac"], axis_name) / world
    xor = jnp.bitwise_xor(frame["elected"], vh.prev_elected)
    flips = jnp.sum(lax.population_count(xor).astype(jnp.int32)).astype(
        jnp.float32)
    # flip fractions are per (re)voted coordinate and only well-defined once
    # a previous election exists for the REFRESHED coords AND this step
    # actually voted: has_prev gates the accumulator's first fold, and the
    # frame's flip_valid gates the optimizer's own cold start (under lazy
    # refresh, slot j first votes at count == j against zero-init cache
    # bytes — counting those as flips would fake a thrashing election)
    counts_flip = (vh.has_prev > 0) & did_vote & frame["flip_valid"]
    flip_frac = jnp.where(counts_flip, flips / denom, 0.0)
    valid_frac = frame["valid"].astype(jnp.float32) / max(n_params, 1)
    return VoteHealth(
        steps=vh.steps + 1,
        voted=vh.voted + voted,
        voted_steps=vh.voted_steps + did_vote.astype(jnp.int32),
        margin_hist=vh.margin_hist + hist_frac,
        flip_sum=vh.flip_sum + flip_frac,
        flip_steps=vh.flip_steps + counts_flip.astype(jnp.int32),
        disagree_sum=vh.disagree_sum + disagree,
        stoch_flip_sum=vh.stoch_flip_sum + stoch,
        valid_sum=vh.valid_sum + valid_frac,
        prev_elected=frame["elected"],
        has_prev=jnp.ones((), jnp.int32),
    )


def drain(vh: VoteHealth, margin_exact: bool) -> dict:
    """One host transfer: the accumulator as plain floats, normalized per
    folded step. The margin histogram is normalized per voted coordinate, so
    its mass is ≈ 1.0 exactly when every voted coordinate landed in a bin
    (the check_evidence 'telemetry' stage's invariant) — only meaningful
    when ``margin_exact`` (tally wire)."""
    host = jax.device_get(vh)
    steps = int(host.steps)
    s = max(steps, 1)
    vs = max(int(host.voted_steps), 1)  # per-voted-coordinate fractions
    hist = [float(x) / vs for x in np.asarray(host.margin_hist)]
    return {
        "steps": steps,
        "voted_per_step": float(host.voted) / s,
        "margin_exact": 1 if margin_exact else 0,
        "margin_hist": [round(h, 6) for h in hist],
        "hist_mass": round(float(sum(hist)), 6),
        "flip_rate": float(host.flip_sum) / max(int(host.flip_steps), 1),
        "disagree_frac": float(host.disagree_sum) / vs,
        "stoch_flip_frac": float(host.stoch_flip_sum) / s,
        "valid_frac": float(host.valid_sum) / s,
    }


def reset_counters(vh: VoteHealth) -> VoteHealth:
    """Zero the drained counters; the previous election (and its validity
    bit) carries over so the flip rate stays continuous across log
    intervals. Host-side, log-cadence only."""
    z = lambda x: jnp.zeros_like(x)  # noqa: E731
    return VoteHealth(
        steps=z(vh.steps), voted=z(vh.voted), voted_steps=z(vh.voted_steps),
        margin_hist=z(vh.margin_hist),
        flip_sum=z(vh.flip_sum), flip_steps=z(vh.flip_steps),
        disagree_sum=z(vh.disagree_sum), stoch_flip_sum=z(vh.stoch_flip_sum),
        valid_sum=z(vh.valid_sum),
        prev_elected=vh.prev_elected, has_prev=vh.has_prev,
    )


# --------------------------------------------------------- measured wire legs
def measure_step_wire(step_fn, *example_args) -> Optional[dict]:
    """Trace ``step_fn`` once under abstract evaluation with the wire tally
    capturing, and return the per-step measured ledger: the bytes each vote
    collective is ACTUALLY handed (real operand shapes at the call sites,
    ``parallel.collectives.WIRE_TALLY``), per fabric leg and per collective
    launch. Costs one extra trace at startup and nothing per step.

    This is the measured counterpart of ``profiling.comm_report``'s analytic
    accounting: the two agree exactly in-process (drift == 0, pinned by
    test) and the trainer logs their difference every interval, so any
    future divergence between what the accounting claims and what the
    collectives move becomes a first-class metric instead of a latent lie.
    """
    from distributed_lion_tpu.parallel.collectives import WIRE_TALLY

    with WIRE_TALLY.capture() as entries:
        jax.eval_shape(step_fn, *example_args)
    total = sum(b for _, b in entries)
    dcn = sum(b for leg, b in entries if leg == "dcn")
    return {
        "bytes_per_step": total,
        "dcn_bytes_per_step": dcn,
        "calls_per_step": len(entries),
        "per_call": [{"leg": leg, "bytes": b} for leg, b in entries],
    }


# ------------------------------------------------------------ host heartbeat
def host_step_skew(step: int) -> Optional[int]:
    """Multi-host heartbeat: max − min of the per-process step counter (a
    tiny all_gather at log cadence). A growing skew names a straggling or
    wedged host long before the next blocking collective does. None on
    single-process runs (nothing to compare)."""
    if jax.process_count() <= 1:
        return None
    try:
        from jax.experimental import multihost_utils

        steps = multihost_utils.process_allgather(np.asarray(step, np.int64))
        return int(np.max(steps) - np.min(steps))
    except Exception as e:  # heartbeat must never take down training
        emit(f"[telemetry] heartbeat unavailable: {e}")
        return None


# -------------------------------------------------------------- crash bundles
def nonfinite_leaf_report(tree: Any) -> dict:
    """{leaf path: non-finite element count} over the floating leaves of a
    pytree — the crash bundle's "which leaf is poisoned" answer. One device
    round-trip per floating leaf, but only at crash time, where clarity
    beats latency."""
    out = {}
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    for path, leaf in leaves:
        if not hasattr(leaf, "dtype") or not jnp.issubdtype(
                leaf.dtype, jnp.floating):
            continue
        bad = int(jax.device_get(jnp.sum(~jnp.isfinite(leaf))))
        if bad:
            out[jax.tree_util.keystr(path)] = bad
    return out


def _json_safe(obj):
    """Recursive JSON sanitizer for bundle payloads: non-finite floats
    become their repr strings ('nan', 'inf') — a crash bundle should SHOW
    the poison, not smuggle invalid JSON."""
    if isinstance(obj, float) and not math.isfinite(obj):
        return repr(obj)
    if isinstance(obj, dict):
        return {str(k): _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    return repr(obj)


def write_crash_bundle(output_dir: str, step: int, reason: str,
                       cfg_dict: dict, params: Any, opt_state: Any,
                       metrics_window, guard: Optional[dict] = None,
                       journal_tail=None) -> str:
    """Write ``<output_dir>/crash/step_<n>/bundle.json``: everything needed
    to explain a non-finite step without re-running under a profiler —
    step, trip reason, the full train config, per-leaf non-finite counts
    for params AND optimizer state (naming the poisoned leaves), the recent
    metrics window, and (``guard``) the vote guard's per-WORKER health
    report — mask, strikes, signal counters — so the bundle names the sick
    worker, not just the poisoned leaves. ``journal_tail`` (the run
    journal's ring buffer, train/journal.py) lands beside the bundle as
    ``journal_tail.jsonl`` — the anomaly carries its own timeline: the last
    N spans/events before the trip, in the same strict-JSONL schema the
    live journal writes. Returns the bundle directory."""
    crash_dir = os.path.join(output_dir, "crash", f"step_{step:08d}")
    os.makedirs(crash_dir, exist_ok=True)
    if journal_tail:
        with open(os.path.join(crash_dir, "journal_tail.jsonl"), "w") as f:
            for rec in journal_tail:
                f.write(json.dumps(_json_safe(rec), allow_nan=False) + "\n")
    bundle = {
        "step": step,
        "reason": reason,
        "written": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "config": cfg_dict,
        "nonfinite_params": nonfinite_leaf_report(params),
        "nonfinite_opt_state": nonfinite_leaf_report(opt_state),
        "metrics_window": list(metrics_window),
    }
    if guard is not None:
        bundle["guard"] = guard
    with open(os.path.join(crash_dir, "bundle.json"), "w") as f:
        json.dump(_json_safe(bundle), f, indent=1, allow_nan=False)
        f.write("\n")
    return crash_dir
