"""DPO: direct preference optimization loss over policy + frozen reference.

The reference's DPO entry point is broken as shipped (syntax error at
dpo_llama2.py:81, undefined ``base_model`` at :210-213 — SURVEY §2.10); this
implements the INTENDED workload: policy and frozen reference model score
(prompt, chosen) and (prompt, rejected); the loss is

    -log σ(β · [(logπ_c − logπ_r) − (logref_c − logref_r)])

with β=0.1 (dpo_llama2.py:25, :223). Batches are pytrees
{"chosen", "rejected", "chosen_mask", "rejected_mask"} of [B, T] arrays,
masks selecting completion tokens only (prompt excluded, padding excluded),
produced by data/dpo.py.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def sequence_logprob(logits: jnp.ndarray, tokens: jnp.ndarray,
                     mask: jnp.ndarray) -> jnp.ndarray:
    """Sum of label log-probs over masked (completion) positions, [B]."""
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, tokens[:, 1:, None], axis=-1)[..., 0]
    return (ll * mask[:, 1:].astype(jnp.float32)).sum(-1)


def sequence_logprob_seq_parallel(
    logits: jnp.ndarray, tokens: jnp.ndarray, mask: jnp.ndarray,
    axis_name: str,
) -> jnp.ndarray:
    """Seq-parallel :func:`sequence_logprob` (inside shard_map): each device
    holds a contiguous [B, T/S] chunk of tokens/mask and ITS chunk's logits.
    Boundary labels (and their mask bits — a label counts iff the mask at
    the LABEL position is set, exactly like the dense path's
    ``mask[:, 1:]``) arrive from the next shard via one [B, 1] ppermute;
    per-shard partial sums are psum'd so every shard returns the full-
    sequence [B] logprob — the nonlinear pairwise DPO loss downstream then
    computes identically on every shard, and the train loop's seq-axis grad
    psum stitches the shard-local cotangent paths into the full gradient."""
    from distributed_lion_tpu.models.loss import shift_in_next_shard

    labels, is_last = shift_in_next_shard(tokens, axis_name)
    lmask, _ = shift_in_next_shard(mask, axis_name)
    lmask = lmask.astype(jnp.float32)
    # the final shard's last position has no next token (dense path drops it
    # via logits[:, :-1])
    lmask = lmask.at[:, -1].set(jnp.where(is_last, 0.0, lmask[:, -1]))
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    # the reduced [B] logprob is consumed replicated (every shard computes
    # the same pairwise loss), so the exit reduce is the Megatron g operator
    # — identity backward; a raw psum's transpose would scale every
    # adapter gradient by S (uniform, so sign-Lion hid it, but exact is
    # exact). The train loop's seq-axis grad psum then sums the per-shard
    # partial cotangent paths into the full gradient.
    from distributed_lion_tpu.parallel.tensor_parallel import reduce_from_tp_region

    return reduce_from_tp_region((ll * lmask).sum(-1), axis_name)


def sequence_logprob_chunked(
    hidden: jnp.ndarray, head: jnp.ndarray, tokens: jnp.ndarray,
    mask: jnp.ndarray, n_chunks: int, emb_layout: str = "dv",
) -> jnp.ndarray:
    """:func:`sequence_logprob` from HIDDEN STATES via the streaming
    chunked-vocab logsumexp (ops/xent.chunked_softmax_xent): per-position
    label logprob is −nll, so the [B, T, V] f32 ``log_softmax`` — ~1.3 GB
    per microbatch pass at Llama vocab 32k, and DPO runs FOUR such passes
    (policy/ref × chosen/rejected) — is never materialized. Exact same
    math (pinned by tests/test_dpo_chunked.py)."""
    from distributed_lion_tpu.ops.xent import chunked_softmax_xent

    b, t, d = hidden.shape
    h = hidden[:, :-1].reshape(b * (t - 1), d)
    labels = tokens[:, 1:].reshape(-1).astype(jnp.int32)
    nll, _ = chunked_softmax_xent(h, head, labels, n_chunks, emb_layout)
    ll = -nll.reshape(b, t - 1)
    return (ll * mask[:, 1:].astype(jnp.float32)).sum(-1)


def sequence_logprob_chunked_seq_parallel(
    hidden: jnp.ndarray, head: jnp.ndarray, tokens: jnp.ndarray,
    mask: jnp.ndarray, axis_name: str, n_chunks: int,
    emb_layout: str = "dv",
) -> jnp.ndarray:
    """Chunked × sequence-parallel :func:`sequence_logprob`: the boundary
    protocol of :func:`sequence_logprob_seq_parallel` (labels and their
    mask bits ppermute in from the next shard; final shard's last position
    dropped) with the local shard's label logprobs computed by the
    streaming chunked logsumexp instead of a materialized log_softmax."""
    from distributed_lion_tpu.models.loss import shift_in_next_shard
    from distributed_lion_tpu.ops.xent import chunked_softmax_xent
    from distributed_lion_tpu.parallel.tensor_parallel import reduce_from_tp_region

    labels, is_last = shift_in_next_shard(tokens, axis_name)
    lmask, _ = shift_in_next_shard(mask, axis_name)
    lmask = lmask.astype(jnp.float32)
    lmask = lmask.at[:, -1].set(jnp.where(is_last, 0.0, lmask[:, -1]))
    b, t, d = hidden.shape
    nll, _ = chunked_softmax_xent(
        hidden.reshape(b * t, d), head,
        labels.reshape(-1).astype(jnp.int32), n_chunks, emb_layout)
    ll = -nll.reshape(b, t)
    # replicated consumer ⇒ Megatron g-operator exit (identity backward),
    # same rationale as sequence_logprob_seq_parallel
    return reduce_from_tp_region((ll * lmask).sum(-1), axis_name)


def _accepts_dropout_key(fn: Callable) -> bool:
    """True when ``fn`` can take a ``dropout_key`` keyword (LoRA adapter
    dropout); plain ``(params, tokens)`` callables keep their signature."""
    import inspect

    try:
        return any(
            p.name == "dropout_key" or p.kind is inspect.Parameter.VAR_KEYWORD
            for p in inspect.signature(fn).parameters.values())
    except (TypeError, ValueError):  # builtins/partials without signatures
        return False


def make_dpo_loss_fn(
    policy_apply: Callable,
    ref_apply: Callable,
    beta: float = 0.1,
    seq_axis: str | None = None,
    vocab_chunks: int = 0,
    emb_layout: str = "dv",
) -> Callable:
    """Build ``loss_fn(params, batch, dropout_key) -> (loss, metrics)`` for
    the Trainer. ``policy_apply(params, tokens)`` and ``ref_apply(tokens)``
    (ref params are frozen/closed-over, mirroring the reference's separate
    4-bit ref model, dpo_llama2.py:146-152). With ``seq_axis``, the batch
    leaves are token-sharded chunks and the apply fns are expected to run
    the model with the same seq axis (ring attention). With
    ``vocab_chunks > 0``, the apply fns must return ``(hidden, head)``
    instead of logits and the logprobs stream through the chunked-vocab
    logsumexp (no [B, T, V] materialization — DPO's four scoring passes
    make this the biggest activation saving of any workload)."""

    def seqlp(out, tokens, mask):
        if vocab_chunks > 0:
            if not (isinstance(out, tuple) and len(out) == 2):
                # a [B,T,V] logits array would silently unpack along batch
                raise TypeError(
                    "vocab_chunks > 0 requires apply fns returning "
                    "(hidden, head); got a single array — wire the hidden/"
                    "head forward (see cli/run_dpo._hidden_and_head)")
            hidden, head = out
            if seq_axis is None:
                return sequence_logprob_chunked(
                    hidden, head, tokens, mask, vocab_chunks, emb_layout)
            return sequence_logprob_chunked_seq_parallel(
                hidden, head, tokens, mask, seq_axis, vocab_chunks,
                emb_layout)
        if seq_axis is None:
            return sequence_logprob(out, tokens, mask)
        return sequence_logprob_seq_parallel(out, tokens, mask, seq_axis)

    _accepts_key = _accepts_dropout_key(policy_apply)

    def _policy(params, tokens, key):
        if _accepts_key:
            return policy_apply(params, tokens, dropout_key=key)
        return policy_apply(params, tokens)

    def loss_fn(params, batch, dropout_key):
        # adapter (lora_dropout) keys: one per policy pass, None in eval —
        # the reference's PEFT dropout is train-time only (sft_llama2.py:48)
        kc = kr = None
        if dropout_key is not None:
            kc, kr = jax.random.split(dropout_key)
        pol_c = seqlp(_policy(params, batch["chosen"], kc),
                      batch["chosen"], batch["chosen_mask"])
        pol_r = seqlp(_policy(params, batch["rejected"], kr),
                      batch["rejected"], batch["rejected_mask"])
        ref_c = seqlp(ref_apply(batch["chosen"]),
                      batch["chosen"], batch["chosen_mask"])
        ref_r = seqlp(ref_apply(batch["rejected"]),
                      batch["rejected"], batch["rejected_mask"])
        # stop_gradient is belt-and-braces: ref_apply takes no params arg.
        ref_c = jax.lax.stop_gradient(ref_c)
        ref_r = jax.lax.stop_gradient(ref_r)

        logits = beta * ((pol_c - pol_r) - (ref_c - ref_r))
        loss = -jax.nn.log_sigmoid(logits).mean()
        reward_c = beta * (pol_c - ref_c)
        reward_r = beta * (pol_r - ref_r)
        metrics = {
            "loss": loss,
            "reward_accuracy": (reward_c > reward_r).mean(),
            "reward_margin": (reward_c - reward_r).mean(),
        }
        return loss, metrics

    loss_fn._vocab_chunked = vocab_chunks > 0  # Trainer guard handshake
    return loss_fn


def make_dpo_loss_fn_frozen(
    policy_apply: Callable,
    ref_apply: Callable,
    beta: float = 0.1,
) -> Callable:
    """Frozen-as-argument variant for the Trainer's ``frozen_params`` path
    (tensor parallelism: the base/ref trees arrive as live sharded args, not
    closures). ``policy_apply(params, frozen, tokens)``,
    ``ref_apply(frozen, tokens)``; returns
    ``loss_fn(params, frozen, batch, dropout_key)``."""

    _accepts_key = _accepts_dropout_key(policy_apply)

    def loss_fn(params, frozen, batch, dropout_key):
        if _accepts_key:
            pol = (lambda p, t, dropout_key=None:
                   policy_apply(p, frozen, t, dropout_key=dropout_key))
        else:
            pol = lambda p, t: policy_apply(p, frozen, t)  # noqa: E731
        inner = make_dpo_loss_fn(pol, lambda t: ref_apply(frozen, t), beta)
        return inner(params, batch, dropout_key)

    return loss_fn
