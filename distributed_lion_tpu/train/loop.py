"""The training loop: jit-compiled train step with the vote inside.

This is the native replacement for the stack the reference borrows —
HF ``Trainer`` + ``accelerate``/DDP + the ``AsyncTrainer`` subclass
(/root/reference/async_trainer.py:8-34). The reference's one idea at this
layer is ``model.no_sync()``: gradients are NEVER all-reduced; the only
cross-worker traffic is the optimizer's 1-bit vote (async_trainer.py:15,
SURVEY §2.6). In JAX that contract is structural: the train step below is a
single ``shard_map`` over the data axis in which per-device gradients feed
per-device momentum, and the sole collective is the optimizer's majority
vote. With ``async_grad=False`` it degrades to classic data parallelism
(``lax.pmean`` of grads — DDP's all-reduce) for the reference's plain-Trainer
path.

Grad accumulation is a ``lax.scan`` over microbatches (the reference's
``gradient_accumulation_steps=8``, README.md:31), fwd/bwd via
``jax.value_and_grad``, loss/metrics pmean'd for logging only.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import os
import time
from functools import partial
from typing import Any, Callable, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_lion_tpu.models.gpt2 import GPT2Config, count_params, gpt2_apply, gpt2_init
from distributed_lion_tpu.models.loss import clm_loss_and_metrics
from distributed_lion_tpu.ops.codec import vote_chunk_elems, wire_bytes_per_param
from distributed_lion_tpu.optim import (
    distributed_lion,
    expand_worker_state,
    heal_worker_momentum,
    init_global_state,
    remap_worker_momentum,
    squeeze_worker_state,
)
from distributed_lion_tpu.optim.lion import FunctionalOptimizer, LionState
from distributed_lion_tpu.optim.optax_adapter import OptaxState, adamw
from distributed_lion_tpu.optim.zero import (
    Zero1State,
    adamw_zero1,
    expand_zero_state,
    squeeze_zero_state,
)
from distributed_lion_tpu.parallel.mesh import (
    DATA_AXIS,
    EXPERT_AXIS,
    PIPE_AXIS,
    SEQ_AXIS,
    TENSOR_AXIS,
    data_axis_size,
)
from distributed_lion_tpu.train import (
    control_plane,
    journal,
    resilience,
    telemetry,
    vote_guard,
)
from distributed_lion_tpu.train.journal import emit
from distributed_lion_tpu.train.checkpoint import Checkpointer
from distributed_lion_tpu.train.metrics import MetricsLogger
from distributed_lion_tpu.train.profiling import (
    StepProfiler,
    StepTimer,
    comm_report,
    peak_hbm_gb,
    peak_hbm_per_device,
)
from distributed_lion_tpu.train.schedule import (
    constant_schedule,
    cosine_schedule_with_warmup,
    linear_schedule_with_warmup,
)


@dataclasses.dataclass
class TrainConfig:
    """The reference's CLI surface (run_clm.py AsyncTrainingArguments +
    TrainingArguments subset actually exercised, README.md:18-38) as one
    dataclass. ``lion`` and ``async_grad`` are the two reference-specific
    flags (run_clm.py:73-86)."""

    lion: bool = True
    async_grad: bool = True
    zero1: bool = False  # AdamW path only: shard Adam m/v over the data axis
    # (ZeRO-1, optim/zero.py) — 2N/W floats of optimizer state per device
    # instead of 2N, updated chunks re-assembled with one all_gather.
    wire: str = "auto"  # vote wire format. 'auto' picks per mesh shape
    # (resolve_auto_comm): W=1 → sign_psum (no traffic); single-host W>1 →
    # packed_a2a (minimum received bytes AND fastest measured wire,
    # scripts/SWEEP_wires.md); multi-host → hier:<local_devices> (only the
    # 1-bit verdict chunks cross the DCN boundary). All wires elect
    # IDENTICAL signs (tests/test_collectives.py wire equivalence) — the
    # choice changes bytes moved, never the trajectory.
    vote_every: int = 0  # K > 1: lazy sign refresh — each step votes a 1/K
    # coordinate slice (wire volume ÷ K; packed_a2a at K=4 ≈ 0.375 bit/
    # param/step at W=4, the BASELINE.md ≤0.5-bit comm budget), stale
    # elected signs applied elsewhere (optim.distributed_lion). 0 = auto:
    # currently ALWAYS 1, the reference's strict every-step vote — lazy
    # voting is opt-in (--vote_every 4) until a full-scale parity:lazy leg
    # PASSES the pre-registered criterion (check_evidence parity:lazy;
    # runs/parity holds no lazy curve yet, so auto must not default to a
    # trajectory claim the evidence doesn't back — VERDICT weak #1).
    # Mechanism correctness at test scale IS pinned (tests/test_vote_every
    # convergence + replica consistency); the open question is trajectory
    # parity at 100M+ scale, which only the parity leg can answer.
    vote_buckets: int = 0  # B > 1: bucketed, overlapped vote wire — the
    # ballot splits into B contiguous wire-aligned chunks (codec.
    # bucket_bounds) voted as B independent collectives, software-pipelined
    # against the fused apply (bucket k rides the interconnect while bucket
    # k−1 updates in VMEM, optim.distributed_lion). Params/momentum and the
    # summed wire bytes are bit-identical to B=1 (tests/test_vote_buckets.py)
    # — bucketing changes WHEN bytes move, never what is elected. 0 = auto
    # (resolve_auto_comm): 4 when W > 1 and the per-step ballot slice is
    # ≥ AUTO_BUCKET_MIN_COORDS, else 1 (the monolithic vote).
    dcn_pipeline_depth: int = 0  # d > 0 (hier wire only): cross-step DCN
    # overlap — each step computes/combines its level-1 ICI tally
    # immediately and LAUNCHES the level-2 cross-group (DCN) ring for its
    # own ballot, but consumes the ring only d steps later (the in-flight
    # packed tallies ride LionState.dcn_ring, one slot per step), so the
    # slow fabric's round trip hides behind d steps of compute instead of
    # bounding every step. Elections applied at step t are the complete
    # two-level election of step t−d's ballots — uniformly stale, replicas
    # bit-identical; the first d steps apply no update (cold start, the
    # vote_every rule). Composes with vote_buckets/vote_every/the vote
    # guard; bytes per step are depth-invariant (comm_drift_bytes stays 0).
    # 0 = today's synchronous hier wire. Checkpoints carry the ring, so
    # crash-resume stays bit-identical at any depth; a depth toggle on
    # resume errors loudly. See ARCHITECTURE 'DCN overlap'.
    ep_dcn_pipeline: Optional[int] = None  # MoE balance-feedback staleness
    # when the EXPERT axis spans DCN (ISSUE 16). None (default) = today's
    # per-shard local aux, bit for bit. 0 = synchronous global balance:
    # each MoE block psums its routing tallies over the expert axis inside
    # the forward (a blocking DCN collective per MoE block — exact, and at
    # ep=1 bit-identical to unflagged). d > 0 = pipelined: the aux consumes
    # the globally-psummed tallies from d steps ago (LionState.moe_ring,
    # one slot per in-flight step, per-data-worker divergent — no DATA-axis
    # collective is added, so async_grad's only-collective-is-the-vote
    # contract holds), and this step's fresh tallies launch into the ring
    # after the backward — the slow fabric's round trip rides behind d
    # steps of compute. Token activations stay synchronous (the two MoE
    # all_to_all hops are exact); ONLY the non-differentiable load
    # estimate in the aux loss goes stale. First d steps fall back to the
    # local aux (cold start). Lion-only at d > 0 (the ring rides
    # LionState); needs MoE blocks; checkpoints carry the ring and a depth
    # toggle on resume errors loudly, like --dcn_pipeline_depth.
    kernel: str = "auto"  # auto | pallas | xla (ops/pallas_lion fused path)
    row_block: int = 0  # Pallas lion kernel tile rows (multiple of 32).
    # 0 = auto: the Trainer consults the device-keyed autotune cache
    # (ops/autotune, knob 'lion_row_block', cli/run_tune) when the Pallas
    # path is live on TPU, else pallas_lion.ROW_BLOCK. Pure tiling — the
    # elections/params are bit-identical at any value
    # (tests/test_autotune.py); only VMEM residency changes.
    remat_policy: str = dataclasses.field(
        default="", metadata={"cli": False})  # '' = honor the model
    # config's own remat/remat_policy; 'full' | 'dots' overrides it at
    # Trainer build. Programmatic only (no CLI flag — run_clm's
    # model-level --remat_policy drives the model config directly; this
    # field is the override bench.py and tests hand the Trainer builders).
    # (models/gpt2._remat_policy: 'dots' keeps matmul outputs and
    # recomputes elementwise — the cheaper backward the sweep's dots leg
    # measures). A perf knob under the vote, not a semantics knob: at f32
    # compute the Lion trajectory AND the lazy elected-sign cache are
    # bit-identical across policies; at bf16 compute jax.checkpoint's
    # fusion barriers shift a few ULPs so elections may flip only on
    # near-tie coordinates (tests/test_train.py pins both halves, the
    # PR 6 remat-equivalence precedent).
    mom_dtype: str = ""  # Lion momentum dtype override ('bfloat16' halves
    # the per-worker optimizer state and its read/write traffic — at 7B
    # full-param scale that is ~14 GB of HBM; '' = the param dtype, the
    # reference's exp_avg = zeros_like(p) behavior)
    vocab_chunks: int = 0  # > 0: chunked-vocab cross entropy (ops/xent) —
    # the [B,T,V] f32 logits (the largest activation at GPT-2 124M: ~823MB
    # per microbatch) are never materialized; streaming logsumexp over V/N
    # chunks, chunk logits rematerialized in backward. Same math, less HBM.
    tp_vocab: bool = False  # Llama path, tensor_parallel > 1: shard the
    # lm_head's vocab columns over the tensor axis and compute the CLM loss
    # with Megatron vocab-parallel CE (ops/xent.tp_vocab_xent) — V/tp logit
    # columns per rank instead of every rank computing the full [B,T,V].
    tensor_parallel: int = 1  # tensor mesh axis size (consumed by the CLIs
                              # when building the mesh; net-new vs reference)
    seq_parallel: int = 1  # sequence/context mesh axis size: batches are
                           # sharded over tokens, attention rings over the
                           # 'seq' axis (parallel.ring_attention); net-new
    pipeline_parallel: int = 1  # pipeline stages over the 'pipe' mesh axis
    # (blocks stacked [pp, L/pp], GPipe microbatch schedule — models/gpt2_pipe
    # + parallel/pipeline); net-new
    pipeline_microbatches: int = 0  # GPipe microbatches per accum step
    # (0 → pipeline_parallel; bubble fraction = (S-1)/(M+S-1))
    expert_parallel: int = 1  # expert mesh axis size: MoE FFN banks sharded
    # over 'expert', tokens ride dispatch/return all_to_all; the axis doubles
    # as extra data parallelism for dense layers (parallel/expert); net-new
    max_grad_norm: Optional[float] = None  # set → stochastic binarization
    grad_clip_norm: Optional[float] = None  # global-norm gradient clipping
    # (HF Trainer, which the reference sits on, clips at 1.0 by default —
    # run_clm inherits it via TrainingArguments). When max_grad_norm is set
    # and this is not, grads are clipped at max_grad_norm: the stochastic
    # quantizer's unbiasedness needs |β₁m+(1−β₁)g| ≤ r (SURVEY §2.4).
    learning_rate: float = 1e-4
    weight_decay: float = 0.1
    beta1: float = 0.9
    beta2: float = 0.99
    lr_scheduler_type: str = "cosine"  # cosine | linear | constant
    warmup_steps: int = 2000
    max_steps: int = 100_000
    per_device_train_batch_size: int = 20
    gradient_accumulation_steps: int = 8
    per_device_eval_batch_size: int = 20
    steps_per_call: int = 1  # optimizer steps fused into one device dispatch
    # (lax.scan over staged batches). >1 amortizes host→device dispatch
    # latency — the hot loop stays on device; logging granularity coarsens
    # to the chunk. Net-new vs the reference (HF Trainer dispatches per step).
    block_size: int = 1024
    seed: int = 42
    logging_steps: int = 50
    eval_steps: int = 1000
    eval_iters: int = 20
    save_steps: int = 1000
    save_total_limit: Optional[int] = 2
    output_dir: Optional[str] = None
    resume_from_checkpoint: bool = True
    async_ckpt: bool = True  # async double-buffered checkpointing
    # (train/checkpoint.py): save() kicks off the Orbax async write and
    # returns after the device→host copy; the blocking drain moves to the
    # NEXT save boundary (and close()/anomaly paths), so serialization and
    # disk I/O hide behind the following train steps. The ckpt_stall_s
    # metric logs the loop's actual checkpoint tax; tests pin it below the
    # synchronous baseline. False = the old blocking save.
    ckpt_integrity: bool = True  # per-file sha256 manifest + COMMITTED
    # marker written last (atomic commit): resume autodetect verifies
    # newest-first and falls back to the newest GOOD checkpoint, so a torn
    # leaf file or corrupted manifest costs one save interval, not the run.
    on_preempt: str = "save_exit"  # save_exit | off. save_exit installs a
    # SIGTERM guard (train/resilience.PreemptionGuard) checked once per
    # dispatch: on trip the loop drains the in-flight async save, writes an
    # emergency checkpoint tagged 'preempt', and returns cleanly so the
    # process exits 0 and the watcher restarts into a normal resume.
    elastic_resume: bool = False  # allow resuming a checkpoint written at a
    # DIFFERENT data-parallel world size: the stacked [W, ...] Lion momenta
    # are remapped to [W', ...] by optim.distributed_lion.
    # remap_worker_momentum (shard-group re-averaging W'<W, replication
    # W'>W, mean broadcast otherwise — the cross-worker momentum mean, the
    # vote distribution's center, is preserved exactly in every case).
    # Off by default: a world-size mismatch is loud, not silently remapped.
    report_to_wandb: bool = False
    profile_dir: Optional[str] = None  # capture a jax.profiler trace window
    profile_start_step: int = 10
    profile_num_steps: int = 3
    telemetry: bool = False  # vote-health telemetry (train/telemetry.py):
    # an on-device VoteHealth accumulator rides the jitted step (margin
    # histogram, elected-sign flip rate, worker disagreement, stochastic
    # flip fraction, valid-update sparsity) and drains to the metrics log
    # at logging_steps cadence — zero added host transfers per step, and
    # elections stay bit-identical to telemetry-off (tests/test_telemetry).
    # Also arms measured wire counters (trace-time byte ledger at the vote-
    # collective call sites, cross-checked against the analytic comm_report
    # as comm_drift_bytes) and the multi-host step heartbeat. Lion-only:
    # the AdamW path has no election to observe.
    nan_sentinel: bool = False  # per-step isfinite watch over loss + grad
    # norm (checked one dispatch behind so the device pipeline stays full);
    # on trip, writes a crash bundle (step, config, per-leaf finite masks
    # naming the poisoned leaves, recent metrics window) to
    # output_dir/crash/step_<n>/ and raises FloatingPointError.
    retrace_guard: str = "warn"  # off | warn | error. The runtime leg of the
    # static-analysis subsystem (analysis/): hash the jitted train step's
    # abstract input signature (leaf shapes/dtypes) at each dispatch and
    # surface any UNSEEN signature after the first — a recompilation: new
    # batch shape/dtype, a drifted state structure; signatures jax already
    # compiled and cached re-dispatch freely — as a loud retraces metric +
    # warning, or a RuntimeError under 'error', instead of a silent 2x
    # step-time cliff. Checked BEFORE dispatch (host-side hash over leaf
    # avals, no device traffic), so 'error' refuses the recompile before
    # paying for it. Purely observational under 'warn': elections and
    # trajectories are bit-identical to 'off'.
    trace_on_anomaly: bool = False  # with nan_sentinel: instead of raising
    # immediately, arm a StepProfiler window at the tripping step (trace
    # written into the crash bundle), run profile_num_steps more steps to
    # capture the poisoned dataflow, then raise.
    vote_guard: str = "off"  # off | observe | enforce. The vote guard
    # (train/vote_guard.py + optim.distributed_lion guard mode): the jitted
    # step emits per-worker ballot-health signals (nonfinite local
    # grad/momentum before sign-encoding, frozen ballots via popcount(XOR
    # prev), outlier disagreement vs the healthy peers) and a host-side
    # quarantine machine — checked one dispatch behind, like the NaN
    # sentinel — strikes, quarantines and (after --guard_cooldown steps)
    # readmits workers. 'enforce' additionally masks quarantined ballots
    # out of the election (the majority threshold shrinks to the healthy
    # quorum), zeroes nonfinite gradients out of the momentum update, and
    # re-averages a readmitted worker's momentum from the healthy mean;
    # with an all-healthy mask it is bit-identical to 'off'
    # (tests/test_vote_guard.py). 'observe' reports what enforce would do
    # without touching the election. Lion-only: AdamW has no election.
    min_quorum: int = 0  # vote_guard enforce: refuse to continue (loud
    # RuntimeError) when the healthy quorum drops below this. 0 = auto:
    # a strict majority (W//2 + 1) — a vote with a sick majority is noise.
    guard_strikes: int = 3  # consecutive-ish bad observed steps before a
    # worker is quarantined (a clean dispatch resets its strikes, so
    # transient faults — one bad batch — never escalate)
    guard_cooldown: int = 50  # optimizer steps a quarantined worker sits
    # out before a readmission probe (healed momentum, mask cleared; a
    # still-sick worker re-strikes within guard_strikes steps)
    journal: bool = False  # run journal (train/journal.py): a host-side
    # span/event recorder around every loop region — trainer dispatch,
    # device wait (the log-cadence drain), data wait, logging drain,
    # checkpoint serialize/drain, preemption/quarantine transitions —
    # written as rank-stamped strict-JSON JSONL under --journal_dir and
    # analyzed offline by cli/run_analyze (step-time attribution, top
    # stall sources, cross-host skew, BENCH baseline diff). Host wall
    # clocks only: zero added device syncs per step, and elections are
    # pinned bit-identical journal-on vs journal-off
    # (tests/test_journal.py).
    journal_dir: str = ""  # journal sink directory ('' = output_dir/journal;
    # with neither set the journal runs ring-only: crash bundles still get
    # their journal_tail.jsonl, nothing else is written)
    inject_poison: str = ""  # fault injection for the guard's evidence and
    # tests: '<kind>:<worker>[:<start_step>]' with kind in
    # nan_grads | frozen_ballot | flipped_ballot
    # (train/resilience.parse_poison; baked into the step at trace time
    # through the resilience fault registry). Works with --vote_guard off
    # too — that is the degradation baseline the guard is measured against.
    control_plane: bool = False  # unified membership control plane
    # (train/control_plane.py): one host-side lifecycle per worker
    # (healthy → suspect → quarantined → departed → rejoining → healthy)
    # consuming the signals the NaN sentinel, the PreemptionGuard and the
    # vote guard each held a slice of, whose single output is the alive
    # mask the masked elections already accept. Live leave/join without a
    # restart: a departing worker (injected worker_drop, repeated guard
    # strikes, preemption) becomes a mask transition at the next dispatch
    # boundary — training continues at W−1 — and a rejoining worker is
    # re-absorbed in-run (momentum re-averaged from the healthy mean via
    # heal_worker_momentum, ballot history reset, probation window). Auto-
    # arms --vote_guard enforce when the guard is off (all-healthy enforce
    # is pinned bit-identical to off); refuses 'observe' (it never touches
    # the mask). Lion-only. In-run rejoin at --dcn_pipeline_depth > 0 is
    # refused loudly, mirroring the elastic-resume rule.
    rejoin_probe_steps: int = 0  # control plane: optimizer steps a
    # rejoined worker stays on probation ('rejoining'). A rejoiner that
    # re-strikes inside the window departs again (never the quarantine/
    # readmit cycle a dead host would loop forever); a clean window
    # promotes it to healthy. 0 = auto: --guard_cooldown.
    inject_membership: str = ""  # membership fault injection for the
    # control plane's evidence and tests: comma-separated
    # 'worker_drop:<w>[:<start_step>]' / 'worker_rejoin:<w>:<step>' specs
    # (train/resilience.parse_membership), consumed HOST-side at dispatch
    # boundaries through the resilience fault registry — a drop/rejoin is
    # a mask transition plus state surgery, never a trace change.
    # Requires --control_plane (the plane is the only consumer).

    def schedule(self) -> Callable:
        if self.lr_scheduler_type == "cosine":
            return cosine_schedule_with_warmup(self.learning_rate, self.warmup_steps, self.max_steps)
        if self.lr_scheduler_type == "linear":
            return linear_schedule_with_warmup(self.learning_rate, self.warmup_steps, self.max_steps)
        return constant_schedule(self.learning_rate)


def apply_remat_policy(cfg: "TrainConfig", model_cfg):
    """Thread ``TrainConfig.remat_policy`` through the Trainer builders:
    ``''`` honors the model config's own setting; ``'full' | 'dots'``
    replaces it (models/gpt2._remat_policy). Loud on an unknown policy
    and on an override with remat disabled — a policy that silently
    never applies is the kind of no-op a sweep leg would then measure."""
    if not cfg.remat_policy:
        return model_cfg
    if cfg.remat_policy not in ("full", "dots"):
        raise ValueError(
            f"unknown remat_policy {cfg.remat_policy!r} (full | dots)")
    if not model_cfg.remat:
        raise ValueError(
            "TrainConfig.remat_policy set but the model config has "
            "remat=False — the policy would silently never apply; drop "
            "the override or enable remat")
    return dataclasses.replace(model_cfg, remat_policy=cfg.remat_policy)


def validate_seq_block(cfg: "TrainConfig", model_cfg, sp: int) -> None:
    """Config-time guards shared by every sequence-parallel path (plain,
    pipelined, both families): tokens must split evenly over the seq axis,
    and the TOTAL sequence must fit the positional scheme — without the
    n_ctx check the wpe dynamic_slice clamps at the table end (later shards
    silently duplicate positional rows) and rope offsets extrapolate."""
    if cfg.block_size % sp:
        raise ValueError(f"block_size {cfg.block_size} not divisible by "
                         f"seq axis {sp}")
    if cfg.block_size > model_cfg.n_ctx:
        raise ValueError(
            f"seq-parallel block_size {cfg.block_size} (total tokens across "
            f"the {sp}-way seq axis) exceeds n_ctx {model_cfg.n_ctx}: the "
            f"positional scheme (wpe table / rope range) is too small"
        )


# the ballot size at which lazy vote refresh WOULD be worth auto-enabling
# (below it the full vote is cheap anyway). Auto currently resolves
# vote_every to 1 regardless — lazy is opt-in until a full-scale
# parity:lazy leg passes the pre-registered criterion (see
# resolve_auto_comm) — but the threshold is kept: it still gates the
# advisory trainer message, and it is the line the auto default re-arms at
# once the evidence lands.
AUTO_LAZY_MIN_PARAMS = 10_000_000

# bucketed-vote auto threshold: pipeline the wire only when the PER-STEP
# ballot slice (after vote_every's ÷K) is at least this many coordinates —
# 4 buckets of ≥4M coords each still amortize per-collective launch latency,
# while smaller ballots' wires are too cheap for overlap to matter and
# tiny/debug models keep the simplest single-collective graph
AUTO_BUCKET_MIN_COORDS = 16_000_000


def _spec_sharded_axes(param_specs) -> set:
    """Mesh axes any param PartitionSpec shards over (empty = replicated
    params). ``None`` specs (the default-replicated case) give the empty
    set."""
    if param_specs is None:
        return set()
    return {
        ax for s in jax.tree.leaves(
            param_specs, is_leaf=lambda x: isinstance(x, P))
        for dim in s for ax in
        (dim if isinstance(dim, (tuple, list)) else (dim,))
        if ax is not None
    }


def resolve_auto_comm(cfg: TrainConfig, mesh, n_params: int,
                      params_replicated: bool) -> TrainConfig:
    """Resolve the comm sentinels (``wire='auto'``, ``vote_every=0``,
    ``vote_buckets=0``) into concrete values for this mesh + model — the one
    place the multi-chip default wire recipe lives (README 'wire recipe';
    BASELINE.md ≤0.5-bit budget vs the reference's always-sign_psum analog,
    /root/reference/distributed_lion.py:80-81). Idempotent: a cfg with all
    three fields explicit is returned unchanged, so factories can resolve
    early (for their byte-accounting print) and Trainer.__init__ resolves
    only what reaches it unresolved."""
    if (cfg.wire != "auto" and cfg.vote_every != 0
            and cfg.vote_buckets != 0):
        return cfg
    world = data_axis_size(mesh)
    wire, ve, vb = cfg.wire, cfg.vote_every, cfg.vote_buckets
    if wire == "auto":
        # hier's subgroups must be DATA-axis workers sharing a host. data is
        # the slowest-varying mesh axis (make_mesh), so consecutive data
        # indices sit `inner` devices apart (inner = product of the model
        # axes); a host of L local devices therefore holds L // inner whole
        # data rows. Grouping by local_device_count alone would straddle
        # hosts whenever inner > 1 and run the full ballot reduce-scatter
        # over DCN — the opposite of the wire's point.
        inner = 1
        for ax, sz in mesh.shape.items():
            if ax != DATA_AXIS:
                inner *= sz
        local = jax.local_device_count()
        hier_g = local // inner if inner and local % inner == 0 else 0
        if not cfg.lion or world == 1:
            wire = "sign_psum"  # W=1 short-circuits: no bytes move
        elif jax.process_count() > 1 and hier_g > 1 and world % hier_g == 0:
            # multi-host: only the 1-bit verdict chunks should cross DCN —
            # hier's DCN leg is 0.125 bits/param at g=4 vs packed_a2a's
            # cross-host phases (scripts/SWEEP_wires.md)
            wire = f"hier:{hier_g}"
        else:
            # minimum received bytes AND fastest measured wire at W=8
            # (scripts/SWEEP_wires.md: 1.75 bits/param, 1276 ms vs
            # sign_psum's 8.0 bits, 1885 ms); also the multi-host fallback
            # when the host layout gives no intact ICI data subgroup
            wire = "packed_a2a"
    if ve == 0:
        # The lazy default is OFF until evidenced: auto resolves to the
        # reference's strict every-step vote. Round 4 shipped ve=4 here for
        # big replicated ballots with a message claiming the trajectory
        # "overlays every-step voting at this scale (runs/parity)" — but
        # runs/parity holds NO lazy leg, so the default was asserting
        # evidence that does not exist (VERDICT weak #1). Until a
        # full-scale lazy leg PASSES the pre-registered criterion
        # (scripts/check_evidence.py parity:lazy + PARITY_EPS_NATS), lazy
        # voting stays an explicit opt-in; the candidate threshold it
        # would re-arm at is kept as AUTO_LAZY_MIN_PARAMS.
        ve = 1
        if (cfg.lion and world > 1 and params_replicated
                and n_params >= AUTO_LAZY_MIN_PARAMS):
            bits = wire_bytes_per_param(
                n_params, world, wire, vote_every=4)["bits_per_param"]
            emit(
                f"[trainer] auto comm: wire={wire} vote_every=1 (strict "
                f"every-step voting). Lazy --vote_every 4 would cut the "
                f"{n_params/1e6:.0f}M-coordinate ballot to {bits:.2f} "
                "bits/param/step, but it stays opt-in until the "
                "full-scale parity:lazy leg passes the pre-registered "
                "criterion (scripts/loss_parity.py; check_evidence "
                "parity:lazy)."
            )
    if vb == 0:
        # bucketed overlap: worth it only when there is a wire (W > 1) AND
        # the per-step ballot slice is big enough that each of 4 buckets
        # still amortizes collective launch latency. Elections are
        # bit-identical at any B, so auto never changes the trajectory —
        # only whether the wire can hide behind the fused apply. A
        # device-keyed autotune measurement for THIS ballot size
        # (ops/autotune knob 'vote_buckets', key dtype int8 — the wire
        # payload) outranks the heuristic; the heuristic stays the miss
        # path.
        n_voted = (n_params if ve <= 1
                   else min(n_params, vote_chunk_elems(n_params, ve)))
        tuned_vb = None
        if cfg.lion and world > 1:
            from distributed_lion_tpu.ops.autotune import lookup

            v = lookup("vote_buckets", f"N{n_voted}", "int8") or {}
            # .get, not [..]: the schema admits any {str:int} value, and a
            # mistyped operator-written entry must degrade to the
            # heuristic (the autotune failure philosophy), never crash
            # trainer construction
            if isinstance(v.get("vote_buckets"), int):
                tuned_vb = v["vote_buckets"]
        if tuned_vb:
            vb = tuned_vb
        else:
            vb = (4 if (cfg.lion and world > 1
                        and n_voted >= AUTO_BUCKET_MIN_COORDS) else 1)
    return dataclasses.replace(cfg, wire=wire, vote_every=ve,
                               vote_buckets=vb)


def _resolve_row_block_auto(cfg: TrainConfig, n_params: int,
                            params) -> TrainConfig:
    """Resolve ``row_block=0`` (auto) from the device-keyed autotune cache
    when the Pallas lion path is actually live — TPU backend and
    ``kernel`` auto/pallas. Key: knob ``lion_row_block``, shape
    ``N<ballot coords>``, dtype = the momentum dtype (mom_dtype override
    or the param dtype, mirroring distributed_lion's state init). Off-TPU
    and on cache miss the 0 passes through and pallas_lion.ROW_BLOCK
    applies — interpret-mode tests stay independent of whatever cache the
    repo happens to carry."""
    if cfg.row_block != 0 or not cfg.lion or cfg.kernel == "xla":
        return cfg
    from distributed_lion_tpu.ops.autotune import lookup
    from distributed_lion_tpu.ops.pallas_lion import pallas_available

    if not pallas_available():
        return cfg
    leaves = jax.tree.leaves(params)
    mom_dtype = (cfg.mom_dtype
                 or (jnp.dtype(leaves[0].dtype).name if leaves else "float32"))
    v = lookup("lion_row_block", f"N{n_params}", jnp.dtype(mom_dtype).name)
    # .get, not [..]: a mistyped operator-written entry degrades to the
    # built-in ROW_BLOCK (autotune failure philosophy), never crashes init
    if not v or not isinstance(v.get("row_block"), int):
        return cfg
    return dataclasses.replace(cfg, row_block=v["row_block"])


def make_optimizer(cfg: TrainConfig) -> FunctionalOptimizer:
    """The reference's optimizer wiring (run_clm.py:580-585): ``--lion`` →
    Lion(lr, wd) else AdamW(wd=0.1 hardcoded); both under a cosine-warmup
    schedule."""
    if cfg.zero1 and cfg.lion:
        raise ValueError(
            "--zero1 applies only to the AdamW path; with --lion the optimizer "
            "state is the per-worker vote momentum, which ZeRO-1 sharding "
            "would silently drop — drop one of the two flags"
        )
    if cfg.zero1 and cfg.async_grad:
        raise ValueError(
            "--zero1 requires synchronized gradients (async_grad=False): each "
            "worker updates the Adam-state chunk it owns, so all workers must "
            "see the same gradient for that chunk — with async_grad the "
            "all_gather would stitch together chunk-wise single-worker updates"
        )
    if cfg.telemetry and not cfg.lion:
        raise ValueError(
            "--telemetry instruments the majority-vote election; the AdamW "
            "path has no vote to observe — drop one of the two flags"
        )
    if cfg.vote_guard != "off" and not cfg.lion:
        raise ValueError(
            "--vote_guard protects the majority-vote election; the AdamW "
            "path has no vote to guard — drop one of the two flags"
        )
    if cfg.dcn_pipeline_depth > 0:
        from distributed_lion_tpu.ops.codec import parse_wire

        if not cfg.lion:
            raise ValueError(
                "--dcn_pipeline_depth pipelines the vote wire; the AdamW "
                "path has no vote collective — drop one of the two flags")
        if cfg.wire == "auto":
            # the Trainer resolves 'auto' before reaching here, so a
            # literal sentinel means a standalone caller skipped
            # resolve_auto_comm — and staleness must never ride an
            # implicit wire choice either way
            raise ValueError(
                f"--dcn_pipeline_depth {cfg.dcn_pipeline_depth} needs an "
                "explicitly named hier wire, but the wire is the "
                "unresolved 'auto' sentinel — pass --wire hier:<g>")
        if parse_wire(cfg.wire)[0] != "hier":
            raise ValueError(
                f"--dcn_pipeline_depth {cfg.dcn_pipeline_depth} pipelines "
                f"the hier wire's level-2 (DCN) leg, but the wire here is "
                f"{cfg.wire!r} — a wire without a DCN leg has nothing to "
                "overlap; pass --wire hier:<g>")
    if cfg.ep_dcn_pipeline is not None:
        if cfg.ep_dcn_pipeline < 0:
            raise ValueError(
                f"--ep_dcn_pipeline must be >= 0, got {cfg.ep_dcn_pipeline}")
        if cfg.ep_dcn_pipeline > 0 and not cfg.lion:
            raise ValueError(
                f"--ep_dcn_pipeline {cfg.ep_dcn_pipeline} stores the "
                "in-flight MoE balance tallies on LionState.moe_ring; the "
                "AdamW path has no per-worker optimizer state to carry "
                "them — use --lion, or --ep_dcn_pipeline 0 (the "
                "synchronous global balance needs no ring)")
    if cfg.lion:
        mom_dtype = jnp.dtype(cfg.mom_dtype) if cfg.mom_dtype else None
        return distributed_lion(
            cfg.schedule(),
            b1=cfg.beta1,
            b2=cfg.beta2,
            weight_decay=cfg.weight_decay,
            axis_name=DATA_AXIS,
            max_grad_norm=cfg.max_grad_norm,
            # standalone callers may pass an unresolved cfg (no mesh in this
            # signature): the sentinels degrade to the reference's strict
            # semantics; the Trainer always resolves via resolve_auto_comm
            # before reaching here
            wire="sign_psum" if cfg.wire == "auto" else cfg.wire,
            vote_every=cfg.vote_every or 1,
            vote_buckets=cfg.vote_buckets or 1,
            dcn_pipeline_depth=cfg.dcn_pipeline_depth,
            kernel=cfg.kernel,
            row_block=cfg.row_block,
            mom_dtype=mom_dtype,
            telemetry=cfg.telemetry,
            guard=cfg.vote_guard,
        )
    if cfg.async_grad:
        raise ValueError(
            "--async_grad without --lion would let replicas diverge (no grad "
            "sync and no vote); the reference silently permits this broken "
            "combination — we refuse it"
        )
    # default weight_decay=0.1 matches the reference's hardcoded AdamW value
    # (run_clm.py:583-585), but an explicit --weight_decay is honored here
    # rather than silently dropped as the reference does.
    if cfg.zero1:
        return adamw_zero1(cfg.schedule(), weight_decay=cfg.weight_decay,
                           axis_name=DATA_AXIS)
    return adamw(cfg.schedule(), weight_decay=cfg.weight_decay)


def _opt_state_specs(cfg: TrainConfig, exp_avg_specs):
    if cfg.lion:
        # stacked per-worker momentum: [world, ...] over 'data' (+ any
        # tensor-parallel dims the param itself carries); the elected-sign
        # cache (vote_every > 1) and the guard's health mask are replicated;
        # the guard's per-worker previous ballot and the DCN pipeline ring
        # (each member owns a different 1/g coordinate chunk) shard like
        # the momenta
        guard_on = cfg.vote_guard != "off"
        return LionState(count=P(), exp_avg=exp_avg_specs, rng=P(),
                         elected=P() if cfg.vote_every > 1 else None,
                         health=P() if guard_on else None,
                         prev_ballot=P(DATA_AXIS) if guard_on else None,
                         dcn_ring=(P(DATA_AXIS)
                                   if cfg.dcn_pipeline_depth > 0 else None),
                         moe_ring=(P(DATA_AXIS)
                                   if (cfg.ep_dcn_pipeline or 0) > 0
                                   else None))
    if cfg.zero1:
        # [world, chunk] m/v sharded over 'data': ZeRO-1 state partitioning
        return Zero1State(count=P(), m=P(DATA_AXIS), v=P(DATA_AXIS))
    return OptaxState(count=P(), inner=P(), rng=P())  # replicated


class Trainer:
    """Train/eval/checkpoint driver for the CLM workload.

    Model-agnostic: ``apply_fn(params, tokens, dropout_key) -> logits`` and an
    initial params pytree; GPT-2 helpers are provided by ``for_gpt2``.
    """

    def __init__(
        self,
        cfg: TrainConfig,
        mesh,
        apply_fn: Callable,
        params: Any,
        loss_mask_fn: Optional[Callable] = None,
        loss_fn: Optional[Callable] = None,
        param_specs: Any = None,
        batch_spec: Optional[P] = None,
        frozen_params: Any = None,
        frozen_specs: Any = None,
    ):
        """``loss_fn(params, batch, dropout_key) -> (loss, metrics)`` may
        replace the default CLM loss; ``batch`` is then any pytree whose
        leaves carry a leading global-batch axis (e.g. DPO's
        chosen/rejected pairs). ``param_specs`` is an optional PartitionSpec
        pytree (parallel.tensor_parallel) for tensor-parallel params;
        default replicated.

        ``frozen_params`` is an optional NON-trained pytree (LoRA bases, DPO
        reference models) threaded through the train/eval shard_maps as a
        live sharded argument — required whenever the frozen tree must be
        sharded over a non-data mesh axis (a closure capture would be
        replicated). When set, ``loss_fn`` takes
        ``(params, frozen, batch, dropout_key)`` and ``frozen_specs`` gives
        its PartitionSpecs (default replicated)."""
        n_params = count_params(params)
        cfg = resolve_auto_comm(
            cfg, mesh, n_params,
            params_replicated=not _spec_sharded_axes(param_specs),
        )
        cfg = _resolve_row_block_auto(cfg, n_params, params)
        cplane_auto_armed = False
        if cfg.control_plane:
            if not cfg.lion:
                raise ValueError(
                    "--control_plane drives the majority-vote election's "
                    "membership mask; the AdamW path has no election — "
                    "drop one of the two flags")
            if cfg.vote_guard == "observe":
                raise ValueError(
                    "--control_plane needs masked elections to act on its "
                    "membership decisions, but --vote_guard observe never "
                    "touches the mask — use 'enforce' (or leave the guard "
                    "off: the plane auto-arms enforce)")
            if cfg.vote_guard == "off":
                # all-healthy enforce is pinned bit-identical to off
                # (tests/test_vote_guard.py), so arming the mask machinery
                # never changes a healthy run's trajectory
                cfg = dataclasses.replace(cfg, vote_guard="enforce")
                cplane_auto_armed = True
        if cfg.inject_membership and not cfg.control_plane:
            raise ValueError(
                "--inject_membership schedules live worker leave/join, "
                "which only the control plane consumes — pass "
                "--control_plane (or drop the injection)")
        self.cfg = cfg
        self.mesh = mesh
        self.world = data_axis_size(mesh)
        # the run journal comes up FIRST so every construction/resume
        # message below already lands in the event stream; it is host-side
        # only — nothing it does can reach the traced step
        jdir = cfg.journal_dir or (os.path.join(cfg.output_dir, "journal")
                                   if cfg.output_dir else "")
        self.journal = (journal.Journal(jdir or None,
                                        rank=jax.process_index())
                        if cfg.journal else journal.NULL)
        if cfg.journal:
            journal.install(self.journal)
        if cfg.zero1:
            shape = dict(mesh.shape)
            for ax in (TENSOR_AXIS, SEQ_AXIS):
                if shape.get(ax, 1) > 1:
                    raise ValueError(
                        f"--zero1 is incompatible with a '{ax}' mesh axis of "
                        f"size {shape[ax]}: inside shard_map each {ax} rank "
                        "ravels its own local param shard, so the m/v chunks "
                        "diverge across ranks while the out_specs assume "
                        f"{ax}-replication — one rank's moments would silently "
                        "win. Use pure data parallelism with ZeRO-1."
                    )
        if cfg.lion and cfg.learning_rate < 1e-3 and any(
            p.dtype == jnp.bfloat16 for p in jax.tree.leaves(params)
        ):
            # Lion applies a FIXED ±lr step; bf16's ULP at |p| ~ 0.1 is
            # ~8e-4, so at small lr the update rounds to a no-op on every
            # large-magnitude coordinate (silently frozen params). bf16
            # params are a throughput/memory opt-in for benching; real
            # training should keep f32 master params with bf16 COMPUTE
            # (the model configs' default split), like torch's f32 master
            # weights under autocast.
            emit(
                "[trainer] WARNING: bf16 param storage with Lion lr "
                f"{cfg.learning_rate:g} < 1e-3 — the fixed ±lr update is "
                "below bf16 ULP for |p| > ~lr*256, so those coordinates "
                "will NOT move. Use f32 param_dtype (bf16 compute_dtype "
                "keeps the matmul speed) unless this is a throughput bench."
            )
        if (cfg.vocab_chunks > 0 and loss_fn is not None
                and not getattr(loss_fn, "_vocab_chunked", False)):
            # vocab_chunks is consumed by losses that opt in (for_gpt2's
            # dense path, run_sft's SFT losses, run_dpo's chunked scoring —
            # marked _vocab_chunked); any other caller-supplied loss would
            # silently ignore the CLI-auto-exposed flag.
            raise NotImplementedError(
                "--vocab_chunks is not wired into this entry point's loss "
                "function (supported: run_clm's dp/tp/sp/pp paths, run_sft, "
                "run_dpo)"
            )
        if cfg.tp_vocab and not getattr(loss_fn, "_tp_vocab", False):
            # same silent-ignore trap as vocab_chunks: the flag is
            # CLI-auto-exposed everywhere but only the dense dp x tp losses
            # of for_gpt2/for_llama consume it (parse_dataclasses exposes
            # every TrainConfig field)
            raise NotImplementedError(
                "--tp_vocab is wired for run_clm's dense dp x tp paths "
                "(gpt2 and llama families) only; this entry point's loss "
                "would silently ignore it"
            )
        self.batch_spec = batch_spec if batch_spec is not None else P(DATA_AXIS)
        # number of ways batch ROWS (dim 0) are sharded: data alone normally;
        # data x expert under expert parallelism (tokens ride both axes)
        dim0 = self.batch_spec[0] if len(self.batch_spec) else None
        dim0_axes = (tuple(dim0) if isinstance(dim0, (tuple, list))
                     else (dim0,) if dim0 else ())
        self.batch_shards = 1
        for a in dim0_axes:
            self.batch_shards *= dict(mesh.shape).get(a, 1)
        self.apply_fn = apply_fn
        self.opt = make_optimizer(cfg)
        if param_specs is None:
            param_specs = jax.tree.map(lambda _: P(), params)
        elif not cfg.lion:
            raise NotImplementedError("tensor-parallel param_specs require the Lion path")
        self.param_specs = param_specs
        if cfg.lion and cfg.vote_every > 1:
            sharded_axes = _spec_sharded_axes(param_specs)
            if sharded_axes:
                raise ValueError(
                    f"--vote_every > 1 is incompatible with params sharded "
                    f"over {sorted(sharded_axes)}: each rank's ballot covers "
                    "its own local param shards, so the elected-sign caches "
                    "differ across ranks while the P() spec declares them "
                    "replicated — one rank's cache would silently win and "
                    "stale signs would land on the wrong coordinates. Use "
                    "lazy vote refresh with replicated params (dp / dp x sp)."
                )
        if cfg.telemetry and _spec_sharded_axes(param_specs):
            raise ValueError(
                f"--telemetry is incompatible with params sharded over "
                f"{sorted(_spec_sharded_axes(param_specs))}: each rank's "
                "ballot covers its own local shards, so the packed election "
                "state the accumulator carries would differ across ranks "
                "while its P() spec declares it replicated. Use vote-health "
                "telemetry with replicated params (dp / dp x sp)."
            )
        vote_guard.parse_guard_mode(cfg.vote_guard)
        if cfg.vote_guard != "off" and _spec_sharded_axes(param_specs):
            raise ValueError(
                f"--vote_guard is incompatible with params sharded over "
                f"{sorted(_spec_sharded_axes(param_specs))}: the guard's "
                "per-worker ballot state covers each rank's LOCAL shards, "
                "so health decisions would mix different coordinate sets. "
                "Use the vote guard with replicated params (dp / dp x sp)."
            )
        self._guard = (vote_guard.make_guard(
            self.world, cfg.vote_guard, cfg.guard_strikes,
            cfg.guard_cooldown, cfg.min_quorum, journal=self.journal)
            if cfg.lion and cfg.vote_guard != "off" else None)
        self._guard_pending = None  # (step, obs-device-arrays, advanced)
        self._cplane = (control_plane.make_control_plane(
            self._guard, self.world, cfg.rejoin_probe_steps,
            cfg.dcn_pipeline_depth, journal=self.journal)
            if cfg.control_plane else None)
        if cplane_auto_armed:
            emit("[trainer] control plane: --vote_guard auto-armed to "
                 "'enforce' (the plane's membership mask rides the guard's "
                 "masked elections; all-healthy enforce is bit-identical "
                 "to off)")
        if cfg.inject_membership:
            sched = resilience.parse_membership_specs(cfg.inject_membership)
            bad = [(k, w) for k, w, _ in sched if w >= self.world]
            if bad:
                # fail at construction, not steps into the run
                raise ValueError(
                    f"--inject_membership names worker(s) "
                    f"{sorted(set(w for _, w in bad))} outside world "
                    f"{self.world}: {cfg.inject_membership!r}")
            if cfg.dcn_pipeline_depth > 0 and any(
                    k == "worker_rejoin" for k, _, _ in sched):
                # fail at construction, not steps into the run: the in-run
                # rejoin mirrors the elastic-resume depth rule (the DCN
                # ring's in-flight slots are functions of the membership)
                raise ValueError(
                    "--inject_membership schedules a worker_rejoin but "
                    f"--dcn_pipeline_depth {cfg.dcn_pipeline_depth} > 0: "
                    "the in-flight DCN tally ring cannot re-absorb a "
                    "worker mid-flight (the same reason --elastic_resume "
                    "refuses depth > 0). Run the rejoin at depth 0")
            resilience.inject_fault("membership", sched)
            emit(f"[trainer] FAULT INJECTION armed: membership "
                 f"{cfg.inject_membership!r}")
        if cfg.inject_poison:
            # route the spec through the resilience fault registry — the
            # same transport tests use directly; the step bakes it in at
            # trace time
            resilience.inject_fault(
                "ballot_poison", resilience.parse_poison(cfg.inject_poison))
            emit(f"[trainer] FAULT INJECTION armed: ballot poison "
                  f"{cfg.inject_poison!r}")

        self.params = jax.tree.map(
            lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, param_specs
        )
        self.frozen = None
        self.frozen_specs = None
        if frozen_params is not None:
            from distributed_lion_tpu.ops.quant import QuantizedTensor

            _is_qt = lambda x: isinstance(x, QuantizedTensor)  # noqa: E731
            if frozen_specs is None:
                frozen_specs = jax.tree.map(lambda _: P(), frozen_params,
                                            is_leaf=_is_qt)
            self.frozen_specs = frozen_specs

            def _put(p, s):
                # a QuantizedTensor node takes its dense leaf's spec: the
                # shaped layout keeps codes/absmax rank-aligned with the
                # dense weight, so the same P shards both children
                return jax.tree.map(
                    lambda c: jax.device_put(c, NamedSharding(mesh, s)), p)

            self.frozen = jax.tree.map(_put, frozen_params, frozen_specs,
                                       is_leaf=_is_qt)
        rng = jax.random.key(cfg.seed)
        self._exp_avg_specs = jax.tree.map(
            lambda s: P(*((DATA_AXIS,) + tuple(s))), param_specs
        )
        if cfg.lion:
            state = init_global_state(
                self.opt, self.params, self.world,
                rng=rng if cfg.max_grad_norm is not None else None,
            )
            if (cfg.ep_dcn_pipeline or 0) > 0:
                # the MoE balance ring (--ep_dcn_pipeline d > 0): one
                # [n_moe_blocks, E+1] tally slot per in-flight step, stacked
                # per data worker like the momenta. Created HERE, not by
                # init_global_state — the tally shape is model config,
                # which the optimizer never sees; the loss the MoE trainer
                # built stamps it on itself (_moe_tally_shape).
                tshape = getattr(loss_fn, "_moe_tally_shape", None)
                if tshape is None:
                    raise ValueError(
                        f"--ep_dcn_pipeline {cfg.ep_dcn_pipeline} > 0 "
                        "needs the MoE trainer's loss (make_trainer with "
                        "--moe_experts), which stamps the balance-tally "
                        "shape the ring is sized from; this loss carries "
                        "none")
                state = state._replace(moe_ring=jnp.zeros(
                    (self.world, cfg.ep_dcn_pipeline) + tuple(tshape),
                    jnp.float32))
            self.state = jax.device_put(
                state,
                LionState(
                    count=NamedSharding(mesh, P()),
                    exp_avg=jax.tree.map(
                        lambda s: NamedSharding(mesh, s), self._exp_avg_specs
                    ),
                    rng=None if state.rng is None else NamedSharding(mesh, P()),
                    elected=None if state.elected is None else NamedSharding(mesh, P()),
                    health=None if state.health is None
                    else NamedSharding(mesh, P()),
                    prev_ballot=None if state.prev_ballot is None
                    else NamedSharding(mesh, P(DATA_AXIS)),
                    dcn_ring=None if state.dcn_ring is None
                    else NamedSharding(mesh, P(DATA_AXIS)),
                    moe_ring=None if state.moe_ring is None
                    else NamedSharding(mesh, P(DATA_AXIS)),
                ),
            )
        elif cfg.zero1:
            state = self.opt.init(self.params, world=self.world)
            self.state = jax.device_put(
                state,
                Zero1State(
                    count=NamedSharding(mesh, P()),
                    m=NamedSharding(mesh, P(DATA_AXIS)),
                    v=NamedSharding(mesh, P(DATA_AXIS)),
                ),
            )
        else:
            self.state = jax.device_put(self.opt.init(self.params), NamedSharding(mesh, P()))

        # Vote-health telemetry state (train/telemetry.py): a small
        # replicated accumulator pytree threaded through the jitted step —
        # the ONLY signature change telemetry makes ({} when off keeps the
        # arity fixed, like the frozen arg). Drained + reset at log cadence.
        self._telemetry_on = bool(cfg.telemetry and cfg.lion)
        self._margin_exact = (self._telemetry_on
                              and telemetry.tally_wire(cfg.wire))
        if self._telemetry_on:
            n_tel = sum(int(np.prod(p.shape))
                        for p in jax.tree.leaves(self.params))
            self._n_ballot = n_tel
            self.vote_health = jax.device_put(
                telemetry.init_vote_health(n_tel, cfg.vote_every or 1),
                NamedSharding(mesh, P()),
            )
        else:
            self._n_ballot = 0
            self.vote_health = {}
        self._wire_measured: Optional[dict] = None  # trace-time byte ledger
        self._metrics_window: collections.deque = collections.deque(maxlen=16)
        self._sentinel_pending = None   # (step, metrics) awaiting the check
        self._anomaly_deadline = None   # step to stop the anomaly trace at
        self._anomaly_reason = ""

        self.step_count = 0
        self._resume_skip_batches = 0
        # caller-provided data-provenance stamps (e.g. the native loader's
        # served shard list) merged into every checkpoint's manifest meta,
        # so resume can verify the deterministic replay will see the SAME
        # data the original run consumed (cli/run_clm's shard-fleet check)
        self.data_meta: dict = {}
        self._schedule = cfg.schedule()
        if loss_fn is None:
            def loss_fn(params, batch, dropout_key):
                logits = self.apply_fn(params, batch, dropout_key)
                mask = loss_mask_fn(batch) if loss_mask_fn else None
                return clm_loss_and_metrics(logits, batch, mask)

        self.loss_fn = loss_fn
        self._train_step_core = self._build_train_step_core()
        # the accumulator (arg 2) is NOT donated: its zero-initialized
        # scalar counters can alias one device buffer, which XLA rejects as
        # a double donation — and its buffers are rebuilt every step anyway
        self._train_step = jax.jit(self._train_step_core,
                                   donate_argnums=(0, 1))
        self._train_chunk = jax.jit(self._build_train_chunk(),
                                    donate_argnums=(0, 1))
        self._eval_step = self._build_eval_step()
        self.checkpointer = (
            Checkpointer(f"{cfg.output_dir}/checkpoints", cfg.save_total_limit,
                         async_save=cfg.async_ckpt,
                         integrity=cfg.ckpt_integrity,
                         journal=self.journal)
            if cfg.output_dir
            else None
        )
        if cfg.on_preempt not in ("save_exit", "off"):
            raise ValueError(
                f"--on_preempt {cfg.on_preempt!r}: expected 'save_exit' "
                "(drain + emergency checkpoint + clean return) or 'off'")
        if cfg.retrace_guard not in ("off", "warn", "error"):
            raise ValueError(
                f"--retrace_guard {cfg.retrace_guard!r}: expected 'off', "
                "'warn' (count + log recompilations) or 'error' (refuse "
                "them before compiling)")
        # retrace guard state: the abstract input signatures each jitted
        # entry point has ALREADY compiled ('step' and 'chunk' specialize
        # separately by design) — a set, because jax caches every
        # specialization: only an UNSEEN signature costs a compile
        self._retrace_sigs: dict = {}
        self.retrace_count = 0
        self.preempted = False
        self._preempt_guard = (
            resilience.PreemptionGuard(journal=self.journal)
            if cfg.on_preempt == "save_exit" else None)
        self.logger = MetricsLogger(cfg.output_dir, use_wandb=cfg.report_to_wandb)
        self.profiler = StepProfiler(cfg.profile_dir, cfg.profile_start_step,
                                     cfg.profile_num_steps)
        self.timer = StepTimer()
        self.n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(self.params))
        self._maybe_resume()

    def _frozen_arg(self):
        """The frozen pytree as passed to the jitted steps ({} when unused —
        an empty pytree keeps the shard_map arity fixed)."""
        return self.frozen if self.frozen is not None else {}

    def comm_stats(self, steps_per_sec: Optional[float] = None) -> dict:
        """Analytic bytes-on-wire report for the vote collective (empty for
        the AdamW path, which has no optimizer collective)."""
        if not self.cfg.lion or self.world <= 1:
            # W=1: no vote collective executes at all — logging a comm
            # report (even a zeroed one) would dress a single-chip run in
            # multi-chip wire numbers
            return {}
        return comm_report(self.n_params, self.world, self.cfg.wire, steps_per_sec,
                           vote_every=self.cfg.vote_every,
                           accum_steps=self.cfg.gradient_accumulation_steps,
                           vote_buckets=self.cfg.vote_buckets or 1,
                           dcn_pipeline_depth=self.cfg.dcn_pipeline_depth)

    # -------------------------------------------------------------- telemetry
    def telemetry_summary(self, reset: bool = False) -> Optional[dict]:
        """Current vote-health summary as host floats (None when telemetry
        is off) — used by bench.py's record rows and available to callers
        that drive the jitted steps directly instead of train()."""
        if not self._telemetry_on:
            return None
        out = telemetry.drain(self.vote_health, self._margin_exact)
        if reset:
            self.vote_health = telemetry.reset_counters(self.vote_health)
        return out

    def _measure_wire_once(self, batch_example) -> None:
        """Capture the measured per-step wire ledger (one abstract trace of
        the step with the collectives' tally recording — zero steady-state
        cost). Runs once, lazily, because the batch structure is only known
        when training starts."""
        if (self._wire_measured is not None or not self._telemetry_on
                or self.world <= 1):
            return
        try:
            self._wire_measured = telemetry.measure_step_wire(
                self._train_step_core, self.params, self.state,
                self.vote_health, self._frozen_arg(), batch_example,
                jax.random.key(0),
            )
        except Exception as e:  # measurement must never take down training
            emit(f"[telemetry] wire measurement unavailable: {e}")
            self._wire_measured = {}

    def _check_retrace(self, kind: str, *args) -> None:
        """The retrace guard (--retrace_guard): compare this dispatch's
        abstract input signature against the first dispatch's. A change
        means jax is about to compile a second specialization of the train
        step — a one-off multi-second stall plus a silently cached second
        program, which on a chip reads as a 2x step-time cliff with no
        error anywhere. Host-side hash over leaf shapes/dtypes, checked
        BEFORE dispatch so 'error' mode refuses the recompile before
        paying for it."""
        if self.cfg.retrace_guard == "off":
            return
        # the treedef is part of the signature: structure drift with an
        # identical leaf sequence (a renamed key, same-shaped leaves
        # swapped between containers) recompiles just the same
        sig = hash((jax.tree.structure(args), tuple(
            (getattr(leaf, "shape", None),
             str(getattr(leaf, "dtype", type(leaf).__name__)))
            for leaf in jax.tree.leaves(args))))
        seen = self._retrace_sigs.setdefault(kind, set())
        if not seen or sig in seen:
            # first dispatch, or a specialization jax already compiled and
            # cached (e.g. a short last-epoch batch alternating with the
            # full one) — re-dispatching a cached signature costs nothing
            # and must not re-warn forever
            seen.add(sig)
            return
        self.retrace_count += 1
        msg = (f"RETRACE: the jitted train {kind} saw a new abstract input "
               f"signature at step {self.step_count} — jax will compile "
               "another specialization (multi-second stall now, a silent "
               "step-time cliff if it recurs). Usual causes: a batch "
               "shape/dtype change mid-run, or optimizer-state structure "
               "drift. --retrace_guard off silences; error refuses.")
        if self.cfg.retrace_guard == "error":
            # do NOT adopt the refused signature: a caller that catches and
            # re-dispatches the same shapes must be refused again, not
            # silently recompiled on the retry
            raise RuntimeError(msg)
        seen.add(sig)
        emit(f"[trainer] {msg}")

    def _enforce_events(self, step: int, heal: list, reset_ballot: list,
                        mask_changed: bool) -> None:
        """Act on guard/control-plane transitions against the device
        state: heal momenta from the healthy mean, zero rejoiners' ballot
        history, push the refreshed health mask, and enforce the quorum
        floor. The one place optimizer-state surgery happens — the guard
        and the plane only decide."""
        if heal:
            # healing: the healed worker's momentum restarts at the
            # HEALTHY mean (the vote distribution's center — the same
            # quantity the elastic-resume remap preserves) instead of
            # whatever it drifted or was poisoned to while away
            source = np.array(self._guard.healthy, dtype=bool)
            for w in heal:
                source[w] = False  # a healed worker is not its own source
            exp_avg = heal_worker_momentum(self.state.exp_avg, source, heal)
            exp_avg = jax.device_put(
                exp_avg, jax.tree.map(lambda s: NamedSharding(self.mesh, s),
                                      self._exp_avg_specs))
            self.state = self.state._replace(exp_avg=exp_avg)
        if reset_ballot and self.state.prev_ballot is not None:
            # a rejoiner's frozen-ballot XOR base must not reference a
            # vote it cast before it left; zeros read as 'no real previous
            # election' to the flip detector (flip_valid gates on it)
            prev = jnp.asarray(self.state.prev_ballot)
            for w in reset_ballot:
                prev = prev.at[w].set(0)
            self.state = self.state._replace(prev_ballot=jax.device_put(
                prev, NamedSharding(self.mesh, P(DATA_AXIS))))
        if mask_changed:
            # same shape/dtype as before — no retrace; the next dispatch's
            # elections exclude (or re-include) the flipped workers
            self.state = self.state._replace(health=jax.device_put(
                jnp.asarray(self._guard.healthy),
                NamedSharding(self.mesh, P())))
        if not self._guard.quorum_ok():
            if self.checkpointer:
                # the last good checkpoint must be durable before we refuse
                self.checkpointer.finalize()
            if self._cplane is not None:
                raise RuntimeError(self._cplane.quorum_error(step))
            raise RuntimeError(
                f"vote guard: healthy quorum {self._guard.healthy_count()}/"
                f"{self.world} fell below --min_quorum "
                f"{self._guard.min_quorum} at step {step} — a majority "
                "election with a sick majority is noise, refusing to "
                f"continue. Sick workers: {self._guard.sick_workers()} "
                f"(counters: {self._guard.sick_report()['sick_workers']})")

    def _apply_guard(self, step: int, obs: dict, advanced: int) -> None:
        """Drive the host quarantine machine — or, under --control_plane,
        the unified membership lifecycle — with one dispatch's guard
        observations (device arrays fetched HERE, one dispatch behind — the
        values finished computing long ago, so the get is a cheap copy),
        then act on the transitions via :meth:`_enforce_events`."""
        if not obs:
            return
        host = {k: np.asarray(jax.device_get(v)) for k, v in obs.items()}
        if self._cplane is not None:
            events = self._cplane.observe(step, host, advanced)
            heal, reset_ballot = events.heal, events.reset_ballot
            tag = "control plane"
        else:
            events = self._guard.update(step, host, advanced)
            heal, reset_ballot = events.readmitted, []
            tag = "vote guard"
        for line in events.logs:
            emit(f"[trainer] {tag}: {line}")
        if self.cfg.vote_guard != "enforce":
            return  # observe mode: bookkeeping + logs only
        self._enforce_events(step, heal, reset_ballot, events.mask_changed)

    def _apply_membership(self, step: int) -> None:
        """Consume due membership transitions (injected worker_drop /
        worker_rejoin) at a dispatch boundary, BEFORE the dispatch — so a
        drop scheduled for step s is already masked out of step s+1's
        election (and a step-0 drop out of the very first), and a
        rejoiner's healed momentum enters the very next vote."""
        events = self._cplane.membership_due(step)
        for line in events.logs:
            emit(f"[trainer] control plane: {line}")
        if events.left or events.rejoined or events.mask_changed:
            self._enforce_events(step, events.heal, events.reset_ballot,
                                 events.mask_changed)

    def _check_sentinel(self, step: int, metrics,
                        force_raise: bool = False) -> None:
        """The NaN sentinel's host half: isfinite over the step's loss (and
        pre-clip grad norm). On trip, writes the crash bundle and raises —
        or, under --trace_on_anomaly, arms a profiler window at the
        tripping step first so the poisoned dataflow lands in a trace."""
        if self._anomaly_deadline is not None and not force_raise:
            return  # already tripped; the armed trace window is draining
        vals = {}
        for k in ("loss", "grad_norm"):
            if k in metrics:
                vals[k] = float(np.asarray(jax.device_get(metrics[k])))
        bad = {k: v for k, v in vals.items() if not math.isfinite(v)}
        if not bad:
            return
        reason = ("non-finite " + ", ".join(f"{k}={v!r}"
                                            for k, v in bad.items())
                  + f" at step {step}")
        if self._guard is not None and self._guard.sick_workers():
            # the guard's per-worker health counters feed the sentinel: the
            # trip names the sick WORKER(s), not just the poisoned leaves —
            # a single worker's nonfinite local grad that loses every vote
            # never shows in the global loss, but it shows here
            reason += (" (vote guard sick workers: "
                       f"{self._guard.sick_workers()})")
        emit(f"[trainer] ANOMALY: {reason}")
        crash_dir = None
        if self.cfg.output_dir:
            window = list(self._metrics_window)
            window.append({"step": step, "tripped": True, **{
                k: float(np.asarray(jax.device_get(v)))
                for k, v in metrics.items()}})
            crash_dir = telemetry.write_crash_bundle(
                self.cfg.output_dir, step, reason,
                dataclasses.asdict(self.cfg), self.params, self.state,
                window,
                guard=(self._cplane.report() if self._cplane is not None
                       else self._guard.sick_report()
                       if self._guard is not None else None),
                journal_tail=self.journal.tail())
            emit(f"[trainer] crash bundle written to {crash_dir}")
        if self.cfg.trace_on_anomaly and not force_raise:
            trace_base = crash_dir or self.cfg.profile_dir
            if trace_base:
                # a --profile_dir window may be mid-capture: flush it before
                # swapping profilers, or the anomaly window's start_trace
                # would raise on the still-open jax profiler session
                self.profiler.close(sync=metrics)
                self.profiler = StepProfiler(
                    os.path.join(trace_base, "trace"),
                    start_step=self.step_count,
                    num_steps=self.cfg.profile_num_steps)
                self._anomaly_deadline = (self.step_count
                                          + self.cfg.profile_num_steps + 1)
                self._anomaly_reason = reason
                emit("[trainer] armed anomaly trace window for steps "
                      f"[{self.step_count}, {self._anomaly_deadline - 1})")
                return
        if self.checkpointer:
            # don't die with an async save half-committed: the last good
            # checkpoint must be durable before the anomaly unwinds us
            self.checkpointer.finalize()
        raise FloatingPointError(reason)

    # ------------------------------------------------------------------ steps
    def _build_train_step_core(self):
        cfg = self.cfg
        accum = cfg.gradient_accumulation_steps
        opt = self.opt
        loss_fn = self.loss_fn
        tp_axis = TENSOR_AXIS if dict(self.mesh.shape).get(TENSOR_AXIS, 1) > 1 else None
        param_specs = self.param_specs

        st_specs = _opt_state_specs(cfg, self._exp_avg_specs if cfg.lion else None)

        sp = dict(self.mesh.shape).get(SEQ_AXIS, 1)
        pp = dict(self.mesh.shape).get(PIPE_AXIS, 1)
        ep = dict(self.mesh.shape).get(EXPERT_AXIS, 1)
        has_frozen = self.frozen is not None
        frozen_specs = self.frozen_specs if has_frozen else {}
        telemetry_on = self._telemetry_on
        n_ballot = self._n_ballot
        world = self.world
        nan_sentinel = cfg.nan_sentinel
        guard_on = self._guard is not None
        guard_enforce = guard_on and cfg.vote_guard == "enforce"
        vh_specs = jax.tree.map(lambda _: P(), self.vote_health)
        # --ep_dcn_pipeline d > 0: the loss takes a stale global balance
        # tally (read from LionState.moe_ring pre-scan) and returns this
        # step's fresh local tallies on the metrics dict under the
        # reserved 'moe_tallies' key (popped in-trace below, never logged)
        ring_on = getattr(self.loss_fn, "_wants_moe_balance", False)

        @partial(
            jax.shard_map,
            mesh=self.mesh,
            in_specs=(self.param_specs, st_specs, vh_specs, frozen_specs,
                      self.batch_spec, P()),
            out_specs=(self.param_specs, st_specs, vh_specs, P()),
            check_vma=False,
        )
        def step(params, state, vh, frozen, batch, base_key):
            call_loss = ((lambda p, b, k, *a: loss_fn(p, frozen, b, k, *a))
                         if has_frozen else loss_fn)
            stale_balance, ring, ring_slot = None, None, None
            if ring_on:
                # this data worker's ring of in-flight global tallies:
                # slot (count mod depth) was written at step count − depth
                # — read it now (the d-step-stale balance the aux
                # consumes), overwrite it with this step's fresh tally
                # after the backward. All-zero slots (cold start) make
                # moe_ffn fall back to the fresh local aux.
                ring = state.moe_ring[0]  # [depth, n_moe, E+1]
                ring_slot = lax.rem(_count_of(state),
                                    jnp.int32(ring.shape[0]))
                stale_balance = lax.dynamic_index_in_dim(
                    ring, ring_slot, 0, keepdims=False)
            # each batch leaf: [accum * local_bs, ...] → [accum, local_bs, ...]
            local = jax.tree.map(
                lambda b: b.reshape((accum, -1) + b.shape[1:]), batch
            )
            widx = lax.axis_index(DATA_AXIS)
            key = jax.random.fold_in(jax.random.fold_in(base_key, widx), _count_of(state))
            if ep > 1:
                # expert ranks hold different batch rows → distinct dropout keys
                key = jax.random.fold_in(key, lax.axis_index(EXPERT_AXIS))

            def micro(gsum, inp):
                microbatch, i = inp
                extra = (stale_balance,) if ring_on else ()
                (loss, metrics), g = jax.value_and_grad(
                    call_loss, has_aux=True
                )(params, microbatch, jax.random.fold_in(key, i), *extra)
                gsum = jax.tree.map(jnp.add, gsum, g)
                return gsum, metrics

            zeros = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
            gsum, metrics = lax.scan(micro, zeros, (local, jnp.arange(accum)))
            grads = jax.tree.map(lambda g: g / accum, gsum)

            new_moe_ring = None
            if ring_on:
                # pop the reserved tally key BEFORE the scalarizing pmean
                # below; counts ADD across microbatches, then the expert
                # axis psum makes them global. No DATA-axis collective —
                # each data worker launches its own batch's tally into its
                # own ring row (async_grad's contract: the vote stays the
                # only optimizer collective).
                fresh = metrics.pop("moe_tallies").sum(axis=0)
                if ep > 1:
                    fresh = lax.psum(fresh, EXPERT_AXIS)
                new_moe_ring = ring.at[ring_slot].set(fresh)

            if sp > 1:
                # sequence parallelism: each seq shard computed the grad of
                # ITS tokens' loss term (normalized by the global token
                # count) — the full gradient is their sum.
                grads = lax.psum(grads, SEQ_AXIS)
            for ax, deg in ((PIPE_AXIS, pp), (EXPERT_AXIS, ep)):
                if deg <= 1:
                    continue
                # Leaves SHARDED over this axis carry complete local grads
                # (pipe: each stage owns its blocks; expert: the all_to_all
                # transpose already routed cross-shard cotangents home).
                # REPLICATED leaves carry per-shard partials — pipe: disjoint
                # stage contributions (stage-0 embedding, last-stage logits
                # tie); expert: per-row loss terms normalized by the global
                # token count — whose psum is the full gradient.
                from distributed_lion_tpu.parallel.tensor_parallel import (
                    spec_uses_axis,
                )

                flat_g, gdef = jax.tree.flatten(grads)
                flat_s = gdef.flatten_up_to(param_specs)
                flat_g = [
                    g if spec_uses_axis(s, ax) else lax.psum(g, ax)
                    for g, s in zip(flat_g, flat_s)
                ]
                grads = jax.tree.unflatten(gdef, flat_g)
            if not cfg.async_grad:
                # classic DDP all-reduce; the reference's non-async path.
                grads = lax.pmean(grads, DATA_AXIS)
            # else: no gradient sync — the AsyncTrainer contract
            # (async_trainer.py:15). The ONLY collective is the vote in
            # opt.step.
            poison = resilience.fault("ballot_poison")
            if poison is not None:
                # ballot-poisoning fault injection, baked in at trace time
                # (train/resilience registry): worker `pw` becomes a sick
                # voter from optimizer step `ps` on — NaN grads (poisons
                # momentum + votes −1 everywhere), zero grads (its ballot
                # freezes at sign(m)), or negated grads (its momentum and
                # ballot become the exact inverse — an adversarial voter)
                kind, pw, ps = poison
                hit = (widx == pw) & (_count_of(state) >= ps)
                if kind == "nan_grads":
                    grads = jax.tree.map(
                        lambda g: jnp.where(
                            hit, jnp.asarray(jnp.nan, g.dtype), g), grads)
                elif kind == "frozen_ballot":
                    grads = jax.tree.map(
                        lambda g: jnp.where(hit, jnp.zeros_like(g), g),
                        grads)
                else:  # flipped_ballot
                    grads = jax.tree.map(
                        lambda g: jnp.where(hit, -g, g), grads)
            shard_axes = tuple(a for a, flag in
                               ((TENSOR_AXIS, tp_axis is not None),
                                (PIPE_AXIS, pp > 1),
                                (EXPERT_AXIS, ep > 1)) if flag)
            gnorm = None
            if nan_sentinel:
                # pre-clip global norm (clipping would mask the explosion
                # the sentinel exists to catch); same exact cross-axis sum
                # the clipper uses, then meaned over workers for logging
                gsq = global_grad_sq(grads, specs=param_specs,
                                     shard_axes=shard_axes)
                if guard_enforce:
                    # degraded-mode training: one worker's nonfinite LOCAL
                    # grad must not poison the pmean'd metric and trip the
                    # sentinel on a run the guard is keeping healthy — the
                    # norm averages the finite workers, and the sick one is
                    # named through the guard's own counters instead
                    finite = jnp.isfinite(gsq)
                    gnorm = jnp.sqrt(
                        lax.psum(jnp.where(finite, gsq, 0.0), DATA_AXIS)
                        / jnp.maximum(
                            lax.psum(finite.astype(jnp.float32), DATA_AXIS),
                            1.0))
                else:
                    gnorm = jnp.sqrt(lax.pmean(gsq, DATA_AXIS))
            clip = (cfg.grad_clip_norm if cfg.grad_clip_norm is not None
                    else cfg.max_grad_norm)
            if clip:
                # per-worker clip (grads are local in async mode; in DDP mode
                # this runs on the already-averaged grads, matching HF Trainer
                # clipping after the all-reduce). Under TP/PP the grads of
                # sharded leaves get their norms psum'd across that axis so
                # every rank derives the same scale.
                grads = clip_by_global_norm(grads, clip, specs=param_specs,
                                            shard_axes=shard_axes)
            if cfg.lion:
                st = squeeze_worker_state(state)
            elif cfg.zero1:
                st = squeeze_zero_state(state)
            else:
                st = state
            outs = opt.step(params, grads, st)
            new_params, new_st = outs[0], outs[1]
            extra = list(outs[2:])
            if telemetry_on:
                # the optimizer emits the per-step vote-health frame; fold
                # it into the replicated accumulator on device (the only
                # additions are two scalar psums — no host traffic, and the
                # election itself is untouched)
                vh = telemetry.fold(vh, extra.pop(0), DATA_AXIS, world,
                                    n_ballot)
            gframe = extra.pop(0) if guard_on else None
            if cfg.lion:
                new_state = expand_worker_state(new_st)
            elif cfg.zero1:
                new_state = expand_zero_state(new_st)
            else:
                new_state = new_st
            if new_moe_ring is not None:
                # the optimizer's step passes the balance ring through
                # untouched (it constructs its result state without it) —
                # re-attach this step's launch here, re-stacked [1, ...]
                new_state = new_state._replace(moe_ring=new_moe_ring[None])

            mean_metrics = {k: lax.pmean(v.mean(), DATA_AXIS) for k, v in metrics.items()}
            if gnorm is not None:
                mean_metrics["grad_norm"] = gnorm
            if gframe is not None:
                # the guard's per-dispatch observations ride the metrics
                # dict as replicated [W] vectors under the reserved
                # 'guard_*' names; the trainer pops them before logging and
                # feeds the host quarantine machine one dispatch behind
                # (vote_guard.OBS_KEYS). Frozen = a (re)vote with ZERO
                # ballot bit flips against a REAL previous election.
                frozen = ((gframe["flips"] == 0) & gframe["flip_valid"]
                          & (gframe["voted"] > 0))
                mean_metrics["guard_nonfinite"] = (
                    gframe["nonfinite"] > 0).astype(jnp.int32)
                mean_metrics["guard_frozen"] = frozen.astype(jnp.int32)
                mean_metrics["guard_disagree"] = gframe["disagree"]
                mean_metrics["guard_voted_steps"] = (
                    gframe["voted"] > 0).astype(jnp.int32)
            return new_params, new_state, vh, mean_metrics

        return step

    def _build_train_chunk(self):
        """K optimizer steps per device dispatch: ``lax.scan`` of the train
        step over a staged ``[K, global_batch, ...]`` batch stack. One
        host→device round trip per K steps instead of per step."""
        step = self._train_step_core

        def chunk(params, state, vh, frozen, batches, base_key):
            def body(carry, batch):
                p, s, v = carry
                p, s, v, m = step(p, s, v, frozen, batch, base_key)
                return (p, s, v), m

            (params, state, vh), ms = lax.scan(body, (params, state, vh),
                                               batches)
            # per-chunk mean for logging (loss of the last step alone would
            # alias a single microbatch draw); the guard's 'guard_*'
            # observations are bad-step COUNTS and summed fractions — they
            # sum over the chunk so the host strike counter sees every step
            return params, state, vh, {
                k: (v.sum(0) if k.startswith("guard_") else v.mean(0))
                for k, v in ms.items()}

        return chunk

    def _build_eval_step(self):
        loss_fn = self.loss_fn
        has_frozen = self.frozen is not None
        frozen_specs = self.frozen_specs if has_frozen else {}

        @partial(
            jax.shard_map,
            mesh=self.mesh,
            in_specs=(self.param_specs, frozen_specs, self.batch_spec),
            out_specs=P(),
            check_vma=False,
        )
        def step(params, frozen, batch):
            loss, metrics = (loss_fn(params, frozen, batch, None) if has_frozen
                             else loss_fn(params, batch, None))
            return {k: lax.pmean(v, DATA_AXIS) for k, v in metrics.items()}

        return jax.jit(step)

    # ------------------------------------------------------------- train/eval
    def global_train_batch(self) -> int:
        return (self.batch_shards * self.cfg.per_device_train_batch_size
                * self.cfg.gradient_accumulation_steps)

    def train(
        self,
        train_iter: Iterator[np.ndarray],
        eval_blocks: Optional[np.ndarray] = None,
        max_steps: Optional[int] = None,
    ) -> list[dict]:
        """Run the step-based training loop (the reference trains by
        max_steps, README.md:25). ``train_iter`` yields
        [world*accum*per_device_bs, block] token batches."""
        cfg = self.cfg
        total = min(cfg.max_steps, self.step_count + max_steps if max_steps else cfg.max_steps)
        history = []
        data_spec = NamedSharding(self.mesh, self.batch_spec)
        base_key = jax.random.key(cfg.seed + 1)
        tokens_per_step = self.global_train_batch() * cfg.block_size
        # After resume, fast-forward the (deterministically seeded) data
        # iterator past the batches the checkpointed run consumed, so a
        # resumed run sees the same data a continuous run would. Seekable
        # iterators (data.sources.BatchIterator, the native loader) skip by
        # index arithmetic — no data reads; plain generators are replayed.
        if self._resume_skip_batches:
            if hasattr(train_iter, "skip"):
                train_iter.skip(self._resume_skip_batches)
            else:
                for _ in range(self._resume_skip_batches):
                    next(train_iter)
            self._resume_skip_batches = 0
        t_last, s_last = time.time(), self.step_count
        chunk_spec = NamedSharding(self.mesh, P(None, *self.batch_spec))
        jr = self.journal  # journal.NULL when --journal is off: every span
        # below is a no-op, and the loop body is byte-identical in behavior
        jr.event("train_start", step=self.step_count, total=int(total))

        while self.step_count < total:
            if self._cplane is not None:
                # membership transitions land at dispatch boundaries: a
                # due drop is masked out of the NEXT election, a due
                # rejoin is healed before it votes again
                self._apply_membership(self.step_count)
            self.profiler.maybe_start(self.step_count)
            k = min(self.cfg.steps_per_call, total - self.step_count)
            advanced = k
            if k == self.cfg.steps_per_call and k > 1:
                # fused K-step dispatch; the tail below K runs step-by-step
                # (avoids a second jit specialization for the remainder)
                with jr.span("data_wait", step=self.step_count, steps=k):
                    stack = [next(train_iter) for _ in range(k)]
                    self._measure_wire_once(stack[0])
                    batches = jax.device_put(
                        jax.tree.map(lambda *xs: np.stack(xs), *stack),
                        chunk_spec)
                self._check_retrace("chunk", self.params, self.state,
                                    self.vote_health, self._frozen_arg(),
                                    batches)
                with self.profiler.annotate(self.step_count), \
                        jr.span("dispatch", step=self.step_count, steps=k):
                    (self.params, self.state, self.vote_health,
                     metrics) = self._train_chunk(
                        self.params, self.state, self.vote_health,
                        self._frozen_arg(), batches, base_key
                    )
                self.step_count += k
                self.timer.tick(k)
            else:
                with jr.span("data_wait", step=self.step_count, steps=1):
                    raw_batch = next(train_iter)
                    self._measure_wire_once(raw_batch)
                    batch = jax.device_put(raw_batch, data_spec)
                self._check_retrace("step", self.params, self.state,
                                    self.vote_health, self._frozen_arg(),
                                    batch)
                with self.profiler.annotate(self.step_count), \
                        jr.span("dispatch", step=self.step_count, steps=1):
                    (self.params, self.state, self.vote_health,
                     metrics) = self._train_step(
                        self.params, self.state, self.vote_health,
                        self._frozen_arg(), batch, base_key
                    )
                self.step_count += 1
                self.timer.tick()
                advanced = 1
            self.profiler.maybe_stop(self.step_count, sync=metrics)
            if self._guard is not None:
                # pop the guard's [W]-vector observations before anything
                # host-floats the metrics dict; the machine runs one
                # dispatch behind (same pattern as the sentinel) so the
                # device pipeline never stalls on the host read
                obs = {k: metrics.pop(k) for k in vote_guard.OBS_KEYS
                       if k in metrics}
                if self._guard_pending is not None:
                    self._apply_guard(*self._guard_pending)
                self._guard_pending = (self.step_count, obs, advanced)
            if cfg.nan_sentinel:
                # trailing isfinite watch: the PREVIOUS dispatch's metrics
                # are checked after this one is in flight, so the device
                # pipeline stays full while anomalies are still caught one
                # dispatch late (the bundle names the tripping step)
                if self._sentinel_pending is not None:
                    self._check_sentinel(*self._sentinel_pending)
                self._sentinel_pending = (self.step_count, metrics)
            if (self._anomaly_deadline is not None
                    and self.step_count >= self._anomaly_deadline):
                # trace_on_anomaly: the armed window has captured its steps
                self.profiler.maybe_stop(self.step_count, sync=metrics)
                if self.checkpointer:
                    self.checkpointer.finalize()
                raise FloatingPointError(self._anomaly_reason)

            # boundary tests are "crossed a multiple of N during this
            # dispatch" so chunked advances never skip a log/eval/save
            if self.step_count % cfg.logging_steps < advanced or self.step_count == total:
                if self.cfg.journal:
                    # the ONE device drain the loop already pays per log
                    # interval (the host-float below blocks on it either
                    # way) made explicit, so the journal sees device-bound
                    # time as a span instead of smearing it into the
                    # logging bucket — no sync is added that the float()
                    # conversions were not about to perform
                    with jr.span("device_wait", step=self.step_count):
                        jax.block_until_ready(metrics)
                _t_log = time.monotonic()
                m = {k: float(v) for k, v in metrics.items()}
                now = time.time()
                steps_per_sec = (self.step_count - s_last) / max(now - t_last, 1e-9)
                m["tokens_per_sec"] = tokens_per_step * steps_per_sec
                # the step just executed ran with optimizer count step_count-1
                m["lr"] = float(self._schedule(jnp.asarray(self.step_count - 1, jnp.float32)))
                m.update(self.timer.stats())
                comm = self.comm_stats(steps_per_sec)
                if comm:
                    m["comm_bytes_per_step"] = comm["comm_bytes_per_step"]
                    m["comm_mbytes_per_sec"] = comm.get("comm_mbytes_per_sec", 0.0)
                    # analytic pipelineable wire share under vote_buckets
                    # (profiling.comm_report); the measured counterpart is
                    # bench.py's overlap-ablation comm_overlap_frac
                    m["comm_overlap_frac"] = comm.get("comm_overlap_frac", 0.0)
                    if "dcn_overlap_frac" in comm:
                        # analytic share of the hier wire's level-2 latency
                        # off the critical path under --dcn_pipeline_depth;
                        # measured counterpart: bench_dcn's depth ablation
                        m["dcn_overlap_frac"] = comm["dcn_overlap_frac"]
                from distributed_lion_tpu.parallel.collectives import (
                    DCN_WAIT,
                )

                dcn_waits = DCN_WAIT.pop()
                if dcn_waits:
                    # the emulated DCN link's measured residual (unhidden)
                    # wait this interval — nonzero only under the dcn_delay
                    # fault (train/resilience registry); sub-delay values
                    # are the cross-step pipeline visibly hiding the leg
                    wait_s = sum(dcn_waits.values())
                    m["dcn_wait_s"] = wait_s
                    if self.cfg.journal:
                        # thread-tagged: the wait happened inside the
                        # device program (run_analyze excludes it from
                        # step-thread attribution — it overlaps dispatch)
                        jr.record({"kind": "span", "name": "dcn_wait",
                                   "dur": round(wait_s, 9),
                                   "step": self.step_count,
                                   "thread": "dcn-link"})
                hbm = peak_hbm_gb()
                if hbm is not None:
                    m["peak_hbm_gb"] = hbm
                if self.checkpointer:
                    # seconds the loop spent blocked on checkpointing since
                    # the last log — async saves keep this near 0 while the
                    # sync path pays the full serialize+write here
                    m["ckpt_stall_s"] = self.checkpointer.pop_stall_s()
                if self.retrace_count:
                    # recompilations the retrace guard observed (should stay
                    # 0 for the whole run; see --retrace_guard)
                    m["retraces"] = self.retrace_count
                if self._telemetry_on:
                    # drain the on-device accumulator (the interval's ONLY
                    # telemetry host transfer) and reset its counters; the
                    # previous election carries over so flip rates stay
                    # continuous across intervals
                    vote = telemetry.drain(self.vote_health,
                                           self._margin_exact)
                    self.vote_health = telemetry.reset_counters(
                        self.vote_health)
                    m.update({f"vote/{k}": v for k, v in vote.items()})
                    if self._wire_measured:
                        mw = self._wire_measured
                        m["comm_measured_bytes_per_step"] = mw[
                            "bytes_per_step"]
                        m["comm_measured_calls_per_step"] = mw[
                            "calls_per_step"]
                        if mw.get("dcn_bytes_per_step"):
                            m["comm_measured_dcn_bytes_per_step"] = mw[
                                "dcn_bytes_per_step"]
                        if comm:
                            # analytic-vs-measured drift, a first-class
                            # metric: 0 unless the accounting and the
                            # collectives have diverged
                            m["comm_drift_bytes"] = (
                                mw["bytes_per_step"]
                                - comm["comm_bytes_per_step"])
                    skew = telemetry.host_step_skew(self.step_count)
                    if skew is not None:
                        m["host_step_skew"] = skew
                    per_dev = peak_hbm_per_device()
                    if per_dev is not None and len(per_dev) > 1:
                        m["peak_hbm_per_device"] = per_dev
                if self._guard is not None:
                    # scalar guard health for the record stream (the [W]
                    # observation vectors were popped above)
                    m.update(self._guard.summary())
                if self._cplane is not None:
                    m.update(self._cplane.summary())
                if hasattr(train_iter, "health_metrics"):
                    # input-pipeline health (e.g. the native loader's
                    # skipped_shards / shard_read_retries counters) rides
                    # the same strict-JSON metrics stream
                    m.update(train_iter.health_metrics())
                t_last, s_last = now, self.step_count
                self.logger.log(self.step_count, m, prefix="train")
                self._metrics_window.append({"step": self.step_count, **m})
                history.append({"step": self.step_count, **m})
                if self.cfg.journal:
                    # the multi-host step-skew heartbeat becomes a journal
                    # event (PR 2 only PRINTED it, and only under
                    # --telemetry): run_analyze derives cross-host skew
                    # percentiles from these per-rank step_log records
                    jskew = (m.get("host_step_skew") if self._telemetry_on
                             else telemetry.host_step_skew(self.step_count))
                    jr.event("step_log", step=self.step_count,
                             steps_per_sec=round(steps_per_sec, 6),
                             **({} if jskew is None
                                else {"skew_steps": int(jskew)}))
                    # everything since the device drain — metric assembly,
                    # telemetry drain, the strict-JSON write — is the
                    # logging tax
                    jr.record({"kind": "span", "name": "logging_drain",
                               "dur": round(time.monotonic() - _t_log, 9),
                               "step": self.step_count})
                    jr.flush()

            if eval_blocks is not None and self.step_count % cfg.eval_steps < advanced:
                with jr.span("eval", step=self.step_count):
                    history.append({"step": self.step_count,
                                    **self.evaluate(eval_blocks)})

            if self.checkpointer and self.step_count % cfg.save_steps < advanced:
                self.save()

            if (self._preempt_guard is not None
                    and self._preempt_guard.should_stop()):
                # preemption drain: flag was set by SIGTERM/maintenance;
                # checked once per dispatch so we act at a consistent
                # boundary. Drain the in-flight async save, make the
                # emergency checkpoint durable, and return cleanly — the
                # caller exits 0 and the watcher restarts into a resume.
                if self._cplane is not None:
                    # the one membership stream records the departure too:
                    # a preempted process is every local worker leaving
                    self._cplane.note_preempt(self.step_count)
                if self.checkpointer:
                    emit(f"[trainer] preemption at step {self.step_count}:"
                          " draining in-flight save, writing emergency "
                          "checkpoint")
                    self.save(tag="preempt")
                    self.checkpointer.finalize()
                else:
                    emit(f"[trainer] preemption at step {self.step_count}:"
                          " no output_dir — NOTHING SAVED; a restart "
                          "begins from step 0")
                self.preempted = True
                break
        if self._guard is not None and self._guard_pending is not None:
            # the final dispatch's guard observations are still pending;
            # fold them so the machine's counters (and any quorum refusal)
            # cover the whole run — and so a sentinel bundle written just
            # below names the sick workers from complete evidence
            pending, self._guard_pending = self._guard_pending, None
            self._apply_guard(*pending)
        if cfg.nan_sentinel and self._sentinel_pending is not None:
            # the final dispatch's metrics were still awaiting their check
            pending, self._sentinel_pending = self._sentinel_pending, None
            self._check_sentinel(*pending, force_raise=True)
        jr.event("train_end", step=self.step_count,
                 preempted=bool(self.preempted))
        jr.flush()
        return history

    def evaluate(self, eval_blocks: np.ndarray) -> dict:
        """Eval loss / token accuracy / perplexity=exp(loss)
        (run_clm.py:630-636)."""
        cfg = self.cfg
        n_examples = len(jax.tree.leaves(eval_blocks)[0])
        per_dev = cfg.per_device_eval_batch_size
        # under pipelining the local batch must split into GPipe microbatches
        # (pp from the mesh, like the train step — cfg.pipeline_parallel is
        # only the CLI's mesh-building input)
        pp = dict(self.mesh.shape).get(PIPE_AXIS, 1)
        div = (cfg.pipeline_microbatches or pp) if pp > 1 else 1
        if n_examples < self.batch_shards * per_dev:
            # shrink rather than silently skipping eval on small validation
            # splits (jit re-specializes on the new shape)
            per_dev = max(div, n_examples // self.batch_shards // div * div)
        bs = self.batch_shards * per_dev
        if n_examples < bs:
            emit(f"[trainer] eval skipped: {n_examples} examples < "
                  f"{self.batch_shards} batch shards")
            return {"eval/loss": float("nan"), "eval/accuracy": float("nan"),
                    "eval/perplexity": float("nan")}
        data_spec = NamedSharding(self.mesh, self.batch_spec)
        per_key: dict = {}
        n_batches = min(cfg.eval_iters, n_examples // bs)
        for i in range(n_batches):
            batch = jax.device_put(
                jax.tree.map(
                    lambda x: np.ascontiguousarray(x[i * bs : (i + 1) * bs]), eval_blocks
                ),
                data_spec,
            )
            m = self._eval_step(self.params, self._frozen_arg(), batch)
            for k, v in m.items():
                per_key.setdefault(k, []).append(float(v))
        # aggregate EVERY metric the loss_fn reports (CLM: loss/accuracy/
        # n_tokens; DPO: loss/reward_accuracy/reward_margin; custom: anything)
        out = {f"eval/{k}": float(np.mean(v)) for k, v in per_key.items() if k != "n_tokens"}
        loss = out.get("eval/loss", float("nan"))
        if "n_tokens" in per_key:  # token-level LM loss → perplexity applies
            out["eval/perplexity"] = float(np.exp(min(loss, 80.0)))
        self.logger.log(self.step_count, out, prefix="")
        return out

    # ------------------------------------------------------------ checkpoints
    @staticmethod
    def _pack_state_rng(state):
        """Typed PRNG keys are not serializable (Orbax sees an opaque
        key dtype); store the raw key data and re-wrap on restore. A
        stochastic-binarization checkpoint without this loses its RNG —
        save simply failed before the resilience PR."""
        rng = getattr(state, "rng", None)
        if rng is None or not jnp.issubdtype(rng.dtype, jax.dtypes.prng_key):
            return state
        return state._replace(rng=jax.random.key_data(rng))

    @staticmethod
    def _unpack_state_rng(state):
        rng = getattr(state, "rng", None)
        if rng is None or jnp.issubdtype(rng.dtype, jax.dtypes.prng_key):
            return state
        return state._replace(rng=jax.random.wrap_key_data(rng))

    def _payload(self, world: Optional[int] = None):
        # 0-d ndarray, not np.int64 scalar: older orbax StandardCheckpointHandler
        # versions only accept ndarray/jax.Array leaves
        payload = {"params": self.params,
                   "opt_state": self._pack_state_rng(self.state),
                   "step": np.asarray(self.step_count, np.int64),
                   # data-iterator position (1 batch per step) and the world
                   # size the momenta were stacked at, explicit in the
                   # payload so resume doesn't have to infer either
                   "batches_consumed": np.asarray(self.step_count, np.int64),
                   "world": np.asarray(world or self.world, np.int64)}
        if self._telemetry_on:
            # the vote-health accumulator rides the checkpoint so flip
            # rates / histograms stay continuous across a restart
            payload["vote_health"] = self.vote_health
        return payload

    def save(self, tag: str = "periodic") -> None:
        assert self.checkpointer is not None
        if self.checkpointer.latest_step() == self.step_count:
            return  # already saved at this step (e.g. final save on a save_steps boundary)
        meta = {"world": self.world, "tag": tag,
                "step": self.step_count,
                "batches_consumed": self.step_count,
                "has_vote_health": self._telemetry_on,
                "has_guard": self._guard is not None,
                "wire": self.cfg.wire, "vote_every": self.cfg.vote_every,
                "dcn_pipeline_depth": self.cfg.dcn_pipeline_depth,
                "ep_dcn_pipeline": int(self.cfg.ep_dcn_pipeline or 0),
                "control_plane": self._cplane is not None,
                **self.data_meta}
        if self._cplane is not None:
            # mid-run membership survives the restart: the mask itself
            # rides LionState.health, but departed-vs-quarantined is plane
            # state — without this stamp a resume would auto-readmit a
            # worker the run knew was GONE
            meta["cp_departed"] = sorted(
                int(w) for w in self._cplane.departed)
            # the consumed-schedule watermark: a resume must not replay
            # drop/rejoin entries this run already acted on
            meta["cp_sched_through"] = int(self._cplane.sched_through)
            # probation windows + quarantine history: a crash mid-probation
            # must resume the probe-fail rule (a still-sick rejoiner
            # departs again), not fall back to the cooldown cycle
            meta["cp_rejoining_until"] = [
                int(x) for x in self._cplane.rejoining_until]
            meta["cp_quarantine_counts"] = [
                int(x) for x in self._cplane.quarantine_counts]
        self.checkpointer.save(self.step_count, self._payload(), meta=meta)

    def _with_guard_fields(self, tpl: dict, on: bool,
                           world: Optional[int] = None) -> dict:
        """Shape a restore template's opt_state for a checkpoint WITH or
        WITHOUT the vote-guard state (Orbax rejects templates missing — or
        mis-shaping — a saved key, so the manifest's has_guard stamp
        decides, not this run's flags). ``world`` sizes the stacked
        prev-ballot / mask for the elastic path."""
        w = world or self.world
        out = dict(tpl)
        if not on:
            out["opt_state"] = out["opt_state"]._replace(
                health=None, prev_ballot=None)
            return out
        from distributed_lion_tpu.optim.distributed_lion import (
            _guard_ballot_len,
        )

        blen = _guard_ballot_len(self.n_params, self.cfg.vote_every or 1)
        out["opt_state"] = out["opt_state"]._replace(
            health=jax.ShapeDtypeStruct(
                (w,), jnp.bool_,
                sharding=NamedSharding(self.mesh, P())),
            prev_ballot=jax.ShapeDtypeStruct(
                (w, blen), jnp.uint8,
                sharding=NamedSharding(
                    self.mesh,
                    P(DATA_AXIS) if w % self.world == 0 else P())),
        )
        return out

    def _fresh_guard_state(self):
        """(health, prev_ballot) reinitialized for THIS run's world — used
        when a checkpoint carries no guard state (or an incompatible one)
        but the guard is on."""
        from distributed_lion_tpu.optim.distributed_lion import (
            _guard_ballot_len,
        )

        blen = _guard_ballot_len(self.n_params, self.cfg.vote_every or 1)
        return (
            jax.device_put(jnp.ones((self.world,), jnp.bool_),
                           NamedSharding(self.mesh, P())),
            jax.device_put(jnp.zeros((self.world, blen), jnp.uint8),
                           NamedSharding(self.mesh, P(DATA_AXIS))),
        )

    def _vote_health_template(self, ckpt_vote_every: int):
        """A restore template for the checkpoint's vote_health accumulator,
        sized by the CHECKPOINT's vote_every (prev_elected's packed length
        depends on it) — the current config's value may differ, in which
        case the restored accumulator is discarded after restore. The
        template must still match what was saved: Orbax rejects templates
        missing (or mis-shaping) a saved key."""
        return jax.device_put(
            telemetry.init_vote_health(self.n_params, ckpt_vote_every),
            NamedSharding(self.mesh, P()))

    def _elastic_template(self, ckpt_world: int, meta: dict):
        """Restore template for a checkpoint stacked at a DIFFERENT world
        size: momentum leaves get a [ckpt_world, ...] leading dim. Params
        restore straight into their real shardings (same shapes at any
        world); the momentum stack shards its leading axis over 'data'
        whenever ckpt_world divides by the current world — only the
        non-divisible upscale case (e.g. 2→4) falls back to replicated
        restore, the one shape the mesh can't split evenly."""
        repl = NamedSharding(self.mesh, P())

        def _repl(x):
            if isinstance(x, jax.Array):
                return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=repl)
            return x

        tpl = jax.tree.map(_repl, self._payload())
        tpl["params"] = jax.tree.map(
            lambda p, s: jax.ShapeDtypeStruct(
                p.shape, p.dtype, sharding=NamedSharding(self.mesh, s)),
            self.params, self.param_specs)
        # shape the vote_health slot to what the CHECKPOINT holds (it is
        # restored then discarded — its normalizations reference the old
        # world, so the telemetry window restarts fresh after remap)
        tpl.pop("vote_health", None)
        if meta.get("has_vote_health"):
            tpl["vote_health"] = self._vote_health_template(
                int(meta.get("vote_every", 1)) or 1)
        mom_shard = (NamedSharding(self.mesh, P(DATA_AXIS))
                     if ckpt_world % self.world == 0 else repl)
        tpl["opt_state"] = tpl["opt_state"]._replace(
            exp_avg=jax.tree.map(
                lambda m: jax.ShapeDtypeStruct(
                    (ckpt_world,) + m.shape[1:], m.dtype,
                    sharding=mom_shard),
                tpl["opt_state"].exp_avg),
        )
        # guard fields sized by the CHECKPOINT's world (the meta stamp
        # decides presence, like vote_health); the restored mask drives the
        # healthy-only momentum heal below, then both reinit at W'
        tpl = self._with_guard_fields(tpl, bool(meta.get("has_guard")),
                                      world=ckpt_world)
        return tpl

    def _adopt_guard_state(self, step: int, meta: Optional[dict] = None) -> None:
        """Reconcile the restored state's guard fields with THIS run's
        guard flag: adopt a checkpointed health mask exactly (quarantined
        workers resume quarantined, cooldown restarting at the resumed
        step), attach fresh guard state when the checkpoint predates the
        guard, strip it when the guard is off now. Under --control_plane
        the manifest meta's ``cp_departed`` stamp restores the
        departed-vs-quarantined distinction (a control-plane toggle in
        either direction is tolerated like the guard toggle: a plane-off
        resume degrades departed workers to plain quarantine, a plane-on
        resume of a plane-off checkpoint starts with nobody departed)."""
        st = self.state
        if self._guard is not None:
            if st.health is None or st.prev_ballot is None:
                health, prev = self._fresh_guard_state()
                self.state = st._replace(health=health, prev_ballot=prev)
            else:
                mask = np.asarray(jax.device_get(st.health), dtype=bool)
                if self._cplane is not None:
                    m = meta or {}
                    self._cplane.adopt(
                        mask, step,
                        departed=m.get("cp_departed"),
                        sched_through=m.get("cp_sched_through"),
                        rejoining_until=m.get("cp_rejoining_until"),
                        quarantine_counts=m.get("cp_quarantine_counts"))
                    lc = self._cplane.lifecycle()
                    if not mask.all():
                        emit("[trainer] control plane: resumed with "
                             "lifecycle "
                             f"{dict((w, s) for w, s in enumerate(lc) if s != 'healthy')}"
                             f" at step {step}")
                else:
                    self._guard.adopt_mask(mask, step)
                    if not mask.all():
                        emit("[trainer] vote guard: resumed with "
                             "quarantined workers "
                             f"{[int(w) for w in np.nonzero(~mask)[0]]}"
                             f" (cooldown restarts at step {step})")
        elif st.health is not None or st.prev_ballot is not None:
            self.state = st._replace(health=None, prev_ballot=None)

    def _restore_step(self, step: int, meta: dict, ckpt_world: int) -> None:
        ckpt_ve = int(meta.get("vote_every", self.cfg.vote_every or 1)) or 1
        if ckpt_world == self.world:
            tpl = self._payload()
            # shape the template to what the checkpoint actually holds —
            # Orbax rejects templates missing (or mis-shaping) a saved key,
            # so the meta's has_vote_health/vote_every stamps decide the
            # vote_health slot, not the current run's flags
            has_vh = meta.get("has_vote_health")
            if has_vh is False:
                tpl.pop("vote_health", None)
            elif has_vh:
                tpl["vote_health"] = self._vote_health_template(ckpt_ve)
            tries = [tpl]
            if has_vh is None:
                # no manifest meta (--ckpt_integrity false / legacy dir):
                # the checkpoint's vote_health presence is unknown, so a
                # telemetry-flag toggle between save and resume would brick
                # the first template — also try the opposite shape
                alt = dict(tpl)
                if "vote_health" in alt:
                    alt.pop("vote_health")
                else:
                    alt["vote_health"] = self._vote_health_template(ckpt_ve)
                tries.append(alt)
            if self.cfg.lion:
                # guard-state presence follows the same stamp logic: the
                # manifest's has_guard decides the template's shape; with
                # no meta, try this run's shape first, then the opposite
                # (a --vote_guard toggle between save and resume)
                has_guard = meta.get("has_guard")
                cur_guard = self._guard is not None
                if has_guard is None:
                    tries = ([self._with_guard_fields(t, cur_guard)
                              for t in tries]
                             + [self._with_guard_fields(t, not cur_guard)
                                for t in tries])
                else:
                    tries = [self._with_guard_fields(t, bool(has_guard))
                             for t in tries]
            # pre-resilience checkpoints lack the world/batches_consumed/
            # vote_health keys entirely; the legacy payload shape last
            legacy_state = self._pack_state_rng(self.state)
            if self.cfg.lion:
                legacy_state = legacy_state._replace(health=None,
                                                     prev_ballot=None,
                                                     dcn_ring=None,
                                                     moe_ring=None)
            tries.append({"params": self.params,
                          "opt_state": legacy_state,
                          "step": np.asarray(self.step_count, np.int64)})
            restored = None
            for i, t in enumerate(tries):
                try:
                    restored = self.checkpointer.restore(step, t)
                    break
                except Exception:
                    if i == len(tries) - 1:
                        raise
            self.params = restored["params"]
            self.state = self._unpack_state_rng(restored["opt_state"])
            if self.cfg.lion:
                self._adopt_guard_state(step, meta)
            if ("vote_health" in restored and self._telemetry_on
                    and ckpt_ve == (self.cfg.vote_every or 1)):
                # adopt the accumulator only when its packing still matches
                # this run (vote_every sizes prev_elected); otherwise the
                # telemetry window restarts fresh
                self.vote_health = restored["vote_health"]
        else:
            restored = self.checkpointer.restore(
                step, self._elastic_template(ckpt_world, meta))
            self.params = jax.tree.map(
                lambda p, s: jax.device_put(p, NamedSharding(self.mesh, s)),
                restored["params"], self.param_specs)
            st = self._unpack_state_rng(restored["opt_state"])
            exp_avg = st.exp_avg
            if st.health is not None:
                # a checkpoint with quarantined workers: only HEALTHY
                # momenta may enter the remap — heal the quarantined rows
                # to the healthy mean first (mean-preserving, so the vote
                # center the remap promises to keep is the healthy one)
                mask = np.asarray(jax.device_get(st.health), dtype=bool)
                sick = [int(w) for w in np.nonzero(~mask)[0]]
                if sick:
                    exp_avg = heal_worker_momentum(exp_avg, mask, sick)
                    emit(f"[trainer] elastic resume: healed quarantined "
                          f"worker momenta {sick} from the healthy mean "
                          "before the world remap")
            st = st._replace(
                exp_avg=remap_worker_momentum(exp_avg, ckpt_world,
                                              self.world))
            if self._guard is not None:
                # worker identity does not survive a world change: the
                # guard restarts all-healthy at W' with a zero ballot
                # history (a still-sick HOST re-strikes within
                # --guard_strikes steps)
                health, prev = self._fresh_guard_state()
                st = st._replace(health=health, prev_ballot=prev)
            else:
                st = st._replace(health=None, prev_ballot=None)
            self.state = jax.device_put(
                st,
                LionState(
                    count=NamedSharding(self.mesh, P()),
                    exp_avg=jax.tree.map(
                        lambda s: NamedSharding(self.mesh, s),
                        self._exp_avg_specs),
                    rng=(None if st.rng is None
                         else NamedSharding(self.mesh, P())),
                    elected=(None if st.elected is None
                             else NamedSharding(self.mesh, P())),
                    health=(None if st.health is None
                            else NamedSharding(self.mesh, P())),
                    prev_ballot=(None if st.prev_ballot is None
                                 else NamedSharding(self.mesh,
                                                    P(DATA_AXIS))),
                ),
            )
            # the accumulator's normalizations reference the old world; a
            # fresh window is honest, stale continuity is not
            emit(f"[trainer] elastic resume: remapped [{ckpt_world}, ...] "
                  f"momenta to [{self.world}, ...] "
                  f"({'group mean' if ckpt_world > self.world else 'replicate'}"
                  f" policy, cross-worker mean preserved)")
        self.step_count = int(restored["step"])
        self._resume_skip_batches = int(
            restored.get("batches_consumed", restored["step"]))

    def _maybe_resume(self) -> None:
        if not (self.checkpointer and self.cfg.resume_from_checkpoint):
            return
        # verified autodetect, newest GOOD first: a torn leaf / corrupted
        # manifest / uncommitted save falls back one save interval instead
        # of poisoning the run (or killing the resume outright)
        candidates = (self.checkpointer.valid_steps()
                      if self.cfg.ckpt_integrity else
                      [s for s in [self.checkpointer.latest_step()]
                       if s is not None])
        for step in candidates:
            meta = (self.checkpointer.manifest_meta(step)
                    if self.cfg.ckpt_integrity else None) or {}
            ckpt_world = int(meta.get("world", self.world))
            if meta:
                # a depth toggle is an operator decision, never a silent
                # remap: the ring holds IN-FLIGHT level-2 tallies whose
                # slot count and staleness semantics are the depth — there
                # is no meaning-preserving reshape between depths (in a
                # stamped manifest, an absent key = pre-ring checkpoint =
                # depth 0). Checkpoints with NO manifest meta at all
                # (--ckpt_integrity false / legacy dirs) cannot be
                # depth-checked up front: a matching depth restores through
                # the normal templates, and a mismatch surfaces as the
                # all-templates-failed RuntimeError below, which names the
                # depth toggle as a candidate cause.
                ckpt_depth = int(meta.get("dcn_pipeline_depth", 0) or 0)
                if ckpt_depth != self.cfg.dcn_pipeline_depth:
                    raise ValueError(
                        f"checkpoint step {step} was written at "
                        f"--dcn_pipeline_depth {ckpt_depth} but this run "
                        f"uses {self.cfg.dcn_pipeline_depth}: the in-flight"
                        " DCN tally ring does not survive a depth change. "
                        "Resume with the matching depth (then change it at "
                        "the NEXT fresh start), or point --output_dir "
                        "elsewhere")
                # the MoE balance ring has the same no-remap property: its
                # slot count IS the staleness (None and 0 both mean no
                # ring, so toggling between those is fine)
                ckpt_ep = int(meta.get("ep_dcn_pipeline", 0) or 0)
                run_ep = int(self.cfg.ep_dcn_pipeline or 0)
                if ckpt_ep != run_ep:
                    raise ValueError(
                        f"checkpoint step {step} was written at "
                        f"--ep_dcn_pipeline {ckpt_ep} but this run uses "
                        f"{run_ep}: the in-flight MoE balance ring does "
                        "not survive a depth change. Resume with the "
                        "matching depth, or point --output_dir elsewhere")
            if ckpt_world != self.world:
                # a mismatched world is an operator decision, not a bad
                # checkpoint — never silently fall back past it
                if not self.cfg.elastic_resume:
                    raise ValueError(
                        f"checkpoint step {step} holds momenta for world="
                        f"{ckpt_world} but this mesh has world="
                        f"{self.world}; pass --elastic_resume to remap "
                        "them (or match the chip count)")
                if not self.cfg.lion:
                    raise NotImplementedError(
                        "--elastic_resume remaps the stacked per-worker "
                        "Lion momenta; the AdamW/ZeRO-1 states have no "
                        "defined remap")
                if self.cfg.dcn_pipeline_depth > 0:
                    raise NotImplementedError(
                        "--elastic_resume cannot remap the DCN pipeline "
                        "ring: its slots are in-flight level-2 tallies "
                        "whose chunk ownership and group count are "
                        "functions of the world size. Resume at the "
                        "original world (drain the pipeline), or restart "
                        "with --dcn_pipeline_depth 0")
                if (self.cfg.ep_dcn_pipeline or 0) > 0:
                    raise NotImplementedError(
                        "--elastic_resume cannot remap the MoE balance "
                        "ring: its rows are per-data-worker stale tallies "
                        "of batches the new world never routed. Resume at "
                        "the original world, or restart with "
                        "--ep_dcn_pipeline 0")
            try:
                self._restore_step(step, meta, ckpt_world)
            except Exception as e:
                emit(f"[trainer] checkpoint step {step} failed to restore "
                      f"({e}); falling back to the previous good checkpoint")
                continue
            purged = self.checkpointer.purge_steps_after(step)
            if purged:
                emit(f"[trainer] purged stale newer checkpoints {purged}: "
                      "left on disk they make Orbax silently drop every "
                      "post-resume save below them (the deterministic "
                      "replay re-creates them bit-identically)")
            emit(f"[trainer] resumed from checkpoint step {step}")
            return
        if candidates:
            # every verified checkpoint failed to restore — that's a
            # structural mismatch (model/optimizer config changed), not a
            # bad checkpoint. Restarting from step 0 underneath them would
            # also be unsaveable (Orbax drops saves below existing steps).
            raise RuntimeError(
                f"resume_from_checkpoint: all {len(candidates)} verified "
                f"checkpoint(s) (steps {candidates}) failed to restore "
                "into this run's state structure — likely a model/optimizer"
                " config change since they were written"
                + (" (this run's --dcn_pipeline_depth "
                   f"{self.cfg.dcn_pipeline_depth} is one candidate: a "
                   "checkpoint without manifest meta cannot be "
                   "depth-checked up front, and the DCN ring does not "
                   "survive a depth change)"
                   if self.cfg.dcn_pipeline_depth > 0 else "")
                + ". Refusing to "
                "silently restart from step 0; pass --resume_from_checkpoint"
                " false (or point --output_dir elsewhere) to start fresh")

    def close(self) -> None:
        self.profiler.close()
        if self.cfg.inject_poison:
            # disarm the poison this trainer injected so a later Trainer in
            # the same process does not inherit a sick worker
            resilience.inject_fault("ballot_poison", None)
        if self.cfg.inject_membership:
            # same hygiene for the membership schedule (unconsumed entries
            # must not fire inside a later Trainer's run)
            resilience.inject_fault("membership", None)
        if self._preempt_guard is not None:
            self._preempt_guard.close()
        try:
            if self.checkpointer:
                # may re-raise a committer-thread commit failure (the drain
                # boundary); the metrics log must still be flushed/closed
                self.checkpointer.close()
        finally:
            self.logger.close()
            # the journal closes LAST: the checkpointer drain above still
            # records its ckpt spans, and a commit failure propagating out
            # of this method leaves a flushed journal behind it
            journal.uninstall(self.journal)
            self.journal.close()

    # ------------------------------------------------------------- factories
    @staticmethod
    def for_gpt2(cfg: TrainConfig, mesh, model_cfg: GPT2Config, seed: Optional[int] = None,
                 initial_params: Any = None):
        """``initial_params`` (e.g. an HF checkpoint imported via
        models/hf_import) replaces the random init — the reference's
        finetune-from-pretrained path (run_clm.py:425-444)."""
        from distributed_lion_tpu.parallel.mesh import TENSOR_AXIS
        from distributed_lion_tpu.parallel.tensor_parallel import (
            gpt2_param_specs,
            validate_tp,
        )

        model_cfg = apply_remat_policy(cfg, model_cfg)
        params = (initial_params if initial_params is not None else
                  gpt2_init(jax.random.key(seed if seed is not None else cfg.seed), model_cfg))
        n = count_params(params)
        shape = dict(mesh.shape)
        cfg = resolve_auto_comm(
            cfg, mesh, n,
            # tp/pp/expert all shard params; only dp(/sp) keeps them
            # replicated, the precondition for the lazy elected-sign cache
            params_replicated=all(
                shape.get(ax, 1) == 1
                for ax in (TENSOR_AXIS, PIPE_AXIS, EXPERT_AXIS)),
        )
        acct = wire_bytes_per_param(n, data_axis_size(mesh), cfg.wire,
                                    vote_every=cfg.vote_every,
                                    accum_steps=cfg.gradient_accumulation_steps,
                                    vote_buckets=cfg.vote_buckets or 1)
        tp = mesh.shape[TENSOR_AXIS]
        emit(
            f"[trainer] GPT-2 {n/1e6:.1f}M params | world={data_axis_size(mesh)} "
            f"tp={tp} | vote wire={cfg.wire}"
            + (f" (vote_every={cfg.vote_every})" if cfg.vote_every > 1 else "")
            + (f" (vote_buckets={cfg.vote_buckets}, "
               f"{acct['overlappable_wire_frac']*100:.0f}% of the wire "
               "pipelineable)" if cfg.vote_buckets > 1 else "")
            + f": {acct['bits_per_param']:.2f} bits/param/step "
            f"({acct['vs_bf16_allreduce']*100:.1f}% of bf16 all-reduce; "
            f"{acct['bits_per_param_per_microbatch']:.2f} bits/param/microbatch)"
            + (f" | DCN leg {acct['dcn_bits_per_param']:.3f} bits/param"
               if "dcn_bits_per_param" in acct else "")
        )
        pp = dict(mesh.shape).get(PIPE_AXIS, 1)
        if cfg.vocab_chunks > 0 and model_cfg.moe_experts > 0:
            raise NotImplementedError(
                "--vocab_chunks is wired for the dense dp/tp/sp/pp paths "
                "(the MoE branch carries its own loss function); drop one"
            )
        if pp > 1:
            from distributed_lion_tpu.models.gpt2_pipe import (
                make_pipeline_loss,
                pipeline_param_specs,
                pipeline_params,
                validate_pipeline,
            )

            if dict(mesh.shape).get(EXPERT_AXIS, 1) > 1:
                raise NotImplementedError(
                    "pipeline parallelism composes with data, tensor and "
                    "sequence parallelism (dp x tp x sp x pp); an expert "
                    "axis alongside pipe is not wired"
                )
            if model_cfg.moe_experts > 0:
                raise NotImplementedError(
                    "MoE blocks under pipeline parallelism are not wired "
                    "(mixed dense/MoE stage structures); drop one of the two"
                )
            if cfg.tp_vocab:
                raise NotImplementedError(
                    "--tp_vocab under --pipeline_parallel is not wired (the "
                    "pipeline loss carries its own replicated head); drop one"
                )
            if tp > 1:
                validate_tp(model_cfg, tp, "gpt2")
            sp_pipe = dict(mesh.shape).get(SEQ_AXIS, 1)
            if sp_pipe > 1:
                validate_seq_block(cfg, model_cfg, sp_pipe)
            n_micro = cfg.pipeline_microbatches or pp
            validate_pipeline(model_cfg, cfg, pp, n_micro)
            loss_fn = make_pipeline_loss(
                model_cfg, n_micro,
                tp_axis=TENSOR_AXIS if tp > 1 else None,
                vocab_chunks=cfg.vocab_chunks,
                seq_axis=SEQ_AXIS if sp_pipe > 1 else None)
            if cfg.vocab_chunks > 0:
                loss_fn._vocab_chunked = True  # consumed; don't trip the guard
            return Trainer(
                cfg, mesh,
                apply_fn=None,
                params=pipeline_params(params, pp),
                param_specs=pipeline_param_specs(tensor=tp > 1),
                loss_fn=loss_fn,
                batch_spec=(P(DATA_AXIS, SEQ_AXIS) if sp_pipe > 1 else None),
            )

        ep = dict(mesh.shape).get(EXPERT_AXIS, 1)
        if ep > 1 and model_cfg.moe_experts == 0:
            raise ValueError(
                f"an 'expert' mesh axis of size {ep} needs MoE blocks "
                "(--moe_experts); a dense model would silently duplicate all "
                "compute across the axis"
            )
        if cfg.ep_dcn_pipeline is not None and model_cfg.moe_experts == 0:
            raise ValueError(
                "--ep_dcn_pipeline schedules the MoE balance feedback; a "
                "dense model (--moe_experts 0) has no routing to balance. "
                "Drop the flag or add --moe_experts")
        if model_cfg.moe_experts > 0:
            from distributed_lion_tpu.models.gpt2 import gpt2_moe_param_specs
            from distributed_lion_tpu.models.loss import (
                clm_loss_and_metrics,
                clm_loss_sharded_rows,
            )

            if dict(mesh.shape).get(SEQ_AXIS, 1) > 1:
                raise NotImplementedError(
                    "MoE composes with data, expert and tensor parallelism "
                    "(dp x ep x tp); a seq axis alongside MoE is not wired"
                )
            if model_cfg.moe_experts % ep:
                raise ValueError(
                    f"moe_experts {model_cfg.moe_experts} not divisible by "
                    f"expert axis {ep}"
                )
            if cfg.tp_vocab:
                raise NotImplementedError(
                    "--tp_vocab on the MoE path is not wired (the MoE loss "
                    "uses the replicated tied head); drop one"
                )
            if tp > 1:
                validate_tp(model_cfg, tp, "gpt2")
            expert_axis = EXPERT_AXIS if ep > 1 else None
            moe_tp_axis = TENSOR_AXIS if tp > 1 else None
            moe_specs = (gpt2_moe_param_specs(model_cfg, tensor=tp > 1)
                         if (ep > 1 or tp > 1) else None)

            ep_depth = cfg.ep_dcn_pipeline
            # depth 0 = synchronous fed balance: psum the routing tallies
            # over the expert axis INSIDE the forward (at ep=1 the axis
            # psum is the identity, so the aux stays bit-identical to the
            # unflagged local path — the depth-0 pin). depth > 0 feeds the
            # stale ring tally instead (4th loss arg, below).
            balance_axis = (EXPERT_AXIS
                            if (ep_depth == 0 and ep > 1) else None)

            def moe_apply(params, tokens, dropout_key, moe_balance=None,
                          return_tallies=False):
                return gpt2_apply(params, tokens, model_cfg,
                                  dropout_key=dropout_key,
                                  expert_axis=expert_axis,
                                  tp_axis=moe_tp_axis, return_aux=True,
                                  moe_balance=moe_balance,
                                  moe_balance_axis=balance_axis,
                                  return_moe_tallies=return_tallies)

            if ep > 1:
                def moe_loss(params, batch, dropout_key, moe_balance=None):
                    if moe_balance is None:
                        logits, aux = moe_apply(params, batch, dropout_key)
                        tallies = None
                    else:
                        logits, aux, tallies = moe_apply(
                            params, batch, dropout_key, moe_balance, True)
                    loss, metrics = clm_loss_sharded_rows(
                        logits, batch, EXPERT_AXIS, aux=aux)
                    if tallies is not None:
                        metrics["moe_tallies"] = tallies
                    return loss, metrics

                moe_batch_spec = P((DATA_AXIS, EXPERT_AXIS))
            else:
                def moe_loss(params, batch, dropout_key, moe_balance=None):
                    if moe_balance is None:
                        logits, aux = moe_apply(params, batch, dropout_key)
                        tallies = None
                    else:
                        logits, aux, tallies = moe_apply(
                            params, batch, dropout_key, moe_balance, True)
                    loss, metrics = clm_loss_and_metrics(logits, batch)
                    metrics["aux_loss"] = aux
                    if tallies is not None:
                        metrics["moe_tallies"] = tallies
                    return loss + 0.01 * aux, metrics

                moe_batch_spec = None
            if (ep_depth or 0) > 0:
                from distributed_lion_tpu.models.gpt2 import is_moe_block
                n_moe = sum(1 for i in range(model_cfg.n_layer)
                            if is_moe_block(model_cfg, i))
                # consumed by Trainer.__init__ (ring sizing) and the step
                # core (ring read/feed/write); the tally row is per-expert
                # token counts + the lane count in the last entry
                moe_loss._wants_moe_balance = True
                moe_loss._moe_tally_shape = (n_moe,
                                             model_cfg.moe_experts + 1)
            n_active = count_params(params) - sum(
                p.size for b in params["blocks"] if "moe" in b
                for p in jax.tree.leaves(b["moe"])
            )
            emit(f"[trainer] GPT-2-MoE: {count_params(params)/1e6:.1f}M total "
                  f"({n_active/1e6:.1f}M dense) | {model_cfg.moe_experts} "
                  f"experts every {model_cfg.moe_every} blocks | ep={ep}")
            return Trainer(cfg, mesh, apply_fn=None, params=params,
                           param_specs=moe_specs, loss_fn=moe_loss,
                           batch_spec=moe_batch_spec)

        if cfg.tp_vocab and tp <= 1:
            raise ValueError("--tp_vocab needs --tensor_parallel > 1 (it "
                             "shards the tied embedding over the tensor axis)")
        if cfg.tp_vocab and cfg.vocab_chunks > 0:
            raise NotImplementedError(
                "--tp_vocab and --vocab_chunks are alternative head "
                "strategies; pick one"
            )
        if cfg.tp_vocab and dict(mesh.shape).get(SEQ_AXIS, 1) > 1:
            raise NotImplementedError(
                "--tp_vocab under --seq_parallel is not wired; pick one"
            )
        param_specs = None
        tp_axis = None
        if tp > 1:
            validate_tp(model_cfg, tp, "gpt2")
            if cfg.tp_vocab and model_cfg.padded_vocab % tp:
                raise ValueError(
                    f"--tp_vocab: embedding rows {model_cfg.padded_vocab} not "
                    f"divisible by tensor axis {tp}; vocab_pad_multiple "
                    f"(models/gpt2) pads a ragged vocab so it shards evenly"
                )
            param_specs = gpt2_param_specs(model_cfg,
                                           vocab_parallel=cfg.tp_vocab)
            tp_axis = TENSOR_AXIS

        sp = dict(mesh.shape).get(SEQ_AXIS, 1)
        seq_axis = SEQ_AXIS if sp > 1 else None
        batch_spec = None
        loss_fn = None
        if seq_axis:
            validate_seq_block(cfg, model_cfg, sp)
            if model_cfg.dropout > 0.0:
                emit(
                    "[trainer] WARNING: attention-probability dropout is "
                    "disabled under sequence parallelism (scores never exist "
                    "in one place on the ring path); residual/embedding "
                    "dropout still applies — semantics differ from "
                    "replicated training at the same dropout rate"
                )
            batch_spec = P(DATA_AXIS, SEQ_AXIS)  # rows over data, tokens over seq
            from distributed_lion_tpu.models.loss import clm_loss_seq_parallel

            if cfg.vocab_chunks > 0:
                # long-context x chunked-vocab: stream the tied head over
                # vocab chunks per shard (ops/xent) — the [B, T/sp, V]
                # logits never materialize either
                from distributed_lion_tpu.models.gpt2 import gpt2_hidden
                from distributed_lion_tpu.ops.xent import (
                    chunked_clm_loss_seq_parallel,
                )

                def loss_fn(params, batch, dropout_key):
                    hidden, _ = gpt2_hidden(params, batch, model_cfg,
                                            dropout_key=dropout_key,
                                            tp_axis=tp_axis,
                                            seq_axis=SEQ_AXIS)
                    return chunked_clm_loss_seq_parallel(
                        hidden, params["wte"], batch, cfg.vocab_chunks,
                        SEQ_AXIS, valid_v=model_cfg.vocab_size)

                loss_fn._vocab_chunked = True
            else:
                def loss_fn(params, batch, dropout_key):
                    logits = apply_fn(params, batch, dropout_key)
                    return clm_loss_seq_parallel(logits, batch, SEQ_AXIS)

        def apply_fn(params, tokens, dropout_key):
            return gpt2_apply(params, tokens, model_cfg, dropout_key=dropout_key,
                              tp_axis=tp_axis, seq_axis=seq_axis)

        if cfg.tp_vocab and loss_fn is None:
            from distributed_lion_tpu.models.gpt2 import gpt2_hidden
            from distributed_lion_tpu.ops.xent import tp_vocab_clm_loss_and_metrics

            def loss_fn(params, batch, dropout_key):
                # params["wte"] is this rank's [V/tp, d] vocab-row slice:
                # VocabParallelEmbedding on the way in, its transpose as the
                # tied vocab-parallel head on the way out
                hidden, _ = gpt2_hidden(params, batch, model_cfg,
                                        dropout_key=dropout_key,
                                        tp_axis=tp_axis,
                                        vocab_axis=TENSOR_AXIS)
                return tp_vocab_clm_loss_and_metrics(
                    hidden, params["wte"].T, batch, TENSOR_AXIS,
                    valid_v=model_cfg.vocab_size)

            loss_fn._tp_vocab = True  # consumed; don't trip the guard

        elif cfg.vocab_chunks > 0 and loss_fn is None:
            from distributed_lion_tpu.models.gpt2 import gpt2_hidden
            from distributed_lion_tpu.ops.xent import chunked_clm_loss_and_metrics

            def loss_fn(params, batch, dropout_key):
                hidden, _ = gpt2_hidden(params, batch, model_cfg,
                                        dropout_key=dropout_key, tp_axis=tp_axis)
                return chunked_clm_loss_and_metrics(
                    hidden, params["wte"], batch, cfg.vocab_chunks,
                    valid_v=model_cfg.vocab_size)

            loss_fn._vocab_chunked = True  # consumed; don't trip the guard

        return Trainer(cfg, mesh, apply_fn, params, param_specs=param_specs,
                       loss_fn=loss_fn, batch_spec=batch_spec)

    @staticmethod
    def for_llama(cfg: TrainConfig, mesh, model_cfg, seed: Optional[int] = None,
                  initial_params: Any = None):
        """Full-parameter CLM training of a Llama-family model — the
        reference's run_clm is architecture-agnostic (AutoModelForCausalLM,
        run_clm.py:425-444), so ours trains Llama from scratch or from an
        imported checkpoint too. Composes with dp, tensor (dp×tp), sequence
        (dp×sp) and pipeline (dp×pp, models/llama_pipe) parallelism; the
        expert axis is GPT-2-MoE-only."""
        from distributed_lion_tpu.models.llama import (
            llama_apply,
            llama_hidden,
            llama_init,
        )
        from distributed_lion_tpu.models.loss import clm_loss_seq_parallel
        from distributed_lion_tpu.parallel.tensor_parallel import (
            llama_param_specs,
            validate_tp,
        )

        if dict(mesh.shape).get(EXPERT_AXIS, 1) > 1:
            raise NotImplementedError(
                "an 'expert' mesh axis is wired for GPT-2-MoE only; Llama "
                "composes with dp x tp x sp x pp"
            )
        model_cfg = apply_remat_policy(cfg, model_cfg)
        params = (initial_params if initial_params is not None else
                  llama_init(jax.random.key(seed if seed is not None else cfg.seed),
                             model_cfg))
        n = count_params(params)
        shape = dict(mesh.shape)
        cfg = resolve_auto_comm(
            cfg, mesh, n,
            params_replicated=all(
                shape.get(ax, 1) == 1 for ax in (TENSOR_AXIS, PIPE_AXIS)),
        )
        acct = wire_bytes_per_param(n, data_axis_size(mesh), cfg.wire,
                                    vote_every=cfg.vote_every,
                                    accum_steps=cfg.gradient_accumulation_steps,
                                    vote_buckets=cfg.vote_buckets or 1)
        tp = mesh.shape[TENSOR_AXIS]
        pp = dict(mesh.shape).get(PIPE_AXIS, 1)
        emit(
            f"[trainer] Llama {n/1e6:.1f}M params | world={data_axis_size(mesh)} "
            f"tp={tp}" + (f" pp={pp}" if pp > 1 else "") + f" | vote wire={cfg.wire}"
            + (f" (vote_every={cfg.vote_every})" if cfg.vote_every > 1 else "")
            + (f" (vote_buckets={cfg.vote_buckets})"
               if cfg.vote_buckets > 1 else "")
            + f": {acct['bits_per_param']:.2f} bits/param/step"
            + (f" | DCN leg {acct['dcn_bits_per_param']:.3f} bits/param"
               if "dcn_bits_per_param" in acct else "")
        )
        if pp > 1:
            from distributed_lion_tpu.models.llama_pipe import (
                llama_pipeline_param_specs,
                llama_pipeline_params,
                make_llama_pipeline_loss,
                validate_llama_pipeline,
            )

            if cfg.tp_vocab:
                raise NotImplementedError(
                    "--tp_vocab under --pipeline_parallel is not wired (the "
                    "pipeline loss carries its own replicated head); drop one"
                )
            if tp > 1:
                validate_tp(model_cfg, tp, "llama")
            sp_pipe = dict(mesh.shape).get(SEQ_AXIS, 1)
            if sp_pipe > 1:
                validate_seq_block(cfg, model_cfg, sp_pipe)
            n_micro = cfg.pipeline_microbatches or pp
            validate_llama_pipeline(model_cfg, cfg, pp, n_micro)
            loss_fn = make_llama_pipeline_loss(
                model_cfg, n_micro,
                tp_axis=TENSOR_AXIS if tp > 1 else None,
                vocab_chunks=cfg.vocab_chunks,
                seq_axis=SEQ_AXIS if sp_pipe > 1 else None)
            if cfg.vocab_chunks > 0:
                loss_fn._vocab_chunked = True  # consumed; don't trip the guard
            return Trainer(
                cfg, mesh,
                apply_fn=None,
                params=llama_pipeline_params(params, pp),
                param_specs=llama_pipeline_param_specs(tensor=tp > 1),
                loss_fn=loss_fn,
                batch_spec=(P(DATA_AXIS, SEQ_AXIS) if sp_pipe > 1 else None),
            )
        if cfg.tp_vocab and tp <= 1:
            raise ValueError("--tp_vocab needs --tensor_parallel > 1 (it "
                             "shards the lm_head over the tensor axis)")
        if cfg.tp_vocab and cfg.vocab_chunks > 0:
            raise NotImplementedError(
                "--tp_vocab and --vocab_chunks are alternative head "
                "strategies (vocab sharded across ranks vs streamed in "
                "chunks); pick one"
            )
        param_specs = None
        tp_axis = None
        if tp > 1:
            validate_tp(model_cfg, tp, "llama")
            if cfg.tp_vocab and model_cfg.vocab_size % tp:
                raise ValueError(
                    f"--tp_vocab: vocab {model_cfg.vocab_size} not divisible "
                    f"by tensor axis {tp}"
                )
            param_specs = llama_param_specs(model_cfg,
                                            vocab_parallel=cfg.tp_vocab)
            tp_axis = TENSOR_AXIS

        sp = dict(mesh.shape).get(SEQ_AXIS, 1)
        seq_axis = SEQ_AXIS if sp > 1 else None
        batch_spec = None
        loss_fn = None
        if seq_axis and cfg.tp_vocab:
            raise NotImplementedError(
                "--tp_vocab under --seq_parallel is not wired; pick one"
            )
        if seq_axis:
            validate_seq_block(cfg, model_cfg, sp)
            batch_spec = P(DATA_AXIS, SEQ_AXIS)

            if cfg.vocab_chunks > 0:
                # long-context x huge-vocab: stream the lm_head per shard
                # (ops/xent chunked CE + the shard-boundary label ppermute)
                from distributed_lion_tpu.ops.xent import (
                    chunked_clm_loss_seq_parallel,
                )

                def loss_fn(params, batch, dropout_key):
                    hidden = llama_hidden(params, batch, model_cfg,
                                          tp_axis=tp_axis, seq_axis=SEQ_AXIS)
                    return chunked_clm_loss_seq_parallel(
                        hidden, params["lm_head"], batch, cfg.vocab_chunks,
                        SEQ_AXIS, emb_layout="dv")

                loss_fn._vocab_chunked = True
            else:
                def loss_fn(params, batch, dropout_key):
                    logits = llama_apply(params, batch, model_cfg,
                                         tp_axis=tp_axis, seq_axis=SEQ_AXIS)
                    return clm_loss_seq_parallel(logits, batch, SEQ_AXIS)

        def apply_fn(params, tokens, dropout_key):
            del dropout_key  # our Llama (like HF's) has no dropout
            return llama_apply(params, tokens, model_cfg, tp_axis=tp_axis)

        if cfg.tp_vocab and loss_fn is None:
            from distributed_lion_tpu.ops.xent import tp_vocab_clm_loss_and_metrics

            def loss_fn(params, batch, dropout_key):
                hidden = llama_hidden(params, batch, model_cfg, tp_axis=tp_axis)
                # params["lm_head"] is this rank's [d, V/tp] column slice
                return tp_vocab_clm_loss_and_metrics(
                    hidden, params["lm_head"], batch, TENSOR_AXIS)

            loss_fn._tp_vocab = True  # consumed; don't trip the guard

        elif cfg.vocab_chunks > 0 and loss_fn is None:
            from distributed_lion_tpu.ops.xent import chunked_clm_loss_and_metrics

            def loss_fn(params, batch, dropout_key):
                hidden = llama_hidden(params, batch, model_cfg, tp_axis=tp_axis)
                return chunked_clm_loss_and_metrics(
                    hidden, params["lm_head"], batch, cfg.vocab_chunks,
                    None, emb_layout="dv")

            loss_fn._vocab_chunked = True  # consumed; don't trip the guard

        return Trainer(cfg, mesh, apply_fn, params, param_specs=param_specs,
                       loss_fn=loss_fn, batch_spec=batch_spec)


def _count_of(state) -> jnp.ndarray:
    return state.count


def global_grad_sq(grads, specs=None, shard_axes: tuple = ()):
    """Exact squared global L2 norm of a gradient pytree inside shard_map.

    Under tensor/pipeline/expert parallelism (``shard_axes`` + ``specs``),
    the squared norm of each leaf SHARDED over one of those axes is psum'd
    across that axis (each rank holds one shard of that gradient) while
    replicated leaves — whose grads are complete and identical on every
    rank, via the copy_to_tp_region boundary / the pipe-axis grad psum —
    are counted once, so every rank derives the same value. The data axis
    is deliberately never summed: per-worker grads get per-worker norms
    (they are different gradients, not shards of one). Shared by the
    clipper and the NaN sentinel's grad-norm metric."""
    def _sq(g):
        return jnp.sum(jnp.square(g.astype(jnp.float32)))

    if not shard_axes:
        return sum(_sq(g) for g in jax.tree.leaves(grads))
    from distributed_lion_tpu.parallel.tensor_parallel import spec_uses_axis

    flat_g, gdef = jax.tree.flatten(grads)
    flat_s = gdef.flatten_up_to(specs)  # P leaves; same structure as grads
    # accumulate per axis-subset: a leaf sharded over axis A contributes
    # its local sq, psum'd over A; leaves sharded over several axes are
    # psum'd over each in turn
    sq = jnp.float32(0)
    by_axes: dict = {}
    for g, s in zip(flat_g, flat_s):
        axes = tuple(a for a in shard_axes if spec_uses_axis(s, a))
        by_axes[axes] = by_axes.get(axes, jnp.float32(0)) + _sq(g)
    for axes, part in by_axes.items():
        for a in axes:
            part = lax.psum(part, a)
        sq = sq + part
    return sq


def clip_by_global_norm(grads, clip: float, specs=None,
                        shard_axes: tuple = ()):
    """Scale the whole pytree so its global L2 norm is ≤ ``clip`` — the
    torch.nn.utils.clip_grad_norm_ semantics HF Trainer applies before every
    optimizer step (default max_grad_norm=1.0), which the reference inherits.
    Norm semantics under model parallelism: see :func:`global_grad_sq`."""
    sq = global_grad_sq(grads, specs=specs, shard_axes=shard_axes)
    scale = jnp.minimum(1.0, clip / jnp.maximum(jnp.sqrt(sq), 1e-12))
    return jax.tree.map(lambda g: (g * scale).astype(g.dtype), grads)
