"""Resilience subsystem: preemption drain + fault injection.

Distributed Lion's whole optimizer state is the stacked ``[world, ...]``
per-worker momentum pytree — losing it or tearing it silently changes every
future election, so durability is a correctness feature here, not an ops
nicety. This module holds the pieces that are about *surviving the
environment* rather than writing bytes (that's ``train/checkpoint.py``):

- :class:`PreemptionGuard` — a SIGTERM/maintenance handler that sets a flag
  the Trainer checks once per dispatch. On trip the loop drains the
  in-flight async save, writes an emergency checkpoint tagged ``preempt``,
  and returns cleanly so the process exits 0 and the watcher
  (scripts/tpu_watch_loop.sh) restarts it into a normal resume.
- A **fault-injection registry** consumed by ``train/checkpoint.py``'s save
  pipeline, so tests (tests/test_resilience.py) and the runbook's
  resilience stage can simulate a crash mid-save, a slow serializer, or
  flaky save I/O *inside* the real code path instead of monkeypatching it.
- File-corruption helpers (:func:`tear_leaf_file`, :func:`corrupt_manifest`)
  that damage a committed checkpoint the way real incidents do — a torn
  write, a bit-flipped manifest — for the recovery matrix.

The elastic world-size remap itself lives with the optimizer
(``optim.distributed_lion.remap_worker_momentum``) because its semantics are
a statement about the vote distribution; the Trainer's resume path drives it.
"""

from __future__ import annotations

import json
import os
import pathlib
import signal
import threading
import time
from typing import Any, Iterable, Optional

# --------------------------------------------------------------------------
# Fault injection
# --------------------------------------------------------------------------
# A process-global name -> value registry. checkpoint.py consults it at the
# few points where real failures strike (the serializer call, the commit
# thread); everything else — torn files, corrupt manifests — is injected by
# mutating the on-disk checkpoint post-commit with the helpers below.
# Supported names (value semantics in parentheses):
#   ckpt_save_raise      (int: fail the first N manager.save calls)
#   ckpt_crash_before_manifest (bool: commit dies before the manifest lands)
#   ckpt_crash_before_marker   (bool: manifest lands, commit marker doesn't)
#   ckpt_slow_commit     (float: seconds the commit thread stalls, i.e. a
#                         slow serialize/write — what async saving must hide)
#   dcn_delay            (float: seconds of round-trip latency the hier
#                         wire's level-2 (DCN) leg is emulated to take.
#                         Consumed at TRACE time by parallel.collectives'
#                         launch/consume gates: the launch stamps a wall
#                         clock per optimizer step, the consume blocks
#                         until stamp + delay — so compute executed between
#                         launch and consume (the --dcn_pipeline_depth
#                         cross-step window) counts toward the deadline and
#                         only the UNHIDDEN residual is paid, recorded in
#                         collectives.DCN_WAIT. This is how the bench_dcn
#                         ablation shapes DCN latency on a CPU mesh. Arm
#                         BEFORE building the optimizer/trainer (trace
#                         time); call collectives.dcn_link_reset() between
#                         measured legs.)
#   journal_torn_write   (int: tear the next N journal sink writes)
#   ballot_poison        ((kind, worker, start_step) from parse_poison():
#                         the trainer's step bakes a worker-k gradient
#                         transform in at trace time — nan_grads → NaN,
#                         frozen_ballot → 0 (its vote freezes at sign(m)),
#                         flipped_ballot → −g (its ballot becomes the exact
#                         inverse of the honest one, adversarial voter).
#                         Inject BEFORE the first dispatch; start_step gates
#                         the onset against the traced optimizer count, so
#                         mid-run onset needs no retrace.)
#   membership           (list of (kind, worker, step) from
#                         parse_membership_specs(): live leave/join
#                         schedule the control plane
#                         (train/control_plane.py) consumes at dispatch
#                         boundaries — worker_drop masks the worker out of
#                         the election (departed, no restart), worker_rejoin
#                         re-absorbs it in-run (momentum healed from the
#                         healthy mean, ballot history reset, probation
#                         window). Host-side only: membership transitions
#                         are mask flips between dispatches, never traced.)
#   serve                (list of (kind, replica, tick, arg) from
#                         parse_serve_specs(): the serve-side replica
#                         fault schedule serve/replica_plane.ServingFleet
#                         consumes at fleet-tick boundaries —
#                         replica_crash kills the replica's engine (its
#                         residents migrate from the fleet's recovery
#                         shadow), replica_drain stops admission and lets
#                         residents finish, slow_tick:<r>:<ms> injects ms
#                         of latency into every tick of replica r (the
#                         tick-latency watch must detect it and route new
#                         work around), replica_rejoin re-enters a
#                         departed replica with a FRESH engine/page pool.
#                         Host-side only, like membership.)
_FAULTS: dict[str, Any] = {}
_FAULTS_LOCK = threading.Lock()


def inject_fault(name: str, value: Any = True) -> None:
    with _FAULTS_LOCK:
        _FAULTS[name] = value


def clear_faults() -> None:
    with _FAULTS_LOCK:
        _FAULTS.clear()


def fault(name: str, default: Any = None) -> Any:
    with _FAULTS_LOCK:
        return _FAULTS.get(name, default)


def consume_due(name: str, through: int, step_of=None) -> list:
    """Atomically pop the DUE entries of a list-valued schedule fault:
    entries whose step/tick (``step_of``, default ``entry[2]``) is
    ``<= through`` are returned in schedule order and removed from the
    registry; later entries stay armed. The membership schedule
    (train/control_plane.membership_due) and the serve-side replica
    schedule (serve/replica_plane.ServingFleet) both consume their
    boundaries through this one helper, so 'due' can never mean two
    different things."""
    if step_of is None:
        def step_of(e):
            return int(e[2])
    with _FAULTS_LOCK:
        pending = _FAULTS.get(name)
        if not pending:
            return []
        due = [e for e in pending if step_of(e) <= through]
        if due:
            _FAULTS[name] = [e for e in pending if step_of(e) > through]
        return due


POISON_KINDS = ("nan_grads", "frozen_ballot", "flipped_ballot")

MEMBERSHIP_KINDS = ("worker_drop", "worker_rejoin")


def parse_membership(spec: str) -> tuple[str, int, int]:
    """Parse one membership-fault spec — ``worker_drop:<w>[:<start_step>]``
    or ``worker_rejoin:<w>:<step>`` — into ``(kind, worker, step)``. The
    control plane (train/control_plane.py) consumes these at dispatch
    boundaries: a drop masks the worker out of the election at the first
    boundary at or after ``step`` (default 0 — departed from the very
    first dispatch), a rejoin re-absorbs it in-run (momentum healed from
    the healthy mean, ballot history reset). A rejoin REQUIRES an explicit
    step: rejoining a worker that never left is undefined, so the schedule
    must be stated. Single source of truth for the --inject_membership CLI
    flag and direct registry injection in tests/the runbook."""
    parts = spec.split(":")
    if len(parts) not in (2, 3) or parts[0] not in MEMBERSHIP_KINDS:
        raise ValueError(
            f"bad membership spec {spec!r}: expected '<kind>:<worker>"
            f"[:<step>]' with kind in {MEMBERSHIP_KINDS}")
    if parts[0] == "worker_rejoin" and len(parts) != 3:
        raise ValueError(
            f"bad membership spec {spec!r}: worker_rejoin requires an "
            "explicit step ('worker_rejoin:<worker>:<step>')")
    try:
        worker = int(parts[1])
        step = int(parts[2]) if len(parts) == 3 else 0
    except ValueError:
        raise ValueError(f"bad membership spec {spec!r}: worker/step must "
                         "be integers")
    if worker < 0 or step < 0:
        raise ValueError(f"bad membership spec {spec!r}: worker/step must "
                         "be >= 0")
    return parts[0], worker, step


def parse_membership_specs(specs: str) -> list:
    """Comma-separated membership specs (the --inject_membership flag) →
    the ``membership`` fault registry value: a list of (kind, worker, step)
    tuples, consumed in order by the control plane as their steps come
    due."""
    return [parse_membership(s.strip())
            for s in specs.split(",") if s.strip()]


SERVE_FAULT_KINDS = ("replica_crash", "replica_kill", "replica_drain",
                     "slow_tick", "replica_rejoin")


def parse_serve_fault(spec: str) -> tuple[str, int, int, int]:
    """Parse one serve-side replica-fault spec into the normalized
    ``(kind, replica, tick, arg)`` tuple the fleet consumes (the third
    field is ALWAYS the due tick, so the schedule pops through
    :func:`consume_due` like membership):

    - ``replica_crash:<r>:<tick>`` — replica r dies at that fleet tick
      (engine discarded; residents migrate from the recovery shadow)
    - ``replica_kill:<r>:<tick>`` — the PROCESS-death twin: on a
      process-isolated replica (serve/fleet_proc) a real SIGKILL is
      armed inside the child's next tick (mid-decode — the decode
      dispatch runs, the reply never arrives); on an in-process engine
      it degrades to the simulated crash above
    - ``replica_drain:<r>[:<tick>]`` — r stops admitting at tick (default
      0), finishes its residents, then departs
    - ``slow_tick:<r>:<ms>`` — every tick of replica r pays <ms> extra
      milliseconds, armed from tick 0 (``arg`` carries the ms)
    - ``replica_rejoin:<r>:<tick>`` — a departed r re-enters the rotation
      with a fresh engine/page pool; requires an explicit tick (rejoining
      a replica that never left is undefined, same rule as
      worker_rejoin)

    Single source of truth for the --inject_serve CLI flag and direct
    registry injection in tests/the bench."""
    parts = spec.split(":")
    if len(parts) not in (2, 3) or parts[0] not in SERVE_FAULT_KINDS:
        raise ValueError(
            f"bad serve fault spec {spec!r}: expected '<kind>:<replica>"
            f"[:<tick|ms>]' with kind in {SERVE_FAULT_KINDS}")
    if parts[0] in ("replica_crash", "replica_kill", "slow_tick",
                    "replica_rejoin") and len(parts) != 3:
        raise ValueError(
            f"bad serve fault spec {spec!r}: {parts[0]} requires an "
            f"explicit third field ('{parts[0]}:<replica>:"
            f"{'<ms>' if parts[0] == 'slow_tick' else '<tick>'}')")
    try:
        replica = int(parts[1])
        val = int(parts[2]) if len(parts) == 3 else 0
    except ValueError:
        raise ValueError(f"bad serve fault spec {spec!r}: replica/"
                         "tick/ms must be integers")
    if replica < 0 or val < 0:
        raise ValueError(f"bad serve fault spec {spec!r}: replica/"
                         "tick/ms must be >= 0")
    if parts[0] == "slow_tick":
        return parts[0], replica, 0, val   # armed from tick 0; arg = ms
    return parts[0], replica, val, 0


def parse_serve_specs(specs: str) -> list:
    """Comma-separated serve fault specs (the --inject_serve flag) → the
    ``serve`` fault registry value, consumed in order by the fleet as
    their ticks come due."""
    return [parse_serve_fault(s.strip())
            for s in specs.split(",") if s.strip()]


def parse_poison(spec: str) -> tuple[str, int, int]:
    """Parse a ballot-poisoning spec ``<kind>:<worker>[:<start_step>]``
    (e.g. ``nan_grads:2`` or ``flipped_ballot:0:100``) into the
    ``(kind, worker, start_step)`` tuple the ``ballot_poison`` fault
    carries. Single source of truth for the --inject_poison CLI flag and
    direct registry injection in tests/the runbook."""
    parts = spec.split(":")
    if len(parts) not in (2, 3) or parts[0] not in POISON_KINDS:
        raise ValueError(
            f"bad poison spec {spec!r}: expected '<kind>:<worker>"
            f"[:<start_step>]' with kind in {POISON_KINDS}")
    try:
        worker = int(parts[1])
        start = int(parts[2]) if len(parts) == 3 else 0
    except ValueError:
        raise ValueError(f"bad poison spec {spec!r}: worker/start_step "
                         "must be integers")
    if worker < 0 or start < 0:
        raise ValueError(f"bad poison spec {spec!r}: worker/start_step "
                         "must be >= 0")
    return parts[0], worker, start


def consume_fault_count(name: str) -> bool:
    """Decrement a counted fault; True while it still has charges. Lets a
    test say 'the first two save attempts fail' and have the retry loop
    observe exactly that."""
    with _FAULTS_LOCK:
        n = _FAULTS.get(name, 0)
        if isinstance(n, bool):
            return n
        if n and n > 0:
            _FAULTS[name] = n - 1
            return True
        return False


# --------------------------------------------------------------------------
# Manifest verification (pure stdlib — importable by scripts/check_evidence
# without dragging jax/orbax in; train/checkpoint.py writes these artifacts
# and re-exports the readers)
# --------------------------------------------------------------------------

MANIFEST = "manifest.json"
MARKER = "COMMITTED"
# root-level stamp: "steps in this directory are committed with manifests".
# Its presence flips the no-marker interpretation from 'legacy checkpoint,
# assume good' to 'commit never finished, reject' — without it a crash
# before the first manifest would masquerade as a legacy checkpoint.
MANIFESTS_STAMP = "MANIFESTS_ENABLED"
MANIFEST_FORMAT = 1


def sha256_file(path: pathlib.Path | str, chunk: int = 1 << 20) -> str:
    import hashlib

    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def read_manifest(sdir: pathlib.Path | str) -> Optional[dict]:
    """The manifest of a COMMITTED step, after checking it against the
    marker's recorded digest (cheap — no data-file hashing). None when the
    step is uncommitted or its manifest doesn't match the marker."""
    import hashlib

    sdir = pathlib.Path(sdir)
    marker = read_json(sdir / MARKER)
    if not marker:
        return None
    try:
        raw = (sdir / MANIFEST).read_bytes()
    except OSError:
        return None
    if hashlib.sha256(raw).hexdigest() != marker.get("manifest_sha256"):
        return None
    try:
        return json.loads(raw)
    except json.JSONDecodeError:
        return None


def verify_step_dir(sdir: pathlib.Path | str) -> bool:
    """Full integrity check of one committed step: marker → manifest digest
    → every data file present with matching size and sha256."""
    sdir = pathlib.Path(sdir)
    manifest = read_manifest(sdir)
    if manifest is None:
        return False
    for rel, info in manifest.get("files", {}).items():
        p = sdir / rel
        try:
            if p.stat().st_size != info["bytes"]:
                return False
            if sha256_file(p) != info["sha256"]:
                return False
        except OSError:
            return False
    return True


def latest_valid_step_in(directory: str | os.PathLike) -> Optional[int]:
    """Standalone verified autodetect over a checkpoint root (no
    CheckpointManager needed — scripts/check_evidence.py's resilience stage
    runs this). Mirrors ``Checkpointer.latest_valid_step``: newest GOOD
    step wins; marker-less steps are valid only in pre-manifest (unstamped)
    directories."""
    root = pathlib.Path(directory)
    try:
        steps = sorted((int(p.name) for p in root.iterdir()
                        if p.is_dir() and p.name.isdigit()), reverse=True)
    except OSError:
        return None
    stamped = (root / MANIFESTS_STAMP).exists()
    for s in steps:
        sdir = root / str(s)
        if verify_step_dir(sdir):
            return s
        if not stamped and read_json(sdir / MARKER) is None:
            return s  # legacy pre-manifest checkpoint: assumed good
    return None


# --------------------------------------------------------------------------
# Checkpoint corruption helpers (the recovery matrix's torn/corrupt legs)
# --------------------------------------------------------------------------

def step_dir(directory: str | os.PathLike, step: int) -> pathlib.Path:
    """The Orbax step directory for ``step`` under a checkpoint root."""
    return pathlib.Path(directory) / str(step)


def tear_leaf_file(directory: str | os.PathLike, step: int) -> pathlib.Path:
    """Truncate the largest data file of a committed checkpoint in place —
    the classic torn write (process/node died mid-flush, filesystem kept
    the prefix). Returns the torn path. The manifest's digest for that
    file no longer matches, so verification must reject the step."""
    sdir = step_dir(directory, step)
    candidates = [
        p for p in sdir.rglob("*") if p.is_file()
        and p.name not in (MANIFEST, MARKER)
        and p.stat().st_size > 0
    ]
    if not candidates:
        raise FileNotFoundError(f"no data files under {sdir}")
    victim = max(candidates, key=lambda p: p.stat().st_size)
    size = victim.stat().st_size
    with open(victim, "r+b") as f:
        f.truncate(max(size // 2, 1) - 1 if size > 1 else 0)
    return victim


def corrupt_manifest(directory: str | os.PathLike, step: int) -> pathlib.Path:
    """Flip bytes inside a committed checkpoint's manifest. The commit
    marker records the manifest's own digest, so verification must reject
    the step without even re-hashing the data files."""
    path = step_dir(directory, step) / MANIFEST
    raw = bytearray(path.read_bytes())
    if not raw:
        raise OSError(f"empty manifest at {path}")
    mid = len(raw) // 2
    raw[mid] = raw[mid] ^ 0xFF
    path.write_bytes(bytes(raw))
    return path


def delete_commit_marker(directory: str | os.PathLike, step: int) -> None:
    """Simulate a crash between the manifest write and the commit marker:
    the checkpoint's bytes are all present but it was never committed."""
    (step_dir(directory, step) / MARKER).unlink()


# --------------------------------------------------------------------------
# Preemption
# --------------------------------------------------------------------------

class PreemptionGuard:
    """Signal-driven preemption flag, checked once per train dispatch.

    Installs handlers for ``signals`` (default SIGTERM — what TPU
    maintenance events and the watcher's ``timeout`` deliver) that only set
    a :class:`threading.Event`; all actual work (draining the in-flight
    save, writing the ``preempt``-tagged checkpoint) happens on the train
    loop's thread at the next dispatch boundary, where the program state is
    consistent. Off the main thread (bench harnesses drive Trainers from
    worker threads) signal installation is impossible; the guard degrades
    to a manually-triggerable flag (:meth:`trigger`) instead of failing.
    """

    def __init__(self, signals: Iterable[int] = (signal.SIGTERM,),
                 journal=None):
        self._flag = threading.Event()
        self._prev: dict[int, Any] = {}
        # run-journal hook (train/journal.py, duck-typed so this module
        # stays import-light): the drain event is recorded from
        # should_stop() on the TRAIN LOOP's thread, never from the signal
        # handler — a handler must stay async-signal-safe (flag + one
        # clock read, nothing that allocates or takes locks)
        self._journal = journal
        self._tripped_mono: Optional[float] = None
        self._drain_logged = False
        for sig in signals:
            try:
                self._prev[sig] = signal.signal(sig, self._on_signal)
            except ValueError:  # not the main thread
                pass

    def _on_signal(self, signum, frame) -> None:
        if self._flag.is_set():
            # second delivery: the loop never reached a dispatch boundary
            # (hung collective, wedged step) — stop absorbing the signal.
            # Restore the previous disposition and re-deliver so `timeout`
            # and operators can still kill a stuck process with TERM.
            prev = self._prev.get(signum, signal.SIG_DFL)
            signal.signal(signum, prev if prev is not None else signal.SIG_DFL)
            signal.raise_signal(signum)
            return
        # first delivery, async-signal-safe: stamp the clock + set the
        # flag, nothing else (the stamp is what lets the journal report
        # signal→drain-boundary latency — how long a preemption waits for
        # a consistent dispatch boundary)
        self._tripped_mono = time.monotonic()
        self._flag.set()

    def trigger(self) -> None:
        """Programmatic preemption (tests; cluster agents that learn of
        maintenance through an API rather than a signal)."""
        if self._tripped_mono is None:
            self._tripped_mono = time.monotonic()
        self._flag.set()

    def should_stop(self) -> bool:
        tripped = self._flag.is_set()
        if tripped and not self._drain_logged:
            # first observation at a dispatch boundary: THE preemption-
            # drain event (the trainer is about to drain the in-flight
            # save and write the emergency checkpoint)
            self._drain_logged = True
            if self._journal is not None:
                latency = (time.monotonic() - self._tripped_mono
                           if self._tripped_mono is not None else 0.0)
                self._journal.event("preempt_drain",
                                    signal_to_boundary_s=round(latency, 6))
        return tripped

    def close(self) -> None:
        """Restore the previous handlers (Trainers are created and torn
        down many times per test process)."""
        for sig, prev in self._prev.items():
            try:
                if signal.getsignal(sig) == self._on_signal:
                    signal.signal(sig, prev)
            except ValueError:
                pass
        self._prev.clear()


# --------------------------------------------------------------------------
# Small shared utilities
# --------------------------------------------------------------------------

def read_json(path: str | os.PathLike) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
