"""Run journal: structured span/event tracing for the training control plane.

The reference has no profiling at all (PAPER/SURVEY §5) and our own
observability stopped at vote *semantics* (train/telemetry.py): nothing
explained where the wall clock goes, which is exactly what blocks the
ROADMAP-1 MFU push (37.4% measured, no attribution of the missing 60%) and
the ROADMAP-2 control plane (27 ad-hoc ``print()`` calls are not a
consumable event stream). This module is the recording half; the offline
half — multi-host merge, clock-skew correction, step-time attribution —
is ``cli/run_analyze.py`` (stdlib-only, loadable by file path like
``train/resilience``'s manifest verifier).

Design constraints, in order:

- **Zero step-side host syncs.** Every span is HOST wall time around a
  host-side region (``time.monotonic`` — immune to NTP slews); device time
  is never polled per step. The one device sync the journal relies on is
  the trainer's existing log-cadence drain (the host-float of the metrics
  pytree), which the trainer wraps in the ``device_wait`` span — so the
  journal's device-time estimate costs nothing the loop wasn't already
  paying.
- **Strict-JSON JSONL sink with atomic rotation.** One record per line,
  ``allow_nan=False`` (the MetricsLogger contract,
  scripts/validate_metrics.py validates journals too), newline-terminated
  records as the durability unit: a crash mid-write tears at most the last
  line, and re-opening the file truncates the torn tail back to the last
  complete record (the torn record was never durable — same atomicity
  story as the checkpoint commit marker). Rotation renames the live file
  to ``journal_rank<r>.<seq>.jsonl`` via ``os.replace`` and re-anchors a
  fresh meta record, so every file is self-describing for the analyzer.
- **Bounded memory.** A ring buffer (``deque(maxlen)``) keeps the last N
  records in memory for crash bundles (``journal_tail.jsonl``) — an
  anomaly carries its own timeline without re-reading the sink.
- **A sink failure must not take down training.** The first OSError from
  the file sink disables it LOUDLY (stderr); recording continues into the
  ring. The ``journal_torn_write`` fault (train/resilience registry) tears
  a write mid-line to prove the recovery path.

Record schema (validated by scripts/validate_metrics.py):

- every record: ``kind`` (meta | span | event | log), ``name``, ``t``
  (monotonic seconds, this process's clock), ``rank`` (process index).
- ``meta``/``journal_start``: adds ``wall`` (``time.time()`` at the same
  instant as ``t``) — the anchor the analyzer uses to map each rank's
  monotonic clock onto one wall timeline (skew correction).
- ``span``: adds ``dur`` (seconds). A span stamped with a ``thread`` field
  (``"committer"`` for the checkpoint commit thread, ``"dcn-link"`` for
  the emulated DCN link's residual waits) ran off the step thread and is
  excluded from step-wall attribution (it overlaps compute by design).
- free-form extra fields must be JSON scalars; non-finite floats are
  serialized as ``null`` with the repr under ``<k>_repr``.

Span taxonomy (the name's head — before any ``/`` — is the attribution
bucket): ``data_wait`` (batch fetch + host→device put), ``dispatch`` (the
jitted-step call), ``device_wait`` (the log-cadence device drain — the
loop's direct view of device-bound time), ``logging_drain`` (metric
assembly + telemetry drain + JSONL write), ``ckpt/*`` (checkpoint
serialize/drain on the step thread; committer-thread spans carry
``thread="committer"``). Everything else lands in the analyzer's ``other``
bucket.

Layering: stdlib + ``train.resilience`` (itself pure stdlib) only — no
jax, no numpy — so host-side consumers (``train/vote_guard``,
``data/native_loader``) stay importable without jax and the module can be
loaded by file path.
"""

from __future__ import annotations

import collections
import json
import math
import os
import sys
import threading
import time
from typing import Any, Optional

from distributed_lion_tpu.train import resilience

SCHEMA_VERSION = 1
KINDS = ("meta", "span", "event", "log")
DEFAULT_MAX_BYTES = 32 << 20  # rotate the sink at 32 MiB per file
DEFAULT_RING = 512


def journal_filename(rank: int) -> str:
    return f"journal_rank{rank}.jsonl"


def _safe_fields(fields: dict) -> dict:
    """Strict-JSON view of free-form record fields: non-finite floats become
    ``null`` + ``<k>_repr`` (the MetricsLogger convention); non-scalar
    values are repr'd rather than risking a non-serializable record.
    One-level dicts of scalars flatten to dotted keys (``stats.ticks``) —
    the serve metrics drain emits grouped counters and a nested object
    would otherwise collapse to an unqueryable repr string; deeper
    nesting still falls through to repr."""
    out: dict = {}
    for k, v in fields.items():
        if isinstance(v, float) and not math.isfinite(v):
            out[k] = None
            out[f"{k}_repr"] = repr(v)
        elif v is None or isinstance(v, (str, int, float, bool)):
            out[k] = v
        elif isinstance(v, (list, tuple)) and all(
                e is None or isinstance(e, (str, int, bool))
                or (isinstance(e, float) and math.isfinite(e))
                for e in v):
            # flat scalar lists are valid strict JSON and survive as data
            # (the control plane's mask_before/mask_after fields); anything
            # nested or non-finite still falls through to repr
            out[k] = list(v)
        elif isinstance(v, dict) and all(
                isinstance(kk, str) and (
                    e is None or isinstance(e, (str, int, bool))
                    or (isinstance(e, float) and math.isfinite(e)))
                for kk, e in v.items()):
            for kk, e in v.items():
                out[f"{k}.{kk}"] = e
        else:
            out[k] = repr(v)
    return out


class _SpanCtx:
    """Context manager recording one span on exit (monotonic end time +
    duration). Exceptions propagate; the span still records, flagged
    ``error=True``, so a failing region is visible in the timeline."""

    __slots__ = ("_journal", "_name", "_fields", "_t0")

    def __init__(self, journal: "Journal", name: str, fields: dict):
        self._journal = journal
        self._name = name
        self._fields = fields

    def __enter__(self) -> "_SpanCtx":
        self._t0 = time.monotonic()
        return self

    def set(self, **fields) -> None:
        """Attach fields computed INSIDE the span; recorded at exit."""
        self._fields.update(fields)

    def __exit__(self, exc_type, exc, tb) -> bool:
        dur = time.monotonic() - self._t0
        fields = self._fields
        if exc_type is not None:
            fields = {**fields, "error": True}
        self._journal.record({"kind": "span", "name": self._name,
                              "dur": round(dur, 9), **fields})
        return False


class Journal:
    """Thread-safe, rank-stamped span/event recorder (see module doc).

    ``directory=None`` runs ring-only (no file sink) — bench harnesses use
    this to compute an attribution summary without touching disk.
    """

    def __init__(self, directory: Optional[str], rank: int = 0, *,
                 max_bytes: int = DEFAULT_MAX_BYTES, ring: int = DEFAULT_RING):
        self.rank = int(rank)
        self.directory = str(directory) if directory else None
        self.max_bytes = int(max_bytes)
        # RLock, not Lock: rotation runs inside record()'s critical section
        # and re-enters record() to anchor the fresh file's meta record
        self._lock = threading.RLock()
        self._ring: collections.deque = collections.deque(maxlen=ring)
        self._fh = None
        self._bytes = 0
        self._rotations = 0
        self._sink_failed = False
        if self.directory:
            os.makedirs(self.directory, exist_ok=True)
            self._rotations = self._next_rotation_seq()
            self._open_sink()
        self._write_meta()

    # ------------------------------------------------------------------ sink
    def _path(self) -> str:
        return os.path.join(self.directory, journal_filename(self.rank))

    def _next_rotation_seq(self) -> int:
        stem = journal_filename(self.rank)[:-len(".jsonl")]
        seqs = [0]
        try:
            for name in os.listdir(self.directory):
                if name.startswith(stem + ".") and name.endswith(".jsonl"):
                    mid = name[len(stem) + 1:-len(".jsonl")]
                    if mid.isdigit():
                        seqs.append(int(mid) + 1)
        except OSError:
            pass
        return max(seqs)

    def _open_sink(self) -> None:
        """Open (or re-open) the live file, truncating a torn tail left by
        a crash mid-write: newline-terminated records are the durability
        unit, so everything after the last newline was never committed."""
        path = self._path()
        recovered = 0
        if os.path.exists(path):
            with open(path, "rb") as f:
                raw = f.read()
            if raw and not raw.endswith(b"\n"):
                keep = raw.rfind(b"\n") + 1  # 0 when no newline at all
                recovered = len(raw) - keep
                with open(path, "r+b") as f:
                    f.truncate(keep)
        self._fh = open(path, "a", encoding="utf-8")
        self._bytes = os.path.getsize(path)
        if recovered:
            self.event("journal_recovered", torn_bytes=recovered)

    def _rotate(self) -> None:
        """Atomic rotation: flush + close the live file, ``os.replace`` it
        to its sequence name, open a fresh live file and re-anchor a meta
        record so the new file is independently analyzable."""
        self._fh.flush()
        self._fh.close()
        stem = journal_filename(self.rank)[:-len(".jsonl")]
        os.replace(self._path(), os.path.join(
            self.directory, f"{stem}.{self._rotations}.jsonl"))
        self._rotations += 1
        self._fh = open(self._path(), "a", encoding="utf-8")
        self._bytes = 0
        self._write_meta(rotated=self._rotations)

    def _write_meta(self, **extra) -> None:
        self.record({"kind": "meta", "name": "journal_start",
                     "wall": time.time(), "pid": os.getpid(),
                     "version": SCHEMA_VERSION, **extra})

    # ------------------------------------------------------------- recording
    def record(self, rec: dict) -> None:
        """Append one record (``t``/``rank`` stamped here). Sink I/O errors
        disable the file sink loudly; the ring keeps recording."""
        rec = {"kind": rec.get("kind", "event"),
               "name": str(rec.get("name", "")),
               "t": round(time.monotonic(), 9), "rank": self.rank,
               **_safe_fields({k: v for k, v in rec.items()
                               if k not in ("kind", "name")})}
        with self._lock:
            self._ring.append(rec)
            if self._fh is None or self._sink_failed:
                return
            line = json.dumps(rec, allow_nan=False)
            try:
                if resilience.consume_fault_count("journal_torn_write"):
                    # simulated death mid-write: half the record, no
                    # newline, then the failure surfaces like real I/O
                    self._fh.write(line[:max(len(line) // 2, 1)])
                    self._fh.flush()
                    raise OSError("injected torn journal write")
                self._fh.write(line + "\n")
                self._bytes += len(line) + 1
            except OSError as e:
                self._sink_failed = True
                print(f"[journal] sink write failed ({e}); journal file "
                      "DISABLED for the rest of this run — the in-memory "
                      "ring keeps recording", file=sys.stderr, flush=True)
                return
            if self._bytes >= self.max_bytes:
                try:
                    self._rotate()
                except OSError as e:
                    self._sink_failed = True
                    print(f"[journal] rotation failed ({e}); journal file "
                          "DISABLED for the rest of this run",
                          file=sys.stderr, flush=True)

    def event(self, name: str, **fields) -> None:
        self.record({"kind": "event", "name": name, **fields})

    def span(self, name: str, **fields) -> _SpanCtx:
        """``with journal.span("data_wait", step=n): ...`` — records the
        region's host wall time on exit."""
        return _SpanCtx(self, name, fields)

    def log(self, msg: str, stream: str = "stdout") -> None:
        self.record({"kind": "log", "name": "log", "msg": str(msg),
                     "stream": stream})

    # -------------------------------------------------------------- plumbing
    def tail(self) -> list:
        """The ring buffer's records, oldest first — the crash bundle's
        ``journal_tail.jsonl`` payload."""
        with self._lock:
            return list(self._ring)

    def records(self) -> list:
        """Alias of :meth:`tail` for ring-only journals (bench harnesses
        feed this straight to ``run_analyze.attribute``)."""
        return self.tail()

    def flush(self) -> None:
        with self._lock:
            if self._fh is not None and not self._sink_failed:
                self._fh.flush()

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.flush()
                    self._fh.close()
                except OSError:
                    pass  # a dead sink at teardown has already been
                    # reported by the write path; close must not mask the
                    # run's real exit status  # graft: disable=DLT006
                self._fh = None


class _NullJournal:
    """No-op stand-in with the full :class:`Journal` surface, so call sites
    never branch on whether journaling is enabled."""

    rank = 0
    directory = None

    def record(self, rec: dict) -> None:
        pass

    def event(self, name: str, **fields) -> None:
        pass

    def span(self, name: str, **fields) -> "_NullSpan":
        return _NULL_SPAN

    def log(self, msg: str, stream: str = "stdout") -> None:
        pass

    def tail(self) -> list:
        return []

    records = tail

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class _NullSpan:
    def __enter__(self):
        return self

    def set(self, **fields):
        pass

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()
NULL = _NullJournal()

# ---------------------------------------------------------------- the emitter
# The ONE stdout/stderr emitter for train/ and data/ modules (graft-check
# DLT009 pins this: a bare print() there bypasses the journal, so the
# control plane loses the event). Messages mirror to the console exactly as
# before AND land in the active journal as `log` records.
_ACTIVE: Optional[Journal] = None


def install(journal: Journal) -> None:
    """Make ``journal`` the process's active journal — module-level
    ``emit``/``event`` route to it. Latest install wins (one Trainer at a
    time owns the stream; tests create/tear down many)."""
    global _ACTIVE
    _ACTIVE = journal


def uninstall(journal: Journal) -> None:
    """Release the active slot if ``journal`` still owns it."""
    global _ACTIVE
    if _ACTIVE is journal:
        _ACTIVE = None


def active() -> Any:
    return _ACTIVE if _ACTIVE is not None else NULL


def emit(msg: str, *, stderr: bool = False, record: bool = True) -> None:
    """Print ``msg`` (stdout by default, flushed — byte-for-byte what the
    old bare prints produced) and record it in the active journal.
    ``record=False`` is for streams that already have their own durable
    sink (the MetricsLogger console line: its record IS metrics.jsonl)."""
    print(msg, file=sys.stderr if stderr else sys.stdout, flush=True)
    if record and _ACTIVE is not None:
        _ACTIVE.log(msg, stream="stderr" if stderr else "stdout")


def event(name: str, **fields) -> None:
    """Record an event into the active journal (no console output) — for
    modules that don't hold a journal reference (data/native_loader's
    shard-retry counters)."""
    if _ACTIVE is not None:
        _ACTIVE.event(name, **fields)
