from distributed_lion_tpu.train.schedule import (
    cosine_schedule_with_warmup,
    linear_schedule_with_warmup,
    constant_schedule,
)
