"""Control plane: one authoritative per-worker membership lifecycle.

Before this module the host side ran three separate state machines that
each owned a slice of "is worker w trustworthy": the NaN sentinel
(train/telemetry + the trainer's ``_check_sentinel``) watched for
nonfinite losses, the PreemptionGuard (train/resilience) watched for
SIGTERM, and the vote guard (train/vote_guard) struck/quarantined/
readmitted sick voters. None of them could express the production event
on a preemptible fleet — *a worker left, keep training; it came back,
re-absorb it* — so losing a host meant a full process restart through
``--elastic_resume`` even though the masked elections (PR 5) already
train correctly on a degraded quorum.

This module unifies them. :class:`ControlPlane` consumes every signal —
the guard's per-dispatch observations, injected membership faults
(``worker_drop:<w>[:<start>]`` / ``worker_rejoin:<w>:<step>`` through the
PR-3 registry), the preemption flag, the sentinel's worker attribution —
and drives ONE lifecycle per worker::

    healthy ──strikes──▶ suspect ──threshold──▶ quarantined
       ▲                                            │
       │ probe ok                        cooldown   │   repeated
       │                                 readmit ◀──┘   quarantines
    rejoining ◀──worker_rejoin── departed ◀─────────────(or injected
                                                         drop / preempt)

whose single output is the ``alive`` mask the masked elections in
``parallel/collectives`` already accept (via ``LionState.health``). A
departure is a mask transition at the next dispatch boundary — training
continues at W−1 with elections over the healthy quorum, no checkpoint
round-trip — and a rejoin is an in-run heal: the trainer re-averages the
rejoiner's momentum from the healthy mean
(``optim.distributed_lion.heal_worker_momentum``, the same mean-preserving
machinery as the elastic-resume remap), resets its ballot history, and
the plane watches it through a ``--rejoin_probe_steps`` probation window
(a still-sick rejoiner goes straight back to departed, never into the
quarantine/readmit loop a dead host would cycle forever).

``departed`` differs from ``quarantined`` in exactly one way: no
automatic readmission. Quarantine is the guard's hypothesis that a worker
is transiently sick (cooldown, probe, re-strike); departure is knowledge
that it is GONE (preempted host, injected drop, or a worker the guard has
re-quarantined ``DEPART_AFTER_QUARANTINES`` times — at that point the
cooldown loop is evidence of a dead worker, not a noisy one).

Layering: host-side only (numpy + stdlib — importable without jax, like
train/vote_guard); it must NOT import ``optim`` or ``train.loop``. The
trainer owns all device-state surgery (momentum heal, prev-ballot reset,
mask push); this module only decides.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from distributed_lion_tpu.train import resilience
from distributed_lion_tpu.train.vote_guard import VoteGuard

# a worker the guard keeps re-quarantining is not transiently sick, it is
# gone: after this many quarantine events the plane escalates it to
# departed (no more cooldown/readmit cycles; only an explicit
# worker_rejoin brings it back)
DEPART_AFTER_QUARANTINES = 3

STATES = ("healthy", "suspect", "quarantined", "departed", "rejoining")


@dataclasses.dataclass
class PlaneEvents:
    """What one boundary changed, for the trainer to act on: workers that
    left / rejoined / were quarantined / readmitted, the momentum rows to
    heal from the healthy mean, the prev-ballot rows to reset, whether the
    device mask must be re-pushed, and human-readable log lines."""

    left: list          # (worker, cause) pairs
    rejoined: list      # worker indices re-absorbed this boundary
    quarantined: list   # guard quarantines (plane passthrough)
    readmitted: list    # guard cooldown readmissions (plane passthrough)
    heal: list          # momentum rows to re-average from the healthy mean
    reset_ballot: list  # prev-ballot rows to zero (rejoiners only)
    mask_changed: bool
    logs: list


def _new_events() -> PlaneEvents:
    return PlaneEvents([], [], [], [], [], [], False, [])


class ControlPlane:
    """The unified membership state machine (see module doc).

    Wraps (and owns the authority over) a :class:`VoteGuard`: the guard
    keeps its strike/outlier detection and cooldown bookkeeping, while the
    plane layers the departed/rejoining states on top and suppresses the
    guard's auto-readmission for workers it knows are gone. The ``alive``
    mask is always ``guard.healthy`` — the plane enforces its own states
    by editing that mask, so the trainer keeps exactly one mask source.
    """

    def __init__(self, guard: VoteGuard, world: int,
                 rejoin_probe_steps: int = 0,
                 dcn_pipeline_depth: int = 0, journal=None):
        if guard is None:
            raise ValueError(
                "the control plane drives the live membership mask through "
                "the vote guard's masked elections — construct it with a "
                "VoteGuard (the trainer auto-arms 'enforce' when "
                "--control_plane is on)")
        if guard.world != int(world):
            raise ValueError(f"guard world {guard.world} != plane world "
                             f"{world}")
        self.guard = guard
        self.world = int(world)
        # 0 = auto: the guard's cooldown is the natural probation length —
        # the same window a quarantined worker must survive
        self.rejoin_probe_steps = (int(rejoin_probe_steps)
                                   or guard.cooldown_steps)
        if self.rejoin_probe_steps < 1:
            raise ValueError(f"rejoin_probe_steps must be >= 1, got "
                             f"{self.rejoin_probe_steps}")
        self.dcn_pipeline_depth = int(dcn_pipeline_depth)
        self._journal = journal
        self.departed: dict = {}          # worker -> cause
        # workers whose NEXT observation window must be discarded: the
        # guard runs one dispatch behind, so the first window after a
        # rejoin describes a dispatch the worker was still masked out of —
        # striking it for ballots it cast while gone would be judging the
        # wrong regime
        self._stale_obs = set()
        self.rejoining_until = np.full(self.world, -1, dtype=np.int64)
        self.quarantine_counts = np.zeros(self.world, dtype=np.int64)
        self.transitions = 0              # lifetime membership transitions
        self.left_events = 0
        self.rejoin_events = 0
        self._preempt_noted = False
        # highest boundary step whose membership schedule has been
        # consumed — rides checkpoints (manifest meta cp_sched_through) so
        # a resume does not REPLAY already-consumed drop/rejoin entries
        # (replaying a consumed rejoin would re-depart and re-heal the
        # worker at the resume boundary, diverging from the uninterrupted
        # run)
        self.sched_through = -1

    # ---------------------------------------------------------------- state
    def alive_mask(self) -> np.ndarray:
        return self.guard.healthy.copy()

    def lifecycle(self) -> list:
        """Per-worker state names — THE authoritative view the three old
        machines each held a slice of."""
        out = []
        for w in range(self.world):
            if w in self.departed:
                out.append("departed")
            elif not self.guard.healthy[w]:
                out.append("quarantined")
            elif self.rejoining_until[w] >= 0:
                out.append("rejoining")
            elif self.guard.strikes[w] > 0:
                out.append("suspect")
            else:
                out.append("healthy")
        return out

    def report(self) -> dict:
        """The guard's sick report extended with the plane's lifecycle —
        what crash bundles and the quorum refusal attach."""
        rep = self.guard.sick_report()
        rep["lifecycle"] = self.lifecycle()
        rep["departed"] = {str(w): c for w, c in sorted(self.departed.items())}
        return rep

    def summary(self) -> dict:
        """Scalar metrics for the logging cadence (strict-JSON friendly),
        merged beside the guard's own summary."""
        return {
            "cp_departed": len(self.departed),
            "cp_rejoining": int((self.rejoining_until >= 0).sum()),
            "cp_transitions": self.transitions,
        }

    def adopt(self, healthy, step: int, departed=None,
              sched_through=None, rejoining_until=None,
              quarantine_counts=None) -> None:
        """Resume path: adopt a checkpointed mask plus the manifest meta's
        departed set. Masked-out workers NOT named departed resume as
        plain quarantine (fresh cooldown — the guard's conservative
        reading); named ones stay departed with no auto-readmission. A
        plane-off checkpoint (departed=None) degrades to all-quarantined,
        the PR 5 semantics. ``sched_through`` restores the consumed
        membership-schedule watermark (meta ``cp_sched_through``) and
        drops the registry's already-consumed entries so the resumed run
        never replays them. ``rejoining_until``/``quarantine_counts``
        restore mid-run probation windows and quarantine history (meta
        ``cp_rejoining_until``/``cp_quarantine_counts``) so a crash
        mid-probation resumes the probe-fail rule — a still-sick rejoiner
        departs on its first re-strike, like the uninterrupted run;
        wrong-length lists (e.g. an elastic-resume world change, where
        the membership machine restarts fresh anyway) are ignored."""
        self.guard.adopt_mask(healthy, step)
        self.departed = {}
        self._stale_obs.clear()
        self.rejoining_until[:] = -1
        self.quarantine_counts[:] = 0
        if rejoining_until is not None and len(rejoining_until) == self.world:
            self.rejoining_until[:] = [int(x) for x in rejoining_until]
        if (quarantine_counts is not None
                and len(quarantine_counts) == self.world):
            self.quarantine_counts[:] = [int(x) for x in quarantine_counts]
        if sched_through is not None:
            self.sched_through = int(sched_through)
            pending = resilience.fault("membership")
            if pending:
                resilience.inject_fault(
                    "membership",
                    [m for m in pending if int(m[2]) > self.sched_through])
        for w in (departed or []):
            w = int(w)
            if not 0 <= w < self.world:
                raise ValueError(f"departed worker {w} outside world "
                                 f"{self.world}")
            self.departed[w] = "resumed"
            self.guard.healthy[w] = False

    # ----------------------------------------------------------- transitions
    def _emit_transition(self, events: PlaneEvents, name: str, worker: int,
                         step: int, cause: str, before: np.ndarray) -> None:
        self.transitions += 1
        after = self.alive_mask()
        if self._journal is not None:
            self._journal.event(
                name, worker=int(worker), step=int(step), cause=cause,
                alive=int(after.sum()), world=self.world,
                mask_before=[bool(b) for b in before],
                mask_after=[bool(b) for b in after])
            if name in ("worker_left", "worker_rejoined"):
                # the generic stream carries every transition too, so a
                # timeline consumer needs exactly one event name
                self._journal.event(
                    "membership_transition", worker=int(worker),
                    step=int(step), cause=cause, transition=name,
                    alive=int(after.sum()), world=self.world)
        events.mask_changed = True

    def _depart(self, events: PlaneEvents, worker: int, step: int,
                cause: str) -> None:
        if worker in self.departed:
            return  # already gone; a second signal is not a transition
        before = self.alive_mask()
        self.departed[worker] = cause
        self.guard.healthy[worker] = False
        self.guard.strikes[worker] = 0
        # pin the quarantine stamp so the guard's cooldown never elapses
        # for a departed worker (refreshed every observe() too)
        self.guard.quarantined_at[worker] = step
        self.rejoining_until[worker] = -1
        self.left_events += 1
        events.left.append((worker, cause))
        events.logs.append(
            f"worker {worker} LEFT at step {step} ({cause}); training "
            f"continues at {int(self.guard.healthy.sum())}/{self.world} "
            "— elections over the healthy quorum, no restart")
        self._emit_transition(events, "worker_left", worker, step, cause,
                              before)

    def _rejoin(self, events: PlaneEvents, worker: int, step: int) -> None:
        if worker not in self.departed:
            events.logs.append(
                f"worker_rejoin:{worker} at step {step} ignored — the "
                "worker never left (lifecycle "
                f"{self.lifecycle()[worker]!r})")
            return
        if self.dcn_pipeline_depth > 0:
            # the PR 8 elastic rule, extended to the in-run path: the DCN
            # ring's slots are in-flight level-2 tallies whose chunk
            # ownership is a function of the membership — a rejoiner's
            # slots hold tallies it never launched. Refuse loudly rather
            # than invent their meaning.
            raise RuntimeError(
                f"control plane: worker_rejoin:{worker} at step {step} "
                f"with --dcn_pipeline_depth {self.dcn_pipeline_depth}: "
                "the in-flight DCN tally ring cannot re-absorb a worker "
                "mid-flight (its ring slots hold level-2 tallies it never "
                "launched — the same reason --elastic_resume refuses "
                "depth > 0). Drain the pipeline first: restart with "
                "--dcn_pipeline_depth 0, or rejoin at the next fresh start")
        before = self.alive_mask()
        cause = self.departed.pop(worker)
        self.guard.healthy[worker] = True
        self.guard.strikes[worker] = 0
        self.guard.quarantined_at[worker] = -1
        # clean slate: the pre-departure quarantine history must not put
        # the re-absorbed worker on a hair-trigger to re-departure (one
        # later transient quarantine would otherwise re-cross
        # DEPART_AFTER_QUARANTINES immediately)
        self.quarantine_counts[worker] = 0
        self.rejoining_until[worker] = step + self.rejoin_probe_steps
        self._stale_obs.add(worker)
        self.rejoin_events += 1
        events.rejoined.append(worker)
        events.heal.append(worker)
        events.reset_ballot.append(worker)
        events.logs.append(
            f"worker {worker} REJOINED at step {step} (left: {cause}): "
            "momentum re-averaged from the healthy mean, ballot history "
            f"reset; on probation for {self.rejoin_probe_steps} steps "
            "(a still-sick rejoiner departs again)")
        self._emit_transition(events, "worker_rejoined", worker, step,
                              "rejoin", before)

    def membership_due(self, step: int) -> PlaneEvents:
        """Consume the ``membership`` fault registry's due entries —
        called at every dispatch boundary BEFORE the dispatch, so a
        ``worker_drop:<w>:0`` masks the very first election. Drops apply
        before rejoins at the same boundary (so a same-step drop+rejoin
        pair heals the worker rather than silently ignoring the rejoin),
        schedule order within each kind."""
        self.sched_through = max(self.sched_through, int(step))
        events = _new_events()
        # one shared pop-the-due-entries helper with the serve-side
        # replica plane (resilience.consume_due): 'due at boundary b'
        # means the same thing to both lifecycles
        due = sorted(resilience.consume_due("membership", int(step)),
                     key=lambda m: (int(m[2]),
                                    0 if m[0] == "worker_drop" else 1))
        for kind, worker, at in due:
            worker = int(worker)
            if not 0 <= worker < self.world:
                raise ValueError(
                    f"membership fault {kind}:{worker} outside world "
                    f"{self.world}")
            if kind == "worker_drop":
                self._depart(events, worker, step, "injected_drop")
            else:
                self._rejoin(events, worker, step)
        return events

    def note_preempt(self, step: int) -> None:
        """The PreemptionGuard's flag, folded into the one event stream:
        the whole process is departing — every local worker's lifecycle
        ends here, and the journal records it as a membership transition
        (cause 'preempt') so the timeline explains the gap a restart
        leaves. The drain/emergency-checkpoint mechanics stay with the
        trainer; the plane only records."""
        if self._preempt_noted:
            return
        self._preempt_noted = True
        self.transitions += 1
        if self._journal is not None:
            self._journal.event(
                "membership_transition", step=int(step), cause="preempt",
                transition="process_departing", world=self.world,
                alive=int(self.guard.healthy.sum()))

    # --------------------------------------------------------------- observe
    def observe(self, step: int, obs: dict, advanced: int) -> PlaneEvents:
        """Fold one dispatch's guard observations through the guard, then
        apply the plane's authority: departed workers never auto-readmit,
        a failed probe departs instead of re-entering the cooldown loop,
        and repeated quarantines escalate to departure. Replaces the
        trainer's direct ``guard.update`` when the plane is on."""
        events = _new_events()
        if obs:
            if self._stale_obs:
                # one-window amnesty for fresh rejoiners (see _stale_obs)
                obs = dict(obs)
                for k in ("guard_nonfinite", "guard_frozen"):
                    if k in obs:
                        v = np.array(obs[k])
                        for w in self._stale_obs:
                            v[w] = 0
                        obs[k] = v
                if "guard_disagree" in obs:
                    # neutral substitution, NOT zero: the rejoiner's
                    # disagreement describes a dispatch it was masked out
                    # of, but a zero would drag the healthy-peer mean
                    # down and could flag an innocent borderline peer as
                    # an outlier — give it the peers' mean instead (every
                    # peer's relative baseline is unchanged, and it can
                    # never flag the rejoiner: mean > mean + margin is
                    # false)
                    v = np.array(obs["guard_disagree"], dtype=np.float64)
                    peers = [i for i in range(self.world)
                             if self.guard.healthy[i]
                             and i not in self._stale_obs]
                    fill = float(v[peers].mean()) if peers else 0.0
                    for w in self._stale_obs:
                        v[w] = fill
                    obs["guard_disagree"] = v
                self._stale_obs.clear()
            for w in self.departed:
                # refresh the pin: cooldown must never elapse while gone
                self.guard.quarantined_at[w] = step
            gev = self.guard.update(step, obs, advanced)
            events.quarantined.extend(gev.quarantined)
            events.readmitted.extend(gev.readmitted)
            events.heal.extend(gev.readmitted)
            events.mask_changed |= gev.mask_changed
            events.logs.extend(gev.logs)
            for w in gev.quarantined:
                self.quarantine_counts[w] += 1
                if 0 <= self.rejoining_until[w]:
                    # probe failure: a rejoiner that re-strikes inside its
                    # probation window is still gone — back to departed,
                    # not into the quarantine/readmit cycle
                    self.rejoining_until[w] = -1
                    self._depart(events, w, step, "probe_failed")
                elif self.quarantine_counts[w] >= DEPART_AFTER_QUARANTINES:
                    self._depart(events, w, step, "guard_strikes")
                else:
                    self.transitions += 1
                    if self._journal is not None:
                        self._journal.event(
                            "membership_transition", worker=int(w),
                            step=int(step), cause="guard_quarantine",
                            transition="quarantined",
                            alive=int(self.guard.healthy.sum()),
                            world=self.world)
            for w in gev.readmitted:
                self.transitions += 1
                if self._journal is not None:
                    self._journal.event(
                        "membership_transition", worker=int(w),
                        step=int(step), cause="guard_readmit",
                        transition="readmitted",
                        alive=int(self.guard.healthy.sum()),
                        world=self.world)
        # probation windows that elapsed cleanly: rejoining → healthy
        for w in range(self.world):
            if 0 <= self.rejoining_until[w] <= step and \
                    self.guard.healthy[w] and w not in self.departed:
                self.rejoining_until[w] = -1
                events.logs.append(
                    f"worker {w} probation complete at step {step}: "
                    "rejoining → healthy")
                if self._journal is not None:
                    self._journal.event(
                        "membership_transition", worker=int(w),
                        step=int(step), cause="probe_complete",
                        transition="healthy",
                        alive=int(self.guard.healthy.sum()),
                        world=self.world)
                self.transitions += 1
        return events

    def quorum_ok(self) -> bool:
        return self.guard.quorum_ok()

    def quorum_error(self, step: int) -> str:
        rep = self.report()
        return (
            f"control plane: healthy quorum "
            f"{int(self.guard.healthy.sum())}/{self.world} fell below "
            f"--min_quorum {self.guard.min_quorum} at step {step} — a "
            "majority election with a sick majority is noise, refusing to "
            f"continue. Lifecycle: {rep['lifecycle']}; departed: "
            f"{rep['departed']}; sick counters: {rep['sick_workers']}")


def make_control_plane(guard: Optional[VoteGuard], world: int,
                       rejoin_probe_steps: int, dcn_pipeline_depth: int,
                       journal=None) -> ControlPlane:
    """The trainer's constructor (mirrors vote_guard.make_guard)."""
    return ControlPlane(guard, world,
                        rejoin_probe_steps=rejoin_probe_steps,
                        dcn_pipeline_depth=dcn_pipeline_depth,
                        journal=journal)
