"""Vote guard: the host-side quarantine state machine.

signSGD-with-majority-vote is provably fault tolerant to a MINORITY of
adversarial voters (Bernstein et al., 2019) — but only if the run actually
cashes that guarantee in. This module is the decision half of the vote-guard
layer: the jitted step (optim.distributed_lion, ``guard != 'off'``) emits
cheap per-worker health signals every step — nonfinite ballot-input counts,
ballot-flip counts vs the previous vote (popcount XOR ≈ 0 ⇔ a frozen
voter), local-vs-elected disagreement fractions — and the trainer hands
them to :class:`VoteGuard` one dispatch behind (the NaN-sentinel pattern:
the device pipeline never stalls on the host read).

The machine is three per-worker registers and two thresholds:

- **strikes** accumulate one per bad observed step (a nonfinite input, a
  frozen ballot, an outlier disagreement) and decay one per clean dispatch —
  transient faults (one bad batch) never escalate, while an intermittent
  outlier still ratchets toward the threshold.
- at ``strike_threshold`` strikes a healthy worker is **quarantined**: in
  ``enforce`` mode the trainer flips its bit in the ``LionState.health``
  mask, so the masked election (parallel.collectives) excludes its ballots
  and the majority threshold shrinks to the healthy quorum. ``observe``
  mode runs the same bookkeeping but never touches the mask — it reports
  what enforce WOULD do.
- after ``cooldown_steps`` in quarantine the worker is **readmitted** as a
  probe: the trainer re-averages its momentum from the healthy mean
  (optim.distributed_lion.heal_worker_momentum — the same mean-preserving
  machinery as the elastic-resume remap) and clears its bit. A still-sick
  worker strikes out again within ``strike_threshold`` steps and returns
  to quarantine.

If the healthy quorum ever drops below ``min_quorum`` the trainer refuses
to continue (loud RuntimeError): a majority election with a sick majority
is not degraded-mode training, it is noise.

Layering: host-side only (numpy + stdlib — importable without jax, like
train/resilience's manifest readers); it must NOT import ``optim`` or
``train.loop``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

# Outlier rule, two arms that must BOTH fire: an absolute floor (honest
# voters in a healthy election sit well under this disagreement fraction;
# a noise-dominated one puts EVERYONE near 0.5, which the relative arm
# absorbs) and a relative margin over the mean of the worker's healthy
# peers — the test that separates "the election is noisy for everyone"
# from "this one voter is inverted/divergent". Calibrated against measured
# traces: honest workers cluster within ~±0.03 of each other while a
# flipped (sign-inverted) voter sits ~0.15 above the cluster; the peer
# mean INCLUDES the outlier when judging an honest worker, which widens
# the honest worker's bar and narrows the outlier's — the asymmetry that
# makes one adversary separable at these margins.
DISAGREE_ABS = 0.35
DISAGREE_MARGIN = 0.1

# metrics keys the jitted step emits per dispatch (the trainer pops them
# from the metrics dict before logging — they are [W] vectors / counters,
# not loggable scalars). Chunked dispatches SUM these over the scanned
# steps, so each is "count of steps" (or a summed fraction) per worker.
OBS_KEYS = ("guard_nonfinite", "guard_frozen", "guard_disagree",
            "guard_voted_steps")


@dataclasses.dataclass
class GuardEvents:
    """What one observation window changed: worker indices quarantined /
    readmitted (or, under observe, WOULD have been), whether the device
    mask must be re-pushed, and human-readable log lines."""

    quarantined: list
    readmitted: list
    mask_changed: bool
    logs: list


class VoteGuard:
    """Per-worker strike/quarantine/cooldown bookkeeping (see module doc)."""

    def __init__(self, world: int, mode: str, strike_threshold: int = 3,
                 cooldown_steps: int = 50, min_quorum: int = 0,
                 disagree_abs: float = DISAGREE_ABS,
                 disagree_margin: float = DISAGREE_MARGIN,
                 journal=None):
        if mode not in ("observe", "enforce"):
            raise ValueError(f"guard mode must be 'observe' or 'enforce', "
                             f"got {mode!r}")
        if strike_threshold < 1:
            raise ValueError(f"strike_threshold must be >= 1, got "
                             f"{strike_threshold}")
        if cooldown_steps < 1:
            raise ValueError(f"cooldown_steps must be >= 1, got "
                             f"{cooldown_steps}")
        self.world = int(world)
        self.mode = mode
        self.strike_threshold = int(strike_threshold)
        self.cooldown_steps = int(cooldown_steps)
        # 0 = auto: a strict majority must stay healthy — below that the
        # "election" no longer estimates anything
        self.min_quorum = int(min_quorum) or (self.world // 2 + 1)
        if not 1 <= self.min_quorum <= self.world:
            raise ValueError(
                f"min_quorum {self.min_quorum} outside [1, {self.world}]")
        self.disagree_abs = float(disagree_abs)
        self.disagree_margin = float(disagree_margin)
        # run-journal hook (train/journal.py; duck-typed — this module
        # stays importable without jax and without the journal): every
        # quarantine/readmission transition is recorded as an event, so
        # the control plane consumes the state machine as a stream instead
        # of scraping log lines
        self._journal = journal
        self.healthy = np.ones(self.world, dtype=bool)
        self.strikes = np.zeros(self.world, dtype=np.int64)
        self.quarantined_at = np.full(self.world, -1, dtype=np.int64)
        # cumulative per-worker signal counters (bad steps observed), kept
        # for the crash bundle / sentinel so a bundle can NAME the sick
        # worker, not just the poisoned leaves
        self.counters = {k: np.zeros(self.world, dtype=np.int64)
                         for k in ("nonfinite", "frozen", "outlier")}
        self.quarantine_events = 0
        self.readmit_events = 0

    # ---------------------------------------------------------------- state
    def healthy_count(self) -> int:
        return int(self.healthy.sum())

    def quorum_ok(self) -> bool:
        return self.healthy_count() >= self.min_quorum

    def adopt_mask(self, healthy, step: int) -> None:
        """Resume path: adopt a checkpointed health mask. Quarantined
        workers restart their cooldown at ``step`` (the original
        quarantine step is not persisted — a fresh probe window is the
        conservative reading)."""
        healthy = np.asarray(healthy, dtype=bool).reshape(-1)
        if healthy.shape[0] != self.world:
            raise ValueError(
                f"health mask has {healthy.shape[0]} workers, guard expects "
                f"{self.world}")
        self.healthy = healthy.copy()
        self.strikes[:] = 0
        self.quarantined_at[:] = -1
        self.quarantined_at[~self.healthy] = int(step)

    def sick_report(self) -> dict:
        """Per-worker health snapshot for crash bundles / operators: the
        mask, strikes, and every worker with a nonzero signal counter."""
        sick = {}
        for w in range(self.world):
            entry = {k: int(v[w]) for k, v in self.counters.items() if v[w]}
            if entry or not self.healthy[w]:
                entry["healthy"] = bool(self.healthy[w])
                sick[str(w)] = entry
        return {
            "mode": self.mode,
            "healthy_mask": [bool(h) for h in self.healthy],
            "strikes": [int(s) for s in self.strikes],
            "sick_workers": sick,
        }

    def sick_workers(self) -> list:
        """Workers currently quarantined or carrying nonzero counters —
        the names the NaN sentinel attaches to its trip reason."""
        flagged = ~self.healthy
        for v in self.counters.values():
            flagged = flagged | (v > 0)
        return [int(w) for w in np.nonzero(flagged)[0]]

    def summary(self) -> dict:
        """Scalar metrics for the logging cadence (strict-JSON friendly)."""
        return {
            "guard_healthy": self.healthy_count(),
            "guard_quarantined": self.world - self.healthy_count(),
            "guard_strikes_max": int(self.strikes.max(initial=0)),
            "guard_quarantine_events": self.quarantine_events,
            "guard_readmit_events": self.readmit_events,
        }

    # --------------------------------------------------------------- update
    def _outliers(self, disagree: np.ndarray, voted_steps: int) -> np.ndarray:
        """Per-worker outlier flags from the window's mean disagreement
        fractions. Absolute + relative-to-healthy-peers test; workers with
        no healthy peer to compare against are never flagged by the
        relative arm alone."""
        out = np.zeros(self.world, dtype=bool)
        if voted_steps <= 0:
            return out
        dis = disagree / voted_steps
        for w in range(self.world):
            if dis[w] <= self.disagree_abs:
                continue
            peers = dis[[i for i in range(self.world)
                         if i != w and self.healthy[i]]]
            base = float(peers.mean()) if peers.size else 0.0
            if dis[w] > base + self.disagree_margin:
                out[w] = True
        return out

    def update(self, step: int, obs: dict, advanced: int) -> GuardEvents:
        """Fold one dispatch's summed observations (``OBS_KEYS``, already
        host numpy) covering ``advanced`` optimizer steps ending at
        ``step``. Returns the transitions for the trainer to act on."""
        nonfinite = np.asarray(obs["guard_nonfinite"]).reshape(-1)
        frozen = np.asarray(obs["guard_frozen"]).reshape(-1)
        disagree = np.asarray(obs["guard_disagree"], dtype=np.float64
                              ).reshape(-1)
        voted_steps = int(np.asarray(obs["guard_voted_steps"]).reshape(())
                          ) if "guard_voted_steps" in obs else advanced
        outlier = self._outliers(disagree, voted_steps)

        # bad steps per worker this window: nonfinite and frozen arrive as
        # counts of bad steps from the device; an outlier verdict covers
        # the whole window
        bad_steps = np.clip(nonfinite, 0, advanced).astype(np.int64)
        bad_steps = np.maximum(bad_steps,
                               np.clip(frozen, 0, advanced).astype(np.int64))
        bad_steps = np.maximum(bad_steps,
                               np.where(outlier, advanced, 0))
        self.counters["nonfinite"] += np.clip(nonfinite, 0, advanced
                                              ).astype(np.int64)
        self.counters["frozen"] += np.clip(frozen, 0, advanced
                                           ).astype(np.int64)
        self.counters["outlier"] += np.where(outlier, advanced, 0
                                             ).astype(np.int64)

        events = GuardEvents([], [], False, [])
        would = "" if self.mode == "enforce" else "[observe] would have "
        for w in range(self.world):
            if self.healthy[w]:
                if bad_steps[w] > 0:
                    self.strikes[w] += int(bad_steps[w])
                else:
                    # a clean window forgives gradually (decay, not reset):
                    # transient faults still never escalate, but an
                    # INTERMITTENT outlier that flags most windows keeps
                    # ratcheting toward the threshold
                    self.strikes[w] = max(0, int(self.strikes[w]) - 1)
                if self.strikes[w] >= self.strike_threshold:
                    self.healthy[w] = False
                    self.quarantined_at[w] = step
                    self.strikes[w] = 0
                    self.quarantine_events += 1
                    events.quarantined.append(w)
                    events.mask_changed = True
                    sig = [k for k, v in (("nonfinite", nonfinite[w]),
                                          ("frozen", frozen[w]),
                                          ("outlier", outlier[w])) if v]
                    events.logs.append(
                        f"{would}QUARANTINED worker {w} at step {step} "
                        f"({'+'.join(sig) or 'strikes'}); healthy quorum "
                        f"{self.healthy_count()}/{self.world}")
                    if self._journal is not None:
                        self._journal.event(
                            "guard_quarantine", worker=int(w),
                            step=int(step), mode=self.mode,
                            signals="+".join(sig) or "strikes",
                            healthy=self.healthy_count())
            else:
                if step - self.quarantined_at[w] >= self.cooldown_steps:
                    self.healthy[w] = True
                    self.quarantined_at[w] = -1
                    self.strikes[w] = 0
                    self.readmit_events += 1
                    events.readmitted.append(w)
                    events.mask_changed = True
                    events.logs.append(
                        f"{would}READMITTED worker {w} at step {step} "
                        "(cooldown elapsed; momentum re-averaged from the "
                        "healthy mean — a still-sick worker re-strikes)")
                    if self._journal is not None:
                        self._journal.event(
                            "guard_readmit", worker=int(w), step=int(step),
                            mode=self.mode, healthy=self.healthy_count())
        return events


def parse_guard_mode(mode: str) -> str:
    if mode not in ("off", "observe", "enforce"):
        raise ValueError(
            f"--vote_guard {mode!r}: expected 'off' (no guard), 'observe' "
            "(detect + report, elections untouched) or 'enforce' (masked "
            "elections + quarantine + readmission healing)")
    return mode


def make_guard(world: int, mode: str, strike_threshold: int,
               cooldown_steps: int, min_quorum: int,
               journal=None) -> Optional[VoteGuard]:
    """The trainer's constructor: None when the guard is off."""
    if parse_guard_mode(mode) == "off":
        return None
    return VoteGuard(world, mode, strike_threshold=strike_threshold,
                     cooldown_steps=cooldown_steps, min_quorum=min_quorum,
                     journal=journal)
