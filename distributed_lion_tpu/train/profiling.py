"""Tracing / profiling hooks for the train loop.

The reference has no profiling at all (SURVEY §5: no profiler imports; its
only perf statement is README.md:2's "currently slow" admission). Here
profiling is a first-class trainer subsystem:

- :class:`StepProfiler` — captures a ``jax.profiler`` device trace (viewable
  in TensorBoard / Perfetto) for a configurable window of steps, and tags
  every step with ``StepTraceAnnotation`` so the trace viewer groups ops by
  step. Capturing a bounded window (not the whole run) keeps trace files
  small and the steady-state steps representative.
- :class:`StepTimer` — lightweight wall-clock EMA of step latency with
  percentile tracking, always on (no device sync: it times the *dispatch*
  cadence which equals steady-state step time once the pipeline fills).
- :func:`comm_report` — analytic bytes-on-the-wire accounting for the vote
  collective (ops/codec.wire_bytes_per_param), the number BASELINE.md's
  ≤1/32-of-bf16-all-reduce budget is judged against.
"""

from __future__ import annotations

import collections
import time
from typing import Optional

import numpy as np

from distributed_lion_tpu.ops.codec import wire_bytes_per_param
from distributed_lion_tpu.train.journal import emit


class StepProfiler:
    """Trace steps [start_step, start_step + num_steps) to ``trace_dir``.

    Inactive when ``trace_dir`` is None — zero overhead beyond an int
    compare per step. ``annotate()`` returns a ``StepTraceAnnotation``
    context while tracing (so ops group per-step in the viewer) and a
    null context otherwise.
    """

    def __init__(self, trace_dir: Optional[str], start_step: int = 10,
                 num_steps: int = 3):
        self.trace_dir = trace_dir
        self.start_step = int(start_step)
        self.num_steps = int(num_steps)
        self.stop_step = self.start_step + self.num_steps
        self._active = False
        self._done = False

    def maybe_start(self, step: int) -> None:
        # >= (not ==) so a checkpoint-resumed run that re-enters past the
        # configured start still captures a window (anchored at the first
        # step it actually sees)
        if (self.trace_dir and not self._active and not self._done
                and step >= self.start_step):
            import jax

            jax.profiler.start_trace(self.trace_dir)
            self.stop_step = step + self.num_steps
            self._active = True

    def annotate(self, step: int):
        if self._active:
            import jax

            return jax.profiler.StepTraceAnnotation("train", step_num=step)
        import contextlib

        return contextlib.nullcontext()

    def maybe_stop(self, step: int, sync=None) -> None:
        """Stop at the window end. ``sync`` (e.g. the last metrics pytree) is
        block_until_ready'd first so in-flight device work lands in the
        trace."""
        if self._active and step >= self.stop_step:
            import jax

            if sync is not None:
                jax.block_until_ready(sync)
            jax.profiler.stop_trace()
            self._active = False
            self._done = True
            emit(f"[profiler] trace for steps [{self.start_step}, "
                 f"{self.stop_step}) written to {self.trace_dir}")

    def close(self, sync=None) -> None:
        if self._active:
            self.maybe_stop(self.stop_step, sync)


class StepTimer:
    """Step-latency stats from dispatch timestamps: EMA + p50/p95 over a
    sliding window."""

    def __init__(self, ema_alpha: float = 0.1, window: int = 256):
        self.alpha = ema_alpha
        self.window = window
        # deque(maxlen) evicts in O(1); the old list.pop(0) shifted the
        # whole 256-sample window on every steady-state step
        self._samples: collections.deque[float] = collections.deque(
            maxlen=window)
        self.ema: Optional[float] = None
        self._last: Optional[float] = None

    def tick(self, n_steps: int = 1) -> Optional[float]:
        """Call once per dispatch covering ``n_steps`` optimizer steps;
        returns per-step latency (None on first call)."""
        now = time.perf_counter()
        if self._last is None:
            self._last = now
            return None
        dt = (now - self._last) / max(n_steps, 1)
        self._last = now
        self.ema = dt if self.ema is None else self.alpha * dt + (1 - self.alpha) * self.ema
        self._samples.append(dt)
        return dt

    def stats(self) -> dict:
        if not self._samples:
            return {}
        arr = np.asarray(self._samples)
        return {
            "step_time_ema_s": float(self.ema),
            "step_time_p50_s": float(np.percentile(arr, 50)),
            "step_time_p95_s": float(np.percentile(arr, 95)),
        }


def peak_hbm_per_device() -> Optional[list[float]]:
    """Peak device-memory high-water mark in GiB for EVERY local device (in
    ``jax.local_devices()`` order), or None where the backend exposes no
    memory_stats (host CPU). Per-device values matter because sharded
    workloads are limited by the WORST device — an imbalanced shard or a
    stray buffer on one chip is invisible in a device-0-only reading."""
    try:
        import jax

        out = []
        for d in jax.local_devices():
            ms = d.memory_stats()
            if not ms or "peak_bytes_in_use" not in ms:
                return None
            out.append(round(ms["peak_bytes_in_use"] / 2**30, 3))
        return out or None
    except Exception:  # graft: disable=DLT006
        return None  # metric probe, not a code path: any backend without
        # (or with quirky) memory_stats must read as "no HBM metric", never
        # take down the training loop that polls this at log cadence


def peak_hbm_gb() -> Optional[float]:
    """The high-water mark across ALL local devices (the number an OOM is
    actually decided by), not device 0's alone."""
    per = peak_hbm_per_device()
    return max(per) if per else None


def comm_report(num_params: int, world: int, wire: str,
                steps_per_sec: Optional[float] = None,
                vote_every: int = 1, accum_steps: int = 1,
                vote_buckets: int = 1, dcn_pipeline_depth: int = 0) -> dict:
    """Vote-collective wire accounting (+ bandwidth when a rate is known).

    ``comm_overlap_frac`` is the ANALYTIC pipelineable share of the wire
    under ``vote_buckets`` bucketing: the optimizer overlaps bucket k's
    collective with bucket k−1's fused apply, so every bucket after the
    first can ride behind compute — 0.0 for the monolithic vote, ≈(B−1)/B
    for B equal buckets. The measured counterpart (step-time actually
    recovered on hardware) comes from bench.py's overlap-ablation rows.

    ``dcn_overlap_frac`` (hier wire only) is the analytic share of the
    level-2 (DCN) leg's LATENCY eligible to leave the critical path under
    ``--dcn_pipeline_depth``: 1.0 once the leg rides the cross-step ring
    (depth ≥ 1 — the whole round trip hides behind d steps of compute),
    0.0 for the synchronous wire. Bytes are depth-invariant. The measured
    counterpart comes from the bench_dcn ablation (scripts/bench_dcn.py).
    """
    acct = wire_bytes_per_param(num_params, world, wire,
                                vote_every=vote_every, accum_steps=accum_steps,
                                vote_buckets=vote_buckets,
                                dcn_pipeline_depth=dcn_pipeline_depth)
    out = {
        "wire": acct["wire"],
        "comm_bytes_per_step": acct["bytes_per_step"],
        "comm_bits_per_param": acct["bits_per_param"],
        "comm_bits_per_param_per_microbatch": acct["bits_per_param_per_microbatch"],
        "vote_buckets": acct["vote_buckets"],
        "comm_overlap_frac": acct["overlappable_wire_frac"],
        "vs_bf16_allreduce": acct["vs_bf16_allreduce"],
        "vs_reference_wire": acct["bytes_per_step"]
        / max(acct["reference_bytes_per_step"], 1),
    }
    if "dcn_bytes_per_step" in acct:  # hier wire: the slow-fabric leg alone
        out["comm_dcn_bytes_per_step"] = acct["dcn_bytes_per_step"]
        out["comm_dcn_bits_per_param"] = acct["dcn_bits_per_param"]
        out["dcn_pipeline_depth"] = acct["dcn_pipeline_depth"]
        out["dcn_overlap_frac"] = acct["dcn_overlap_frac"]
    if steps_per_sec:
        out["comm_mbytes_per_sec"] = acct["bytes_per_step"] * steps_per_sec / 1e6
    return out
