"""LR schedules.

The reference pairs every optimizer with HF's
``get_cosine_schedule_with_warmup`` (/root/reference/run_clm.py:582,
sft_llama2.py:165, dpo_llama2.py:211; canonical config: 2k warmup of 100k
steps, README.md:26-27). These are pure ``step -> multiplier·peak`` functions
usable directly as the ``learning_rate`` of any optimizer here.
"""

from __future__ import annotations

import jax.numpy as jnp


def cosine_schedule_with_warmup(
    peak_lr: float,
    warmup_steps: int,
    total_steps: int,
    num_cycles: float = 0.5,
    min_ratio: float = 0.0,
):
    """Bit-parity with transformers.get_cosine_schedule_with_warmup:
    linear 0→peak over ``warmup_steps``, then cosine to ``min_ratio``·peak
    over the remainder (num_cycles=0.5 → a single half-cosine to 0)."""

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warm = step / jnp.maximum(1.0, warmup_steps)
        progress = (step - warmup_steps) / jnp.maximum(1.0, total_steps - warmup_steps)
        cos = 0.5 * (1.0 + jnp.cos(jnp.pi * num_cycles * 2.0 * progress))
        mult = jnp.where(step < warmup_steps, warm, jnp.maximum(min_ratio, cos))
        return peak_lr * mult

    return schedule


def linear_schedule_with_warmup(peak_lr: float, warmup_steps: int, total_steps: int):
    """Parity with transformers.get_linear_schedule_with_warmup."""

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warm = step / jnp.maximum(1.0, warmup_steps)
        decay = (total_steps - step) / jnp.maximum(1.0, total_steps - warmup_steps)
        return peak_lr * jnp.where(step < warmup_steps, warm, jnp.maximum(0.0, decay))

    return schedule


def constant_schedule(peak_lr: float):
    def schedule(step):
        return jnp.full((), peak_lr, jnp.float32)

    return schedule
