"""Metrics logging: stdout + JSONL + optional wandb.

Replaces the reference's HF `trainer.log_metrics`/wandb reporting
(/root/reference/run_clm.py:620-621, README.md:28). The reference calls
``wandb.login`` with a hardcoded API credential (run_clm.py:58-59 — a leaked
secret); here wandb activates ONLY when ``WANDB_API_KEY`` is present in the
environment (env-var/netrc auth, never a literal key in code).
"""

from __future__ import annotations

import json
import math
import os
import pathlib
import time
from typing import Optional

from distributed_lion_tpu.train.journal import emit


class MetricsLogger:
    def __init__(self, output_dir: Optional[str] = None, run_name: str = "run",
                 use_wandb: bool = False):
        self.jsonl = None
        if output_dir:
            path = pathlib.Path(output_dir)
            path.mkdir(parents=True, exist_ok=True)
            self.jsonl = open(path / "metrics.jsonl", "a", buffering=1)
        self.wandb = None
        if use_wandb and os.environ.get("WANDB_API_KEY"):
            try:
                import wandb

                wandb.init(project=os.environ.get("WANDB_PROJECT", "distributed-lion-tpu"),
                           name=run_name)
                self.wandb = wandb
            except Exception as e:  # offline / not installed: degrade to local logs
                emit(f"[metrics] wandb unavailable ({e}); logging locally",
                     stderr=True)
        self._t0 = time.time()

    def log(self, step: int, metrics: dict, prefix: str = "train") -> None:
        record = {"step": step, "elapsed_s": round(time.time() - self._t0, 3)}
        sep = "/" if prefix else ""
        record.update({f"{prefix}{sep}{k}": _scalar(v) for k, v in metrics.items()})
        line = " ".join(f"{k}={v:.6g}" if isinstance(v, float) else f"{k}={v}"
                        for k, v in record.items())
        # record=False: the metrics stream's durable form IS metrics.jsonl
        # below — duplicating every row into the run journal would bloat it
        # with data the analyzer reads from the metrics file anyway
        emit(line, record=False)
        if self.jsonl:
            # allow_nan=False: json.dumps(nan) silently emits the bare token
            # `NaN`, which is NOT JSON — every strict consumer downstream
            # (jq, pandas read_json, check_evidence) chokes on the whole
            # line. Non-finite floats become null with the raw value
            # preserved under "<k>_repr" (jsonable_record), and the flag
            # turns any future regression into a loud error instead of a
            # corrupt log. scripts/validate_metrics.py is the CI check.
            self.jsonl.write(
                json.dumps(jsonable_record(record), allow_nan=False) + "\n")
        if self.wandb:
            self.wandb.log(record, step=step)

    def close(self) -> None:
        if self.jsonl:
            self.jsonl.close()
        if self.wandb:
            self.wandb.finish()


def _scalar(v):
    try:
        return float(v)
    except (TypeError, ValueError):
        return v


def jsonable_record(record: dict) -> dict:
    """Strict-JSON view of a flat metrics record: NaN/±Inf floats become
    ``null`` with the raw value preserved as a string under ``"<k>_repr"``
    (so a diverged loss is still visible in the log, in valid JSON). Lists
    (e.g. the vote-margin histogram, per-device HBM) are sanitized
    elementwise — a non-finite element inside one would corrupt the line
    just the same."""
    out: dict = {}
    for k, v in record.items():
        if isinstance(v, float) and not math.isfinite(v):
            out[k] = None
            out[f"{k}_repr"] = repr(v)
        elif isinstance(v, (list, tuple)):
            out[k] = [None if isinstance(x, float) and not math.isfinite(x)
                      else x for x in v]
            if any(isinstance(x, float) and not math.isfinite(x) for x in v):
                out[f"{k}_repr"] = repr(list(v))
        else:
            out[k] = v
    return out
