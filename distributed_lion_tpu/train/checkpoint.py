"""Checkpoint save/restore with Orbax: async, atomic, self-verifying.

Replaces what the reference borrows from HF Trainer: last-checkpoint
autodetect (/root/reference/run_clm.py:289-302), ``resume_from_checkpoint``
(:604-610), ``save_total_limit`` rotation (README.md:34). One deliberate fix
over the reference: with ``--async_grad`` the Lion momenta are
per-worker-distinct, and HF Trainer saves only rank-0's optimizer state —
silent corruption on resume (SURVEY §5). Here the stacked ``[world, ...]``
momentum pytree is saved shard-by-shard via Orbax, so resume restores every
worker's momentum exactly.

Resilience layer (train/resilience.py is the companion module):

- **Async double-buffered saves** (``async_save=True``): ``save()`` kicks off
  the Orbax async write and returns after the device→host copy; the blocking
  ``wait_until_finished`` moves to the NEXT save boundary (and to
  ``close()``/anomaly paths), so serialization and disk I/O overlap the
  following train steps instead of stalling them. ``pop_stall_s()`` reports
  exactly how long the loop was blocked — the ``ckpt_stall_s`` metric that
  proves the overlap (tests pin async < sync).
- **Atomic commit + integrity manifest** (``integrity=True``): once Orbax
  finalizes a step, a background commit writes ``manifest.json`` (per-file
  sha256 + sizes + caller metadata like the world size) and then a
  ``COMMITTED`` marker — marker last, both via tmp+rename. A checkpoint
  without its marker was torn mid-commit and is never resumed from.
- **Verified autodetect**: ``latest_valid_step()`` re-hashes candidates
  newest-first and falls back to the newest GOOD checkpoint, so a torn leaf
  file or a bit-flipped manifest costs one save interval, not the run.
  Directories written before this layer existed (no ``MANIFESTS_ENABLED``
  stamp) are grandfathered as valid.
- **Retry/backoff** around the save call: transient I/O failures (flaky
  NFS/GCS) retry with exponential backoff before surfacing.
"""

from __future__ import annotations

import hashlib
import json
import os
import pathlib
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from distributed_lion_tpu.train import journal as run_journal
from distributed_lion_tpu.train import resilience
from distributed_lion_tpu.train.journal import emit
# the read side (verify, autodetect) lives in resilience.py so the
# dependency-light evidence checker can import it without jax/orbax;
# re-exported here because this module is the checkpoint API surface
from distributed_lion_tpu.train.resilience import (  # noqa: F401
    MANIFEST,
    MANIFEST_FORMAT,
    MANIFESTS_STAMP,
    MARKER,
    latest_valid_step_in,
    read_manifest,
    sha256_file as _sha256_file,
    verify_step_dir,
)


def _atomic_write(path: pathlib.Path, data: bytes) -> None:
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_bytes(data)
    os.replace(tmp, path)


def write_manifest(sdir: pathlib.Path, step: int,
                   meta: Optional[dict] = None) -> str:
    """Digest every data file under a finalized step directory into
    ``manifest.json``; returns the manifest's own sha256 (recorded in the
    commit marker so a corrupted manifest is caught without re-hashing)."""
    files = {}
    for p in sorted(sdir.rglob("*")):
        if p.is_file() and p.name not in (MANIFEST, MARKER):
            files[str(p.relative_to(sdir))] = {
                "sha256": _sha256_file(p), "bytes": p.stat().st_size}
    raw = json.dumps(
        {"format": MANIFEST_FORMAT, "step": int(step), "files": files,
         "meta": meta or {}},
        sort_keys=True, allow_nan=False).encode()
    _atomic_write(sdir / MANIFEST, raw)
    return hashlib.sha256(raw).hexdigest()


def _read_marker(sdir: pathlib.Path) -> Optional[dict]:
    return resilience.read_json(sdir / MARKER)


class Checkpointer:
    def __init__(self, directory: str | pathlib.Path,
                 save_total_limit: Optional[int] = None, *,
                 async_save: bool = False, integrity: bool = True,
                 max_retries: int = 3, retry_backoff_s: float = 0.1,
                 journal=None):
        # the run journal (train/journal.py; NULL no-op when the trainer
        # runs without --journal): caller-thread spans (ckpt/serialize,
        # ckpt/drain) are the step loop's checkpoint tax — the same wall
        # time the ckpt_stall_s ledger counts, cross-checked by
        # tests/test_journal.py — while the committer-thread spans
        # (thread="committer") show where the BACKGROUND commit spends its
        # time without counting against the step wall
        self._journal = journal if journal is not None else run_journal.NULL
        self.directory = pathlib.Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)
        self.integrity = integrity
        self.async_save = async_save
        self.max_retries = max_retries
        self.retry_backoff_s = retry_backoff_s
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=save_total_limit,
                create=True,
                enable_async_checkpointing=async_save,
            ),
        )
        if integrity and jax.process_index() == 0:
            stamp = self.directory / MANIFESTS_STAMP
            if not stamp.exists():
                # don't retroactively invalidate a sync-era directory:
                # stamping flips 'no marker' from legacy-good to
                # torn-commit-reject, so it only happens when every
                # existing step already carries a marker (or none exist)
                legacy = any(
                    p.is_dir() and p.name.isdigit()
                    and not (p / MARKER).exists()
                    for p in self.directory.iterdir())
                if not legacy:
                    _atomic_write(stamp, b"1\n")
        self._executor = (
            ThreadPoolExecutor(max_workers=1, thread_name_prefix="ckpt-commit")
            if async_save else None
        )
        self._inflight: Optional[Future] = None
        self._inflight_step: Optional[int] = None
        # stall ledger: wall time the CALLING thread spent blocked inside
        # save()/finalize() — the step loop's checkpoint tax. _unread is
        # drained by pop_stall_s() at the metrics-log cadence.
        self.total_stall_s = 0.0
        self.last_stall_s = 0.0
        self._unread_stall_s = 0.0

    # ----------------------------------------------------------------- save
    def save(self, step: int, payload: Any,
             meta: Optional[dict] = None) -> None:
        """Save a pytree (params / optimizer state / counters); sharded
        arrays are written distributed, one shard per host. With
        ``async_save`` this blocks only for the previous save's drain (the
        double-buffer wait, usually 0 once steps outlast serialization)
        plus the device→host copy; the write + digest + commit run behind
        the following train steps."""
        t0 = time.monotonic()
        drained = 0.0
        try:
            try:
                drained = self.finalize()  # accounts its own stall;
                # subtracted below so the drain isn't double-counted in
                # this save's ledger
            except Exception:
                # finalize's finally already ledgered the drain seconds;
                # mark them consumed so this save's finally doesn't add
                # the same wall time again on the way out
                drained = time.monotonic() - t0
                raise
            # caller-thread serialize span: the D2H copy + Orbax enqueue
            # (async) or the full serialize+write+commit (sync) — with the
            # drain above, the whole of save()'s step-loop tax
            with self._journal.span("ckpt/serialize", step=int(step)):
                delay = self.retry_backoff_s
                for attempt in range(self.max_retries + 1):
                    try:
                        if resilience.consume_fault_count("ckpt_save_raise"):
                            raise OSError("injected save fault")
                        self.manager.save(step,
                                          args=ocp.args.StandardSave(payload))
                        break
                    except Exception as e:
                        if attempt == self.max_retries:
                            # out of retries: re-raise with step/path context
                            # attached, same exception class so callers (and
                            # tests) matching on the original type still do
                            try:
                                wrapped = type(e)(
                                    f"checkpoint save(step={step}) under "
                                    f"{self.directory} failed after "
                                    f"{attempt + 1} attempts: {e}")
                            except Exception:
                                raise e  # exotic ctor signature: original as-is
                            raise wrapped from e
                        emit(f"[ckpt] save({step}) attempt {attempt + 1} "
                             f"failed ({e}); retrying in {delay:.2f}s")
                        time.sleep(delay)
                        delay *= 2
                if self._executor is not None:
                    self._inflight = self._executor.submit(self._commit, step,
                                                           meta)
                    self._inflight_step = step
                else:
                    self._commit(step, meta)
        finally:
            self._add_stall(max(time.monotonic() - t0 - drained, 0.0))

    def _commit(self, step: int, meta: Optional[dict]) -> Optional[int]:
        """Wait for Orbax to finalize the step, then write manifest + commit
        marker (marker LAST — its presence is the atomic commit point).
        Runs on the committer thread under async_save, inline otherwise."""
        with self._journal.span("ckpt/orbax_finalize", step=int(step),
                                thread="committer"):
            self.manager.wait_until_finished()
            slow = resilience.fault("ckpt_slow_commit")
            if slow:
                time.sleep(float(slow))
        if not self.integrity or jax.process_index() != 0:
            return step
        if resilience.fault("ckpt_crash_before_manifest"):
            return None  # simulated death after Orbax finalize, before commit
        sdir = self._step_dir(step)
        with self._journal.span("ckpt/digest", step=int(step),
                                thread="committer"):
            digest = write_manifest(sdir, step, meta)
        if resilience.fault("ckpt_crash_before_marker"):
            return None
        with self._journal.span("ckpt/commit_marker", step=int(step),
                                thread="committer"):
            _atomic_write(
                sdir / MARKER,
                json.dumps({"manifest_sha256": digest, "step": int(step),
                            "committed_at_unix": time.time()},
                           allow_nan=False).encode())
        return step

    def finalize(self) -> float:
        """Drain the in-flight async save, if any; returns the seconds this
        call blocked. An exception the committer thread hit (Orbax
        finalization, manifest I/O) is re-raised HERE — the drain boundary
        — with step/path context attached: swallowing it left the run
        believing in checkpoints that were never committed. (Injected
        crash-faults simulate death by returning early, not by raising, so
        the fault matrix still exercises the fall-back-past-it path.)"""
        if self._inflight is None:
            return 0.0
        t0 = time.monotonic()
        fut, step = self._inflight, self._inflight_step
        self._inflight, self._inflight_step = None, None
        try:
            with self._journal.span("ckpt/drain", step=int(step)):
                fut.result()
        except Exception as e:
            raise RuntimeError(
                f"checkpoint commit for step {step} under "
                f"{self._step_dir(step)} failed on the committer thread; "
                "that checkpoint was never committed and will not be "
                "resumed from") from e
        finally:
            dt = time.monotonic() - t0
            self._add_stall(dt)
        return dt

    def _add_stall(self, dt: float) -> None:
        self.total_stall_s += dt
        self.last_stall_s = dt
        self._unread_stall_s += dt

    def pop_stall_s(self) -> float:
        """Checkpoint-blocked seconds accrued since the last pop — the
        ``ckpt_stall_s`` metric."""
        out, self._unread_stall_s = self._unread_stall_s, 0.0
        return out

    # ------------------------------------------------------------- discovery
    def _step_dir(self, step: int) -> pathlib.Path:
        return self.directory / str(step)

    def latest_step(self) -> Optional[int]:
        """The reference's get_last_checkpoint autodetect (run_clm.py:289-302)
        — Orbax's view, integrity-unverified. Used only to dedupe saves;
        resume goes through :meth:`latest_valid_step`."""
        return self.manager.latest_step()

    def valid_steps(self) -> list[int]:
        """Committed-and-verified steps, newest first. In a pre-manifest
        (unstamped) directory, steps without markers are grandfathered."""
        steps = sorted((int(s) for s in self.manager.all_steps()),
                       reverse=True)
        if not self.integrity:
            return steps
        stamped = (self.directory / MANIFESTS_STAMP).exists()
        out = []
        for s in steps:
            sdir = self._step_dir(s)
            if verify_step_dir(sdir):
                out.append(s)
            elif not stamped and _read_marker(sdir) is None:
                out.append(s)  # legacy checkpoint from the sync-only era
        return out

    def latest_valid_step(self) -> Optional[int]:
        steps = self.valid_steps()
        return steps[0] if steps else None

    def purge_steps_after(self, step: int) -> list[int]:
        """Delete EVERY step newer than the resumed one. Left in place they
        poison Orbax's step ordering: with a step 1488 still on disk, a
        post-resume save at 1460 is silently dropped/rotated away, so the
        run makes progress it can never checkpoint again — and the
        ``latest_step()`` save dedupe would skip re-saving 1488 when the
        run re-reaches it. This applies to hash-VALID newer steps too (a
        step that verified but failed to restore): once the run resumed
        below them they are an abandoned future, and the deterministic
        replay re-creates them bit-identically anyway."""
        purged: list[int] = []
        failures: list[tuple[int, Exception]] = []
        for s in sorted(int(x) for x in self.manager.all_steps()):
            if s > step:
                try:
                    self.manager.delete(s)
                except Exception as e:
                    # keep purging the rest, then raise with full context:
                    # a stale step left on disk silently eats every future
                    # save below it — "could not purge" is not a warning
                    failures.append((s, e))
                    continue
                purged.append(s)
        if failures:
            detail = "; ".join(f"step {s} ({self._step_dir(s)}): {e}"
                               for s, e in failures)
            raise RuntimeError(
                f"could not purge stale checkpoint step(s) "
                f"{[s for s, _ in failures]} newer than the resumed step "
                f"{step} — left on disk they make Orbax silently drop every "
                f"post-resume save below them: {detail}") from failures[0][1]
        return purged

    def manifest_meta(self, step: int) -> Optional[dict]:
        """The caller metadata recorded at commit (world size, tag, data
        counters) — read before restore so elastic resume can size the
        template without guessing."""
        manifest = read_manifest(self._step_dir(step))
        return manifest.get("meta") if manifest else None

    # --------------------------------------------------------------- restore
    def restore(self, step: int, like: Any) -> Any:
        """Restore into the shardings/dtypes of ``like`` (an abstract or
        concrete pytree template)."""
        template = jax.tree.map(_as_abstract, like)
        return self.manager.restore(step, args=ocp.args.StandardRestore(template))

    def close(self) -> None:
        # the drain may re-raise a committer-thread failure; the executor
        # and Orbax manager must still be torn down before it propagates
        try:
            self.finalize()
        finally:
            if self._executor is not None:
                self._executor.shutdown(wait=True)
                self._executor = None
            self.manager.close()


def _as_abstract(x):
    if isinstance(x, jax.Array):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
    if isinstance(x, (np.ndarray, np.generic)):
        return jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype)
    return x
