"""Checkpoint save/restore with Orbax.

Replaces what the reference borrows from HF Trainer: last-checkpoint
autodetect (/root/reference/run_clm.py:289-302), ``resume_from_checkpoint``
(:604-610), ``save_total_limit`` rotation (README.md:34). One deliberate fix
over the reference: with ``--async_grad`` the Lion momenta are
per-worker-distinct, and HF Trainer saves only rank-0's optimizer state —
silent corruption on resume (SURVEY §5). Here the stacked ``[world, ...]``
momentum pytree is saved shard-by-shard via Orbax, so resume restores every
worker's momentum exactly.
"""

from __future__ import annotations

import pathlib
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp


class Checkpointer:
    def __init__(self, directory: str | pathlib.Path, save_total_limit: Optional[int] = None):
        self.directory = pathlib.Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=save_total_limit,
                create=True,
                enable_async_checkpointing=False,
            ),
        )

    def save(self, step: int, payload: Any) -> None:
        """Save a pytree (params / optimizer state / data-iterator counters);
        sharded arrays are written distributed, one shard per host."""
        self.manager.save(step, args=ocp.args.StandardSave(payload))
        self.manager.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        """The reference's get_last_checkpoint autodetect (run_clm.py:289-302)."""
        return self.manager.latest_step()

    def restore(self, step: int, like: Any) -> Any:
        """Restore into the shardings/dtypes of ``like`` (an abstract or
        concrete pytree template)."""
        template = jax.tree.map(_as_abstract, like)
        return self.manager.restore(step, args=ocp.args.StandardRestore(template))

    def close(self) -> None:
        self.manager.close()


def _as_abstract(x):
    if isinstance(x, jax.Array):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
    if isinstance(x, (np.ndarray, np.generic)):
        return jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype)
    return x
