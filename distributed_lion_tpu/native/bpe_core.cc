// BPE merge core: the hot inner loop of GPT-2 byte-level BPE encoding.
//
// The reference's tokenizer is HF `transformers` GPT2Tokenizer(Fast) — a
// native (Rust) encoder behind a Python API (/root/reference/run_clm.py:
// 398-423). Our equivalent: Python owns the published pre-tokenization
// regex and the byte<->unicode table (data/bpe.py); this file owns the
// merge loop, which dominates encoding cost for uncached words.
//
// Everything runs in *id space*: Python lowers the vocab to raw byte
// strings (id = array index) and each merge rule to an (left_id, right_id)
// pair; the merged token's id is resolved here once at construction. A
// word is then a vector<int32>, and one merge step is "find the
// lowest-ranked adjacent pair, replace every occurrence left-to-right" —
// exactly data/bpe.py's _bpe, which tests pin token-for-token.
//
// C ABI (consumed via ctypes in distributed_lion_tpu/native/__init__.py):
//   bpe_new(vocab_blob, vocab_off, n_vocab, merge_pairs, n_merges) -> handle
//   bpe_encode(handle, bytes, pretok_off, n_pretok, out, cap) -> n or -needed
//   bpe_cache_size(handle) -> entries in the word cache
//   bpe_free(handle)
//   bpe_last_error() -> static message for the last failed bpe_new

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <utility>
#include <vector>

namespace {

struct Encoder {
  std::unordered_map<std::string, int32_t> vocab;  // raw byte-string -> id
  // (left_id, right_id) -> (rank, merged_id)
  std::unordered_map<uint64_t, std::pair<int32_t, int32_t>> ranks;
  int32_t byte_id[256];  // id of each single-byte token, -1 if absent
  std::unordered_map<std::string, std::vector<int32_t>> cache;
};

inline uint64_t pair_key(int32_t l, int32_t r) {
  return (uint64_t(uint32_t(l)) << 32) | uint32_t(r);
}

const char* g_err = "";

// Merge one pre-token (raw bytes, already regex-split by the caller) into
// ids appended onto `out`. Mirrors data/bpe.py::_bpe: repeatedly find the
// best-ranked adjacent pair and collapse every occurrence in one pass.
void encode_word(Encoder* e, const std::string& w, std::vector<int32_t>& out) {
  auto hit = e->cache.find(w);
  if (hit != e->cache.end()) {
    out.insert(out.end(), hit->second.begin(), hit->second.end());
    return;
  }
  std::vector<int32_t> ids;
  ids.reserve(w.size());
  for (unsigned char ch : w) {
    int32_t id = e->byte_id[ch];
    if (id >= 0) ids.push_back(id);  // byte-level vocabs cover all 256
  }
  while (ids.size() > 1) {
    int32_t best_rank = INT32_MAX, best_merged = -1;
    int32_t L = 0, R = 0;
    for (size_t i = 0; i + 1 < ids.size(); ++i) {
      auto it = e->ranks.find(pair_key(ids[i], ids[i + 1]));
      if (it != e->ranks.end() && it->second.first < best_rank) {
        best_rank = it->second.first;
        best_merged = it->second.second;
        L = ids[i];
        R = ids[i + 1];
      }
    }
    if (best_merged < 0) break;
    std::vector<int32_t> next;
    next.reserve(ids.size());
    for (size_t i = 0; i < ids.size();) {
      if (i + 1 < ids.size() && ids[i] == L && ids[i + 1] == R) {
        next.push_back(best_merged);
        i += 2;
      } else {
        next.push_back(ids[i]);
        ++i;
      }
    }
    ids.swap(next);
  }
  if (e->cache.size() < 65536) e->cache.emplace(w, ids);
  out.insert(out.end(), ids.begin(), ids.end());
}

}  // namespace

extern "C" {

// vocab_blob/vocab_off: n_vocab raw byte-string tokens, token i =
// blob[off[i], off[i+1]); id == i. merge_pairs: [n_merges*2] left/right ids
// in merge-priority order. Returns nullptr (and sets bpe_last_error) if a
// merge references an out-of-range id or a merged token missing from vocab.
void* bpe_new(const uint8_t* vocab_blob, const int64_t* vocab_off,
              int32_t n_vocab, const int32_t* merge_pairs, int32_t n_merges) {
  auto* e = new Encoder();
  std::vector<std::string> toks(n_vocab);
  e->vocab.reserve(size_t(n_vocab) * 2);
  for (int32_t i = 0; i < n_vocab; ++i) {
    toks[i].assign(reinterpret_cast<const char*>(vocab_blob) + vocab_off[i],
                   size_t(vocab_off[i + 1] - vocab_off[i]));
    e->vocab.emplace(toks[i], i);
  }
  for (int b = 0; b < 256; ++b) e->byte_id[b] = -1;
  for (int32_t i = 0; i < n_vocab; ++i)
    if (toks[i].size() == 1) e->byte_id[uint8_t(toks[i][0])] = i;
  for (int b = 0; b < 256; ++b) {
    if (e->byte_id[b] < 0) {
      // refuse partial byte coverage: silently dropping bytes would corrupt
      // the token stream; the caller falls back to the Python path, which
      // raises KeyError if such a byte is ever actually encoded
      delete e;
      g_err = "vocab does not cover all 256 byte values";
      return nullptr;
    }
  }
  e->ranks.reserve(size_t(n_merges) * 2);
  for (int32_t m = 0; m < n_merges; ++m) {
    int32_t l = merge_pairs[2 * m], r = merge_pairs[2 * m + 1];
    if (l < 0 || l >= n_vocab || r < 0 || r >= n_vocab) {
      delete e;
      g_err = "merge pair id out of range";
      return nullptr;
    }
    auto it = e->vocab.find(toks[l] + toks[r]);
    if (it == e->vocab.end()) {
      delete e;
      g_err = "merged token not present in vocab";
      return nullptr;
    }
    e->ranks.emplace(pair_key(l, r), std::make_pair(m, it->second));
  }
  return e;
}

// bytes/off: n_pretok regex pre-tokens, pre-token p = bytes[off[p],
// off[p+1]). Writes ids to out (capacity cap); returns the count, or
// -needed if cap was too small (never happens when cap >= off[n_pretok],
// since merging only shrinks the per-byte id sequence).
int64_t bpe_encode(void* h, const uint8_t* bytes, const int64_t* off,
                   int64_t n_pretok, int32_t* out_buf, int64_t cap) {
  auto* e = static_cast<Encoder*>(h);
  std::vector<int32_t> out;
  out.reserve(size_t(off[n_pretok] / 3 + 8));
  std::string w;
  for (int64_t p = 0; p < n_pretok; ++p) {
    w.assign(reinterpret_cast<const char*>(bytes) + off[p],
             size_t(off[p + 1] - off[p]));
    encode_word(e, w, out);
  }
  if (int64_t(out.size()) > cap) return -int64_t(out.size());
  std::memcpy(out_buf, out.data(), out.size() * sizeof(int32_t));
  return int64_t(out.size());
}

int64_t bpe_cache_size(void* h) {
  return int64_t(static_cast<Encoder*>(h)->cache.size());
}

void bpe_free(void* h) { delete static_cast<Encoder*>(h); }

const char* bpe_last_error() { return g_err; }

}  // extern "C"
