"""Native (C++) runtime components, built on demand with the system g++.

The reference's input pipeline is HF ``datasets`` (Arrow + Python worker
processes). Here the equivalent is a small C++ runtime (``dataloader.cc``):
mmap'd token shards, shuffled sampling, and a background prefetch thread,
exposed over a C ABI and consumed via :mod:`ctypes` (no pybind11 in this
environment). The library is compiled lazily into the package directory the
first time it is needed and cached; callers fall back to the pure-Python
path when no compiler is available.
"""

from __future__ import annotations

import ctypes
import os
import pathlib
import subprocess
import tempfile
import threading
from typing import Optional

_HERE = pathlib.Path(__file__).resolve().parent
_SRC = _HERE / "dataloader.cc"
_LIB = _HERE / "_dataloader.so"
_BPE_SRC = _HERE / "bpe_core.cc"
_BPE_LIB = _HERE / "_bpe_core.so"
_lock = threading.Lock()
_cached: Optional[ctypes.CDLL] = None
_bpe_cached: Optional[ctypes.CDLL] = None


class NativeBuildError(RuntimeError):
    pass


def library_path() -> pathlib.Path:
    return _LIB


def _compile(src: pathlib.Path, lib: pathlib.Path,
             force: bool = False) -> pathlib.Path:
    """Compile one .cc → .so (atomic rename, so concurrent builders race
    benignly). Raises NativeBuildError on failure."""
    if not force and lib.exists() and lib.stat().st_mtime >= src.stat().st_mtime:
        return lib
    with tempfile.NamedTemporaryFile(
        suffix=".so", dir=str(_HERE), delete=False
    ) as tmp:
        tmp_path = tmp.name
    cmd = [
        os.environ.get("CXX", "g++"),
        "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        str(src), "-o", tmp_path,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        pathlib.Path(tmp_path).unlink(missing_ok=True)
        raise NativeBuildError(f"cannot run {cmd[0]}: {e}") from e
    if proc.returncode != 0:
        pathlib.Path(tmp_path).unlink(missing_ok=True)
        raise NativeBuildError(f"g++ failed:\n{proc.stderr}")
    os.replace(tmp_path, lib)
    return lib


def build(force: bool = False) -> pathlib.Path:
    return _compile(_SRC, _LIB, force)


def load() -> ctypes.CDLL:
    """Build (if needed) and load the native library, with typed signatures."""
    global _cached
    with _lock:
        if _cached is not None:
            return _cached
        lib = ctypes.CDLL(str(build()))
        c_i32p = ctypes.POINTER(ctypes.c_int32)
        lib.dl_open.restype = ctypes.c_void_p
        lib.dl_open.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_int,
            ctypes.c_longlong,
        ]
        lib.dl_num_blocks.restype = ctypes.c_longlong
        lib.dl_num_blocks.argtypes = [ctypes.c_void_p]
        lib.dl_read_block.restype = ctypes.c_int
        lib.dl_read_block.argtypes = [ctypes.c_void_p, ctypes.c_longlong, c_i32p]
        lib.dl_start.restype = ctypes.c_int
        lib.dl_start.argtypes = [
            ctypes.c_void_p, ctypes.c_longlong, ctypes.c_ulonglong,
            ctypes.c_int, ctypes.c_int, ctypes.c_longlong,
            ctypes.c_longlong, ctypes.c_longlong, ctypes.c_longlong,
        ]
        lib.dl_next.restype = ctypes.c_int
        lib.dl_next.argtypes = [ctypes.c_void_p, c_i32p]
        lib.dl_close.restype = None
        lib.dl_close.argtypes = [ctypes.c_void_p]
        lib.dl_last_error.restype = ctypes.c_char_p
        lib.dl_last_error.argtypes = []
        _cached = lib
        return lib


def available() -> bool:
    try:
        load()
        return True
    except (NativeBuildError, OSError):
        # OSError covers a stale/corrupt/wrong-arch .so that CDLL rejects —
        # callers should fall back to the Python path, not crash
        return False


def load_bpe() -> ctypes.CDLL:
    """Build (if needed) and load the BPE merge core (bpe_core.cc), with
    typed signatures; consumed by data/bpe.py's native fast path."""
    global _bpe_cached
    with _lock:
        if _bpe_cached is not None:
            return _bpe_cached
        lib = ctypes.CDLL(str(_compile(_BPE_SRC, _BPE_LIB)))
        c_u8p = ctypes.POINTER(ctypes.c_uint8)
        c_i64p = ctypes.POINTER(ctypes.c_int64)
        c_i32p = ctypes.POINTER(ctypes.c_int32)
        lib.bpe_new.restype = ctypes.c_void_p
        lib.bpe_new.argtypes = [c_u8p, c_i64p, ctypes.c_int32, c_i32p,
                                ctypes.c_int32]
        lib.bpe_encode.restype = ctypes.c_int64
        lib.bpe_encode.argtypes = [ctypes.c_void_p, c_u8p, c_i64p,
                                   ctypes.c_int64, c_i32p, ctypes.c_int64]
        lib.bpe_cache_size.restype = ctypes.c_int64
        lib.bpe_cache_size.argtypes = [ctypes.c_void_p]
        lib.bpe_free.restype = None
        lib.bpe_free.argtypes = [ctypes.c_void_p]
        lib.bpe_last_error.restype = ctypes.c_char_p
        lib.bpe_last_error.argtypes = []
        _bpe_cached = lib
        return lib


def bpe_available() -> bool:
    try:
        load_bpe()
        return True
    except (NativeBuildError, OSError):
        return False
