// Native data-loader runtime for distributed_lion_tpu.
//
// The reference delegates its input pipeline to HF `datasets` (Arrow +
// Python workers, run_clm.py:316-381). This is the TPU-native equivalent,
// in C++ as a real runtime component: memory-mapped pre-tokenized shards
// (uint16/uint32 `.bin`, the standard offline-pretraining format), fixed
// `block_size` views (group_texts semantics, run_clm.py:509-522 — the
// per-shard tail remainder below one block is dropped), a deterministic
// per-epoch shuffled sampler, and a background prefetch thread that gathers
// batches into int32 host buffers while the TPU step runs, handing them to
// Python over a bounded queue (C ABI, consumed via ctypes — no pybind11).
//
// Build: see distributed_lion_tpu/native/__init__.py (g++ -O3 -shared).

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

thread_local std::string g_last_error;

void set_error(const std::string& msg) { g_last_error = msg; }

struct Shard {
  const uint8_t* base = nullptr;
  size_t bytes = 0;
  int fd = -1;
  int64_t n_blocks = 0;  // full blocks in this shard (tail dropped)
};

struct Loader {
  std::vector<Shard> shards;
  int dtype_bytes = 2;  // 2 = uint16, 4 = uint32
  int64_t block = 0;    // tokens per block
  int64_t n_blocks = 0;
  std::vector<int64_t> block_off;  // prefix sum of per-shard block counts

  // --- prefetch state ---
  int64_t batch = 0;
  uint64_t seed = 0;
  bool shuffle = true;
  int64_t epochs = 0;  // <=0: infinite
  int64_t lo = 0, hi = 0;  // half-open sample range [lo, hi)
  int64_t skip0 = 0;       // batches to fast-forward at start (resume seek:
                           // skipped epochs never even draw their shuffle,
                           // skipped batches never read data)
  size_t depth = 4;
  std::thread worker;
  std::mutex mu;
  std::condition_variable cv_space, cv_item;
  std::deque<std::vector<int32_t>> queue;
  bool finished = false;  // producer exhausted all epochs
  std::atomic<bool> stop{false};
  bool started = false;

  ~Loader() {
    shutdown();
    for (auto& s : shards) {
      if (s.base) munmap(const_cast<uint8_t*>(s.base), s.bytes);
      if (s.fd >= 0) close(s.fd);
    }
  }

  void shutdown() {
    if (started) {
      stop.store(true);
      {
        std::lock_guard<std::mutex> lk(mu);
        cv_space.notify_all();
        cv_item.notify_all();
      }
      if (worker.joinable()) worker.join();
      started = false;
    }
  }

  // Decode global block index -> int32 out[block].
  void read_block(int64_t idx, int32_t* out) const {
    size_t s = std::upper_bound(block_off.begin(), block_off.end(), idx) -
               block_off.begin() - 1;
    int64_t local = idx - block_off[s];
    const uint8_t* p =
        shards[s].base + static_cast<size_t>(local) * block * dtype_bytes;
    if (dtype_bytes == 2) {
      const uint16_t* t = reinterpret_cast<const uint16_t*>(p);
      for (int64_t i = 0; i < block; ++i) out[i] = static_cast<int32_t>(t[i]);
    } else {
      const uint32_t* t = reinterpret_cast<const uint32_t*>(p);
      for (int64_t i = 0; i < block; ++i) out[i] = static_cast<int32_t>(t[i]);
    }
  }

  void producer() {
    const int64_t n = hi - lo;
    const int64_t bpe = n / batch;  // batches per epoch (drop-last)
    int64_t skip = skip0;
    std::vector<int64_t> order(static_cast<size_t>(n));
    for (int64_t e = 0; epochs <= 0 || e < epochs; ++e) {
      if (skip >= bpe && bpe > 0) {
        skip -= bpe;  // whole epoch skipped: no shuffle draw, no reads
        continue;
      }
      for (int64_t i = 0; i < n; ++i) order[i] = lo + i;
      if (shuffle) {
        std::mt19937_64 rng(seed + 0x9e3779b97f4a7c15ULL * (uint64_t)(e + 1));
        std::shuffle(order.begin(), order.end(), rng);
      }
      const int64_t i0 = skip * batch;
      skip = 0;
      // drop-last batching, matching sources.batch_iterator
      for (int64_t i = i0; i + batch <= n; i += batch) {
        std::vector<int32_t> buf(static_cast<size_t>(batch * block));
        for (int64_t b = 0; b < batch; ++b)
          read_block(order[i + b], buf.data() + b * block);
        std::unique_lock<std::mutex> lk(mu);
        cv_space.wait(lk, [&] { return queue.size() < depth || stop.load(); });
        if (stop.load()) return;
        queue.emplace_back(std::move(buf));
        cv_item.notify_one();
      }
    }
    std::lock_guard<std::mutex> lk(mu);
    finished = true;
    cv_item.notify_all();
  }
};

}  // namespace

extern "C" {

const char* dl_last_error() { return g_last_error.c_str(); }

// Open n_paths mmap'd shards of `dtype_bytes`-wide tokens, cut into
// block_size views. Returns an opaque handle or nullptr (see dl_last_error).
void* dl_open(const char** paths, int n_paths, int dtype_bytes,
              long long block_size) {
  if (dtype_bytes != 2 && dtype_bytes != 4) {
    set_error("dtype_bytes must be 2 (uint16) or 4 (uint32)");
    return nullptr;
  }
  if (block_size <= 0 || n_paths <= 0) {
    set_error("need block_size > 0 and at least one shard");
    return nullptr;
  }
  auto* L = new Loader();
  L->dtype_bytes = dtype_bytes;
  L->block = block_size;
  L->block_off.push_back(0);
  for (int i = 0; i < n_paths; ++i) {
    Shard s;
    s.fd = open(paths[i], O_RDONLY);
    if (s.fd < 0) {
      set_error(std::string("cannot open ") + paths[i]);
      delete L;
      return nullptr;
    }
    struct stat st;
    fstat(s.fd, &st);
    s.bytes = static_cast<size_t>(st.st_size);
    s.n_blocks = static_cast<int64_t>(s.bytes) / (block_size * dtype_bytes);
    if (s.bytes > 0) {
      void* m = mmap(nullptr, s.bytes, PROT_READ, MAP_PRIVATE, s.fd, 0);
      if (m == MAP_FAILED) {
        set_error(std::string("mmap failed for ") + paths[i]);
        close(s.fd);
        delete L;
        return nullptr;
      }
      madvise(m, s.bytes, MADV_WILLNEED);
      s.base = static_cast<const uint8_t*>(m);
    }
    L->n_blocks += s.n_blocks;
    L->block_off.push_back(L->n_blocks);
    L->shards.push_back(s);
  }
  if (L->n_blocks == 0) {
    set_error("shards contain zero full blocks");
    delete L;
    return nullptr;
  }
  return L;
}

long long dl_num_blocks(void* h) {
  return static_cast<Loader*>(h)->n_blocks;
}

// Random access (eval sets, debugging). Returns 1 on success.
int dl_read_block(void* h, long long idx, int32_t* out) {
  auto* L = static_cast<Loader*>(h);
  if (idx < 0 || idx >= L->n_blocks) {
    set_error("block index out of range");
    return 0;
  }
  L->read_block(idx, out);
  return 1;
}

// Start the prefetch thread: [global_batch, block] int32 batches, shuffled
// per epoch with `seed`, drop-last; epochs<=0 cycles forever. Sampling is
// restricted to blocks [lo, hi) (hi<=0 → num_blocks), so callers can hold
// out a validation range from the same shards. skip_batches fast-forwards
// the deterministic stream by index arithmetic (checkpoint-resume seek).
int dl_start(void* h, long long global_batch, unsigned long long seed,
             int shuffle, int prefetch_depth, long long epochs,
             long long lo, long long hi, long long skip_batches) {
  auto* L = static_cast<Loader*>(h);
  if (L->started) {
    set_error("loader already started");
    return 0;
  }
  if (hi <= 0) hi = L->n_blocks;
  if (lo < 0 || lo >= hi || hi > L->n_blocks) {
    set_error("invalid sample range [lo, hi)");
    return 0;
  }
  if (global_batch <= 0 || global_batch > hi - lo) {
    set_error("global_batch must be in [1, range size]");
    return 0;
  }
  L->lo = lo;
  L->hi = hi;
  L->batch = global_batch;
  L->seed = seed;
  L->shuffle = shuffle != 0;
  L->depth = prefetch_depth > 0 ? static_cast<size_t>(prefetch_depth) : 1;
  L->epochs = epochs;
  L->skip0 = skip_batches > 0 ? skip_batches : 0;
  L->stop.store(false);
  L->finished = false;
  L->started = true;
  L->worker = std::thread([L] { L->producer(); });
  return 1;
}

// Pop the next batch into out[global_batch * block]. Blocks until a batch
// is ready. Returns 1, or 0 once all epochs are exhausted.
int dl_next(void* h, int32_t* out) {
  auto* L = static_cast<Loader*>(h);
  std::vector<int32_t> buf;
  {
    std::unique_lock<std::mutex> lk(L->mu);
    L->cv_item.wait(lk, [&] {
      return !L->queue.empty() || L->finished || L->stop.load();
    });
    if (L->queue.empty()) return 0;
    buf = std::move(L->queue.front());
    L->queue.pop_front();
    L->cv_space.notify_one();
  }
  std::memcpy(out, buf.data(), buf.size() * sizeof(int32_t));
  return 1;
}

void dl_close(void* h) { delete static_cast<Loader*>(h); }

}  // extern "C"
