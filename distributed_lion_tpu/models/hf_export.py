"""Export trained pytrees back to the HF ``save_pretrained`` layout.

The reference ends every workload by writing an HF checkpoint — Trainer's
``save_model`` (/root/reference/run_clm.py:611-622), the SFT merge flow
(sft_llama2.py:183-199: save → reload → ``merge_and_unload`` → save merged),
and optionally ``push_to_hub`` (run_clm.py:650-653). Push is out of scope
(zero egress), but the *format* isn't: this module is the exact inverse of
models/hf_import — same Conv1D orientation, q|k|v packing, RoPE
interleaved → half-rotation permutation, tied-head handling — so a model
trained here loads straight into ``GPT2LMHeadModel.from_pretrained`` /
``LlamaForCausalLM.from_pretrained`` (pinned by tests/test_hf_export.py,
which round-trips through the torch models' own logits).

Weights are written as ``model.safetensors`` (via torch tensors, so bf16
survives) with a ``config.json``; quantized (NF4/int8) frozen bases must be
dequantized first (ops/quant.dequantize_tree).
"""

from __future__ import annotations

import json
import os
from typing import Any

import numpy as np


def _to_numpy(tree):
    import jax

    return jax.tree.map(lambda x: np.asarray(x), tree)


def _write_tensors(tensors: dict, path: str, stem: str) -> None:
    """{name: torch.Tensor} → <stem>.safetensors (or <stem>.bin fallback)."""
    import torch

    os.makedirs(path, exist_ok=True)
    try:
        from safetensors.torch import save_file

        save_file(tensors, os.path.join(path, f"{stem}.safetensors"))
    except ImportError:  # pragma: no cover
        torch.save(tensors, os.path.join(path, f"{stem}.bin"))


def _save_state_dict(sd: dict, path: str, config: dict) -> None:
    """{name: np.ndarray} → model.safetensors + config.json under path."""
    import torch

    tensors = {}
    for k, v in sd.items():
        arr = np.ascontiguousarray(v)
        if arr.dtype.name == "bfloat16":  # ml_dtypes bf16 → torch bf16
            t = torch.from_numpy(arr.view(np.uint16).copy()).view(torch.bfloat16)
        else:
            t = torch.from_numpy(arr.copy())
        tensors[k] = t
    _write_tensors(tensors, path, "model")
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(config, f, indent=1, allow_nan=False)


# ----------------------------------------------------------------------- GPT-2

def gpt2_to_hf(params: dict, cfg: Any, path: str) -> None:
    """Our GPT-2 pytree → an HF ``GPT2LMHeadModel`` checkpoint directory.

    Inverse of hf_import.gpt2_from_hf: stacked qkv [d, 3, d] flattens to
    Conv1D's c_attn [d, 3d]; the lm_head is tied to wte (GPT-2 convention),
    so only ``transformer.*`` weights are written.
    """
    p = _to_numpy(params)
    d = cfg.d_model
    sd = {
        # a vocab_pad_multiple layout carries MXU-alignment rows HF models
        # don't have; slice back to the true vocab (no-op when unpadded)
        "transformer.wte.weight": p["wte"][: cfg.vocab_size],
        "transformer.wpe.weight": p["wpe"],
        "transformer.ln_f.weight": p["ln_f"]["scale"],
        "transformer.ln_f.bias": p["ln_f"]["bias"],
    }
    for i, blk in enumerate(p["blocks"]):
        if "moe" in blk:
            raise ValueError(
                "MoE blocks have no HF GPT-2 equivalent; export is for the "
                "dense reference architecture"
            )
        h = f"transformer.h.{i}"
        sd[f"{h}.ln_1.weight"] = blk["ln_1"]["scale"]
        sd[f"{h}.ln_1.bias"] = blk["ln_1"]["bias"]
        sd[f"{h}.attn.c_attn.weight"] = blk["attn"]["qkv"].reshape(d, 3 * d)
        sd[f"{h}.attn.c_attn.bias"] = blk["attn"]["qkv_b"].reshape(3 * d)
        sd[f"{h}.attn.c_proj.weight"] = blk["attn"]["proj"]
        sd[f"{h}.attn.c_proj.bias"] = blk["attn"]["proj_b"]
        sd[f"{h}.ln_2.weight"] = blk["ln_2"]["scale"]
        sd[f"{h}.ln_2.bias"] = blk["ln_2"]["bias"]
        sd[f"{h}.mlp.c_fc.weight"] = blk["mlp"]["fc"]
        sd[f"{h}.mlp.c_fc.bias"] = blk["mlp"]["fc_b"]
        sd[f"{h}.mlp.c_proj.weight"] = blk["mlp"]["proj"]
        sd[f"{h}.mlp.c_proj.bias"] = blk["mlp"]["proj_b"]
    config = {
        "model_type": "gpt2",
        "architectures": ["GPT2LMHeadModel"],
        "vocab_size": int(cfg.vocab_size),
        "n_layer": int(cfg.n_layer),
        "n_head": int(cfg.n_head),
        "n_embd": int(cfg.d_model),
        "n_positions": int(cfg.n_ctx),
        "n_ctx": int(cfg.n_ctx),
        "tie_word_embeddings": True,
    }
    _save_state_dict(sd, path, config)


# ----------------------------------------------------------------------- Llama

def write_model_card(path: str, *, model_type: str, train_summary: dict) -> None:
    """Write a README.md model card next to the exported weights.

    The reference ends run_clm with ``trainer.create_model_card`` /
    ``push_to_hub`` (run_clm.py:650-653); push is out of scope (zero
    egress), the card isn't. ``train_summary`` is free-form config/metric
    key-values rendered as a table.
    """
    os.makedirs(path, exist_ok=True)
    lines = [
        f"# {model_type} — trained with distributed_lion_tpu",
        "",
        "Trained with majority-vote **Distributed Lion** "
        "(arXiv:2404.00438) on TPU via JAX/XLA.",
        "",
        "| key | value |",
        "|---|---|",
    ]
    lines += [f"| {k} | {v} |" for k, v in train_summary.items()]
    lines.append("")
    with open(os.path.join(path, "README.md"), "w") as f:
        f.write("\n".join(lines))


_TOKENIZER_FILES = (
    "vocab.json", "merges.txt", "tokenizer.json", "tokenizer.model",
    "tokenizer_config.json", "special_tokens_map.json",
)


def copy_tokenizer_files(tokenizer_name: str | None, path: str) -> list:
    """Copy tokenizer files next to the exported weights, if resolvable.

    The reference's save flow persists the tokenizer alongside the model
    (HF ``save_pretrained`` writes both), so ``AutoTokenizer.from_pretrained``
    works on the export directory. ``tokenizer_name`` is the same spec
    data.tokenizer.load_tokenizer takes: ``bpe:<dir>`` or a directory with
    tokenizer files. HF-cache names and the ByteTokenizer have no local
    files to copy — the gap is recorded in the model card instead (the
    caller includes the tokenizer spec in ``train_summary``). Returns the
    list of files copied.
    """
    import shutil

    if not tokenizer_name:
        return []
    src = tokenizer_name
    for prefix in ("bpe:", "sp:"):
        if src.startswith(prefix):
            src = src[len(prefix):]
            break
    copied = []
    if os.path.isfile(src):
        # a bare tokenizer.model / tokenizer.json / vocab file path
        name = os.path.basename(src)
        if name in _TOKENIZER_FILES or src.endswith(".model"):
            os.makedirs(path, exist_ok=True)
            dst = "tokenizer.model" if src.endswith(".model") else name
            shutil.copy2(src, os.path.join(path, dst))
            copied.append(dst)
        return copied
    if not os.path.isdir(src):
        return []
    os.makedirs(path, exist_ok=True)
    for name in _TOKENIZER_FILES:
        fp = os.path.join(src, name)
        if os.path.isfile(fp):
            shutil.copy2(fp, os.path.join(path, name))
            copied.append(name)
    return copied


def _rope_from_interleaved(w_out_in: np.ndarray, n_heads: int) -> np.ndarray:
    """Inverse of hf_import._rope_to_interleaved: per head, channel 2i goes
    back to slot i and channel 2i+1 to slot i + hd/2 (HF's half-rotation
    layout)."""
    out, d_in = w_out_in.shape
    hd = out // n_heads
    w = w_out_in.reshape(n_heads, hd // 2, 2, d_in)
    return np.ascontiguousarray(w.transpose(0, 2, 1, 3)).reshape(out, d_in)


# our llama leaf name → (PEFT module path, heads attr for rope un-permute)
_PEFT_MODULES = {
    "wq": ("self_attn.q_proj", "n_head"),
    "wk": ("self_attn.k_proj", "n_kv_head"),
    "wv": ("self_attn.v_proj", None),
    "wo": ("self_attn.o_proj", None),
    "w_gate": ("mlp.gate_proj", None),
    "w_up": ("mlp.up_proj", None),
    "w_down": ("mlp.down_proj", None),
}


def lora_to_peft(adapters: dict, model_cfg: Any, lora_cfg: Any,
                 path: str, base_model_name: str = "") -> None:
    """Export trained LoRA adapters as a HF PEFT checkpoint directory.

    The reference's SFT saves the PEFT adapter before merging
    (sft_llama2.py:183-190, ``trainer.save_model`` on a peft-wrapped model);
    this is that artifact for our adapters: ``adapter_model.safetensors`` +
    ``adapter_config.json``, loadable by ``peft.PeftModel.from_pretrained``
    on top of an exported base (:func:`llama_to_hf`) — logit parity with
    our ``apply_adapters`` forward is pinned by tests/test_hf_export.py.

    Layout mapping per adapted leaf (ours: A [in, r], B [r, out] on a
    [in, out] matmul weight): PEFT's lora_A.weight = A.T, lora_B.weight =
    B.T — with q/k projections additionally un-permuting B's output rows
    from our interleaved RoPE layout to HF's half-rotation
    (:func:`_rope_from_interleaved`). ``scaling = alpha/r`` matches PEFT's
    convention, so values export verbatim.
    """
    import torch

    sd = {}
    modules = set()
    for apath, ab in adapters.items():
        parts = apath.split("/")  # e.g. blocks/3/attn/wq
        if apath == "wte":
            # PEFT Embedding adapter layout: lora_embedding_A is
            # [r, num_embeddings], lora_embedding_B is [embedding_dim, r]
            # (transposed relative to the Linear lora_A/lora_B convention).
            prefix = "base_model.model.model.embed_tokens"
            A = np.ascontiguousarray(np.asarray(ab["A"]).T)  # [r, V]
            B = np.ascontiguousarray(np.asarray(ab["B"]).T)  # [d, r]
            sd[f"{prefix}.lora_embedding_A"] = torch.from_numpy(
                A.astype(np.float32))
            sd[f"{prefix}.lora_embedding_B"] = torch.from_numpy(
                B.astype(np.float32))
            modules.add("embed_tokens")
            continue
        if parts[0] != "blocks" or parts[-1] not in _PEFT_MODULES:
            raise ValueError(
                f"adapter on {apath!r} has no PEFT-Llama equivalent "
                f"(exportable targets: {sorted(_PEFT_MODULES)} + wte)"
            )
        layer = parts[1]
        module, heads_attr = _PEFT_MODULES[parts[-1]]
        A = np.ascontiguousarray(np.asarray(ab["A"]).T)  # [r, in]
        B = np.ascontiguousarray(np.asarray(ab["B"]).T)  # [out, r]
        if heads_attr is not None:
            B = _rope_from_interleaved(B, int(getattr(model_cfg, heads_attr)))
        prefix = f"base_model.model.model.layers.{layer}.{module}"
        sd[f"{prefix}.lora_A.weight"] = torch.from_numpy(A.astype(np.float32))
        sd[f"{prefix}.lora_B.weight"] = torch.from_numpy(B.astype(np.float32))
        modules.add(module.split(".")[-1])

    _write_tensors(sd, path, "adapter_model")
    config = {
        "peft_type": "LORA",
        "task_type": "CAUSAL_LM",
        "r": int(lora_cfg.r),
        "lora_alpha": int(lora_cfg.alpha),
        "lora_dropout": 0.0,
        "bias": "none",
        "fan_in_fan_out": False,
        "inference_mode": True,
        "target_modules": sorted(modules),
        "base_model_name_or_path": base_model_name,
    }
    with open(os.path.join(path, "adapter_config.json"), "w") as f:
        json.dump(config, f, indent=1, allow_nan=False)


def llama_to_hf(params: dict, cfg: Any, path: str) -> None:
    """Our Llama pytree → an HF ``LlamaForCausalLM`` checkpoint directory.

    Inverse of hf_import.llama_from_hf: [in, out] matmul weights transpose
    back to Linear's [out, in]; q/k projections un-permute from interleaved
    to half-rotation RoPE; a tied head (lm_head == wte.T) is detected and
    omitted with ``tie_word_embeddings``.
    """
    p = _to_numpy(params)
    tied = (p["lm_head"].shape == p["wte"].T.shape
            and np.array_equal(p["lm_head"], p["wte"].T))
    sd = {
        "model.embed_tokens.weight": p["wte"],
        "model.norm.weight": p["ln_f"]["scale"],
    }
    if not tied:
        sd["lm_head.weight"] = np.ascontiguousarray(p["lm_head"].T)
    for i, blk in enumerate(p["blocks"]):
        L = f"model.layers.{i}"
        a, m = blk["attn"], blk["mlp"]
        sd[f"{L}.input_layernorm.weight"] = blk["ln_attn"]["scale"]
        sd[f"{L}.self_attn.q_proj.weight"] = _rope_from_interleaved(
            np.ascontiguousarray(a["wq"].T), cfg.n_head)
        sd[f"{L}.self_attn.k_proj.weight"] = _rope_from_interleaved(
            np.ascontiguousarray(a["wk"].T), cfg.n_kv_head)
        sd[f"{L}.self_attn.v_proj.weight"] = np.ascontiguousarray(a["wv"].T)
        sd[f"{L}.self_attn.o_proj.weight"] = np.ascontiguousarray(a["wo"].T)
        sd[f"{L}.post_attention_layernorm.weight"] = blk["ln_mlp"]["scale"]
        sd[f"{L}.mlp.gate_proj.weight"] = np.ascontiguousarray(m["w_gate"].T)
        sd[f"{L}.mlp.up_proj.weight"] = np.ascontiguousarray(m["w_up"].T)
        sd[f"{L}.mlp.down_proj.weight"] = np.ascontiguousarray(m["w_down"].T)
    config = {
        "model_type": "llama",
        "architectures": ["LlamaForCausalLM"],
        "vocab_size": int(cfg.vocab_size),
        "num_hidden_layers": int(cfg.n_layer),
        "num_attention_heads": int(cfg.n_head),
        "num_key_value_heads": int(cfg.n_kv_head),
        "hidden_size": int(cfg.d_model),
        "intermediate_size": int(cfg.d_ff),
        "max_position_embeddings": int(cfg.n_ctx),
        "rope_theta": float(cfg.rope_theta),
        "rms_norm_eps": float(cfg.rms_eps),
        "tie_word_embeddings": bool(tied),
    }
    _save_state_dict(sd, path, config)
