"""Pipeline-parallel Llama: blocks as GPipe stages, trainable end-to-end.

The Llama twin of models/gpt2_pipe.py (same generic schedule —
parallel/pipeline.py's stacked stage params over the ``pipe`` axis,
activations rotating via ``ppermute``, one ``lax.scan``), so ``run_clm
--model_family llama --pipeline_parallel N`` trains with the reference's
second architecture family split into N stages. Differences from the GPT-2
wiring, all boundary-layer: rotary tables (cos/sin, computed once per step
from T and closed over — identical on every stage) replace the learned
positional embedding, RMSNorm replaces LayerNorm, and the head is the
untied ``lm_head`` rather than the tied embedding.

Gradient contract matches gpt2_pipe: stage leaves carry complete local
grads; replicated leaves (wte / lm_head / ln_f) carry disjoint per-stage
partials (stage 0: embedding; last stage: head + final norm) that the train
loop psums over the pipe axis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from distributed_lion_tpu.models.llama import (
    LlamaConfig,
    _block,
    _block_remat_for,
    _rms_norm,
    rope_angles,
)
from distributed_lion_tpu.models.loss import clm_loss_and_metrics
from distributed_lion_tpu.parallel.mesh import PIPE_AXIS
from distributed_lion_tpu.parallel.pipeline import (
    pipeline_apply,
    stack_stage_params,
    unstack_stage_params,
)


def llama_pipeline_params(params: dict, pp: int) -> dict:
    """Standard llama_init layout → pipeline layout with stacked stages."""
    return {
        "wte": params["wte"],
        "lm_head": params["lm_head"],
        "ln_f": params["ln_f"],
        "stages": stack_stage_params(params["blocks"], pp),
    }


def llama_unpipeline_params(pparams: dict, n_layer: int) -> dict:
    """Inverse of :func:`llama_pipeline_params` (export / generation)."""
    return {
        "wte": pparams["wte"],
        "lm_head": pparams["lm_head"],
        "ln_f": pparams["ln_f"],
        "blocks": unstack_stage_params(pparams["stages"], n_layer),
    }


def llama_pipeline_param_specs(tensor: bool = False) -> dict:
    """Replicated embeddings/head/final-norm; stage leaves sharded over
    ``pipe`` (their stacked leading dim).

    ``tensor=True`` ADDITIONALLY shards each stage's weights over the
    tensor axis (tp × pp): parallel/tensor_parallel.llama_param_specs'
    per-layer Megatron specs shifted past the two stacked-stage dims.
    wte / lm_head / ln_f stay replicated over tensor (replicated-head TP);
    the per-stage RMSNorm scales stay pipe-sharded only, their tensor-axis
    grads arriving complete through the Megatron copy boundary (same
    argument as gpt2_pipe)."""
    rep = P()
    stage_rms = {"scale": P(PIPE_AXIS)}
    if not tensor:
        att = {k: P(PIPE_AXIS) for k in ("wq", "wk", "wv", "wo")}
        mlp = {k: P(PIPE_AXIS) for k in ("w_gate", "w_up", "w_down")}
    else:
        from distributed_lion_tpu.parallel.mesh import TENSOR_AXIS

        col = P(PIPE_AXIS, None, None, TENSOR_AXIS)   # [pp, L/pp, d, k]
        row = P(PIPE_AXIS, None, TENSOR_AXIS, None)   # [pp, L/pp, k, d]
        att = {"wq": col, "wk": col, "wv": col, "wo": row}
        mlp = {"w_gate": col, "w_up": col, "w_down": row}
    stages = {"ln_attn": stage_rms, "attn": att, "ln_mlp": stage_rms,
              "mlp": mlp}
    return {"wte": rep, "lm_head": rep, "ln_f": {"scale": rep},
            "stages": stages}


def make_llama_pipeline_loss(model_cfg: LlamaConfig, n_micro: int,
                             axis_name: str = PIPE_AXIS,
                             tp_axis=None, vocab_chunks: int = 0,
                             seq_axis=None):
    """Build ``loss_fn(params, tokens, dropout_key) -> (loss, metrics)`` for
    the Trainer. Must run inside ``shard_map`` with ``axis_name`` bound;
    ``tokens`` [B_local, T] with B_local divisible by ``n_micro``.
    ``tp_axis`` runs each stage's blocks tensor-parallel (tp × pp) — see
    gpt2_pipe.make_pipeline_loss. ``vocab_chunks`` streams the last stage's
    untied lm_head through the chunked CE (the win that matters most at
    Llama-3's 128k vocab: [B, T, 128k] f32 logits never materialize).
    ``seq_axis`` shards tokens over a sequence axis on top of the pipeline
    (sp × pp): rotary angles offset by the seq shard index, ring attention
    over ``seq_axis`` inside every pipeline tick, seq-parallel CE at the
    last stage — see gpt2_pipe.make_pipeline_loss for the cond/collective
    argument."""

    def loss_fn(params, tokens, dropout_key):
        del dropout_key  # Llama (like HF's) has no dropout
        B, T = tokens.shape
        if seq_axis is None:
            if T > model_cfg.n_ctx:
                raise ValueError(f"sequence length {T} exceeds n_ctx "
                                 f"{model_cfg.n_ctx}")
            offset = 0
        else:
            # static guard (axis sizes are static under shard_map): an
            # oversized total sequence would silently RoPE-extrapolate past
            # n_ctx instead of failing; mirror gpt2_pipe's loud check
            total_t = T * lax.axis_size(seq_axis)
            if total_t > model_cfg.n_ctx:
                raise ValueError(
                    f"total sequence length {total_t} (T_local {T} x "
                    f"{lax.axis_size(seq_axis)} seq shards) exceeds n_ctx "
                    f"{model_cfg.n_ctx}")
            offset = lax.axis_index(seq_axis) * T
        cos, sin = rope_angles(T, model_cfg.head_dim, model_cfg.rope_theta,
                               offset=offset)
        # same remat wrapper as the non-pipelined path (honors remat_policy)
        block = _block_remat_for(model_cfg) if model_cfg.remat else _block

        def layer_fn(p_layer, h):
            return block(h, p_layer, model_cfg, cos, sin, tp_axis, seq_axis)

        x = params["wte"][tokens].astype(model_cfg.compute_dtype)
        xm = x.reshape((n_micro, B // n_micro, T, x.shape[-1]))
        # local stage view inside shard_map keeps a leading [1] shard axis
        stage_local = jax.tree.map(lambda a: a[0], params["stages"])
        acc = pipeline_apply(layer_fn, stage_local, xm, axis_name=axis_name)

        if seq_axis is not None:
            # sp × pp scaffold (collective hoisting + grad contract) shared
            # with gpt2_pipe: models/loss.pipelined_seq_parallel_loss.
            from distributed_lion_tpu.models.loss import (
                pipelined_seq_parallel_loss,
            )
            from distributed_lion_tpu.ops.xent import masked_local_nll

            def head_partials(acc, labels, mask):
                h = _rms_norm(acc.reshape((B, T, x.shape[-1])),
                              params["ln_f"], model_cfg.rms_eps)
                return masked_local_nll(
                    h, params["lm_head"], labels, mask, vocab_chunks,
                    emb_layout="dv")

            return pipelined_seq_parallel_loss(
                head_partials, acc, tokens, seq_axis, axis_name)

        def head_loss(acc):
            h = acc.reshape((B, T, x.shape[-1]))
            h = _rms_norm(h, params["ln_f"], model_cfg.rms_eps)
            if vocab_chunks > 0:
                from distributed_lion_tpu.ops.xent import (
                    chunked_clm_loss_and_metrics,
                )

                return chunked_clm_loss_and_metrics(
                    h, params["lm_head"], tokens, vocab_chunks,
                    emb_layout="dv")
            logits = jnp.einsum(
                "btd,dv->btv", h, params["lm_head"].astype(h.dtype),
                preferred_element_type=jnp.float32,
            )
            return clm_loss_and_metrics(logits, tokens)

        def skip_loss(acc):
            z = jnp.float32(0)
            return z, {"loss": z, "accuracy": z, "n_tokens": z}

        # only the last stage saw real activations (see gpt2_pipe: cond
        # skips the vocab projection elsewhere; the psum broadcasts the
        # value and routes zero cotangent into the skip branch)
        stage = lax.axis_index(axis_name)
        last = lax.psum(1, axis_name) - 1
        loss_local, metrics = lax.cond(stage == last, head_loss, skip_loss, acc)
        loss = lax.psum(loss_local, axis_name)
        metrics = {k: lax.psum(v, axis_name) for k, v in metrics.items()}
        return loss, metrics

    return loss_fn


def validate_llama_pipeline(model_cfg: LlamaConfig, cfg, pp: int,
                            n_micro: int) -> None:
    """Config-time guards for ``--pipeline_parallel`` on the Llama family."""
    if model_cfg.n_layer % pp:
        raise ValueError(f"n_layer {model_cfg.n_layer} not divisible by "
                         f"pipeline stages {pp}")
    if cfg.per_device_train_batch_size % n_micro:
        raise ValueError(
            f"per_device_train_batch_size {cfg.per_device_train_batch_size} "
            f"not divisible by pipeline_microbatches {n_micro}"
        )
    if cfg.per_device_eval_batch_size % n_micro:
        raise ValueError(
            f"per_device_eval_batch_size {cfg.per_device_eval_batch_size} "
            f"not divisible by pipeline_microbatches {n_micro}"
        )
