"""Pipeline-parallel GPT-2: blocks as stages, trainable end-to-end.

Net-new vs the reference (data-parallel only, SURVEY §2.7). This wires the
generic GPipe schedule (parallel/pipeline.py: stacked stage params sharded
over the ``pipe`` mesh axis, activations rotating via ``ppermute``, one
``lax.scan``) to the real GPT-2 of models/gpt2.py so ``run_clm
--pipeline_parallel N`` trains with blocks split into N stages.

SPMD layout inside the train-step ``shard_map`` (axes data × pipe):

- params = {wte, wpe, ln_f, stages} — ``stages`` leaves are
  ``[pp, n_layer/pp, ...]`` sharded ``P('pipe', ...)``; the embedding/final
  norm stay replicated.
- every stage runs the same program: embed (only stage 0's result is
  ingested), pipeline over the stages, ln_f + tied-logits + CLM loss (only
  the LAST stage's is real — selected with a masked ``psum``); the backward
  through the other stages' garbage compute receives zero cotangent.
- replicated-leaf gradients (wte/wpe/ln_f) are per-stage partials over
  disjoint contributions (stage 0: embedding; last stage: logits tie) —
  the train loop ``psum``s them over the pipe axis (train/loop.py), exactly
  like the seq-parallel gradient reduction.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from distributed_lion_tpu.models.gpt2 import (
    GPT2Config,
    _block,
    _block_remat_for,
    _layer_norm,
)
from distributed_lion_tpu.models.loss import clm_loss_and_metrics
from distributed_lion_tpu.parallel.mesh import PIPE_AXIS
from distributed_lion_tpu.parallel.pipeline import (
    pipeline_apply,
    stack_stage_params,
    unstack_stage_params,
)


def pipeline_params(params: dict, pp: int) -> dict:
    """Standard gpt2_init layout → pipeline layout with stacked stages."""
    return {
        "wte": params["wte"],
        "wpe": params["wpe"],
        "ln_f": params["ln_f"],
        "stages": stack_stage_params(params["blocks"], pp),
    }


def unpipeline_params(pparams: dict, n_layer: int) -> dict:
    """Inverse of :func:`pipeline_params` (export / generation)."""
    return {
        "wte": pparams["wte"],
        "wpe": pparams["wpe"],
        "ln_f": pparams["ln_f"],
        "blocks": unstack_stage_params(pparams["stages"], n_layer),
    }


def pipeline_param_specs(tensor: bool = False) -> dict:
    """Replicated embeddings/norm; stage leaves sharded over ``pipe`` (the
    stacked-stage leading dim is implied by ``P(PIPE_AXIS)`` alone — no
    config dependence).

    ``tensor=True`` ADDITIONALLY shards each stage's weights over the
    tensor axis (tp × pp, the classic large-model mesh): the per-layer
    Megatron specs of parallel/tensor_parallel.gpt2_param_specs shift right
    by the two stacked-stage dims ``[pp, layers/stage, ...]``. Embeddings,
    final norm, and the tied head stay replicated over tensor (the
    replicated-head TP layout) — the per-stage LayerNorms stay sharded over
    pipe only, and their tensor-axis gradients arrive complete through the
    Megatron copy boundary inside each block, so no extra reduction is
    needed (same argument as the non-pipelined TP path)."""
    rep = P()
    ln = {"scale": rep, "bias": rep}
    stage_ln = {"scale": P(PIPE_AXIS), "bias": P(PIPE_AXIS)}
    if not tensor:
        att = {k: P(PIPE_AXIS) for k in ("qkv", "qkv_b", "proj", "proj_b")}
        mlp = {k: P(PIPE_AXIS) for k in ("fc", "fc_b", "proj", "proj_b")}
    else:
        from distributed_lion_tpu.parallel.mesh import TENSOR_AXIS

        def stage_spec(*tensor_dims):
            return P(PIPE_AXIS, None, *tensor_dims)

        att = {
            "qkv": stage_spec(None, None, TENSOR_AXIS),   # [d, 3, d/tp]
            "qkv_b": stage_spec(None, TENSOR_AXIS),
            "proj": stage_spec(TENSOR_AXIS, None),        # row-parallel
            "proj_b": stage_spec(),
        }
        mlp = {
            "fc": stage_spec(None, TENSOR_AXIS),          # column-parallel
            "fc_b": stage_spec(TENSOR_AXIS),
            "proj": stage_spec(TENSOR_AXIS, None),        # row-parallel
            "proj_b": stage_spec(),
        }
    stages = {"ln_1": stage_ln, "attn": att, "ln_2": stage_ln, "mlp": mlp}
    return {"wte": rep, "wpe": rep, "ln_f": ln, "stages": stages}


def make_pipeline_loss(model_cfg: GPT2Config, n_micro: int,
                       axis_name: str = PIPE_AXIS,
                       tp_axis: Optional[str] = None,
                       vocab_chunks: int = 0,
                       seq_axis: Optional[str] = None):
    """Build ``loss_fn(params, tokens, dropout_key) -> (loss, metrics)`` for
    the Trainer. Must run inside ``shard_map`` with ``axis_name`` bound;
    ``tokens`` [B_local, T] with B_local divisible by ``n_micro``. Dropout is
    unsupported under pipelining (guarded at config time).

    ``tp_axis`` runs each stage's blocks tensor-parallel (tp × pp):
    activations enter every stage replicated over the tensor axis, each
    block's column/row-parallel matmuls psum over it (models/gpt2._block),
    and they exit replicated again — so the ppermute pipeline rotation and
    the last-stage replicated head are untouched by tensor sharding.

    ``vocab_chunks`` streams the last stage's tied head through the chunked
    CE (ops/xent) — the [B, T, V] logits never materialize even on the one
    stage that computes the loss (and ONLY there: the cond still skips the
    head on every other stage).

    ``seq_axis`` shards TOKENS over a sequence axis on top of the pipeline
    (sp × pp, long-context pipelined training): each stage's blocks ring
    their attention k/v over ``seq_axis`` inside every pipeline tick, the
    positional rows are offset by the seq shard index, and the last stage's
    loss runs the seq-parallel CE. Its collectives (boundary-label
    ppermute, count/metric psums) are hoisted OUTSIDE the lax.cond — XLA
    aborts on collectives under conditional control flow — so the cond
    computes only collective-free masked NLL partials
    (ops/xent.masked_local_nll)."""

    # _block_remat_for honors cfg.remat_policy ('dots' keeps matmul
    # outputs) — the same wrapper the non-pipelined path uses
    block = _block_remat_for(model_cfg) if model_cfg.remat else _block

    def layer_fn(p_layer, h):
        return block(h, p_layer, None, model_cfg, tp_axis, seq_axis)

    def loss_fn(params, tokens, dropout_key):
        del dropout_key  # dropout unsupported under pipelining
        B, T = tokens.shape
        if seq_axis is None:
            if T > model_cfg.n_ctx:
                raise ValueError(
                    f"sequence length {T} exceeds n_ctx {model_cfg.n_ctx}")
            pos_start = 0
        else:
            # axis sizes are static under shard_map, so this guard is
            # shape-static too: without it an oversized TOTAL sequence
            # (T_local × seq shards > n_ctx) would make the wpe
            # dynamic_slice below clamp silently and hand later seq shards
            # duplicated positional rows — callers bypassing the Trainer's
            # config-time validate_seq_block must still fail loudly here
            total_t = T * lax.axis_size(seq_axis)
            if total_t > model_cfg.n_ctx:
                raise ValueError(
                    f"total sequence length {total_t} (T_local {T} x "
                    f"{lax.axis_size(seq_axis)} seq shards) exceeds n_ctx "
                    f"{model_cfg.n_ctx}")
            pos_start = lax.axis_index(seq_axis) * T
        x = params["wte"][tokens].astype(model_cfg.compute_dtype)
        x = x + lax.dynamic_slice_in_dim(
            params["wpe"], pos_start, T, axis=0
        ).astype(model_cfg.compute_dtype)
        xm = x.reshape((n_micro, B // n_micro, T, x.shape[-1]))
        # local stage view inside shard_map keeps a leading [1] shard axis
        stage_local = jax.tree.map(lambda a: a[0], params["stages"])
        acc = pipeline_apply(layer_fn, stage_local, xm, axis_name=axis_name)

        if seq_axis is not None:
            # sp × pp scaffold (collective hoisting + grad contract) lives
            # in models/loss.pipelined_seq_parallel_loss, shared with
            # llama_pipe; only the family head is defined here.
            from distributed_lion_tpu.models.loss import (
                pipelined_seq_parallel_loss,
            )
            from distributed_lion_tpu.ops.xent import masked_local_nll

            def head_partials(acc, labels, mask):
                h = _layer_norm(acc.reshape((B, T, x.shape[-1])),
                                params["ln_f"])
                return masked_local_nll(
                    h, params["wte"], labels, mask, vocab_chunks,
                    valid_v=model_cfg.vocab_size)

            return pipelined_seq_parallel_loss(
                head_partials, acc, tokens, seq_axis, axis_name)

        def head_loss(acc):
            h = acc.reshape((B, T, x.shape[-1]))
            h = _layer_norm(h, params["ln_f"])
            if vocab_chunks > 0:
                from distributed_lion_tpu.ops.xent import (
                    chunked_clm_loss_and_metrics,
                )

                return chunked_clm_loss_and_metrics(
                    h, params["wte"], tokens, vocab_chunks,
                    valid_v=model_cfg.vocab_size)
            logits = jnp.einsum(
                "btd,vd->btv", h, params["wte"].astype(h.dtype),
                preferred_element_type=jnp.float32,
            )
            # padded-vocab layout (models/gpt2 vocab_pad_multiple): drop the
            # alignment columns before the loss, same as gpt2_apply
            return clm_loss_and_metrics(logits[..., : model_cfg.vocab_size],
                                        tokens)

        def skip_loss(acc):
            z = jnp.float32(0)
            return z, {"loss": z, "accuracy": z, "n_tokens": z}

        # only the last stage saw real activations; lax.cond skips the
        # (expensive) vocab projection + loss on the other stages entirely —
        # XLA executes just the taken branch — and the psum then both
        # broadcasts the value and routes zero cotangent to the skip branch
        stage = lax.axis_index(axis_name)
        last = lax.psum(1, axis_name) - 1
        loss_local, metrics = lax.cond(stage == last, head_loss, skip_loss, acc)
        loss = lax.psum(loss_local, axis_name)
        metrics = {k: lax.psum(v, axis_name) for k, v in metrics.items()}
        return loss, metrics

    return loss_fn


def validate_pipeline(model_cfg: GPT2Config, cfg, pp: int, n_micro: int) -> None:
    """Config-time guards for ``--pipeline_parallel``."""
    if model_cfg.n_layer % pp:
        raise ValueError(f"n_layer {model_cfg.n_layer} not divisible by "
                         f"pipeline stages {pp}")
    if model_cfg.dropout > 0.0:
        raise ValueError("dropout is unsupported under pipeline parallelism "
                         "(per-microbatch keys would need schedule-aware "
                         "plumbing); set --dropout 0")
    if cfg.per_device_train_batch_size % n_micro:
        raise ValueError(
            f"per_device_train_batch_size {cfg.per_device_train_batch_size} "
            f"not divisible by pipeline_microbatches {n_micro}"
        )
    if cfg.per_device_eval_batch_size % n_micro:
        raise ValueError(
            f"per_device_eval_batch_size {cfg.per_device_eval_batch_size} "
            f"not divisible by pipeline_microbatches {n_micro}"
        )
