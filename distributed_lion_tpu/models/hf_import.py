"""HF-checkpoint ingestion: local GPT-2 / Llama weights → this repo's pytrees.

The reference finetunes *pretrained* models pulled from HF hub — GPT-2 via
``AutoModelForCausalLM.from_pretrained`` (/root/reference/run_clm.py:425-444)
and Llama-2-7B for SFT/DPO (/root/reference/sft_llama2.py:141-154,
dpo_llama2.py:133-152). This environment is zero-egress, so ingestion is from
*local files only*: a ``save_pretrained`` directory (``*.safetensors`` —
optionally index-sharded — or ``pytorch_model.bin`` + ``config.json``), a bare
safetensors/bin file, or an ``.npz``. No hub, no network.

Layout notes (the actual conversion work):

- **GPT-2 stores Conv1D weights as [in, out]** (not torch-Linear's
  [out, in]), so ``c_attn``/``c_proj``/``c_fc`` map without transposition;
  ``c_attn.weight [d, 3d]`` reshapes straight into our stacked
  ``qkv [d, 3, d]`` because HF packs q|k|v contiguously on the output dim.
- **Llama stores Linear weights as [out, in]** → every projection is
  transposed into our [in, out] matmul layout.
- **RoPE convention**: HF Llama applies the *half-rotation* (rotate_half)
  form; this repo's ``apply_rope`` uses the *interleaved* (even/odd pairs)
  form. The two are related by a per-head permutation of the q/k output
  channels — ``new[2i] = old[i]``, ``new[2i+1] = old[i + hd/2]`` — which is
  the inverse of the permutation HF's own conversion script applies to the
  original Meta weights. Applied here to ``wq``/``wk`` so logits match HF
  bit-for-bit-in-fp32 (pinned by tests/test_hf_import.py).
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import numpy as np


# --------------------------------------------------------------------- loading

def _load_safetensors(path: str) -> dict:
    """One .safetensors file → {name: np.ndarray} (bf16 via torch)."""
    from safetensors import safe_open

    out = {}
    with safe_open(path, framework="pt", device="cpu") as f:
        for name in f.keys():
            t = f.get_tensor(name)
            if t.dtype.is_floating_point:
                t = t.float()
            out[name] = t.numpy()
    return out


def _load_torch_bin(path: str) -> dict:
    import torch

    sd = torch.load(path, map_location="cpu", weights_only=True)
    out = {}
    for name, t in sd.items():
        if t.dtype.is_floating_point:
            t = t.float()
        out[name] = t.numpy()
    return out


def load_state_dict(path: str) -> dict:
    """Local checkpoint → flat {hf_name: np.ndarray} (floats upcast to f32).

    ``path`` may be a ``save_pretrained`` directory, a single
    ``.safetensors`` / ``.bin`` / ``.pt`` file, or an ``.npz``.
    """
    if os.path.isdir(path):
        index = os.path.join(path, "model.safetensors.index.json")
        if os.path.exists(index):
            with open(index) as f:
                shards = sorted(set(json.load(f)["weight_map"].values()))
            sd = {}
            for shard in shards:
                sd.update(_load_safetensors(os.path.join(path, shard)))
            return sd
        single = os.path.join(path, "model.safetensors")
        if os.path.exists(single):
            return _load_safetensors(single)
        bin_index = os.path.join(path, "pytorch_model.bin.index.json")
        if os.path.exists(bin_index):
            with open(bin_index) as f:
                shards = sorted(set(json.load(f)["weight_map"].values()))
            sd = {}
            for shard in shards:
                sd.update(_load_torch_bin(os.path.join(path, shard)))
            return sd
        bin_path = os.path.join(path, "pytorch_model.bin")
        if os.path.exists(bin_path):
            return _load_torch_bin(bin_path)
        raise FileNotFoundError(
            f"no model.safetensors(.index.json) or pytorch_model.bin under {path!r}"
        )
    if path.endswith(".safetensors"):
        return _load_safetensors(path)
    if path.endswith((".bin", ".pt")):
        return _load_torch_bin(path)
    if path.endswith(".npz"):
        with np.load(path) as z:
            return {k: z[k] for k in z.files}
    raise ValueError(f"unrecognized checkpoint format: {path!r}")


def load_hf_config(path: str) -> Optional[dict]:
    cfg_path = os.path.join(path, "config.json") if os.path.isdir(path) else None
    if cfg_path and os.path.exists(cfg_path):
        with open(cfg_path) as f:
            return json.load(f)
    return None


def _strip_prefix(sd: dict, prefix: str) -> dict:
    if any(k.startswith(prefix) for k in sd):
        return {k[len(prefix):] if k.startswith(prefix) else k: v for k, v in sd.items()}
    return sd


def _cast_tree(params, dtype):
    import jax
    import jax.numpy as jnp

    return jax.tree.map(lambda x: jnp.asarray(x, dtype), params)


# ----------------------------------------------------------------------- GPT-2

def gpt2_from_hf(path: str, param_dtype: Any = None, **config_overrides):
    """HF GPT-2 checkpoint → ``(params, GPT2Config)``.

    Parity target: ``GPT2LMHeadModel.from_pretrained`` as used by the
    reference's run_clm (run_clm.py:425-444). Logit equivalence vs the torch
    model is pinned by tests/test_hf_import.py.
    """
    import jax.numpy as jnp

    from distributed_lion_tpu.models.gpt2 import GPT2Config

    sd = _strip_prefix(load_state_dict(path), "transformer.")
    hf_cfg = load_hf_config(path) or {}

    wte = sd["wte.weight"]
    wpe = sd["wpe.weight"]
    vocab, d = wte.shape
    n_layer = 1 + max(
        int(k.split(".")[1]) for k in sd if k.startswith("h.") and k.split(".")[1].isdigit()
    )
    n_head = int(hf_cfg.get("n_head", config_overrides.get("n_head", 12)))
    cfg_kw = dict(
        vocab_size=vocab,
        n_layer=n_layer,
        n_head=n_head,
        d_model=d,
        n_ctx=wpe.shape[0],
    )
    cfg_kw.update(config_overrides)
    if param_dtype is not None:
        cfg_kw["param_dtype"] = param_dtype
    cfg = GPT2Config(**cfg_kw)
    dt = cfg.param_dtype

    def ln(prefix):
        return {"scale": jnp.asarray(sd[f"{prefix}.weight"], dt),
                "bias": jnp.asarray(sd[f"{prefix}.bias"], dt)}

    params = {
        "wte": jnp.asarray(wte, dt),
        "wpe": jnp.asarray(wpe, dt),
        "ln_f": ln("ln_f"),
        "blocks": [],
    }
    for i in range(n_layer):
        h = f"h.{i}"
        # Conv1D weights are [in, out]; c_attn's output dim is q|k|v
        # contiguous → a straight reshape lands in our stacked [d, 3, d].
        params["blocks"].append({
            "ln_1": ln(f"{h}.ln_1"),
            "attn": {
                "qkv": jnp.asarray(sd[f"{h}.attn.c_attn.weight"].reshape(d, 3, d), dt),
                "qkv_b": jnp.asarray(sd[f"{h}.attn.c_attn.bias"].reshape(3, d), dt),
                "proj": jnp.asarray(sd[f"{h}.attn.c_proj.weight"], dt),
                "proj_b": jnp.asarray(sd[f"{h}.attn.c_proj.bias"], dt),
            },
            "ln_2": ln(f"{h}.ln_2"),
            "mlp": {
                "fc": jnp.asarray(sd[f"{h}.mlp.c_fc.weight"], dt),
                "fc_b": jnp.asarray(sd[f"{h}.mlp.c_fc.bias"], dt),
                "proj": jnp.asarray(sd[f"{h}.mlp.c_proj.weight"], dt),
                "proj_b": jnp.asarray(sd[f"{h}.mlp.c_proj.bias"], dt),
            },
        })
    return params, cfg


# ----------------------------------------------------------------------- Llama

def _rope_to_interleaved(w_out_in: np.ndarray, n_heads: int) -> np.ndarray:
    """Permute a [heads*hd, in] q/k projection from HF's half-rotation RoPE
    layout to this repo's interleaved layout: new[2i] = old[i],
    new[2i+1] = old[i + hd/2], per head."""
    out, d_in = w_out_in.shape
    hd = out // n_heads
    w = w_out_in.reshape(n_heads, 2, hd // 2, d_in)
    return np.ascontiguousarray(w.transpose(0, 2, 1, 3)).reshape(out, d_in)


def llama_from_hf(path: str, param_dtype: Any = None, **config_overrides):
    """HF Llama checkpoint → ``(params, LlamaConfig)``.

    Parity target: ``AutoModelForCausalLM.from_pretrained(llama)`` as the
    reference's SFT/DPO base (sft_llama2.py:141-154). Handles GQA, tied or
    untied lm_head, and the RoPE layout permutation (module docstring).
    """
    import jax.numpy as jnp

    from distributed_lion_tpu.models.llama import LlamaConfig

    sd = load_state_dict(path)
    hf_cfg = load_hf_config(path) or {}

    wte = sd["model.embed_tokens.weight"]
    vocab, d = wte.shape
    n_layer = 1 + max(
        int(k.split(".")[2]) for k in sd if k.startswith("model.layers.")
    )
    d_ff = sd["model.layers.0.mlp.gate_proj.weight"].shape[0]
    kv_out = sd["model.layers.0.self_attn.k_proj.weight"].shape[0]
    n_head = int(hf_cfg.get("num_attention_heads",
                            config_overrides.get("n_head", 32)))
    hd = d // n_head
    n_kv_head = kv_out // hd
    cfg_kw = dict(
        vocab_size=vocab,
        n_layer=n_layer,
        n_head=n_head,
        n_kv_head=n_kv_head,
        d_model=d,
        d_ff=d_ff,
        n_ctx=int(hf_cfg.get("max_position_embeddings", 4096)),
        rope_theta=float(hf_cfg.get("rope_theta", 10000.0)),
        rms_eps=float(hf_cfg.get("rms_norm_eps", 1e-5)),
    )
    cfg_kw.update(config_overrides)
    if param_dtype is not None:
        cfg_kw["param_dtype"] = param_dtype
    cfg = LlamaConfig(**cfg_kw)
    dt = cfg.param_dtype

    if "lm_head.weight" in sd and not hf_cfg.get("tie_word_embeddings", False):
        lm_head = sd["lm_head.weight"].T  # [V, d] -> [d, V]
    else:
        lm_head = wte.T  # tied embeddings

    params = {
        "wte": jnp.asarray(wte, dt),
        "lm_head": jnp.asarray(lm_head, dt),
        "ln_f": {"scale": jnp.asarray(sd["model.norm.weight"], dt)},
        "blocks": [],
    }
    for i in range(n_layer):
        a = f"model.layers.{i}.self_attn"
        m = f"model.layers.{i}.mlp"
        params["blocks"].append({
            "ln_attn": {"scale": jnp.asarray(
                sd[f"model.layers.{i}.input_layernorm.weight"], dt)},
            "attn": {
                # Linear [out, in] → permute rope channels, then T → [in, out]
                "wq": jnp.asarray(
                    _rope_to_interleaved(sd[f"{a}.q_proj.weight"], cfg.n_head).T, dt),
                "wk": jnp.asarray(
                    _rope_to_interleaved(sd[f"{a}.k_proj.weight"], cfg.n_kv_head).T, dt),
                "wv": jnp.asarray(sd[f"{a}.v_proj.weight"].T, dt),
                "wo": jnp.asarray(sd[f"{a}.o_proj.weight"].T, dt),
            },
            "ln_mlp": {"scale": jnp.asarray(
                sd[f"model.layers.{i}.post_attention_layernorm.weight"], dt)},
            "mlp": {
                "w_gate": jnp.asarray(sd[f"{m}.gate_proj.weight"].T, dt),
                "w_up": jnp.asarray(sd[f"{m}.up_proj.weight"].T, dt),
                "w_down": jnp.asarray(sd[f"{m}.down_proj.weight"].T, dt),
            },
        })
    return params, cfg


def peft_to_lora(path: str, model_cfg: Any, dtype: Any = None) -> tuple:
    """Import a HF PEFT LoRA checkpoint → (adapters pytree, LoraConfig).

    Inverse of hf_export.lora_to_peft: ``lora_A.weight`` [r, in] → A [in, r],
    ``lora_B.weight`` [out, r] → B [r, out] with q/k output rows permuted
    from HF's half-rotation RoPE layout to ours (same transform as the base
    import). Lets run_sft/run_dpo continue training an adapter produced by
    the torch/PEFT stack (or by our own ``--adapter_output``).
    """
    import json as _json

    import jax.numpy as jnp

    from distributed_lion_tpu.models.hf_export import _PEFT_MODULES
    from distributed_lion_tpu.models.lora import LoraConfig

    with open(os.path.join(path, "adapter_config.json")) as f:
        pc = _json.load(f)
    if pc.get("peft_type") != "LORA":
        raise ValueError(f"not a LoRA adapter: peft_type={pc.get('peft_type')!r}")
    # Scaling variants this importer does not model: rsLoRA rescales
    # alpha/sqrt(r), and rank/alpha_pattern give per-module overrides.
    # Importing one with the plain alpha/r scaling would silently train the
    # adapter at the wrong effective magnitude — refuse instead.
    if pc.get("use_rslora"):
        raise ValueError(
            "PEFT adapter was trained with use_rslora=True (scaling "
            "alpha/sqrt(r)); this importer applies plain alpha/r scaling and "
            "would be silently wrong. Merge the adapter with PEFT first, or "
            "retrain without rslora."
        )
    for pat in ("rank_pattern", "alpha_pattern"):
        if pc.get(pat):
            raise ValueError(
                f"PEFT adapter sets {pat}={pc[pat]!r} (per-module rank/alpha "
                "overrides); this importer supports a single global r/alpha "
                "only and would import with wrong effective scaling."
            )
    # PEFT names its weight file adapter_model.*, not model.* — load directly
    st_path = os.path.join(path, "adapter_model.safetensors")
    if os.path.exists(st_path):
        sd = _load_safetensors(st_path)
    else:
        sd = _load_torch_bin(os.path.join(path, "adapter_model.bin"))

    module_to_ours = {v[0]: (k, v[1]) for k, v in _PEFT_MODULES.items()}
    dt = dtype or jnp.float32
    adapters: dict = {}
    for key, val in sd.items():
        if key.endswith(".lora_embedding_A"):
            # PEFT Embedding adapter: A [r, V], B [d, r] (transposed vs the
            # Linear convention) on embed_tokens → our gather-side "wte"
            # adapter {A: [V, r], B: [r, d]} (models/lora.lora_embed)
            b_key = key[: -len("lora_embedding_A")] + "lora_embedding_B"
            if b_key not in sd:
                raise ValueError(
                    f"malformed PEFT checkpoint: {key!r} has no paired "
                    f"{b_key!r}")
            adapters["wte"] = {
                "A": jnp.asarray(np.asarray(val).T, dt),
                "B": jnp.asarray(np.asarray(sd[b_key]).T, dt),
            }
            continue
        if not key.endswith(".lora_A.weight"):
            continue
        stem = key[: -len(".lora_A.weight")]
        b_key = stem + ".lora_B.weight"
        if b_key not in sd:
            raise ValueError(
                f"malformed PEFT checkpoint: {key!r} has no paired {b_key!r}"
            )
        # stem like base_model.model.model.layers.3.self_attn.q_proj
        parts = stem.split(".")
        layer = parts[parts.index("layers") + 1]
        module = ".".join(parts[parts.index("layers") + 2:])
        if module not in module_to_ours:
            raise ValueError(f"unsupported PEFT target module {module!r}")
        ours, heads_attr = module_to_ours[module]
        A = np.asarray(sd[key]).T                     # [in, r]
        B = np.asarray(sd[b_key])                     # [out, r]
        if heads_attr is not None:
            B = _rope_to_interleaved(B, int(getattr(model_cfg, heads_attr)))
        group = "attn" if ours.startswith("w") and ours in (
            "wq", "wk", "wv", "wo") else "mlp"
        adapters[f"blocks/{layer}/{group}/{ours}"] = {
            "A": jnp.asarray(A, dt),
            "B": jnp.asarray(np.ascontiguousarray(B.T), dt),  # [r, out]
        }
    if not adapters:
        raise ValueError(f"no lora_A/lora_B pairs found under {path!r}")
    lcfg = LoraConfig(r=int(pc["r"]), alpha=int(pc["lora_alpha"]),
                      target_patterns=tuple(sorted(
                          {p.split("/")[-1] for p in adapters})))
    return adapters, lcfg


def detect_family(path: str) -> str:
    """'gpt2' | 'llama' from config.json (or key shapes as fallback)."""
    hf_cfg = load_hf_config(path)
    if hf_cfg:
        mt = hf_cfg.get("model_type", "")
        if mt in ("gpt2",):
            return "gpt2"
        if mt in ("llama", "mistral"):
            return "llama"
    sd_keys = load_state_dict(path).keys()
    if any("embed_tokens" in k for k in sd_keys):
        return "llama"
    if any(k.endswith("wte.weight") for k in sd_keys):
        return "gpt2"
    raise ValueError(f"cannot detect model family of checkpoint at {path!r}")
