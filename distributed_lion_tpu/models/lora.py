"""LoRA adapters over frozen (optionally quantized) base weights.

The reference applies PEFT LoRA on Llama-2's q/v projections with r=8, α=16,
dropout 0.05 (/root/reference/sft_llama2.py:44-51) and a wider target set for
DPO (q/v/k/out_proj + fc_in/fc_out/wte, dpo_llama2.py:192-207), then merges
adapters into the base on save (sft_llama2.py:193-199 ``merge_and_unload``).

Native design: adapters live in a SEPARATE flat dict keyed by the adapted
leaf's '/'-joined path, each entry {"A": [d_in, r], "B": [r, *out_dims]}.
The model apply stays untouched — an adapted leaf is swapped for a
:class:`LoraTensor` pytree node and the models' ``_matmul`` computes the
FACTORED form ``x @ W + (α/r)·(x @ A) @ B`` (never materializing ``W + ΔW``:
at 7B that would re-form every adapted dense weight per call — VERDICT r1
weak #5). Training differentiates ONLY the adapter tree, so the optimizer
(and its vote) sees just the LoRA params — the base stays frozen/quantized.

Tensor parallelism: adapters of column-parallel targets shard ``B`` on the
output dim (``A`` replicated); row-parallel targets shard ``A`` on the input
dim (``B`` replicated) — :func:`lora_adapter_specs`. Replicated factors are
used INSIDE the Megatron-parallel region, so their backward only carries the
local shard's contribution; :func:`apply_adapters` wraps them in
``copy_to_tp_region`` (identity fwd, tensor-psum bwd) so every rank's
adapter gradient is complete and replicas stay in sync.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp

from distributed_lion_tpu.ops.quant import QuantizedTensor, maybe_dequant


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class LoraTensor:
    """A frozen base weight + its low-rank adapter, consumed by the models'
    ``_matmul``/einsum sites in factored form. ``base`` may be a dense array
    or a QuantizedTensor. ``dropout_key`` (set by apply_adapters during
    training) enables the reference's ``lora_dropout`` on the adapter
    branch — PEFT semantics: dropout on the INPUT of the A projection only,
    the frozen-base path never dropped (sft_llama2.py:48)."""

    base: Any               # [d_in, *out_dims] dense or QuantizedTensor
    A: jnp.ndarray          # [d_in, r]
    B: jnp.ndarray          # [r, *out_dims]
    scaling: float          # α/r (static)
    dropout_rate: float = 0.0          # static
    dropout_key: Any = None            # child; None ⇒ eval mode

    def tree_flatten(self):
        return (self.base, self.A, self.B, self.dropout_key), (
            self.scaling, self.dropout_rate)

    @classmethod
    def tree_unflatten(cls, aux, children):
        base, A, B, key = children
        return cls(base, A, B, aux[0], aux[1], key)

    @property
    def shape(self):
        return self.base.shape

    @property
    def ndim(self):
        return len(self.base.shape)


def _branch_dropout(x: jnp.ndarray, w: "LoraTensor") -> jnp.ndarray:
    """Inverted dropout on the adapter-branch input (torch nn.Dropout
    semantics: scale kept units by 1/(1-p)); identity when no key."""
    if w.dropout_key is None or w.dropout_rate <= 0.0:
        return x
    keep = 1.0 - w.dropout_rate
    mask = jax.random.bernoulli(w.dropout_key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


def lora_matmul(x: jnp.ndarray, w) -> jnp.ndarray:
    """``x @ w`` for dense / quantized / LoRA-adapted 2-D weights — the
    single hook the models route every projection through."""
    if isinstance(w, LoraTensor):
        base = maybe_dequant(w.base, x.dtype)
        xd = _branch_dropout(x, w)
        delta = (xd @ w.A.astype(x.dtype)) @ w.B.astype(x.dtype)
        return x @ base.astype(x.dtype) + w.scaling * delta
    return x @ maybe_dequant(w, x.dtype).astype(x.dtype)


def lora_embed(w, tokens: jnp.ndarray, dtype=jnp.bfloat16) -> jnp.ndarray:
    """Embedding lookup for dense / quantized / LoRA-adapted tables — the
    gather-side counterpart of :func:`lora_matmul` (the reference's DPO
    adapts ``wte`` too, dpo_llama2.py:192-207). For a LoraTensor:
    ``base[tokens] + (α/r)·(A[tokens] @ B)`` — the one-hot-gather factored
    form. No adapter dropout here: PEFT's lora_dropout lives on Linear
    layers only (dropout over integer indices is meaningless)."""
    if isinstance(w, LoraTensor):
        base = maybe_dequant(w.base, dtype)[tokens].astype(dtype)
        a_rows = w.A[tokens].astype(dtype)          # [B, T, r]
        return base + (w.scaling * (a_rows @ w.B.astype(dtype))).astype(dtype)
    return maybe_dequant(w, dtype)[tokens].astype(dtype)


@dataclasses.dataclass(frozen=True)
class LoraConfig:
    """sft_llama2.py:44-51 defaults: r=8, alpha=16, lora_dropout=0.05 on the
    adapter branch (PEFT semantics — applied when apply_adapters gets a
    dropout key, i.e. during training only), targets q/v projections."""

    r: int = 8
    alpha: int = 16
    dropout: float = 0.0
    target_patterns: Sequence[str] = ("wq", "wv", "q_proj", "v_proj", "qkv")

    @property
    def scaling(self) -> float:
        return self.alpha / self.r


# the reference's DPO target set (dpo_llama2.py:192-207: q/v/k/out_proj +
# fc_in/fc_out/wte) translated to this repo's Llama leaf names: all four
# attention projections, the full SwiGLU MLP, and the token embedding
# (gather-side adapter, :func:`lora_embed`).
DPO_TARGET_PATTERNS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
                       "wte", "q_proj", "k_proj", "v_proj", "out_proj")


def _is_weight_leaf(x) -> bool:
    # 2-D projections and 3-D stacked projections (GPT-2's [d, 3, d] qkv)
    return isinstance(x, QuantizedTensor) or getattr(x, "ndim", 0) in (2, 3)


def _iter_paths(tree, prefix=()):
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield from _iter_paths(v, prefix + (str(k),))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            yield from _iter_paths(v, prefix + (str(i),))
    else:
        yield prefix, tree


def lora_init(key: jax.Array, base_params: Any, cfg: LoraConfig,
              dtype=jnp.float32) -> dict:
    """Build the adapter pytree: {'/'-joined path: {"A", "B"}} for every 2-D
    base leaf whose last path component matches a target pattern.

    A ~ N(0, 1/r), B = 0 (standard LoRA init: adapter starts as identity).
    """
    adapters = {}
    paths = [
        (path, leaf) for path, leaf in _iter_paths(base_params)
        if _is_weight_leaf(leaf) and any(re.fullmatch(p, path[-1]) for p in cfg.target_patterns)
    ]
    keys = jax.random.split(key, max(len(paths), 1))
    for k, (path, leaf) in zip(keys, paths):
        shape = tuple(int(s) for s in leaf.shape)
        d_in, out_dims = shape[0], shape[1:]  # n-D: B carries the trailing dims
        adapters["/".join(path)] = {
            "A": (jax.random.normal(k, (d_in, cfg.r)) / jnp.sqrt(cfg.r)).astype(dtype),
            "B": jnp.zeros((cfg.r,) + out_dims, dtype),
        }
    if not adapters:
        raise ValueError(f"no base weights matched LoRA targets {cfg.target_patterns}")
    return adapters


def _tree_get(tree, path):
    node = tree
    for p in path:
        node = node[int(p)] if isinstance(node, (list, tuple)) else node[p]
    return node


def _tree_set(tree, path, value):
    node = tree
    for p in path[:-1]:
        node = node[int(p)] if isinstance(node, (list, tuple)) else node[p]
    last = path[-1]
    if isinstance(node, (list, tuple)):
        node[int(last)] = value
    else:
        node[last] = value


def _copy_tree(tree):
    if isinstance(tree, dict):
        return {k: _copy_tree(v) for k, v in tree.items()}
    if isinstance(tree, list):
        return [_copy_tree(v) for v in tree]
    return tree  # leaves shared by reference — merge replaces, never mutates


def merge_lora(base_params: Any, adapters: dict, cfg: LoraConfig,
               dequant_dtype=jnp.float32) -> Any:
    """W' = W + (α/r)·A@B per adapted leaf (PEFT ``merge_and_unload``,
    sft_llama2.py:197-199). Quantized bases are dequantized dense first."""
    merged = _copy_tree(base_params)
    for path_str, ab in adapters.items():
        path = tuple(path_str.split("/"))
        w = maybe_dequant(_tree_get(base_params, path), dequant_dtype)
        b = ab["B"].reshape(ab["B"].shape[0], -1)  # [r, prod(out_dims)]
        delta = ((ab["A"] @ b) * cfg.scaling).reshape(w.shape)
        _tree_set(merged, path, (w + delta.astype(w.dtype)))
    return merged


def apply_adapters(base_params: Any, adapters: dict, cfg: LoraConfig,
                   tp_axis: Optional[str] = None,
                   base_specs: Any = None,
                   dropout_key: Optional[jax.Array] = None) -> Any:
    """Swap each adapted leaf for a :class:`LoraTensor` (factored form — no
    ``W + ΔW`` materialization; the models' matmul sites consume it).

    ``dropout_key`` (training only) arms ``cfg.dropout`` on every adapter
    branch, one derived key per adapted leaf (deterministic in the leaf's
    sorted position, so replicas agree bit-for-bit).

    Under tensor parallelism (``tp_axis`` + ``base_specs``), the adapter
    factor that is REPLICATED across the tensor axis (A for column-parallel
    targets, B for row-parallel) is wrapped in ``copy_to_tp_region`` so its
    backward psums the per-rank partial gradients — without it, per-rank
    adapter momenta/votes would silently diverge.
    """
    effective = _copy_tree(base_params)
    rate = cfg.dropout if dropout_key is not None else 0.0
    site_keys = {}
    if rate > 0.0:
        ordered = sorted(adapters)
        for k, p in zip(jax.random.split(dropout_key, len(ordered)), ordered):
            site_keys[p] = k
    for path_str, ab in adapters.items():
        path = tuple(path_str.split("/"))
        A, B = ab["A"], ab["B"]
        if tp_axis is not None:
            from distributed_lion_tpu.parallel.tensor_parallel import (
                copy_to_tp_region,
            )

            spec = _tree_get(base_specs, path)
            a_sharded = len(spec) > 0 and _dim_uses(spec, 0, tp_axis)
            b_sharded = any(_dim_uses(spec, i, tp_axis)
                            for i in range(1, len(spec)))
            # wrap the replicated factor ONLY when its partner is sharded:
            # with a tp-sharded partner the replicated factor's backward
            # carries just the local shard's contribution (psum needed); a
            # fully replicated target computes identical complete grads on
            # every rank already — a psum there would scale them by tp.
            if b_sharded and not a_sharded:
                A = copy_to_tp_region(A, tp_axis)
            if a_sharded and not b_sharded:
                B = copy_to_tp_region(B, tp_axis)
        base_leaf = _tree_get(base_params, path)
        _tree_set(effective, path, LoraTensor(
            base_leaf, A, B, cfg.scaling,
            rate, site_keys.get(path_str)))
    return effective


def _dim_uses(spec, i: int, axis: str) -> bool:
    if i >= len(spec):
        return False
    p = spec[i]
    return p == axis or (isinstance(p, (tuple, list)) and axis in p)


def lora_adapter_specs(adapters: dict, base_specs: Any, tp_axis: str) -> dict:
    """PartitionSpec tree for the adapter dict under tensor parallelism:
    ``A`` inherits the base's dim-0 sharding, ``B`` its output-dim sharding
    (its own leading rank-r dim replicated)."""
    from jax.sharding import PartitionSpec as P

    specs = {}
    for path_str, ab in adapters.items():
        spec = _tree_get(base_specs, tuple(path_str.split("/")))
        a0 = spec[0] if len(spec) > 0 else None
        specs[path_str] = {
            "A": P(a0 if a0 == tp_axis else None, None),
            "B": P(None, *spec[1:]) if len(spec) > 1 else P(None),
        }
    return specs


def lora_apply_fn(base_apply: Callable, base_params: Any, cfg: LoraConfig) -> Callable:
    """Wrap ``base_apply(params, tokens, **kw)`` into
    ``apply(adapters, tokens, **kw)`` over a CLOSED-OVER frozen base (the
    single-axis data-parallel path; for tensor parallelism pass the base as
    a live argument and call :func:`apply_adapters` directly).

    The LoraTensor swap happens inside the traced function, so the rank-r
    factors differentiate only w.r.t. the adapters; the base (captured as a
    constant, possibly quantized) gets no gradient.
    """

    def apply(adapters, tokens, *args, dropout_key=None, **kwargs):
        effective = apply_adapters(base_params, adapters, cfg,
                                   dropout_key=dropout_key)
        return base_apply(effective, tokens, *args, **kwargs)

    return apply
