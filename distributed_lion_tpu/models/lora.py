"""LoRA adapters over frozen (optionally quantized) base weights.

The reference applies PEFT LoRA on Llama-2's q/v projections with r=8, α=16,
dropout 0.05 (/root/reference/sft_llama2.py:44-51) and a wider target set for
DPO (q/v/k/out_proj + fc_in/fc_out/wte, dpo_llama2.py:192-207), then merges
adapters into the base on save (sft_llama2.py:193-199 ``merge_and_unload``).

Native design: adapters live in a SEPARATE flat dict keyed by the adapted
leaf's '/'-joined path, each entry {"A": [d_in, r], "B": [r, d_out]}. The
model apply stays untouched — :func:`lora_apply_fn` wraps any base ``apply``
by materializing ``W + (α/r)·A@B`` per adapted leaf before the call; XLA
fuses the rank-r update into the surrounding graph. Training differentiates ONLY
the adapter tree, so the optimizer (and its vote) sees just the LoRA params —
the base stays frozen/quantized.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

from distributed_lion_tpu.ops.quant import QuantizedTensor, maybe_dequant


@dataclasses.dataclass(frozen=True)
class LoraConfig:
    """sft_llama2.py:44-51 defaults: r=8, alpha=16, dropout 0.05 (dropout is
    applied at the data level here; adapter dropout is rarely load-bearing),
    targets q/v projections."""

    r: int = 8
    alpha: int = 16
    target_patterns: Sequence[str] = ("wq", "wv", "q_proj", "v_proj", "qkv")

    @property
    def scaling(self) -> float:
        return self.alpha / self.r


def _is_weight_leaf(x) -> bool:
    # 2-D projections and 3-D stacked projections (GPT-2's [d, 3, d] qkv)
    return isinstance(x, QuantizedTensor) or getattr(x, "ndim", 0) in (2, 3)


def _iter_paths(tree, prefix=()):
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield from _iter_paths(v, prefix + (str(k),))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            yield from _iter_paths(v, prefix + (str(i),))
    else:
        yield prefix, tree


def lora_init(key: jax.Array, base_params: Any, cfg: LoraConfig,
              dtype=jnp.float32) -> dict:
    """Build the adapter pytree: {'/'-joined path: {"A", "B"}} for every 2-D
    base leaf whose last path component matches a target pattern.

    A ~ N(0, 1/r), B = 0 (standard LoRA init: adapter starts as identity).
    """
    adapters = {}
    paths = [
        (path, leaf) for path, leaf in _iter_paths(base_params)
        if _is_weight_leaf(leaf) and any(re.fullmatch(p, path[-1]) for p in cfg.target_patterns)
    ]
    keys = jax.random.split(key, max(len(paths), 1))
    for k, (path, leaf) in zip(keys, paths):
        shape = tuple(int(s) for s in leaf.shape)
        d_in, out_dims = shape[0], shape[1:]  # n-D: B carries the trailing dims
        adapters["/".join(path)] = {
            "A": (jax.random.normal(k, (d_in, cfg.r)) / jnp.sqrt(cfg.r)).astype(dtype),
            "B": jnp.zeros((cfg.r,) + out_dims, dtype),
        }
    if not adapters:
        raise ValueError(f"no base weights matched LoRA targets {cfg.target_patterns}")
    return adapters


def _tree_get(tree, path):
    node = tree
    for p in path:
        node = node[int(p)] if isinstance(node, (list, tuple)) else node[p]
    return node


def _tree_set(tree, path, value):
    node = tree
    for p in path[:-1]:
        node = node[int(p)] if isinstance(node, (list, tuple)) else node[p]
    last = path[-1]
    if isinstance(node, (list, tuple)):
        node[int(last)] = value
    else:
        node[last] = value


def _copy_tree(tree):
    if isinstance(tree, dict):
        return {k: _copy_tree(v) for k, v in tree.items()}
    if isinstance(tree, list):
        return [_copy_tree(v) for v in tree]
    return tree  # leaves shared by reference — merge replaces, never mutates


def merge_lora(base_params: Any, adapters: dict, cfg: LoraConfig,
               dequant_dtype=jnp.float32) -> Any:
    """W' = W + (α/r)·A@B per adapted leaf (PEFT ``merge_and_unload``,
    sft_llama2.py:197-199). Quantized bases are dequantized dense first."""
    merged = _copy_tree(base_params)
    for path_str, ab in adapters.items():
        path = tuple(path_str.split("/"))
        w = maybe_dequant(_tree_get(base_params, path), dequant_dtype)
        b = ab["B"].reshape(ab["B"].shape[0], -1)  # [r, prod(out_dims)]
        delta = ((ab["A"] @ b) * cfg.scaling).reshape(w.shape)
        _tree_set(merged, path, (w + delta.astype(w.dtype)))
    return merged


def lora_apply_fn(base_apply: Callable, base_params: Any, cfg: LoraConfig) -> Callable:
    """Wrap ``base_apply(params, tokens, **kw)`` into
    ``apply(adapters, tokens, **kw)`` over the frozen base.

    The merged weight is formed inside the traced function, so the rank-r
    update differentiates only w.r.t. the adapters; the base (captured as a
    constant, possibly quantized) gets no gradient.
    """

    def apply(adapters, tokens, *args, **kwargs):
        effective = merge_lora(base_params, adapters, cfg,
                               dequant_dtype=jnp.bfloat16)
        return base_apply(effective, tokens, *args, **kwargs)

    return apply
