"""Llama-class decoder transformer in pure JAX.

The reference's SFT/DPO workloads run Llama-2-7B from HF hub
(/root/reference/sft_llama2.py:141-154, dpo_llama2.py:133-152); here the
architecture is our own implementation — RMSNorm, rotary position embeddings,
SwiGLU MLP, grouped-query attention, no biases, separate (untied) LM head —
covering Llama-2/-3-style configs. TPU-first like gpt2.py: bf16 compute with
f32 accumulation/softmax, static shapes, per-block rematerialization.

Frozen-base quantization (the reference's QLoRA 4-bit path) plugs in via
``ops.quant``: any weight leaf may be a QuantizedTensor and ``_matmul``
dequantizes on the fly.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp

from distributed_lion_tpu.ops.attention import attention as shared_attention
from distributed_lion_tpu.ops.quant import maybe_dequant
from distributed_lion_tpu.parallel.tensor_parallel import (
    copy_to_tp_region,
    reduce_from_tp_region,
)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    n_layer: int = 32
    n_head: int = 32
    n_kv_head: int = 32          # < n_head → grouped-query attention
    d_model: int = 4096
    d_ff: int = 11008
    n_ctx: int = 4096
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    attn_impl: str = "auto"  # ops.attention: auto | xla | xla_bf16 | flash | splash
    seq_impl: str = "ring"   # sequence-parallel attention: ring | ulysses
    remat: bool = True  # per-block jax.checkpoint; off when activations fit
    remat_policy: str = "full"  # 'full' | 'dots' (keep matmul outputs,
    # recompute elementwise — models/gpt2._remat_policy)
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_head == 0
        return self.d_model // self.n_head

    @staticmethod
    def tiny(**kw) -> "LlamaConfig":
        base = dict(vocab_size=256, n_layer=2, n_head=4, n_kv_head=2,
                    d_model=64, d_ff=128, n_ctx=128)
        base.update(kw)
        return LlamaConfig(**base)

    @staticmethod
    def small(**kw) -> "LlamaConfig":
        """A ~25M-param preset (at byte-level vocab): large enough for the
        auto comm defaults and meaningful CPU-mesh evidence runs (DPO
        step-rate rows when the TPU tunnel is down), small enough that a
        1-core host steps it in seconds."""
        base = dict(vocab_size=256, n_layer=8, n_head=8, n_kv_head=4,
                    d_model=512, d_ff=1376, n_ctx=1024)
        base.update(kw)
        return LlamaConfig(**base)

    @staticmethod
    def llama2_7b(**kw) -> "LlamaConfig":
        return LlamaConfig(**kw)

    @staticmethod
    def llama3_8b(**kw) -> "LlamaConfig":
        base = dict(vocab_size=128256, n_layer=32, n_head=32, n_kv_head=8,
                    d_model=4096, d_ff=14336, n_ctx=8192, rope_theta=500000.0)
        base.update(kw)
        return LlamaConfig(**base)

    @classmethod
    def named(cls, name: str, **kw) -> "LlamaConfig":
        """Resolve a CLI model name — single source for every entry point
        (run_clm / run_sft / run_dpo / run_generate)."""
        ctors = {"tiny": cls.tiny, "small": cls.small,
                 "llama2_7b": cls.llama2_7b, "llama3_8b": cls.llama3_8b}
        if name not in ctors:
            raise ValueError(
                f"unknown llama model_name {name!r}; pick one of "
                f"{sorted(ctors)}"
            )
        return ctors[name](**kw)


def _normal(key, shape, std, dtype):
    return (jax.random.normal(key, shape) * std).astype(dtype)


def llama_init(key: jax.Array, cfg: LlamaConfig) -> dict:
    d, dt = cfg.d_model, cfg.param_dtype
    hd, nh, nkv = cfg.head_dim, cfg.n_head, cfg.n_kv_head
    std = 0.02
    keys = iter(jax.random.split(key, 2 + 7 * cfg.n_layer))
    params: dict = {
        "wte": _normal(next(keys), (cfg.vocab_size, d), std, dt),
        "lm_head": _normal(next(keys), (d, cfg.vocab_size), std, dt),
        "ln_f": {"scale": jnp.ones((d,), dt)},
        "blocks": [],
    }
    for _ in range(cfg.n_layer):
        params["blocks"].append({
            "ln_attn": {"scale": jnp.ones((d,), dt)},
            "attn": {
                "wq": _normal(next(keys), (d, nh * hd), std, dt),
                "wk": _normal(next(keys), (d, nkv * hd), std, dt),
                "wv": _normal(next(keys), (d, nkv * hd), std, dt),
                "wo": _normal(next(keys), (nh * hd, d), std / math.sqrt(2 * cfg.n_layer), dt),
            },
            "ln_mlp": {"scale": jnp.ones((d,), dt)},
            "mlp": {
                "w_gate": _normal(next(keys), (d, cfg.d_ff), std, dt),
                "w_up": _normal(next(keys), (d, cfg.d_ff), std, dt),
                "w_down": _normal(next(keys), (cfg.d_ff, d), std / math.sqrt(2 * cfg.n_layer), dt),
            },
        })
    return params


def _rms_norm(x, p, eps):
    x32 = x.astype(jnp.float32)
    scale = jax.lax.rsqrt((x32 * x32).mean(-1, keepdims=True) + eps)
    return (x32 * scale * p["scale"].astype(jnp.float32)).astype(x.dtype)


def rope_angles(t: int, head_dim: int, theta: float, offset=0) -> tuple:
    """cos/sin tables [T, head_dim/2] (f32). ``offset`` may be a traced
    scalar (sequence-parallel shard start), so the arange is static-length
    with the offset added."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, jnp.float32) / head_dim))
    pos = jnp.arange(t, dtype=jnp.float32) + offset
    ang = jnp.outer(pos, inv_freq)
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """x: [B, H, T, hd]; rotate pairs (even, odd) — the interleaved
    formulation. cos/sin are [T, hd/2] (one position track shared by the
    batch) or [B, T, hd/2] (per-row position tracks: the paged decode tick
    and left-padded batched generation gather each row its own angles)."""
    x1, x2 = x[..., 0::2], x[..., 1::2]
    if cos.ndim == 3:
        c = cos[:, None, :, :].astype(x.dtype)
        s = sin[:, None, :, :].astype(x.dtype)
    else:
        c = cos[None, None, :, :].astype(x.dtype)
        s = sin[None, None, :, :].astype(x.dtype)
    out = jnp.stack([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)
    return out.reshape(x.shape)


def _matmul(x, w):
    # dense / QuantizedTensor / LoraTensor (factored x@W + s·(x@A)@B) —
    # models.lora.lora_matmul is the single dispatch point
    from distributed_lion_tpu.models.lora import lora_matmul

    return lora_matmul(x, w)


def _attention(x, p, cfg: LlamaConfig, cos, sin, tp_axis=None, seq_axis=None):
    """GQA attention; with ``tp_axis``, wq/wk/wv are column-parallel (this
    device holds n_head/tp query and n_kv_head/tp kv heads) and wo is
    row-parallel with a psum over the tensor axis (Megatron pattern). With
    ``seq_axis``, x is this device's contiguous token chunk and attention
    rings over the sequence axis (cos/sin already offset by the caller)."""
    B, T, D = x.shape
    tp = 1 if tp_axis is None else jax.lax.psum(1, tp_axis)
    if tp_axis is not None:
        # Megatron f: identity fwd, psum bwd (see parallel.tensor_parallel)
        x = copy_to_tp_region(x, tp_axis)
    H, KV, hd = cfg.n_head // tp, cfg.n_kv_head // tp, cfg.head_dim
    q = _matmul(x, p["wq"]).reshape(B, T, H, hd).transpose(0, 2, 1, 3)
    k = _matmul(x, p["wk"]).reshape(B, T, KV, hd).transpose(0, 2, 1, 3)
    v = _matmul(x, p["wv"]).reshape(B, T, KV, hd).transpose(0, 2, 1, 3)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    if KV != H:  # GQA: repeat kv heads
        rep = H // KV
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    if seq_axis is not None:
        from distributed_lion_tpu.parallel.ring_attention import (
            ring_attention,
            ulysses_attention,
        )

        seq_attn = (ulysses_attention if cfg.seq_impl == "ulysses"
                    else ring_attention)
        out = seq_attn(q, k, v, axis_name=seq_axis)
    else:
        out = shared_attention(q, k, v, causal=True, impl=cfg.attn_impl)
    out = out.transpose(0, 2, 1, 3).reshape(B, T, H * hd)
    out = _matmul(out, p["wo"])
    if tp_axis is not None:
        out = reduce_from_tp_region(out, tp_axis)
    return out


def _mlp(x, p, tp_axis=None):
    if tp_axis is not None:
        x = copy_to_tp_region(x, tp_axis)
    gate = jax.nn.silu(_matmul(x, p["w_gate"]))
    out = _matmul(gate * _matmul(x, p["w_up"]), p["w_down"])
    if tp_axis is not None:
        out = reduce_from_tp_region(out, tp_axis)
    return out


def _block(x, p, cfg: LlamaConfig, cos, sin, tp_axis=None, seq_axis=None):
    x = x + _attention(_rms_norm(x, p["ln_attn"], cfg.rms_eps), p["attn"], cfg,
                       cos, sin, tp_axis, seq_axis)
    x = x + _mlp(_rms_norm(x, p["ln_mlp"], cfg.rms_eps), p["mlp"], tp_axis)
    return x


def _block_remat_for(cfg):
    from distributed_lion_tpu.models.gpt2 import _remat_policy

    return partial(jax.checkpoint, static_argnums=(2, 5, 6),
                   policy=_remat_policy(cfg.remat_policy))(_block)


def llama_init_cache(cfg: LlamaConfig, batch: int, max_len: int) -> list:
    """Per-layer KV cache [B, n_kv_head, max_len, hd] — stored UN-repeated
    (GQA): repeat-to-query-heads happens at attend time, so cache memory
    scales with kv heads, the GQA payoff."""
    shape = (batch, cfg.n_kv_head, max_len, cfg.head_dim)
    return [
        {"k": jnp.zeros(shape, cfg.compute_dtype), "v": jnp.zeros(shape, cfg.compute_dtype)}
        for _ in range(cfg.n_layer)
    ]


def _decode_attention(x, p, cfg: LlamaConfig, c, pos, cos, sin, offset=None):
    """``offset`` (optional [B] int32): per-row left-pad width in a
    batched variable-length prompt — cache slots below it are masked out
    of that row's attention (cli/run_generate multi-prompt mode; the rope
    angles are already per-row-shifted by the caller)."""
    B, S, _ = x.shape
    H, KV, hd = cfg.n_head, cfg.n_kv_head, cfg.head_dim
    q = _matmul(x, p["wq"]).reshape(B, S, H, hd).transpose(0, 2, 1, 3)
    k = _matmul(x, p["wk"]).reshape(B, S, KV, hd).transpose(0, 2, 1, 3)
    v = _matmul(x, p["wv"]).reshape(B, S, KV, hd).transpose(0, 2, 1, 3)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    k_cache = jax.lax.dynamic_update_slice_in_dim(c["k"], k.astype(c["k"].dtype), pos, axis=2)
    v_cache = jax.lax.dynamic_update_slice_in_dim(c["v"], v.astype(c["v"].dtype), pos, axis=2)
    rep = H // KV
    k_full = jnp.repeat(k_cache, rep, axis=1) if rep > 1 else k_cache
    v_full = jnp.repeat(v_cache, rep, axis=1) if rep > 1 else v_cache
    T = k_cache.shape[2]
    scores = jnp.einsum("bhsd,bhtd->bhst", q, k_full,
                        preferred_element_type=jnp.float32) / math.sqrt(hd)
    valid = jnp.arange(T)[None, :] <= (pos + jnp.arange(S))[:, None]
    if offset is None:
        scores = jnp.where(valid[None, None], scores, -1e30)
    else:
        row_valid = valid[None] & (jnp.arange(T)[None, None, :]
                                   >= offset[:, None, None])
        scores = jnp.where(row_valid[:, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
    out = jnp.einsum("bhst,bhtd->bhsd", probs, v_full,
                     preferred_element_type=jnp.float32).astype(x.dtype)
    out = out.transpose(0, 2, 1, 3).reshape(B, S, H * hd)
    return _matmul(out, p["wo"]), {"k": k_cache, "v": v_cache}


def _head_logits(x, params):
    return jnp.einsum("btd,dv->btv", x,
                      maybe_dequant(params["lm_head"], x.dtype).astype(x.dtype),
                      preferred_element_type=jnp.float32)


def llama_decode(params: dict, tokens: jnp.ndarray, cfg: LlamaConfig, cache: list,
                 pos, offset=None):
    """Incremental forward with rotary offset: prefill with the prompt at
    pos=0, then one token at a time. Matches ``llama_apply`` logits
    position-for-position (tests/test_generate.py). ``offset`` [B]: per-row
    left-pad width for batched variable-length prompts — row b's tokens at
    cache slot t get rotary position ``t - offset[b]`` and never attend
    below slot ``offset[b]`` (solo semantics, shifted into the batch)."""
    B, S = tokens.shape
    from distributed_lion_tpu.models.lora import lora_embed

    x = lora_embed(params["wte"], tokens, cfg.compute_dtype)
    # rope tables at the absolute positions of these S tokens: build a
    # max-length table once and slice at pos (pos is traced under jit)
    cos_all, sin_all = rope_angles(cache[0]["k"].shape[2], cfg.head_dim, cfg.rope_theta)
    if offset is None:
        cos = jax.lax.dynamic_slice_in_dim(cos_all, pos, S, axis=0)
        sin = jax.lax.dynamic_slice_in_dim(sin_all, pos, S, axis=0)
    else:
        pos_ids = jnp.clip(pos + jnp.arange(S)[None, :] - offset[:, None],
                           0, cos_all.shape[0] - 1)
        cos, sin = cos_all[pos_ids], sin_all[pos_ids]  # [B, S, hd/2]
    new_cache = []
    for p, c in zip(params["blocks"], cache):
        a, c = _decode_attention(_rms_norm(x, p["ln_attn"], cfg.rms_eps), p["attn"],
                                 cfg, c, pos, cos, sin, offset)
        x = x + a
        x = x + _mlp(_rms_norm(x, p["ln_mlp"], cfg.rms_eps), p["mlp"])
        new_cache.append(c)
    x = _rms_norm(x, params["ln_f"], cfg.rms_eps)
    return _head_logits(x, params), new_cache


def _paged_attention_block(x, p, cfg: LlamaConfig, c, tables, pos, cos, sin,
                           valid, tp_axis=None):
    """The paged twin of :func:`_decode_attention` (serve/kv_cache layout):
    scatter the roped new k (and v) into block-table pages, attend over
    the gathered history via ops.attention.paged_decode_attention — the
    same masked-softmax chain, so greedy decode is bit-identical to the
    dense cache whenever the attended length matches. With ``tp_axis``
    (the TP serving engine) wq/wk/wv are column-parallel — this rank holds
    n_head/tp query and n_kv_head/tp kv heads and the page pool's matching
    kv-head shard — the scatter/gather/attend chain is shard-local (GQA
    repeat preserved: H/tp over KV/tp), and wo is row-parallel with one
    psum over the tensor axis."""
    from distributed_lion_tpu.ops.attention import (
        paged_decode_attention,
        paged_scatter_kv,
    )

    B, S, _ = x.shape
    tp = 1 if tp_axis is None else jax.lax.psum(1, tp_axis)
    H, KV, hd = cfg.n_head // tp, cfg.n_kv_head // tp, cfg.head_dim
    q = _matmul(x, p["wq"]).reshape(B, S, H, hd).transpose(0, 2, 1, 3)
    k = _matmul(x, p["wk"]).reshape(B, S, KV, hd).transpose(0, 2, 1, 3)
    v = _matmul(x, p["wv"]).reshape(B, S, KV, hd)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin).transpose(0, 2, 1, 3)  # back to [B, S, KV, hd]
    k_pages = paged_scatter_kv(c["k"], tables, pos, k.astype(c["k"].dtype), valid)
    v_pages = paged_scatter_kv(c["v"], tables, pos, v.astype(c["v"].dtype), valid)
    out = paged_decode_attention(q, k_pages, v_pages, tables, pos)
    out = out.transpose(0, 2, 1, 3).reshape(B, S, H * hd)
    out = _matmul(out, p["wo"])
    if tp_axis is not None:
        out = reduce_from_tp_region(out, tp_axis)
    return out, {"k": k_pages, "v": v_pages}


def llama_decode_paged(params: dict, tokens: jnp.ndarray, cfg: LlamaConfig,
                       pages: list, tables: jnp.ndarray, pos: jnp.ndarray,
                       valid=None, tp_axis=None):
    """Block-table decode (the serving engine's model hook): row b's
    ``tokens`` [B, S] sit at positions ``pos[b] .. pos[b]+S-1`` of its own
    sequence (rotary angles gathered per row); ``pages`` is the per-layer
    {"k","v"} pool of [num_blocks, block_size, n_kv_head, hd] (GQA: pages
    store kv heads un-repeated, like the dense cache). Returns (logits
    [B, S, vocab] f32, updated pages). One jitted program serves both the
    bucketed prefill (S = padded prompt, ``valid`` masks the tail) and the
    rolling decode tick (S = 1, pos = per-slot lengths). With ``tp_axis``
    (inside shard_map — the TP serving engine, ISSUE 13) attention/MLP
    weights and the pool's kv-head axis are pre-sharded per
    ``parallel.tensor_parallel.llama_param_specs``; wte/lm_head stay
    replicated, so logits are identical on every tensor rank."""
    B, S = tokens.shape
    from distributed_lion_tpu.models.lora import lora_embed

    x = lora_embed(params["wte"], tokens, cfg.compute_dtype)
    max_pos = tables.shape[1] * pages[0]["k"].shape[1]
    cos_all, sin_all = rope_angles(max_pos, cfg.head_dim, cfg.rope_theta)
    pos_ids = jnp.clip(pos[:, None] + jnp.arange(S)[None, :], 0, max_pos - 1)
    cos, sin = cos_all[pos_ids], sin_all[pos_ids]  # [B, S, hd/2]
    new_pages = []
    for p, c in zip(params["blocks"], pages):
        a, c = _paged_attention_block(_rms_norm(x, p["ln_attn"], cfg.rms_eps),
                                      p["attn"], cfg, c, tables, pos, cos, sin,
                                      valid, tp_axis)
        x = x + a
        x = x + _mlp(_rms_norm(x, p["ln_mlp"], cfg.rms_eps), p["mlp"], tp_axis)
        new_pages.append(c)
    x = _rms_norm(x, params["ln_f"], cfg.rms_eps)
    return _head_logits(x, params), new_pages


def llama_hidden(
    params: dict,
    tokens: jnp.ndarray,
    cfg: LlamaConfig,
    *,
    tp_axis: Optional[str] = None,
    seq_axis: Optional[str] = None,
) -> jnp.ndarray:
    """Backbone forward: tokens [B, T] → final hidden [B, T, d] after the
    last RMSNorm. The lm_head is applied by :func:`llama_apply`, or streamed
    chunk-wise by ops/xent (vocab 32k/128k logits never materialized)."""
    B, T = tokens.shape
    if seq_axis is None:
        if T > cfg.n_ctx:
            raise ValueError(f"sequence length {T} exceeds n_ctx {cfg.n_ctx}")
        offset = 0
    else:
        offset = jax.lax.axis_index(seq_axis) * T
    from distributed_lion_tpu.models.lora import lora_embed

    x = lora_embed(params["wte"], tokens, cfg.compute_dtype)
    cos, sin = rope_angles(T, cfg.head_dim, cfg.rope_theta, offset=offset)
    block = _block_remat_for(cfg) if cfg.remat else _block
    for p in params["blocks"]:
        x = block(x, p, cfg, cos, sin, tp_axis, seq_axis)
    return _rms_norm(x, params["ln_f"], cfg.rms_eps)


def llama_apply(
    params: dict,
    tokens: jnp.ndarray,
    cfg: LlamaConfig,
    *,
    dropout_key: Optional[jax.Array] = None,  # parity arg; Llama uses none
    tp_axis: Optional[str] = None,
    seq_axis: Optional[str] = None,
) -> jnp.ndarray:
    """int32 tokens [B, T] → f32 logits [B, T, vocab].

    With ``tp_axis`` (inside shard_map), weights are expected pre-sharded per
    ``parallel.tensor_parallel.llama_param_specs``. With ``seq_axis``,
    ``tokens`` is this device's contiguous chunk: rotary angles are offset by
    the shard index and attention rings over the axis.
    """
    x = llama_hidden(params, tokens, cfg, tp_axis=tp_axis, seq_axis=seq_axis)
    return jnp.einsum(
        "btd,dv->btv", x, maybe_dequant(params["lm_head"], x.dtype).astype(x.dtype),
        preferred_element_type=jnp.float32,
    )
