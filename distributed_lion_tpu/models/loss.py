"""Causal-LM loss and eval metrics.

Parity targets: HF's shift-by-one CLM cross entropy (the loss the reference's
run_clm optimizes via AutoModelForCausalLM) and its eval metrics — argmax
token accuracy computed on shifted labels (/root/reference/run_clm.py:562-577)
and perplexity = exp(eval_loss) (:630-636, computed in train.eval).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def clm_loss_and_metrics(
    logits: jnp.ndarray,
    tokens: jnp.ndarray,
    loss_mask: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, dict]:
    """Next-token cross entropy with shift-by-one labels.

    Args:
        logits: [B, T, V] float32.
        tokens: [B, T] int32 — inputs; labels are ``tokens[:, 1:]``.
        loss_mask: optional [B, T] bool/float; positions where the LABEL
            (i.e. mask index 1..T-1) should count. Used by SFT completion-only
            training and padding exclusion.

    Returns:
        (mean_loss, {"loss", "accuracy", "n_tokens"}) — accuracy is argmax
        token accuracy on the shifted labels (run_clm.py:569-577 semantics).
    """
    shift_logits = logits[:, :-1]
    shift_labels = tokens[:, 1:]
    if loss_mask is None:
        mask = jnp.ones(shift_labels.shape, jnp.float32)
    else:
        mask = loss_mask[:, 1:].astype(jnp.float32)

    logp = jax.nn.log_softmax(shift_logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, shift_labels[..., None], axis=-1)[..., 0]
    n = jnp.maximum(mask.sum(), 1.0)
    loss = (nll * mask).sum() / n

    pred = shift_logits.argmax(-1)
    acc = ((pred == shift_labels) * mask).sum() / n
    return loss, {"loss": loss, "accuracy": acc, "n_tokens": mask.sum()}
