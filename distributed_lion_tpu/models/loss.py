"""Causal-LM loss and eval metrics.

Parity targets: HF's shift-by-one CLM cross entropy (the loss the reference's
run_clm optimizes via AutoModelForCausalLM) and its eval metrics — argmax
token accuracy computed on shifted labels (/root/reference/run_clm.py:562-577)
and perplexity = exp(eval_loss) (:630-636, computed in train.eval).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def clm_loss_and_metrics(
    logits: jnp.ndarray,
    tokens: jnp.ndarray,
    loss_mask: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, dict]:
    """Next-token cross entropy with shift-by-one labels.

    Args:
        logits: [B, T, V] float32.
        tokens: [B, T] int32 — inputs; labels are ``tokens[:, 1:]``.
        loss_mask: optional [B, T] bool/float; positions where the LABEL
            (i.e. mask index 1..T-1) should count. Used by SFT completion-only
            training and padding exclusion.

    Returns:
        (mean_loss, {"loss", "accuracy", "n_tokens"}) — accuracy is argmax
        token accuracy on the shifted labels (run_clm.py:569-577 semantics).
    """
    shift_logits = logits[:, :-1]
    shift_labels = tokens[:, 1:]
    if loss_mask is None:
        mask = jnp.ones(shift_labels.shape, jnp.float32)
    else:
        mask = loss_mask[:, 1:].astype(jnp.float32)

    logp = jax.nn.log_softmax(shift_logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, shift_labels[..., None], axis=-1)[..., 0]
    n = jnp.maximum(mask.sum(), 1.0)
    loss = (nll * mask).sum() / n

    pred = shift_logits.argmax(-1)
    acc = ((pred == shift_labels) * mask).sum() / n
    return loss, {"loss": loss, "accuracy": acc, "n_tokens": mask.sum()}


def clm_loss_sharded_rows(
    logits: jnp.ndarray,
    tokens: jnp.ndarray,
    axis_name: str,
    aux: jnp.ndarray | None = None,
    aux_weight: float = 0.01,
) -> tuple[jnp.ndarray, dict]:
    """CLM loss when batch ROWS are sharded over ``axis_name`` but params are
    replicated along it (expert parallelism's token sharding — the 'expert'
    axis doubles as extra data parallelism for the dense layers).

    Returns ``local_row_nll_sum / global_token_count`` (+ the MoE aux loss,
    averaged over shards) so that a ``psum`` of its GRADIENT over
    ``axis_name`` equals the full-batch gradient — the train loop reduces
    replicated-leaf grads exactly that way (train/loop.py). Expert-SHARDED
    leaves need no such reduction: every path from them to any shard's loss
    crosses the dispatch/return all_to_all, whose transpose routes the
    cross-shard cotangents home. Metrics are globally reduced.
    """
    shift_logits = logits[:, :-1]
    shift_labels = tokens[:, 1:]
    logp = jax.nn.log_softmax(shift_logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, shift_labels[..., None], axis=-1)[..., 0]
    n_local = jnp.float32(nll.size)
    shards = jax.lax.psum(1, axis_name)
    n_global = jnp.maximum(jax.lax.psum(n_local, axis_name), 1.0)
    ce_local = nll.sum() / n_global
    loss_local = ce_local
    pred = shift_logits.argmax(-1)
    acc = jax.lax.psum((pred == shift_labels).sum().astype(jnp.float32),
                       axis_name) / n_global
    metrics = {
        "loss": jax.lax.psum(ce_local, axis_name),  # CE only, aux reported apart
        "accuracy": acc,
        "n_tokens": n_global / shards,  # per-shard average (logging parity)
    }
    if aux is not None:
        loss_local = loss_local + aux_weight * aux / shards
        metrics["aux_loss"] = jax.lax.psum(aux / shards, axis_name)
    return loss_local, metrics


def shift_in_next_shard(
    x: jnp.ndarray, axis_name: str
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """The seq-parallel shard-boundary protocol, in one place: shift a
    [B, T_local] array left by one column, filling the last column with the
    NEXT shard's first column via a single [B, 1] ``ppermute``. Returns
    ``(shifted, is_last_shard)`` — the final shard's fill is garbage (wraps
    to shard 0) and must be masked by the caller using the flag. Shared by
    :func:`clm_loss_seq_parallel` and train/dpo's seq-parallel logprob so
    the perm direction and boundary masking can't drift apart."""
    S = jax.lax.psum(1, axis_name)
    sidx = jax.lax.axis_index(axis_name)
    nxt = jax.lax.ppermute(
        x[:, :1], axis_name, [(i, (i - 1) % S) for i in range(S)]
    )
    return jnp.concatenate([x[:, 1:], nxt], axis=1), sidx == S - 1


def shifted_labels_and_mask(
    tokens: jnp.ndarray, axis_name: str
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """:func:`shift_in_next_shard` plus the boundary MASK — the other half
    of the shard-boundary protocol (the final shard's last position has no
    next token and must not count), in one place so no caller hand-rolls
    it. Returns ``(labels [B, T_local], mask [B, T_local] f32)``."""
    labels, is_last = shift_in_next_shard(tokens, axis_name)
    mask = jnp.ones(labels.shape, jnp.float32)
    mask = mask.at[:, -1].set(jnp.where(is_last, 0.0, 1.0))
    return labels, mask


def clm_loss_seq_parallel(
    logits: jnp.ndarray,
    tokens: jnp.ndarray,
    axis_name: str,
) -> tuple[jnp.ndarray, dict]:
    """CLM loss under sequence parallelism (inside shard_map).

    Each device holds a contiguous chunk ``tokens`` [B, T_local] of the full
    sequence and that chunk's ``logits``. The label of a chunk's LAST
    position is the NEXT chunk's first token — fetched with one tiny
    ``ppermute`` ([B, 1] per hop) — so no token's loss signal is dropped at
    shard boundaries; only the final position of the final chunk (which has
    no next token, exactly like the last position in the non-parallel loss)
    is masked.

    Returns a loss whose value is ``local_nll_sum / global_token_count`` —
    psum of its GRADIENT over ``axis_name`` equals the full-sequence
    gradient, which is how the train loop reduces it. The reported metrics
    are globally reduced (identical on every shard).
    """
    S = jax.lax.psum(1, axis_name)
    # my last position's label = next shard's first token (shard i gets it
    # from shard i+1; shard S-1 receives garbage from shard 0 and masks it)
    labels, mask = shifted_labels_and_mask(tokens, axis_name)  # [B, T_local]

    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    n_global = jnp.maximum(jax.lax.psum(mask.sum(), axis_name), 1.0)
    loss_local = (nll * mask).sum() / n_global  # grad psums to the full grad

    pred = logits.argmax(-1)
    acc = jax.lax.psum(((pred == labels) * mask).sum(), axis_name) / n_global
    loss_global = jax.lax.psum(loss_local, axis_name)
    return loss_local, {
        "loss": loss_global,
        "accuracy": acc,
        "n_tokens": n_global / jnp.maximum(S, 1),  # per-shard average, matches
        # the replicated path's per-device count convention for logging
    }


def pipelined_seq_parallel_loss(head_partials, acc, tokens, seq_axis: str,
                                pipe_axis: str):
    """The sp × pp loss scaffold, shared by gpt2_pipe and llama_pipe so the
    trickiest contracts live in ONE place:

    - collective hoisting: XLA aborts on collectives under conditional
      control flow, so the boundary-label ``ppermute`` (tokens-only — free
      to hoist) and every psum run OUT here while the ``lax.cond`` over
      pipeline stages wraps only ``head_partials(acc, labels, mask) ->
      (masked nll sum, masked correct sum)``, which must be
      collective-free (ops/xent.masked_local_nll);
    - grad contract: the returned loss differentiates as
      ``local_nll_sum / global_token_count`` per (seq, pipe) rank — the
      train loop psums grads over the seq axis and (for replicated leaves)
      the pipe axis, completing the sum.

    Returns ``(loss, metrics)`` in the Trainer's contract; metrics are
    globally reduced, ``n_tokens`` is the per-seq-shard average (the seq
    loss's logging convention, uniform across pipe)."""
    labels, mask = shifted_labels_and_mask(tokens, seq_axis)
    S = jax.lax.psum(1, seq_axis)
    n_global = jnp.maximum(jax.lax.psum(mask.sum(), seq_axis), 1.0)

    stage = jax.lax.axis_index(pipe_axis)
    last = jax.lax.psum(1, pipe_axis) - 1
    nll_sum, correct_sum = jax.lax.cond(
        stage == last,
        lambda a: head_partials(a, labels, mask),
        lambda a: (jnp.float32(0), jnp.float32(0)),
        acc,
    )
    loss_local = nll_sum / n_global
    loss = jax.lax.psum(loss_local, pipe_axis)
    metrics = {
        "loss": jax.lax.psum(jax.lax.psum(loss_local, seq_axis), pipe_axis),
        "accuracy": jax.lax.psum(
            jax.lax.psum(correct_sum, seq_axis), pipe_axis) / n_global,
        "n_tokens": n_global / S,
    }
    return loss, metrics
