"""Autoregressive generation: KV-cache prefill + decode with sampling.

Net-new capability vs the reference (which has no inference path anywhere —
its three scripts only train). TPU-first design: the whole generation runs
as ONE jitted ``lax.scan`` over decode steps — static shapes (fixed-size KV
cache written at a position index), no host round-trip per token.

Sampling: greedy (``temperature=0``), temperature, top-k, and top-p
(nucleus), with explicit PRNG keys. EOS handling: once a row emits ``eos_id`` every later position is
padded with ``pad_id`` (the sampled token is masked), so finished rows cost
no extra host logic.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def sample_logits(logits: jnp.ndarray, key, temperature: float = 1.0,
                  top_k: Optional[int] = None,
                  top_p: Optional[float] = None) -> jnp.ndarray:
    """[B, V] logits → [B] sampled token ids.

    ``top_p`` is nucleus sampling (HF ``generate`` convention): keep the
    smallest descending-probability prefix whose mass reaches ``top_p``
    (the EXCLUSIVE-cumulative test below always keeps the top token, so
    top_p → 0 degrades to greedy, not to an empty support). Composes with
    top_k (filter intersection) and temperature (applied first, as HF's
    logits-processor ordering does)."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    return jax.random.categorical(
        key, filter_logits(logits, temperature, top_k, top_p), axis=-1)


def filter_logits(logits: jnp.ndarray, temperature: float = 1.0,
                  top_k: Optional[int] = None,
                  top_p: Optional[float] = None) -> jnp.ndarray:
    """The warper half of :func:`sample_logits` without the draw:
    temperature + top-k/top-p filtering, filtered-out entries at ``-inf``.
    Split out so the serving engine (serve/engine.py) can draw with
    PER-ROW keys — one key per request, making a request's samples
    independent of which batch slot it rides in."""
    logits = logits / temperature
    if top_k is not None or top_p is not None:
        # ONE descending argsort serves both filters (each runs inside the
        # jitted per-token decode step — no duplicated O(B·V log V) sort)
        order = jnp.argsort(-logits, axis=-1)
        sorted_logits = jnp.take_along_axis(logits, order, axis=-1)
        v = logits.shape[-1]
        keep_sorted = jnp.ones(sorted_logits.shape, bool)
        if top_k is not None:
            keep_sorted &= jnp.arange(v)[None, :] < top_k
        if top_p is not None:
            # HF warper ordering: nucleus mass over the top-k-FILTERED
            # distribution; exclusive cumulative mass BEFORE each token
            probs = jax.nn.softmax(
                jnp.where(keep_sorted, sorted_logits, -jnp.inf), axis=-1)
            before = jnp.cumsum(probs, axis=-1) - probs
            keep_sorted &= before < top_p
        # the best token ALWAYS survives — top_p <= 0 (or top_k <= 0)
        # degrades to greedy instead of an all-masked row that categorical
        # would silently turn into token id 0
        keep_sorted = keep_sorted.at[:, 0].set(True)
        keep = jnp.zeros_like(keep_sorted).at[
            jnp.arange(logits.shape[0])[:, None], order].set(keep_sorted)
        logits = jnp.where(keep, logits, -jnp.inf)
    return logits


@partial(jax.jit, static_argnames=("decode_fn", "init_cache_fn", "max_new_tokens",
                                   "temperature", "top_k", "top_p", "eos_id",
                                   "pad_id", "max_len"))
def generate(decode_fn, init_cache_fn, params, prompt: jnp.ndarray,
             max_new_tokens: int, *, key=None, temperature: float = 0.0,
             top_k: Optional[int] = None, top_p: Optional[float] = None,
             eos_id: Optional[int] = None,
             pad_id: int = 0, max_len: Optional[int] = None,
             prompt_lens: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Generate ``max_new_tokens`` continuations for ``prompt`` [B, T].

    ``decode_fn(params, tokens, cache, pos[, offset]) -> (logits, cache)``
    and ``init_cache_fn(batch, max_len) -> cache`` come from the model
    module (``gpt2_decode``/``gpt2_init_cache`` or the llama pair, partially
    applied over their config). Returns [B, max_new_tokens] token ids.

    ``prompt_lens`` [B] enables batched variable-length prompts: each row
    is LEFT-padded to T (pad tokens first, real tokens right-aligned so
    row b's last prompt token sits at slot T-1 for every row), and the
    per-row pad width ``T - prompt_lens`` flows to the model as the decode
    offset — pad slots are masked out of attention and position ids count
    from the first REAL token, so each row ATTENDS with solo semantics
    (greedy outputs match solo runs exactly; sampled draws still share
    one PRNG stream over the batch — per-request streams are the serving
    engine's job, serve/engine.py). MoE checkpoints compose (ISSUE 15 —
    the PR 9 refusal lifted): pad lanes are valid-masked out of expert
    routing and inference routing is no-drop per-token
    (models/gpt2._decode_mlp), so batched greedy MoE output equals solo
    runs too (tests/test_moe_serve.py pins it).
    """
    B, T = prompt.shape
    total = max_len or (T + max_new_tokens)
    cache = init_cache_fn(B, total)
    key = key if key is not None else jax.random.key(0)
    offset = None if prompt_lens is None else (T - prompt_lens).astype(jnp.int32)

    def dec(params, toks, cache, pos):
        if offset is None:
            return decode_fn(params, toks, cache, pos)
        return decode_fn(params, toks, cache, pos, offset)

    logits, cache = dec(params, prompt, cache, 0)  # prefill
    tok = sample_logits(logits[:, -1], key, temperature, top_k, top_p)
    finished = jnp.zeros((B,), bool) if eos_id is None else tok == eos_id

    def step(carry, i):
        tok, cache, finished, key = carry
        key, sub = jax.random.split(key)
        logits, cache = dec(params, tok[:, None], cache, T + i)
        nxt = sample_logits(logits[:, -1], sub, temperature, top_k, top_p)
        if eos_id is not None:
            nxt = jnp.where(finished, pad_id, nxt)
            finished = finished | (nxt == eos_id)
        return (nxt, cache, finished, key), tok

    (last, _, _, _), toks = lax.scan(
        step, (tok, cache, finished, key), jnp.arange(max_new_tokens - 1)
    )
    return jnp.concatenate([toks.T, last[:, None]], axis=1)  # [B, max_new]
