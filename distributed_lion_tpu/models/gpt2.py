"""GPT-2-class decoder transformer in pure JAX.

The reference's pretraining workload is GPT-2 124M from HF hub
(/root/reference/run_clm.py:425-444, README.md:21-23); here the model is our
own implementation — pre-LN residual decoder with learned positional
embeddings, GELU MLP, tied input/output embedding — designed for the MXU:

- all matmuls batched and expressed as einsums XLA tiles onto the systolic
  array; compute in bf16 with f32 accumulation (``preferred_element_type``);
- static shapes everywhere (fixed block size, as the reference's fixed-block
  ``group_texts`` packing guarantees, run_clm.py:509-522);
- params as a plain nested dict pytree → optimizer/sharding/checkpoint code
  stays generic.

124M default config matches GPT-2 small: vocab 50257, 12 layers, 12 heads,
d_model 768, context 1024.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

from distributed_lion_tpu.ops.attention import attention as shared_attention
from distributed_lion_tpu.parallel.tensor_parallel import (
    copy_to_tp_region,
    reduce_from_tp_region,
)


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    n_ctx: int = 1024
    dropout: float = 0.0
    attn_impl: str = "auto"  # ops.attention: auto | xla | xla_bf16 | flash | splash
    flash_block_q: int = 0   # flash kernel tile overrides (0 = defaults);
    flash_block_kv: int = 0  # see ops.attention.attention_flash
    flash_block_q_bwd: int = 0   # backward-pass tile overrides (0 = inherit
    flash_block_kv_bwd: int = 0  # the fwd tiles); spec impl@FWD@BWD
    seq_impl: str = "ring"   # sequence-parallel attention: 'ring' (k/v
    # blocks rotate over the seq axis — O(T/S) memory, any head count) or
    # 'ulysses' (all_to_all to head sharding — needs n_head % sp == 0,
    # two collective hops but full-T local attention)
    remat: bool = True  # rematerialize blocks (HBM for FLOPs); turn off when
                        # activations fit — backward skips the fwd recompute
    remat_policy: str = "full"  # what the per-block checkpoint SAVES:
    # 'full' (nothing — recompute everything), 'dots' (keep matmul outputs,
    # recompute elementwise/softmax — the usual best trade on TPU: matmuls
    # are the expensive recompute, elementwise is free next to HBM)
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    moe_experts: int = 0  # > 0: Switch-MoE FFN (parallel/expert.py) replaces
                          # the dense MLP in every ``moe_every``-th block;
                          # net-new vs the reference (data-parallel only)
    moe_every: int = 2    # MoE in blocks with index % moe_every == moe_every-1
    moe_capacity_factor: float = 1.25
    vocab_pad_multiple: int = 0  # > 0: round the EMBEDDING TABLE rows up to
    # a multiple (wte becomes [padded_vocab, d]) so the tied-head matmul and
    # the chunked-CE slices land on MXU-aligned tile boundaries — GPT-2's
    # 50257 is ragged (Llama vocabs are already 128-multiples). A pure
    # LAYOUT choice, not a semantics change: logits are sliced back to
    # vocab_size in gpt2_apply and the chunked loss masks the pad columns,
    # so loss/generation are exact and the pad rows get zero loss gradient.
    # (Under vote-Lion the tie→−1 rule still walks zero-gradient pad rows;
    # they stay out of every consumer and hf_export slices them off.)

    def __post_init__(self):
        if self.moe_experts > 0 and self.moe_every < 1:
            raise ValueError(
                f"moe_every must be >= 1 when moe_experts is set, got "
                f"{self.moe_every}"
            )
        if self.vocab_pad_multiple < 0:
            raise ValueError(
                f"vocab_pad_multiple must be >= 0, got {self.vocab_pad_multiple}"
            )

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_head == 0
        return self.d_model // self.n_head

    @property
    def padded_vocab(self) -> int:
        """Embedding-table rows: ``vocab_size`` rounded up to
        ``vocab_pad_multiple`` (== ``vocab_size`` when padding is off)."""
        m = self.vocab_pad_multiple
        if m <= 0:
            return self.vocab_size
        return -(-self.vocab_size // m) * m

    @staticmethod
    def tiny(**kw) -> "GPT2Config":
        """A test-sized config (for unit tests and the dryrun path)."""
        base = dict(vocab_size=256, n_layer=2, n_head=4, d_model=64, n_ctx=128)
        base.update(kw)
        return GPT2Config(**base)

    @staticmethod
    def small(**kw) -> "GPT2Config":
        """The reduced evidence-scale preset (~12.7M params at a 16k
        vocab): the smallest architecture the ≥10M auto comm defaults
        apply to — shared by the reduced CPU parity legs
        (scripts/loss_parity.py --reduced) and the reduced convergence
        run, so the two tunnel-dead fallbacks evidence the same model."""
        base = dict(vocab_size=16384, n_layer=6, n_head=5, d_model=320,
                    n_ctx=256)
        base.update(kw)
        return GPT2Config(**base)

    @staticmethod
    def gpt2_124m(**kw) -> "GPT2Config":
        return GPT2Config(**kw)


def _normal(key, shape, std, dtype):
    return (jax.random.normal(key, shape) * std).astype(dtype)


def pad_wte(wte: jnp.ndarray, cfg: "GPT2Config") -> jnp.ndarray:
    """Append the zero MXU-alignment rows of ``cfg.vocab_pad_multiple`` to a
    true-vocab embedding table (no-op when padding is off). The single
    source of the pad layout — used by :func:`gpt2_init` and by CLI
    checkpoint import, so fresh inits and imported tables can't drift."""
    extra = cfg.padded_vocab - wte.shape[0]
    if extra <= 0:
        return wte
    return jnp.concatenate(
        [wte, jnp.zeros((extra, wte.shape[1]), wte.dtype)]
    )


def is_moe_block(cfg: GPT2Config, i: int) -> bool:
    return cfg.moe_experts > 0 and i % cfg.moe_every == cfg.moe_every - 1


def gpt2_init(key: jax.Array, cfg: GPT2Config) -> dict:
    """Initialize parameters (GPT-2 init: N(0, 0.02), residual projections
    scaled by 1/sqrt(2*n_layer) as in the original OpenAI scheme). With
    ``cfg.moe_experts``, every ``moe_every``-th block carries a Switch-MoE
    FFN (``"moe"`` entry, parallel/expert.moe_init) instead of the dense
    ``"mlp"``."""
    d, dt = cfg.d_model, cfg.param_dtype
    std = 0.02
    resid_std = std / math.sqrt(2 * cfg.n_layer)
    keys = iter(jax.random.split(key, 4 + 7 * cfg.n_layer))

    # pad rows are ZEROS appended after the draw, so the true-vocab rows are
    # bit-identical to the unpadded init under the same key (pinned by
    # tests/test_vocab_pad.py) and exports can slice the pad back off
    params: dict = {
        "wte": pad_wte(_normal(next(keys), (cfg.vocab_size, d), std, dt), cfg),
        "wpe": _normal(next(keys), (cfg.n_ctx, d), std, dt),
        "ln_f": {"scale": jnp.ones((d,), dt), "bias": jnp.zeros((d,), dt)},
        "blocks": [],
    }
    for i in range(cfg.n_layer):
        block = {
            "ln_1": {"scale": jnp.ones((d,), dt), "bias": jnp.zeros((d,), dt)},
            "attn": {
                # [d, 3, d]: q/k/v stacked on axis 1 so tensor parallelism
                # shards the last (head) dim without cutting across q|k|v
                "qkv": _normal(next(keys), (d, 3, d), std, dt),
                "qkv_b": jnp.zeros((3, d), dt),
                "proj": _normal(next(keys), (d, d), resid_std, dt),
                "proj_b": jnp.zeros((d,), dt),
            },
            "ln_2": {"scale": jnp.ones((d,), dt), "bias": jnp.zeros((d,), dt)},
        }
        if is_moe_block(cfg, i):
            from distributed_lion_tpu.parallel.expert import moe_init

            block["moe"] = moe_init(next(keys), cfg.moe_experts, d, 4 * d, dt)
        else:
            block["mlp"] = {
                "fc": _normal(next(keys), (d, 4 * d), std, dt),
                "fc_b": jnp.zeros((4 * d,), dt),
                "proj": _normal(next(keys), (4 * d, d), resid_std, dt),
                "proj_b": jnp.zeros((d,), dt),
            }
        params["blocks"].append(block)
    return params


def _layer_norm(x, p, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * p["scale"].astype(jnp.float32) + p["bias"].astype(jnp.float32)).astype(x.dtype)


def _dropout(x, rate, key):
    if rate == 0.0 or key is None:
        return x
    keep = jax.random.bernoulli(key, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0).astype(x.dtype)


def _qkv_project(x, w):
    """[B,T,d] @ [d,3,d] stacked qkv — dense or LoRA-adapted (factored)."""
    from distributed_lion_tpu.models.lora import LoraTensor
    from distributed_lion_tpu.ops.quant import maybe_dequant

    if isinstance(w, LoraTensor):
        base = jnp.einsum("btd,dce->btce", x,
                          maybe_dequant(w.base, x.dtype).astype(x.dtype),
                          preferred_element_type=jnp.float32).astype(x.dtype)
        xa = x @ w.A.astype(x.dtype)
        delta = jnp.einsum("btr,rce->btce", xa, w.B.astype(x.dtype),
                           preferred_element_type=jnp.float32).astype(x.dtype)
        return base + w.scaling * delta
    # maybe_dequant: NF4/int8 frozen-weight serving (ops/quant) — a
    # QuantizedTensor in the qkv slot dequantizes into the matmul's
    # producer fusion; dense weights pass through untouched
    return jnp.einsum("btd,dce->btce", x, maybe_dequant(w, x.dtype).astype(x.dtype),
                      preferred_element_type=jnp.float32).astype(x.dtype)


def _attention(x, p, cfg: GPT2Config, key, tp_axis=None, seq_axis=None):
    """Causal multi-head attention; f32 softmax for stability.

    With ``tp_axis`` (Megatron tensor parallelism): qkv is column-parallel
    (this device holds H/tp heads), proj is row-parallel (partial sums are
    psum-reduced over the tensor axis; bias added after the reduction).
    With ``seq_axis`` (sequence/context parallelism): x holds this device's
    contiguous token chunk and attention runs as ring attention — (k, v)
    blocks rotate over the seq axis (parallel.ring_attention).
    """
    B, T, D = x.shape
    tp = 1 if tp_axis is None else jax.lax.psum(1, tp_axis)
    if tp_axis is not None:
        # Megatron f: identity fwd, psum bwd — dx re-assembled across tensor
        # ranks so upstream (LN/embedding) grads are complete, not partials
        x = copy_to_tp_region(x, tp_axis)
    H, hd = cfg.n_head // tp, cfg.head_dim
    qkv = _qkv_project(x, p["qkv"]) + p["qkv_b"].astype(x.dtype)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    q = q.reshape(B, T, H, hd).transpose(0, 2, 1, 3)
    k = k.reshape(B, T, H, hd).transpose(0, 2, 1, 3)
    v = v.reshape(B, T, H, hd).transpose(0, 2, 1, 3)

    if cfg.dropout > 0.0 and key is not None and seq_axis is None:
        # attention-prob dropout needs materialized scores; training with
        # dropout keeps the XLA path. Under sequence parallelism the scores
        # never exist in one place, so attention-prob dropout is skipped
        # (residual/embedding dropout still applies).
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
        scores = scores / math.sqrt(hd)
        causal = jnp.tril(jnp.ones((T, T), bool))
        scores = jnp.where(causal, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        probs = _dropout(probs, cfg.dropout, key)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs, v, preferred_element_type=jnp.float32)
        out = out.astype(x.dtype)
    elif seq_axis is not None:
        from distributed_lion_tpu.parallel.ring_attention import (
            ring_attention,
            ulysses_attention,
        )

        seq_attn = (ulysses_attention if cfg.seq_impl == "ulysses"
                    else ring_attention)
        out = seq_attn(q, k, v, axis_name=seq_axis)
    else:
        out = shared_attention(q, k, v, causal=True, impl=cfg.attn_impl,
                               block_q=cfg.flash_block_q,
                               block_kv=cfg.flash_block_kv,
                               block_q_bwd=cfg.flash_block_q_bwd,
                               block_kv_bwd=cfg.flash_block_kv_bwd)
    out = out.transpose(0, 2, 1, 3).reshape(B, T, H * hd)
    out = _proj(out, p["proj"])
    if tp_axis is not None:
        out = reduce_from_tp_region(out, tp_axis)  # row-parallel exit (g op)
    return out + p["proj_b"].astype(x.dtype)


def _proj(x, w):
    """2-D projection through the dense/quant/LoRA dispatch."""
    from distributed_lion_tpu.models.lora import lora_matmul

    return lora_matmul(x, w)


def _mlp(x, p, tp_axis=None):
    if tp_axis is not None:
        x = copy_to_tp_region(x, tp_axis)
    h = _proj(x, p["fc"]) + p["fc_b"].astype(x.dtype)
    h = jax.nn.gelu(h, approximate=True)
    out = _proj(h, p["proj"])
    if tp_axis is not None:
        out = reduce_from_tp_region(out, tp_axis)
    return out + p["proj_b"].astype(x.dtype)


def _block(x, p, key, cfg: GPT2Config, tp_axis=None, seq_axis=None):
    """One pre-LN transformer block. When ``cfg.remat`` the block is wrapped
    in ``jax.checkpoint`` so activations are recomputed in backward — HBM for
    FLOPs, the standard TPU trade for big models/long context; small models
    whose activations fit HBM set ``remat=False`` and skip the ~⅓ extra
    forward FLOPs in backward."""
    k1, k2, k3 = (None, None, None) if key is None else jax.random.split(key, 3)
    x = x + _dropout(
        _attention(_layer_norm(x, p["ln_1"]), p["attn"], cfg, k1, tp_axis, seq_axis),
        cfg.dropout, k2,
    )
    x = x + _dropout(_mlp(_layer_norm(x, p["ln_2"]), p["mlp"], tp_axis), cfg.dropout, k3)
    return x


def _remat_policy(name: str):
    if name == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    if name == "full":
        return None  # save nothing: recompute the whole block in backward
    raise ValueError(f"unknown remat_policy {name!r} (full | dots)")


def _block_remat_for(cfg):
    return partial(jax.checkpoint, static_argnums=(3, 4, 5),
                   policy=_remat_policy(cfg.remat_policy))(_block)


def _moe_block(x, p, key, cfg: GPT2Config, expert_axis=None, tp_axis=None,
               balance_tokens=None, return_tallies=False,
               balance_axis=None):
    """Pre-LN block whose FFN is the Switch-MoE layer: tokens flattened to
    [B*T, D], routed/dispatched by parallel/expert.moe_ffn (two all_to_all
    hops when ``expert_axis`` is bound), combined back. ``tp_axis`` runs
    the attention half column/row-parallel and Megatron-splits each
    expert's FFN (ep × tp). Returns ``(x, aux_loss)`` — the load-balance
    auxiliary to add to the train loss. ``balance_tokens`` ([E+1] f32,
    optional) substitutes a fed-in (global / ring-stale) token-load tally
    for the local one in the aux (the ``--ep_dcn_pipeline`` wire, see
    parallel/expert.moe_ffn); ``return_tallies`` additionally returns
    this block's fresh local tally; ``balance_axis`` is the synchronous
    depth-0 alternative (psum the tallies in the forward)."""
    from distributed_lion_tpu.parallel.expert import moe_ffn

    k1, k2, k3 = (None, None, None) if key is None else jax.random.split(key, 3)
    x = x + _dropout(
        _attention(_layer_norm(x, p["ln_1"]), p["attn"], cfg, k1, tp_axis, None),
        cfg.dropout, k2,
    )
    B, T, D = x.shape
    h = _layer_norm(x, p["ln_2"]).reshape(B * T, D)
    out = moe_ffn(p["moe"], h, capacity_factor=cfg.moe_capacity_factor,
                  axis_name=expert_axis, tp_axis=tp_axis,
                  balance_tokens=balance_tokens, balance_axis=balance_axis,
                  return_tallies=return_tallies)
    if return_tallies:
        y, aux, tally = out
    else:
        (y, aux), tally = out, None
    x = x + _dropout(y.reshape(B, T, D), cfg.dropout, k3)
    if return_tallies:
        return x, aux, tally
    return x, aux


def _moe_block_remat_for(cfg):
    # balance_tokens (argnum 6) is a traced array; return_tallies (7) and
    # balance_axis (8) are static python values like the axis names
    return partial(jax.checkpoint, static_argnums=(3, 4, 5, 7, 8),
                   policy=_remat_policy(cfg.remat_policy))(_moe_block)


def vocab_parallel_embed(wte_shard: jnp.ndarray, tokens: jnp.ndarray,
                         vocab_axis: str, out_dtype=None) -> jnp.ndarray:
    """Megatron VocabParallelEmbedding: ``wte_shard`` [V/tp, d] is this
    rank's contiguous vocab-row slice; out-of-range tokens contribute zero
    and the partial embeddings reduce over the tensor axis (the *g*
    operator — exact identity backward). Pairs with the vocab-parallel tied
    head (ops/xent.tp_vocab_xent on ``wte_shard.T``) so the full [V, d]
    table never exists on one device. ``out_dtype`` casts BEFORE the
    collective: exactly one rank contributes a nonzero row per token, so
    reducing in the (usually narrower) compute dtype is bit-identical at
    half the wire bytes."""
    vshard = wte_shard.shape[0]
    start = lax.axis_index(vocab_axis) * vshard
    in_range = (tokens >= start) & (tokens < start + vshard)
    idx = jnp.clip(tokens - start, 0, vshard - 1)
    part = wte_shard[idx] * in_range[..., None].astype(wte_shard.dtype)
    if out_dtype is not None:
        part = part.astype(out_dtype)
    return reduce_from_tp_region(part, vocab_axis)


def gpt2_hidden(
    params: dict,
    tokens: jnp.ndarray,
    cfg: GPT2Config,
    *,
    dropout_key: Optional[jax.Array] = None,
    tp_axis: Optional[str] = None,
    seq_axis: Optional[str] = None,
    expert_axis: Optional[str] = None,
    vocab_axis: Optional[str] = None,
    moe_balance: Optional[jnp.ndarray] = None,
    moe_balance_axis: Optional[str] = None,
    return_moe_tallies: bool = False,
) -> tuple:
    """Backbone forward: tokens [B, T] → (final hidden [B, T, d] after ln_f,
    MoE aux loss scalar). The tied-logits head is applied by
    :func:`gpt2_apply`, or streamed chunk-wise by ops/xent for the
    memory-lean loss path. With ``vocab_axis``, ``params["wte"]`` is this
    rank's vocab-row shard (:func:`vocab_parallel_embed`).

    ``moe_balance`` ([n_moe_blocks, E+1] f32, optional) feeds each MoE
    block's aux loss a substituted token-load tally — PER BLOCK, so a
    size-1 psum of the fresh tallies reproduces the unfed aux bit-for-bit
    (the ``--ep_dcn_pipeline`` depth-0 pin, train/loop.py).
    ``moe_balance_axis`` is the synchronous depth-0 form: each MoE block
    psums its fresh tallies over that axis inside the forward.
    ``return_moe_tallies`` appends a third output: the stacked fresh local
    tallies [n_moe_blocks, E+1] (stop-gradient)."""
    B, T = tokens.shape
    if seq_axis is None:
        if T > cfg.n_ctx:
            raise ValueError(f"sequence length {T} exceeds n_ctx {cfg.n_ctx}")
        pos_start = 0
    else:
        sidx = lax.axis_index(seq_axis)
        pos_start = sidx * T
        if dropout_key is not None:
            dropout_key = jax.random.fold_in(dropout_key, sidx)
    if vocab_axis is not None:
        x = vocab_parallel_embed(params["wte"], tokens, vocab_axis,
                                 out_dtype=cfg.compute_dtype)
    else:
        x = params["wte"][tokens]
    x = x.astype(cfg.compute_dtype)
    x = x + lax.dynamic_slice_in_dim(params["wpe"], pos_start, T, axis=0).astype(
        cfg.compute_dtype
    )
    keys = (
        [None] * (cfg.n_layer + 1)
        if dropout_key is None
        else list(jax.random.split(dropout_key, cfg.n_layer + 1))
    )
    x = _dropout(x, cfg.dropout, keys[-1])
    block = _block_remat_for(cfg) if cfg.remat else _block
    moe_block = _moe_block_remat_for(cfg) if cfg.remat else _moe_block
    aux_total = jnp.float32(0)
    tallies = []
    moe_i = 0
    for p, k in zip(params["blocks"], keys[: cfg.n_layer]):
        if "moe" in p:  # static pytree-structure branch, resolved at trace
            bt = None if moe_balance is None else moe_balance[moe_i]
            out = moe_block(x, p, k, cfg, expert_axis, tp_axis, bt,
                            return_moe_tallies, moe_balance_axis)
            if return_moe_tallies:
                x, aux, tally = out
                tallies.append(tally)
            else:
                x, aux = out
            aux_total = aux_total + aux
            moe_i += 1
        else:
            x = block(x, p, k, cfg, tp_axis, seq_axis)
    hidden = _layer_norm(x, params["ln_f"])
    if return_moe_tallies:
        stacked = (jnp.stack(tallies) if tallies
                   else jnp.zeros((0, 1), jnp.float32))
        return hidden, aux_total, stacked
    return hidden, aux_total


def gpt2_apply(
    params: dict,
    tokens: jnp.ndarray,
    cfg: GPT2Config,
    *,
    dropout_key: Optional[jax.Array] = None,
    tp_axis: Optional[str] = None,
    seq_axis: Optional[str] = None,
    expert_axis: Optional[str] = None,
    return_aux: bool = False,
    moe_balance: Optional[jnp.ndarray] = None,
    moe_balance_axis: Optional[str] = None,
    return_moe_tallies: bool = False,
) -> jnp.ndarray:
    """Forward pass: int32 tokens [B, T] → logits [B, T, vocab] (f32).

    Output projection is tied to the input embedding (GPT-2 weight tying).
    With ``tp_axis`` (inside shard_map), attention/MLP weights are expected
    pre-sharded per ``parallel.tensor_parallel.gpt2_param_specs``. With
    ``seq_axis`` (sequence parallelism), ``tokens`` is this device's
    contiguous chunk of the full sequence: positions offset by the shard
    index, attention rings over the axis, per-shard dropout keys.
    """
    out = gpt2_hidden(
        params, tokens, cfg, dropout_key=dropout_key, tp_axis=tp_axis,
        seq_axis=seq_axis, expert_axis=expert_axis,
        moe_balance=moe_balance, moe_balance_axis=moe_balance_axis,
        return_moe_tallies=return_moe_tallies,
    )
    x, aux_total = out[0], out[1]
    logits = jnp.einsum(
        "btd,vd->btv", x, params["wte"].astype(x.dtype),
        preferred_element_type=jnp.float32,
    )
    # padded-vocab layout: the matmul ran MXU-aligned over padded_vocab
    # columns; slicing back to vocab_size here keeps every downstream
    # consumer (losses, generation, eval) on exact true-vocab semantics
    logits = logits[..., : cfg.vocab_size]
    if return_moe_tallies:
        if return_aux:
            return logits, aux_total, out[2]
        return logits, out[2]
    if return_aux:
        return logits, aux_total
    return logits


def count_params(params) -> int:
    return sum(p.size for p in jax.tree.leaves(params))


def gpt2_moe_param_specs(cfg: GPT2Config, tensor: bool = False) -> dict:
    """PartitionSpec tree for a MoE config: expert FFN banks sharded over the
    'expert' mesh axis (parallel/expert.moe_param_specs); everything else
    replicated. Valid for ep == 1 too (a P('expert') dim over a size-1 axis
    is replication). ``tensor=True`` (ep × tp) additionally applies the
    Megatron split to attention, the dense MLP blocks, and each expert's
    FFN (the same layouts as gpt2_param_specs / moe_param_specs(tensor))."""
    from jax.sharding import PartitionSpec as P

    from distributed_lion_tpu.parallel.expert import moe_param_specs

    rep = P()
    ln = {"scale": rep, "bias": rep}
    if tensor:
        # ONE source of truth for the Megatron attn/mlp layouts: reuse the
        # dense-TP spec tree rather than hand-copying it (a layout change
        # there must not silently diverge the MoE-TP sharding)
        from distributed_lion_tpu.parallel.tensor_parallel import (
            gpt2_param_specs,
        )

        dense_block = gpt2_param_specs(cfg)["blocks"][0]
        att, mlp = dense_block["attn"], dense_block["mlp"]
    else:
        att = {k: rep for k in ("qkv", "qkv_b", "proj", "proj_b")}
        mlp = {k: rep for k in ("fc", "fc_b", "proj", "proj_b")}
    blocks = []
    for i in range(cfg.n_layer):
        block = {"ln_1": ln, "attn": att, "ln_2": ln}
        if is_moe_block(cfg, i):
            block["moe"] = moe_param_specs(tensor=tensor)
        else:
            block["mlp"] = mlp
        blocks.append(block)
    return {"wte": rep, "wpe": rep, "ln_f": ln, "blocks": blocks}


# ------------------------------------------------------------------ decoding
def gpt2_init_cache(cfg: GPT2Config, batch: int, max_len: int) -> list:
    """Per-layer KV cache [B, H, max_len, hd] (static shape: decode writes
    into a fixed-size buffer with a position index — no dynamic shapes under
    jit). Net-new vs the reference, which has no inference path at all."""
    shape = (batch, cfg.n_head, max_len, cfg.head_dim)
    return [
        {"k": jnp.zeros(shape, cfg.compute_dtype), "v": jnp.zeros(shape, cfg.compute_dtype)}
        for _ in range(cfg.n_layer)
    ]


def _decode_attention(x, p, cfg: GPT2Config, c, pos, offset=None):
    """Cache-aware attention for S new tokens at absolute position ``pos``:
    project qkv for the new tokens, write k/v into the cache, attend q over
    the whole (masked) cache. ``offset`` (optional [B] int32) is the
    per-row count of left-pad slots in a batched, variable-length prompt
    (cli/run_generate's multi-prompt mode): slots below it are masked out
    of every row's attention, so the pad prefix never leaks into scores."""
    B, S, _ = x.shape
    H, hd = cfg.n_head, cfg.head_dim
    qkv = _qkv_project(x, p["qkv"]) + p["qkv_b"].astype(x.dtype)
    q, k, v = (qkv[:, :, i].reshape(B, S, H, hd).transpose(0, 2, 1, 3) for i in range(3))
    k_cache = lax.dynamic_update_slice_in_dim(c["k"], k.astype(c["k"].dtype), pos, axis=2)
    v_cache = lax.dynamic_update_slice_in_dim(c["v"], v.astype(c["v"].dtype), pos, axis=2)
    T = k_cache.shape[2]
    scores = jnp.einsum("bhsd,bhtd->bhst", q, k_cache,
                        preferred_element_type=jnp.float32) / math.sqrt(hd)
    valid = jnp.arange(T)[None, :] <= (pos + jnp.arange(S))[:, None]  # causal + unwritten
    if offset is None:
        scores = jnp.where(valid[None, None], scores, -1e30)
    else:
        row_valid = valid[None] & (jnp.arange(T)[None, None, :]
                                   >= offset[:, None, None])
        scores = jnp.where(row_valid[:, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
    out = jnp.einsum("bhst,bhtd->bhsd", probs, v_cache,
                     preferred_element_type=jnp.float32).astype(x.dtype)
    out = out.transpose(0, 2, 1, 3).reshape(B, S, H * hd)
    out = _proj(out, p["proj"]) + p["proj_b"].astype(x.dtype)
    return out, {"k": k_cache, "v": v_cache}


def _decode_mlp(x, p, cfg: GPT2Config, tp_axis=None, valid=None,
                ep_axis=None, moe_stats=None, stats_axis=None,
                stats_lanes=None):
    """The post-attention half of a decode block (dense MLP or the MoE
    FFN with decode-friendly capacity) — shared by the dense-cache and
    paged decode paths so their numerics cannot drift. ``tp_axis`` runs
    the dense MLP (and, with ``ep_axis``×tp, each expert's FFN)
    Megatron-split — the TP serving engine's path.

    MoE at inference is NO-DROP: ``capacity_override = B*S`` for every
    decode-path call (single-token ticks AND prefill/verify windows), so
    routing is an exact per-token function — no batchmate, padding bucket
    or speculation window can displace another token's expert slot. That
    is what makes paged==dense, batched==solo and speculative==plain hold
    bit-for-bit for MoE (training keeps the Switch capacity bound; the
    inference trade is a [E, B*S, D] dispatch buffer — bounded by the
    page-geometry bucket, ephemeral, and tiny next to the KV pages).
    ``valid`` ([B, S] bool) masks pad/sentinel lanes out of routing
    (parallel/expert.moe_ffn) so dead lanes consume zero expert capacity;
    ``ep_axis`` shards the expert banks over the serving mesh's expert
    axis (two all_to_all hops); ``moe_stats`` (a list) collects this
    block's routing-load scalars when the engine benchmarks capacity
    utilization; ``stats_axis`` (batch-sharded ep serving, ISSUE 16)
    psums the routing-load counters over the expert axis so the stats
    stay GLOBAL when each shard routes only its batch slice, and
    ``stats_lanes`` (static) overrides the budget's lane count for
    dispatches whose non-owner shards carry fake all-invalid lanes (the
    batch-sharded batch-1 prefill)."""
    if "moe" in p:
        from distributed_lion_tpu.parallel.expert import moe_ffn

        B2, S2, D2 = x.shape
        h = _layer_norm(x, p["ln_2"]).reshape(B2 * S2, D2)
        v = None if valid is None else valid.reshape(B2 * S2)
        out = moe_ffn(p["moe"], h, capacity_factor=cfg.moe_capacity_factor,
                      axis_name=ep_axis, capacity_override=B2 * S2,
                      tp_axis=tp_axis, valid=v,
                      return_stats=moe_stats is not None,
                      stats_axis=stats_axis, stats_lanes=stats_lanes)
        if moe_stats is not None:
            y, _, st = out
            moe_stats.append(st)
        else:
            y, _ = out
        return x + y.reshape(B2, S2, D2)
    return x + _mlp(_layer_norm(x, p["ln_2"]), p["mlp"], tp_axis)


def _decode_embed(params, tokens, cfg: GPT2Config, pos, offset):
    """Token + position embeddings for a decode chunk. Scalar ``pos``
    slices wpe uniformly; with per-row ``offset`` (left-padded batch) each
    row gathers its own shifted position ids (clipped at 0 — pad slots
    reuse position 0, masked out of attention anyway). Both lookups route
    through lora_embed/maybe_dequant so NF4-quantized tables serve."""
    from distributed_lion_tpu.models.lora import lora_embed
    from distributed_lion_tpu.ops.quant import maybe_dequant

    B, S = tokens.shape
    x = lora_embed(params["wte"], tokens, cfg.compute_dtype)
    if offset is None:
        wpe = maybe_dequant(params["wpe"], cfg.compute_dtype)
        return x + lax.dynamic_slice_in_dim(wpe, pos, S, axis=0).astype(
            cfg.compute_dtype)
    pos_ids = jnp.clip(pos + jnp.arange(S)[None, :] - offset[:, None],
                       0, cfg.n_ctx - 1)
    return x + lora_embed(params["wpe"], pos_ids, cfg.compute_dtype)


def _tied_logits(x, params, cfg: GPT2Config):
    from distributed_lion_tpu.ops.quant import maybe_dequant

    logits = jnp.einsum("btd,vd->btv", x,
                        maybe_dequant(params["wte"], x.dtype).astype(x.dtype),
                        preferred_element_type=jnp.float32)
    return logits[..., : cfg.vocab_size]


def gpt2_decode(params: dict, tokens: jnp.ndarray, cfg: GPT2Config, cache: list,
                pos, offset=None):
    """Incremental forward: ``tokens`` [B, S] are the next S tokens at
    absolute cache slots [pos, pos+S). Returns (logits [B, S, vocab] f32,
    updated cache). ``gpt2_decode(params, prompt, cfg, cache, 0)`` is the
    prefill; single-token calls are the decode loop. Matches ``gpt2_apply``
    logits position-for-position (pinned by tests/test_generate.py).
    ``offset`` [B]: per-row left-pad width for batched variable-length
    prompts — row b's real tokens sit at slots >= offset[b] and get
    position ids ``slot - offset[b]`` (solo semantics, shifted). MoE
    checkpoints compose with the offset path: the left-pad lanes are
    masked out of expert routing (``valid`` below) and inference routing
    is no-drop per-token (see _decode_mlp), so batched greedy output
    equals solo runs for MoE exactly as it does for dense models."""
    valid = None
    if offset is not None:
        # lane (b, s) sits at absolute cache slot pos + s; slots below the
        # row's left-pad width are dead lanes for expert routing
        valid = (pos + jnp.arange(tokens.shape[1]))[None, :] >= offset[:, None]
    x = _decode_embed(params, tokens, cfg, pos, offset)
    new_cache = []
    for p, c in zip(params["blocks"], cache):
        a, c = _decode_attention(_layer_norm(x, p["ln_1"]), p["attn"], cfg, c,
                                 pos, offset)
        x = _decode_mlp(x + a, p, cfg, valid=valid)
        new_cache.append(c)
    x = _layer_norm(x, params["ln_f"])
    return _tied_logits(x, params, cfg), new_cache


def _paged_attention_block(x, p, cfg: GPT2Config, c, tables, pos, valid,
                           tp_axis=None):
    """The paged twin of :func:`_decode_attention`: scatter the new k/v
    into block-table pages, attend over the gathered history
    (ops.attention.paged_decode_attention — same masked-softmax chain as
    the dense path, so greedy decode is bit-identical when T matches).
    With ``tp_axis`` (inside shard_map — the TP serving engine): qkv is
    column-parallel (this rank holds H/tp heads and the page pool's
    matching kv-head shard), the scatter/gather/attend chain is entirely
    shard-local, and only the row-parallel output projection crosses the
    tensor axis (one psum; bias added after the reduction, once)."""
    from distributed_lion_tpu.ops.attention import (
        paged_decode_attention,
        paged_scatter_kv,
    )

    B, S, _ = x.shape
    tp = 1 if tp_axis is None else jax.lax.psum(1, tp_axis)
    H, hd = cfg.n_head // tp, cfg.head_dim
    qkv = _qkv_project(x, p["qkv"]) + p["qkv_b"].astype(x.dtype)
    q, k, v = (qkv[:, :, i].reshape(B, S, H, hd) for i in range(3))
    k_pages = paged_scatter_kv(c["k"], tables, pos, k.astype(c["k"].dtype), valid)
    v_pages = paged_scatter_kv(c["v"], tables, pos, v.astype(c["v"].dtype), valid)
    out = paged_decode_attention(q.transpose(0, 2, 1, 3), k_pages, v_pages,
                                 tables, pos)
    out = out.transpose(0, 2, 1, 3).reshape(B, S, H * hd)
    out = _proj(out, p["proj"])
    if tp_axis is not None:
        out = reduce_from_tp_region(out, tp_axis)
    out = out + p["proj_b"].astype(x.dtype)
    return out, {"k": k_pages, "v": v_pages}


def gpt2_decode_paged(params: dict, tokens: jnp.ndarray, cfg: GPT2Config,
                      pages: list, tables: jnp.ndarray, pos: jnp.ndarray,
                      valid=None, tp_axis=None, ep_axis=None,
                      return_moe_stats=False, stats_axis=None,
                      stats_lanes=None):
    """Block-table decode (the serving engine's model hook): ``tokens``
    [B, S] where row b's tokens sit at absolute positions
    ``pos[b] .. pos[b]+S-1`` of its own sequence; ``pages`` is the
    per-layer page pool ({"k","v"} of [num_blocks, block_size, H, hd]),
    ``tables`` [B, blocks_per_seq] the per-row block tables, ``valid``
    optional [B, S] (False = right-pad tail of a bucketed prefill — no
    page write, logits discarded by the caller). Returns (logits
    [B, S, vocab] f32, updated pages). Positions are PER ROW, so one call
    serves prefill (S = padded prompt, pos = 0) and the rolling decode
    tick (S = 1, pos = per-slot lengths) — one jitted program each.
    With ``tp_axis`` (inside shard_map — the TP serving engine, ISSUE 13)
    attention/MLP weights and the page pool's kv-head axis are expected
    pre-sharded per ``parallel.tensor_parallel.gpt2_param_specs``;
    embeddings and the tied head stay replicated, so the returned logits
    are identical on every tensor rank.

    MoE checkpoints serve through this path (ISSUE 15 — the PR 9 refusal
    lifted): ``valid`` masks pad/sentinel lanes out of expert routing and
    inference routing is no-drop (see _decode_mlp), so paged MoE decode
    is bit-identical to the dense-KV MoE path at matched attended length.
    ``ep_axis`` (inside the serving engine's shard_map) shards the expert
    banks over the mesh's expert axis — two all_to_all hops per MoE block,
    the page pools untouched. ``return_moe_stats`` additionally returns a
    dict of routing-load scalars summed over the MoE blocks (the bench's
    capacity-utilization columns; {} for a dense checkpoint); under
    batch-sharded ep (ISSUE 16) ``stats_axis`` makes those counters
    global (see _decode_mlp).

    Batch-sharded expert-parallel decode (ISSUE 16): when the engine
    shards the decode batch over the expert axis, every operand here is
    this shard's LOCAL slice — B local slots, the page pool's local block
    span, tables carrying LOCAL page ids (sentinel == local pool size).
    Attention is row-local so nothing changes; the MoE dispatch
    all_to_all hops are exactly the training-style layout moe_ffn was
    written for, and no-drop routing keeps per-token outputs bit-equal
    to the replicated program."""
    pos_ids = jnp.clip(pos[:, None] + jnp.arange(tokens.shape[1])[None, :],
                       0, cfg.n_ctx - 1)
    from distributed_lion_tpu.models.lora import lora_embed

    x = lora_embed(params["wte"], tokens, cfg.compute_dtype)
    x = x + lora_embed(params["wpe"], pos_ids, cfg.compute_dtype)
    stats = [] if return_moe_stats else None
    new_pages = []
    for p, c in zip(params["blocks"], pages):
        a, c = _paged_attention_block(_layer_norm(x, p["ln_1"]), p["attn"],
                                      cfg, c, tables, pos, valid, tp_axis)
        x = _decode_mlp(x + a, p, cfg, tp_axis, valid, ep_axis, stats,
                        stats_axis, stats_lanes)
        new_pages.append(c)
    x = _layer_norm(x, params["ln_f"])
    logits = _tied_logits(x, params, cfg)
    if return_moe_stats:
        agg = ({k: sum(s[k] for s in stats)
                for k in ("valid", "kept", "capacity_slots")}
               if stats else {})
        return logits, new_pages, agg
    return logits, new_pages
