from distributed_lion_tpu.models.generate import generate, sample_logits
from distributed_lion_tpu.models.gpt2 import (
    GPT2Config,
    gpt2_apply,
    gpt2_decode,
    gpt2_init,
    gpt2_init_cache,
)
from distributed_lion_tpu.models.llama import (
    LlamaConfig,
    llama_apply,
    llama_decode,
    llama_init,
    llama_init_cache,
)
from distributed_lion_tpu.models.loss import clm_loss_and_metrics
