from distributed_lion_tpu.models.gpt2 import GPT2Config, gpt2_init, gpt2_apply
from distributed_lion_tpu.models.loss import clm_loss_and_metrics
