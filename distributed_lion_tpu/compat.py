"""JAX API compatibility layer.

The codebase targets the current ``jax.shard_map`` entry point (with its
``check_vma`` argument). Older jax releases — including the pinned toolchain
on some CI hosts — only ship ``jax.experimental.shard_map.shard_map`` whose
equivalent flag is ``check_rep``. Rather than scattering version branches
over every call site (train/loop, optim/sharded, parallel/*, tests),
:func:`install` publishes one forwarding wrapper as ``jax.shard_map`` when
the attribute is missing, so both ``jax.shard_map(...)`` calls and
``from jax import shard_map`` imports work on either jax.

Installed automatically at package import (``distributed_lion_tpu``) and
from ``tests/conftest.py`` (which must run before test modules that do
``from jax import shard_map`` at module scope).
"""

from __future__ import annotations

import functools

import jax


def _compat_shard_map(f=None, *, mesh, in_specs, out_specs, check_vma=True,
                      **kwargs):
    """``jax.shard_map`` signature adapter over the experimental API.

    Supports the partial-application form ``shard_map(mesh=..., ...)(f)``
    used with ``functools.partial`` decorators throughout the repo.
    """
    from jax.experimental.shard_map import shard_map as _shard_map

    if f is None:
        return functools.partial(
            _compat_shard_map, mesh=mesh, in_specs=in_specs,
            out_specs=out_specs, check_vma=check_vma, **kwargs)
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=check_vma, **kwargs)


def _compat_pcast(x, axes=None, *, to=None, **kwargs):
    """``jax.lax.pcast`` fallback for jax versions without varying-manual-axes
    typing: on those versions the cast is PURELY a type-system annotation
    (there is no vma type to move between), so identity — over any pytree —
    is exact, not an approximation. Used by parallel.pipeline to mark scan
    carries device-varying before the first ppermute."""
    del axes, to, kwargs
    return x


def _compat_axis_size(axis_name):
    """``jax.lax.axis_size`` fallback: ``psum(1, axis)`` of a Python literal
    folds to the static axis size at trace time (shard_map axis sizes are
    static), so callers may keep using the result in shape math and
    ``if`` guards exactly as with the real entry point."""
    return jax.lax.psum(1, axis_name)


def install() -> None:
    """Idempotently publish ``jax.shard_map`` / ``jax.lax.pcast`` /
    ``jax.lax.axis_size`` on jax versions that predate them. A no-op (and
    therefore zero-risk) wherever jax already provides the real entry
    points."""
    if not hasattr(jax, "shard_map"):
        jax.shard_map = _compat_shard_map
    if not hasattr(jax.lax, "pcast"):
        jax.lax.pcast = _compat_pcast
    if not hasattr(jax.lax, "axis_size"):
        jax.lax.axis_size = _compat_axis_size
