"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

Net-new capability vs the reference (which handles length only by truncation
to 1024 — SURVEY §5 "Long-context: absent"), built the ICI-native way the
task calls for:

- :func:`ring_attention` — q stays put, (k, v) blocks rotate around the
  ``seq`` mesh axis via ``lax.ppermute`` while an online-softmax accumulator
  (running max / denominator / numerator) folds in one block per hop.
  Causality at chunk granularity: earlier chunks attend fully, the diagonal
  chunk applies the triangular mask, later chunks are skipped. Communication
  overlaps compute hop by hop; per-device memory is O(T_local²) only for the
  diagonal.
- :func:`ulysses_attention` — ``lax.all_to_all`` re-shards sequence ↔ heads,
  runs dense local attention over the full sequence on each device's head
  slice, and re-shards back. Cheaper at moderate T when H ≥ axis size.

Both run inside ``jax.shard_map`` with q/k/v sharded [B, H, T/S, hd] on the
sequence axis and are exact (tested against single-device full attention).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from distributed_lion_tpu.ops.attention import attention_xla


def ring_attention(q, k, v, axis_name: str):
    """Causal flash-style attention over a ring of sequence shards.

    Args:
        q, k, v: [B, H, T_local, hd] — this device's sequence chunk (chunks
            are contiguous: device i owns positions [i*T_local, (i+1)*T_local)).
        axis_name: the sequence mesh axis.

    Returns:
        [B, H, T_local, hd] in q's dtype.
    """
    S = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, H, T, hd = q.shape
    scale = 1.0 / math.sqrt(hd)

    m = jnp.full((B, H, T, 1), -jnp.inf, jnp.float32)   # running max
    l = jnp.zeros((B, H, T, 1), jnp.float32)            # running denominator
    acc = jnp.zeros((B, H, T, hd), jnp.float32)         # running numerator

    perm = [(i, (i + 1) % S) for i in range(S)]
    k_blk, v_blk = k, v
    for step in range(S):
        src = (idx - step) % S  # whose chunk we hold this hop
        scores = jnp.einsum(
            "bhqd,bhkd->bhqk", q, k_blk, preferred_element_type=jnp.float32
        ) * scale
        # chunk-level causality
        diag = jnp.tril(jnp.ones((T, T), bool))
        allow = jnp.where(
            src == idx, diag, (src < idx)[None, None]
        )  # [T,T] or broadcast scalar
        scores = jnp.where(allow, scores, -jnp.inf)

        blk_max = scores.max(-1, keepdims=True)  # may be -inf for skipped chunks
        new_m = jnp.maximum(m, blk_max)
        # guard: rows with all -inf so far keep exp(0)=... use safe max
        safe_m = jnp.where(jnp.isinf(new_m), 0.0, new_m)
        alpha = jnp.exp(jnp.where(jnp.isinf(m), -jnp.inf, m) - safe_m)
        alpha = jnp.where(jnp.isinf(m), 0.0, alpha)
        p = jnp.exp(scores - safe_m)
        p = jnp.where(jnp.isinf(scores), 0.0, p)

        l = l * alpha + p.sum(-1, keepdims=True)
        acc = acc * alpha + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32,
        )
        m = new_m
        if step + 1 < S:
            k_blk = lax.ppermute(k_blk, axis_name, perm)
            v_blk = lax.ppermute(v_blk, axis_name, perm)

    out = acc / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style).

    Re-shard [B, H, T/S, hd] (seq-sharded) → [B, H/S, T, hd] (head-sharded),
    run full causal attention locally, re-shard back. Requires H % S == 0.
    """
    S = lax.psum(1, axis_name)
    B, H, T_local, hd = q.shape
    if H % S != 0:
        raise ValueError(f"n_heads {H} not divisible by seq axis size {S}")

    def seq_to_heads(x):
        # [B, H, T/S, hd] → [B, H/S, T, hd]: split heads across, gather seq
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    out = attention_xla(seq_to_heads(q), seq_to_heads(k), seq_to_heads(v), causal=True)
    return heads_to_seq(out)
