"""Pipeline parallelism: GPipe-style microbatched stage execution.

Net-new vs the reference (which is data-parallel only, SURVEY §2.7) but a
first-class axis of this framework's mesh. Design is TPU-idiomatic rather
than a port of GPU pipeline runtimes:

- **Same program on every stage** (SPMD under ``jax.shard_map``): the layer
  stack is stored stacked ``[n_stages, layers_per_stage, ...]`` and sharded
  over the ``pipe`` mesh axis, so each device holds one stage's slice.
- **Activations rotate on the interconnect** with ``lax.ppermute`` — the
  classic shift-register schedule: at tick ``t`` stage 0 ingests microbatch
  ``t`` while stage ``s`` works on microbatch ``t-s``; after
  ``n_micro + n_stages - 1`` ticks every microbatch has exited the last
  stage. The whole schedule is one ``lax.scan`` — static shapes, one XLA
  compilation, no host round-trips.
- **Autodiff for free**: ``ppermute``'s transpose is the reverse permute, so
  ``jax.grad`` through :func:`pipeline_apply` yields exactly the backward
  pipeline (bubbles and all) without a hand-written schedule.

Bubble fraction is ``(S-1)/(M+S-1)`` for S stages / M microbatches — pick
``n_micro >= 4*stages`` to keep it small. Outputs are only *real* on the
last stage; :func:`from_last_stage` broadcasts (or use the value inside a
masked loss, which is cheaper than broadcasting activations).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from distributed_lion_tpu.parallel.mesh import PIPE_AXIS


def stack_stage_params(layer_params: list, n_stages: int):
    """[L layers] pytree-list → stacked pytree with leading [n_stages, L/S]
    axes, ready to shard with ``P('pipe', ...)``."""
    n_layer = len(layer_params)
    if n_layer % n_stages:
        raise ValueError(f"{n_layer} layers not divisible by {n_stages} stages")
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *layer_params)
    return jax.tree.map(
        lambda x: x.reshape((n_stages, n_layer // n_stages) + x.shape[1:]), stacked
    )


def unstack_stage_params(stacked, n_layer: int) -> list:
    """Inverse of :func:`stack_stage_params` (checkpoint export)."""
    flat = jax.tree.map(
        lambda x: x.reshape((n_layer,) + x.shape[2:]), stacked
    )
    return [jax.tree.map(lambda x: x[i], flat) for i in range(n_layer)]


def pipeline_apply(
    layer_fn: Callable,
    stage_params,
    x: jnp.ndarray,
    *,
    axis_name: str = PIPE_AXIS,
) -> jnp.ndarray:
    """Run microbatches through the pipelined layer stack.

    Must be called inside ``shard_map`` with ``stage_params`` sharded over
    ``axis_name`` (leading stage axis already consumed — the local view is
    ``[layers_per_stage, ...]``) and ``x`` replicated along it.

    Args:
        layer_fn: ``layer_fn(one_layer_params, x) -> y`` (same shape).
        stage_params: this stage's layers, leading ``[layers_per_stage]``.
        x: ``[n_micro, micro_batch, ...]`` microbatched activations
            (embedded tokens), identical on every stage.

    Returns:
        ``[n_micro, micro_batch, ...]`` outputs — REAL on the last stage,
        zeros elsewhere (see :func:`from_last_stage`).
    """
    stage = lax.axis_index(axis_name)
    n_stages = lax.psum(1, axis_name)
    n_micro = x.shape[0]
    total_ticks = n_micro + n_stages - 1  # fill + drain

    def stage_fn(params, h):
        # sequentially apply this stage's layers_per_stage layers
        return lax.scan(lambda c, p: (layer_fn(p, c), None), h, params)[0]

    def tick(carry, t):
        state, acc = carry
        # stage 0 ingests microbatch t (clamped index keeps shapes static;
        # ticks past n_micro-1 feed garbage that drains before the last stage)
        cur = jnp.where(stage == 0, x[jnp.clip(t, 0, n_micro - 1)], state)
        y = stage_fn(stage_params, cur)
        out_idx = t - (n_stages - 1)
        acc = jnp.where(
            (stage == n_stages - 1) & (out_idx >= 0),
            acc.at[jnp.clip(out_idx, 0, n_micro - 1)].set(y),
            acc,
        )
        # ring shift stage s -> s+1 (the wrap edge last->0 carries values
        # that stage 0 always overwrites with fresh ingest — harmless)
        state = lax.ppermute(y, axis_name, _shift_pairs(axis_name))
        return (state, acc), None

    # the carry becomes device-varying after the first ppermute/at-set, so
    # the init must already be marked varying over the pipe axis (JAX vma
    # typing under shard_map)
    init = jax.lax.pcast(
        (jnp.zeros_like(x[0]), jnp.zeros_like(x)), (axis_name,), to="varying"
    )
    (_, acc), _ = lax.scan(tick, init, jnp.arange(total_ticks))
    return acc


def _shift_pairs(axis_name: str):
    n = jax.lax.psum(1, axis_name)  # static under shard_map
    return [(i, (i + 1) % n) for i in range(n)]


def from_last_stage(val: jnp.ndarray, axis_name: str = PIPE_AXIS) -> jnp.ndarray:
    """Broadcast a value that is only real on the last stage (zeros
    elsewhere, as produced by :func:`pipeline_apply`) to every stage."""
    stage = lax.axis_index(axis_name)
    n_stages = lax.psum(1, axis_name)
    return lax.psum(jnp.where(stage == n_stages - 1, val, jnp.zeros_like(val)),
                    axis_name)


def to_microbatches(x: jnp.ndarray, n_micro: int) -> jnp.ndarray:
    """[batch, ...] → [n_micro, batch/n_micro, ...]."""
    if x.shape[0] % n_micro:
        raise ValueError(f"batch {x.shape[0]} not divisible by n_micro {n_micro}")
    return x.reshape((n_micro, x.shape[0] // n_micro) + x.shape[1:])


def from_microbatches(x: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`to_microbatches`."""
    return x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:])
