"""Expert parallelism: Switch-style MoE FFN with all_to_all dispatch.

Net-new vs the reference (data-parallel only, SURVEY §2.7), designed for the
TPU fabric rather than ported: experts live sharded over the ``expert`` mesh
axis, and each device's tokens reach their experts through exactly two
``lax.all_to_all`` collectives (dispatch + return) riding ICI — the standard
TPU MoE layout (tokens stay in fixed-capacity buffers, every shape static,
no host-side routing).

Routing is top-1 ("Switch Transformer"): per-token argmax over a learned
gate, fixed per-expert capacity ``ceil(cf * N / E)`` with overflow dropped
(the residual path carries dropped tokens unchanged), and the usual
load-balancing auxiliary loss. All arithmetic is batched einsums over
[tokens, experts, capacity] one-hot masks — MXU-friendly, autodiff-clean.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from distributed_lion_tpu.parallel.mesh import EXPERT_AXIS


def moe_init(key, n_experts: int, d_model: int, d_ff: int, dtype=jnp.float32):
    """Gate + per-expert FFN params. Shard the ``w_/b_`` leaves over the
    expert axis with :func:`moe_param_specs`; the gate stays replicated."""
    kg, ki, ko = jax.random.split(key, 3)
    init = jax.nn.initializers.normal(0.02)
    return {
        "gate": init(kg, (d_model, n_experts), dtype),
        "w_in": init(ki, (n_experts, d_model, d_ff), dtype),
        "b_in": jnp.zeros((n_experts, d_ff), dtype),
        "w_out": init(ko, (n_experts, d_ff, d_model), dtype),
        "b_out": jnp.zeros((n_experts, d_model), dtype),
    }


def moe_param_specs(tensor: bool = False):
    """Expert banks over the 'expert' axis; ``tensor=True`` ADDITIONALLY
    Megatron-splits each expert's FFN over the tensor axis (w_in column-
    parallel on d_ff, w_out row-parallel — the same split as a dense MLP,
    batched over the expert dim). The gate and b_out stay replicated over
    tensor (b_out is added AFTER the row-parallel psum in moe_ffn)."""
    from jax.sharding import PartitionSpec as P

    from distributed_lion_tpu.parallel.mesh import TENSOR_AXIS

    e = EXPERT_AXIS
    if not tensor:
        return {
            "gate": P(),
            "w_in": P(e), "b_in": P(e),
            "w_out": P(e), "b_out": P(e),
        }
    t = TENSOR_AXIS
    return {
        "gate": P(),
        "w_in": P(e, None, t), "b_in": P(e, t),   # [E, d, f/tp], [E, f/tp]
        "w_out": P(e, t, None), "b_out": P(e),    # [E, f/tp, d]
    }


def capacity(n_tokens: int, n_experts: int, capacity_factor: float) -> int:
    return max(1, math.ceil(capacity_factor * n_tokens / n_experts))


def moe_ffn(
    params,
    x: jnp.ndarray,
    *,
    capacity_factor: float = 1.25,
    axis_name: Optional[str] = EXPERT_AXIS,
    capacity_override: Optional[int] = None,
    tp_axis: Optional[str] = None,
    valid: Optional[jnp.ndarray] = None,
    return_stats: bool = False,
    stats_axis: Optional[str] = None,
    stats_lanes: Optional[int] = None,
    balance_tokens: Optional[jnp.ndarray] = None,
    balance_axis: Optional[str] = None,
    return_tallies: bool = False,
):
    """Apply the MoE FFN to local tokens ``x [N, D]``.

    Under ``shard_map`` with ``axis_name`` bound, ``params['w_in']`` etc.
    hold only this shard's experts ``[E_local, ...]`` while the gate scores
    ALL ``E = E_local * shards`` experts; tokens travel over the fabric.
    With ``axis_name=None`` (or axis size 1) it is the single-device
    reference semantics — same routing, same drops, no collectives.

    ``tp_axis`` (ep × tp): each expert's FFN is ADDITIONALLY Megatron-split
    over the tensor axis — w_in column-parallel on d_ff, w_out row-parallel
    with one psum (moe_param_specs(tensor=True) layout). Routing/dispatch
    see the full D on every tensor rank (x is replicated over tensor), so
    the gate decisions and the expert all_to_all are identical across tp.

    ``valid`` (optional ``[N]`` bool) marks the lanes that carry real
    tokens — the serving engine's pad/sentinel lanes (right-padded bucketed
    prefill tails, inactive decode slots, left-pad offsets in batched
    generate) pass False. Invalid lanes are masked out of the gate
    assignment BEFORE the capacity one-hot, so a dead lane never occupies
    an expert-capacity slot and never perturbs which real tokens get
    dropped: a padded batch's routed assignment for its real tokens equals
    the unpadded batch's assignment at the same capacity (pinned by
    tests/test_moe_serve.py), and invalid lanes produce exact-zero output
    rows. ``valid=None`` (training) keeps every lane, bit-identical to the
    pre-mask code path.

    ``return_stats`` additionally returns a dict of routing-load scalars
    measured over the VALID lanes against the ``capacity_factor`` budget
    ``capacity(n, E, capacity_factor)`` — regardless of any
    ``capacity_override`` in effect, so the serving engine's no-drop
    override still reports how its traffic loads the Switch capacity
    budget: ``valid`` (real lanes routed), ``kept`` (of those, how many
    fit the per-expert budget), ``capacity_slots`` (E × budget). All f32
    scalars computable on-device with zero host syncs.

    ``stats_axis`` (batch-sharded serving, ISSUE 16): when the TOKEN batch
    is sharded over a mesh axis, each shard sees only its slice of the
    tick's lanes — the stats psum the per-expert counts over that axis and
    size the budget from the GLOBAL lane count, so capacity utilization /
    dropped rate stay global quantities, bit-equal to the unsharded run.
    Naively psumming the per-shard scalars is WRONG: ``capacity`` is a
    ceil, so per-shard budgets don't sum to the global budget.
    ``stats_lanes`` (static int) overrides that global lane count for
    dispatches whose shards carry FAKE lanes the unsharded run never had
    — the batch-sharded batch-1 prefill replays the prompt width on every
    group with non-owners all-invalid, so its budget must come from the
    true width, not ``n × shards``. Counts still psum (invalid lanes
    contribute zero), keeping stats bit-equal to the unsharded engine.

    ``balance_tokens`` (training ``--ep_dcn_pipeline``, ISSUE 16): an
    ``[E+1]`` f32 vector — per-expert routed-token counts plus the total
    lane count — substituted for the LOCAL token-load fraction in the aux
    loss. The differentiable gate-probability factor stays fresh and
    local; only the non-differentiable load estimate is replaced, which
    is what lets the trainer feed a globally-psummed (and, at depth > 0,
    ring-stale) load through the aux without adding a blocking collective
    to the backward pass. ``return_tallies`` additionally returns this
    step's fresh local ``[E+1]`` tally (stop-gradient) for the caller to
    aggregate. ``balance_tokens=None`` is bit-identical to the historical
    local-fraction aux; an all-zero tally (lane-count entry 0) is the
    ring's cold-start sentinel and falls back to the local fraction.
    ``balance_axis`` is the SYNCHRONOUS alternative (``--ep_dcn_pipeline
    0``): psum the raw tallies over that axis inside the forward before
    forming the load fraction — blocking, but exactly global-fresh; at
    axis size 1 it is the local aux bit for bit. Mutually exclusive with
    ``balance_tokens``.

    Returns ``(y [N, D], aux_loss scalar)`` (plus the stats dict when
    requested); add ``aux`=0.01*aux_loss`` to the train loss to balance
    expert load (Switch Transformer recipe).
    """
    # NF4/int8 frozen-weight serving (ops/quant): QuantizedTensor expert
    # banks dequantize into their einsum's producer fusion; dense leaves
    # (and every training call) pass through maybe_dequant untouched.
    # Dequant FIRST: under shard_map a quantized leaf's static .shape is
    # the GLOBAL shape, while the dequantized array has this shard's
    # local expert count — the only honest source for e_local.
    from distributed_lion_tpu.ops.quant import maybe_dequant

    w_in = maybe_dequant(params["w_in"], x.dtype)
    w_out = maybe_dequant(params["w_out"], x.dtype)
    b_in = maybe_dequant(params["b_in"], x.dtype)
    b_out = maybe_dequant(params["b_out"], x.dtype)

    n, d = x.shape
    ep = 1 if axis_name is None else lax.psum(1, axis_name)
    e_local = w_in.shape[0]
    n_experts = e_local * ep
    # capacity_override: incremental decode calls with tiny per-step token
    # counts (n = batch) would otherwise compute cap ≈ 1 and systematically
    # drop colliding tokens that training/prefill (n = B*T) never drops —
    # the decode paths pass cap = n so no token is ever dropped at
    # generation time (models/gpt2._decode_mlp documents the trade).
    cap = (capacity_override if capacity_override is not None
           else capacity(n, n_experts, capacity_factor))

    # --- route (every device scores the full expert set) ---
    logits = x @ maybe_dequant(params["gate"], x.dtype)  # [N, E]
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)  # [N]
    gate_p = jnp.take_along_axis(probs, expert_idx[:, None], axis=-1)[:, 0]

    # Routing arithmetic stays in int32/float32 regardless of x.dtype:
    # bf16 can't represent integers > 256, so a bf16 cumsum would collide
    # ranks once an expert sees > 256 local tokens (tokens silently summed
    # into one dispatch slot). Only the final masks are cast to x.dtype.
    one_hot_i = jax.nn.one_hot(expert_idx, n_experts, dtype=jnp.int32)  # [N, E]
    if valid is not None:
        # dead lanes leave the assignment BEFORE the capacity cumsum: they
        # take no queue position, so real tokens' slots (and therefore
        # which real tokens overflow) are exactly the unpadded batch's
        one_hot_i = one_hot_i * valid.astype(jnp.int32)[:, None]
    pos = jnp.cumsum(one_hot_i, axis=0) * one_hot_i - 1  # slot in expert queue
    keep = (pos >= 0) & (pos < cap)
    slot = jax.nn.one_hot(pos.max(axis=-1), cap, dtype=x.dtype)  # [N, C]
    one_hot = one_hot_i.astype(x.dtype)
    mask = one_hot[:, :, None] * slot[:, None, :] * keep.max(-1)[:, None, None].astype(x.dtype)

    # --- load-balance aux loss (computed on pre-drop assignments) ---
    counts_f = one_hot_i.astype(jnp.float32).sum(axis=0)  # [E] real lanes
    if valid is None:
        n_lanes = jnp.float32(n)
        frac_probs = probs.mean(axis=0)
    else:
        # averages over the REAL lanes only — pads must not dilute the
        # load estimate (inference-only today, but the mask must not make
        # the auxiliary silently wrong if it is ever consumed)
        v32 = valid.astype(jnp.float32)
        n_lanes = v32.sum()
        frac_probs = (probs * v32[:, None]).sum(axis=0) \
            / jnp.maximum(n_lanes, 1.0)
    local_frac = counts_f / jnp.maximum(n_lanes, 1.0)
    if balance_tokens is not None:
        # the fed-in (global, possibly stale) load estimate replaces the
        # local one; gradients still flow through frac_probs only — the
        # token-count factor was never differentiable to begin with. An
        # all-zero tally (lane count 0) is the ring's cold-start sentinel:
        # until depth steps have launched there is no stale global load
        # yet, so the aux falls back to the fresh local fraction (every
        # real tally has lane count > 0 — a training batch is never empty)
        fed_frac = balance_tokens[:n_experts] \
            / jnp.maximum(balance_tokens[n_experts], 1.0)
        frac_tokens = jnp.where(balance_tokens[n_experts] > 0.0,
                                fed_frac, local_frac)
    elif balance_axis is not None:
        # synchronous global balance (--ep_dcn_pipeline 0): psum the raw
        # token tallies over the expert axis BEFORE forming the fraction —
        # a blocking collective in the forward, which is exactly what
        # depth 0 means. At axis size 1 the psums are identity, so this is
        # the local fraction bit for bit.
        frac_tokens = lax.psum(counts_f, balance_axis) \
            / jnp.maximum(lax.psum(n_lanes, balance_axis), 1.0)
    else:
        frac_tokens = local_frac
    aux = n_experts * jnp.sum(frac_tokens * frac_probs)

    tallies = None
    if return_tallies:
        tallies = lax.stop_gradient(jnp.concatenate(
            [counts_f, jnp.reshape(jnp.asarray(n_lanes, jnp.float32), (1,))]))

    stats = None
    if return_stats:
        counts = counts_f
        n_stats = n
        if stats_axis is not None:
            counts = lax.psum(counts, stats_axis)
            n_stats = n * lax.psum(1, stats_axis)
        if stats_lanes is not None:
            n_stats = stats_lanes
        budget = capacity(n_stats, n_experts, capacity_factor)
        kept = jnp.minimum(counts, jnp.float32(budget)).sum()
        stats = {
            "valid": counts.sum(),
            "kept": kept,
            "capacity_slots": jnp.float32(n_experts * budget),
        }

    # --- dispatch: [E, C, D] buffers, tokens in their expert's slots ---
    dispatch = jnp.einsum("nec,nd->ecd", mask, x)
    if axis_name is not None and ep > 1:
        # split the expert axis across shards, concat arrivals along
        # capacity: [E, C, D] -> [E_local, S*C, D] in ONE all_to_all
        dispatch = lax.all_to_all(
            dispatch, axis_name, split_axis=0, concat_axis=1, tiled=True
        )

    # --- expert FFN (batched over this shard's experts) ---
    if tp_axis is not None:
        # Megatron f-operator: identity forward, psum backward — each
        # tensor rank's partial input-cotangent (from its w_in shard)
        # completes here, so upstream sees the full gradient
        from distributed_lion_tpu.parallel.tensor_parallel import (
            copy_to_tp_region,
            reduce_from_tp_region,
        )

        dispatch = copy_to_tp_region(dispatch, tp_axis)
    h = jax.nn.gelu(
        jnp.einsum("ecd,edf->ecf", dispatch, w_in) + b_in[:, None, :]
    )
    out = jnp.einsum("ecf,efd->ecd", h, w_out)
    if tp_axis is not None:
        # g-operator: row-parallel partials psum to the full output; b_out
        # is replicated over tensor and must be added exactly once — AFTER
        # the psum (adding per rank would scale it by tp)
        out = reduce_from_tp_region(out, tp_axis)
    out = out + b_out[:, None, :]

    if axis_name is not None and ep > 1:
        # inverse: [E_local, S*C, D] -> [E, C, D] back on the token's shard
        out = lax.all_to_all(
            out, axis_name, split_axis=1, concat_axis=0, tiled=True
        )

    # --- combine: weight each token's slot by its gate probability ---
    y = jnp.einsum("nec,ecd->nd", mask * gate_p[:, None, None], out)
    if return_stats and return_tallies:
        return y, aux, stats, tallies
    if return_stats:
        return y, aux, stats
    if return_tallies:
        return y, aux, tallies
    return y, aux
