"""Vote collectives: the wire layer of Distributed Lion.

TPU-native replacement for the reference's only two collective calls —
``dist.get_world_size()`` and ``dist.all_gather`` of a packed sign tensor
(/root/reference/distributed_lion.py:80-81, 120-121) followed by a Python-side
``torch.mode`` vote (:33-43, :91). Here the vote itself is a collective:

- :func:`majority_vote_psum` — sum ±1 int8 votes with ``lax.psum``: the
  reduction happens *on the interconnect* (receive volume independent of
  world size), and ``sum > 0 ⇔ majority True``. The idiomatic ICI path.
- :func:`majority_vote_packed_allgather` — bit-pack votes to real uint8
  (1 bit/param/worker on the wire, 8× less than the reference's accidental
  int64 lanes) and ``lax.all_gather``, then popcount locally. The path for
  bandwidth-starved DCN edges, and byte-for-byte the wire format the
  reference *intended*.
- :func:`majority_vote_packed_a2a` — two-phase 1-bit vote: ``all_to_all``
  of packed ballot chunks (each worker tallies one chunk), then
  ``all_gather`` of the packed verdicts. ~2 bits/param received per worker
  **independent of world size** — the minimum-bandwidth path, and the wire
  to use when W is large enough that ``packed_allgather``'s W bits/param
  hurts.
- :func:`majority_vote_hier` (wire ``"hier:<g>"``) — two-level chunked vote
  for multi-host meshes: ballots reduce-scattered *inside* g-worker ICI
  subgroups (each member owns 1/g of the coordinates), then only the
  owners' bit-packed 1-bit verdict chunks cross the group boundary (the
  DCN leg: (W/g − 1)/g bits/param). Majority-of-majorities semantics;
  degenerates to the flat vote at g=1 and g=W.

Every wire also has a **bucketed** form (:func:`vote_total_buckets` /
:func:`vote_total_bucketed` / :func:`majority_vote_bucketed`): the ballot is
split at ``codec.bucket_bounds``' wire-aligned boundaries and each chunk is
voted with its OWN collective. Elections are elementwise, so the bucketed
result is bit-identical to the one-shot vote and the per-bucket byte
accounting sums to exactly the unbucketed totals; what bucketing buys is
*pipelining* — the optimizer overlaps bucket k's collective with bucket
k−1's fused apply (optim.distributed_lion).

Both must be called inside ``jax.shard_map`` (or any context where
``axis_name`` is bound). Tie rule: ties vote −1, matching ``torch.mode``'s
smaller-value behavior on even worlds (SURVEY §2.3 step 6).
"""

from __future__ import annotations

import threading
import time

import jax
import jax.numpy as jnp
from jax import lax

from distributed_lion_tpu.ops.codec import (
    a2a_chunk_bytes,
    bucket_bounds,
    pack_signs,
    parse_wire,
    unpack_signs,
)
from distributed_lion_tpu.train import resilience


class WireTally:
    """Measured wire counters, recorded at TRACE time from the operands the
    vote collectives are actually handed.

    Bytes on the wire are a pure function of operand shapes, and the call
    sites below execute exactly once per compiled step — so a one-trace
    capture (``telemetry.measure_step_wire`` wraps ``jax.eval_shape``)
    yields the exact per-step ledger with zero runtime overhead. Each entry
    is ``(leg, received_bytes)`` per collective launch: one entry per bucket
    of the bucketed wire, per phase of the two-phase wires, per ring of the
    hier wire ('dcn' for its cross-group leg, 'ici' for everything else).

    The per-leg byte conventions deliberately mirror
    ``ops.codec._recv_bytes`` (bytes RECEIVED per worker) — what makes the
    cross-check against ``profiling.comm_report`` non-circular is that the
    values here come from the LIVE padded/chunked array shapes at the call
    sites, so any drift between the accounting's assumptions (alignment,
    chunk padding, per-bucket splits, call counts) and what the collectives
    actually move shows up as a nonzero ``comm_drift_bytes`` metric.
    Recording is inert (None sink) outside a capture, and W = 1 records
    nothing: every wire short-circuits on a 1-device axis.
    """

    def __init__(self):
        self._entries: list | None = None

    class _Capture:
        def __init__(self, tally: "WireTally"):
            self._tally = tally

        def __enter__(self):
            self._prev = self._tally._entries
            self._tally._entries = []
            return self._tally._entries

        def __exit__(self, *exc):
            self._tally._entries = self._prev
            return False

    def capture(self) -> "WireTally._Capture":
        return WireTally._Capture(self)

    def record(self, leg: str, nbytes: int) -> None:
        if self._entries is not None and nbytes > 0:
            self._entries.append((leg, int(nbytes)))


WIRE_TALLY = WireTally()


class DcnWaitTally:
    """Measured residual waits of the emulated DCN link (the ``dcn_delay``
    fault, train/resilience registry): per step key, the MAX wait any
    device/bucket paid at the consume gate — devices run concurrently, so
    the max is the step's critical-path exposure to the link's latency.
    Sub-delay values mean the cross-step pipeline (``--dcn_pipeline_depth``)
    hid part of the round trip behind compute; the trainer drains this at
    log cadence into the ``dcn_wait_s`` metric and bench_dcn derives its
    measured overlap fraction from it. Host-side only — the traced step
    never reads it."""

    def __init__(self):
        self._lock = threading.Lock()
        self._waits: dict = {}

    def add(self, key, wait_s: float) -> None:
        with self._lock:
            self._waits[key] = max(self._waits.get(key, 0.0), float(wait_s))

    def pop(self) -> dict:
        """{step key: max wait seconds} accumulated since the last pop."""
        with self._lock:
            out, self._waits = self._waits, {}
            return out


DCN_WAIT = DcnWaitTally()

# launch wall-clock stamps of the emulated DCN link, keyed by the optimizer
# step count the launching program carried (first device to stamp a step
# wins; pruned as consumes pass)
_DCN_STAMPS: dict = {}
_DCN_STAMPS_LOCK = threading.Lock()


def dcn_link_reset() -> None:
    """Reset the emulated DCN link between runs: stamps are keyed by the
    optimizer step count, so a fresh run re-using counts 0..N would
    otherwise find a previous run's long-expired stamps and pay no latency
    at all. Benches and tests call this before every measured leg."""
    with _DCN_STAMPS_LOCK:
        _DCN_STAMPS.clear()
    DCN_WAIT.pop()


def _dcn_host_launch(slot, count, delay_s):
    """Host half of the launch gate: stamp 'the transfer for step `count`
    started now'. Identity on the data."""
    key = int(count)
    with _DCN_STAMPS_LOCK:
        _DCN_STAMPS.setdefault(key, time.monotonic())
        for k in [k for k in _DCN_STAMPS if k < key - 64]:
            del _DCN_STAMPS[k]
    return slot

def _dcn_host_consume(slot, count, delay_s, depth):
    """Host half of the consume gate: block until the transfer launched at
    step ``count − depth`` has been on the (emulated) link for ``delay_s``
    seconds. The wall clock already spent by the intervening steps counts
    toward the deadline — that is exactly what cross-step pipelining buys —
    so the residual wait recorded into DCN_WAIT measures the UNHIDDEN part
    of the round trip. Identity on the data."""
    key = int(count) - depth
    if key >= 0:
        with _DCN_STAMPS_LOCK:
            t0 = _DCN_STAMPS.get(key)
        if t0 is not None:
            rem = t0 + delay_s - time.monotonic()
            if rem > 0:
                time.sleep(rem)
            DCN_WAIT.add(key, max(rem, 0.0))
    return slot


def _dcn_gate_launch(slot: jnp.ndarray, count):
    """Trace-time hook of the ``dcn_delay`` fault on the level-2 launch: a
    no-op unless the fault is armed AT TRACE TIME (the unarmed step's jaxpr
    carries zero host callbacks — the trace_check contract). With no step
    count threaded (direct majority_vote_* callers) the link degrades to a
    synchronous sleep at the consume gate."""
    delay = resilience.fault("dcn_delay")
    if not delay or count is None:
        return slot
    from functools import partial as _partial

    # fault-injection-only path: the callback exists to EMULATE a slow DCN
    # link on CPU and is never traced in production steps
    return jax.pure_callback(  # graft: disable=DLT003
        _partial(_dcn_host_launch, delay_s=float(delay)),
        jax.ShapeDtypeStruct(slot.shape, slot.dtype), slot, count)


def _dcn_gate_consume(slot: jnp.ndarray, count, depth: int, token=None):
    """Trace-time hook of the ``dcn_delay`` fault on the level-2 consume.
    ``token`` (any small array computed from THIS step's launch) pins the
    gate behind the launch in XLA's serial CPU schedule, so the emulated
    gap between stamp and consume is the real ``depth`` steps of compute —
    without it XLA:CPU may hoist the wait to the start of the program and
    fake a synchronous link. No-op (and dependency-free) unless the fault
    is armed at trace time."""
    delay = resilience.fault("dcn_delay")
    if not delay:
        return slot
    from functools import partial as _partial

    if count is None:
        # no step key: synchronous-link fallback — sleep the full delay
        def _sync(slot_h):
            time.sleep(float(delay))
            DCN_WAIT.add(None, float(delay))
            return slot_h

        return jax.pure_callback(  # graft: disable=DLT003
            _sync, jax.ShapeDtypeStruct(slot.shape, slot.dtype), slot)
    args = (slot, count) if token is None else (slot, count, token)

    def _consume(slot_h, count_h, *_tok):
        return _dcn_host_consume(slot_h, count_h, float(delay), int(depth))

    return jax.pure_callback(  # graft: disable=DLT003
        _consume, jax.ShapeDtypeStruct(slot.shape, slot.dtype), *args)


def axis_size(axis_name: str) -> int:
    """Static size of a bound mesh axis (the reference's world_size,
    distributed_lion.py:80)."""
    return lax.psum(1, axis_name)


def vote_total(vote_pos: jnp.ndarray, axis_name: str, wire: str,
               alive=None, count=None) -> jnp.ndarray:
    """The vote reduction over workers. Every wire satisfies the contract
    callers rely on — ``total > 0`` ⇔ majority True, ``total ≤ 0`` ⇔ elect −1
    (ties → −1, the torch.mode smaller-value rule) — but only ``sign_psum``
    and ``packed_allgather`` return the exact tally Σ ±1 ballots in [-W, W];
    ``packed_a2a`` reduces at the chunk owner and returns the elected sign as
    a ±1 proxy (magnitude information never crosses the wire — that is the
    point of the two-phase format). Do not consume the magnitude for
    vote-margin metrics without excluding the a2a wire. Single source of
    truth for the XLA and Pallas optimizer paths and both ``majority_vote_*``
    views.

    ``alive`` (optional ``[W]`` bool, replicated — the vote guard's health
    mask) turns every wire into a **masked election**: workers with
    ``alive == False`` abstain — their ballots are zeroed out of the tally
    and the majority threshold shrinks to the healthy quorum (Σ alive), so
    ``total > 0`` still means "strict majority of the HEALTHY voters" with
    ties electing −1. With ``alive`` all-True the masked election is
    bit-identical to ``alive=None`` for every wire (pinned by
    tests/test_vote_guard.py) — the guard's all-healthy contract.

    ``count`` (optional replicated int32 scalar — the optimizer step count)
    is consumed ONLY by the ``dcn_delay`` fault's link emulator on the hier
    wire; it never enters the election math.
    """
    w = axis_size(axis_name)
    kind, group = parse_wire(wire)  # raises on unknown formats
    if kind == "sign_psum":
        # ±1 in int8 keeps the wire at 1 byte/param; XLA accumulates int8
        # exactly for |sum| ≤ 127, so promote only for large worlds.
        acc = jnp.int8 if w <= 127 else jnp.int32
        ballots = jnp.where(vote_pos, 1, -1).astype(acc)
        if alive is not None:
            # an abstainer ships 0-ballots: it drops out of the on-fabric
            # sum AND out of the implicit threshold (Σ±1 of the healthy)
            own = alive[lax.axis_index(axis_name)]
            ballots = jnp.where(own, ballots, jnp.zeros_like(ballots))
        if w > 1:  # ring all-reduce: received ≈ the tensor once, on-fabric
            WIRE_TALLY.record("ici", ballots.size * ballots.dtype.itemsize)
        return lax.psum(ballots, axis_name)
    if kind == "packed_allgather":
        # The reference's pack → all_gather → unpack → vote pipeline
        # (distributed_lion.py:71-91) with a true-uint8 wire format;
        # vote_pos must be 1-D (callers vote on a flattened pytree).
        packed = pack_signs(vote_pos)                  # [ceil(n/8)] uint8
        if w > 1:
            WIRE_TALLY.record("ici", w * packed.size)
        gathered = lax.all_gather(packed, axis_name)   # [W, ceil(n/8)] uint8
        bits = unpack_signs(gathered.reshape(-1), (w, gathered.shape[1] * 8))
        if alive is not None:
            # every worker holds the full ballot matrix here, so masking is
            # a row weighting: count over healthy rows, threshold = quorum
            weights = alive.astype(jnp.int32)
            count = (bits.astype(jnp.int32)
                     * weights[:, None]).sum(0)[: vote_pos.shape[0]]
            return count * 2 - weights.sum()
        count = bits.astype(jnp.int32).sum(0)[: vote_pos.shape[0]]
        return count * 2 - w
    if kind == "packed_a2a":
        # Two-phase vote. The verdict (not the tally) crosses the wire in
        # phase 2, so the returned "total" is the ±1 proxy of the elected
        # sign — every caller only tests ``total > 0``, and the tie rule
        # (tie → −1) is applied at the tallying worker in phase 1.
        return jnp.where(_packed_a2a_elect(vote_pos, axis_name, w, alive),
                         1, -1)
    # kind == "hier": per-worker tallies never leave the ICI subgroup, so
    # (like packed_a2a) only a ±1 proxy of the elected sign is available.
    return jnp.where(_hier_elect(vote_pos, axis_name, w, group, alive,
                                 count), 1, -1)


def vote_total_buckets(
    vote_pos: jnp.ndarray, axis_name: str, wire: str, vote_buckets: int,
    alive=None, count=None,
) -> list[jnp.ndarray]:
    """The bucketed wire: one *independent* collective per contiguous ballot
    chunk (codec.bucket_bounds — the same boundaries the byte accounting
    sums over), returned per bucket so a caller can interleave each bucket's
    apply with the next bucket's collective (the optimizer's software
    pipeline). Elections are elementwise per coordinate, so the
    concatenation of the bucket results is bit-identical to the one-shot
    ``vote_total`` for EVERY wire — bucketing changes when bytes move,
    never what is elected (tests/test_vote_buckets.py pins this).
    """
    w = axis_size(axis_name)
    bounds = bucket_bounds(vote_pos.shape[0], vote_buckets, w, wire)
    return [
        vote_total(lax.slice(vote_pos, (start,), (start + size,)),
                   axis_name, wire, alive, count)
        for start, size in bounds
    ]


def vote_total_bucketed(
    vote_pos: jnp.ndarray, axis_name: str, wire: str, vote_buckets: int,
    alive=None, count=None,
) -> jnp.ndarray:
    """Concatenated bucketed vote — same contract (and bit pattern) as
    :func:`vote_total`, but issued as ``vote_buckets`` independent
    collectives XLA's async scheduler can overlap with unrelated compute."""
    if vote_buckets <= 1:
        return vote_total(vote_pos, axis_name, wire, alive, count)
    totals = vote_total_buckets(vote_pos, axis_name, wire, vote_buckets,
                                alive, count)
    return totals[0] if len(totals) == 1 else jnp.concatenate(totals)


def majority_vote_bucketed(
    vote_pos: jnp.ndarray, axis_name: str, wire: str, vote_buckets: int,
    alive=None,
) -> jnp.ndarray:
    """Elected bool votes via the bucketed wire; bit-identical to
    :func:`majority_vote` for every wire format."""
    return vote_total_bucketed(vote_pos, axis_name, wire, vote_buckets,
                               alive) > 0


def _packed_a2a_elect(vote_pos: jnp.ndarray, axis_name: str, w: int,
                      alive=None) -> jnp.ndarray:
    """Elected bool votes via all_to_all of 1-bit ballots + all_gather of
    1-bit verdicts (~2 bits/param received per worker, W-independent)."""
    n = vote_pos.shape[0]
    chunk = a2a_chunk_bytes(n, w)  # uint8 bytes per worker-chunk
    pad = chunk * 8 * w - n
    padded = jnp.concatenate([vote_pos, jnp.zeros((pad,), vote_pos.dtype)]) if pad else vote_pos
    packed = pack_signs(padded).reshape(w, chunk)  # row j = my ballot for chunk j
    if w > 1:  # phase 1: (W−1) peers each send me their copy of my chunk
        WIRE_TALLY.record("ici", (w - 1) * chunk)
    # phase 1: worker j receives every worker's row j → [W, chunk]
    arrived = lax.all_to_all(packed, axis_name, split_axis=0, concat_axis=0, tiled=True)
    bits = unpack_signs(arrived.reshape(-1), (w, chunk * 8))
    if alive is not None:
        # the chunk owner sees every worker's row, so the masked tally is a
        # row weighting; the threshold shrinks to the healthy quorum
        weights = alive.astype(jnp.int32)
        count = (bits.astype(jnp.int32) * weights[:, None]).sum(0)
        verdict = count * 2 > weights.sum()            # tie → False (−1)
    else:
        count = bits.astype(jnp.int32).sum(0)          # per-bit True tally
        verdict = count * 2 > w                        # tie → False (−1)
    if w > 1:  # phase 2: (W−1) peers each send me their chunk's verdict
        WIRE_TALLY.record("ici", (w - 1) * chunk)
    # phase 2: broadcast my chunk's packed verdict to everyone
    gathered = lax.all_gather(pack_signs(verdict), axis_name)  # [W, chunk]
    return unpack_signs(gathered.reshape(-1), (n,))


def _intra_perm(w: int, g: int) -> list:
    """The intra-group ring permutation (member i → member i+1 mod g)."""
    return [(s, (s // g) * g + ((s % g) + 1) % g) for s in range(w)]


def hier_launch(vote_pos: jnp.ndarray, axis_name: str, w: int,
                group_size: int, alive=None, count=None) -> jnp.ndarray:
    """Phases 1+2 of the hier election — everything UP TO the point where
    the level-2 (DCN) traffic has arrived: intra-group ballot
    reduce-scatter (ICI), then the cross-group ring of the owners' packed
    level-1 verdict chunks, gathered per source group instead of folded
    into a count so the consume half can re-judge group health later.

    Returns the flat uint8 *slot segment* for this ballot chunk
    (codec.hier_chunk_slot_bytes): a ``[n_groups]`` launch-time group-alive
    byte mask followed by the ``[n_groups, chunk/8]`` packed verdict stack
    for this worker's OWNED 1/g chunk of coordinates. Per-worker divergent
    (each member owns a different chunk id) — under cross-step pipelining
    (``--dcn_pipeline_depth``) the slot rides ``LionState.dcn_ring`` for
    ``d`` steps before :func:`hier_consume` turns it into elected bits; the
    synchronous wire (depth 0) consumes it immediately. In the jaxpr the
    slot's only consumer at depth ≥ 1 is the state output, which is what
    lets XLA's async collective scheduling (and ``lax.scan`` over fused
    steps) overlap the DCN ring with the following steps' compute.

    ``count`` is the optimizer step count, used ONLY by the ``dcn_delay``
    fault's link emulator (train/resilience registry) to stamp the
    transfer's launch wall time.
    """
    if w % group_size:
        raise ValueError(
            f"hier wire: group size {group_size} does not divide world {w}"
        )
    g = group_size
    n_groups = w // g
    n = vote_pos.shape[0]
    acc = jnp.int8 if g <= 127 else jnp.int32
    chunk = 8 * a2a_chunk_bytes(n, g)  # byte-aligned coords per member
    pad = g * chunk - n
    flat = (jnp.concatenate([vote_pos, jnp.zeros((pad,), vote_pos.dtype)])
            if pad else vote_pos)
    buf = jnp.where(flat, 1, -1).astype(acc).reshape(g, chunk)
    group_alive = None
    if alive is not None:
        # level 1: my ballots abstain from the reduce-scatter when I am
        # quarantined (I still relay partial sums — the ring needs me)
        own_alive = alive[lax.axis_index(axis_name)]
        buf = jnp.where(own_alive, buf, jnp.zeros_like(buf))
        group_alive = alive.reshape(w // g, g).any(axis=1)
    idx = lax.axis_index(axis_name) % g  # my position within the group
    intra_perm = _intra_perm(w, g)

    # phase 1 — reduce-scatter (lax.scan ring, one traced hop): at hop t I
    # pass on the partial sum of chunk (idx − t) mod g and fold my ballots
    # into the arriving partial, ending with the full tally of owned chunk
    # (idx + 1) mod g.
    def _rs_hop(msg, t):
        msg = lax.ppermute(msg, axis_name, intra_perm)
        recv = (idx - t - 1) % g
        return msg + lax.dynamic_slice(buf, (recv, 0), (1, chunk))[0], None

    msg = lax.dynamic_slice(buf, (idx % g, 0), (1, chunk))[0]
    if g > 1 and w > 1:  # leg 1: (g−1) ballot-chunk hops at the acc width
        WIRE_TALLY.record("ici", (g - 1) * chunk * jnp.dtype(acc).itemsize)
    if g > 1:
        msg, _ = lax.scan(_rs_hop, msg, jnp.arange(g - 1))
    verdict_own = msg > 0  # subgroup tie → −1, for my owned coords

    # phase 2 — cross-group ring of packed verdicts, GATHERED per source
    # group: member i of every group owns the SAME chunk id, so a ring over
    # same-position peers delivers every group's verdict for my coords. The
    # hop-t packet originated at group (my_group − t − 1) mod G; storing
    # arrivals by source (instead of folding them into a count here) moves
    # the health gating and the majority threshold to hier_consume, where
    # the CURRENT alive mask is known — that is what keeps a group
    # quarantined mid-flight from poisoning a stale tally.
    cross_perm = [
        (s, ((s // g + 1) % n_groups) * g + s % g) for s in range(w)
    ]
    my_group = lax.axis_index(axis_name) // g
    packed_own = pack_signs(verdict_own)  # [chunk/8] uint8
    stack = jnp.zeros((n_groups, chunk // 8), jnp.uint8)
    stack = lax.dynamic_update_slice(stack, packed_own[None], (my_group, 0))

    def _cross_hop(carry, t):
        stack, rot = carry
        rot = lax.ppermute(rot, axis_name, cross_perm)
        src = (my_group - t - 1) % n_groups
        stack = lax.dynamic_update_slice(stack, rot[None], (src, 0))
        return (stack, rot), None

    if n_groups > 1 and w > 1:  # leg 2: the ONLY cross-group (DCN) traffic
        WIRE_TALLY.record("dcn", (n_groups - 1) * (chunk // 8))
    if n_groups > 1:
        (stack, _), _ = lax.scan(_cross_hop, (stack, packed_own),
                                 jnp.arange(n_groups - 1))
    mask_row = (group_alive.astype(jnp.uint8) if group_alive is not None
                else jnp.ones((n_groups,), jnp.uint8))
    slot = jnp.concatenate([mask_row, stack.reshape(-1)])
    return _dcn_gate_launch(slot, count)


def hier_consume(slot: jnp.ndarray, n: int, axis_name: str, w: int,
                 group_size: int, alive=None, count=None, depth: int = 0,
                 token=None) -> jnp.ndarray:
    """Phase 3 of the hier election, fed by a (possibly ``depth`` steps
    stale) :func:`hier_launch` slot: gate each source group's verdict chunk
    by its health at BOTH ends of the flight (the slot's launch-time mask
    AND the current ``alive`` — a group fully quarantined mid-flight
    abstains from the stale tally), take the majority over the surviving
    quorum (ties → −1, both levels), then reassemble the full elected
    vector with the intra-group (ICI) ring all-gather of the packed elected
    chunks. Elections are replicated: every worker combines the same
    per-group verdicts under the same masks.

    A worker quarantined mid-flight inside a still-healthy group keeps its
    launch-time level-1 contribution — the per-worker ballots were folded
    into the group verdict before the guard could know, and only group-
    granular abstention is possible at level 2 (documented staleness
    semantics, ARCHITECTURE 'DCN overlap').

    ``count``/``depth``/``token`` feed the ``dcn_delay`` link emulator only
    (see :func:`_dcn_gate_consume`).
    """
    g = group_size
    n_groups = w // g
    chunk = 8 * a2a_chunk_bytes(n, g)
    slot = _dcn_gate_consume(slot, count, depth, token)
    launch_mask = slot[:n_groups] > 0
    stack = slot[n_groups:].reshape(n_groups, chunk // 8)
    effective = launch_mask
    if alive is not None:
        effective = launch_mask & alive.reshape(n_groups, g).any(axis=1)
    bits = unpack_signs(stack.reshape(-1), (n_groups, chunk))
    contrib = bits.astype(jnp.int32) * effective.astype(jnp.int32)[:, None]
    counts = contrib.sum(0)  # [chunk] per-coordinate +1-verdict tally
    elected_own = counts * 2 > effective.astype(jnp.int32).sum()

    # phase 3 — intra-group all-gather of the packed elected chunks.
    idx = lax.axis_index(axis_name) % g
    own = (idx + 1) % g
    intra_perm = _intra_perm(w, g)

    def _ag_hop(carry, t):
        out, rot = carry
        rot = lax.ppermute(rot, axis_name, intra_perm)
        # the hop-t packet originated at the member t+1 behind me, which
        # owns chunk (idx − t − 1 + 1) mod g
        out = lax.dynamic_update_slice(out, rot[None], ((idx - t) % g, 0))
        return (out, rot), None

    packed_own = pack_signs(elected_own)  # [chunk/8] uint8
    out = jnp.zeros((g, chunk // 8), jnp.uint8)
    out = lax.dynamic_update_slice(out, packed_own[None], (own, 0))
    if g > 1 and w > 1:  # leg 3: (g−1) packed elected-chunk hops
        WIRE_TALLY.record("ici", (g - 1) * (chunk // 8))
    if g > 1:
        (out, _), _ = lax.scan(_ag_hop, (out, packed_own), jnp.arange(g - 1))
    return unpack_signs(out.reshape(-1), (g * chunk,))[:n]


def _hier_elect(
    vote_pos: jnp.ndarray, axis_name: str, w: int, group_size: int,
    alive=None, count=None,
) -> jnp.ndarray:
    """Hierarchical majority-of-majorities vote over a two-level fabric.

    Workers [k*group_size, (k+1)*group_size) form subgroup k — on a
    multi-host mesh, construct the data axis so that a subgroup is one
    ICI-connected host/slice (jax orders devices process-major, so
    consecutive axis indices share a host by default). Member i of each
    subgroup *owns* 1/g of the coordinates: ballots are reduce-scattered
    inside the subgroup, only the owners' bit-packed verdict chunks ride the
    cross-group (DCN) ring, and the elected bits are re-assembled by an
    intra-group all-gather — see the leg-by-leg comment below and the
    mirrored byte accounting in ops/codec.wire_bytes_per_param.

    Tie rule at BOTH levels: ties elect −1 (torch.mode's smaller-value
    behavior, SURVEY §2.3 step 6). Majority-of-majorities can differ from
    the flat majority (e.g. W=8 g=4, ballots [+,+,−,−][+,+,+,+] → group
    verdicts [tie→−, +] → group-level tie → −1, where the flat 6−2 vote
    elects +1); it degenerates to the flat vote at g=1 and g=W. Every worker
    applies the same elected bits, so replicas stay bit-identical.

    Masked election (``alive``): a quarantined member abstains at level 1
    (its ±1 ballots are zeroed out of the subgroup tally, so the subgroup
    verdict is the majority of its HEALTHY members), and a subgroup with
    zero healthy members abstains at level 2 (its verdict chunk is dropped
    from the cross-group count and the group-level threshold shrinks to the
    number of groups that still hold a healthy member). A quarantined worker
    still computes/forwards ring traffic — elections stay replicated; only
    its ballot's weight is gone.

    All three legs run as ppermute rings under ``lax.scan`` (subgrouped
    psum/all_gather via axis_index_groups is not supported under
    shard_map), chunked so no leg ever moves the full ballot vector more
    than once:

    1. intra-group reduce-scatter — (g−1)·n/g ballot bytes, ICI;
    2. cross-group ring of the owners' bit-packed verdict chunks — the only
       traffic that crosses the group boundary ((W/g − 1)·n/(8g) bytes DCN);
    3. intra-group ring all-gather of the packed ELECTED chunks
       ((g−1)·n/(8g) ≈ n/8 bytes, ICI).

    Byte accounting in ops/codec.wire_bytes_per_param mirrors exactly this.

    Since the cross-step DCN pipeline (``--dcn_pipeline_depth``,
    optim.distributed_lion) the implementation is the launch/consume split:
    phases 1+2 live in :func:`hier_launch` (producing the per-group packed
    verdict slot), the masked threshold + phase 3 in :func:`hier_consume`.
    This synchronous composition — consume the slot in the same step it was
    launched — is the depth-0 wire, bit-identical to the pre-split election
    (integer tallies summed in a different order; pinned by
    tests/test_dcn_overlap.py against an independent reference).
    """
    slot = hier_launch(vote_pos, axis_name, w, group_size, alive, count)
    return hier_consume(slot, vote_pos.shape[0], axis_name, w, group_size,
                        alive, count, depth=0)


def majority_vote_hier(
    vote_pos: jnp.ndarray, axis_name: str, group_size: int
) -> jnp.ndarray:
    """Two-level chunked majority vote: ICI-subgroup ballot reduce-scatter,
    cross-group packed-verdict ring, intra-group elected-bits all-gather;
    ties → False (−1) at both levels."""
    return _hier_elect(vote_pos, axis_name, axis_size(axis_name), group_size)


def majority_vote_psum(vote_pos: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Majority vote via an on-fabric sum of ±1 votes; ties → False (−1)."""
    return vote_total(vote_pos, axis_name, "sign_psum") > 0


def majority_vote_packed_allgather(vote_pos: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Majority vote via 1-bit packed all-gather + local popcount."""
    return vote_total(vote_pos, axis_name, "packed_allgather") > 0


def majority_vote_packed_a2a(vote_pos: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Majority vote via two-phase 1-bit all_to_all + all_gather; ties → False."""
    return _packed_a2a_elect(vote_pos, axis_name, axis_size(axis_name))


def majority_vote(vote_pos: jnp.ndarray, axis_name: str, wire: str,
                  alive=None) -> jnp.ndarray:
    """Elected bool votes for any wire format (``total > 0`` ⇔ majority True;
    the ±1-proxy wires compute the election directly — XLA folds the
    round-trip). ``alive`` masks quarantined workers out of the tally (the
    vote guard's masked election — see :func:`vote_total`)."""
    return vote_total(vote_pos, axis_name, wire, alive) > 0


def masked_majority_vote_psum(
    vote_pos: jnp.ndarray, alive: jnp.ndarray, axis_name: str
) -> jnp.ndarray:
    """Drop-out-robust vote: workers with ``alive == False`` abstain.

    The reference README claims robustness to worker drop-out but its fixed
    world-size ``all_gather`` would hang (SURVEY §5, failure detection). Here
    drop-out is an algorithm-level feature: dead workers contribute 0 ballots
    and the majority is taken over the survivors.
    """
    ballots = jnp.where(vote_pos, 1, -1).astype(jnp.int32) * alive.astype(jnp.int32)
    total = lax.psum(ballots, axis_name)
    return total > 0


def unpack_gathered(gathered: jnp.ndarray, n: int) -> jnp.ndarray:
    """[W, ceil(n/8)] uint8 → [W, n] bool (per-worker ballots, for tests)."""
    return jnp.stack([unpack_signs(row, (n,)) for row in gathered])
