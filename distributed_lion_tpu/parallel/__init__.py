from distributed_lion_tpu.parallel.mesh import (
    make_mesh,
    data_axis_size,
    replicated,
    data_sharded,
)
from distributed_lion_tpu.parallel.collectives import (
    majority_vote_psum,
    majority_vote_packed_allgather,
)
