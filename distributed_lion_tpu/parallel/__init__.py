from distributed_lion_tpu.parallel.mesh import (
    make_mesh,
    data_axis_size,
    replicated,
    data_sharded,
)
from distributed_lion_tpu.parallel.collectives import (
    majority_vote_psum,
    majority_vote_packed_allgather,
)
from distributed_lion_tpu.parallel.pipeline import (
    pipeline_apply,
    stack_stage_params,
    unstack_stage_params,
    from_last_stage,
)
from distributed_lion_tpu.parallel.expert import moe_init, moe_ffn, moe_param_specs
