"""Megatron-style tensor-parallel sharding rules for the model families.

Net-new vs the reference (data parallelism only — SURVEY §2.7): attention
qkv / MLP up-projections are column-parallel (sharded on the output dim),
attention proj / MLP down-projections are row-parallel (sharded on the input
dim, partial products ``psum``-reduced over the ``tensor`` axis inside the
model, see models/gpt2._attention / models/llama TP paths). LayerNorms,
embeddings and the LM head stay replicated.

The spec trees returned here drive shard_map in/out specs AND device_put
layouts; the optimizer is oblivious — its ``data``-axis vote runs
independently on each tensor shard.

:func:`copy_to_tp_region` is Megatron's *f* operator — identity forward,
``psum`` over the tensor axis backward. The models insert it where replicated
activations enter a column-parallel region (attention/MLP entry): each tensor
rank's backward only carries its own heads'/columns' contribution to dx, so
without the boundary psum the gradients of everything upstream (layer norms,
embeddings) would be per-rank partials — and per-rank momenta/votes would
silently drift replicated parameters apart. (Under ``shard_map`` with
``check_vma=False`` JAX does not insert this reduction automatically.)

**The f/g pairing makes TP gradients exact.** jax.grad runs INSIDE the
train step's shard_map, where the transpose of a raw ``lax.psum`` is
``psum`` — correct for arbitrary per-rank cotangents, but an over-count by
W when the reduced value is consumed replicated (the cotangent is already
the one true dL/dy on every rank). Every region therefore uses the paired
custom-vjp operators: :func:`copy_to_tp_region` (*f*: identity fwd, psum
bwd) at entry and :func:`reduce_from_tp_region` (*g*: psum fwd, identity
bwd) at exit — and with both in place the TP gradient of every leaf equals
the pure-dp gradient up to float noise (measured median ratio 1.0000
per leaf; raw psum exits instead produced depth-dependent mixed W^k
factors with sign flips).
"""

from __future__ import annotations

from functools import partial

import jax
from jax import lax
from jax.sharding import PartitionSpec as P

from distributed_lion_tpu.parallel.mesh import TENSOR_AXIS


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_tp_region(x, axis_name: str):
    """Identity forward; backward ``psum``s the cotangent over ``axis_name``."""
    return x


def _copy_fwd(x, axis_name):
    return x, None


def _copy_bwd(axis_name, _, g):
    return (lax.psum(g, axis_name),)


copy_to_tp_region.defvjp(_copy_fwd, _copy_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_from_tp_region(x, axis_name: str):
    """Megatron's *g* operator: ``psum`` forward, identity backward.

    The exit reduce of every row-parallel region. jax's default transpose of
    ``lax.psum`` is ``psum`` (correct for arbitrary per-rank cotangents),
    but here the reduced value is consumed REPLICATED downstream — the
    cotangent arriving at the output is the one true dL/dy, identical on
    every rank — so the exact adjoint is the identity: each rank's partial
    receives dL/dy once. Using raw ``psum`` instead multiplies the
    cotangent by W at every crossing, and residual paths crossing different
    numbers of regions then mix DIFFERENT powers of W into one leaf's
    gradient (measurably flipping signs vs the pure-dp gradient).
    """
    return lax.psum(x, axis_name)


def _reduce_fwd(x, axis_name):
    return lax.psum(x, axis_name), None


def _reduce_bwd(axis_name, _, g):
    return (g,)


reduce_from_tp_region.defvjp(_reduce_fwd, _reduce_bwd)


def spec_uses_axis(spec, axis_name: str) -> bool:
    """True if a PartitionSpec shards any dim over ``axis_name``."""
    return any(
        p == axis_name or (isinstance(p, (tuple, list)) and axis_name in p)
        for p in spec
    )


def gpt2_param_specs(cfg, vocab_parallel: bool = False) -> dict:
    """PartitionSpec pytree matching models/gpt2.gpt2_init's structure.

    ``vocab_parallel`` shards the tied embedding's vocab ROWS over the
    tensor axis: the input side runs Megatron's VocabParallelEmbedding
    (models/gpt2.vocab_parallel_embed, masked partial lookup + psum) and
    the loss side runs vocab-parallel CE on ``wte_shard.T``
    (ops/xent.tp_vocab_xent) — the full [V, d] table never exists on one
    device."""
    col = P(None, TENSOR_AXIS)   # column-parallel weight [d, k*d]
    row = P(TENSOR_AXIS, None)   # row-parallel weight [k*d, d]
    rep1 = P()
    ln = {"scale": rep1, "bias": rep1}
    block = {
        "ln_1": ln,
        "attn": {
            "qkv": P(None, None, TENSOR_AXIS),
            "qkv_b": P(None, TENSOR_AXIS),
            "proj": row,
            "proj_b": rep1,
        },
        "ln_2": ln,
        "mlp": {"fc": col, "fc_b": P(TENSOR_AXIS), "proj": row, "proj_b": rep1},
    }
    return {
        "wte": P(TENSOR_AXIS, None) if vocab_parallel else rep1,
        "wpe": rep1,
        "ln_f": ln,
        "blocks": [block] * cfg.n_layer,
    }


def llama_param_specs(cfg, vocab_parallel: bool = False) -> dict:
    """PartitionSpec pytree matching models/llama.llama_init's structure.

    ``vocab_parallel`` shards the lm_head's vocab columns over the tensor
    axis (Megatron vocab-parallel CE, ops/xent.tp_vocab_xent): V/tp logit
    columns per rank instead of a replicated [d, V] head — the memory and
    FLOPs win that matters at 128k-class vocabularies."""
    col = P(None, TENSOR_AXIS)
    row = P(TENSOR_AXIS, None)
    rep = P()
    block = {
        "ln_attn": {"scale": rep},
        "attn": {"wq": col, "wk": col, "wv": col, "wo": row},
        "ln_mlp": {"scale": rep},
        "mlp": {"w_gate": col, "w_up": col, "w_down": row},
    }
    return {
        "wte": rep,
        "lm_head": col if vocab_parallel else rep,
        "ln_f": {"scale": rep},
        "blocks": [block] * cfg.n_layer,
    }


def validate_tp(cfg, tp: int, model: str = "gpt2") -> None:
    if model == "gpt2":
        if cfg.n_head % tp:
            raise ValueError(f"n_head {cfg.n_head} not divisible by tensor axis {tp}")
        if (4 * cfg.d_model) % tp:
            raise ValueError(f"d_ff {4 * cfg.d_model} not divisible by tensor axis {tp}")
    else:
        if cfg.n_head % tp or cfg.n_kv_head % tp:
            raise ValueError(
                f"heads ({cfg.n_head}/{cfg.n_kv_head}kv) not divisible by tensor axis {tp}"
            )
        if cfg.d_ff % tp:
            raise ValueError(f"d_ff {cfg.d_ff} not divisible by tensor axis {tp}")
