"""Megatron-style tensor-parallel sharding rules for the model families.

Net-new vs the reference (data parallelism only — SURVEY §2.7): attention
qkv / MLP up-projections are column-parallel (sharded on the output dim),
attention proj / MLP down-projections are row-parallel (sharded on the input
dim, partial products ``psum``-reduced over the ``tensor`` axis inside the
model, see models/gpt2._attention / models/llama TP paths). LayerNorms,
embeddings and the LM head stay replicated.

The spec trees returned here drive shard_map in/out specs AND device_put
layouts; the optimizer is oblivious — its ``data``-axis vote runs
independently on each tensor shard.

:func:`copy_to_tp_region` is Megatron's *f* operator — identity forward,
``psum`` over the tensor axis backward. The models insert it where replicated
activations enter a column-parallel region (attention/MLP entry): each tensor
rank's backward only carries its own heads'/columns' contribution to dx, so
without the boundary psum the gradients of everything upstream (layer norms,
embeddings) would be per-rank partials — and per-rank momenta/votes would
silently drift replicated parameters apart. (Under ``shard_map`` with
``check_vma=False`` JAX does not insert this reduction automatically.)

**Gradient-scale convention.** jax.grad runs INSIDE the train step's
shard_map, where the transpose of ``lax.psum`` is ``psum`` — so each
row-parallel exit reduce and each copy boundary a leaf's backward crosses
multiplies its gradient by W. The net effect is a CONSTANT positive
per-leaf factor W^k (constant across steps; pinned by
tests/test_tp_vocab.py). Sign-based vote-Lion is exactly invariant to a
constant per-leaf scale, which is why tensor-parallel training is
Lion-only (train/loop.py guards the AdamW and stochastic-binarization
paths): AdamW's moments and the stochastic quantizer's Bernoulli
probabilities are magnitude-dependent and would silently mis-scale.
"""

from __future__ import annotations

from functools import partial

import jax
from jax import lax
from jax.sharding import PartitionSpec as P

from distributed_lion_tpu.parallel.mesh import TENSOR_AXIS


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_tp_region(x, axis_name: str):
    """Identity forward; backward ``psum``s the cotangent over ``axis_name``."""
    return x


def _copy_fwd(x, axis_name):
    return x, None


def _copy_bwd(axis_name, _, g):
    return (lax.psum(g, axis_name),)


copy_to_tp_region.defvjp(_copy_fwd, _copy_bwd)


def spec_uses_axis(spec, axis_name: str) -> bool:
    """True if a PartitionSpec shards any dim over ``axis_name``."""
    return any(
        p == axis_name or (isinstance(p, (tuple, list)) and axis_name in p)
        for p in spec
    )


def gpt2_param_specs(cfg) -> dict:
    """PartitionSpec pytree matching models/gpt2.gpt2_init's structure."""
    col = P(None, TENSOR_AXIS)   # column-parallel weight [d, k*d]
    row = P(TENSOR_AXIS, None)   # row-parallel weight [k*d, d]
    rep1 = P()
    ln = {"scale": rep1, "bias": rep1}
    block = {
        "ln_1": ln,
        "attn": {
            "qkv": P(None, None, TENSOR_AXIS),
            "qkv_b": P(None, TENSOR_AXIS),
            "proj": row,
            "proj_b": rep1,
        },
        "ln_2": ln,
        "mlp": {"fc": col, "fc_b": P(TENSOR_AXIS), "proj": row, "proj_b": rep1},
    }
    return {
        "wte": rep1,
        "wpe": rep1,
        "ln_f": ln,
        "blocks": [block] * cfg.n_layer,
    }


def llama_param_specs(cfg, vocab_parallel: bool = False) -> dict:
    """PartitionSpec pytree matching models/llama.llama_init's structure.

    ``vocab_parallel`` shards the lm_head's vocab columns over the tensor
    axis (Megatron vocab-parallel CE, ops/xent.tp_vocab_xent): V/tp logit
    columns per rank instead of a replicated [d, V] head — the memory and
    FLOPs win that matters at 128k-class vocabularies."""
    col = P(None, TENSOR_AXIS)
    row = P(TENSOR_AXIS, None)
    rep = P()
    block = {
        "ln_attn": {"scale": rep},
        "attn": {"wq": col, "wk": col, "wv": col, "wo": row},
        "ln_mlp": {"scale": rep},
        "mlp": {"w_gate": col, "w_up": col, "w_down": row},
    }
    return {
        "wte": rep,
        "lm_head": col if vocab_parallel else rep,
        "ln_f": {"scale": rep},
        "blocks": [block] * cfg.n_layer,
    }


def validate_tp(cfg, tp: int, model: str = "gpt2") -> None:
    if model == "gpt2":
        if cfg.n_head % tp:
            raise ValueError(f"n_head {cfg.n_head} not divisible by tensor axis {tp}")
        if (4 * cfg.d_model) % tp:
            raise ValueError(f"d_ff {4 * cfg.d_model} not divisible by tensor axis {tp}")
    else:
        if cfg.n_head % tp or cfg.n_kv_head % tp:
            raise ValueError(
                f"heads ({cfg.n_head}/{cfg.n_kv_head}kv) not divisible by tensor axis {tp}"
            )
        if cfg.d_ff % tp:
            raise ValueError(f"d_ff {cfg.d_ff} not divisible by tensor axis {tp}")
