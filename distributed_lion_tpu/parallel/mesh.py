"""Device-mesh construction and sharding helpers.

TPU-native replacement for the reference's implicit ``torchrun`` NCCL process
group (/root/reference/README.md:19, distributed_lion.py:160-164): parallelism
is expressed as a named `jax.sharding.Mesh` and `PartitionSpec`s, and the
collectives ride ICI/DCN wherever the mesh axes land.

Axis conventions used throughout the framework:
- ``data``   — data parallelism (the reference's DDP ranks; the vote axis).
- ``tensor`` — tensor/model parallelism (net-new vs the reference).
- ``seq``    — sequence/context parallelism for ring attention (net-new).
"""

from __future__ import annotations

import contextlib
import os
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
TENSOR_AXIS = "tensor"
SEQ_AXIS = "seq"


def make_mesh(
    data: int | None = None,
    tensor: int = 1,
    seq: int = 1,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a (data, tensor, seq) mesh over the available devices.

    ``data=None`` absorbs all remaining devices, mirroring how ``torchrun
    --nproc_per_node N`` sizes the reference's world (README.md:19). On real
    hardware, prefer contiguous ICI neighbors for ``tensor``/``seq`` (the
    high-traffic axes) — `mesh_utils.create_device_mesh` handles that.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if data is None:
        if n % (tensor * seq):
            raise ValueError(f"{n} devices not divisible by tensor*seq={tensor * seq}")
        data = n // (tensor * seq)
    if data * tensor * seq != n:
        raise ValueError(f"mesh {data}x{tensor}x{seq} != {n} devices")
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh((data, tensor, seq), devices=devices)
    except Exception:
        dev_array = np.array(devices).reshape(data, tensor, seq)
    return Mesh(dev_array, (DATA_AXIS, TENSOR_AXIS, SEQ_AXIS))


def data_axis_size(mesh: Mesh) -> int:
    return mesh.shape[DATA_AXIS]


def replicated(mesh: Mesh) -> NamedSharding:
    """Sharding for tensors identical on every device (params under pure DP)."""
    return NamedSharding(mesh, P())


def data_sharded(mesh: Mesh, axis: int = 0) -> NamedSharding:
    """Shard a tensor's ``axis`` across the data axis (batches; stacked
    per-worker optimizer state, see optim.distributed_lion)."""
    spec = [None] * (axis + 1)
    spec[axis] = DATA_AXIS
    return NamedSharding(mesh, P(*spec))


def multihost_initialize() -> None:
    """Initialize JAX's distributed runtime when launched multi-host.

    Replaces the reference's ``torchrun`` rendezvous. No-op when the
    coordinator env vars are absent (single-host / test runs).
    """
    if os.environ.get("COORDINATOR_ADDRESS") or os.environ.get("JAX_COORDINATOR_ADDRESS"):
        with contextlib.suppress(RuntimeError):
            jax.distributed.initialize()
