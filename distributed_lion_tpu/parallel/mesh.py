"""Device-mesh construction and sharding helpers.

TPU-native replacement for the reference's implicit ``torchrun`` NCCL process
group (/root/reference/README.md:19, distributed_lion.py:160-164): parallelism
is expressed as a named `jax.sharding.Mesh` and `PartitionSpec`s, and the
collectives ride ICI/DCN wherever the mesh axes land.

Axis conventions used throughout the framework:
- ``data``   — data parallelism (the reference's DDP ranks; the vote axis).
- ``tensor`` — tensor/model parallelism (net-new vs the reference).
- ``seq``    — sequence/context parallelism for ring attention (net-new).
- ``pipe``   — pipeline parallelism over layer stages (net-new).
- ``expert`` — expert parallelism for MoE layers (net-new).
"""

from __future__ import annotations

import os
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
TENSOR_AXIS = "tensor"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"
EXPERT_AXIS = "expert"


def make_mesh(
    data: int | None = None,
    tensor: int = 1,
    seq: int = 1,
    pipe: int = 1,
    expert: int = 1,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a (data, tensor, seq, pipe, expert) mesh over the devices.

    ``data=None`` absorbs all remaining devices, mirroring how ``torchrun
    --nproc_per_node N`` sizes the reference's world (README.md:19). On real
    hardware, prefer contiguous ICI neighbors for ``tensor``/``seq`` (the
    high-traffic axes) — `mesh_utils.create_device_mesh` handles that.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    model = tensor * seq * pipe * expert
    if data is None:
        if n % model:
            raise ValueError(
                f"{n} devices not divisible by tensor*seq*pipe*expert={model}"
            )
        data = n // model
    if data * model != n:
        raise ValueError(
            f"mesh {data}x{tensor}x{seq}x{pipe}x{expert} != {n} devices"
        )
    shape = (data, tensor, seq, pipe, expert)
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:
        dev_array = np.array(devices).reshape(shape)
    return Mesh(dev_array, (DATA_AXIS, TENSOR_AXIS, SEQ_AXIS, PIPE_AXIS, EXPERT_AXIS))


def data_axis_size(mesh: Mesh) -> int:
    return mesh.shape[DATA_AXIS]


def replicated(mesh: Mesh) -> NamedSharding:
    """Sharding for tensors identical on every device (params under pure DP)."""
    return NamedSharding(mesh, P())


def data_sharded(mesh: Mesh, axis: int = 0) -> NamedSharding:
    """Shard a tensor's ``axis`` across the data axis (batches; stacked
    per-worker optimizer state, see optim.distributed_lion)."""
    spec = [None] * (axis + 1)
    spec[axis] = DATA_AXIS
    return NamedSharding(mesh, P(*spec))


def force_cpu_platform() -> bool:
    """Honor ``DLION_PLATFORM=cpu|cpu8``: switch JAX to the host-CPU
    backend BEFORE first device use (the axon sitecustomize force-registers
    a TPU plugin and OVERRIDES the ``JAX_PLATFORMS`` env var; a dead tunnel
    then hangs backend init forever — the config knob is the only reliable
    override). ``cpu8`` also requests 8 virtual devices, APPENDING to any
    existing ``XLA_FLAGS`` (a plain setdefault would silently drop the
    device count when other flags are set). The one shared copy of this
    workaround — CLIs and bench scripts all route through it. Returns
    whether the override was applied."""
    plat = os.environ.get("DLION_PLATFORM")
    if plat not in ("cpu", "cpu8"):
        return False
    if plat == "cpu8":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
    jax.config.update("jax_platforms", "cpu")
    return True


def multihost_initialize() -> None:
    """Initialize JAX's distributed runtime when launched multi-host.

    Replaces the reference's ``torchrun`` rendezvous. No-op when the
    coordinator env vars are absent (single-host / test runs).
    """
    if os.environ.get("COORDINATOR_ADDRESS") or os.environ.get("JAX_COORDINATOR_ADDRESS"):
        try:
            jax.distributed.initialize()
        except RuntimeError as e:
            # double-initialize (e.g. a CLI composed into a larger program
            # that already called it) is benign; anything else must be LOUD
            # — swallowing it silently trains N disconnected single-host
            # replicas instead of one job
            # ONLY jax's double-initialize message is benign; matching
            # anything broader (e.g. substring "already") would also match
            # coordination-service failures like "task ... already
            # registered" and silently recreate the disconnected-replica bug
            if "only be called once" in str(e).lower():
                return
            raise RuntimeError(
                "multi-host init failed with coordinator env vars set; "
                "refusing to continue as a silently-disconnected replica "
                "(note: jax.distributed.initialize() must run before "
                "anything initializes the XLA backend)"
            ) from e
