"""Serving entry point: continuous batching over a paged KV cache.

Completes the train → export → SERVE cycle (ROADMAP item 4): loads the
same checkpoints ``run_generate`` does (model.npz, training output dirs,
HF save_pretrained dirs — family auto-detected), builds the serving
engine (serve/engine.py), and drains a request file:

    python -m distributed_lion_tpu.cli.run_serve \
        --model_path ./out --model_family gpt2 --model_name tiny \
        --requests requests.jsonl --out responses.jsonl \
        --quant nf4 --max_seqs 32 --block_size 16

With no --requests, --prompt strings (repeatable) become the workload —
a smoke mode mirroring run_generate (scripts/workload_gen.py emits
seeded open-loop request files in the same schema). ``--journal_dir``
records ``serve/*`` spans (train/journal) for ``cli/run_analyze
--serve``. ``--serve_metrics`` arms the request-lifecycle metrics plane
(serve/metrics.py: TTFT/per-token sketches, gauges, drain-cadence
journal events); ``--slo_ttft_ms``/``--slo_tok_ms``/``--slo_p99`` add
the SLO monitor with burn-rate ``slo_breach`` accounting. Both are
pinned inert — token streams are bit-identical with or without them.

``--serve_tp N`` shards the decode path (weights per the Megatron specs,
page pools over kv heads) across the first N local devices — how the
NF4 Llama-2-7B artifact serves on a v5e slice (ISSUE 13); ``--serve_ep N``
shards a MoE checkpoint's expert banks over the expert axis (composes
with --serve_tp, ISSUE 15); ``--prefix_cache`` shares prompt-prefix KV
pages across requests with copy-on-write semantics. All are pinned
output-identical to the plain engine.

``--replicas N`` serves through the elastic fleet
(serve/replica_plane.py, ISSUE 14): N engines over the one loaded
checkpoint, live replica crash/drain/slow/rejoin (``--inject_serve``
schedules the fault matrix), in-flight requests migrating
token-identically from their recovery records, per-request ``deadline_s``
honored with honest ``timeout``/``failed`` statuses.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional


@dataclasses.dataclass
class ServeArguments:
    requests: Optional[str] = None   # request JSONL (serve/api schema);
    # unset → --prompt strings (GenerateArguments) become the workload.
    # Sampling (--temperature/--top_k/--top_p/--seed) and --max_new_tokens
    # ride GenerateArguments — one knob surface across generate and serve
    out: Optional[str] = None        # response JSONL (default stdout)
    max_seqs: int = 8
    block_size: int = 16
    max_blocks_per_seq: int = 8
    num_blocks: int = 0              # 0 = auto (max_seqs * max_blocks_per_seq)
    prefill_cap_tokens: int = 512
    quant: str = "none"              # none | nf4 | int8 (ops/quant)
    quant_block: Optional[int] = None  # quant block override; shrink so
    # every --serve_tp-sharded last dim splits (ops/quant.validate_quant_tp
    # names the offending leaf when it can't)
    serve_tp: int = 0                # tensor-parallel serving degree
    # (ISSUE 13): 0 = single-device (the pre-TP engine, bit for bit);
    # N >= 1 shards weights per the Megatron param specs and the page
    # pools over kv heads across the first N local devices, one
    # shard_map'd dispatch per tick. tp=1 is pinned bit-identical to the
    # single-device engine; heads/kv-heads/d_ff must divide N.
    serve_ep: int = 0                # expert-parallel serving degree
    # (ISSUE 15): 0 = no expert axis; N >= 1 needs a MoE checkpoint
    # (moe_experts % N == 0) and shards the expert FFN banks over the
    # expert axis of a (data=1, expert=N, tensor=max(tp,1)) mesh — two
    # all_to_all hops per MoE block per tick, page pools untouched.
    # Composes with --serve_tp (N x tp devices). ep=1 is pinned
    # bit-identical to the unsharded engine; ep>1 token-identical.
    serve_ep_batch: bool = False     # batch-shard the decode/prefill batch
    # over the expert axis (ISSUE 16): slots and page pools split into
    # --serve_ep groups (max_seqs and num_blocks must divide ep), per-chip
    # FLOPs scale with ep, tokens cross chips only inside the two MoE
    # all_to_all hops. Needs --serve_ep >= 1. ep=1 is pinned bit-identical
    # to the replicated engine; ep>1 token-identical. Composes with
    # --serve_tp, --prefix_cache (caches go group-local) and
    # --speculate ngram:<k>.
    serve_ep_overlap: bool = False   # split each decode tick into two
    # software-pipelined microbatches so one half's expert all_to_all is
    # in flight while the other half runs attention. Needs
    # --serve_ep_batch and an even per-group slot count >= 2. Pinned
    # bit-identical to the unoverlapped tick (attention is row-local and
    # no-drop routing is an exact per-token function).
    prefix_cache: bool = False       # share prompt-prefix KV pages across
    # requests (copy-on-write block tables, serve/kv_cache.PrefixCache):
    # N requests carrying the same system prompt hold ONE physical copy
    # of its pages. Outputs pinned identical to the unshared engine —
    # MoE checkpoints included (no-drop per-token inference routing means
    # shared pages cannot change any expert assignment).
    serve_retrace_guard: str = "warn"  # off | warn | error — the serve
    # twin of the trainer's --retrace_guard, at tick granularity: every
    # dispatch's operand signature (shapes + dtypes) is checked against
    # the compile budget (ONE decode/verify/cow program, one prefill per
    # power-of-two page bucket) BEFORE tracing. 'warn' counts
    # stats['serve_retraces'] and warns; 'error' raises before the extra
    # lowering compiles; both are bit-identical to 'off' on the token
    # streams (analysis/serve_check pins the budget statically).
    speculate: str = ""              # '<drafter>:<k>' — speculative decode
    # (serve/speculate.py): 'ngram:4' self-drafts from each request's own
    # history (zero extra device memory); 'draft:2' proposes with a small
    # draft model (--draft_model_path/--draft_model_name, same family and
    # vocab as the target). Outputs are pinned identical to non-speculative
    # serving; the knob only changes tokens per dispatch.
    draft_model_path: Optional[str] = None   # draft checkpoint for
    # --speculate draft:<k> (same loaders as --model_path)
    draft_model_name: Optional[str] = None   # draft architecture (default:
    # the target's model_name — self-drafting smoke mode)
    listen: str = ""                 # live socket mode (serve/net.py):
    # '<port>' or '<host>:<port>' ('0' = ephemeral, address printed as a
    # JSON line on stdout). Newline-delimited JSON requests in (the SAME
    # strict serve/api schema as --requests), per-token streaming frames
    # out at host tick boundaries, honest backpressure reject frames
    # when the admission queue or page pool is tight. Mutually exclusive
    # with --requests — one transport per run.
    listen_wall_s: float = 0.0       # stop the socket server after this
    # many wall seconds (0 = run until interrupted); the bounded mode
    # the soak bench and the runbook stage use
    replica_procs: bool = False      # process-isolated fleet
    # (serve/fleet_proc.py): each replica is its own
    # ``serve.replica_worker`` subprocess speaking the length-prefixed
    # pipe protocol — replica failure becomes a real OS event (the
    # replica_kill fault SIGKILLs the child mid-decode; migration stays
    # token-identical from the fleet's shadow). The parent loads the
    # checkpoint once (tokenizer + validation); each child loads its own
    # copy — real isolation costs real memory. Implies the fleet path
    # even at --replicas 1.
    heartbeat_timeout_s: float = 60.0  # per-tick reply deadline for a
    # process replica; a miss journals replica_heartbeat_missed and the
    # tick stays outstanding (a late reply is consumed next round)
    heartbeat_max_misses: int = 3    # consecutive misses before the
    # replica is declared dead (replica_declared_dead), SIGKILLed, and
    # its requests migrate from the recovery shadow
    fleet_state_dir: Optional[str] = None  # fleet-restart persistence
    # (serve/fleet_state.py): recovery shadow + prefix chains persist
    # here (atomic tmp+rename, sha256 manifest) on the
    # --fleet_persist_every cadence and at drain. Implies the fleet path.
    fleet_persist_every: int = 0     # persistence cadence in fleet ticks
    # (0 = only at drain/exit)
    resume_fleet: bool = False       # restore the newest valid persisted
    # state from --fleet_state_dir before serving: in-flight requests
    # re-submit (re-prefill from committed — token-identical by
    # construction) and persisted shared-prefix chains re-prefill once
    # as priming requests so the page pool warm-starts
    replicas: int = 1                # elastic serving fleet width
    # (serve/replica_plane, ISSUE 14): N independent engines (weights
    # shared, page pools per-replica) behind one admission queue with
    # prefix_group-affine routing; replicas leave/drain/rejoin live and
    # in-flight requests migrate token-identically from their recovery
    # records. 1 (default) = the single engine, no fleet layer at all.
    inject_serve: str = ""           # serve-side fault schedule
    # (resilience.parse_serve_specs, comma-separated):
    # replica_crash:<r>:<tick> | replica_drain:<r>[:<tick>] |
    # slow_tick:<r>:<ms> | replica_rejoin:<r>:<tick> — consumed by the
    # fleet at tick boundaries. Needs --replicas >= 2 to mean anything
    # (a 1-replica fleet with a crash has nowhere to migrate).
    serve_metrics: bool = False      # arm the request-lifecycle metrics
    # plane (serve/metrics.ServeMetrics): TTFT/per-token latency
    # sketches, live gauges, drain-cadence serve_metrics/serve_stats
    # journal events. Pinned inert — token streams are bit-identical
    # with the plane on or off. Implied by any --slo_* flag.
    slo_ttft_ms: Optional[float] = None   # SLO: time-to-first-token
    # bound (wall ms). Setting it arms the metrics plane + SLO monitor;
    # violations count per request, rolling-window burn rate journals
    # edge-triggered slo_breach events (serve/metrics.SLOMonitor).
    slo_tok_ms: Optional[float] = None    # SLO: mean per-token decode
    # latency bound (wall ms per generated token)
    slo_p99: float = 0.99            # SLO quantile target: the error
    # budget is 1 - slo_p99 (the violation fraction the SLO tolerates);
    # burn rate = window violation fraction / budget
    journal_dir: Optional[str] = None


def build_engine_factory(gen_args, serve_args: "ServeArguments"):
    """(tokenizer, factory) from the run_generate model surface + serve
    knobs: checkpoints load ONCE, ``factory()`` builds a fresh
    :class:`ServingEngine` over the shared weights (its own page pool and
    block tables each call — what a rejoining fleet replica needs).
    Shared by :func:`build_engine`, the ``--replicas`` fleet path, and
    the bench."""
    from distributed_lion_tpu.cli.run_generate import build
    from distributed_lion_tpu.serve.engine import (
        ServeConfig,
        ServeModel,
        ServingEngine,
    )

    def as_serve_model(p, c):
        return (ServeModel.for_gpt2(p, c) if gen_args.model_family == "gpt2"
                else ServeModel.for_llama(p, c))

    if serve_args.speculate:
        # pure-config refusals BEFORE any checkpoint loads — a spec error
        # must cost milliseconds, not minutes of target-weight loading
        from distributed_lion_tpu.serve.speculate import parse_speculate

        name, _ = parse_speculate(serve_args.speculate)
        if name == "draft" and not serve_args.draft_model_path:
            raise ValueError(
                "--speculate draft:<k> needs --draft_model_path (a TRAINED "
                "draft checkpoint; without it the loader would random-init "
                "the drafter, whose proposals all reject — every tick then "
                "pays the draft dispatch plus the k+1-wide verify for "
                "nothing, silently slower than plain decode)")
    tok, cfg, params, _, _ = build(gen_args)
    model = as_serve_model(params, cfg)
    draft_model = None
    if serve_args.speculate.startswith("draft"):
        # the draft checkpoint rides the same loader surface as the target
        # (npz / training output dir / HF dir); family must match — the
        # vocab check in serve/speculate.build_speculator is the loud gate
        d_args = dataclasses.replace(
            gen_args, model_path=serve_args.draft_model_path,
            model_name=serve_args.draft_model_name or gen_args.model_name)
        _, dcfg, dparams, _, _ = build(d_args)
        draft_model = as_serve_model(dparams, dcfg)
    scfg = ServeConfig(
        max_seqs=serve_args.max_seqs, block_size=serve_args.block_size,
        max_blocks_per_seq=serve_args.max_blocks_per_seq,
        num_blocks=serve_args.num_blocks,
        prefill_cap_tokens=serve_args.prefill_cap_tokens,
        max_new_tokens=gen_args.max_new_tokens,
        temperature=gen_args.temperature, top_k=gen_args.top_k,
        top_p=gen_args.top_p, quant=serve_args.quant,
        quant_block=serve_args.quant_block,
        tp=serve_args.serve_tp, ep=serve_args.serve_ep,
        ep_batch=serve_args.serve_ep_batch,
        ep_overlap=serve_args.serve_ep_overlap,
        prefix_cache=serve_args.prefix_cache,
        retrace_guard=serve_args.serve_retrace_guard,
        speculate=serve_args.speculate,
        metrics=(serve_args.serve_metrics
                 or serve_args.slo_ttft_ms is not None
                 or serve_args.slo_tok_ms is not None),
        eos_id=getattr(tok, "eos_id", None))
    slo_armed = (serve_args.slo_ttft_ms is not None
                 or serve_args.slo_tok_ms is not None)

    def factory() -> ServingEngine:
        engine = ServingEngine(model, scfg, draft_model=draft_model)
        if slo_armed:
            # each engine (each fleet replica) gets its own monitor —
            # burn rate is a per-replica signal; the fleet aggregate
            # rides metrics_snapshot()'s sketch merge
            from distributed_lion_tpu.serve.metrics import (
                ServeMetrics, SLOMonitor)

            engine.metrics = ServeMetrics(
                engine.times,
                slo=SLOMonitor(ttft_ms=serve_args.slo_ttft_ms,
                               tok_ms=serve_args.slo_tok_ms,
                               p99=serve_args.slo_p99))
        return engine

    return tok, factory


def build_engine(gen_args, serve_args: "ServeArguments"):
    """(tokenizer, engine) — the single-engine surface this CLI, the
    decode bench, and tests share."""
    tok, factory = build_engine_factory(gen_args, serve_args)
    return tok, factory()


def build_fleet(gen_args, serve_args: "ServeArguments"):
    """(tokenizer, fleet) for ``--replicas N`` — N engines over ONE
    loaded checkpoint behind the replica plane's admission queue
    (serve/replica_plane.ServingFleet). With ``--replica_procs`` each
    replica is instead a ``serve.replica_worker`` subprocess built from
    the SAME argument surface (the child re-runs this CLI's build), so
    replica death is a real OS event."""
    from distributed_lion_tpu.serve.replica_plane import ServingFleet

    if serve_args.replica_procs:
        from distributed_lion_tpu.cli.run_generate import build
        from distributed_lion_tpu.serve.fleet_proc import (
            process_replica_factory)

        # the parent builds once for the tokenizer (and to fail fast on
        # a bad checkpoint BEFORE spawning N children that would each
        # fail slower); children load their own weights — process
        # isolation is not free, it is the point
        tok, _, _, _, _ = build(gen_args)
        builder = {"kind": "cli",
                   "gen": dataclasses.asdict(gen_args),
                   "serve": dataclasses.asdict(serve_args)}
        factory = process_replica_factory(
            builder,
            heartbeat_timeout_s=serve_args.heartbeat_timeout_s)
    else:
        tok, factory = build_engine_factory(gen_args, serve_args)
    return tok, ServingFleet(
        factory, replicas=serve_args.replicas,
        heartbeat_max_misses=serve_args.heartbeat_max_misses,
        state_dir=serve_args.fleet_state_dir,
        persist_every=serve_args.fleet_persist_every)


def main(argv=None):
    from distributed_lion_tpu.parallel.mesh import force_cpu_platform

    force_cpu_platform()

    from distributed_lion_tpu.cli.run_generate import GenerateArguments
    from distributed_lion_tpu.serve import api
    from distributed_lion_tpu.serve.engine import Request
    from distributed_lion_tpu.train import journal as journal_mod
    from distributed_lion_tpu.utils.argparsing import parse_dataclasses

    gen_args, args = parse_dataclasses((GenerateArguments, ServeArguments),
                                       argv)
    if args.replicas < 1:
        raise ValueError(f"--replicas must be >= 1, got {args.replicas}")
    if args.inject_serve and args.replicas < 2:
        raise ValueError(
            "--inject_serve needs --replicas >= 2: a one-replica fleet "
            "has no survivor to migrate in-flight requests to")
    if args.listen and args.requests:
        raise ValueError(
            "--listen and --requests are two transports over the same "
            "core — pick one per run (workload_gen --stream drives the "
            "socket side with the same request files)")
    if args.resume_fleet and not args.fleet_state_dir:
        raise ValueError(
            "--resume_fleet restores from --fleet_state_dir; set it to "
            "the directory the previous run persisted into")
    # the fleet path is implied by any fleet-plane knob: a 1-replica
    # process fleet or a persistence-armed single replica still needs
    # the fleet's shadow/heartbeat/persist machinery
    use_fleet = (args.replicas > 1 or args.replica_procs
                 or args.fleet_state_dir is not None)
    jrnl = None
    if args.journal_dir:
        jrnl = journal_mod.Journal(args.journal_dir)
        journal_mod.install(jrnl)
    try:
        if args.inject_serve:
            from distributed_lion_tpu.train import resilience

            resilience.inject_fault(
                "serve", resilience.parse_serve_specs(args.inject_serve))
        if use_fleet:
            tok, engine = build_fleet(gen_args, args)
        else:
            tok, engine = build_engine(gen_args, args)
        if args.resume_fleet:
            import time as _time

            from distributed_lion_tpu.serve import fleet_state

            state = fleet_state.load_fleet_state(args.fleet_state_dir,
                                                 now=_time.monotonic())
            info = fleet_state.resume_into(engine, state)
            print(json.dumps({"resumed": info["restored"],
                              "chains_primed": info["chains_primed"],
                              "from_tick": info["tick"]},
                             allow_nan=False), flush=True)
        if args.listen:
            from distributed_lion_tpu.serve.net import ServeServer

            spec = args.listen
            host, _, port = spec.rpartition(":")
            server = ServeServer(engine, host=host or "127.0.0.1",
                                 port=int(port), tokenizer=tok)
            print(json.dumps({"listening": list(server.addr)},
                             allow_nan=False), flush=True)
            try:
                server.run(max_wall_s=args.listen_wall_s or None)
            except KeyboardInterrupt:
                pass
            finally:
                server.close()
            records = []
        elif args.requests:
            records = api.serve_request_file(engine, args.requests,
                                             args.out or "/dev/stdout", tok)
        else:
            prompts = list(gen_args.prompt) or ["Hello"]  # smoke default
            reqs = [Request(req_id=f"req{i}",
                            tokens=tok.encode(p, add_bos=False) or [0],
                            max_new_tokens=gen_args.max_new_tokens,
                            seed=gen_args.seed)
                    for i, p in enumerate(prompts)]
            records = api.handle_requests(engine, reqs, tokenizer=tok)
            for p, rec in zip(prompts, records):
                print(json.dumps({"prompt": p, **rec}, allow_nan=False),
                      flush=True)
        journal_mod.active().event("serve_done", **{
            k: (float(v) if isinstance(v, float) else int(v))
            for k, v in engine.stats.items()})
        # final metrics drain: the end-of-run snapshot lands in the
        # journal even when the run was shorter than one drain cadence
        if use_fleet:
            snap = engine.metrics_snapshot()
            if snap is not None:
                journal_mod.active().event("serve_fleet_metrics", **{
                    f"{sec}_{k}": v for sec, d in snap.items()
                    if isinstance(d, dict) for k, v in d.items()})
            if args.fleet_state_dir:
                # the at-drain save: whatever is still in flight (a
                # --listen server interrupted mid-decode included)
                # survives into the next --resume_fleet
                engine.save_state()
            engine.close()
        elif engine.metrics is not None:
            engine.metrics.drain(engine.stats["ticks"])
        return records
    finally:
        if args.inject_serve:
            from distributed_lion_tpu.train import resilience

            resilience.inject_fault("serve", [])  # disarm leftovers — a
            # half-consumed schedule must not leak into the next engine
            # built in this process (tests drive main() in-process)
        if jrnl is not None:
            journal_mod.uninstall(jrnl)
            jrnl.close()


if __name__ == "__main__":
    main()
