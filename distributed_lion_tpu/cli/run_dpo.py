"""DPO entry point — the INTENDED workload of the reference's broken
``dpo_llama2.py`` (/root/reference/dpo_llama2.py; syntax error at :81 and
undefined ``base_model`` at :210-213 make it unrunnable — SURVEY §2.10).

Pieces mapped:
- policy + frozen reference model, both from the SFT checkpoint (:133-152)
  → ``--sft_checkpoint`` loads a merged .npz (from run_sft --merged_output);
  both start identical, the ref stays frozen (optionally quantized);
- β=0.1 pairwise loss (:25, :223) → train/dpo.make_dpo_loss_fn;
- prompt/chosen/rejected prep + length filter (:84-125, :158-168)
  → data/dpo.prepare_dpo_batch (max_length 1024, max_prompt_length 512);
- --sanity_check (:62) truncates to 1000 pairs;
- LoRA on the policy (:192-207) with the reference's wider target set;
- --lion/--async_grad optimizer wiring (:209-231).
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class DPOArguments:
    """dpo_llama2.py ScriptArguments (:18-81), repaired."""

    model_name: str = "llama2_7b"  # llama2_7b | llama3_8b | small | tiny
    model_path: Optional[str] = None  # local HF Llama checkpoint: policy+ref
    # both start from the pretrained base (dpo_llama2.py:133-152); an
    # --sft_checkpoint takes precedence (the reference's canonical flow runs
    # DPO on the SFT-merged model)
    dataset: str = "synthetic"     # synthetic | jsonl:<path>
    sft_checkpoint: Optional[str] = None  # merged .npz from run_sft
    beta: float = 0.1
    max_length: int = 1024
    max_prompt_length: int = 512
    num_train_samples: int = 512
    size_valid_set: int = 64
    sanity_check: bool = False
    attn_impl: str = "auto"  # ops.attention: auto | xla | xla_bf16 | flash | splash
    seq_impl: str = "ring"   # under --seq_parallel: ring | ulysses
    quant_ref: str = "none"        # none | int8 | nf4 — frozen ref model
    quant_block: Optional[int] = None  # quant block size override; shrink so
    # a small model's projections shard under --tensor_parallel
    lora_r: int = 8
    lora_alpha: int = 16
    lora_dropout: float = 0.05  # adapter-branch dropout (PEFT semantics)
    tokenizer_name: Optional[str] = None
    adapter_path: Optional[str] = None  # start the policy from a PEFT
    # adapter checkpoint (models/hf_import.peft_to_lora) instead of fresh init
    adapter_output: Optional[str] = None  # save the trained policy LoRA
    # adapters as a HF PEFT checkpoint directory (models/hf_export.lora_to_peft)
    merged_output: Optional[str] = None  # save the LoRA-merged policy here:
    # *.npz → flat save_pytree archive; any other path → HF save_pretrained
    # directory (models/hf_export)


def main(argv=None):
    from distributed_lion_tpu.utils.argparsing import parse_dataclasses

    script_args, train_cfg = parse_dataclasses((DPOArguments, _train_cfg_cls()), argv)

    import jax
    import numpy as np

    from distributed_lion_tpu.cli.run_clm import build_mesh
    from distributed_lion_tpu.data.dpo import dpo_batch_iterator, prepare_dpo_batch
    from distributed_lion_tpu.data.sft import load_pairs_jsonl, synthetic_qa_pairs
    from distributed_lion_tpu.data.tokenizer import load_tokenizer
    from distributed_lion_tpu.models.llama import LlamaConfig, llama_apply, llama_init
    from distributed_lion_tpu.models.lora import LoraConfig, lora_apply_fn, lora_init, merge_lora
    from distributed_lion_tpu.ops.quant import dequantize_tree, quantize_tree
    from distributed_lion_tpu.train.dpo import make_dpo_loss_fn
    from distributed_lion_tpu.train.loop import Trainer
    from distributed_lion_tpu.utils.serialization import load_pytree, save_pytree

    sp = train_cfg.seq_parallel
    if sp > 1 and train_cfg.tensor_parallel > 1:
        raise NotImplementedError(
            "--tensor_parallel x --seq_parallel on the DPO path is not "
            "wired; pick one"
        )
    mesh = build_mesh(train_cfg.tensor_parallel, sp)
    tok = load_tokenizer(script_args.tokenizer_name)

    pretrained_params = None
    if script_args.model_path:
        from distributed_lion_tpu.models.hf_import import llama_from_hf

        pretrained_params, model_cfg = llama_from_hf(script_args.model_path)
        print(f"[run_dpo] loaded pretrained Llama from {script_args.model_path}: "
              f"{model_cfg.n_layer}L d={model_cfg.d_model} vocab={model_cfg.vocab_size}")
    else:
        model_cfg = LlamaConfig.named(script_args.model_name,
                                      vocab_size=max(tok.vocab_size, 259))
    model_cfg = dataclasses.replace(model_cfg, attn_impl=script_args.attn_impl,
                                    seq_impl=script_args.seq_impl)
    if script_args.max_length > model_cfg.n_ctx:
        script_args.max_length = model_cfg.n_ctx
    if sp > 1 and script_args.max_length % sp:
        # checked after the n_ctx clamp: the padded rows use this value
        raise ValueError(
            f"--max_length {script_args.max_length} (after the n_ctx clamp) "
            f"must divide evenly over the {sp}-way seq axis"
        )
    train_cfg.block_size = script_args.max_length

    # Policy and reference both start from the SFT model (dpo_llama2.py:133-152).
    if script_args.sft_checkpoint:
        import jax.numpy as jnp

        base_params = load_pytree(script_args.sft_checkpoint)
        # npz leaves are numpy; move to device arrays (traced indexing needs
        # jax arrays) and normalize float dtypes to the model's param dtype
        base_params = jax.tree.map(
            lambda x: jnp.asarray(
                x, model_cfg.param_dtype
                if np.issubdtype(np.asarray(x).dtype, np.floating) else None
            ),
            base_params,
        )
        print(f"[run_dpo] loaded SFT model from {script_args.sft_checkpoint}")
    elif pretrained_params is not None:
        base_params = pretrained_params
    else:
        print("[run_dpo] no --sft_checkpoint/--model_path given; starting from fresh init")
        base_params = llama_init(jax.random.key(train_cfg.seed), model_cfg)

    ref_params = base_params
    if script_args.quant_ref != "none":
        ref_params = quantize_tree(base_params, script_args.quant_ref,
                                   block=script_args.quant_block)

    # LoRA on the policy, the reference's wider DPO target set (:192-207).
    if script_args.adapter_path:
        from distributed_lion_tpu.models.hf_import import peft_to_lora

        adapters, lora_cfg = peft_to_lora(script_args.adapter_path, model_cfg)
        print(f"[run_dpo] resumed PEFT adapter from {script_args.adapter_path} "
              f"(r={lora_cfg.r} alpha={lora_cfg.alpha})")
    else:
        # the reference's full DPO target set (dpo_llama2.py:192-207):
        # q/k/v/out projections + the MLP (fc_in/fc_out class) + the token
        # embedding (wte — gather-side adapter, models/lora.lora_embed)
        from distributed_lion_tpu.models.lora import DPO_TARGET_PATTERNS

        lora_cfg = LoraConfig(
            r=script_args.lora_r, alpha=script_args.lora_alpha,
            dropout=script_args.lora_dropout,
            target_patterns=DPO_TARGET_PATTERNS,
        )
        adapters = lora_init(jax.random.key(train_cfg.seed + 1), base_params, lora_cfg)

    vc = train_cfg.vocab_chunks
    if vc > 0 and train_cfg.tensor_parallel > 1:
        raise NotImplementedError(
            "--vocab_chunks x --tensor_parallel on the DPO path is not "
            "wired (the TP head is already vocab-sharded; chunking it "
            "again buys nothing) — drop one"
        )

    def _hidden_and_head(params, tokens, **kw):
        # chunked-vocab scoring contract: (hidden, head) instead of logits;
        # train/dpo streams the label logprobs through ops/xent
        from distributed_lion_tpu.models.llama import llama_hidden
        from distributed_lion_tpu.ops.quant import maybe_dequant

        return (llama_hidden(params, tokens, model_cfg, **kw),
                maybe_dequant(params["lm_head"], model_cfg.compute_dtype))

    tp = train_cfg.tensor_parallel
    frozen_params = frozen_specs = None
    if tp > 1:
        from distributed_lion_tpu.models.lora import apply_adapters, lora_adapter_specs
        from distributed_lion_tpu.parallel.mesh import TENSOR_AXIS
        from distributed_lion_tpu.parallel.tensor_parallel import (
            llama_param_specs,
            validate_tp,
        )
        from distributed_lion_tpu.train.dpo import make_dpo_loss_fn_frozen

        validate_tp(model_cfg, tp, "llama")
        base_specs = llama_param_specs(model_cfg)
        if script_args.quant_ref != "none":
            # the shaped QuantizedTensor layout shards with the dense specs
            # — multi-chip DPO holds TWO 7B models, exactly where sharding
            # the NF4 ref matters
            from distributed_lion_tpu.ops.quant import validate_quant_tp

            validate_quant_tp(ref_params, base_specs, tp, TENSOR_AXIS)
        frozen_params = {"base": base_params, "ref": ref_params}
        frozen_specs = {"base": base_specs, "ref": base_specs}

        def policy_apply(params, frozen, tokens, dropout_key=None):
            effective = apply_adapters(frozen["base"], params, lora_cfg,
                                       tp_axis=TENSOR_AXIS, base_specs=base_specs,
                                       dropout_key=dropout_key)
            return llama_apply(effective, tokens, model_cfg, tp_axis=TENSOR_AXIS)

        loss_fn = make_dpo_loss_fn_frozen(
            policy_apply=policy_apply,
            ref_apply=lambda frozen, t: llama_apply(frozen["ref"], t, model_cfg,
                                                    tp_axis=TENSOR_AXIS),
            beta=script_args.beta,
        )
        adapter_specs = lora_adapter_specs(adapters, base_specs, TENSOR_AXIS)
    elif sp > 1:
        # long-context DPO: chosen/rejected rows sharded over tokens — ring
        # attention through policy and frozen ref, per-shard logprob partials
        # psum'd before the pairwise sigmoid (train/dpo.py)
        from distributed_lion_tpu.parallel.mesh import SEQ_AXIS

        if vc > 0:
            base_fwd = lambda p, t: _hidden_and_head(p, t, seq_axis=SEQ_AXIS)  # noqa: E731
            ref_fwd = lambda t: _hidden_and_head(ref_params, t, seq_axis=SEQ_AXIS)  # noqa: E731
        else:
            base_fwd = lambda p, t: llama_apply(p, t, model_cfg, seq_axis=SEQ_AXIS)  # noqa: E731
            ref_fwd = lambda t: llama_apply(ref_params, t, model_cfg,
                                            seq_axis=SEQ_AXIS)  # noqa: E731
        policy_apply_lora = lora_apply_fn(base_fwd, base_params, lora_cfg)
        loss_fn = make_dpo_loss_fn(
            policy_apply=policy_apply_lora,
            ref_apply=ref_fwd,
            beta=script_args.beta,
            seq_axis=SEQ_AXIS,
            vocab_chunks=vc,
        )
        adapter_specs = None
    else:
        if vc > 0:
            base_fwd = _hidden_and_head
            ref_fwd = lambda t: _hidden_and_head(ref_params, t)  # noqa: E731
        else:
            base_fwd = lambda p, t: llama_apply(p, t, model_cfg)  # noqa: E731
            ref_fwd = lambda t: llama_apply(ref_params, t, model_cfg)  # noqa: E731
        policy_apply_lora = lora_apply_fn(base_fwd, base_params, lora_cfg)
        loss_fn = make_dpo_loss_fn(
            policy_apply=policy_apply_lora,
            ref_apply=ref_fwd,
            beta=script_args.beta,
            vocab_chunks=vc,
        )
        adapter_specs = None

    if script_args.dataset == "synthetic":
        records = synthetic_qa_pairs(script_args.num_train_samples + script_args.size_valid_set)
    elif script_args.dataset.startswith("jsonl:"):
        train_recs, _ = load_pairs_jsonl(script_args.dataset[len("jsonl:"):])
        records = train_recs
    else:
        raise ValueError(f"unknown dataset spec {script_args.dataset!r}")

    data = prepare_dpo_batch(
        records, tok,
        max_length=script_args.max_length,
        max_prompt_length=script_args.max_prompt_length,
        sanity_check=script_args.sanity_check,
    )
    n = len(data["chosen"])
    n_valid = min(script_args.size_valid_set, n // 4)
    eval_data = {k: v[:n_valid] for k, v in data.items()} if n_valid else None
    train_data = {k: v[n_valid:] for k, v in data.items()}
    print(f"[run_dpo] {len(train_data['chosen'])} train / {n_valid} eval pairs "
          f"(after length filtering)")

    batch_spec = None
    if sp > 1:
        from jax.sharding import PartitionSpec as P

        from distributed_lion_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS

        batch_spec = P(DATA_AXIS, SEQ_AXIS)  # every [B, T] leaf token-sharded
    trainer = Trainer(train_cfg, mesh, apply_fn=None, params=adapters,
                      loss_fn=loss_fn, param_specs=adapter_specs,
                      frozen_params=frozen_params, frozen_specs=frozen_specs,
                      batch_spec=batch_spec)
    it = dpo_batch_iterator(train_data, trainer.global_train_batch(), seed=train_cfg.seed)
    try:
        trainer.train(it, eval_blocks=eval_data)
        if trainer.preempted:
            print("[run_dpo] preempted: "
                  + ("checkpoint durable, " if trainer.checkpointer
                     else "NO checkpointer (no --output_dir) — nothing "
                          "saved, ")
                  + "exiting cleanly")
            return
        if eval_data is not None:
            trainer.evaluate(eval_data)
        if trainer.checkpointer:
            trainer.save()
        if script_args.adapter_output:
            from distributed_lion_tpu.models.hf_export import lora_to_peft

            lora_to_peft(jax.device_get(trainer.params), model_cfg, lora_cfg,
                         script_args.adapter_output,
                         base_model_name=script_args.model_path or "")
            print(f"[run_dpo] PEFT adapter saved to {script_args.adapter_output}")
        if script_args.merged_output:
            merged = dequantize_tree(merge_lora(base_params, trainer.params, lora_cfg))
            if script_args.merged_output.endswith(".npz"):
                save_pytree(script_args.merged_output, merged)
            else:
                # HF save_pretrained layout, like run_sft's merge flow
                import jax

                from distributed_lion_tpu.models.hf_export import (
                    copy_tokenizer_files, llama_to_hf)

                llama_to_hf(jax.device_get(merged), model_cfg,
                            script_args.merged_output)
                copy_tokenizer_files(script_args.tokenizer_name
                                     or script_args.model_path,
                                     script_args.merged_output)
            print(f"[run_dpo] merged policy saved to {script_args.merged_output}")
    finally:
        trainer.close()


def _train_cfg_cls():
    from distributed_lion_tpu.train.loop import TrainConfig

    return TrainConfig


if __name__ == "__main__":
    main()
